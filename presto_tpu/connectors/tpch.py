"""TPC-H data-generator connector.

Role model: presto-tpch (presto-tpch/.../TpchMetadata.java:91,
TpchPageSourceProvider.java:26) — the reference's keystone test/benchmark
fixture: deterministic generated data, zero IO, any scale (SURVEY §4.7).

Design differences from the reference (which wraps io.airlift.tpch, a java
dbgen port):

- **Counter-based generation.**  dbgen advances sequential per-column RNG
  streams, which forces split generation to "skip ahead".  Here every cell
  is a pure function ``value = f(splitmix64(table, column, key))`` of its
  row key, so any key range of any column generates independently, in
  vectorized numpy, with no stream state.  This matches how splits must
  behave on a multi-host TPU system: any host can generate any shard.
- **Column-lazy.**  Only requested columns are generated (the reference
  achieves the same via lazy blocks).
- **Strings are dictionary-encoded at birth** (types.VarcharType): enum-ish
  columns (shipmode, priority, ...) carry spec vocabularies; free-text
  comments draw from a capped pseudo-text space; per-row-distinct columns
  (c_name, phones) format their range on demand.

The data follows the TPC-H 4.3 value distributions (value ranges, date
windows, price formula, supplier-spread formula, 2/3-customer rule,
returnflag/linestatus/orderstatus derivation) so that the standard 22
queries produce representative selectivities.  It is not a byte-exact dbgen
clone; correctness testing diffs results against a SQL oracle over the SAME
generated data (SURVEY §4.2's H2-oracle pattern), so absolute dbgen parity
is not load-bearing.

Like the reference's connector, money columns are DOUBLE by default
(TpchMetadata's default column naming/typing) with an opt-in exact
``decimal`` mode, which maps to int64 on device — the TPU-native fast path.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from presto_tpu import types as T
from presto_tpu.batch import Batch, Column, Dictionary
from presto_tpu.connectors.api import (
    ColumnMetadata, Connector, PageSource, Split, TableHandle, TableSchema,
    TableStatistics,
)

# ---------------------------------------------------------------------------
# Deterministic counter-based randomness
# ---------------------------------------------------------------------------

_GOLDEN = np.uint64(0x9E3779B97F4A7C15)


def _mix(x: np.ndarray) -> np.ndarray:
    """splitmix64 finalizer (public-domain algorithm), vectorized."""
    x = np.asarray(x, dtype=np.uint64)
    x = (x ^ (x >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
    x = (x ^ (x >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
    return x ^ (x >> np.uint64(31))


def h64(stream: int, keys: np.ndarray) -> np.ndarray:
    """64 pseudo-random bits per key, independent per stream id."""
    k = np.asarray(keys, dtype=np.uint64)
    offset = np.uint64((stream * 0xD1B54A32D192ED03) & 0xFFFFFFFFFFFFFFFF)
    return _mix((k + np.uint64(1)) * _GOLDEN + offset)


def u_int(stream: int, keys: np.ndarray, lo: int, hi: int) -> np.ndarray:
    """Uniform integer in [lo, hi] per key (int64)."""
    span = np.uint64(hi - lo + 1)
    return (h64(stream, keys) % span).astype(np.int64) + lo


# ---------------------------------------------------------------------------
# Spec vocabularies (TPC-H 4.3 §4.2.2-4.2.3)
# ---------------------------------------------------------------------------

REGIONS = ["AFRICA", "AMERICA", "ASIA", "EUROPE", "MIDDLE EAST"]

# (name, regionkey) in nationkey order, per the spec's nation table.
NATIONS = [
    ("ALGERIA", 0), ("ARGENTINA", 1), ("BRAZIL", 1), ("CANADA", 1),
    ("EGYPT", 4), ("ETHIOPIA", 0), ("FRANCE", 3), ("GERMANY", 3),
    ("INDIA", 2), ("INDONESIA", 2), ("IRAN", 4), ("IRAQ", 4),
    ("JAPAN", 2), ("JORDAN", 4), ("KENYA", 0), ("MOROCCO", 0),
    ("MOZAMBIQUE", 0), ("PERU", 1), ("CHINA", 2), ("ROMANIA", 3),
    ("SAUDI ARABIA", 4), ("VIETNAM", 2), ("RUSSIA", 3),
    ("UNITED KINGDOM", 3), ("UNITED STATES", 1),
]

SEGMENTS = ["AUTOMOBILE", "BUILDING", "FURNITURE", "MACHINERY", "HOUSEHOLD"]
PRIORITIES = ["1-URGENT", "2-HIGH", "3-MEDIUM", "4-NOT SPECIFIED", "5-LOW"]
SHIP_MODES = ["REG AIR", "AIR", "RAIL", "SHIP", "TRUCK", "MAIL", "FOB"]
INSTRUCTIONS = ["DELIVER IN PERSON", "COLLECT COD", "NONE", "TAKE BACK RETURN"]
TYPE_S1 = ["STANDARD", "SMALL", "MEDIUM", "LARGE", "ECONOMY", "PROMO"]
TYPE_S2 = ["ANODIZED", "BURNISHED", "PLATED", "POLISHED", "BRUSHED"]
TYPE_S3 = ["TIN", "NICKEL", "BRASS", "STEEL", "COPPER"]
CONTAINER_S1 = ["SM", "LG", "MED", "JUMBO", "WRAP"]
CONTAINER_S2 = ["CASE", "BOX", "BAG", "JAR", "PKG", "PACK", "CAN", "DRUM"]

COLORS = (
    "almond antique aquamarine azure beige bisque black blanched blue blush "
    "brown burlywood burnished chartreuse chiffon chocolate coral cornflower "
    "cornsilk cream cyan dark deep dim dodger drab firebrick floral forest "
    "frosted gainsboro ghost goldenrod green grey honeydew hot indian ivory "
    "khaki lace lavender lawn lemon light lime linen magenta maroon medium "
    "metallic midnight mint misty moccasin navajo navy olive orange orchid "
    "pale papaya peach peru pink plum powder puff purple red rose rosy royal "
    "saddle salmon sandy seashell sienna sky slate smoke snow spring steel "
    "tan thistle tomato turquoise violet wheat white yellow"
).split()

# Comment vocabulary: includes the marker words the standard queries grep
# for (Q13 '%special%requests%', Q16 '%Customer%Complaints%', Q20 like).
_COMMENT_WORDS = (
    "carefully bold final ironic regular express silent pending furious "
    "quickly blithely slyly fluffily even special unusual packages requests "
    "deposits accounts instructions theodolites dependencies foxes pinto "
    "beans asymptotes dolphins platelets sleep wake haggle nag use cajole "
    "engage detect integrate maintain print Customer Complaints Recommends "
    "among about above across after against along"
).split()

_TEXT_SPACE = 8192  # distinct comments per column (capped pseudo-text space)

DATE_LO = 8035     # 1992-01-01 as days since epoch
DATE_HI = 10591    # 1998-12-31
CURRENT_DATE = 9298  # 1995-06-17, the spec's "currentdate"


def _comment_dictionary(stream: int, min_words: int, max_words: int) -> Dictionary:
    """The capped pseudo-text space for one comment column."""
    n = _TEXT_SPACE
    counts = u_int(stream + 1, np.arange(n), min_words, max_words)
    total = int(counts.sum())
    word_ids = u_int(stream + 2, np.arange(total), 0, len(_COMMENT_WORDS) - 1)
    out: List[str] = []
    pos = 0
    for c in counts:
        out.append(" ".join(_COMMENT_WORDS[w] for w in word_ids[pos:pos + c]))
        pos += int(c)
    return Dictionary(out)


_COMMENT_CACHE: Dict[int, Dictionary] = {}


def _comments(stream: int, keys: np.ndarray) -> Column:
    d = _COMMENT_CACHE.get(stream)
    if d is None:
        d = _comment_dictionary(stream, 5, 11)
        _COMMENT_CACHE[stream] = d
    codes = (h64(stream, keys) % np.uint64(_TEXT_SPACE)).astype(np.int32)
    return Column(T.VARCHAR, codes, None, d)


_ENUM_CACHE: Dict[tuple, Dictionary] = {}


def _enum_column(stream: int, keys: np.ndarray, values: List[str]) -> Column:
    codes = (h64(stream, keys) % np.uint64(len(values))).astype(np.int32)
    # one Dictionary instance per enum domain: downstream kernel caches
    # key on dictionary identity, so a fresh object per scan would force
    # a re-trace of every string expression on every query
    d = _ENUM_CACHE.get(tuple(values))
    if d is None:
        d = _ENUM_CACHE.setdefault(tuple(values), Dictionary(values))
    return Column(T.VARCHAR, codes, None, d)


def _interned_dict(values: tuple) -> Dictionary:
    """One Dictionary instance per enum domain, process-wide.  Kernel
    caches (filter/project AND fused segments) key on the dictionary
    binding (token, length): a fresh Dictionary per generated batch gave
    every execution fresh tokens, forcing one full segment recompile per
    query — measured ~0.4 s of the 0.54 s warm SF0.05 Q1 engine wall."""
    d = _ENUM_CACHE.get(values)
    if d is None:
        d = _ENUM_CACHE.setdefault(values, Dictionary(list(values)))
    return d


def _fmt_column(prefix: str, keys: np.ndarray) -> Column:
    """Per-row-distinct formatted identifier column, e.g. Customer#000000001."""
    d = Dictionary([f"{prefix}#{int(k):09d}" for k in keys])
    return Column(T.VARCHAR, np.arange(len(keys), dtype=np.int32), None, d)


def _phone_column(stream: int, keys: np.ndarray, nationkey: np.ndarray) -> Column:
    a = u_int(stream + 1, keys, 100, 999)
    b = u_int(stream + 2, keys, 100, 999)
    c = u_int(stream + 3, keys, 1000, 9999)
    cc = nationkey + 10
    d = Dictionary([f"{int(cc[i]):02d}-{int(a[i])}-{int(b[i])}-{int(c[i])}"
                    for i in range(len(keys))])
    return Column(T.VARCHAR, np.arange(len(keys), dtype=np.int32), None, d)


def _address_column(stream: int, keys: np.ndarray) -> Column:
    return _comments(stream ^ 0x5555, keys)


def _money(values_cents: np.ndarray, money_type: T.Type) -> Column:
    if isinstance(money_type, T.DecimalType):
        return Column(money_type, values_cents.astype(np.int64))
    return Column(T.DOUBLE, values_cents.astype(np.float64) / 100.0)


def retail_price_cents(partkey: np.ndarray) -> np.ndarray:
    """p_retailprice per the spec formula (TPC-H 4.3 §4.2.3), in cents."""
    p = partkey.astype(np.int64)
    return 90000 + (p // 10) % 20001 + 100 * (p % 1000)


# ---------------------------------------------------------------------------
# Table generators
# ---------------------------------------------------------------------------

# stream-id bases per table; column streams are base+i
_S_NATION, _S_REGION, _S_SUPP, _S_CUST, _S_PART, _S_PSUPP, _S_ORD, _S_LINE = (
    1000, 2000, 3000, 4000, 5000, 6000, 7000, 8000)


# Largest full-domain interning table the generator pre-builds for a
# per-row-distinct column (names, phones): above this, the host-string
# cost of the whole domain outweighs the per-split retrace it prevents
# and generation falls back to per-split dictionaries.
_SHARED_DICT_MAX = 1 << 20


class TpchGenerator:
    """Vectorized per-range column generation for all eight tables."""

    def __init__(self, scale: float = 1.0, money: str = "double"):
        import threading

        self.scale = scale
        self.money_type: T.Type = (
            T.DecimalType("decimal", 15, 2) if money == "decimal" else T.DOUBLE)
        self.n_supplier = max(int(10_000 * scale), 1)
        self.n_customer = max(int(150_000 * scale), 1)
        self.n_part = max(int(200_000 * scale), 1)
        self.n_orders = max(int(1_500_000 * scale), 1)
        self.n_clerks = max(int(1_000 * scale), 1)
        # per-(table, column) full-domain interning tables shared by
        # every split (stable (token, length) -> the unfused tier
        # compiles each expression once per table, not once per split)
        self._dict_cache: Dict[str, Dictionary] = {}
        self._dict_lock = threading.Lock()

    def _shared_dict(self, name: str, domain: int,
                     build) -> Optional[Dictionary]:
        """The full-domain dictionary for ``name``, built once under a
        lock (concurrent feed drivers race on first use); None when the
        domain is too large to pre-build."""
        if domain > _SHARED_DICT_MAX:
            return None
        d = self._dict_cache.get(name)
        if d is not None:
            return d
        with self._dict_lock:
            d = self._dict_cache.get(name)
            if d is None:
                d = Dictionary(build())
                self._dict_cache[name] = d
        return d

    def _fmt_shared(self, name: str, prefix: str, keys: np.ndarray,
                    lo: int, hi: int) -> Column:
        """Per-row-distinct formatted identifier over the table's full
        key domain: codes are ``key - lo`` so every split indexes one
        shared dictionary."""
        d = self._shared_dict(
            name, hi - lo,
            lambda: [f"{prefix}#{k:09d}" for k in range(lo, hi)])
        if d is None:
            return _fmt_column(prefix, keys)
        return Column(T.VARCHAR, (keys - lo).astype(np.int32), None, d)

    def _phone_shared(self, name: str, stream: int, nk_stream: int,
                      keys: np.ndarray, nationkey: np.ndarray,
                      lo: int, hi: int) -> Column:
        def build():
            ks = np.arange(lo, hi, dtype=np.int64)
            nk = u_int(nk_stream, ks, 0, 24)
            a = u_int(stream + 1, ks, 100, 999)
            b = u_int(stream + 2, ks, 100, 999)
            c = u_int(stream + 3, ks, 1000, 9999)
            cc = nk + 10
            return [f"{int(cc[i]):02d}-{int(a[i])}-{int(b[i])}-{int(c[i])}"
                    for i in range(len(ks))]

        d = self._shared_dict(name, hi - lo, build)
        if d is None:
            return _phone_column(stream, keys, nationkey)
        return Column(T.VARCHAR, (keys - lo).astype(np.int32), None, d)

    def _pname_column(self, keys: np.ndarray) -> Column:
        """P_NAME: five color words per part key (spec's P_NAME), over
        the table's full key domain so every split shares one
        dictionary."""
        def words(ks: np.ndarray) -> list:
            ids = [u_int(_S_PART + 10 + i, ks, 0, len(COLORS) - 1)
                   for i in range(5)]
            return [" ".join(COLORS[int(ids[i][j])] for i in range(5))
                    for j in range(len(ks))]

        d = self._shared_dict(
            "part:p_name", self.n_part,
            lambda: words(np.arange(1, self.n_part + 1, dtype=np.int64)))
        if d is None:
            return Column(T.VARCHAR,
                          np.arange(len(keys), dtype=np.int32), None,
                          Dictionary(words(keys)))
        return Column(T.VARCHAR, (keys - 1).astype(np.int32), None, d)

    # -- tiny fixed tables ----------------------------------------------
    def gen_region(self, columns: Sequence[str]) -> Batch:
        keys = np.arange(5, dtype=np.int64)
        cols = []
        for c in columns:
            if c == "r_regionkey":
                cols.append(Column(T.BIGINT, keys))
            elif c == "r_name":
                cols.append(Column(T.VARCHAR, np.arange(5, dtype=np.int32),
                                   None, _interned_dict(tuple(REGIONS))))
            elif c == "r_comment":
                cols.append(_comments(_S_REGION + 2, keys))
            else:
                raise KeyError(c)
        return Batch(tuple(cols), 5)

    def gen_nation(self, columns: Sequence[str]) -> Batch:
        keys = np.arange(25, dtype=np.int64)
        cols = []
        for c in columns:
            if c == "n_nationkey":
                cols.append(Column(T.BIGINT, keys))
            elif c == "n_name":
                cols.append(Column(T.VARCHAR, np.arange(25, dtype=np.int32),
                                   None, _interned_dict(
                                       tuple(n for n, _ in NATIONS))))
            elif c == "n_regionkey":
                cols.append(Column(
                    T.BIGINT, np.array([r for _, r in NATIONS], dtype=np.int64)))
            elif c == "n_comment":
                cols.append(_comments(_S_NATION + 3, keys))
            else:
                raise KeyError(c)
        return Batch(tuple(cols), 25)

    # -- entity tables ---------------------------------------------------
    def gen_supplier(self, columns: Sequence[str], lo: int, hi: int) -> Batch:
        keys = np.arange(lo, hi, dtype=np.int64)  # s_suppkey, 1-based
        nationkey = u_int(_S_SUPP + 3, keys, 0, 24)
        cols = []
        for c in columns:
            if c == "s_suppkey":
                cols.append(Column(T.BIGINT, keys))
            elif c == "s_name":
                cols.append(self._fmt_shared("supplier:s_name", "Supplier",
                                             keys, 1, self.n_supplier + 1))
            elif c == "s_address":
                cols.append(_address_column(_S_SUPP + 2, keys))
            elif c == "s_nationkey":
                cols.append(Column(T.BIGINT, nationkey))
            elif c == "s_phone":
                cols.append(self._phone_shared(
                    "supplier:s_phone", _S_SUPP + 4, _S_SUPP + 3, keys,
                    nationkey, 1, self.n_supplier + 1))
            elif c == "s_acctbal":
                cols.append(_money(u_int(_S_SUPP + 5, keys, -99_999, 999_999),
                                   self.money_type))
            elif c == "s_comment":
                cols.append(_comments(_S_SUPP + 6, keys))
            else:
                raise KeyError(c)
        return Batch(tuple(cols), hi - lo)

    def gen_customer(self, columns: Sequence[str], lo: int, hi: int) -> Batch:
        keys = np.arange(lo, hi, dtype=np.int64)  # c_custkey
        nationkey = u_int(_S_CUST + 3, keys, 0, 24)
        cols = []
        for c in columns:
            if c == "c_custkey":
                cols.append(Column(T.BIGINT, keys))
            elif c == "c_name":
                cols.append(self._fmt_shared("customer:c_name", "Customer",
                                             keys, 1, self.n_customer + 1))
            elif c == "c_address":
                cols.append(_address_column(_S_CUST + 2, keys))
            elif c == "c_nationkey":
                cols.append(Column(T.BIGINT, nationkey))
            elif c == "c_phone":
                cols.append(self._phone_shared(
                    "customer:c_phone", _S_CUST + 4, _S_CUST + 3, keys,
                    nationkey, 1, self.n_customer + 1))
            elif c == "c_acctbal":
                cols.append(_money(u_int(_S_CUST + 5, keys, -99_999, 999_999),
                                   self.money_type))
            elif c == "c_mktsegment":
                cols.append(_enum_column(_S_CUST + 6, keys, SEGMENTS))
            elif c == "c_comment":
                cols.append(_comments(_S_CUST + 7, keys))
            else:
                raise KeyError(c)
        return Batch(tuple(cols), hi - lo)

    def gen_part(self, columns: Sequence[str], lo: int, hi: int) -> Batch:
        keys = np.arange(lo, hi, dtype=np.int64)  # p_partkey
        cols = []
        for c in columns:
            if c == "p_partkey":
                cols.append(Column(T.BIGINT, keys))
            elif c == "p_name":
                cols.append(self._pname_column(keys))
            elif c == "p_mfgr":
                m = u_int(_S_PART + 2, keys, 1, 5)
                d = _interned_dict(tuple(
                    f"Manufacturer#{i}" for i in range(1, 6)))
                cols.append(Column(T.VARCHAR, (m - 1).astype(np.int32), None, d))
            elif c == "p_brand":
                # brand = mfgr*10 + 1..5 (spec ties brand to mfgr)
                m = u_int(_S_PART + 2, keys, 1, 5)
                n = u_int(_S_PART + 3, keys, 1, 5)
                code = ((m - 1) * 5 + (n - 1)).astype(np.int32)
                d = _interned_dict(tuple(
                    f"Brand#{i}{j}" for i in range(1, 6)
                    for j in range(1, 6)))
                cols.append(Column(T.VARCHAR, code, None, d))
            elif c == "p_type":
                t = u_int(_S_PART + 4, keys, 0,
                          len(TYPE_S1) * len(TYPE_S2) * len(TYPE_S3) - 1)
                d = _interned_dict(tuple(
                    f"{a} {b} {c2}" for a in TYPE_S1
                    for b in TYPE_S2 for c2 in TYPE_S3))
                cols.append(Column(T.VARCHAR, t.astype(np.int32), None, d))
            elif c == "p_size":
                cols.append(Column(T.BIGINT, u_int(_S_PART + 5, keys, 1, 50)))
            elif c == "p_container":
                t = u_int(_S_PART + 6, keys, 0,
                          len(CONTAINER_S1) * len(CONTAINER_S2) - 1)
                d = _interned_dict(tuple(
                    f"{a} {b}" for a in CONTAINER_S1
                    for b in CONTAINER_S2))
                cols.append(Column(T.VARCHAR, t.astype(np.int32), None, d))
            elif c == "p_retailprice":
                cols.append(_money(retail_price_cents(keys), self.money_type))
            elif c == "p_comment":
                cols.append(_comments(_S_PART + 8, keys))
            else:
                raise KeyError(c)
        return Batch(tuple(cols), hi - lo)

    def _psupp_suppkey(self, partkey: np.ndarray, i: np.ndarray) -> np.ndarray:
        """Supplier-spread formula (TPC-H 4.3 §4.2.3 shape): the i-th of 4
        suppliers for a part, scattered across the supplier space.  Unlike
        dbgen's exact formula this guarantees 4 *distinct* suppliers at any
        scale (i*(S//4) < S for i<4), which the spec requires and tiny test
        scales would otherwise violate."""
        s = self.n_supplier
        return (partkey + i * max(s // 4, 1)) % s + 1

    def gen_partsupp(self, columns: Sequence[str], lo: int, hi: int) -> Batch:
        """Range is over partkeys; each part contributes 4 rows."""
        pk = np.repeat(np.arange(lo, hi, dtype=np.int64), 4)
        i = np.tile(np.arange(4, dtype=np.int64), hi - lo)
        rowkey = pk * 4 + i
        cols = []
        for c in columns:
            if c == "ps_partkey":
                cols.append(Column(T.BIGINT, pk))
            elif c == "ps_suppkey":
                cols.append(Column(T.BIGINT, self._psupp_suppkey(pk, i)))
            elif c == "ps_availqty":
                cols.append(Column(T.BIGINT, u_int(_S_PSUPP + 3, rowkey, 1, 9999)))
            elif c == "ps_supplycost":
                cols.append(_money(u_int(_S_PSUPP + 4, rowkey, 100, 100_000),
                                   self.money_type))
            elif c == "ps_comment":
                cols.append(_comments(_S_PSUPP + 5, rowkey))
            else:
                raise KeyError(c)
        return Batch(tuple(cols), len(pk))

    # -- orders & lineitem ----------------------------------------------
    def _order_custkey(self, okey: np.ndarray) -> np.ndarray:
        """2/3-customer rule: orders reference only custkeys % 3 != 0."""
        m = (self.n_customer // 3) * 2
        u = h64(_S_ORD + 2, okey) % np.uint64(max(m, 1))
        u = u.astype(np.int64)
        return u // 2 * 3 + u % 2 + 1

    def _order_date(self, okey: np.ndarray) -> np.ndarray:
        return u_int(_S_ORD + 5, okey, DATE_LO, DATE_HI - 151).astype(np.int32)

    def _line_counts(self, okey: np.ndarray) -> np.ndarray:
        return u_int(_S_LINE + 1, okey, 1, 7)

    def _line_parts(self, okey: np.ndarray, ln: np.ndarray):
        """Per-(order, linenumber) part/supplier/qty/discount/tax/dates."""
        rk = okey * 8 + ln  # row key for per-line streams
        partkey = u_int(_S_LINE + 2, rk, 1, self.n_part)
        supp_i = u_int(_S_LINE + 3, rk, 0, 3)
        suppkey = self._psupp_suppkey(partkey, supp_i)
        quantity = u_int(_S_LINE + 4, rk, 1, 50)
        discount = u_int(_S_LINE + 5, rk, 0, 10)   # cents-of-dollar (0.00-0.10)
        tax = u_int(_S_LINE + 6, rk, 0, 8)
        odate = self._order_date(okey)
        shipdate = odate + u_int(_S_LINE + 7, rk, 1, 121).astype(np.int32)
        commitdate = odate + u_int(_S_LINE + 8, rk, 30, 90).astype(np.int32)
        receiptdate = shipdate + u_int(_S_LINE + 9, rk, 1, 30).astype(np.int32)
        ext_cents = quantity * retail_price_cents(partkey)
        return (partkey, suppkey, quantity, discount, tax, shipdate,
                commitdate, receiptdate, ext_cents)

    def _order_totals(self, okey: np.ndarray):
        """o_totalprice (cents) and o_orderstatus derived from the order's
        lineitems, computed vectorized over the max-7 line slots."""
        counts = self._line_counts(okey)
        total = np.zeros(len(okey), dtype=np.int64)
        n_open = np.zeros(len(okey), dtype=np.int64)
        for line in range(1, 8):
            mask = counts >= line
            ln = np.full(len(okey), line, dtype=np.int64)
            (_, _, _, disc, tax, shipdate, _, _, ext) = self._line_parts(okey, ln)
            # extendedprice * (1 - discount) * (1 + tax), in cents
            line_total = ext * (100 - disc) * (100 + tax) // 10_000
            total += np.where(mask, line_total, 0)
            n_open += np.where(mask & (shipdate > CURRENT_DATE), 1, 0)
        status = np.where(n_open == 0, 0, np.where(n_open == counts, 1, 2))
        return total, status  # status codes into ["F", "O", "P"]

    def gen_orders(self, columns: Sequence[str], lo: int, hi: int) -> Batch:
        okey = np.arange(lo, hi, dtype=np.int64)
        cols = []
        totals = statuses = None
        for c in columns:
            if c == "o_orderkey":
                cols.append(Column(T.BIGINT, okey))
            elif c == "o_custkey":
                cols.append(Column(T.BIGINT, self._order_custkey(okey)))
            elif c == "o_orderstatus":
                if statuses is None:
                    totals, statuses = self._order_totals(okey)
                cols.append(Column(T.VARCHAR, statuses.astype(np.int32), None,
                                   _interned_dict(("F", "O", "P"))))
            elif c == "o_totalprice":
                if totals is None:
                    totals, statuses = self._order_totals(okey)
                cols.append(_money(totals, self.money_type))
            elif c == "o_orderdate":
                cols.append(Column(T.DATE, self._order_date(okey)))
            elif c == "o_orderpriority":
                cols.append(_enum_column(_S_ORD + 6, okey, PRIORITIES))
            elif c == "o_clerk":
                clerk = u_int(_S_ORD + 7, okey, 1, self.n_clerks)
                d = self._shared_dict(
                    "orders:o_clerk", self.n_clerks,
                    lambda: [f"Clerk#{i:09d}"
                             for i in range(1, self.n_clerks + 1)])
                if d is None:
                    d = Dictionary([f"Clerk#{i:09d}"
                                    for i in range(1, self.n_clerks + 1)])
                cols.append(Column(T.VARCHAR, (clerk - 1).astype(np.int32),
                                   None, d))
            elif c == "o_shippriority":
                cols.append(Column(T.BIGINT, np.zeros(hi - lo, dtype=np.int64)))
            elif c == "o_comment":
                cols.append(_comments(_S_ORD + 9, okey))
            else:
                raise KeyError(c)
        return Batch(tuple(cols), hi - lo)

    def gen_lineitem(self, columns: Sequence[str], lo: int, hi: int) -> Batch:
        """Range is over ORDER keys; emits all lineitems of those orders."""
        okeys = np.arange(lo, hi, dtype=np.int64)
        counts = self._line_counts(okeys)
        okey = np.repeat(okeys, counts)
        offsets = np.concatenate([[0], np.cumsum(counts)[:-1]])
        ln = (np.arange(len(okey), dtype=np.int64)
              - np.repeat(offsets, counts) + 1)
        (partkey, suppkey, quantity, discount, tax, shipdate, commitdate,
         receiptdate, ext_cents) = self._line_parts(okey, ln)
        rk = okey * 8 + ln
        cols = []
        for c in columns:
            if c == "l_orderkey":
                cols.append(Column(T.BIGINT, okey))
            elif c == "l_partkey":
                cols.append(Column(T.BIGINT, partkey))
            elif c == "l_suppkey":
                cols.append(Column(T.BIGINT, suppkey))
            elif c == "l_linenumber":
                cols.append(Column(T.BIGINT, ln))
            elif c == "l_quantity":
                cols.append(Column(T.DOUBLE, quantity.astype(np.float64))
                            if not isinstance(self.money_type, T.DecimalType)
                            else Column(T.DecimalType("decimal", 12, 2),
                                        quantity * 100))
            elif c == "l_extendedprice":
                cols.append(_money(ext_cents, self.money_type))
            elif c == "l_discount":
                cols.append(Column(T.DOUBLE, discount.astype(np.float64) / 100.0)
                            if not isinstance(self.money_type, T.DecimalType)
                            else Column(T.DecimalType("decimal", 12, 2), discount))
            elif c == "l_tax":
                cols.append(Column(T.DOUBLE, tax.astype(np.float64) / 100.0)
                            if not isinstance(self.money_type, T.DecimalType)
                            else Column(T.DecimalType("decimal", 12, 2), tax))
            elif c == "l_returnflag":
                returned = receiptdate <= CURRENT_DATE
                coin = (h64(_S_LINE + 10, rk) & np.uint64(1)).astype(bool)
                code = np.where(returned, np.where(coin, 0, 1), 2).astype(np.int32)
                cols.append(Column(T.VARCHAR, code, None,
                                   _interned_dict(("R", "A", "N"))))
            elif c == "l_linestatus":
                code = (shipdate > CURRENT_DATE).astype(np.int32)
                cols.append(Column(T.VARCHAR, code, None,
                                   _interned_dict(("F", "O"))))
            elif c == "l_shipdate":
                cols.append(Column(T.DATE, shipdate.astype(np.int32)))
            elif c == "l_commitdate":
                cols.append(Column(T.DATE, commitdate.astype(np.int32)))
            elif c == "l_receiptdate":
                cols.append(Column(T.DATE, receiptdate.astype(np.int32)))
            elif c == "l_shipinstruct":
                cols.append(_enum_column(_S_LINE + 11, rk, INSTRUCTIONS))
            elif c == "l_shipmode":
                cols.append(_enum_column(_S_LINE + 12, rk, SHIP_MODES))
            elif c == "l_comment":
                cols.append(_comments(_S_LINE + 13, rk))
            else:
                raise KeyError(c)
        return Batch(tuple(cols), len(okey))


# ---------------------------------------------------------------------------
# Schemas
# ---------------------------------------------------------------------------

def _schemas(money: T.Type, qty: T.Type) -> Dict[str, List[Tuple[str, T.Type]]]:
    V = T.VARCHAR
    return {
        "region": [("r_regionkey", T.BIGINT), ("r_name", V), ("r_comment", V)],
        "nation": [("n_nationkey", T.BIGINT), ("n_name", V),
                   ("n_regionkey", T.BIGINT), ("n_comment", V)],
        "supplier": [("s_suppkey", T.BIGINT), ("s_name", V), ("s_address", V),
                     ("s_nationkey", T.BIGINT), ("s_phone", V),
                     ("s_acctbal", money), ("s_comment", V)],
        "customer": [("c_custkey", T.BIGINT), ("c_name", V), ("c_address", V),
                     ("c_nationkey", T.BIGINT), ("c_phone", V),
                     ("c_acctbal", money), ("c_mktsegment", V),
                     ("c_comment", V)],
        "part": [("p_partkey", T.BIGINT), ("p_name", V), ("p_mfgr", V),
                 ("p_brand", V), ("p_type", V), ("p_size", T.BIGINT),
                 ("p_container", V), ("p_retailprice", money),
                 ("p_comment", V)],
        "partsupp": [("ps_partkey", T.BIGINT), ("ps_suppkey", T.BIGINT),
                     ("ps_availqty", T.BIGINT), ("ps_supplycost", money),
                     ("ps_comment", V)],
        "orders": [("o_orderkey", T.BIGINT), ("o_custkey", T.BIGINT),
                   ("o_orderstatus", V), ("o_totalprice", money),
                   ("o_orderdate", T.DATE), ("o_orderpriority", V),
                   ("o_clerk", V), ("o_shippriority", T.BIGINT),
                   ("o_comment", V)],
        "lineitem": [("l_orderkey", T.BIGINT), ("l_partkey", T.BIGINT),
                     ("l_suppkey", T.BIGINT), ("l_linenumber", T.BIGINT),
                     ("l_quantity", qty), ("l_extendedprice", money),
                     ("l_discount", qty), ("l_tax", qty),
                     ("l_returnflag", V), ("l_linestatus", V),
                     ("l_shipdate", T.DATE), ("l_commitdate", T.DATE),
                     ("l_receiptdate", T.DATE), ("l_shipinstruct", V),
                     ("l_shipmode", V), ("l_comment", V)],
    }


class _TpchPageSource(PageSource):
    def __init__(self, gen: TpchGenerator, table: str, columns: Sequence[str],
                 lo: int, hi: int, batch_rows: int):
        self.gen, self.table, self.columns = gen, table, list(columns)
        self.lo, self.hi, self.batch_rows = lo, hi, batch_rows

    def __iter__(self):
        if self.table in ("region", "nation"):
            gen = (self.gen.gen_region if self.table == "region"
                   else self.gen.gen_nation)
            full = gen(self.columns)
            # honor the split's key range (keys == row indices here)
            import numpy as np

            yield full.take(np.arange(self.lo, min(self.hi, full.num_rows)))
            return
        fn = {
            "supplier": self.gen.gen_supplier,
            "customer": self.gen.gen_customer,
            "part": self.gen.gen_part,
            "partsupp": self.gen.gen_partsupp,
            "orders": self.gen.gen_orders,
            "lineitem": self.gen.gen_lineitem,
        }[self.table]
        # partsupp expands x4 and lineitem ~x4 per key; shrink key step so
        # emitted batches stay near batch_rows
        step = self.batch_rows // 4 if self.table in ("partsupp", "lineitem") \
            else self.batch_rows
        step = max(step, 1)
        for lo in range(self.lo, self.hi, step):
            yield fn(self.columns, lo, min(lo + step, self.hi))


class TpchConnector(Connector):
    """The tpch catalog: tables generated on the fly at a given scale."""

    # generated data never changes: whole-query programs
    # may cache device-resident scans
    immutable_data = True

    name = "tpch"

    def __init__(self, scale: float = 1.0, money: str = "double"):
        self.generator = TpchGenerator(scale, money)
        money_t = self.generator.money_type
        qty_t = (T.DecimalType("decimal", 12, 2)
                 if isinstance(money_t, T.DecimalType) else T.DOUBLE)
        self._schemas = {
            name: TableSchema(name, tuple(ColumnMetadata(n, t) for n, t in cols))
            for name, cols in _schemas(money_t, qty_t).items()
        }
        self._stats_cache: Dict[str, TableStatistics] = {}

    # -- key ranges per table (split domain) -----------------------------
    def _key_range(self, table: str) -> Tuple[int, int]:
        g = self.generator
        return {
            "region": (0, 5), "nation": (0, 25),
            "supplier": (1, g.n_supplier + 1),
            "customer": (1, g.n_customer + 1),
            "part": (1, g.n_part + 1),
            "partsupp": (1, g.n_part + 1),     # keyed by part
            "orders": (1, g.n_orders + 1),
            "lineitem": (1, g.n_orders + 1),   # keyed by order
        }[table]

    def row_count(self, table: str) -> int:
        g = self.generator
        return {
            "region": 5, "nation": 25, "supplier": g.n_supplier,
            "customer": g.n_customer, "part": g.n_part,
            "partsupp": 4 * g.n_part, "orders": g.n_orders,
            "lineitem": 4 * g.n_orders,  # expected 4/order
        }[table]

    _SORT_ORDER = {
        "supplier": ["s_suppkey"], "customer": ["c_custkey"],
        "part": ["p_partkey"], "partsupp": ["ps_partkey", "ps_suppkey"],
        "orders": ["o_orderkey"],
        "lineitem": ["l_orderkey", "l_linenumber"],
        "nation": ["n_nationkey"], "region": ["r_regionkey"],
    }

    def sort_order(self, handle: TableHandle) -> List[str]:
        """Generation order: every table is emitted ascending by its
        surrogate key (lineitem clustered by orderkey, then line
        number) — the property StreamingAggregation exploits."""
        return list(self._SORT_ORDER.get(handle.table, []))

    # which column IS the split-range key of each table (the implicit
    # bucketing column, TpchNodePartitioningProvider role)
    _BUCKET_COLUMN = {
        "supplier": "s_suppkey", "customer": "c_custkey",
        "part": "p_partkey", "partsupp": "ps_partkey",
        "orders": "o_orderkey", "lineitem": "l_orderkey",
    }

    def bucket_splits(self, handle: TableHandle, column: str,
                      n_buckets: int):
        """Range buckets over the key domain: orders and lineitem share
        the orderkey domain, so joins on it co-partition exactly (the
        grouped-execution qualifier, Lifespan.java:26)."""
        if self._BUCKET_COLUMN.get(handle.table) != column:
            return None
        lo, hi = self._key_range(handle.table)
        n = hi - lo
        if n < n_buckets:
            return None
        per = -(-n // n_buckets)
        mult = 4 if handle.table in ("partsupp", "lineitem") else 1
        buckets: List[List[Split]] = []
        for b in range(n_buckets):
            blo = lo + b * per
            bhi = min(blo + per, hi)
            if blo >= bhi:
                buckets.append([])
                continue
            buckets.append([Split(handle, (blo, bhi),
                                  estimated_rows=(bhi - blo) * mult)])
        return (lo, hi), buckets

    def list_tables(self) -> List[str]:
        return sorted(self._schemas)

    def get_table(self, table: str) -> Optional[TableHandle]:
        if table not in self._schemas:
            return None
        return TableHandle(self.name, table, extra=self.generator.scale)

    def table_schema(self, handle: TableHandle) -> TableSchema:
        return self._schemas[handle.table]

    def table_statistics(self, handle: TableHandle) -> TableStatistics:
        """Analytic column statistics from the generator's parameters
        (the reference's presto-tpch ships exact ColumnStatistics the same
        way — TpchMetadata.getTableStatistics — because counter-based
        generation makes NDVs and ranges closed-form, no ANALYZE pass)."""
        stats = self._stats_cache.get(handle.table)
        if stats is None:
            stats = self._compute_statistics(handle.table)
            self._stats_cache[handle.table] = stats
        return stats

    def _compute_statistics(self, table: str) -> TableStatistics:
        import datetime as _dt

        def day(days: int) -> _dt.date:
            return _dt.date(1970, 1, 1) + _dt.timedelta(days=int(days))

        g = self.generator
        rows = float(self.row_count(table))
        ts = TableStatistics(row_count=rows)
        nc, ns, np_, no = (g.n_customer, g.n_supplier, g.n_part, g.n_orders)

        def put(col, ndv, lo=None, hi=None):
            ts.ndv[col] = float(min(ndv, rows))
            if lo is not None:
                ts.low[col] = lo
                ts.high[col] = hi

        if table == "region":
            put("r_regionkey", 5, 0, 4)
            put("r_name", 5)
        elif table == "nation":
            put("n_nationkey", 25, 0, 24)
            put("n_name", 25)
            put("n_regionkey", 5, 0, 4)
        elif table == "supplier":
            put("s_suppkey", ns, 1, ns)
            put("s_name", ns)
            put("s_nationkey", 25, 0, 24)
            put("s_acctbal", min(rows, 1_099_999), -999.99, 9999.99)
        elif table == "customer":
            put("c_custkey", nc, 1, nc)
            put("c_name", nc)
            put("c_nationkey", 25, 0, 24)
            put("c_acctbal", min(rows, 1_099_999), -999.99, 9999.99)
            put("c_mktsegment", len(SEGMENTS))
        elif table == "part":
            put("p_partkey", np_, 1, np_)
            put("p_name", np_)
            put("p_mfgr", 5)
            put("p_brand", 25)
            put("p_type", len(TYPE_S1) * len(TYPE_S2) * len(TYPE_S3))
            put("p_size", 50, 1, 50)
            put("p_container", len(CONTAINER_S1) * len(CONTAINER_S2))
            put("p_retailprice", 20001, 900.00, 2099.00)
        elif table == "partsupp":
            put("ps_partkey", np_, 1, np_)
            put("ps_suppkey", ns, 1, ns)
            put("ps_availqty", 9999, 1, 9999)
            put("ps_supplycost", 99_901, 1.00, 1000.00)
        elif table == "orders":
            put("o_orderkey", no, 1, no)
            put("o_custkey", max((nc // 3) * 2, 1), 1, nc)
            put("o_orderstatus", 3)
            put("o_totalprice", rows, 810.00, 600_000.00)
            put("o_orderdate", DATE_HI - 151 - DATE_LO + 1,
                day(DATE_LO), day(DATE_HI - 151))
            put("o_orderpriority", len(PRIORITIES))
            put("o_clerk", g.n_clerks)
            put("o_shippriority", 1, 0, 0)
        elif table == "lineitem":
            put("l_orderkey", no, 1, no)
            put("l_partkey", np_, 1, np_)
            put("l_suppkey", ns, 1, ns)
            put("l_linenumber", 7, 1, 7)
            put("l_quantity", 50, 1.0, 50.0)
            put("l_extendedprice", rows / 10, 900.00, 104_950.00)
            put("l_discount", 11, 0.00, 0.10)
            put("l_tax", 9, 0.00, 0.08)
            put("l_returnflag", 3)
            put("l_linestatus", 2)
            put("l_shipdate", DATE_HI - 151 + 121 - DATE_LO,
                day(DATE_LO + 1), day(DATE_HI - 151 + 121))
            put("l_commitdate", DATE_HI - 151 + 90 - DATE_LO - 30,
                day(DATE_LO + 30), day(DATE_HI - 151 + 90))
            put("l_receiptdate", DATE_HI - 151 + 151 - DATE_LO,
                day(DATE_LO + 2), day(DATE_HI - 151 + 151))
            put("l_shipinstruct", len(INSTRUCTIONS))
            put("l_shipmode", len(SHIP_MODES))
        return ts

    def get_splits(self, handle: TableHandle, desired_splits: int) -> List[Split]:
        lo, hi = self._key_range(handle.table)
        n = hi - lo
        desired = max(1, min(desired_splits, n))
        per = -(-n // desired)
        out = []
        mult = 4 if handle.table in ("partsupp", "lineitem") else 1
        for start in range(lo, hi, per):
            end = min(start + per, hi)
            out.append(Split(handle, (start, end),
                             estimated_rows=(end - start) * mult))
        return out

    def page_source(self, split: Split, columns: Sequence[str],
                    batch_rows: int = 65536) -> PageSource:
        lo, hi = split.info
        return _TpchPageSource(self.generator, split.handle.table, columns,
                               lo, hi, batch_rows)
