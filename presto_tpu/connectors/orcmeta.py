"""Minimal ORC footer/metadata reader: per-stripe column min/max stats.

pyarrow reads ORC data but exposes no accessor for stripe statistics
(`ORCFile.nstripe_statistics` counts them; nothing returns the values),
so the stats-pruning tier parses the file tail itself — the same
protobuf metadata the reference's native reader consumes
(presto-orc/src/main/java/io/prestosql/orc/OrcReader.java:72 footer
parse; stripe-stats pruning drives OrcRecordReader.java:356 nextPage's
stripe skipping).  Only what pruning needs is decoded: PostScript,
Footer.types/statistics, Metadata.stripeStats with integer / double /
string / date min-max.

Layout (ORC spec): ... | metadata | footer | postscript | psLen(1B).
Footer/metadata are compression-chunked when compression != NONE; ZLIB
(raw deflate) and ZSTD are handled, other codecs yield None (callers
fall back to no pruning, never an error).
"""

from __future__ import annotations

import struct
from typing import Any, Dict, Iterator, List, Optional, Tuple

_NONE, _ZLIB, _SNAPPY, _LZO, _LZ4, _ZSTD = range(6)


def _varint(buf: bytes, i: int) -> Tuple[int, int]:
    out = 0
    shift = 0
    while True:
        b = buf[i]
        i += 1
        out |= (b & 0x7F) << shift
        if not b & 0x80:
            return out, i
        shift += 7


def _zigzag(v: int) -> int:
    return (v >> 1) ^ -(v & 1)


def _fields(buf: bytes) -> Iterator[Tuple[int, int, Any]]:
    """(field_number, wire_type, value) over one protobuf message.
    Wire 0 -> int, 2 -> bytes, 1/5 -> raw fixed bytes."""
    i = 0
    n = len(buf)
    while i < n:
        tag, i = _varint(buf, i)
        field, wire = tag >> 3, tag & 7
        if wire == 0:
            v, i = _varint(buf, i)
        elif wire == 2:
            ln, i = _varint(buf, i)
            v = buf[i:i + ln]
            i += ln
        elif wire == 5:
            v = buf[i:i + 4]
            i += 4
        elif wire == 1:
            v = buf[i:i + 8]
            i += 8
        else:  # groups (3/4): not in ORC protos
            raise ValueError(f"wire type {wire}")
        yield field, wire, v


def _decompress(buf: bytes, kind: int) -> Optional[bytes]:
    if kind == _NONE:
        return buf
    if kind == _ZLIB:
        import zlib

        dec = lambda b: zlib.decompress(b, wbits=-15)  # noqa: E731
    elif kind == _ZSTD:
        try:
            import zstandard
        except ImportError:
            return None
        dec = zstandard.ZstdDecompressor().decompress
    else:
        return None
    out = []
    i = 0
    while i + 3 <= len(buf):
        hdr = buf[i] | (buf[i + 1] << 8) | (buf[i + 2] << 16)
        i += 3
        ln, original = hdr >> 1, hdr & 1
        chunk = buf[i:i + ln]
        i += ln
        out.append(chunk if original else dec(chunk))
    return b"".join(out)


def _column_stat(buf: bytes) -> Dict[str, Any]:
    """ColumnStatistics -> {min, max, has_null, n} (min/max None when the
    type carries no orderable stats)."""
    st: Dict[str, Any] = {"min": None, "max": None, "has_null": None,
                          "n": None}
    for field, wire, v in _fields(buf):
        if field == 1 and wire == 0:
            st["n"] = v
        elif field == 10 and wire == 0:
            st["has_null"] = bool(v)
        elif field == 2 and wire == 2:      # IntegerStatistics
            for f2, w2, v2 in _fields(v):
                if f2 == 1 and w2 == 0:
                    st["min"] = _zigzag(v2)
                elif f2 == 2 and w2 == 0:
                    st["max"] = _zigzag(v2)
        elif field == 3 and wire == 2:      # DoubleStatistics
            for f2, w2, v2 in _fields(v):
                if f2 == 1 and w2 == 1:
                    st["min"] = struct.unpack("<d", v2)[0]
                elif f2 == 2 and w2 == 1:
                    st["max"] = struct.unpack("<d", v2)[0]
        elif field == 4 and wire == 2:      # StringStatistics
            for f2, w2, v2 in _fields(v):
                if f2 == 1 and w2 == 2:
                    st["min"] = v2.decode("utf-8", "replace")
                elif f2 == 2 and w2 == 2:
                    st["max"] = v2.decode("utf-8", "replace")
        elif field == 7 and wire == 2:      # DateStatistics (epoch days)
            for f2, w2, v2 in _fields(v):
                if f2 == 1 and w2 == 0:
                    st["min"] = _zigzag(v2)
                elif f2 == 2 and w2 == 0:
                    st["max"] = _zigzag(v2)
    return st


class OrcFileStats:
    """Parsed tail of one ORC file: column names (root struct fields)
    and per-stripe column stats aligned to them."""

    def __init__(self, column_names: List[str],
                 per_stripe: List[List[Dict[str, Any]]]):
        self.column_names = column_names
        self.per_stripe = per_stripe  # [stripe][data_column] -> stat

    @property
    def nstripes(self) -> int:
        return len(self.per_stripe)

    def stripe_column(self, stripe: int,
                      name: str) -> Optional[Dict[str, Any]]:
        if not 0 <= stripe < len(self.per_stripe):
            # stats pruning is strictly best-effort: a split enumerating
            # more stripes than the metadata covers must not fail the
            # query on an out-of-range index
            return None
        try:
            i = self.column_names.index(name)
        except ValueError:
            return None
        row = self.per_stripe[stripe]
        return row[i] if i < len(row) else None


def read_stripe_stats(path: str) -> Optional[OrcFileStats]:
    """None when the tail cannot be parsed (foreign codec, truncation,
    not-ORC) — pruning then simply does not happen."""
    try:
        return _read(path)
    except Exception:  # noqa: BLE001 - stats are an optimization only
        return None


def _read(path: str) -> Optional[OrcFileStats]:
    with open(path, "rb") as f:
        f.seek(0, 2)
        size = f.tell()
        tail_len = min(size, 1 << 20)
        f.seek(size - tail_len)
        tail = f.read(tail_len)
    ps_len = tail[-1]
    ps = tail[-1 - ps_len:-1]
    footer_len = metadata_len = 0
    compression = _NONE
    for field, wire, v in _fields(ps):
        if field == 1 and wire == 0:
            footer_len = v
        elif field == 2 and wire == 0:
            compression = v
        elif field == 5 and wire == 0:
            metadata_len = v
    need = 1 + ps_len + footer_len + metadata_len
    if need > len(tail):
        with open(path, "rb") as f:
            f.seek(size - need)
            tail = f.read(need)
    footer_raw = tail[-1 - ps_len - footer_len:-1 - ps_len]
    meta_raw = tail[-1 - ps_len - footer_len - metadata_len:
                    -1 - ps_len - footer_len]
    footer = _decompress(footer_raw, compression)
    metadata = _decompress(meta_raw, compression)
    if footer is None or metadata is None:
        return None

    # root struct's field names, in data-column order; stats index 0 is
    # the root itself, data column i maps to stats index i+1.  That flat
    # mapping holds ONLY when every root field is primitive: a nested
    # field (struct/list/map/union) owns additional Type entries whose
    # stats slots interleave, so the i+1 indexing would read the wrong
    # column's min/max.  Count the footer's Type entries and refuse the
    # mapping unless the tree is exactly root + one type per field.
    names: List[str] = []
    n_types = 0
    first_type = True
    for field, wire, v in _fields(footer):
        if field == 4 and wire == 2:
            n_types += 1
            if first_type:
                first_type = False
                for f2, w2, v2 in _fields(v):
                    if f2 == 3 and w2 == 2:
                        names.append(v2.decode("utf-8", "replace"))
    if n_types != len(names) + 1:
        return None     # nested schema: no safe flat stats mapping

    per_stripe: List[List[Dict[str, Any]]] = []
    for field, wire, v in _fields(metadata):
        if field == 1 and wire == 2:        # StripeStatistics
            cols = [_column_stat(v2) for f2, w2, v2 in _fields(v)
                    if f2 == 1 and w2 == 2]
            per_stripe.append(cols[1:len(names) + 1])  # drop root
    if not names or not per_stripe:
        return None
    return OrcFileStats(names, per_stripe)
