"""HTTP connector: tables served by a remote HTTP endpoint.

Role model: presto-example-http (presto-example-http/src/main/java/io/
prestosql/plugin/example/ExampleClient.java:41 — a metadata JSON
document fetched over HTTP maps tables to a list of CSV source URIs;
each URI becomes one split, fetched over the network at scan time by
ExampleRecordCursor).  This is the engine's proof that the connector
SPI reaches a real network protocol, not just files and sqlite.

Metadata document (fetched from ``metadata_uri``)::

    {"tables": [{"name": "numbers",
                 "columns": [{"name": "text", "type": "varchar"},
                             {"name": "value", "type": "bigint"}],
                 "sources": ["http://host/numbers-1.csv", ...]}]}

Each source URI is one Split (P5: source partitioning over network
shards); rows decode through the shared record-decoder tier
(connectors/decoder.py CSV rules).  Relative source URIs resolve
against the metadata URI.
"""

from __future__ import annotations

import json
import urllib.parse
import urllib.request
from typing import Dict, List, Optional, Sequence

from presto_tpu import types as T
from presto_tpu.batch import Batch, column_from_pylist
from presto_tpu.connectors.api import (
    ColumnMetadata, Connector, PageSource, Split, TableHandle, TableSchema,
)


class HttpConnector(Connector):
    name = "http"

    def __init__(self, metadata_uri: str, timeout_s: float = 30.0):
        self.metadata_uri = metadata_uri
        self.timeout_s = timeout_s
        self._tables: Optional[Dict[str, dict]] = None

    # -- metadata -------------------------------------------------------
    def _fetch(self, uri: str) -> bytes:
        with urllib.request.urlopen(uri, timeout=self.timeout_s) as resp:
            return resp.read()

    def _load(self) -> Dict[str, dict]:
        if self._tables is None:
            doc = json.loads(self._fetch(self.metadata_uri))
            self._tables = {t["name"]: t for t in doc.get("tables", [])}
        return self._tables

    def list_tables(self) -> List[str]:
        return sorted(self._load())

    def get_table(self, table: str) -> Optional[TableHandle]:
        if table not in self._load():
            raise KeyError(f"http table not found: {table}")
        return TableHandle("http", table)

    def table_schema(self, handle: TableHandle) -> TableSchema:
        doc = self._load()[handle.table]
        return TableSchema(handle.table, tuple(
            ColumnMetadata(c["name"], T.parse_type(c["type"].lower()))
            for c in doc["columns"]))

    # -- reads ----------------------------------------------------------
    def get_splits(self, handle: TableHandle,
                   desired_splits: int) -> List[Split]:
        doc = self._load()[handle.table]
        return [Split(handle,
                      urllib.parse.urljoin(self.metadata_uri, src))
                for src in doc.get("sources", [])]

    def page_source(self, split: Split, columns: Sequence[str],
                    batch_rows: int = 65536) -> PageSource:
        from presto_tpu.connectors.decoder import CsvRowDecoder

        schema = self.table_schema(split.handle)
        conn = self
        names = schema.column_names()
        types = {n: schema.column_type(n) for n in names}
        # decode the SELECTED columns through the shared record-decoder
        # tier: mapping = each column's field index in the CSV record
        decoder = CsvRowDecoder(
            [ColumnMetadata(c, types[c]) for c in columns],
            [str(names.index(c)) for c in columns])

        class _Source(PageSource):
            def __iter__(self):
                body = conn._fetch(split.info)
                rows: List[tuple] = []
                for line in body.splitlines():
                    if not line.strip():
                        continue
                    row = decoder.decode(line)
                    if row is None:
                        continue
                    rows.append(row)
                    if len(rows) >= batch_rows:
                        yield _batch(rows, columns, types)
                        rows = []
                if rows:
                    yield _batch(rows, columns, types)

        return _Source()


def _batch(rows: List[tuple], columns: Sequence[str],
           types: Dict[str, T.Type]) -> Batch:
    cols = []
    for j, c in enumerate(columns):
        cols.append(column_from_pylist(types[c], [r[j] for r in rows]))
    return Batch(tuple(cols), len(rows))
