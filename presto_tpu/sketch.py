"""Mergeable sketches: HyperLogLog for approx_distinct.

The reference's approx_distinct rides airlift-stats HyperLogLog
(presto-main/.../operator/aggregation/ApproximateCountDistinctAggregation
.java, presto-spi HLL state).  This is a dense HLL with 2^11 registers
(standard error ~2.3%, matching the reference's default 2.3% at its
default bucket count); sketches serialize to latin-1 strings so they ride
the varbinary dictionary representation through partial/final exchanges.
"""

from __future__ import annotations

from typing import Iterable, Optional

import numpy as np

P_BITS = 11
M = 1 << P_BITS                     # registers
_ALPHA = 0.7213 / (1 + 1.079 / M)   # standard HLL bias constant


def _hash64(value) -> int:
    from presto_tpu import native

    if value is None:
        return 0
    if isinstance(value, bool):
        data = b"\x01" if value else b"\x00"
    elif isinstance(value, int):
        data = value.to_bytes(8, "little", signed=True)
    elif isinstance(value, float):
        data = np.float64(value).tobytes()
    elif isinstance(value, str):
        data = value.encode("utf-8")
    else:
        data = repr(value).encode("utf-8")
    return native.xxh64(data)


class HyperLogLog:
    __slots__ = ("registers",)

    def __init__(self, registers: Optional[np.ndarray] = None):
        self.registers = (np.zeros(M, np.uint8) if registers is None
                          else registers)

    def add_value(self, value) -> None:
        h = _hash64(value)
        idx = h & (M - 1)
        rest = h >> P_BITS
        # rank = leading-zero count + 1 over the remaining 53 bits
        rank = 1
        while rest & 1 == 0 and rank <= 64 - P_BITS:
            rank += 1
            rest >>= 1
        if rank > self.registers[idx]:
            self.registers[idx] = rank

    def add_many(self, values: Iterable) -> None:
        for v in values:
            if v is not None:
                self.add_value(v)

    def merge(self, other: "HyperLogLog") -> None:
        np.maximum(self.registers, other.registers, out=self.registers)

    def cardinality(self) -> int:
        regs = self.registers.astype(np.float64)
        est = _ALPHA * M * M / np.sum(np.exp2(-regs))
        zeros = int((self.registers == 0).sum())
        if est <= 2.5 * M and zeros:
            est = M * np.log(M / zeros)      # linear counting range
        return int(round(est))

    # -- serde (latin-1 string payload; rides the varbinary dictionary) ---
    def serialize(self) -> str:
        return self.registers.tobytes().decode("latin-1")

    @classmethod
    def deserialize(cls, payload: str) -> "HyperLogLog":
        raw = payload.encode("latin-1")
        if len(raw) != M:
            return cls()                      # unknown/corrupt -> empty
        return cls(np.frombuffer(raw, np.uint8).copy())


def hll_cardinality(payload: str) -> int:
    return HyperLogLog.deserialize(payload).cardinality()


# ---------------------------------------------------------------------------
# KLL quantile sketch (approx_percentile)
# ---------------------------------------------------------------------------

class KllSketch:
    """Mergeable streaming quantile sketch (KLL16-style).

    Replaces the reference's qdigest state
    (presto-main/.../aggregation/QuantileDigestAggregationFunction.java)
    with the simpler KLL compactor scheme: level h holds items each
    representing 2^h input values; a full level sorts itself and keeps
    alternate items (random offset), promoting them one level up.  State
    is O(k * log(n/k)) regardless of input size — the bounded-memory,
    exchange-friendly property the old collect-everything implementation
    lacked.  Error is rank-based (~1.5/k one-sided at default k).

    Values are stored as floats (SQL numeric inputs convert losslessly for
    realistic magnitudes); quantile() returns a float the caller casts to
    the column type.
    """

    K = 200

    def __init__(self, levels=None, count: int = 0, seed: int = 0x9E3779B9):
        self.levels = [list(lv) for lv in levels] if levels else [[]]
        self.count = count
        self._rng = np.random.default_rng(seed)

    # -- building -------------------------------------------------------
    def add_value(self, value) -> None:
        if value is None:
            return
        self.levels[0].append(float(value))
        self.count += 1
        if len(self.levels[0]) >= self._cap(0):
            self._compact()

    def add_many(self, values: Iterable) -> None:
        for v in values:
            self.add_value(v)

    def _cap(self, level: int) -> int:
        # higher levels shrink geometrically (KLL's (2/3)^depth rule,
        # floored) — most memory lives at the base
        depth = max(len(self.levels) - 1 - level, 0)
        return max(int(self.K * (2.0 / 3.0) ** depth), 8)

    def _compact(self) -> None:
        for h in range(len(self.levels)):
            if len(self.levels[h]) < self._cap(h):
                continue
            buf = sorted(self.levels[h])
            keep = buf[int(self._rng.integers(0, 2))::2]
            self.levels[h] = []
            if h + 1 == len(self.levels):
                self.levels.append([])
            self.levels[h + 1].extend(keep)

    # -- merge / query --------------------------------------------------
    def merge(self, other: "KllSketch") -> None:
        while len(self.levels) < len(other.levels):
            self.levels.append([])
        for h, lv in enumerate(other.levels):
            self.levels[h].extend(lv)
        self.count += other.count
        for h in range(len(self.levels)):
            while len(self.levels[h]) >= 2 * self._cap(h):
                self._compact_level(h)

    def _compact_level(self, h: int) -> None:
        buf = sorted(self.levels[h])
        keep = buf[int(self._rng.integers(0, 2))::2]
        self.levels[h] = []
        if h + 1 == len(self.levels):
            self.levels.append([])
        self.levels[h + 1].extend(keep)

    def quantile(self, q: float) -> Optional[float]:
        items: list = []
        for h, lv in enumerate(self.levels):
            w = 1 << h
            items.extend((v, w) for v in lv)
        if not items:
            return None
        items.sort()
        total = sum(w for _, w in items)
        target = q * total
        acc = 0
        for v, w in items:
            acc += w
            if acc >= target:
                return v
        return items[-1][0]

    # -- serde ----------------------------------------------------------
    def serialize(self) -> str:
        import json

        return json.dumps({"c": self.count, "l": self.levels})

    @classmethod
    def deserialize(cls, payload: str) -> "KllSketch":
        import json

        doc = json.loads(payload)
        return cls(levels=doc["l"], count=int(doc["c"]))
