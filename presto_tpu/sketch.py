"""Mergeable sketches: HyperLogLog for approx_distinct.

The reference's approx_distinct rides airlift-stats HyperLogLog
(presto-main/.../operator/aggregation/ApproximateCountDistinctAggregation
.java, presto-spi HLL state).  This is a dense HLL with 2^11 registers
(standard error ~2.3%, matching the reference's default 2.3% at its
default bucket count); sketches serialize to latin-1 strings so they ride
the varbinary dictionary representation through partial/final exchanges.
"""

from __future__ import annotations

from typing import Iterable, Optional

import numpy as np

P_BITS = 11
M = 1 << P_BITS                     # registers
_ALPHA = 0.7213 / (1 + 1.079 / M)   # standard HLL bias constant


def _hash64(value) -> int:
    from presto_tpu import native

    if value is None:
        return 0
    if isinstance(value, bool):
        data = b"\x01" if value else b"\x00"
    elif isinstance(value, int):
        data = value.to_bytes(8, "little", signed=True)
    elif isinstance(value, float):
        data = np.float64(value).tobytes()
    elif isinstance(value, str):
        data = value.encode("utf-8")
    else:
        data = repr(value).encode("utf-8")
    return native.xxh64(data)


class HyperLogLog:
    __slots__ = ("registers",)

    def __init__(self, registers: Optional[np.ndarray] = None):
        self.registers = (np.zeros(M, np.uint8) if registers is None
                          else registers)

    def add_value(self, value) -> None:
        h = _hash64(value)
        idx = h & (M - 1)
        rest = h >> P_BITS
        # rank = leading-zero count + 1 over the remaining 53 bits
        rank = 1
        while rest & 1 == 0 and rank <= 64 - P_BITS:
            rank += 1
            rest >>= 1
        if rank > self.registers[idx]:
            self.registers[idx] = rank

    def add_many(self, values: Iterable) -> None:
        for v in values:
            if v is not None:
                self.add_value(v)

    def merge(self, other: "HyperLogLog") -> None:
        np.maximum(self.registers, other.registers, out=self.registers)

    def cardinality(self) -> int:
        regs = self.registers.astype(np.float64)
        est = _ALPHA * M * M / np.sum(np.exp2(-regs))
        zeros = int((self.registers == 0).sum())
        if est <= 2.5 * M and zeros:
            est = M * np.log(M / zeros)      # linear counting range
        return int(round(est))

    # -- serde (latin-1 string payload; rides the varbinary dictionary) ---
    def serialize(self) -> str:
        return self.registers.tobytes().decode("latin-1")

    @classmethod
    def deserialize(cls, payload: str) -> "HyperLogLog":
        raw = payload.encode("latin-1")
        if len(raw) != M:
            return cls()                      # unknown/corrupt -> empty
        return cls(np.frombuffer(raw, np.uint8).copy())


def hll_cardinality(payload: str) -> int:
    return HyperLogLog.deserialize(payload).cardinality()
