"""Interactive SQL REPL (the presto-cli role).

Two modes, mirroring how the reference CLI targets a server while tests
embed LocalQueryRunner:

    python -m presto_tpu.cli --server http://host:port     # client mode
    python -m presto_tpu.cli --catalog tpch --scale 0.01   # embedded

Multi-line statements end with ';'.  Commands: \\q quit, \\timing toggle.
"""

from __future__ import annotations

import argparse
import sys
import time
from typing import List, Optional, Sequence, Tuple


def format_table(names: Sequence[str], rows: Sequence[Tuple]) -> str:
    cells = [[("NULL" if v is None else str(v)) for v in row]
             for row in rows]
    widths = [len(n) for n in names]
    for row in cells:
        for i, c in enumerate(row):
            widths[i] = max(widths[i], len(c))
    sep = "-+-".join("-" * w for w in widths)
    out = [" | ".join(n.ljust(w) for n, w in zip(names, widths)), sep]
    for row in cells:
        out.append(" | ".join(c.ljust(w) for c, w in zip(row, widths)))
    out.append(f"({len(rows)} row{'s' if len(rows) != 1 else ''})")
    return "\n".join(out)


class _EmbeddedBackend:
    def __init__(self, catalog: str, scale: float):
        from presto_tpu.localrunner import LocalQueryRunner

        if catalog != "tpch":
            raise SystemExit("embedded mode supports --catalog tpch")
        self.runner = LocalQueryRunner.tpch(scale=scale)

    def execute(self, sql: str):
        res = self.runner.execute(sql)
        return res.column_names, res.rows


class _ClientBackend:
    def __init__(self, server: str):
        from presto_tpu.client import StatementClient

        self.client = StatementClient(server)

    def execute(self, sql: str):
        columns, data = self.client.execute(sql)
        return [c["name"] for c in columns], [tuple(r) for r in data]


def repl(backend, instream=sys.stdin, out=sys.stdout) -> None:
    timing = True
    buffer: List[str] = []
    interactive = instream.isatty()
    if interactive:
        out.write("presto-tpu> ")
        out.flush()
    for line in instream:
        stripped = line.strip()
        if not buffer and stripped in (r"\q", "quit", "exit"):
            return
        if not buffer and stripped == r"\timing":
            timing = not timing
            out.write(f"timing {'on' if timing else 'off'}\n")
        elif stripped:
            buffer.append(line)
        if buffer and stripped.endswith(";"):
            sql = "".join(buffer)
            buffer = []
            t0 = time.time()
            try:
                names, rows = backend.execute(sql)
                out.write(format_table(names, rows) + "\n")
                if timing:
                    out.write(f"[{time.time() - t0:.2f}s]\n")
            except Exception as e:  # noqa: BLE001 - REPL survives errors
                out.write(f"error: {e}\n")
        if interactive:
            out.write("presto-tpu> " if not buffer else "        -> ")
            out.flush()


def main(argv: Optional[List[str]] = None) -> None:
    p = argparse.ArgumentParser(prog="presto-tpu-cli")
    p.add_argument("--server", help="coordinator URI (client mode)")
    p.add_argument("--catalog", default="tpch", help="embedded catalog")
    p.add_argument("--scale", type=float, default=0.01,
                   help="embedded tpch scale factor")
    p.add_argument("--execute", "-e", help="run one statement and exit")
    args = p.parse_args(argv)

    backend = (_ClientBackend(args.server) if args.server
               else _EmbeddedBackend(args.catalog, args.scale))
    if args.execute:
        names, rows = backend.execute(args.execute)
        print(format_table(names, rows))
        return
    repl(backend)


if __name__ == "__main__":
    main()
