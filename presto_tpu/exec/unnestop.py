"""UNNEST operator (UnnestOperator.java:39).

Expands ARRAY/MAP columns into rows: each input row emits
max(cardinalities) output rows; replicated channels repeat per element,
shorter arrays null-pad, maps expand to (key, value), arrays of ROW expand
one output column per field, and WITH ORDINALITY appends the 1-based
position.  All offset arithmetic is vectorized host-side; the expansion
itself is gathers — the same shape the device join-expansion kernels use.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np

from presto_tpu import types as T
from presto_tpu.batch import Batch, Column
from presto_tpu.exec.context import OperatorContext
from presto_tpu.exec.operator import Operator, OperatorFactory


def _unnest_outputs(col_type: T.Type) -> List[T.Type]:
    """Output column types for one unnested channel."""
    if isinstance(col_type, T.MapType):
        return [col_type.key, col_type.value]
    if isinstance(col_type, T.ArrayType):
        if isinstance(col_type.element, T.RowType):
            return list(col_type.element.field_types)
        return [col_type.element]
    raise ValueError(f"cannot unnest {col_type.display()}")


class UnnestOperator(Operator):
    def __init__(self, ctx: OperatorContext,
                 replicate_channels: Sequence[int],
                 unnest_channels: Sequence[int], ordinality: bool,
                 outer: bool = False):
        super().__init__(ctx)
        self.replicate_channels = list(replicate_channels)
        self.unnest_channels = list(unnest_channels)
        self.ordinality = ordinality
        self.outer = outer
        self._pending: Optional[Batch] = None

    def needs_input(self) -> bool:
        return self._pending is None and not self._finishing

    def add_input(self, batch: Batch) -> None:
        self._pending = batch
        self.ctx.stats.input_rows += batch.num_rows

    def get_output(self) -> Optional[Batch]:
        if self._pending is None:
            return None
        batch, self._pending = self._pending, None
        batch = batch.compact().to_numpy()
        n = batch.num_rows

        ucols = [batch.columns[c] for c in self.unnest_channels]
        lens = []
        for c in ucols:
            ln = np.asarray(c.values, np.int64).copy()
            if c.valid is not None:            # NULL container => 0 rows
                ln[~np.asarray(c.valid)] = 0
            lens.append(ln)
        maxlen = lens[0]
        for ln in lens[1:]:
            maxlen = np.maximum(maxlen, ln)
        # LEFT JOIN UNNEST keeps empty/NULL-container rows as one
        # all-NULL-unnest-columns row
        efflen = np.maximum(maxlen, 1) if self.outer else maxlen
        total = int(efflen.sum())
        row_of = np.repeat(np.arange(n, dtype=np.int64), efflen)
        ends = np.cumsum(efflen)
        within = np.arange(total, dtype=np.int64) - \
            np.repeat(ends - efflen, efflen)

        out_cols: List[Column] = []
        for ch in self.replicate_channels:
            out_cols.append(batch.columns[ch].take(row_of))
        for c, ln in zip(ucols, lens):
            offsets = np.concatenate(
                [np.zeros(1, np.int64),
                 np.cumsum(np.asarray(c.values, np.int64))])
            present = within < ln[row_of]
            idx = offsets[row_of] + np.minimum(within, np.maximum(
                ln[row_of] - 1, 0))
            # rows whose array here is shorter (even empty) gather a safe
            # slot; `present` masks them to NULL
            idx = np.clip(idx, 0,
                          max(int(offsets[-1]) - 1, 0))
            kids = c.children
            for kid in kids:
                expanded = self._expand_kid(kid, idx, present, total)
                out_cols.extend(expanded)
        if self.ordinality:
            ord_valid = None
            if self.outer:
                present_any = within < maxlen[row_of]
                if not present_any.all():
                    ord_valid = present_any
            out_cols.append(Column(T.BIGINT, within + 1, ord_valid))
        out = Batch(tuple(out_cols), total)
        self.ctx.stats.output_rows += total
        return out if total else None

    def _expand_kid(self, kid: Column, idx: np.ndarray,
                    present: np.ndarray, total: int) -> List[Column]:
        if kid.values.shape[0] == 0:
            from presto_tpu.batch import empty_column

            base = empty_column(kid.type).pad(total)
            cols = [Column(base.type, base.values, np.zeros(total, bool),
                           base.dictionary, base.children)]
        else:
            taken = kid.take(idx)
            valid = present if taken.valid is None \
                else present & np.asarray(taken.valid)
            cols = [Column(taken.type, taken.values, valid,
                           taken.dictionary, taken.children)]
        if isinstance(kid.type, T.RowType):
            # array(row(...)) expands one column per field
            row_col = cols[0]
            out = []
            for f in row_col.children:
                fv = None if f.valid is None else np.asarray(f.valid)
                rv = row_col.valid
                valid = fv if rv is None else (
                    rv if fv is None else fv & rv)
                out.append(Column(f.type, f.values, valid, f.dictionary,
                                  f.children))
            return out
        return cols

    def is_finished(self) -> bool:
        return self._finishing and self._pending is None


class UnnestOperatorFactory(OperatorFactory):
    parallel_safe = True

    def __init__(self, replicate_channels: Sequence[int],
                 unnest_channels: Sequence[int], ordinality: bool,
                 outer: bool = False):
        self.replicate_channels = list(replicate_channels)
        self.unnest_channels = list(unnest_channels)
        self.ordinality = ordinality
        self.outer = outer

    def create(self, ctx: OperatorContext) -> UnnestOperator:
        return UnnestOperator(ctx, self.replicate_channels,
                              self.unnest_channels, self.ordinality,
                              self.outer)
