"""Intra-task driver parallelism: N scan-feed drivers, one consumer.

The reference runs several drivers per pipeline and stitches them with
LocalExchange (presto-main/.../operator/exchange/LocalExchange.java:53),
planned by AddLocalExchanges (sql/planner/optimizations/
AddLocalExchanges.java:95).  On TPU the kernels are internally parallel,
so the win is HOST-side: several drivers pull splits, decode pages, and
queue device work concurrently while the consumer chain drains — the
scan feed no longer starves the accumulating operator between batches.

``LocalExchange`` is a bounded rendezvous (backpressure both ways):
producers block when the buffer is full (OutputBufferMemoryManager role),
the consumer waits briefly when it is empty.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Deque, List, Optional

from presto_tpu.batch import Batch
from presto_tpu.exec.context import OperatorContext
from presto_tpu.exec.operator import Operator, OperatorFactory


class LocalExchange:
    """Deterministic N-producer rendezvous: the consumer drains batches
    in strict producer round-robin, so the DOWNSTREAM batch order is a
    pure function of each producer's (deterministic) output — float
    aggregation results stay reproducible run-to-run even though the
    producers execute concurrently (the reference pins the same property
    with PlanDeterminismChecker / TestQueryPlanDeterminism)."""

    def __init__(self, n_producers: int, capacity: int = 16):
        self._queues: List[Deque[Batch]] = [deque()
                                            for _ in range(n_producers)]
        self._done = [False] * n_producers
        self._cursor = 0
        self._capacity = max(capacity // max(n_producers, 1), 2)
        self._error: Optional[BaseException] = None
        self._cond = threading.Condition()

    def put(self, producer: int, batch: Batch) -> None:
        with self._cond:
            q = self._queues[producer]
            while len(q) >= self._capacity and self._error is None:
                self._cond.wait(timeout=1.0)
            if self._error is not None:
                raise self._error
            q.append(batch)
            self._cond.notify_all()

    def producer_finished(self, producer: int) -> None:
        with self._cond:
            self._done[producer] = True
            self._cond.notify_all()

    def fail(self, exc: BaseException) -> None:
        with self._cond:
            if self._error is None:
                self._error = exc
            self._cond.notify_all()

    def _next_ready_locked(self) -> Optional[int]:
        """The producer whose turn it is, skipping finished-and-empty
        ones; None when every producer is drained.  Waits for the
        CURRENT producer rather than taking whatever arrived first —
        that wait is what buys determinism."""
        n = len(self._queues)
        for _ in range(n):
            q = self._queues[self._cursor]
            if q:
                return self._cursor
            if self._done[self._cursor]:
                self._cursor = (self._cursor + 1) % n
                continue
            return self._cursor  # its turn, but not ready yet
        return None

    def poll(self, wait_s: float = 0.005) -> Optional[Batch]:
        """One batch in deterministic order, or None; raises a
        producer's error."""
        with self._cond:
            if self._error is not None:
                raise self._error
            cur = self._next_ready_locked()
            if cur is not None and not self._queues[cur]:
                self._cond.wait(timeout=wait_s)
                if self._error is not None:
                    raise self._error
                cur = self._next_ready_locked()
            if cur is None or not self._queues[cur]:
                return None
            out = self._queues[cur].popleft()
            self._cursor = (cur + 1) % len(self._queues)
            self._cond.notify_all()
            return out

    def drained(self) -> bool:
        with self._cond:
            return all(self._done) and not any(self._queues)


class LocalExchangeSinkOperator(Operator):
    def __init__(self, ctx: OperatorContext, exchange: LocalExchange,
                 producer: int, signal_finish: bool):
        super().__init__(ctx)
        self.exchange = exchange
        self.producer = producer
        self.signal_finish = signal_finish

    def add_input(self, batch: Batch) -> None:
        self.ctx.stats.input_rows += batch.num_rows
        self.exchange.put(self.producer, batch)

    def finish(self) -> None:
        if not self._finishing and self.signal_finish:
            self.exchange.producer_finished(self.producer)
        super().finish()

    def is_finished(self) -> bool:
        return self._finishing


class LocalExchangeSinkOperatorFactory(OperatorFactory):
    def __init__(self, exchange: LocalExchange, producer: int = 0,
                 signal_finish: bool = True):
        """``signal_finish=False`` for SEQUENTIAL pipelines sharing one
        producer slot (grouped-execution lifespans): the owner signals
        once after the last pipeline, since a strict round-robin
        consumer must never wait on a producer that has not started."""
        self.exchange = exchange
        self.producer = producer
        self.signal_finish = signal_finish

    def create(self, ctx: OperatorContext) -> LocalExchangeSinkOperator:
        return LocalExchangeSinkOperator(ctx, self.exchange,
                                         self.producer,
                                         self.signal_finish)


class LocalExchangeSourceOperator(Operator):
    def __init__(self, ctx: OperatorContext, exchange: LocalExchange):
        super().__init__(ctx)
        self.exchange = exchange

    def needs_input(self) -> bool:
        return False

    def get_output(self) -> Optional[Batch]:
        batch = self.exchange.poll()
        if batch is not None:
            self.ctx.stats.output_rows += batch.num_rows
        return batch

    def is_finished(self) -> bool:
        return self.exchange.drained()


class LocalExchangeSourceOperatorFactory(OperatorFactory):
    def __init__(self, exchange: LocalExchange):
        self.exchange = exchange

    def create(self, ctx: OperatorContext) -> LocalExchangeSourceOperator:
        return LocalExchangeSourceOperator(ctx, self.exchange)
