"""Intra-task driver parallelism: N scan-feed drivers, one consumer.

The reference runs several drivers per pipeline and stitches them with
LocalExchange (presto-main/.../operator/exchange/LocalExchange.java:53),
planned by AddLocalExchanges (sql/planner/optimizations/
AddLocalExchanges.java:95).  On TPU the kernels are internally parallel,
so the win is HOST-side: several drivers pull splits, decode pages, and
queue device work concurrently while the consumer chain drains — the
scan feed no longer starves the accumulating operator between batches.

``LocalExchange`` is a bounded rendezvous (backpressure both ways):
producers block when the buffer is full (OutputBufferMemoryManager role),
the consumer waits briefly when it is empty.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Deque, List, Optional

from presto_tpu.batch import Batch
from presto_tpu.exec.context import OperatorContext
from presto_tpu.exec.operator import Operator, OperatorFactory


class LocalExchange:
    def __init__(self, n_producers: int, capacity: int = 16):
        self._batches: Deque[Batch] = deque()
        self._remaining = n_producers
        self._capacity = capacity
        self._error: Optional[BaseException] = None
        self._cond = threading.Condition()

    def put(self, batch: Batch) -> None:
        with self._cond:
            while (len(self._batches) >= self._capacity
                   and self._error is None):
                self._cond.wait(timeout=1.0)
            if self._error is not None:
                raise self._error
            self._batches.append(batch)
            self._cond.notify_all()

    def producer_finished(self) -> None:
        with self._cond:
            self._remaining -= 1
            self._cond.notify_all()

    def fail(self, exc: BaseException) -> None:
        with self._cond:
            if self._error is None:
                self._error = exc
            self._cond.notify_all()

    def poll(self, wait_s: float = 0.005) -> Optional[Batch]:
        """One batch, or None; raises a producer's error."""
        with self._cond:
            if self._error is not None:
                raise self._error
            if not self._batches and self._remaining > 0:
                self._cond.wait(timeout=wait_s)
            if self._error is not None:
                raise self._error
            if self._batches:
                out = self._batches.popleft()
                self._cond.notify_all()
                return out
            return None

    def drained(self) -> bool:
        with self._cond:
            return self._remaining == 0 and not self._batches


class LocalExchangeSinkOperator(Operator):
    def __init__(self, ctx: OperatorContext, exchange: LocalExchange):
        super().__init__(ctx)
        self.exchange = exchange

    def add_input(self, batch: Batch) -> None:
        self.ctx.stats.input_rows += batch.num_rows
        self.exchange.put(batch)

    def finish(self) -> None:
        if not self._finishing:
            self.exchange.producer_finished()
        super().finish()

    def is_finished(self) -> bool:
        return self._finishing


class LocalExchangeSinkOperatorFactory(OperatorFactory):
    def __init__(self, exchange: LocalExchange):
        self.exchange = exchange

    def create(self, ctx: OperatorContext) -> LocalExchangeSinkOperator:
        return LocalExchangeSinkOperator(ctx, self.exchange)


class LocalExchangeSourceOperator(Operator):
    def __init__(self, ctx: OperatorContext, exchange: LocalExchange):
        super().__init__(ctx)
        self.exchange = exchange

    def needs_input(self) -> bool:
        return False

    def get_output(self) -> Optional[Batch]:
        batch = self.exchange.poll()
        if batch is not None:
            self.ctx.stats.output_rows += batch.num_rows
        return batch

    def is_finished(self) -> bool:
        return self.exchange.drained()


class LocalExchangeSourceOperatorFactory(OperatorFactory):
    def __init__(self, exchange: LocalExchange):
        self.exchange = exchange

    def create(self, ctx: OperatorContext) -> LocalExchangeSourceOperator:
        return LocalExchangeSourceOperator(ctx, self.exchange)
