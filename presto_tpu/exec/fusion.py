"""Pipeline fusion: one jitted XLA program per run of row-local operators.

The reference's performance tier is runtime code generation — its
``ExpressionCompiler``/``PageProcessor`` fuse a filter and all its
projections into one generated loop per page (survey §2.7).  The engine
already matches the intra-operator half (``FilterProjectOperator`` jits
filter+projections together), but a fragment still executed as a chain of
independently-jitted dispatches with a Python driver hop between every
adjacent operator pair, so intermediates round-tripped through HBM (and
sometimes host) at each hop.

This module is the cross-operator generalization: at fragment-lowering
time ``fuse_pipelines`` identifies maximal runs of adjacent row-local,
jit-able operator factories —

- chained ``FilterProject``s (stacked optimizer Projects, join residuals,
  aggregation finalize projections),
- dynamic-filter application (``DynamicFilterOperator``),
- the partial-aggregation input projection (an ordinary FilterProject),
- the hash/partition-id computation feeding ``PartitionedOutputOperator``

— and compiles each run into ONE jitted segment program executed once per
batch.  Inside a segment, consecutive filters combine into one
accumulated mask with a single gather at the end, projection
intermediates never materialize (XLA fuses the elementwise chains), and
the exchange sink's partition ids ride along as one extra output.

Scan-adjacent segments additionally take over the scan staging (the
``ScanFilterAndProjectOperator`` role): the scan hands over raw host
batches and the segment coalesces them up to ``scan_batch_rows`` before
staging + dispatching once, so many tiny per-split batches cost one
launch instead of one each.  Dictionary columns are re-coded into a
per-operator target dictionary so coalesced flushes share one compiled
program.  Segments fed by a remote exchange coalesce the same way
(pages arrive host-side and small), so exchange-fed probe sides stop
dispatching once per tiny page.

Fusion II — in-segment partial-aggregation pre-reduce: a segment that
feeds a partial or single-step ``HashAggregationOperator`` /
``GlobalAggregationOperator`` (device prims only, bounded-domain group
keys) absorbs the per-batch accumulate into the program itself: the
jitted kernel masks, projects, and group-accumulates (via
ops.groupby's segment kernels, no compaction — the filter rides as the
live mask) before anything materializes, emitting partial-state
batches (keys + component columns) instead of row batches.  The
reference avoids the same materialization by pushing the partial
``HashAggregationOperator.Step`` into the generated scan loop
(HashAggregationOperator.java:48).  Downstream, a single-step
aggregation is replaced by its merge form (MERGE_PRIM re-aggregation
of the tiny partials, filter-less finalize projection folded into the
aggregation finish); a partial-step aggregation is dropped outright —
the FINAL stage's merge already accepts partials at any granularity.
Gated by ``EngineConfig.fusion_partial_agg`` (default on; off restores
the PR 3 lowering exactly).

Segment programs are cached globally (``kernelcache``) keyed by segment
expression keys + capacity bucket + dictionary binding (token, length) +
the dynamic-filter value shape — the same keying discipline as
``_FP_KERNELS``.  Gated by ``EngineConfig.pipeline_fusion`` (default on;
off restores per-operator dispatch exactly).

PR 10 extends the segment grammar three ways (see exec/README.md
"Device-resident hash tier"): residual-free inner/semi/anti LookupJoin
probes absorb as ``ProbeStage`` (gate ``device_join_probe``) so
filter -> project -> probe -> partial-agg chains are one dispatch;
grouped FINAL merges directly on a remote exchange absorb into
empty-stage coalescing segments (gate ``fusion_final_merge``); and the
pre-reduce decision is cost-based (gate ``prereduce_cost_based``) —
plan-time NDV hints plus a runtime observed-ratio switch to raw
partial-state emission when grouping stops reducing.

What breaks a segment: any non-row-local operator (aggregation — except
an absorbed one, join — except an absorbed probe, sort, exchange,
limit), expressions that need the host path (nested types, row-wise
string fallbacks), and nested input/output types.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence, Tuple

import numpy as np

from presto_tpu import types as T
from presto_tpu.batch import Batch, Column, Dictionary, next_bucket
from presto_tpu.exec.aggregation import (
    MERGE_PRIM, AggChannel, GlobalAggregationOperatorFactory,
    HashAggregationOperatorFactory,
)
from presto_tpu.exec.context import OperatorContext
from presto_tpu.exec.dynamicfilter import (
    DynamicFilter, DynamicFilterOperatorFactory,
)
from presto_tpu.exec.operator import Operator, OperatorFactory, column_pairs
from presto_tpu.exec.operators import (
    FilterProjectOperatorFactory, TableScanOperatorFactory,
    dictionary_binding_key,
)
from presto_tpu.expr.compile import ExprCompiler, needs_host_path
from presto_tpu.expr.ir import RowExpression
from presto_tpu.kernelcache import cache_get, cache_put, new_cache

# compiled segment programs, shared globally across queries/operators
_SEG_KERNELS = new_cache("fused_segment")

# learned inner-probe expansion buckets, shared ACROSS queries: keyed by
# (segment expr key, probe stage index, input capacity), monotonic max.
# A fresh operator re-learning its bucket per execution would oscillate
# between capacity variants (arrival-order nondeterminism decides which
# batch overflows first) and churn one compiled program per variant per
# query; the sticky global bucket converges once and stays.
_OUT_CAPS_LEARNED: dict = {}


# ---------------------------------------------------------------------------
# segment stages
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class FPStage:
    """One filter+projections step (a FilterProjectOperator's work)."""

    filter_expr: Optional[RowExpression]
    projections: Tuple[RowExpression, ...]
    input_types: Tuple[T.Type, ...]

    def key(self) -> tuple:
        return ("fp", self.filter_expr, self.projections, self.input_types)


@dataclasses.dataclass(frozen=True, eq=False)
class DFStage:
    """Dynamic-filter application over the current channel space.

    The filter VALUES (bounds, IN-set tables) are runtime kernel
    arguments, never trace constants; only the value *shape* (which
    channels are bounded, which have exact sets) keys the program.
    Adaptive shutoff is intentionally absent: it existed to avoid an
    extra per-batch dispatch, and inside a fused segment the filter
    costs no extra launch.
    """

    dyn: DynamicFilter
    key_channels: Tuple[int, ...]

    def key(self) -> tuple:
        return ("df", self.key_channels)


@dataclasses.dataclass(frozen=True, eq=False)
class ProbeStage:
    """An absorbed residual-free LookupJoin probe (device_join_probe):
    the probe primitive runs INSIDE the segment program — the way
    ``segment_pre_reduce`` absorbed partial aggregation — so
    filter -> project -> probe -> partial-agg chains cost one dispatch.

    The build side's table (PagesHash layout, ops/hashtable.py) and data
    columns ride as RUNTIME kernel arguments, never trace constants;
    the program is keyed by the build's shape/binding, so identical
    queries share one executable.  semi/anti probes fold into the
    accumulated mask (no expansion); inner probes expand the row space
    (probe-gather + build-gather) under a static output capacity with
    host retry on overflow — the same policy every expansion kernel in
    ops/join.py uses.
    """

    factory: object                # LookupJoinOperatorFactory

    def key(self) -> tuple:
        f = self.factory
        return ("probe", f.join_type, tuple(f.probe_key_channels),
                f.null_aware, tuple(f.probe_types),
                tuple(f.build.input_types))


def _stage_of(factory) -> object:
    if isinstance(factory, FilterProjectOperatorFactory):
        return FPStage(factory.filter_expr, tuple(factory.projections),
                       tuple(factory.input_types))
    if isinstance(factory, DynamicFilterOperatorFactory):
        return DFStage(factory.dyn, tuple(factory.key_channels))
    from presto_tpu.exec.joinop import LookupJoinOperatorFactory

    if isinstance(factory, LookupJoinOperatorFactory):
        return ProbeStage(factory)
    raise TypeError(f"not a fusable factory: {type(factory).__name__}")


def _fp_jitable(f: FilterProjectOperatorFactory) -> bool:
    """True when the stage can run inside a jitted segment (mirrors the
    FilterProjectOperator host-path eligibility, decided statically)."""
    if needs_host_path([f.filter_expr] + list(f.projections)):
        return False
    if any(t.is_nested for t in f.input_types):
        return False
    if any(p.type.is_nested for p in f.projections):
        return False
    return True


def _probe_absorbable(f, config) -> bool:
    """May this LookupJoin probe run inside a segment?  Residual-free
    inner/semi/anti only; left-outer keeps its operator (its unmatched
    emission interacts with downstream outer-composition paths).
    Grouped execution keeps per-bucket probe operators so Lifespan
    memory retirement stays observable."""
    if not getattr(config, "device_join_probe", False):
        return False
    if getattr(config, "grouped_execution_buckets", 1) > 1:
        return False
    if f.join_type not in ("inner", "semi", "anti"):
        return False
    if f.residual is not None:
        return False
    if any(t.is_nested for t in f.probe_types):
        return False
    if f.join_type == "inner" and any(t.is_nested
                                      for t in f.build.input_types):
        return False
    return True


def _fusable(f, config) -> bool:
    if isinstance(f, DynamicFilterOperatorFactory):
        return True
    if isinstance(f, FilterProjectOperatorFactory):
        return _fp_jitable(f)
    from presto_tpu.exec.joinop import LookupJoinOperatorFactory

    if isinstance(f, LookupJoinOperatorFactory):
        return _probe_absorbable(f, config)
    return False


@dataclasses.dataclass(frozen=True)
class PreReduceSpec:
    """In-segment partial-aggregation pre-reduce (Fusion II).

    ``group_channels``/``aggs`` index the SEGMENT's output channel
    space (== the absorbed aggregation's input space); the segment then
    emits the partial schema [key columns..., one state column per
    aggregation].  ``key_types`` are the group-key output types (kept
    for describe()); ``global_`` marks the ungrouped form, which emits
    exactly one partial row per dispatched batch plus a default row at
    finish when nothing was dispatched (a task must never contribute
    zero partial rows — the merge's count-sum would yield NULL where
    COUNT over empty input is 0).
    """

    group_channels: Tuple[int, ...]
    aggs: Tuple[AggChannel, ...]
    key_types: Tuple[T.Type, ...]
    global_: bool

    def key(self) -> tuple:
        return ("prereduce", self.group_channels, self.global_,
                tuple((a.prim, a.channel, a.out_type) for a in self.aggs))


def _sort_groupable(t: T.Type) -> bool:
    """Key types the in-segment sort-path pre-reduce can normalize to
    int64 words (ops/keys.py); plain varchar (no dictionary) cannot."""
    return bool(t.is_dictionary or T.is_integral(t)
                or t.name in ("boolean", "double", "real", "date",
                              "timestamp")
                or isinstance(t, T.DecimalType))


def _segment_out_types(stages) -> Optional[List[T.Type]]:
    """The segment's output channel types, walked through the stages:
    FP stages remap channels to their projection types, inner probe
    stages append the build channels, semi/anti probes keep the probe
    space (DF stages filter rows, never remap channels)."""
    types: Optional[List[T.Type]] = None
    for s in stages:
        if isinstance(s, FPStage):
            types = [p.type for p in s.projections]
        elif isinstance(s, ProbeStage):
            f = s.factory
            base = list(f.probe_types) if types is None else types
            types = (base + list(f.build.input_types)
                     if f.join_type == "inner" else base)
    return types


def _try_pre_reduce(stages, factory, config, out_types=None,
                    relax_keys=False):
    """When ``factory`` (the operator the run feeds) is an eligible
    aggregation, return ``(spec, replacement)``: the pre-reduce spec the
    segment absorbs and the downstream factory that replaces the
    aggregation — a merge-form aggregation for single/final steps, or
    None for the partial step (the FINAL stage's merge accepts partials
    at any granularity, so the partial operator is dropped outright).

    Eligibility: device prims only (sum/count/min/max — collect-style
    accumulators need the host path), no min/max over dictionary inputs
    (their partial state would be interning codes, not values), and
    every group key dictionary-coded or boolean so the per-batch
    reduction can take the bounded-domain direct path (unbounded keys
    would make per-batch pre-reduce a pessimization: as many groups as
    rows, nothing reduced) — ``relax_keys`` lifts that last rule for
    exchange-fed FINAL merges, whose input is already pre-reduced
    (duplication factor = producer count) and which the cost-based
    raw-emission switch protects at runtime.  A plan-time NDV estimate
    (``factory.prereduce_ratio_hint`` from the memo's stats tier) skips
    pre-reduce outright when estimated groups approach input rows.
    Returns (None, None) when ineligible.
    """
    if not getattr(config, "fusion_partial_agg", False):
        return None, None
    is_hash = isinstance(factory, HashAggregationOperatorFactory)
    is_global = isinstance(factory, GlobalAggregationOperatorFactory)
    if not (is_hash or is_global):
        return None, None
    if out_types is None:
        out_types = _segment_out_types(stages)
    if out_types is None or len(out_types) != len(factory.input_types):
        return None, None
    if (getattr(config, "prereduce_cost_based", False) and is_hash):
        hint = getattr(factory, "prereduce_ratio_hint", None)
        if hint is not None and hint > getattr(
                config, "prereduce_max_group_fraction", 0.9):
            return None, None
    for a in factory.aggs:
        if a.prim not in MERGE_PRIM:
            return None, None
        if a.channel is not None:
            if a.channel >= len(out_types):
                return None, None
            if out_types[a.channel].is_nested:
                return None, None
            if a.prim in ("min", "max") \
                    and out_types[a.channel].is_dictionary:
                return None, None
    groups = tuple(factory.group_channels) if is_hash else ()
    if is_hash:
        if not groups:
            return None, None
        for g in groups:
            t = out_types[g]
            if t.is_nested:
                return None, None
            if not relax_keys and not (t.is_dictionary
                                       or t.name == "boolean"):
                return None, None
            if relax_keys and not _sort_groupable(t):
                return None, None
    spec = PreReduceSpec(groups, tuple(factory.aggs),
                         tuple(out_types[g] for g in groups), is_global)
    step = getattr(factory, "step", "single")
    if step == "partial":
        return spec, None
    k = len(groups)
    partial_types = ([out_types[g] for g in groups]
                     + [a.out_type for a in factory.aggs])
    merge_aggs = [AggChannel(MERGE_PRIM[a.prim], k + i, a.out_type)
                  for i, a in enumerate(factory.aggs)]
    if is_hash:
        replacement = HashAggregationOperatorFactory(
            list(range(k)), merge_aggs, partial_types)
    else:
        replacement = GlobalAggregationOperatorFactory(
            merge_aggs, partial_types)
    replacement.step = step
    return spec, replacement


def _exchange_adjacent(prev) -> bool:
    """True when ``prev`` is a remote-exchange source whose pages the
    segment should coalesce (they arrive host-side and page-sized)."""
    try:
        from presto_tpu.server.exchangeop import (
            ExchangeOperatorFactory, MergeExchangeOperatorFactory,
        )
    except Exception:  # noqa: BLE001 - server tier absent in slim envs
        return False
    return isinstance(prev, (ExchangeOperatorFactory,
                             MergeExchangeOperatorFactory))


def _partition_spec(sink) -> Optional[Tuple[Tuple[int, ...], int]]:
    """(channels, n_partitions) when ``sink`` is a hash-partitioned
    output whose partition ids a segment can precompute."""
    try:
        from presto_tpu.server.exchangeop import (
            PartitionedOutputOperatorFactory,
        )
    except Exception:  # noqa: BLE001 - server tier absent in slim envs
        return None
    if (isinstance(sink, PartitionedOutputOperatorFactory)
            and sink.n_partitions > 1 and sink.channels):
        return (tuple(sink.channels), sink.n_partitions)
    return None


# ---------------------------------------------------------------------------
# the fusion pass
# ---------------------------------------------------------------------------

def _try_final_merge(factory, prev, config):
    """FINAL-merge fusion (PR 4's named remaining depth, gated
    ``fusion_final_merge``): a grouped merge aggregation fed DIRECTLY by
    a remote exchange absorbs into an empty-stage coalescing segment —
    partial pages batch up to scan_batch_rows and merge-accumulate in
    ONE dispatch per flush, with the finalize projections folded into
    the downstream merge's finish.  Global merges stay unfused: their
    empty-input default row must come from the original prims, which
    the merge form no longer names.  Returns (spec, replacement) or
    (None, None)."""
    if not getattr(config, "fusion_final_merge", False):
        return None, None
    if not isinstance(factory, HashAggregationOperatorFactory):
        return None, None
    if not _exchange_adjacent(prev):
        return None, None
    return _try_pre_reduce([], factory, config,
                           out_types=list(factory.input_types),
                           relax_keys=True)


def fuse_chain(factories: List[OperatorFactory], config
               ) -> List[OperatorFactory]:
    """Replace maximal runs of fusable factories with FusedSegment
    factories.  A run fuses when it is ≥ 2 operators, rides directly on
    a device-staging TableScan (scan coalescing) or a remote exchange
    (page coalescing), feeds a hash-partitioned output (partition-id
    fusion), or feeds an eligible aggregation (partial-agg pre-reduce);
    it must contain at least one FilterProject or absorbed-probe stage
    (the segment's type anchor).  An eligible merge aggregation sitting
    DIRECTLY on a remote exchange absorbs without any run at all (the
    FINAL-merge segment)."""
    result: List[OperatorFactory] = []
    n = len(factories)
    i = 0
    while i < n:
        if not _fusable(factories[i], config):
            spec, replacement = _try_final_merge(
                factories[i], result[-1] if result else None, config)
            if spec is not None and replacement is not None:
                consumed = i + 1
                post_stages = []
                while (consumed < n
                        and isinstance(factories[consumed],
                                       FilterProjectOperatorFactory)
                        and factories[consumed].filter_expr is None):
                    post_stages.append(
                        list(factories[consumed].projections))
                    consumed += 1
                if post_stages:
                    replacement.post_projections = post_stages
                result.append(FusedSegmentOperatorFactory(
                    [], coalesce_rows=config.scan_batch_rows,
                    partition_spec=None,
                    min_batch_capacity=config.min_batch_capacity,
                    agg_spec=spec))
                result.append(replacement)
                i = consumed
                continue
            result.append(factories[i])
            i += 1
            continue
        j = i
        while j < n and _fusable(factories[j], config):
            j += 1
        run = factories[i:j]
        stages = [_stage_of(f) for f in run]
        has_fp = any(isinstance(s, (FPStage, ProbeStage))
                     for s in stages)
        scan = (result[-1] if result
                and isinstance(result[-1], TableScanOperatorFactory)
                and result[-1].to_device else None)
        exch = (getattr(config, "fusion_partial_agg", False) and result
                and _exchange_adjacent(result[-1]))
        # in-segment partial-aggregation pre-reduce: the run's output
        # feeds an eligible aggregation -> absorb its per-batch
        # accumulate; the aggregation becomes its merge form (or, for
        # the partial step, disappears — the FINAL merge takes over)
        spec = replacement = None
        consumed = j
        if has_fp and j < n:
            spec, replacement = _try_pre_reduce(stages, factories[j],
                                                config)
            if spec is not None:
                consumed = j + 1
                post_stages = []
                while (replacement is not None and consumed < n
                        and isinstance(factories[consumed],
                                       FilterProjectOperatorFactory)
                        and factories[consumed].filter_expr is None):
                    # fold the finalize projection run into the merge
                    # aggregation's finish: group-sized output, host
                    # vector math beats one more program launch per
                    # stacked projection
                    post_stages.append(
                        list(factories[consumed].projections))
                    consumed += 1
                if post_stages:
                    replacement.post_projections = post_stages
        partition = None
        if spec is None or replacement is None:
            # the segment's own output reaches the next factory (no
            # merge aggregation in between): partition-id fusion may
            # apply — including over pre-reduced partial rows feeding a
            # partial fragment's exchange sink
            partition = (_partition_spec(factories[consumed])
                         if consumed < n else None)
        if not has_fp or (len(run) < 2 and scan is None and not exch
                          and partition is None and spec is None):
            result.extend(run)
            i = j
            continue
        for s in stages:
            if isinstance(s, ProbeStage):
                # the resident build side must stay resident: a spilled
                # build would take the probe out of the segment's reach
                # mid-query (the broadcast-join stance)
                s.factory.build.allow_spill = False
        coalesce_rows = 0
        if scan is not None:
            # the segment takes over staging: the scan now hands over
            # raw host batches (ScanFilterAndProjectOperator role)
            result[-1] = TableScanOperatorFactory(
                scan.connector, scan.columns, scan.batch_rows,
                to_device=False, table=scan.table)
            coalesce_rows = config.scan_batch_rows
        elif exch:
            coalesce_rows = config.scan_batch_rows
        if partition is not None:
            factories[consumed].precomputed = True
        result.append(FusedSegmentOperatorFactory(
            stages, coalesce_rows=coalesce_rows, partition_spec=partition,
            min_batch_capacity=config.min_batch_capacity,
            agg_spec=spec))
        if replacement is not None:
            result.append(replacement)
        i = consumed if spec is not None else j
    return result


def fuse_pipelines(pipelines: Sequence, config) -> None:
    """Apply the fusion pass to every lowered pipeline, in place.  Runs
    after all lowering decisions (streaming-agg eligibility, grouped
    execution, dynamic-filter placement) were made on the unfused
    chains."""
    for p in pipelines:
        p.factories = fuse_chain(p.factories, config)


# ---------------------------------------------------------------------------
# the fused operator
# ---------------------------------------------------------------------------

class _ColView:
    """values/valid/type/dictionary holder for ops.hashing inside a
    traced segment program."""

    __slots__ = ("values", "valid", "type", "dictionary")

    def __init__(self, values, valid, typ, dictionary):
        self.values = values
        self.valid = valid
        self.type = typ
        self.dictionary = dictionary


class FusedSegmentOperator(Operator):
    """Executes a fused run of row-local stages as one jitted program per
    batch; optionally coalesces host scan batches first."""

    def __init__(self, ctx: OperatorContext, stages: Sequence,
                 coalesce_rows: int, partition_spec, min_batch_capacity,
                 agg_spec: Optional[PreReduceSpec] = None):
        super().__init__(ctx)
        self.stages = list(stages)
        self.partition_spec = partition_spec
        self.agg_spec = agg_spec
        # the bounded-domain direct-vs-sort decision is made at trace
        # time against this threshold; programs are shared globally, so
        # the threshold is part of the cache key
        self._max_domain = int(getattr(
            ctx.config, "direct_groupby_max_domain", 1 << 12))
        key_parts: tuple = tuple(s.key() for s in stages)
        if agg_spec is not None:
            key_parts = key_parts + (agg_spec.key(), self._max_domain)
        self._expr_key = key_parts
        self._coalesce = int(coalesce_rows)
        self._min_capacity = int(min_batch_capacity)
        self._pending: Optional[Batch] = None     # device-batch path
        self._emitted_any = False
        # absorbed-probe state: build-source snapshots resolve lazily at
        # first dispatch (the build pipeline has finished by then);
        # learned expansion capacities per inner probe stage persist
        # across batches (overflow bumps them once, then they stick)
        self._probe_idx = [k for k, s in enumerate(stages)
                           if isinstance(s, ProbeStage)]
        self._probe_srcs: Optional[list] = None
        self._out_caps: dict = {}
        # cost-based pre-reduce: flipped True when the observed
        # groups/rows ratio says per-batch grouping is not reducing
        self._raw_emit = False
        # host-coalescing path state
        self._acc: List[List[tuple]] = []          # per-flush batch parts
        self._acc_rows = 0
        self._targets: Optional[List[Optional[Dictionary]]] = None
        self._col_types: Optional[List[T.Type]] = None

    # -- protocol --------------------------------------------------------
    def needs_input(self) -> bool:
        if self._finishing:
            return False
        if self._coalesce:
            return self._acc_rows < self._coalesce
        return self._pending is None

    def add_input(self, batch: Batch) -> None:
        self.ctx.stats.input_batches += 1
        self.ctx.stats.input_rows += batch.num_rows
        if not self._coalesce:
            self._pending = batch
            return
        self._accumulate(batch)

    def get_output(self) -> Optional[Batch]:
        if self._coalesce:
            if self._acc_rows >= self._coalesce or (
                    self._finishing and self._acc_rows > 0):
                if self._passthrough_ok():
                    return self._emit(self._flush().compact())
                return self._emit(self._dispatch(self._flush()))
            if self._finishing and self._needs_default_row():
                return self._emit(self._default_partial_batch())
            return None
        if self._pending is None:
            if self._finishing and self._needs_default_row():
                return self._emit(self._default_partial_batch())
            return None
        batch, self._pending = self._pending, None
        return self._emit(self._dispatch(batch))

    # a FINAL-merge segment flush below this many rows skips its own
    # dispatch: the rows pass through AS partial states (identity — the
    # segment has no stages and its input/output schemas coincide) and
    # the downstream merge pays exactly what the unfused PR 9 path
    # paid.  Pre-reducing a tiny flush costs a full program launch to
    # save the merge almost nothing; at real exchange volumes the
    # flush crosses the bound and the in-segment merge-accumulate wins.
    _PASSTHROUGH_ROWS = 8192

    def _passthrough_ok(self) -> bool:
        return (not self.stages and self.agg_spec is not None
                and not self.agg_spec.global_
                and self._acc_rows < self._PASSTHROUGH_ROWS)

    def _emit(self, out: Optional[Batch]) -> Optional[Batch]:
        if out is None:
            return None
        self._emitted_any = True
        self.ctx.stats.output_batches += 1
        self.ctx.stats.output_rows += out.num_rows
        return out

    def _needs_default_row(self) -> bool:
        """A global pre-reduce segment that dispatched nothing still owes
        one default partial row (count=0, other states NULL): the merge
        aggregation's count components re-aggregate with 'sum', and SUM
        over zero partial rows is NULL where COUNT over empty is 0."""
        return (self.agg_spec is not None and self.agg_spec.global_
                and not self._emitted_any)

    def _default_partial_batch(self) -> Batch:
        cols = []
        for a in self.agg_spec.aggs:
            if a.prim == "count":
                cols.append(Column(a.out_type, np.zeros(1, np.int64)))
            else:
                dictionary = (Dictionary()
                              if a.out_type.is_dictionary else None)
                cols.append(Column(a.out_type,
                                   np.zeros(1, a.out_type.np_dtype),
                                   np.zeros(1, bool), dictionary))
        return Batch(tuple(cols), 1)

    def is_finished(self) -> bool:
        return self._finishing and self._pending is None \
            and self._acc_rows == 0 and not self._needs_default_row()

    # -- host coalescing (scan-adjacent segments) ------------------------
    def _accumulate(self, batch: Batch) -> None:
        batch = batch.to_numpy()
        n = batch.num_rows
        if self._targets is None:
            # adopt the first batch's dictionaries as the per-operator
            # interning targets (append-only, so codes stay stable)
            self._targets = [c.dictionary for c in batch.columns]
            self._col_types = [c.type for c in batch.columns]
        parts = []
        for ci, c in enumerate(batch.columns):
            vals = np.asarray(c.values)[:n]
            target = self._targets[ci]
            if c.dictionary is not None and c.dictionary is not target:
                remap = c.dictionary.remap_into(target)
                if len(remap):
                    vals = remap[vals]
            valid = None if c.valid is None else np.asarray(c.valid)[:n]
            parts.append((vals, valid))
        self._acc.append(parts)
        self._acc_rows += n
        self.ctx.memory.set_bytes(
            sum(v.nbytes for p in self._acc for v, _ in p))

    def _flush(self) -> Batch:
        ncols = len(self._col_types)
        rows = self._acc_rows
        cols = []
        for ci in range(ncols):
            vals = np.concatenate([p[ci][0] for p in self._acc]) \
                if len(self._acc) > 1 else self._acc[0][ci][0]
            valids = [p[ci][1] for p in self._acc]
            if any(v is not None for v in valids):
                valid = np.concatenate([
                    v if v is not None
                    else np.ones(p[ci][0].shape[0], bool)
                    for p, v in zip(self._acc, valids)])
            else:
                valid = None
            cols.append(Column(self._col_types[ci], vals, valid,
                               self._targets[ci]))
        self._acc = []
        self._acc_rows = 0
        self.ctx.memory.set_bytes(0)
        batch = Batch(tuple(cols), rows)
        return batch.pad_rows(next_bucket(rows, self._min_capacity))

    # -- dispatch --------------------------------------------------------
    def _df_snapshot(self):
        """Per-DF-stage (shape, args): shape keys the program, args carry
        the values.  Returns None when an empty build makes the whole
        segment output empty (inner-join semantics)."""
        shapes, args = [], []
        for s in self.stages:
            if not isinstance(s, DFStage):
                continue
            dyn = s.dyn
            if not dyn.ready or dyn.disabled:
                shapes.append(("off",))
                args.append(((), ()))
                continue
            if dyn.build_empty:
                return None
            chans, has_set, bounds, tables = [], [], [], []
            for i, ch in enumerate(s.key_channels):
                if dyn.mins[i] is None:
                    continue
                chans.append(ch)
                st = dyn.sets[i]
                has_set.append(st is not None)
                bounds.append((np.asarray(dyn.mins[i]),
                               np.asarray(dyn.maxs[i])))
                if st is not None:
                    tables.append(st)
            shapes.append((tuple(chans), tuple(has_set)))
            args.append((tuple(bounds), tuple(tables)))
        return tuple(shapes), tuple(args)

    def _probe_snapshot(self):
        """Resolve (and cache) each absorbed probe's build source.  The
        program is keyed by the source's SHAPE (mode, capacities,
        dictionary binding); the arrays themselves ride as runtime
        kernel arguments, so identical queries share executables."""
        import jax.numpy as jnp

        if self._probe_srcs is None:
            srcs = []
            for k in self._probe_idx:
                src = self.stages[k].factory.build.lookup.get()
                if src.mode not in ("hash", "single", "packed"):
                    raise RuntimeError(
                        "absorbed join probe needs a streaming lookup "
                        f"source, got mode={src.mode!r}; rerun with "
                        "device_join_probe=false")
                srcs.append(src)
                self.ctx.stats.kernel_tier = (
                    self.ctx.stats.kernel_tier or
                    ("hash" if src.mode == "hash" else "sorted"))
            self._probe_srcs = srcs
        key_parts, args, metas = [], [], []
        for k, src in zip(self._probe_idx, self._probe_srcs):
            f = self.stages[k].factory
            out_cap = self._out_caps.get(k, 0)
            build_pairs = tuple(column_pairs(src.data))
            if src.mode == "hash":
                aux = (src.pages, src.perm)
                table_cap = src.pages[2].shape[0]
            elif src.mode == "single":
                aux = (src.sorted_ids, src.perm, src.mins,
                       jnp.zeros(1, jnp.int64), jnp.zeros(1, jnp.int64))
                table_cap = 0
            else:
                aux = (src.sorted_ids, src.perm, jnp.asarray(src.mins),
                       jnp.asarray(src.strides), jnp.asarray(src.maxs))
                table_cap = 0
            bstats = (jnp.asarray(src.n_build, jnp.int64),
                      src.has_null_key if src.has_null_key is not None
                      else jnp.zeros((), bool))
            key_parts.append((src.mode, src.data.capacity, table_cap,
                              dictionary_binding_key(src.data.columns),
                              out_cap))
            args.append((build_pairs, aux, bstats))
            metas.append({
                "mode": src.mode, "out_cap": out_cap,
                "join_type": f.join_type,
                "null_aware": f.null_aware,
                "key_channels": tuple(f.probe_key_channels),
                "key_types": src.key_types or (),
                "build_meta": [(c.type, c.dictionary)
                               for c in src.data.columns],
            })
        return tuple(key_parts), tuple(args), metas

    def _default_out_cap(self, capacity: int) -> int:
        """First expansion bucket for an inner probe: the probe space
        itself (exact for FK->PK joins, where every probe row matches
        at most one build row); duplicate-key builds overflow once,
        learn the bucket, and keep it."""
        return next_bucket(max(capacity, 1))

    def _dispatch(self, batch: Batch) -> Optional[Batch]:
        snap = self._df_snapshot()
        if snap is None:
            return None      # empty build: nothing can survive the join
        df_shapes, df_args = snap
        part_n = self.partition_spec[1] if self.partition_spec else 0
        cap = batch.capacity
        for k in self._probe_idx:
            if k not in self._out_caps:
                if self.stages[k].factory.join_type == "inner":
                    cap = max(self._default_out_cap(cap),
                              _OUT_CAPS_LEARNED.get(
                                  (self._expr_key, k, batch.capacity),
                                  0))
                    self._out_caps[k] = cap
                else:
                    self._out_caps[k] = 0
            else:
                cap = max(cap, self._out_caps[k] or cap)
        while True:
            probe_keys, probe_args, probe_metas = ((), (), [])
            if self._probe_idx:
                probe_keys, probe_args, probe_metas = \
                    self._probe_snapshot()
            key = (self._expr_key, batch.capacity,
                   dictionary_binding_key(batch.columns), df_shapes,
                   part_n, probe_keys, self._raw_emit)
            entry = cache_get(_SEG_KERNELS, key)
            if entry is None:
                import time as _time

                from presto_tpu.kernelcache import (
                    record_compile, timed_first_call,
                )

                _t0 = _time.perf_counter_ns()
                built_fn, built_meta = self._compile(batch, df_shapes,
                                                     probe_metas)
                build_ns = _time.perf_counter_ns() - _t0
                self.ctx.stats.jit_compile_ns += build_ns
                record_compile(_SEG_KERNELS, build_ns)
                entry = (timed_first_call(built_fn, self.ctx.stats,
                                          _SEG_KERNELS), built_meta)
                cache_put(_SEG_KERNELS, key, entry)
                self.ctx.stats.jit_compiles += 1
            fn, out_meta = entry
            self.ctx.stats.jit_dispatches += 1
            outs, count, parts, etotals = fn(
                tuple(column_pairs(batch)), batch.num_rows, df_args,
                probe_args)
            # expansion-overflow retry: bump the learned bucket for any
            # inner probe whose exact total exceeded its capacity and
            # re-dispatch (ops/join.py's host-retry policy, in-segment)
            overflowed = False
            for k, total in zip(
                    (k for k in self._probe_idx
                     if self.stages[k].factory.join_type == "inner"),
                    etotals):
                t = int(total)
                if t > self._out_caps[k]:
                    self._out_caps[k] = next_bucket(t)
                    lk = (self._expr_key, k, batch.capacity)
                    _OUT_CAPS_LEARNED[lk] = max(
                        _OUT_CAPS_LEARNED.get(lk, 0), self._out_caps[k])
                    overflowed = True
            if not overflowed:
                break
        if self.agg_spec is not None and not self._raw_emit:
            self.ctx.stats.prereduce_rows += batch.num_rows
        n = int(count)
        self._observe_reduction(batch.num_rows, n)
        if n == 0:
            return None
        cols = tuple(Column(typ, v, valid, d)
                     for (typ, d), (v, valid) in zip(out_meta, outs))
        if parts is not None:
            cols = cols + (Column(T.INTEGER, parts),)
        return Batch(cols, n)

    def _observe_reduction(self, rows_in: int, groups_out: int) -> None:
        """Runtime half of the cost-based pre-reduce decision: when a
        grouped pre-reduce emits nearly one group per input row, later
        batches skip the group kernel and emit raw rows in the partial
        schema (any granularity is legal for the downstream merge)."""
        if (self.agg_spec is None or self.agg_spec.global_
                or self._raw_emit):
            return
        cfg = self.ctx.config
        if not getattr(cfg, "prereduce_cost_based", False):
            return
        if rows_in < 2048:      # tiny batches prove nothing
            return
        frac = getattr(cfg, "prereduce_max_group_fraction", 0.9)
        if groups_out > frac * rows_in:
            self._raw_emit = True

    def _compile(self, batch: Batch, df_shapes, probe_metas=()):
        import jax

        # stage-by-stage expression compilation: each stage's dictionary
        # bindings are the previous stage's projection output
        # dictionaries (stage 0 binds the batch's columns)
        dicts = {i: c.dictionary for i, c in enumerate(batch.columns)
                 if c.dictionary is not None}
        progs = []
        out_meta = [(c.type, c.dictionary) for c in batch.columns]
        di = 0
        pi_meta = 0
        for stage in self.stages:
            if isinstance(stage, FPStage):
                compiler = ExprCompiler(dicts)
                cfilter = (compiler.compile(stage.filter_expr)
                           if stage.filter_expr is not None else None)
                cprojs = [compiler.compile(p) for p in stage.projections]
                progs.append(("fp", cfilter, cprojs))
                dicts = {i: cp.dictionary for i, cp in enumerate(cprojs)
                         if cp.dictionary is not None}
                out_meta = [(cp.type, cp.dictionary) for cp in cprojs]
            elif isinstance(stage, ProbeStage):
                meta = probe_metas[pi_meta]
                pi_meta += 1
                progs.append(("probe", meta))
                if meta["join_type"] == "inner":
                    out_meta = list(out_meta) + list(meta["build_meta"])
                dicts = {i: d for i, (_t, d) in enumerate(out_meta)
                         if d is not None}
            else:
                progs.append(("df", df_shapes[di]))
                di += 1
        partition = self.partition_spec
        agg = self.agg_spec
        max_domain = self._max_domain
        raw_emit = self._raw_emit
        if agg is not None:
            # partial schema: [key columns..., one state col per agg]
            key_meta = [out_meta[g] for g in agg.group_channels]
            final_meta = key_meta + [(a.out_type, None) for a in agg.aggs]
            agg_prims = [(a.prim, a.channel) for a in agg.aggs]
            out_dtypes = [a.out_type.np_dtype for a in agg.aggs]
        else:
            final_meta = out_meta

        def kernel(cols, num_rows, df_args, probe_args):
            import jax.numpy as jnp

            from presto_tpu.ops import join as J
            from presto_tpu.ops.filter import selected_positions

            mask = None
            cur = tuple(cols)
            dfi = 0
            pri = 0
            etotals = []
            for prog in progs:
                if prog[0] == "fp":
                    _, cfilter, cprojs = prog
                    if cfilter is not None:
                        fv, fvalid = cfilter.run(cur, num_rows, jnp)
                        m = fv if fvalid is None else fv & fvalid
                        mask = m if mask is None else mask & m
                    cur = tuple(p.run(cur, num_rows, jnp) for p in cprojs)
                elif prog[0] == "probe":
                    meta = prog[1]
                    build_pairs, aux, bstats = probe_args[pri]
                    pri += 1
                    kc = meta["key_channels"]
                    cap_now = cur[0][0].shape[0]
                    if meta["mode"] == "hash":
                        from presto_tpu.ops.hashtable import (
                            pages_hash_probe,
                        )

                        pages, perm = aux
                        kcols = [(cur[c][0], cur[c][1], kt)
                                 for c, kt in zip(kc, meta["key_types"])]
                        lo, counts, live = pages_hash_probe(
                            pages, kcols, num_rows)
                    else:
                        from presto_tpu.exec.joinop import _ids_from_pairs

                        sorted_ids, perm, mins, strides, maxs = aux
                        ids = _ids_from_pairs(
                            jnp, cur, kc, meta["mode"], mins, strides,
                            maxs, num_rows)
                        lo, counts = J.probe_counts(sorted_ids, perm, ids)
                        live = ids >= 0
                    alive = jnp.arange(cap_now) < num_rows
                    if mask is not None:
                        alive = alive & mask
                    jt = meta["join_type"]
                    if jt == "semi":
                        mask = J.semi_mask(counts, live & alive,
                                           anti=False)
                    elif jt == "anti":
                        n_build, has_null = bstats
                        mask = J.anti_keep_from_parts(
                            counts, live, alive, meta["null_aware"],
                            [cur[c][1] for c in kc], n_build,
                            build_has_null=has_null)
                    else:
                        out_cap = meta["out_cap"]
                        cnts = jnp.where(alive, counts, 0)
                        p_idx, b_idx, rv, _unm, total = J.expand_matches(
                            lo, cnts, perm, out_cap)
                        p32 = p_idx.astype(jnp.int32)
                        b32 = b_idx.astype(jnp.int32)
                        new_cur = [
                            (v[p32],
                             None if valid is None else valid[p32])
                            for v, valid in cur]
                        for v, valid in build_pairs:
                            bvalid = (rv if valid is None
                                      else (valid[b32] & rv))
                            new_cur.append((v[b32], bvalid))
                        cur = tuple(new_cur)
                        mask = rv
                        num_rows = total
                        etotals.append(total)
                else:
                    shape = prog[1]
                    bounds, tables = df_args[dfi]
                    dfi += 1
                    if shape == ("off",) or not shape[0]:
                        continue
                    chans, has_set = shape
                    ti = 0
                    for k, ch in enumerate(chans):
                        v, valid = cur[ch]
                        mn, mx = bounds[k]
                        m = ((v >= mn.astype(v.dtype))
                             & (v <= mx.astype(v.dtype)))
                        if has_set[k]:
                            table = tables[ti].astype(v.dtype)
                            ti += 1
                            idx = jnp.clip(jnp.searchsorted(table, v), 0,
                                           table.shape[0] - 1)
                            m = m & (table[idx] == v)
                        if valid is not None:
                            m = m & valid
                        mask = m if mask is None else mask & m
            cap = cur[0][0].shape[0]
            if agg is not None and raw_emit and not agg.global_:
                # cost-based raw emission: the observed groups/rows
                # ratio said grouping is not reducing — compact the
                # live rows once and emit them AS partial states (one
                # row = one group of one; the downstream merge accepts
                # any granularity)
                m = (mask if mask is not None
                     else jnp.ones(cap, bool))
                idx, count = selected_positions(m, None, num_rows, cap)
                idx = idx.astype(jnp.int32)
                outs = []
                for g in agg.group_channels:
                    v, valid = cur[g]
                    outs.append((v[idx],
                                 None if valid is None else valid[idx]))
                for (prim, ch), dtype in zip(agg_prims, out_dtypes):
                    if ch is None:
                        outs.append((jnp.ones(cap, jnp.int64)[idx],
                                     None))
                    elif prim == "count":
                        v, valid = cur[ch]
                        ones = (jnp.ones(cap, jnp.int64)
                                if valid is None
                                else valid.astype(jnp.int64))
                        outs.append((ones[idx], None))
                    else:
                        v, valid = cur[ch]
                        outs.append((v[idx].astype(dtype),
                                     None if valid is None
                                     else valid[idx]))
                outs = tuple(outs)
            elif agg is not None:
                # pre-reduce: NO compaction — the accumulated mask rides
                # into the group kernels as the live mask, and the
                # segment emits per-batch partial group states instead
                # of rows (HashAggregationOperator.java:48 partial step,
                # fused into the scan program)
                from presto_tpu.ops.groupby import (
                    global_pre_reduce, segment_pre_reduce,
                )

                agg_ins = []
                for prim, ch in agg_prims:
                    if ch is None:
                        agg_ins.append(("count", None, None))
                    else:
                        v, valid = cur[ch]
                        agg_ins.append((prim, v, valid))
                if agg.global_:
                    outs = tuple(global_pre_reduce(
                        agg_ins, out_dtypes, num_rows, mask))
                    count = 1
                else:
                    keys = []
                    doms = []
                    bounded = True
                    total = 1
                    for g, (typ, d) in zip(agg.group_channels, key_meta):
                        v, valid = cur[g]
                        keys.append((v, valid, typ))
                        if d is not None:
                            dom = len(d)
                        elif typ.name == "boolean":
                            dom = 2
                        else:
                            bounded = False
                            dom = 0
                        doms.append(dom)
                        total *= dom + (1 if valid is not None else 0)
                    # direct (bounded-domain) vs sort path, decided at
                    # trace time: the sort fallback runs at the batch
                    # capacity, so per-batch groups can never overflow
                    use_direct = bounded and 0 < total <= max_domain
                    key_outs, agg_outs, count = segment_pre_reduce(
                        keys, agg_ins, out_dtypes, num_rows, mask,
                        doms if use_direct else None, cap)
                    outs = tuple(key_outs) + tuple(agg_outs)
            elif mask is not None:
                # ONE compaction for the whole segment: every stage's
                # filter landed in the accumulated mask, so unselected
                # rows were computed over (harmless, like padding rows)
                # but never gathered or materialized
                idx, count = selected_positions(mask, None, num_rows, cap)
                outs = tuple(
                    (v[idx], None if valid is None else valid[idx])
                    for v, valid in cur)
            else:
                outs = cur
                count = num_rows
            parts = None
            if partition is not None:
                from presto_tpu.ops.hashing import (
                    partition_of, row_hash, value_hash_triple,
                )

                channels, nparts = partition
                triples = []
                for ch in channels:
                    v, valid = outs[ch]
                    typ, d = final_meta[ch]
                    triples.append(value_hash_triple(
                        _ColView(v, valid, typ, d)))
                parts = partition_of(row_hash(triples), nparts)
            return outs, count, parts, tuple(etotals)

        return jax.jit(kernel), list(final_meta)


class FusedSegmentOperatorFactory(OperatorFactory):
    parallel_safe = True

    def __init__(self, stages: Sequence, coalesce_rows: int = 0,
                 partition_spec=None, min_batch_capacity: int = 1024,
                 agg_spec: Optional[PreReduceSpec] = None):
        self.stages = list(stages)
        self.coalesce_rows = coalesce_rows
        self.partition_spec = partition_spec
        self.min_batch_capacity = min_batch_capacity
        self.agg_spec = agg_spec

    def create(self, ctx: OperatorContext) -> FusedSegmentOperator:
        return FusedSegmentOperator(ctx, self.stages, self.coalesce_rows,
                                    self.partition_spec,
                                    self.min_batch_capacity,
                                    agg_spec=self.agg_spec)

    def describe(self) -> str:
        """Human-readable stage summary (tools/fusion_report.py)."""
        parts = []
        for s in self.stages:
            if isinstance(s, FPStage):
                parts.append(
                    "fp(filter=%s, %d proj)" % (
                        "yes" if s.filter_expr is not None else "no",
                        len(s.projections)))
            elif isinstance(s, ProbeStage):
                parts.append("probe(%s, keys=%s)" % (
                    s.factory.join_type,
                    list(s.factory.probe_key_channels)))
            else:
                parts.append("df(keys=%s)" % (list(s.key_channels),))
        if self.agg_spec is not None:
            parts.append("prereduce(%s, %d aggs)" % (
                "global" if self.agg_spec.global_
                else "keys=%s" % (list(self.agg_spec.group_channels),),
                len(self.agg_spec.aggs)))
        extra = []
        if self.coalesce_rows:
            extra.append(f"coalesce={self.coalesce_rows}")
        if self.partition_spec:
            extra.append("partition=%dx%s" % (
                self.partition_spec[1], list(self.partition_spec[0])))
        tail = (" [" + ", ".join(extra) + "]") if extra else ""
        return "FusedSegment{" + " -> ".join(parts) + "}" + tail


def boundary_roles(pipelines) -> List[Tuple[str, str, str]]:
    """(pipeline name, segment description, role) for every fused
    segment that touches a fragment boundary on the HTTP exchange tier:
    'feeds-exchange' when the segment computes the partition ids
    PartitionedOutput routes by (the producer side of a boundary),
    'fed-by-exchange' when it coalesces pages arriving from a remote
    exchange (the consumer side), 'feeds+fed' for both, '' for interior
    segments.  On the device-sharded exchange tier neither side exists
    — the boundary collective splices the exchange-feeding and
    exchange-fed segment programs into ONE trace — so this report names
    exactly the dispatch/serde work the collective tier removes
    (tools/exchange_report.py renders it next to the per-boundary
    exchange-mode column)."""
    out = []
    for p in pipelines:
        for i, f in enumerate(p.factories):
            if not isinstance(f, FusedSegmentOperatorFactory):
                continue
            feeds = f.partition_spec is not None
            fed = i > 0 and _exchange_adjacent(p.factories[i - 1])
            role = ("feeds+fed" if feeds and fed
                    else "feeds-exchange" if feeds
                    else "fed-by-exchange" if fed else "")
            out.append((p.name, f.describe(), role))
    return out
