"""Pipeline fusion: one jitted XLA program per run of row-local operators.

The reference's performance tier is runtime code generation — its
``ExpressionCompiler``/``PageProcessor`` fuse a filter and all its
projections into one generated loop per page (survey §2.7).  The engine
already matches the intra-operator half (``FilterProjectOperator`` jits
filter+projections together), but a fragment still executed as a chain of
independently-jitted dispatches with a Python driver hop between every
adjacent operator pair, so intermediates round-tripped through HBM (and
sometimes host) at each hop.

This module is the cross-operator generalization: at fragment-lowering
time ``fuse_pipelines`` identifies maximal runs of adjacent row-local,
jit-able operator factories —

- chained ``FilterProject``s (stacked optimizer Projects, join residuals,
  aggregation finalize projections),
- dynamic-filter application (``DynamicFilterOperator``),
- the partial-aggregation input projection (an ordinary FilterProject),
- the hash/partition-id computation feeding ``PartitionedOutputOperator``

— and compiles each run into ONE jitted segment program executed once per
batch.  Inside a segment, consecutive filters combine into one
accumulated mask with a single gather at the end, projection
intermediates never materialize (XLA fuses the elementwise chains), and
the exchange sink's partition ids ride along as one extra output.

Scan-adjacent segments additionally take over the scan staging (the
``ScanFilterAndProjectOperator`` role): the scan hands over raw host
batches and the segment coalesces them up to ``scan_batch_rows`` before
staging + dispatching once, so many tiny per-split batches cost one
launch instead of one each.  Dictionary columns are re-coded into a
per-operator target dictionary so coalesced flushes share one compiled
program.

Segment programs are cached globally (``kernelcache``) keyed by segment
expression keys + capacity bucket + dictionary binding (token, length) +
the dynamic-filter value shape — the same keying discipline as
``_FP_KERNELS``.  Gated by ``EngineConfig.pipeline_fusion`` (default on;
off restores per-operator dispatch exactly).

What breaks a segment: any non-row-local operator (aggregation, join,
sort, exchange, limit), expressions that need the host path (nested
types, row-wise string fallbacks), and nested input/output types.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence, Tuple

import numpy as np

from presto_tpu import types as T
from presto_tpu.batch import Batch, Column, Dictionary, next_bucket
from presto_tpu.exec.context import OperatorContext
from presto_tpu.exec.dynamicfilter import (
    DynamicFilter, DynamicFilterOperatorFactory,
)
from presto_tpu.exec.operator import Operator, OperatorFactory, column_pairs
from presto_tpu.exec.operators import (
    FilterProjectOperatorFactory, TableScanOperatorFactory,
    dictionary_binding_key,
)
from presto_tpu.expr.compile import ExprCompiler, needs_host_path
from presto_tpu.expr.ir import RowExpression
from presto_tpu.kernelcache import cache_get, cache_put, new_cache

# compiled segment programs, shared globally across queries/operators
_SEG_KERNELS = new_cache("fused_segment")


# ---------------------------------------------------------------------------
# segment stages
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class FPStage:
    """One filter+projections step (a FilterProjectOperator's work)."""

    filter_expr: Optional[RowExpression]
    projections: Tuple[RowExpression, ...]
    input_types: Tuple[T.Type, ...]

    def key(self) -> tuple:
        return ("fp", self.filter_expr, self.projections, self.input_types)


@dataclasses.dataclass(frozen=True, eq=False)
class DFStage:
    """Dynamic-filter application over the current channel space.

    The filter VALUES (bounds, IN-set tables) are runtime kernel
    arguments, never trace constants; only the value *shape* (which
    channels are bounded, which have exact sets) keys the program.
    Adaptive shutoff is intentionally absent: it existed to avoid an
    extra per-batch dispatch, and inside a fused segment the filter
    costs no extra launch.
    """

    dyn: DynamicFilter
    key_channels: Tuple[int, ...]

    def key(self) -> tuple:
        return ("df", self.key_channels)


def _stage_of(factory) -> object:
    if isinstance(factory, FilterProjectOperatorFactory):
        return FPStage(factory.filter_expr, tuple(factory.projections),
                       tuple(factory.input_types))
    if isinstance(factory, DynamicFilterOperatorFactory):
        return DFStage(factory.dyn, tuple(factory.key_channels))
    raise TypeError(f"not a fusable factory: {type(factory).__name__}")


def _fp_jitable(f: FilterProjectOperatorFactory) -> bool:
    """True when the stage can run inside a jitted segment (mirrors the
    FilterProjectOperator host-path eligibility, decided statically)."""
    if needs_host_path([f.filter_expr] + list(f.projections)):
        return False
    if any(t.is_nested for t in f.input_types):
        return False
    if any(p.type.is_nested for p in f.projections):
        return False
    return True


def _fusable(f) -> bool:
    if isinstance(f, DynamicFilterOperatorFactory):
        return True
    if isinstance(f, FilterProjectOperatorFactory):
        return _fp_jitable(f)
    return False


def _partition_spec(sink) -> Optional[Tuple[Tuple[int, ...], int]]:
    """(channels, n_partitions) when ``sink`` is a hash-partitioned
    output whose partition ids a segment can precompute."""
    try:
        from presto_tpu.server.exchangeop import (
            PartitionedOutputOperatorFactory,
        )
    except Exception:  # noqa: BLE001 - server tier absent in slim envs
        return None
    if (isinstance(sink, PartitionedOutputOperatorFactory)
            and sink.n_partitions > 1 and sink.channels):
        return (tuple(sink.channels), sink.n_partitions)
    return None


# ---------------------------------------------------------------------------
# the fusion pass
# ---------------------------------------------------------------------------

def fuse_chain(factories: List[OperatorFactory], config
               ) -> List[OperatorFactory]:
    """Replace maximal runs of fusable factories with FusedSegment
    factories.  A run fuses when it is ≥ 2 operators, or rides directly
    on a device-staging TableScan (scan coalescing), or feeds a
    hash-partitioned output (partition-id fusion); it must contain at
    least one FilterProject stage (the segment's type anchor)."""
    result: List[OperatorFactory] = []
    n = len(factories)
    i = 0
    while i < n:
        if not _fusable(factories[i]):
            result.append(factories[i])
            i += 1
            continue
        j = i
        while j < n and _fusable(factories[j]):
            j += 1
        run = factories[i:j]
        stages = [_stage_of(f) for f in run]
        has_fp = any(isinstance(s, FPStage) for s in stages)
        scan = (result[-1] if result
                and isinstance(result[-1], TableScanOperatorFactory)
                and result[-1].to_device else None)
        partition = _partition_spec(factories[j]) if j < n else None
        if not has_fp or (len(run) < 2 and scan is None
                          and partition is None):
            result.extend(run)
            i = j
            continue
        coalesce_rows = 0
        if scan is not None:
            # the segment takes over staging: the scan now hands over
            # raw host batches (ScanFilterAndProjectOperator role)
            result[-1] = TableScanOperatorFactory(
                scan.connector, scan.columns, scan.batch_rows,
                to_device=False, table=scan.table)
            coalesce_rows = config.scan_batch_rows
        if partition is not None:
            factories[j].precomputed = True
        result.append(FusedSegmentOperatorFactory(
            stages, coalesce_rows=coalesce_rows, partition_spec=partition,
            min_batch_capacity=config.min_batch_capacity))
        i = j
    return result


def fuse_pipelines(pipelines: Sequence, config) -> None:
    """Apply the fusion pass to every lowered pipeline, in place.  Runs
    after all lowering decisions (streaming-agg eligibility, grouped
    execution, dynamic-filter placement) were made on the unfused
    chains."""
    for p in pipelines:
        p.factories = fuse_chain(p.factories, config)


# ---------------------------------------------------------------------------
# the fused operator
# ---------------------------------------------------------------------------

class _ColView:
    """values/valid/type/dictionary holder for ops.hashing inside a
    traced segment program."""

    __slots__ = ("values", "valid", "type", "dictionary")

    def __init__(self, values, valid, typ, dictionary):
        self.values = values
        self.valid = valid
        self.type = typ
        self.dictionary = dictionary


class FusedSegmentOperator(Operator):
    """Executes a fused run of row-local stages as one jitted program per
    batch; optionally coalesces host scan batches first."""

    def __init__(self, ctx: OperatorContext, stages: Sequence,
                 coalesce_rows: int, partition_spec, min_batch_capacity):
        super().__init__(ctx)
        self.stages = list(stages)
        self.partition_spec = partition_spec
        self._expr_key = tuple(s.key() for s in stages)
        self._coalesce = int(coalesce_rows)
        self._min_capacity = int(min_batch_capacity)
        self._pending: Optional[Batch] = None     # device-batch path
        # host-coalescing path state
        self._acc: List[List[tuple]] = []          # per-flush batch parts
        self._acc_rows = 0
        self._targets: Optional[List[Optional[Dictionary]]] = None
        self._col_types: Optional[List[T.Type]] = None

    # -- protocol --------------------------------------------------------
    def needs_input(self) -> bool:
        if self._finishing:
            return False
        if self._coalesce:
            return self._acc_rows < self._coalesce
        return self._pending is None

    def add_input(self, batch: Batch) -> None:
        self.ctx.stats.input_batches += 1
        self.ctx.stats.input_rows += batch.num_rows
        if not self._coalesce:
            self._pending = batch
            return
        self._accumulate(batch)

    def get_output(self) -> Optional[Batch]:
        if self._coalesce:
            if self._acc_rows >= self._coalesce or (
                    self._finishing and self._acc_rows > 0):
                return self._emit(self._dispatch(self._flush()))
            return None
        if self._pending is None:
            return None
        batch, self._pending = self._pending, None
        return self._emit(self._dispatch(batch))

    def _emit(self, out: Optional[Batch]) -> Optional[Batch]:
        if out is None:
            return None
        self.ctx.stats.output_batches += 1
        self.ctx.stats.output_rows += out.num_rows
        return out

    def is_finished(self) -> bool:
        return self._finishing and self._pending is None \
            and self._acc_rows == 0

    # -- host coalescing (scan-adjacent segments) ------------------------
    def _accumulate(self, batch: Batch) -> None:
        batch = batch.to_numpy()
        n = batch.num_rows
        if self._targets is None:
            # adopt the first batch's dictionaries as the per-operator
            # interning targets (append-only, so codes stay stable)
            self._targets = [c.dictionary for c in batch.columns]
            self._col_types = [c.type for c in batch.columns]
        parts = []
        for ci, c in enumerate(batch.columns):
            vals = np.asarray(c.values)[:n]
            target = self._targets[ci]
            if c.dictionary is not None and c.dictionary is not target:
                remap = c.dictionary.remap_into(target)
                if len(remap):
                    vals = remap[vals]
            valid = None if c.valid is None else np.asarray(c.valid)[:n]
            parts.append((vals, valid))
        self._acc.append(parts)
        self._acc_rows += n
        self.ctx.memory.set_bytes(
            sum(v.nbytes for p in self._acc for v, _ in p))

    def _flush(self) -> Batch:
        ncols = len(self._col_types)
        rows = self._acc_rows
        cols = []
        for ci in range(ncols):
            vals = np.concatenate([p[ci][0] for p in self._acc]) \
                if len(self._acc) > 1 else self._acc[0][ci][0]
            valids = [p[ci][1] for p in self._acc]
            if any(v is not None for v in valids):
                valid = np.concatenate([
                    v if v is not None
                    else np.ones(p[ci][0].shape[0], bool)
                    for p, v in zip(self._acc, valids)])
            else:
                valid = None
            cols.append(Column(self._col_types[ci], vals, valid,
                               self._targets[ci]))
        self._acc = []
        self._acc_rows = 0
        self.ctx.memory.set_bytes(0)
        batch = Batch(tuple(cols), rows)
        return batch.pad_rows(next_bucket(rows, self._min_capacity))

    # -- dispatch --------------------------------------------------------
    def _df_snapshot(self):
        """Per-DF-stage (shape, args): shape keys the program, args carry
        the values.  Returns None when an empty build makes the whole
        segment output empty (inner-join semantics)."""
        shapes, args = [], []
        for s in self.stages:
            if not isinstance(s, DFStage):
                continue
            dyn = s.dyn
            if not dyn.ready or dyn.disabled:
                shapes.append(("off",))
                args.append(((), ()))
                continue
            if dyn.build_empty:
                return None
            chans, has_set, bounds, tables = [], [], [], []
            for i, ch in enumerate(s.key_channels):
                if dyn.mins[i] is None:
                    continue
                chans.append(ch)
                st = dyn.sets[i]
                has_set.append(st is not None)
                bounds.append((np.asarray(dyn.mins[i]),
                               np.asarray(dyn.maxs[i])))
                if st is not None:
                    tables.append(st)
            shapes.append((tuple(chans), tuple(has_set)))
            args.append((tuple(bounds), tuple(tables)))
        return tuple(shapes), tuple(args)

    def _dispatch(self, batch: Batch) -> Optional[Batch]:
        snap = self._df_snapshot()
        if snap is None:
            return None      # empty build: nothing can survive the join
        df_shapes, df_args = snap
        part_n = self.partition_spec[1] if self.partition_spec else 0
        key = (self._expr_key, batch.capacity,
               dictionary_binding_key(batch.columns), df_shapes, part_n)
        entry = cache_get(_SEG_KERNELS, key)
        if entry is None:
            entry = self._compile(batch, df_shapes)
            cache_put(_SEG_KERNELS, key, entry)
            self.ctx.stats.jit_compiles += 1
        fn, out_meta = entry
        self.ctx.stats.jit_dispatches += 1
        outs, count, parts = fn(tuple(column_pairs(batch)),
                                batch.num_rows, df_args)
        n = int(count)
        if n == 0:
            return None
        cols = tuple(Column(typ, v, valid, d)
                     for (typ, d), (v, valid) in zip(out_meta, outs))
        if parts is not None:
            cols = cols + (Column(T.INTEGER, parts),)
        return Batch(cols, n)

    def _compile(self, batch: Batch, df_shapes):
        import jax

        # stage-by-stage expression compilation: each stage's dictionary
        # bindings are the previous stage's projection output
        # dictionaries (stage 0 binds the batch's columns)
        dicts = {i: c.dictionary for i, c in enumerate(batch.columns)
                 if c.dictionary is not None}
        progs = []
        out_meta = [(c.type, c.dictionary) for c in batch.columns]
        di = 0
        for stage in self.stages:
            if isinstance(stage, FPStage):
                compiler = ExprCompiler(dicts)
                cfilter = (compiler.compile(stage.filter_expr)
                           if stage.filter_expr is not None else None)
                cprojs = [compiler.compile(p) for p in stage.projections]
                progs.append(("fp", cfilter, cprojs))
                dicts = {i: cp.dictionary for i, cp in enumerate(cprojs)
                         if cp.dictionary is not None}
                out_meta = [(cp.type, cp.dictionary) for cp in cprojs]
            else:
                progs.append(("df", df_shapes[di]))
                di += 1
        cap = batch.capacity
        partition = self.partition_spec

        def kernel(cols, num_rows, df_args):
            import jax.numpy as jnp

            from presto_tpu.ops.filter import selected_positions

            mask = None
            cur = tuple(cols)
            dfi = 0
            for prog in progs:
                if prog[0] == "fp":
                    _, cfilter, cprojs = prog
                    if cfilter is not None:
                        fv, fvalid = cfilter.run(cur, num_rows, jnp)
                        m = fv if fvalid is None else fv & fvalid
                        mask = m if mask is None else mask & m
                    cur = tuple(p.run(cur, num_rows, jnp) for p in cprojs)
                else:
                    shape = prog[1]
                    bounds, tables = df_args[dfi]
                    dfi += 1
                    if shape == ("off",) or not shape[0]:
                        continue
                    chans, has_set = shape
                    ti = 0
                    for k, ch in enumerate(chans):
                        v, valid = cur[ch]
                        mn, mx = bounds[k]
                        m = ((v >= mn.astype(v.dtype))
                             & (v <= mx.astype(v.dtype)))
                        if has_set[k]:
                            table = tables[ti].astype(v.dtype)
                            ti += 1
                            idx = jnp.clip(jnp.searchsorted(table, v), 0,
                                           table.shape[0] - 1)
                            m = m & (table[idx] == v)
                        if valid is not None:
                            m = m & valid
                        mask = m if mask is None else mask & m
            if mask is not None:
                # ONE compaction for the whole segment: every stage's
                # filter landed in the accumulated mask, so unselected
                # rows were computed over (harmless, like padding rows)
                # but never gathered or materialized
                idx, count = selected_positions(mask, None, num_rows, cap)
                cur = tuple(
                    (v[idx], None if valid is None else valid[idx])
                    for v, valid in cur)
            else:
                count = num_rows
            parts = None
            if partition is not None:
                from presto_tpu.ops.hashing import (
                    partition_of, row_hash, value_hash_triple,
                )

                channels, nparts = partition
                triples = []
                for ch in channels:
                    v, valid = cur[ch]
                    typ, d = out_meta[ch]
                    triples.append(value_hash_triple(
                        _ColView(v, valid, typ, d)))
                parts = partition_of(row_hash(triples), nparts)
            return cur, count, parts

        return jax.jit(kernel), list(out_meta)


class FusedSegmentOperatorFactory(OperatorFactory):
    parallel_safe = True

    def __init__(self, stages: Sequence, coalesce_rows: int = 0,
                 partition_spec=None, min_batch_capacity: int = 1024):
        self.stages = list(stages)
        self.coalesce_rows = coalesce_rows
        self.partition_spec = partition_spec
        self.min_batch_capacity = min_batch_capacity

    def create(self, ctx: OperatorContext) -> FusedSegmentOperator:
        return FusedSegmentOperator(ctx, self.stages, self.coalesce_rows,
                                    self.partition_spec,
                                    self.min_batch_capacity)

    def describe(self) -> str:
        """Human-readable stage summary (tools/fusion_report.py)."""
        parts = []
        for s in self.stages:
            if isinstance(s, FPStage):
                parts.append(
                    "fp(filter=%s, %d proj)" % (
                        "yes" if s.filter_expr is not None else "no",
                        len(s.projections)))
            else:
                parts.append("df(keys=%s)" % (list(s.key_channels),))
        extra = []
        if self.coalesce_rows:
            extra.append(f"coalesce={self.coalesce_rows}")
        if self.partition_spec:
            extra.append("partition=%dx%s" % (
                self.partition_spec[1], list(self.partition_spec[0])))
        tail = (" [" + ", ".join(extra) + "]") if extra else ""
        return "FusedSegment{" + " -> ".join(parts) + "}" + tail
