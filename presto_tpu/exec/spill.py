"""Spill tier: HBM -> host-RAM/disk, below the memory contexts.

The reference's revocable-memory protocol (MemoryRevokingScheduler
triggering Operator.startMemoryRevoke, presto-main/.../execution/
MemoryRevokingScheduler.java:46, Driver.java:478-488) lets accumulating
operators shed state to disk: FileSingleStreamSpiller streams serialized
pages to a temp file, GenericPartitioningSpiller hash-partitions rows
across spill files so each partition can be processed alone
(presto-main/.../spiller/, SURVEY §2.9).

Same architecture here, with the native LZ4 serde as the file format:

- ``FileSpiller``        — one append-only spill file of wire frames
- ``PartitioningSpiller``— K FileSpillers + the device hash kernel
                           routing each batch's rows to partitions

Operators spill when their accumulated bytes cross
``EngineConfig.spill_threshold_bytes`` (the self-triggered equivalent of
the revoking scheduler; a single-process engine needs no cross-thread
revoke rendezvous) and re-read partition-by-partition at finish, bounding
peak HBM by 1/K of the input (P10 in SURVEY §2.13).
"""

from __future__ import annotations

import os
import tempfile
import threading
from typing import Iterator, List, Optional, Sequence

import numpy as np

from presto_tpu.batch import Batch
from presto_tpu.serde import deserialize_batch, frame_size, serialize_batch

_counter = 0
_counter_lock = threading.Lock()


def _next_id() -> int:
    global _counter
    with _counter_lock:
        _counter += 1
        return _counter


class FileSpiller:
    """Append-only spill file of LZ4 wire frames
    (FileSingleStreamSpiller role)."""

    def __init__(self, spill_dir: str, tag: str = "spill"):
        os.makedirs(spill_dir, exist_ok=True)
        fd, self.path = tempfile.mkstemp(
            prefix=f"{tag}-{_next_id()}-", suffix=".bin", dir=spill_dir)
        self._file = os.fdopen(fd, "wb")
        self.bytes_written = 0
        self.rows_written = 0
        self._closed = False

    def spill(self, batch: Batch) -> None:
        frame = serialize_batch(batch)
        self._file.write(frame)
        self.bytes_written += len(frame)
        self.rows_written += batch.num_rows

    def read_all(self) -> Iterator[Batch]:
        """Finish writing and stream the spilled batches back."""
        if not self._closed:
            self._file.flush()
            self._file.close()
            self._closed = True
        if self.bytes_written == 0:
            return
        with open(self.path, "rb") as f:
            data = f.read()
        off = 0
        while off < len(data):
            size = frame_size(data, off)
            yield deserialize_batch(data[off:off + size])
            off += size

    def close(self) -> None:
        if not self._closed:
            self._file.close()
            self._closed = True
        try:
            os.unlink(self.path)
        except OSError:
            pass


class PartitioningSpiller:
    """Hash-partitioned spill (GenericPartitioningSpiller role): rows are
    routed by the device hash of ``channels`` so that any one partition
    contains complete key groups."""

    def __init__(self, spill_dir: str, n_partitions: int,
                 channels: Sequence[int], tag: str = "pspill"):
        self.n = n_partitions
        self.channels = list(channels)
        self.spillers = [FileSpiller(spill_dir, f"{tag}-p{i}")
                         for i in range(n_partitions)]

    def spill(self, batch: Batch) -> None:
        import jax.numpy as jnp

        from presto_tpu.ops.hashing import (
            partition_of, row_hash, value_hash_triple,
        )

        batch = batch.compact()
        key_cols = [value_hash_triple(batch.columns[c])
                    for c in self.channels]
        parts = np.asarray(partition_of(row_hash(key_cols), self.n))
        for p in range(self.n):
            idx = np.nonzero(parts == p)[0]
            if idx.size:
                self.spillers[p].spill(batch.take(jnp.asarray(idx)))

    def partition(self, i: int) -> Iterator[Batch]:
        return self.spillers[i].read_all()

    @property
    def bytes_written(self) -> int:
        return sum(s.bytes_written for s in self.spillers)

    def close(self) -> None:
        for s in self.spillers:
            s.close()
