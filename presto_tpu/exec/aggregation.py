"""Aggregation operators.

Reference models: HashAggregationOperator.java:48 (grouped; partial/final
Step) and AggregationOperator.java:35 (global).  The TPU version
materializes its input (as the reference's builders do), then runs the
sort-based grouped_aggregate kernel once, retrying at the next capacity
bucket when ``num_groups`` overflows — the device-side answer to
GroupByHash's rehash-with-memory-reservation (MultiChannelGroupByHash.java:87).
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence

from presto_tpu import types as T
from presto_tpu.batch import Batch, Column, next_bucket
from presto_tpu.exec.context import OperatorContext
from presto_tpu.exec.operator import Operator, OperatorFactory, device_concat


@dataclasses.dataclass(frozen=True)
class AggChannel:
    """One primitive reduction: prim in {'sum','count','min','max'},
    over input channel ``channel`` (None == count(*))."""

    prim: str
    channel: Optional[int]
    out_type: T.Type


# merge primitive per partial-state component (the Step.FINAL half of
# HashAggregationOperator.Step:61 for the device prims): re-aggregating a
# pre-reduced partial state with these yields the same answer as
# aggregating the raw rows.  Shared by the fusion pass (exec/fusion.py)
# when it pushes the partial accumulate into a scan segment.
MERGE_PRIM = {"count": "sum", "sum": "sum", "min": "min", "max": "max"}


def _apply_post_projections(batch: Batch, stages) -> Batch:
    """Apply absorbed finalize projections to an aggregation's output
    (exec/fusion.py folds the filter-less post-aggregation FilterProject
    run into the aggregation finish).  ``stages`` is a list of
    projection lists, applied in order.  Aggregation outputs are
    group-sized, so vectorized host evaluation costs less than one more
    device program launch per stage."""
    import numpy as np

    from presto_tpu.expr.compile import (
        ExprCompiler, batch_pairs, result_column,
    )

    batch = batch.compact().to_numpy()
    for projections in stages:
        compiler = ExprCompiler({i: c.dictionary
                                 for i, c in enumerate(batch.columns)
                                 if c.dictionary is not None})
        cprojs = [compiler.compile(p) for p in projections]
        pairs = batch_pairs(batch)
        n = batch.num_rows
        cols = tuple(result_column(p, *p.run(pairs, n, np))
                     for p in cprojs)
        batch = Batch(cols, n)
    return batch


def _minmax_dict_input(a: "AggChannel", col):
    """min/max over a dictionary column reduce *lexicographic ranks* (codes
    are interning order, not sort order); the returned postprocess maps the
    winning rank back to a code and reattaches the dictionary."""
    import jax.numpy as jnp
    import numpy as np

    if a.prim not in ("min", "max") or col.dictionary is None:
        return col.values, None
    ranks = col.dictionary.sort_ranks()          # code -> rank
    order = np.argsort(ranks).astype(col.values.dtype)  # rank -> code
    vals = jnp.asarray(ranks)[col.values]
    dictionary = col.dictionary

    def post(agg_ranks):
        codes = jnp.asarray(order)[jnp.clip(agg_ranks, 0, len(order) - 1)]
        return codes, dictionary

    return vals, post


_HOST_PRIMS = ("collect", "collect_merge", "hll", "hll_merge",
               "kll", "kll_merge")


def _has_collect(aggs: Sequence[AggChannel]) -> bool:
    return any(a.prim in _HOST_PRIMS for a in aggs)


def host_aggregate(batches: List[Batch], group_channels: Sequence[int],
                   aggs: Sequence[AggChannel],
                   global_row: bool) -> Optional[Batch]:
    """Host-side aggregation used when a collect-style aggregate
    (array_agg/map_agg/min_by, AccumulatorCompiler's object-state
    accumulators in the reference) is present: device reductions cannot
    produce variable-length results.

    At the FINAL distributed step, collect inputs are the partial step's
    arrays and are flattened (the @CombineFunction merge role).
    """
    import numpy as np

    from presto_tpu.batch import (
        Batch, Column, column_from_pylist, concat_batches,
    )

    live = [b.compact().to_numpy() for b in batches if b.num_rows > 0]
    if not live:
        if not global_row:
            return None
        rows: List[tuple] = []
        data = None
        n = 0
    else:
        data = concat_batches(live) if len(live) > 1 else live[0]
        n = data.num_rows
    key_lists = [data.columns[c].to_pylist(n) for c in group_channels] \
        if data is not None else [[] for _ in group_channels]
    group_ids: dict = {}
    order: List[tuple] = []
    gids = np.zeros(n, np.int64)
    for i in range(n):
        k = tuple(kl[i] for kl in key_lists)
        gid = group_ids.get(k)
        if gid is None:
            gid = group_ids[k] = len(order)
            order.append(k)
        gids[i] = gid
    if global_row and not order:
        order.append(())
    ng = len(order)
    cols: List[Column] = []
    for j, c in enumerate(group_channels):
        src = None if data is None else data.columns[c]
        vals = [k[j] for k in order]
        cols.append(column_from_pylist(src.type, vals))
    for a in aggs:
        in_list = None
        if a.channel is not None and data is not None:
            in_list = data.columns[a.channel].to_pylist(n)
        if a.prim == "count":
            out = [0] * ng
            for i in range(n):
                if in_list is None or in_list[i] is not None:
                    out[int(gids[i])] += 1
            cols.append(column_from_pylist(a.out_type, out))
            continue
        if a.prim in ("collect", "collect_merge"):
            # the FINAL step's inputs are the partial step's arrays; the
            # prim says which step this is (type equality is ambiguous,
            # e.g. array_agg over varbinary-typed inputs)
            flatten = a.prim == "collect_merge"
            acc: List[Optional[list]] = [[] for _ in range(ng)]
            for i in range(n):
                v = in_list[i]
                if flatten:
                    if v is not None:
                        acc[int(gids[i])].extend(v)
                else:
                    acc[int(gids[i])].append(v)
            if n == 0 and global_row:
                acc = [None]       # array_agg over no rows is NULL
            cols.append(column_from_pylist(a.out_type, acc))
            continue
        if a.prim in ("hll", "hll_merge"):
            from presto_tpu.sketch import HyperLogLog

            merge = a.prim == "hll_merge"
            sketches = [HyperLogLog() for _ in range(ng)]
            for i in range(n):
                v = in_list[i]
                if v is None:
                    continue
                g = int(gids[i])
                if merge:
                    sketches[g].merge(HyperLogLog.deserialize(v))
                else:
                    sketches[g].add_value(v)
            cols.append(column_from_pylist(
                a.out_type, [s.serialize() for s in sketches]))
            continue
        if a.prim in ("kll", "kll_merge"):
            from presto_tpu.sketch import KllSketch

            merge = a.prim == "kll_merge"
            qsketches = [KllSketch() for _ in range(ng)]
            for i in range(n):
                v = in_list[i]
                if v is None:
                    continue
                g = int(gids[i])
                if merge:
                    qsketches[g].merge(KllSketch.deserialize(v))
                else:
                    qsketches[g].add_value(v)
            cols.append(column_from_pylist(
                a.out_type, [s.serialize() for s in qsketches]))
            continue
        # sum / min / max over non-null values
        out2: List[Optional[object]] = [None] * ng
        for i in range(n):
            v = in_list[i] if in_list is not None else None
            if v is None:
                continue
            g = int(gids[i])
            cur = out2[g]
            if cur is None:
                out2[g] = v
            elif a.prim == "sum":
                out2[g] = cur + v
            elif a.prim == "min":
                out2[g] = min(cur, v)
            elif a.prim == "max":
                out2[g] = max(cur, v)
        cols.append(column_from_pylist(a.out_type, out2))
    return Batch(tuple(cols), ng)


class HashAggregationOperator(Operator):
    def __init__(self, ctx: OperatorContext, group_channels: Sequence[int],
                 aggs: Sequence[AggChannel], input_types: Sequence[T.Type],
                 post_projections=None):
        super().__init__(ctx)
        self.group_channels = list(group_channels)
        self.aggs = list(aggs)
        self.input_types = list(input_types)
        self.post_projections = (list(post_projections)
                                 if post_projections else None)
        self._batches: List[Batch] = []
        self._outputs: List[Batch] = []
        self._done = False
        self._spiller = None
        self._accumulated_bytes = 0
        self._accumulated_rows = 0
        # device-resident GroupByHash tier (ops/hashtable.py): state
        # arrays live on device ACROSS batches; None until the first
        # batch decides eligibility, False when ineligible
        self._hash_decided = False
        self._hash_state = None
        self._hash_cap = 0
        self._hash_groups = 0
        self._hash_key_meta = None   # [(type, dictionary)] per key col
        # partial-state batches carried over an overflow-to-sort
        # fallback (merge-prim re-aggregated at finish, exactly once)
        self._carried: List[Batch] = []

    def add_input(self, batch: Batch) -> None:
        self.ctx.stats.input_batches += 1
        self.ctx.stats.input_rows += batch.num_rows
        if self._hash_state is not None:
            if self._hash_accumulate(batch):
                return
            # table hit the rehash ceiling: state was extracted into
            # self._carried; THIS batch falls through to the sort tier
        elif (self._spiller is None and not self._hash_decided
                and self._accumulated_rows + batch.num_rows
                >= getattr(self.ctx.config, "hash_groupby_min_rows", 0)):
            # the engagement threshold crossed: small inputs never pay
            # the claim-loop's fixed round costs (one sort at finish is
            # cheaper), large ones drain what accumulated so far into
            # resident state and stream from here with bounded memory
            self._hash_decided = True
            if self._hash_eligible(batch):
                self._hash_begin(batch)
                pending, self._batches = self._batches, []
                self._accumulated_bytes = 0
                self._accumulated_rows = 0
                self.ctx.memory.free()
                for b in pending + [batch]:
                    if self._hash_state is None \
                            or not self._hash_accumulate(b):
                        self._append_sort_tier(b)
                return
        self._append_sort_tier(batch)

    def _append_sort_tier(self, batch: Batch) -> None:
        self._batches.append(batch)
        self.ctx.memory.reserve(batch.size_bytes)
        self._accumulated_bytes += batch.size_bytes
        self._accumulated_rows += batch.num_rows
        cfg = self.ctx.config
        if (cfg.spill_enabled and self.group_channels
                and self._accumulated_bytes > cfg.spill_threshold_bytes):
            self._spill_accumulated()

    # -- device-resident hash tier ---------------------------------------
    def _hash_eligible(self, batch: Batch) -> bool:
        """First-batch decision for the resident GroupByHash tier: device
        prims only, no min/max over dictionary inputs (their resident
        state would be interning codes), keys not already served by the
        bounded-domain direct path (which is faster where it applies),
        and grouping actually present."""
        cfg = self.ctx.config
        if not getattr(cfg, "hash_groupby_enabled", False):
            return False
        if not self.group_channels or _has_collect(self.aggs):
            return False
        for a in self.aggs:
            if a.prim not in ("sum", "count", "min", "max"):
                return False
            if (a.prim in ("min", "max") and a.channel is not None
                    and batch.columns[a.channel].dictionary is not None):
                return False
        if self._direct_domains(batch) is not None:
            return False
        return True

    def _agg_acc_dtype(self, a: AggChannel, batch: Batch):
        import numpy as np

        if a.channel is None or a.prim == "count":
            return None
        return np.asarray(batch.columns[a.channel].values).dtype

    def _hash_begin(self, batch: Batch) -> None:
        from presto_tpu.ops.hashtable import groupby_init

        cfg = self.ctx.config
        cap = int(getattr(cfg, "hash_groupby_init_slots", 1 << 13))
        key_cols = [batch.columns[c] for c in self.group_channels]
        # every key column is declared nullable in the resident state:
        # validity presence may differ batch-to-batch (an all-valid
        # batch arrives with valid=None) and the table's word layout
        # must stay fixed
        import numpy as np

        key_dtypes = [np.asarray(c.values).dtype for c in key_cols]
        self._hash_key_meta = [(c.type, c.dictionary) for c in key_cols]
        agg_specs = [(a.prim, self._agg_acc_dtype(a, batch))
                     for a in self.aggs]
        self._hash_state = groupby_init(
            cap, 2 * len(key_cols), key_dtypes,
            [True] * len(key_cols), agg_specs)
        self._hash_cap = cap
        self.ctx.stats.kernel_tier = "hash"

    def _hash_inputs(self, batch: Batch):
        import jax.numpy as jnp

        key_cols = [(batch.columns[c].values, batch.columns[c].valid,
                     batch.columns[c].type) for c in self.group_channels]
        agg_ins = []
        for a in self.aggs:
            if a.channel is None:
                agg_ins.append(("count", None, None))
            else:
                col = batch.columns[a.channel]
                agg_ins.append((a.prim, col.values, col.valid))
        return key_cols, agg_ins, jnp.asarray(batch.num_rows)

    def _hash_accumulate(self, batch: Batch) -> bool:
        """Fold one batch into resident state; returns False when the
        rehash ladder hit its ceiling (state carried, caller falls back
        to the sort tier for this and later batches)."""
        from presto_tpu.ops.groupby import (
            hash_groupby_rehash_jit, hash_groupby_update_jit,
        )

        cfg = self.ctx.config
        max_slots = int(getattr(cfg, "hash_groupby_max_slots", 1 << 22))
        batch = batch.to_device()
        key_cols, agg_ins, n = self._hash_inputs(batch)
        while True:
            state2, ng, ok = hash_groupby_update_jit(
                self._hash_state, key_cols, agg_ins, n)
            self.ctx.stats.jit_dispatches += 1
            if bool(ok):
                self._hash_state = state2
                self._hash_groups = int(ng)
                # proactive rehash past 1/2 fill keeps probe chains
                # short for the NEXT batch (the rehash() trigger of
                # MultiChannelGroupByHash.java:286)
                if (self._hash_groups * 2 > self._hash_cap
                        and self._hash_cap * 2 <= max_slots):
                    self._hash_state, _ = hash_groupby_rehash_jit(
                        self._hash_state, self._hash_cap * 2,
                        [a.prim for a in self.aggs])
                    self._hash_cap *= 2
                    self.ctx.stats.jit_dispatches += 1
                return True
            # placement failed (table effectively full); nothing was
            # accumulated, so rehash-and-retry is exactly-once
            if self._hash_cap * 2 > max_slots:
                self._hash_overflow_to_sort()
                return False
            self._hash_state, _ = hash_groupby_rehash_jit(
                self._hash_state, self._hash_cap * 2,
                [a.prim for a in self.aggs])
            self._hash_cap *= 2
            self.ctx.stats.jit_dispatches += 1

    def _hash_overflow_to_sort(self) -> None:
        """The overflow rung of the ladder: snapshot the accumulated
        on-device state as a partial-state batch (keys + per-agg value
        columns, valid iff the group saw a non-null input) and drop to
        the sort tier.  The finish-time merge re-aggregates the carried
        partials with merge prims, so no group is dropped or counted
        twice however the input straddled the fallback seam."""
        out = self._hash_extract_batch()
        if out is not None and out.num_rows > 0:
            self._carried.append(out)
        self._hash_state = None
        self._hash_cap = 0
        self.ctx.stats.kernel_tier = "hash+sort"

    def _hash_extract_batch(self) -> Optional[Batch]:
        import numpy as np

        from presto_tpu.ops.hashtable import groupby_extract

        if self._hash_state is None:
            return None
        n, key_outs, agg_outs = groupby_extract(self._hash_state)
        n = int(n)
        if n == 0:
            return None
        cols = []
        for (vals, valid), (typ, dictionary) in zip(key_outs,
                                                    self._hash_key_meta):
            cols.append(Column(typ, vals, valid, dictionary))
        for a, (acc, cnt) in zip(self.aggs, agg_outs):
            if a.prim == "count":
                cols.append(Column(a.out_type, acc.astype("int64")))
            else:
                cols.append(Column(a.out_type,
                                   acc.astype(a.out_type.np_dtype),
                                   cnt > 0))
        self.ctx.stats.jit_dispatches += 1
        return Batch(tuple(cols), n)

    def _merge_partials(self, parts: List[Batch]) -> Optional[Batch]:
        """Merge-prim re-aggregation of partial-state batches (keys +
        one state column per aggregation) — the Step.FINAL half of the
        overflow seam.  Exact: each input row entered exactly one
        partial."""
        k = len(self.group_channels)
        merge_aggs = [AggChannel(MERGE_PRIM[a.prim], k + i, a.out_type)
                      for i, a in enumerate(self.aggs)]
        types = ([self.input_types[c] for c in self.group_channels]
                 + [a.out_type for a in self.aggs])
        mctx = OperatorContext(self.ctx.task, f"{self.ctx.name}.merge")
        sub = HashAggregationOperator(
            mctx, list(range(k)), merge_aggs, types)
        sub._hash_decided = True     # merge runs on the sort tier
        return sub._compute_batches(parts)

    def _spill_accumulated(self) -> None:
        """Revoke: hash-partition accumulated rows to the spill tier
        (SpillableHashAggregationBuilder role); each group lands wholly in
        one partition, so finish aggregates partition-by-partition."""
        from presto_tpu.exec.spill import PartitioningSpiller

        cfg = self.ctx.config
        if self._spiller is None:
            self._spiller = PartitioningSpiller(
                cfg.spill_path, cfg.spill_partitions, self.group_channels,
                tag=f"agg-{self.ctx.name}")
        for b in self._batches:
            self._spiller.spill(b.to_numpy())
        self._batches = []
        self._accumulated_bytes = 0
        self.ctx.memory.free()

    def finish(self) -> None:
        if self._finishing:
            return
        super().finish()
        outs: List[Batch] = []
        if self._hash_state is not None:
            # the steady state of the resident tier: groups come
            # straight off the device table, no materialized input
            out = self._hash_extract_batch()
            if out is not None:
                outs.append(out)
            self._hash_state = None
        elif self._spiller is not None:
            self._spill_accumulated()
            for p in range(self.ctx.config.spill_partitions):
                part = list(self._spiller.partition(p))
                if not part:
                    continue
                out = self._compute_batches(part)
                if out is not None:
                    outs.append(out)
            self._spiller.close()
            self._spiller = None
        else:
            out = self._compute_batches(self._batches)
            if out is not None:
                outs.append(out)
        if self._carried:
            # overflow seam: merge the carried on-device state with the
            # sort-tier results so every group lands exactly once
            merged = self._merge_partials(self._carried + outs)
            outs = [merged] if merged is not None else []
            self._carried = []
        self._outputs.extend(outs)
        self._batches = []
        self.ctx.memory.free()

    def _direct_domains(self, data: Batch) -> Optional[List[int]]:
        """Per-key domain sizes when every key column is bounded (dictionary
        codes / booleans) and the packed domain is small; else None."""
        doms = []
        for c in self.group_channels:
            col = data.columns[c]
            if col.dictionary is not None:
                doms.append(len(col.dictionary))
            elif col.type.name == "boolean":
                doms.append(2)
            else:
                return None
        total = 1
        for d, c in zip(doms, self.group_channels):
            total *= d + (1 if data.columns[c].valid is not None else 0)
        if not doms or total > self.ctx.config.direct_groupby_max_domain:
            return None
        return doms

    def _compute_direct(self, data: Batch, doms: List[int]) -> Batch:
        """Gather-free fast path (see ops.groupby.direct_grouped_aggregate)."""
        import jax.numpy as jnp

        from presto_tpu.ops.groupby import (
            decode_direct_keys, direct_grouped_aggregate,
        )

        key_cols = [data.columns[c] for c in self.group_channels]
        key_codes = [(c.values, c.valid) for c in key_cols]
        agg_ins = []
        posts = []
        for a in self.aggs:
            if a.channel is None:
                agg_ins.append(("count", None, None))  # count(*): no values
                posts.append(None)
            else:
                col = data.columns[a.channel]
                vals, post = _minmax_dict_input(a, col)
                agg_ins.append((a.prim, vals, col.valid))
                posts.append(post)
        n = jnp.asarray(data.num_rows)
        present, results = direct_grouped_aggregate(
            key_codes, doms, agg_ins, n)
        domain = present.shape[0]
        slots = jnp.nonzero(present, size=domain, fill_value=0)[0]
        num_groups = int(present.sum())
        decoded = decode_direct_keys(
            slots, [c.valid is not None for c in key_cols], doms)
        cols = []
        for src, (codes, valid) in zip(key_cols, decoded):
            cols.append(Column(src.type, codes.astype(src.values.dtype),
                               valid, src.dictionary))
        for a, post, (values, cnt) in zip(self.aggs, posts, results):
            if a.prim == "count":
                cols.append(Column(a.out_type, values[slots].astype("int64")))
            else:
                vals = values[slots]
                if post is not None:
                    vals, dictionary = post(vals)
                else:
                    dictionary = None
                cols.append(Column(a.out_type,
                                   vals.astype(a.out_type.np_dtype),
                                   cnt[slots] > 0, dictionary))
        self.ctx.stats.output_rows += num_groups
        return Batch(tuple(cols), num_groups)

    def _compute_batches(self, batches: List[Batch]) -> Optional[Batch]:
        import jax
        import jax.numpy as jnp

        from presto_tpu.ops.groupby import grouped_aggregate_jit

        if _has_collect(self.aggs):
            out = host_aggregate(batches, self.group_channels, self.aggs,
                                 global_row=False)
            if out is not None:
                self.ctx.stats.output_rows += out.num_rows
            return out

        data = device_concat(batches, self.ctx.config.min_batch_capacity)
        if data is None:
            return None  # grouped aggregation of zero rows -> zero rows
        doms = self._direct_domains(data)
        if doms is not None:
            self.ctx.stats.kernel_tier = \
                self.ctx.stats.kernel_tier or "direct"
            return self._compute_direct(data, doms)
        self.ctx.stats.kernel_tier = self.ctx.stats.kernel_tier or "sort"
        key_cols = [(data.columns[c].values, data.columns[c].valid,
                     data.columns[c].type) for c in self.group_channels]
        agg_ins = []
        posts = []
        for a in self.aggs:
            if a.channel is None:
                col = data.columns[0]
                agg_ins.append(("count", jnp.zeros_like(
                    col.values, shape=(data.capacity,)), None))
                posts.append(None)
            else:
                col = data.columns[a.channel]
                vals, post = _minmax_dict_input(a, col)
                agg_ins.append((a.prim, vals, col.valid))
                posts.append(post)
        n = jnp.asarray(data.num_rows)
        group_cap = next_bucket(1, min(max(data.num_rows, 1), 1 << 16))
        while True:
            gi, ng, results = grouped_aggregate_jit(key_cols, agg_ins, n,
                                                    group_cap)
            num_groups = int(ng)
            if num_groups <= group_cap:
                break
            group_cap = next_bucket(num_groups)
        cols = []
        for c in self.group_channels:
            src = data.columns[c]
            values = src.values[gi]
            valid = None if src.valid is None else src.valid[gi]
            cols.append(Column(src.type, values, valid, src.dictionary))
        for a, post, (values, cnt) in zip(self.aggs, posts, results):
            if a.prim == "count":
                cols.append(Column(a.out_type, values.astype("int64")))
            else:
                if post is not None:
                    values, dictionary = post(values)
                else:
                    dictionary = None
                cols.append(Column(a.out_type,
                                   values.astype(a.out_type.np_dtype),
                                   cnt > 0, dictionary))
        out = Batch(tuple(cols), num_groups)
        self.ctx.stats.output_rows += num_groups
        return out

    def get_output(self) -> Optional[Batch]:
        if not self._outputs:
            return None
        self._done = True
        out = self._outputs.pop(0)
        if self.post_projections is not None and out.num_rows:
            out = _apply_post_projections(out, self.post_projections)
        return out

    def is_finished(self) -> bool:
        return self._finishing and not self._outputs


class HashAggregationOperatorFactory(OperatorFactory):
    def __init__(self, group_channels, aggs, input_types,
                 post_projections=None):
        self.group_channels = list(group_channels)
        self.aggs = list(aggs)
        self.input_types = list(input_types)
        # absorbed filter-less finalize projection (exec/fusion.py)
        self.post_projections = post_projections
        # aggregation step this factory lowers ("single"/"partial"/
        # "final"), set by the physical planner for the fusion pass
        self.step = "single"

    def create(self, ctx: OperatorContext) -> HashAggregationOperator:
        return HashAggregationOperator(ctx, self.group_channels, self.aggs,
                                       self.input_types,
                                       post_projections=self.post_projections)


class GlobalAggregationOperator(Operator):
    """Ungrouped aggregation: exactly one output row, even on empty input."""

    def __init__(self, ctx: OperatorContext, aggs: Sequence[AggChannel],
                 input_types: Sequence[T.Type], post_projections=None):
        super().__init__(ctx)
        self.aggs = list(aggs)
        self.input_types = list(input_types)
        self.post_projections = (list(post_projections)
                                 if post_projections else None)
        self._batches: List[Batch] = []
        self._output: Optional[Batch] = None

    def add_input(self, batch: Batch) -> None:
        self._batches.append(batch)
        self.ctx.stats.input_rows += batch.num_rows

    def finish(self) -> None:
        if self._finishing:
            return
        super().finish()
        import jax.numpy as jnp
        import numpy as np

        from presto_tpu.ops.groupby import global_aggregate_jit

        if _has_collect(self.aggs):
            self._output = host_aggregate(self._batches, [], self.aggs,
                                          global_row=True)
            self._batches = []
            return

        data = device_concat(self._batches,
                             self.ctx.config.min_batch_capacity)
        self._batches = []
        cols = []
        if data is None:
            for a in self.aggs:
                if a.prim == "count":
                    cols.append(Column(a.out_type, np.zeros(1, np.int64)))
                else:
                    from presto_tpu.batch import Dictionary

                    dictionary = (Dictionary()
                                  if a.out_type.is_dictionary else None)
                    cols.append(Column(a.out_type,
                                       np.zeros(1, a.out_type.np_dtype),
                                       np.zeros(1, bool), dictionary))
            self._output = Batch(tuple(cols), 1)
            return
        agg_ins = []
        posts = []
        for a in self.aggs:
            if a.channel is None:
                agg_ins.append(("count", data.columns[0].values, None))
                posts.append(None)
            else:
                col = data.columns[a.channel]
                vals, post = _minmax_dict_input(a, col)
                agg_ins.append((a.prim, vals, col.valid))
                posts.append(post)
        results = global_aggregate_jit(agg_ins, jnp.asarray(data.num_rows))
        for a, post, (value, cnt) in zip(self.aggs, posts, results):
            if a.prim == "count":
                cols.append(Column(a.out_type,
                                   np.asarray([int(value)], np.int64)))
            else:
                nonempty = int(cnt) > 0
                dictionary = None
                if post is not None:
                    value, dictionary = post(jnp.asarray([value]))
                    value = np.asarray(value)[0]
                cols.append(Column(
                    a.out_type,
                    np.asarray([value], a.out_type.np_dtype),
                    None if nonempty else np.zeros(1, bool), dictionary))
        self._output = Batch(tuple(cols), 1)

    def get_output(self) -> Optional[Batch]:
        out, self._output = self._output, None
        if out is not None and self.post_projections is not None:
            out = _apply_post_projections(out, self.post_projections)
        return out

    def is_finished(self) -> bool:
        return self._finishing and self._output is None


class GlobalAggregationOperatorFactory(OperatorFactory):
    def __init__(self, aggs, input_types, post_projections=None):
        self.aggs = list(aggs)
        self.input_types = list(input_types)
        self.post_projections = post_projections
        self.step = "single"

    def create(self, ctx: OperatorContext) -> GlobalAggregationOperator:
        return GlobalAggregationOperator(
            ctx, self.aggs, self.input_types,
            post_projections=self.post_projections)
