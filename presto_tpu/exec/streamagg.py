"""Streaming aggregation over key-clustered input.

The reference's StreamingAggregationOperator
(presto-main/.../operator/StreamingAggregationOperator.java:38) exploits
input that is already sorted/clustered on the group keys: it holds ONE
open group instead of a hash table and emits each group the moment the
next key appears.  Same contract here, TPU-shaped: each batch runs the
sort-free ``clustered_aggregate`` kernel (run-boundary detection +
segment reductions — no lexsort, no rehash), all finished groups of the
batch are emitted together, and only the last (possibly still open)
group's partial state carries to the next batch, merged by the agg
primitive's combine rule.

Chosen by the physical planner when the group channels trace to a
prefix of the scan's declared sort order (Connector.sort_order — the
LocalProperties/StreamPropertyDerivations role).  The pipeline must not
be split into concurrent feed drivers (``requires_ordered_input``):
round-robin feeds would interleave key ranges.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import numpy as np

from presto_tpu import types as T
from presto_tpu.batch import Batch, Column, next_bucket
from presto_tpu.exec.aggregation import AggChannel
from presto_tpu.exec.context import OperatorContext
from presto_tpu.exec.operator import Operator, OperatorFactory


class StreamingAggregationOperator(Operator):
    def __init__(self, ctx: OperatorContext,
                 group_channels: Sequence[int],
                 aggs: Sequence[AggChannel],
                 input_types: Sequence[T.Type]):
        super().__init__(ctx)
        self.group_channels = list(group_channels)
        self.aggs = list(aggs)
        self.input_types = list(input_types)
        self._outputs: List[Batch] = []
        # carried open group: (key row values tuple-of-host-scalars,
        # per-agg (value, count) host scalars, key Columns of 1 row)
        self._carry: Optional[Tuple[tuple, List[Tuple[object, int]],
                                    List[Column]]] = None

    # -- kernel ---------------------------------------------------------
    def _aggregate_batch(self, batch: Batch):
        import jax.numpy as jnp

        from presto_tpu.ops.groupby import clustered_aggregate_jit

        data = batch
        key_cols = [data.columns[c] for c in self.group_channels]
        key_triples = [(c.values, c.valid, c.type) for c in key_cols]
        agg_ins = []
        for a in self.aggs:
            if a.channel is None:
                agg_ins.append(("count", jnp.zeros(data.capacity, jnp.int8),
                                None))
            else:
                col = data.columns[a.channel]
                agg_ins.append((a.prim, col.values, col.valid))
        cap = data.capacity
        group_cap = next_bucket(min(cap, max(data.num_rows, 1)),
                                minimum=16)
        gi, ng, results = clustered_aggregate_jit(
            key_triples, agg_ins, jnp.asarray(data.num_rows), group_cap)
        return key_cols, gi, int(ng), results, group_cap

    # -- carry merge (the combine rule per primitive) --------------------
    @staticmethod
    def _combine(prim: str, a, b, cnt_a: int, cnt_b: int):
        if cnt_a == 0:
            return b
        if cnt_b == 0:
            return a
        if prim in ("sum", "count"):
            return a + b
        if prim == "min":
            return min(a, b)
        if prim == "max":
            return max(a, b)
        raise ValueError(prim)

    def add_input(self, batch: Batch) -> None:
        self.ctx.stats.input_batches += 1
        self.ctx.stats.input_rows += batch.num_rows
        if batch.num_rows == 0:
            return
        (key_cols, gi, ng, results,
         group_cap) = self._aggregate_batch(batch)
        if ng == 0:
            return
        # host-materialize the per-group outputs (ng rows)
        gi_h = np.asarray(gi)[:ng]
        key_out = [c.take(gi_h).to_numpy() for c in key_cols]
        vals_h = []
        cnts_h = []
        for values, cnt in results:
            vals_h.append(np.asarray(values)[:ng])
            cnts_h.append(np.asarray(cnt)[:ng])
        first_key = tuple(k.to_pylist(ng)[0] for k in key_out)

        # merge the carried open group into this batch's FIRST group
        # when the key continues; otherwise flush the carry as its own
        # finished group
        flush_rows: List[Tuple[List[Column], List[Tuple[object, int]]]] = []
        if self._carry is not None:
            ckey, cstate, ckey_cols = self._carry
            if ckey == first_key:
                for i, a in enumerate(self.aggs):
                    merged = self._combine(
                        a.prim, cstate[i][0], vals_h[i][0].item(),
                        cstate[i][1], int(cnts_h[i][0]))
                    vals_h[i] = vals_h[i].copy()
                    vals_h[i][0] = merged
                    cnts_h[i] = cnts_h[i].copy()
                    cnts_h[i][0] = cstate[i][1] + int(cnts_h[i][0])
            else:
                flush_rows.append((ckey_cols, cstate))
            self._carry = None

        # carry the LAST group (still open until a new key or finish)
        last = ng - 1
        carry_key = tuple(k.to_pylist(ng)[last] for k in key_out)
        carry_state = [(vals_h[i][last].item(), int(cnts_h[i][last]))
                       for i in range(len(self.aggs))]
        carry_cols = [Column(c.type, c.values[last:last + 1],
                             None if c.valid is None
                             else c.valid[last:last + 1],
                             c.dictionary) for c in key_out]
        self._carry = (carry_key, carry_state, carry_cols)

        emit = ng - 1  # all but the open last group
        out_batches = []
        if flush_rows:
            out_batches.append(self._state_batch(*flush_rows[0]))
        if emit > 0:
            cols = [Column(c.type, c.values[:emit],
                           None if c.valid is None else c.valid[:emit],
                           c.dictionary) for c in key_out]
            for a, v, cnt in zip(self.aggs, vals_h, cnts_h):
                cols.append(self._agg_column(a, v[:emit], cnt[:emit]))
            out_batches.append(Batch(tuple(cols), emit))
        for b in out_batches:
            self.ctx.stats.output_batches += 1
            self.ctx.stats.output_rows += b.num_rows
            self._outputs.append(b)

    def _agg_column(self, a: AggChannel, vals: np.ndarray,
                    cnts: np.ndarray) -> Column:
        vals = vals.astype(a.out_type.np_dtype)
        if a.prim == "count":
            return Column(a.out_type, vals)
        valid = cnts > 0
        return Column(a.out_type, vals,
                      None if bool(valid.all()) else valid)

    def _state_batch(self, key_cols: List[Column],
                     state: List[Tuple[object, int]]) -> Batch:
        cols = list(key_cols)
        for a, (v, cnt) in zip(self.aggs, state):
            vals = np.asarray([v if v is not None else 0],
                              dtype=a.out_type.np_dtype)
            cols.append(Column(a.out_type, vals,
                               None if (cnt > 0 or a.prim == "count")
                               else np.asarray([False])))
        return Batch(tuple(cols), 1)

    def finish(self) -> None:
        if self._finishing:
            return
        super().finish()
        if self._carry is not None:
            ckey, cstate, ckey_cols = self._carry
            b = self._state_batch(ckey_cols, cstate)
            self.ctx.stats.output_batches += 1
            self.ctx.stats.output_rows += 1
            self._outputs.append(b)
            self._carry = None

    def get_output(self) -> Optional[Batch]:
        if self._outputs:
            return self._outputs.pop(0)
        return None

    def is_finished(self) -> bool:
        return self._finishing and not self._outputs


class StreamingAggregationOperatorFactory(OperatorFactory):
    # concurrent feed drivers would interleave key ranges and break the
    # clustering contract — the runner must keep this pipeline serial
    requires_ordered_input = True

    def __init__(self, group_channels: Sequence[int],
                 aggs: Sequence[AggChannel],
                 input_types: Sequence[T.Type]):
        for a in aggs:
            # the planner's eligibility check guarantees this; direct
            # construction must honor it too (the carry merge would
            # compare dictionary interning codes)
            assert not (a.prim in ("min", "max") and a.channel is not None
                        and input_types[a.channel].is_dictionary), \
                "min/max over dictionary columns is not streamable"
        self.group_channels = list(group_channels)
        self.aggs = list(aggs)
        self.input_types = list(input_types)

    def create(self, ctx: OperatorContext) -> StreamingAggregationOperator:
        return StreamingAggregationOperator(
            ctx, self.group_channels, self.aggs, self.input_types)
