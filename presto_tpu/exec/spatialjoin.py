"""Grid-indexed spatial join.

The reference's SpatialJoinOperator builds an R-tree over the build
side's geometries and probes it per row
(presto-main/.../operator/SpatialJoinOperator.java:42, PagesRTreeIndex);
candidate pairs then pass the exact ST_* predicate.  Same contract here
with a uniform GRID index (simpler, and equally effective for the
points-in-polygons workloads the operator serves): build geometries
hash their bounding boxes into grid cells sized by the average build
bbox, probes collect candidates from the cells their own (radius-
expanded) bbox overlaps, and only candidates run the exact geometry
predicate — the cross product never materializes.

Geometry evaluation is host-side by design (WKT strings live in
dictionaries, never in HBM), matching how the ST_* scalar functions
execute.
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from presto_tpu import types as T
from presto_tpu.batch import Batch, Column, concat_batches
from presto_tpu.exec.context import OperatorContext
from presto_tpu.exec.nestedloop import NestedLoopBuildOperatorFactory
from presto_tpu.exec.operator import Operator, OperatorFactory
from presto_tpu.expr.ir import RowExpression


def _geometries(batch: Batch, expr: RowExpression):
    """Evaluate a WKT expression host-side and parse each row."""
    from presto_tpu.expr.compile import evaluate
    from presto_tpu.expr.geo import parse_wkt

    col = evaluate(expr, batch.to_numpy())
    texts = Column(col.type, col.values, col.valid,
                   col.dictionary).to_pylist(batch.num_rows)
    out = []
    for t in texts:
        if t is None:
            out.append(None)
            continue
        try:
            g = parse_wkt(t)
            out.append(g if g.vertices() else None)
        except Exception:  # noqa: BLE001 - unparsable -> no match
            out.append(None)
    return out


class SpatialJoinOperator(Operator):
    def __init__(self, ctx: OperatorContext,
                 factory: "SpatialJoinOperatorFactory"):
        super().__init__(ctx)
        self.f = factory
        self._index: Optional[Dict[Tuple[int, int], List[int]]] = None
        self._build_geoms: List = []
        self._build_data: Optional[Batch] = None
        self._cell: float = 1.0
        self._out: List[Batch] = []

    # -- index build -----------------------------------------------------
    def _ensure_index(self) -> None:
        if self._index is not None:
            return
        data = self.f.build.data
        if data is None:
            raise RuntimeError("spatial build side not finished")
        data = data.compact().to_numpy()
        self._build_data = data
        self._build_geoms = _geometries(data, self.f.build_geom)
        boxes = [g.bbox() if g is not None else None
                 for g in self._build_geoms]
        live = [b for b in boxes if b is not None]
        spans = [max(b[2] - b[0], b[3] - b[1]) for b in live]
        # cell sizing: average build bbox span, floored by the distance
        # radius and the data extent / sqrt(n) — point-only builds have
        # zero spans and would otherwise yield astronomically many cells
        avg = sum(spans) / len(spans) if spans else 0.0
        extent = 0.0
        if live:
            extent = max(max(b[2] for b in live) - min(b[0] for b in live),
                         max(b[3] for b in live) - min(b[1] for b in live))
        grid_floor = extent / max(math.sqrt(len(live)), 1.0) \
            if live else 0.0
        self._cell = max(avg, self.f.radius or 0.0, grid_floor, 1e-9)
        if self._cell <= 1e-9:
            self._cell = 1.0   # all-degenerate build (identical points)
        index: Dict[Tuple[int, int], List[int]] = {}
        for i, b in enumerate(boxes):
            if b is None:
                continue
            for cx in range(int(math.floor(b[0] / self._cell)),
                            int(math.floor(b[2] / self._cell)) + 1):
                for cy in range(int(math.floor(b[1] / self._cell)),
                                int(math.floor(b[3] / self._cell)) + 1):
                    index.setdefault((cx, cy), []).append(i)
        self._index = index

    # -- probe ----------------------------------------------------------
    def add_input(self, batch: Batch) -> None:
        from presto_tpu.expr.geo import (
            contains_geoms, distance_geoms, intersects_geoms,
        )

        self.ctx.stats.input_rows += batch.num_rows
        self._ensure_index()
        if batch.num_rows == 0 or not self._build_geoms:
            return
        batch = batch.compact().to_numpy()
        probe_geoms = _geometries(batch, self.f.probe_geom)
        radius = self.f.radius or 0.0
        pairs_p: List[int] = []
        pairs_b: List[int] = []
        for pi, pg in enumerate(probe_geoms):
            if pg is None:
                continue
            x0, y0, x1, y1 = pg.bbox()
            x0 -= radius
            y0 -= radius
            x1 += radius
            y1 += radius
            cx0 = int(math.floor(x0 / self._cell))
            cx1 = int(math.floor(x1 / self._cell)) + 1
            cy0 = int(math.floor(y0 / self._cell))
            cy1 = int(math.floor(y1 / self._cell)) + 1
            if (cx1 - cx0) * (cy1 - cy0) > 1 << 14:
                # probe bbox spans most of the grid: scanning the whole
                # build side beats enumerating cells
                cells = [(None, None)]
            else:
                cells = [(cx, cy) for cx in range(cx0, cx1)
                         for cy in range(cy0, cy1)]
            seen = set()
            for cell in cells:
                cands = (range(len(self._build_geoms))
                         if cell == (None, None)
                         else self._index.get(cell, ()))
                for bi in cands:
                    if bi in seen:
                        continue
                    seen.add(bi)
                    bg = self._build_geoms[bi]
                    if self.f.kind == "contains":
                        ok = contains_geoms(bg, pg)
                    elif self.f.kind == "within":
                        # probe side is the container
                        ok = contains_geoms(pg, bg)
                    elif self.f.kind == "intersects":
                        ok = intersects_geoms(bg, pg)
                    else:  # distance
                        d = distance_geoms(bg, pg)
                        ok = d is not None and (
                            d < self.f.radius if self.f.strict
                            else d <= self.f.radius)
                    if ok:
                        pairs_p.append(pi)
                        pairs_b.append(bi)
        if not pairs_p:
            return
        pidx = np.asarray(pairs_p)
        bidx = np.asarray(pairs_b)
        probe_out = batch.take(pidx)
        build_out = self._build_data.take(bidx)
        out = Batch(tuple(probe_out.columns) + tuple(build_out.columns),
                    len(pairs_p))
        self.ctx.stats.output_rows += out.num_rows
        self._out.append(out)

    def get_output(self) -> Optional[Batch]:
        if self._out:
            return self._out.pop(0)
        return None

    def is_finished(self) -> bool:
        return self._finishing and not self._out


class SpatialJoinOperatorFactory(OperatorFactory):
    def __init__(self, build: NestedLoopBuildOperatorFactory,
                 build_geom: RowExpression, probe_geom: RowExpression,
                 kind: str, radius: Optional[float] = None,
                 strict: bool = False):
        assert kind in ("contains", "within", "intersects",
                        "distance")
        self.build = build
        self.build_geom = build_geom   # over BUILD-side channels
        self.probe_geom = probe_geom   # over PROBE-side channels
        self.kind = kind
        self.radius = radius
        self.strict = strict           # ST_Distance < r (vs <= r)

    def create(self, ctx: OperatorContext) -> SpatialJoinOperator:
        return SpatialJoinOperator(ctx, self)
