"""Execution contexts: memory accounting + operator statistics.

Mirrors the reference's context tree — QueryContext -> TaskContext ->
PipelineContext -> DriverContext -> OperatorContext
(presto-main/.../memory/QueryContext.java, operator/OperatorContext.java) —
and its hierarchical memory contexts (presto-memory-context, SURVEY §2.2):
reservations roll up to the query root, which enforces a limit.

Stats mirror OperatorStats -> ...  -> QueryStats rollups (SURVEY §5.1): the
Driver records per-operator wall time and row/batch counts around every
get_output/add_input call, which is what EXPLAIN ANALYZE renders.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Dict, List, Optional

from presto_tpu.config import DEFAULT, EngineConfig


class MemoryReservationError(RuntimeError):
    pass


class MemoryContext:
    """One node in the reservation tree (LocalMemoryContext analogue)."""

    def __init__(self, parent: Optional["MemoryContext"], name: str,
                 limit: Optional[int] = None):
        self.parent = parent
        self.name = name
        self.limit = limit
        self.reserved = 0
        self.peak = 0
        self._tree_lock = (parent._tree_lock if parent is not None
                           else threading.Lock())

    def reserve(self, bytes_: int) -> None:
        self.set_bytes(self.reserved + bytes_)

    def set_bytes(self, bytes_: int) -> None:
        # one lock per reservation TREE (root-owned): concurrent feed
        # drivers of one task serialize, unrelated queries do not
        with self._tree_lock:
            self._set_bytes_locked(bytes_)

    def _set_bytes_locked(self, bytes_: int) -> None:
        delta = bytes_ - self.reserved
        node = self
        while node is not None:
            new = node.reserved + delta
            if delta > 0 and node.limit is not None and new > node.limit:
                raise MemoryReservationError(
                    f"memory limit exceeded at {node.name}: "
                    f"{new} > {node.limit}")
            node = node.parent
        node = self
        while node is not None:
            node.reserved += delta
            node.peak = max(node.peak, node.reserved)
            node = node.parent

    def free(self) -> None:
        self.set_bytes(0)


@dataclasses.dataclass
class OperatorStats:
    operator: str = ""
    input_batches: int = 0
    input_rows: int = 0
    output_batches: int = 0
    output_rows: int = 0
    wall_ns: int = 0
    finish_wall_ns: int = 0
    # row-pipeline-tier device program accounting (FilterProject,
    # DynamicFilter, FusedSegment): one dispatch per jitted-program
    # launch, one compile per kernel-cache miss that built a program.
    # Tests assert pipeline fusion's launch-count reduction on these
    # instead of eyeballing traces.
    jit_dispatches: int = 0
    jit_compiles: int = 0
    # rows folded into in-segment partial-aggregation pre-reduce
    # (exec/fusion.py Fusion II): nonzero proves the scan->agg pipeline
    # emitted partial states, not row batches — tests pin on this
    # instead of eyeballing operator chains.
    prereduce_rows: int = 0

    def as_dict(self) -> Dict:
        return dataclasses.asdict(self)


class QueryContext:
    def __init__(self, config: EngineConfig = DEFAULT,
                 memory_limit: Optional[int] = None):
        self.config = config
        self.memory = MemoryContext(None, "query", limit=memory_limit)
        self.start_time = time.time()


class TaskContext:
    def __init__(self, query: QueryContext, task_id: str = "task-0"):
        self.query = query
        self.task_id = task_id
        self.config = query.config
        self.memory = MemoryContext(query.memory, f"task:{task_id}")
        self.operator_stats: List[OperatorStats] = []
        self._cleanups: List = []

    def jit_counters(self) -> Dict[str, int]:
        """Task-level rollup of row-pipeline jit dispatch/compile counts
        (the launch-count surface the fusion tests pin)."""
        return {
            "dispatches": sum(s.jit_dispatches for s in self.operator_stats),
            "compiles": sum(s.jit_compiles for s in self.operator_stats),
            "prereduce_rows": sum(s.prereduce_rows
                                  for s in self.operator_stats),
        }

    def register_cleanup(self, fn) -> None:
        """Register an idempotent resource-release callback to run at task
        teardown (the SqlTask cleanup role): a backstop for reservations
        normally released by a downstream pipeline that may never run."""
        self._cleanups.append(fn)

    def close(self) -> None:
        cleanups, self._cleanups = self._cleanups, []
        for fn in cleanups:
            try:
                fn()
            except Exception:  # noqa: BLE001 - teardown is best-effort
                pass


class OperatorContext:
    def __init__(self, task: TaskContext, name: str):
        self.task = task
        self.config = task.config
        self.name = name
        self.memory = MemoryContext(task.memory, f"op:{name}")
        self.stats = OperatorStats(operator=name)
        task.operator_stats.append(self.stats)
