"""Execution contexts: memory accounting + operator statistics.

Mirrors the reference's context tree — QueryContext -> TaskContext ->
PipelineContext -> DriverContext -> OperatorContext
(presto-main/.../memory/QueryContext.java, operator/OperatorContext.java) —
and its hierarchical memory contexts (presto-memory-context, SURVEY §2.2):
reservations roll up to the query root, which enforces a limit.

Stats mirror OperatorStats -> ...  -> QueryStats rollups (SURVEY §5.1): the
Driver records per-operator wall time and row/batch counts around every
get_output/add_input call, which is what EXPLAIN ANALYZE renders.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Dict, List, Optional

from presto_tpu.config import DEFAULT, EngineConfig


class MemoryReservationError(RuntimeError):
    pass


class MemoryContext:
    """One node in the reservation tree (LocalMemoryContext analogue).

    A ROOT context may additionally charge its deltas into a per-node
    ``MemoryPool`` (server/memorypool.py): growth charges the pool
    BEFORE the tree applies (a full pool blocks the calling driver, and
    a failed charge leaves the tree untouched), shrink frees the pool
    after.  Cross-query frees arrive from other trees, so a driver
    blocked here — holding this tree's lock — is still unblockable.
    """

    def __init__(self, parent: Optional["MemoryContext"], name: str,
                 limit: Optional[int] = None, pool=None,
                 pool_query_id: str = "query"):
        self.parent = parent
        self.name = name
        self.limit = limit
        self.reserved = 0
        self.peak = 0
        self._tree_lock = (parent._tree_lock if parent is not None
                           else threading.Lock())
        if parent is None:
            self.pool = pool
            self.pool_query_id = pool_query_id
            self._pool_charged = 0

    def reserve(self, bytes_: int) -> None:
        self.set_bytes(self.reserved + bytes_)

    def set_bytes(self, bytes_: int) -> None:
        # one lock per reservation TREE (root-owned): concurrent feed
        # drivers of one task serialize, unrelated queries do not
        with self._tree_lock:
            self._set_bytes_locked(bytes_)

    def root(self) -> "MemoryContext":
        node = self
        while node.parent is not None:
            node = node.parent
        return node

    def _set_bytes_locked(self, bytes_: int) -> None:
        delta = bytes_ - self.reserved
        node = self
        root = node
        while node is not None:
            new = node.reserved + delta
            if delta > 0 and node.limit is not None and new > node.limit:
                raise MemoryReservationError(
                    f"memory limit exceeded at {node.name}: "
                    f"{new} > {node.limit}")
            root = node
            node = node.parent
        pool = root.pool
        if pool is not None and delta > 0:
            pool.reserve(root.pool_query_id, delta)
            root._pool_charged += delta
        node = self
        while node is not None:
            node.reserved += delta
            node.peak = max(node.peak, node.reserved)
            node = node.parent
        if pool is not None and delta < 0:
            freed = min(-delta, root._pool_charged)
            if freed > 0:
                root._pool_charged -= freed
                pool.free(root.pool_query_id, freed)

    def release_pool(self) -> None:
        """Detach from the pool, returning any remaining charge: the
        end-of-task backstop for reservations a failure path never freed
        (a leak in a SHARED pool would block other queries forever)."""
        with self._tree_lock:
            root = self.root()
            pool = root.pool
            if pool is not None and root._pool_charged > 0:
                pool.free(root.pool_query_id, root._pool_charged)
                root._pool_charged = 0
            root.pool = None

    def free(self) -> None:
        self.set_bytes(0)


@dataclasses.dataclass
class OperatorStats:
    operator: str = ""
    input_batches: int = 0
    input_rows: int = 0
    output_batches: int = 0
    output_rows: int = 0
    wall_ns: int = 0
    finish_wall_ns: int = 0
    # row-pipeline-tier device program accounting (FilterProject,
    # DynamicFilter, FusedSegment): one dispatch per jitted-program
    # launch, one compile per kernel-cache miss that built a program.
    # Tests assert pipeline fusion's launch-count reduction on these
    # instead of eyeballing traces.
    jit_dispatches: int = 0
    jit_compiles: int = 0
    # wall nanoseconds this operator spent BUILDING device programs
    # (trace + lower + XLA compile, measured around the first dispatch
    # of each freshly built kernel) — split out of execute wall so
    # EXPLAIN ANALYZE and the span tree can attribute compile vs
    # execute per operator (kernelcache.timed_first_call).
    jit_compile_ns: int = 0
    # rows folded into in-segment partial-aggregation pre-reduce
    # (exec/fusion.py Fusion II): nonzero proves the scan->agg pipeline
    # emitted partial states, not row batches — tests pin on this
    # instead of eyeballing operator chains.
    prereduce_rows: int = 0
    # which kernel tier served this operator's group-by/join hot loop:
    # "hash" (device-resident open-addressing, ops/hashtable.py),
    # "direct" (bounded-domain), "sort" (sorted-index), "stream"
    # (clustered), "hash+sort" (overflow seam crossed mid-query) —
    # surfaced per segment/operator by tools/fusion_report.py
    kernel_tier: str = ""

    def as_dict(self) -> Dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass
class DriverStats:
    """One driver run (one instantiated pipeline) — the DriverStats
    rollup level between OperatorStats and TaskStats (SURVEY §5.1)."""

    pipeline: str = ""
    operators: int = 0
    input_rows: int = 0
    output_rows: int = 0
    wall_ns: int = 0

    def as_dict(self) -> Dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass
class TaskStats:
    """Task-level rollup of every operator the task ran, plus the
    memory/exchange/buffer counters the worker owns.  This is the shape
    serialized into the ``/v1/task/{id}`` info payload (``taskStats``)
    and aggregated into StageStats by the coordinator."""

    task_id: str = ""
    state: str = ""
    # wall-clock span (epoch seconds) of the task's execution — the
    # span-timeline surface tools/query_profile.py renders
    start_time: float = 0.0
    end_time: float = 0.0
    elapsed_s: float = 0.0
    # sums over operator stats
    wall_ns: int = 0
    input_rows: int = 0
    input_batches: int = 0
    output_rows: int = 0
    output_batches: int = 0
    jit_dispatches: int = 0
    jit_compiles: int = 0
    jit_compile_ns: int = 0
    prereduce_rows: int = 0
    peak_memory_bytes: int = 0
    # attempt-aware exchange dedup counters (sums across this task's
    # remote sources) + producer-side page accounting
    exchange_fetched: int = 0
    exchange_consumed: int = 0
    exchange_purged: int = 0
    pages_enqueued: int = 0
    # cumulative wire bytes this task's output buffers enqueued — the
    # processedBytes surface of the live progress protocol
    output_bytes: int = 0
    # spooled exchange (server/spool.py): pages written through to the
    # spool, and pages/bytes evicted from the in-memory buffer under
    # max_buffer_bytes pressure (re-servable from the spool)
    pages_spooled: int = 0
    pages_evicted: int = 0
    bytes_evicted: int = 0
    # device-sharded exchange tier: bytes this shard received through
    # in-program collectives (all_to_all / all_gather / gather) at the
    # fragment boundaries it produced — read back as program outputs
    # (parallel/sqlmesh.py per-shard stats) and folded into synthetic
    # per-shard TaskStats; HTTP-plane tasks report 0
    device_exchange_bytes: int = 0

    def add_operator(self, s: OperatorStats) -> None:
        self.wall_ns += s.wall_ns + s.finish_wall_ns
        self.input_rows += s.input_rows
        self.input_batches += s.input_batches
        self.output_rows += s.output_rows
        self.output_batches += s.output_batches
        self.jit_dispatches += s.jit_dispatches
        self.jit_compiles += s.jit_compiles
        self.jit_compile_ns += s.jit_compile_ns
        self.prereduce_rows += s.prereduce_rows

    def as_dict(self) -> Dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: Dict) -> "TaskStats":
        known = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in (d or {}).items() if k in known})


@dataclasses.dataclass
class StageStats:
    """Per-fragment aggregation across that stage's tasks: additive
    counters sum, wall is the slowest task (the stage critical path),
    peak memory is the largest task (StageStats rollup role)."""

    fragment_id: int = -1
    tasks: int = 0          # tasks placed
    reporting: int = 0      # tasks whose info was actually fetched
    input_rows: int = 0
    output_rows: int = 0
    wall_ns: int = 0        # max over tasks
    total_wall_ns: int = 0  # sum over tasks
    jit_dispatches: int = 0
    jit_compiles: int = 0
    jit_compile_ns: int = 0
    prereduce_rows: int = 0
    peak_memory_bytes: int = 0
    exchange_fetched: int = 0
    exchange_consumed: int = 0
    exchange_purged: int = 0
    pages_enqueued: int = 0
    output_bytes: int = 0
    pages_spooled: int = 0
    pages_evicted: int = 0
    bytes_evicted: int = 0
    device_exchange_bytes: int = 0

    def add_task(self, ts: TaskStats) -> None:
        self.reporting += 1
        self.input_rows += ts.input_rows
        self.output_rows += ts.output_rows
        self.wall_ns = max(self.wall_ns, ts.wall_ns)
        self.total_wall_ns += ts.wall_ns
        self.jit_dispatches += ts.jit_dispatches
        self.jit_compiles += ts.jit_compiles
        self.jit_compile_ns += ts.jit_compile_ns
        self.prereduce_rows += ts.prereduce_rows
        self.peak_memory_bytes = max(self.peak_memory_bytes,
                                     ts.peak_memory_bytes)
        self.exchange_fetched += ts.exchange_fetched
        self.exchange_consumed += ts.exchange_consumed
        self.exchange_purged += ts.exchange_purged
        self.pages_enqueued += ts.pages_enqueued
        self.output_bytes += ts.output_bytes
        self.pages_spooled += ts.pages_spooled
        self.pages_evicted += ts.pages_evicted
        self.bytes_evicted += ts.bytes_evicted
        self.device_exchange_bytes += ts.device_exchange_bytes

    def as_dict(self) -> Dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass
class QueryStats:
    """Whole-query rollup over stages (QueryStats role): the shape the
    ``/v1/query/{id}`` detail payload and QueryCompletedEvent carry."""

    query_id: str = ""
    elapsed_s: float = 0.0
    # serving-tier split (server/dispatcher.py): seconds queued for
    # resource-group admission vs executing (admission -> settled);
    # the local tier reports queued 0
    queued_s: float = 0.0
    execution_s: float = 0.0
    total_wall_ns: int = 0
    input_rows: int = 0
    output_rows: int = 0
    jit_dispatches: int = 0
    jit_compiles: int = 0
    jit_compile_ns: int = 0
    prereduce_rows: int = 0
    peak_memory_bytes: int = 0   # max single-task peak across the query
    exchange_fetched: int = 0
    exchange_consumed: int = 0
    exchange_purged: int = 0
    pages_enqueued: int = 0
    output_bytes: int = 0
    pages_spooled: int = 0
    pages_evicted: int = 0
    device_exchange_bytes: int = 0
    # cross-query result cache (server/resultcache.py): 1 when this
    # query was served ENTIRELY from cached spool pages (its jit /
    # dispatch / stage counters are then genuine zeros), and the wire
    # bytes served from the cache
    result_cached: int = 0
    result_cache_bytes: int = 0
    stages: int = 0

    def add_stage(self, st: StageStats) -> None:
        self.stages += 1
        self.total_wall_ns += st.total_wall_ns
        self.input_rows += st.input_rows
        self.output_rows += st.output_rows
        self.jit_dispatches += st.jit_dispatches
        self.jit_compiles += st.jit_compiles
        self.jit_compile_ns += st.jit_compile_ns
        self.prereduce_rows += st.prereduce_rows
        self.peak_memory_bytes = max(self.peak_memory_bytes,
                                     st.peak_memory_bytes)
        self.exchange_fetched += st.exchange_fetched
        self.exchange_consumed += st.exchange_consumed
        self.exchange_purged += st.exchange_purged
        self.pages_enqueued += st.pages_enqueued
        self.output_bytes += st.output_bytes
        self.pages_spooled += st.pages_spooled
        self.pages_evicted += st.pages_evicted
        self.device_exchange_bytes += st.device_exchange_bytes

    def as_dict(self) -> Dict:
        return dataclasses.asdict(self)


def hot_operator_lines(ops, top_n: int = 5) -> List[str]:
    """The EXPLAIN ANALYZE "hot operators" footer: the top-N operators
    by exclusive wall (``wall_ns`` already includes finish wall for
    aggregated dicts), with the compile-vs-execute split per operator.
    ``ops`` are operator-stats dicts; shared by the local and
    distributed EXPLAIN ANALYZE renderers so the two surfaces stay
    diffable."""
    ranked = sorted((o for o in ops if o.get("wall_ns", 0) > 0),
                    key=lambda o: o.get("wall_ns", 0), reverse=True)
    if not ranked:
        return []
    lines = [f"hot operators (top {min(top_n, len(ranked))} "
             f"by exclusive wall):"]
    for o in ranked[:top_n]:
        wall = o.get("wall_ns", 0)
        compile_ns = min(o.get("jit_compile_ns", 0), wall)
        lines.append(
            f"  {o.get('operator', '?'):<36} "
            f"{wall / 1e6:>9.1f} ms wall "
            f"({compile_ns / 1e6:.1f} compile / "
            f"{(wall - compile_ns) / 1e6:.1f} execute), "
            f"{o.get('output_rows', 0)} rows out")
    return lines


class QueryContext:
    def __init__(self, config: EngineConfig = DEFAULT,
                 memory_limit: Optional[int] = None, pool=None,
                 pool_query_id: str = "query"):
        self.config = config
        self.memory = MemoryContext(None, "query", limit=memory_limit,
                                    pool=pool, pool_query_id=pool_query_id)
        self.start_time = time.time()

    def release_pool(self) -> None:
        self.memory.release_pool()


class TaskContext:
    def __init__(self, query: QueryContext, task_id: str = "task-0"):
        self.query = query
        self.task_id = task_id
        self.config = query.config
        self.memory = MemoryContext(query.memory, f"task:{task_id}")
        self.operator_stats: List[OperatorStats] = []
        self.driver_stats: List[DriverStats] = []
        self.start_time = time.time()
        self._cleanups: List = []

    def task_stats(self) -> TaskStats:
        """Roll every operator's stats up into one TaskStats (exchange
        and buffer counters are merged in by the owning SqlTask, which
        owns those objects)."""
        ts = TaskStats(task_id=self.task_id, start_time=self.start_time)
        for s in list(self.operator_stats):
            ts.add_operator(s)
        ts.peak_memory_bytes = self.memory.peak
        return ts

    def jit_counters(self) -> Dict[str, int]:
        """Task-level rollup of row-pipeline jit dispatch/compile counts
        (the launch-count surface the fusion tests pin)."""
        return {
            "dispatches": sum(s.jit_dispatches for s in self.operator_stats),
            "compiles": sum(s.jit_compiles for s in self.operator_stats),
            # compile-vs-execute attribution: wall spent building device
            # programs, split out of the operators' execute wall
            "compile_ns": sum(s.jit_compile_ns
                              for s in self.operator_stats),
            "prereduce_rows": sum(s.prereduce_rows
                                  for s in self.operator_stats),
        }

    def register_cleanup(self, fn) -> None:
        """Register an idempotent resource-release callback to run at task
        teardown (the SqlTask cleanup role): a backstop for reservations
        normally released by a downstream pipeline that may never run."""
        self._cleanups.append(fn)

    def close(self) -> None:
        cleanups, self._cleanups = self._cleanups, []
        for fn in cleanups:
            try:
                fn()
            except Exception:  # noqa: BLE001 - teardown is best-effort
                pass


class OperatorContext:
    def __init__(self, task: TaskContext, name: str):
        self.task = task
        self.config = task.config
        self.name = name
        self.memory = MemoryContext(task.memory, f"op:{name}")
        self.stats = OperatorStats(operator=name)
        task.operator_stats.append(self.stats)

    def should_spill(self, accumulated_bytes: int) -> bool:
        """The revoke decision for accumulating operators (join build,
        sort): shed state to the spill tier past the byte threshold, OR
        as soon as the node's memory pool signals pressure — revocable
        memory is reclaimed BEFORE anyone blocks or the killer fires."""
        cfg = self.config
        if not cfg.spill_enabled:
            return False
        if accumulated_bytes > cfg.spill_threshold_bytes:
            return True
        pool = self.memory.root().pool
        return (pool is not None and accumulated_bytes > 0
                and pool.needs_revoke())
