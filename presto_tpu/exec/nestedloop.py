"""Cross join + scalar-subquery guard operators.

Reference models: NestedLoopJoinOperator/NestedLoopBuildOperator
(presto-main/.../operator/NestedLoopJoinOperator.java:36) and
EnforceSingleRowOperator (EnforceSingleRowOperator.java:27).  The dominant
use here is the scalar-subquery shape the planner emits (EnforceSingleRow
-> cross join of exactly one row), so the product kernel is optimized for
a small build side: probe rows are tiled ``n_build`` times per chunk with
plain gathers — no keys, no sort.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np

from presto_tpu import types as T
from presto_tpu.batch import Batch, Column, next_bucket
from presto_tpu.exec.context import OperatorContext
from presto_tpu.exec.operator import Operator, OperatorFactory, device_concat


class NestedLoopBuildOperator(Operator):
    """Materializes the build side into the shared holder."""

    def __init__(self, ctx: OperatorContext,
                 factory: "NestedLoopBuildOperatorFactory"):
        super().__init__(ctx)
        self.f = factory
        self._batches: List[Batch] = []

    def add_input(self, batch: Batch) -> None:
        self._batches.append(batch)
        self.ctx.stats.input_rows += batch.num_rows
        self.ctx.memory.reserve(batch.size_bytes)

    def finish(self) -> None:
        if self._finishing:
            return
        super().finish()
        data = device_concat(self._batches, 1)
        if data is None:
            from presto_tpu.batch import empty_batch

            data = empty_batch(self.f.input_types)
        self.f.data = data
        self._batches = []

    def get_output(self) -> Optional[Batch]:
        return None

    def is_finished(self) -> bool:
        return self._finishing


class NestedLoopBuildOperatorFactory(OperatorFactory):
    def __init__(self, input_types: Sequence[T.Type]):
        self.input_types = list(input_types)
        self.data: Optional[Batch] = None

    def create(self, ctx: OperatorContext) -> NestedLoopBuildOperator:
        return NestedLoopBuildOperator(ctx, self)

    def reset_for_execution(self) -> None:
        # the build pipeline re-fills this next run; dropping it now
        # releases the previous execution's build rows
        self.data = None


class NestedLoopJoinOperator(Operator):
    """Probe side: emits the cartesian product probe x build.  Output
    layout matches LookupJoinOperator: probe channels then build
    channels."""

    def __init__(self, ctx: OperatorContext,
                 build: NestedLoopBuildOperatorFactory,
                 max_output_rows: int):
        super().__init__(ctx)
        self.build = build
        self.max_output_rows = max_output_rows
        self._out: List[Batch] = []

    def add_input(self, batch: Batch) -> None:
        import jax.numpy as jnp

        self.ctx.stats.input_rows += batch.num_rows
        build = self.build.data
        if build is None:
            raise RuntimeError("cross-join build side not finished")
        nb = build.num_rows
        if nb == 0 or batch.num_rows == 0:
            return
        npr = batch.num_rows
        # chunk the build side so each product batch stays bounded
        chunk = max(1, self.max_output_rows // max(batch.capacity, 1))
        for lo in range(0, nb, chunk):
            k = min(chunk, nb - lo)
            cap_out = next_bucket(batch.capacity * k)
            j = jnp.arange(cap_out)
            pi = (j // k).astype(jnp.int32)
            pi = jnp.minimum(pi, batch.capacity - 1)
            bi = (lo + (j % k)).astype(jnp.int32)
            total = npr * k
            cols = []
            for c in batch.columns:
                cols.append(Column(c.type, c.values[pi],
                                   None if c.valid is None else c.valid[pi],
                                   c.dictionary))
            for c in build.columns:
                cols.append(Column(c.type, c.values[bi],
                                   None if c.valid is None else c.valid[bi],
                                   c.dictionary))
            out = Batch(tuple(cols), total)
            self.ctx.stats.output_rows += total
            self._out.append(out)

    def get_output(self) -> Optional[Batch]:
        if self._out:
            return self._out.pop(0)
        return None

    def is_finished(self) -> bool:
        return self._finishing and not self._out


class NestedLoopJoinOperatorFactory(OperatorFactory):
    def __init__(self, build: NestedLoopBuildOperatorFactory,
                 max_output_rows: int = 1 << 22):
        self.build = build
        self.max_output_rows = max_output_rows

    def create(self, ctx: OperatorContext) -> NestedLoopJoinOperator:
        return NestedLoopJoinOperator(ctx, self.build, self.max_output_rows)


class EnforceSingleRowOperator(Operator):
    """Scalar subqueries must yield exactly one row; zero rows yield one
    all-NULL row (SQL scalar subquery semantics)."""

    def __init__(self, ctx: OperatorContext, types: Sequence[T.Type]):
        super().__init__(ctx)
        self.types = list(types)
        self._rows = 0
        self._batches: List[Batch] = []
        self._emitted = False

    def add_input(self, batch: Batch) -> None:
        self._rows += batch.num_rows
        if self._rows > 1:
            raise RuntimeError(
                "scalar subquery returned more than one row")
        self._batches.append(batch)

    def get_output(self) -> Optional[Batch]:
        if not self._finishing or self._emitted:
            return None
        self._emitted = True
        if self._rows == 1:
            return self._batches[0]
        # zero rows -> one all-NULL row
        cols = []
        for typ in self.types:
            values = np.zeros(1, dtype=typ.np_dtype)
            cols.append(Column(typ, values, np.zeros(1, bool)))
        return Batch(tuple(cols), 1)

    def is_finished(self) -> bool:
        return self._finishing and self._emitted


class EnforceSingleRowOperatorFactory(OperatorFactory):
    def __init__(self, types: Sequence[T.Type]):
        self.types = list(types)

    def create(self, ctx: OperatorContext) -> EnforceSingleRowOperator:
        return EnforceSingleRowOperator(ctx, self.types)
