"""Hash join operators: build + probe pair sharing a LookupSource.

Reference models: HashBuilderOperator.java:51 (build side ->
PartitionedLookupSourceFactory), LookupJoinOperator.java:64 (probe),
HashSemiJoinOperator/SetBuilderOperator (semi), with variants per
LookupJoinOperators.java:45-60 (inner / probe-outer / semi / anti).

TPU design (ops/join.py): the LookupSource is a *sorted id index*, not a
hash table.  Three id strategies, chosen at build finish:

- 'single': one integer-ish key channel; values are ids directly.
- 'packed': multi-channel integer keys packed into one 63-bit word using
  build-side [min,max] ranges; probe values outside a channel's build range
  cannot match and map to the dead sentinel (keeps packing exact).
- 'canonical': arbitrary keys; probe side must materialize, ids come from
  a union sort (exact, collision-free).

Probe is streaming for 'single'/'packed' (one jitted program per probe
batch shape), with output-capacity retry on expansion overflow.
"""

from __future__ import annotations

import dataclasses
from functools import partial as _partial
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from presto_tpu import types as T
from presto_tpu.batch import Batch, Column, next_bucket
from presto_tpu.exec.context import OperatorContext
from presto_tpu.exec.operator import (
    Operator, OperatorFactory, column_pairs, device_concat,
)

_PACKABLE = ("bigint", "integer", "smallint", "tinyint", "date", "boolean")


def _is_single_word_type(t: T.Type) -> bool:
    from presto_tpu.ops.join import single_word_joinable

    return single_word_joinable(t, t.is_dictionary)


@dataclasses.dataclass
class LookupSource:
    """Build-side product handed to probe operators."""

    mode: str                      # 'single' | 'packed' | 'canonical'
                                   # | 'hash' (PagesHash table)
    sorted_ids: object             # int64 [cap_b] (single/packed)
    perm: object                   # int64 [cap_b]
    data: Batch                    # padded device build batch
    n_build: int
    key_channels: List[int]
    mins: Optional[np.ndarray] = None     # packed: per-channel min;
                                          # single: build live min (device)
    strides: Optional[np.ndarray] = None  # packed: per-channel stride
    maxs: Optional[np.ndarray] = None
    has_null_key: object = None           # device bool scalar (single/packed)
    # device_join_probe tier (ops/hashtable.py): the open-addressing
    # table (t_words tuple, t_prefix, t_used, starts, counts) whose
    # (starts, counts) index ``perm`` — the PagesHash role proper
    pages: Optional[tuple] = None
    key_types: Optional[tuple] = None     # probe-normalization types


class LookupSourceFactory:
    """Rendezvous between build and probe pipelines
    (PartitionedLookupSourceFactory analogue; single-partition here — the
    multi-device partitioned variant lives in parallel/)."""

    def __init__(self):
        self.source: Optional[LookupSource] = None

    def set(self, source: LookupSource) -> None:
        self.source = source

    def get(self) -> LookupSource:
        if self.source is None:
            raise RuntimeError("build side not finished before probe "
                               "(pipeline ordering bug)")
        return self.source


@dataclasses.dataclass
class SpilledLookupSource:
    """Build side went to the spill tier (HashBuilderOperator's
    INPUT_SPILLED state, HashBuilderOperator.java:155): the probe operator
    must hash-partition its input the same way and join
    partition-by-partition (grace hash join / GenericPartitioningSpiller).
    """

    spiller: object                # PartitioningSpiller over key channels
    n_partitions: int
    key_channels: List[int]
    input_types: List[T.Type]

    mode: str = "spilled"


@jax.jit
def _build_index_single(kv_pair, num_rows):
    """Single-word build: ids + sorted index + the live minimum + a
    span-overflow flag, one XLA program.  Ids are (value - min + 2) so
    NEGATIVE key values map to valid non-negative ids too (the sentinels
    own {-2,-1}); the min rides to the probe side as a device scalar.
    The caller reads only the flag (one scalar sync) and falls back to
    the canonical path when the live key spread would overflow the id
    arithmetic."""
    from presto_tpu.ops import join as J

    values, valid = kv_pair
    cap = values.shape[0]
    in_row = jnp.arange(cap) < num_rows
    dead = ~in_row
    if valid is not None:
        dead = dead | ~valid
        has_null = (in_row & ~valid).any()
    else:
        has_null = jnp.zeros((), bool)
    v = values.astype(jnp.int64)
    u = v.astype(jnp.uint64) ^ jnp.uint64(1 << 63)
    umin = jnp.min(jnp.where(dead, jnp.uint64(2**64 - 1), u))
    umax = jnp.max(jnp.where(dead, jnp.uint64(0), u))
    span_big = (~jnp.all(dead)) & ((umax - umin) >= jnp.uint64(1 << 62))
    bmin = jnp.min(jnp.where(dead, jnp.int64(2**62), v))
    bmin = jnp.where(jnp.all(dead), jnp.int64(0), bmin)
    ids = jnp.where(dead, jnp.int64(-2), v - bmin + 2)
    sb, perm = J.build_index(ids)
    return sb, perm, bmin, has_null, span_big


@jax.jit
def _key_ranges(pairs, num_rows):
    """Per-key-channel live [min, max] (packed-mode ranges), one program,
    one small host transfer."""
    cap = pairs[0][0].shape[0]
    base_dead = jnp.arange(cap) >= num_rows
    los, his = [], []
    for values, valid in pairs:
        dead = base_dead if valid is None else (base_dead | ~valid)
        v = values.astype(jnp.int64)
        los.append(jnp.where(dead, jnp.int64(2**62), v).min())
        his.append(jnp.where(dead, jnp.int64(-2**62), v).max())
    return jnp.stack(los), jnp.stack(his)


@jax.jit
def _build_index_packed(pairs, mins, strides, num_rows):
    """Packed multi-key build: mixed-radix ids + sorted index."""
    from presto_tpu.ops import join as J

    cap = pairs[0][0].shape[0]
    in_row = jnp.arange(cap) < num_rows
    dead = ~in_row
    has_null = jnp.zeros((), bool)
    ids = jnp.zeros(cap, jnp.int64)
    for i, (values, valid) in enumerate(pairs):
        if valid is not None:
            dead = dead | ~valid
            has_null = has_null | (in_row & ~valid).any()
        ids = ids + (values.astype(jnp.int64) - mins[i]) * strides[i]
    ids = jnp.where(dead, jnp.int64(-2), ids)
    sb, perm = J.build_index(ids)
    return sb, perm, has_null


from presto_tpu.kernelcache import cache_get, cache_put, new_cache

_PAGES_BUILD = new_cache("pages_hash_build")


def _pages_hash_build_jit(key_pairs, key_types, num_rows, table_cap: int):
    """ops.hashtable.pages_hash_build as one cached jitted program (the
    HashBuilderOperator finish -> PagesHash ctor, PagesHash.java:63)."""
    cap_b = key_pairs[0][0].shape[0]
    kvalid = tuple(v is not None for _, v in key_pairs)
    key = ("pages_build", tuple(key_types), kvalid, cap_b, table_cap)
    hit = cache_get(_PAGES_BUILD, key)
    if hit is None:
        def kernel(kvals, kvalids, n):
            from presto_tpu.ops.hashtable import pages_hash_build

            kc = [(kvals[i], kvalids[i], key_types[i])
                  for i in range(len(key_types))]
            return pages_hash_build(kc, n, table_cap)

        hit = jax.jit(kernel)
        cache_put(_PAGES_BUILD, key, hit)
    return hit(tuple(v for v, _ in key_pairs),
               tuple(v for _, v in key_pairs), num_rows)


class HashBuildOperator(Operator):
    def __init__(self, ctx: OperatorContext, factory: "HashBuildOperatorFactory"):
        super().__init__(ctx)
        self.f = factory
        factory._build_ctxs.append(ctx)
        # backstop: if the probe pipeline never instantiates (earlier
        # pipeline failure / cancellation between pipelines) the task
        # teardown releases the build reservation instead of the probe
        ctx.task.register_cleanup(factory.release)
        self._batches: List[Batch] = []
        self._spiller = None
        self._accumulated_bytes = 0

    def close(self) -> None:
        # the LookupSource keeps the build data alive through the probe:
        # the reservation is released by the probe side
        # (LookupJoinOperator.close -> factory.release), not here
        pass

    def add_input(self, batch: Batch) -> None:
        self.ctx.stats.input_rows += batch.num_rows
        if self._spiller is not None:
            self._spiller.spill(batch.to_numpy())
            return
        self._batches.append(batch)
        self.ctx.memory.reserve(batch.size_bytes)
        self._accumulated_bytes += batch.size_bytes
        # byte threshold OR node-pool pressure (revoke-first: shed
        # revocable state before anyone blocks on the memory pool)
        if self.f.allow_spill and \
                self.ctx.should_spill(self._accumulated_bytes):
            self._spill_accumulated()

    def _spill_accumulated(self) -> None:
        """Revoke build-side memory: hash-partition everything seen so far
        to disk; the probe side will partition itself to match."""
        from presto_tpu.exec.spill import PartitioningSpiller

        cfg = self.ctx.config
        self._spiller = PartitioningSpiller(
            cfg.spill_path, cfg.spill_partitions, self.f.key_channels,
            tag=f"joinbuild-{self.ctx.name}")
        for b in self._batches:
            self._spiller.spill(b.to_numpy())
        self._batches = []
        self._accumulated_bytes = 0
        self.ctx.memory.free()

    def finish(self) -> None:
        if self._finishing:
            return
        super().finish()
        if self._spiller is not None:
            # a spilled build side cannot feed dynamic filters cheaply;
            # mark the filter as pass-through
            if self.f.dynamic_filter is not None:
                self.f.dynamic_filter.disable()
            self.f.lookup.set(SpilledLookupSource(
                self._spiller, self.ctx.config.spill_partitions,
                list(self.f.key_channels), list(self.f.input_types)))
            return
        import jax.numpy as jnp

        from presto_tpu import types as TT
        from presto_tpu.exec.operator import pad_batch
        from presto_tpu.ops import join as J

        data = device_concat(self._batches, self.ctx.config.min_batch_capacity)
        if self.f.dynamic_filter is not None:
            self.f.dynamic_filter.fill_from_build(
                None if data is None else data.to_numpy(),
                self.f.key_channels)
        if data is None:
            # empty build side: synthesize a 0-row padded batch
            from presto_tpu.batch import empty_batch

            data = pad_batch(empty_batch(self.f.input_types),
                             self.ctx.config.min_batch_capacity)
        self._batches = []
        chans = self.f.key_channels
        n_build = data.num_rows
        n = jnp.asarray(n_build)
        key_pairs = tuple(
            (data.columns[c].values, data.columns[c].valid) for c in chans)
        cfg = self.ctx.config
        packable = all(_is_single_word_type(data.columns[c].type)
                       for c in chans)
        want_hash = False
        if getattr(cfg, "device_join_probe", False):
            if not packable:
                # canonical-class multi-channel keys: the hash table is
                # what lets the probe STREAM at all (the sorted tier
                # would materialize the probe side for a union sort)
                want_hash = True
            elif (jax.default_backend() == "tpu"
                    and n_build <= getattr(
                        cfg, "device_join_probe_max_build_rows",
                        1 << 17)):
                # packable keys: platform economics decide.  On TPU,
                # sorting is the expensive primitive and gathers run at
                # device rate, so the table wins up to the build-size
                # bound (claim-inserting a huge build still loses to
                # one argsort).  On CPU the measured winner for
                # integer-keyed builds is the existing sorted tier —
                # its dense-histogram probe is two gathers — so the
                # hash table is not engaged there; absorbed probes
                # (exec/fusion.py) carry single/packed sources
                # in-kernel either way, which is where the dispatch
                # reduction lives.
                want_hash = True
        if want_hash and self._set_pages_hash(data, key_pairs, chans,
                                              n, n_build):
            return
        if len(chans) == 1 and _is_single_word_type(data.columns[chans[0]].type):
            # one scalar sync guards the id arithmetic: a live key spread
            # >= 2^62 would overflow the (value - min + 2) ids, silently
            # dropping matches — such builds take the canonical path
            sb, perm, bmin, has_null, span_big = _build_index_single(
                key_pairs[0], n)
            if not bool(span_big):
                self.f.lookup.set(LookupSource(
                    "single", sb, perm, data, n_build, chans, mins=bmin,
                    has_null_key=has_null))
                return
        if all(_is_single_word_type(data.columns[c].type) for c in chans):
            # pack multi-channel integer keys using build-side ranges
            los, his = _key_ranges(key_pairs, n)        # one host sync
            los = np.asarray(los)
            his = np.asarray(his)
            empty = bool((los > his).any())             # no live rows
            if empty:
                los = np.zeros_like(los)
                his = np.zeros_like(his)
            strides = []
            span_product = 1
            for lo, hi in zip(los, his):
                strides.append(span_product)
                span_product *= int(hi - lo + 1)
            if span_product < (1 << 62):
                strides_a = np.asarray(strides, np.int64)
                sb, perm, has_null = _build_index_packed(
                    key_pairs, jnp.asarray(los), jnp.asarray(strides_a), n)
                self.f.lookup.set(LookupSource(
                    "packed", sb, perm, data, n_build, chans,
                    mins=los, strides=strides_a, maxs=his,
                    has_null_key=has_null))
                return
        # key spans overflowed the single/packed id arithmetic: the
        # hash table still streams such keys (equality needs no ids)
        if (getattr(cfg, "device_join_probe", False) and not want_hash
                and self._set_pages_hash(data, key_pairs, chans, n,
                                         n_build)):
            return
        # general path: probe side will materialize and union-sort
        self.f.lookup.set(LookupSource("canonical", None, None, data,
                                       n_build, chans))

    def _set_pages_hash(self, data, key_pairs, chans, n,
                        n_build) -> bool:
        """Build + publish the PagesHash lookup source; False when the
        bounded claim loop could not place the build keys (adversarial
        chains — one retry at 4x capacity quarters the load first).
        ok=False costs one scalar sync, the span_big guard's cost
        class."""
        table_cap = max(2 * data.capacity, 1024)
        ktypes = tuple(data.columns[c].type for c in chans)
        (tw, tp, tu, starts, counts, perm, has_null,
         ok) = _pages_hash_build_jit(key_pairs, ktypes, n, table_cap)
        if not bool(ok):
            (tw, tp, tu, starts, counts, perm, has_null,
             ok) = _pages_hash_build_jit(key_pairs, ktypes, n,
                                         4 * table_cap)
        if not bool(ok):
            return False
        self.ctx.stats.kernel_tier = "hash"
        self.f.lookup.set(LookupSource(
            "hash", None, perm, data, n_build, chans,
            has_null_key=has_null, pages=(tw, tp, tu, starts, counts),
            key_types=ktypes))
        return True

    def get_output(self) -> Optional[Batch]:
        return None

    def is_finished(self) -> bool:
        return self._finishing


class HashBuildOperatorFactory(OperatorFactory):
    def __init__(self, key_channels: Sequence[int],
                 input_types: Sequence[T.Type], dynamic_filter=None,
                 allow_spill: bool = True):
        self.key_channels = list(key_channels)
        self.input_types = list(input_types)
        self.lookup = LookupSourceFactory()
        self.dynamic_filter = dynamic_filter
        # per-partition sub-builds during a grace join must not re-spill
        self.allow_spill = allow_spill
        self._build_ctxs: List[OperatorContext] = []

    def create(self, ctx: OperatorContext) -> HashBuildOperator:
        return HashBuildOperator(ctx, self)

    def release(self) -> None:
        """Drop the lookup source and the build-side reservation.  Called
        when the probe finishes — under grouped execution this is what
        makes peak memory scale with 1/buckets (Lifespan retirement,
        execution/Lifespan.java:26-38 role).  Idempotent: contexts are
        freed once; the task-teardown backstop may call this again for a
        build whose probe pipeline never instantiated."""
        self.lookup.source = None
        ctxs, self._build_ctxs = self._build_ctxs, []
        for ctx in ctxs:
            ctx.memory.free()

    def reset_for_execution(self) -> None:
        # a cached physical plan re-runs its build pipeline; the
        # previous run's lookup source (normally released at probe
        # finish — this is the backstop for error paths) must not leak
        self.release()


def _ids_from_pairs(jnp, pairs, key_channels, mode, mins, strides, maxs,
                    num_rows):
    """Probe ids for 'single'/'packed' modes over (values, valid) pairs."""
    cap = pairs[0][0].shape[0]
    dead = jnp.arange(cap) >= num_rows
    for c in key_channels:
        if pairs[c][1] is not None:
            dead = dead | ~pairs[c][1]
    if mode == "single":
        # mins = build-side live minimum (device scalar); probe values
        # below it cannot match any build row -> dead sentinel
        ids = pairs[key_channels[0]][0].astype(jnp.int64) - mins + 2
        return jnp.where(dead | (ids < 0), jnp.int64(-1), ids)
    ids = jnp.zeros(cap, jnp.int64)
    for i, c in enumerate(key_channels):
        v = pairs[c][0].astype(jnp.int64)
        dead = dead | (v < mins[i]) | (v > maxs[i])
        ids = ids + (v - mins[i]) * strides[i]
    return jnp.where(dead, jnp.int64(-1), ids)


@dataclasses.dataclass(frozen=True)
class _StreamStatics:
    """Hashable static config for the module-level probe kernels; one jit
    cache entry per distinct value + input shapes (the JoinCompiler
    specialization key, shared GLOBALLY across operators and queries —
    closures would re-trace per operator instance)."""

    mode: str
    join_type: str
    key_channels: Tuple[int, ...]
    out_cap: int
    n_probe_cols: int
    null_aware: bool = False
    # 'hash' mode: probe-key types for word normalization inside the
    # kernel (the pages table is keyed on normalized words)
    key_types: Tuple = ()


def _hash_lo_counts(probe_pairs, pages, key_channels, key_types,
                    num_rows):
    """(lo, counts, live) through the PagesHash table (probe half of
    PagesHash.java:63-121; prefix reject before the word compare)."""
    from presto_tpu.ops.hashtable import pages_hash_probe

    kc = [(probe_pairs[c][0], probe_pairs[c][1], key_types[i])
          for i, c in enumerate(key_channels)]
    return pages_hash_probe(pages, kc, num_rows)


@_partial(jax.jit, static_argnames=("key_channels", "mode", "join_type",
                                    "key_types"))
def _probe_expand_total(probe_pairs, sorted_ids, perm, mins, strides,
                        maxs, pages, num_rows, *, key_channels, mode,
                        join_type, key_types=()):
    """Phase 1: exact expansion size for this batch (so phase 2 compiles
    at the right capacity bucket on the first try)."""
    from presto_tpu.ops import join as J

    if mode == "hash":
        _, counts, _ = _hash_lo_counts(probe_pairs, pages, key_channels,
                                       key_types, num_rows)
    else:
        ids = _ids_from_pairs(jnp, probe_pairs, key_channels, mode, mins,
                              strides, maxs, num_rows)
        _, counts = J.probe_counts(sorted_ids, perm, ids)
    if join_type == "left":
        cap = probe_pairs[0][0].shape[0]
        live_probe = jnp.arange(cap) < num_rows
        return jnp.where(live_probe, jnp.maximum(counts, 1), 0).sum()
    return counts.sum()


@_partial(jax.jit, static_argnames=("s",))
def _stream_probe(probe_pairs, build_pairs, sorted_ids, perm, mins,
                  strides, maxs, pages, num_rows, bstats, *,
                  s: _StreamStatics):
    """Phase 2: the streaming probe kernel (inner/left expansion or
    semi/anti masks) as one XLA program.  All build-side data arrives as
    traced arguments: nothing is baked into the executable, so the
    compile caches by shape + statics only."""
    from presto_tpu.ops import join as J
    from presto_tpu.ops.filter import selected_positions

    cap = probe_pairs[0][0].shape[0]
    if s.mode == "hash":
        lo, counts, live = _hash_lo_counts(
            probe_pairs, pages, s.key_channels, s.key_types, num_rows)
    else:
        ids = _ids_from_pairs(jnp, probe_pairs, s.key_channels, s.mode,
                              mins, strides, maxs, num_rows)
        lo, counts = J.probe_counts(sorted_ids, perm, ids)
        live = ids >= 0
    if s.join_type in ("semi", "anti"):
        if s.join_type == "anti":
            n_build, has_null = bstats
            mask = J.anti_keep_from_parts(
                counts, live, jnp.arange(cap) < num_rows, s.null_aware,
                [probe_pairs[c][1] for c in s.key_channels],
                n_build, build_has_null=has_null)
        else:
            mask = J.semi_mask(counts, live, anti=False)
        idx, count = selected_positions(mask, None, num_rows, cap)
        idx = idx.astype(jnp.int32)
        outs = tuple(
            (v[idx], None if valid is None else valid[idx])
            for v, valid in probe_pairs)
        return outs, count, jnp.int64(0)
    if s.join_type == "left":
        pi, bi, rv, unmatched, total = J.expand_matches_outer(
            lo, counts, jnp.arange(cap) < num_rows, perm, s.out_cap)
    else:
        pi, bi, rv, unmatched, total = J.expand_matches(
            lo, counts, perm, s.out_cap)
    pi = pi.astype(jnp.int32)
    bi = bi.astype(jnp.int32)
    outs = []
    for v, valid in probe_pairs:
        outs.append((v[pi], None if valid is None else valid[pi]))
    ones = jnp.ones(s.out_cap, bool)
    for v, valid in build_pairs:
        bvalid = ones if valid is None else valid[bi]
        outs.append((v[bi], bvalid & ~unmatched))
    return tuple(outs), total, total


class LookupJoinOperator(Operator):
    """Probe side.  Output layout: all probe channels, then all build
    channels (planner projects away what it does not need).  semi/anti emit
    probe channels only."""

    def close(self) -> None:
        super().close()
        self.f.build.release()

    def __init__(self, ctx: OperatorContext, factory: "LookupJoinOperatorFactory"):
        super().__init__(ctx)
        self.f = factory
        self._pending: List[Batch] = []
        self._out: List[Batch] = []
        self._kernels: Dict[tuple, object] = {}
        self._drained = False

    # -- probe id computation -------------------------------------------
    def _probe_ids(self, jnp, src: LookupSource, batch: Batch, num_rows):
        chans = self.f.probe_key_channels
        cap = batch.capacity
        dead = jnp.arange(cap) >= num_rows
        for c in chans:
            if batch.columns[c].valid is not None:
                dead = dead | ~batch.columns[c].valid
        if src.mode == "single":
            ids = (batch.columns[chans[0]].values.astype(jnp.int64)
                   - src.mins + 2)
            return jnp.where(dead | (ids < 0), jnp.int64(-1), ids)
        assert src.mode == "packed"
        ids = jnp.zeros(cap, jnp.int64)
        for i, c in enumerate(chans):
            v = batch.columns[c].values.astype(jnp.int64)
            lo = int(src.mins[i])
            hi = int(src.maxs[i])
            dead = dead | (v < lo) | (v > hi)
            ids = ids + (v - lo) * int(src.strides[i])
        return jnp.where(dead, jnp.int64(-1), ids)

    def add_input(self, batch: Batch) -> None:
        self.ctx.stats.input_rows += batch.num_rows
        src = self.f.build.lookup.get()
        if src.mode == "spilled":
            # grace join: partition the probe the same way as the build
            if getattr(self, "_probe_spiller", None) is None:
                from presto_tpu.exec.spill import PartitioningSpiller

                cfg = self.ctx.config
                self._probe_spiller = PartitioningSpiller(
                    cfg.spill_path, src.n_partitions,
                    self.f.probe_key_channels,
                    tag=f"joinprobe-{self.ctx.name}")
            self._probe_spiller.spill(batch.to_numpy())
            return
        if src.mode == "canonical":
            self._pending.append(batch)
            self.ctx.memory.reserve(batch.size_bytes)
            return
        out = self._probe_streaming(src, batch)
        if out is not None and out.num_rows > 0:
            self._out.append(out)

    def _residual_compiled(self, batch: Batch, src: LookupSource):
        """Compile the residual over [probe channels..., build channels...]
        (JoinFilterFunctionCompiler role)."""
        if self.f.residual is None:
            return None
        from presto_tpu.expr.compile import ExprCompiler

        nprobe = batch.num_columns
        dicts = {i: c.dictionary for i, c in enumerate(batch.columns)
                 if c.dictionary is not None}
        for j, c in enumerate(src.data.columns):
            if c.dictionary is not None:
                dicts[nprobe + j] = c.dictionary
        return ExprCompiler(dicts).compile(self.f.residual)

    def _probe_streaming(self, src: LookupSource, batch: Batch) -> Optional[Batch]:
        import jax
        import jax.numpy as jnp

        from presto_tpu.ops import join as J

        join_type = self.f.join_type
        cap = batch.capacity
        n = jnp.asarray(batch.num_rows)
        if self.f.residual is None:
            return self._probe_streaming_global(src, batch, n)
        out_cap = next_bucket(cap * self.f.expansion)
        cres = self._residual_compiled(batch, src)
        while True:
            kernel = self._kernel(src, cap, out_cap, cres)
            outs, count, expand_total = kernel(
                tuple(column_pairs(batch)), tuple(column_pairs(src.data)), n)
            total = int(count)
            if int(expand_total) <= out_cap:
                break
            out_cap = next_bucket(int(expand_total))
        cols = []
        probe_cols = [batch.columns[i] for i in range(batch.num_columns)]
        if join_type in ("semi", "anti"):
            for c, (v, valid) in zip(probe_cols, outs):
                cols.append(Column(c.type, v, valid, c.dictionary))
        else:
            nb = batch.num_columns
            for c, (v, valid) in zip(probe_cols, outs[:nb]):
                cols.append(Column(c.type, v, valid, c.dictionary))
            for c, (v, valid) in zip(src.data.columns, outs[nb:]):
                cols.append(Column(c.type, v, valid, c.dictionary))
        out = Batch(tuple(cols), min(total, out_cap))
        self.ctx.stats.output_rows += out.num_rows
        return out

    def _probe_streaming_global(self, src: LookupSource, batch: Batch,
                                n) -> Optional[Batch]:
        """Residual-free probe through the globally-cached module kernels:
        count phase picks the exact output bucket, expand phase never
        overflows, and compiles are shared across operators and queries
        with the same shapes."""
        import jax.numpy as jnp

        join_type = self.f.join_type
        cap = batch.capacity
        kc = tuple(self.f.probe_key_channels)
        if src.mode == "packed":
            mins = jnp.asarray(src.mins)
            strides = jnp.asarray(src.strides)
            maxs = jnp.asarray(src.maxs)
        elif src.mode == "single":
            # build-side live minimum (device scalar from the build kernel)
            mins = src.mins
            strides = maxs = jnp.zeros(1, jnp.int64)
        else:
            mins = strides = maxs = jnp.zeros(1, jnp.int64)
        key_types = src.key_types if src.mode == "hash" else ()
        if not self.ctx.stats.kernel_tier:
            self.ctx.stats.kernel_tier = (
                "hash" if src.mode == "hash" else "sorted")
        probe_pairs = tuple(column_pairs(batch))
        build_pairs = tuple(column_pairs(src.data))
        if join_type in ("semi", "anti"):
            out_cap = 0
        else:
            etotal = int(_probe_expand_total(
                probe_pairs, src.sorted_ids, src.perm, mins, strides, maxs,
                src.pages, n, key_channels=kc, mode=src.mode,
                join_type=join_type, key_types=key_types))
            out_cap = next_bucket(max(etotal, 1))
        s = _StreamStatics(src.mode, join_type, kc, out_cap,
                           batch.num_columns, self.f.null_aware,
                           key_types)
        bstats = (jnp.asarray(src.n_build, jnp.int64),
                  src.has_null_key if src.has_null_key is not None
                  else jnp.zeros((), bool))
        outs, count, _ = _stream_probe(
            probe_pairs, build_pairs, src.sorted_ids, src.perm, mins,
            strides, maxs, src.pages, n, bstats, s=s)
        # expansion joins already synced the exact total in phase 1; only
        # semi/anti need to read the selected count (host round-trips are
        # ~1s each on remote-attached devices)
        total = etotal if join_type not in ("semi", "anti") else int(count)
        cols = []
        probe_cols = [batch.columns[i] for i in range(batch.num_columns)]
        if join_type in ("semi", "anti"):
            for c, (v, valid) in zip(probe_cols, outs):
                cols.append(Column(c.type, v, valid, c.dictionary))
        else:
            nb = batch.num_columns
            for c, (v, valid) in zip(probe_cols, outs[:nb]):
                cols.append(Column(c.type, v, valid, c.dictionary))
            for c, (v, valid) in zip(src.data.columns, outs[nb:]):
                cols.append(Column(c.type, v, valid, c.dictionary))
        out = Batch(tuple(cols), total if out_cap == 0
                    else min(total, out_cap))
        self.ctx.stats.output_rows += out.num_rows
        return out

    def _kernel(self, src: LookupSource, cap: int, out_cap: int,
                cres=None):
        import jax
        import jax.numpy as jnp

        from presto_tpu.ops import join as J
        from presto_tpu.ops.filter import selected_positions

        key = (src.mode, cap, out_cap, self.f.join_type, id(src))
        hit = self._kernels.get(key)
        if hit is not None:
            return hit
        join_type = self.f.join_type
        probe_op = self
        residual = None if cres is None else cres.run

        def kernel(probe_cols_pairs, build_cols_pairs, num_rows):
            if src.mode == "hash":
                lo, counts, live = _hash_lo_counts(
                    probe_cols_pairs, src.pages,
                    tuple(probe_op.f.probe_key_channels),
                    src.key_types, num_rows)
            else:
                pb = _RebuiltBatch(probe_cols_pairs)
                ids = probe_op._probe_ids(jnp, src, pb, num_rows)
                lo, counts = J.probe_counts(src.sorted_ids, src.perm, ids)
                live = ids >= 0
            zero = jnp.int64(0)
            if join_type in ("semi", "anti"):
                if residual is not None:
                    pi, bi, rv, _, etotal = J.expand_matches(
                        lo, counts, src.perm, out_cap)
                    pairs = tuple(
                        (v[pi], None if g is None else g[pi])
                        for v, g in probe_cols_pairs) + tuple(
                        (v[bi], None if g is None else g[bi])
                        for v, g in build_cols_pairs)
                    rmask, rvalid = residual(pairs, etotal, jnp)
                    ok = rv & rmask
                    if rvalid is not None:
                        ok = ok & rvalid
                    any_pass = jnp.zeros(cap, bool).at[pi].max(
                        ok, mode="drop")
                    mask = live & any_pass
                    if join_type == "anti":
                        pad = jnp.arange(cap) >= num_rows
                        mask = (live & ~any_pass) | ((~live) & (~pad))
                else:
                    etotal = zero
                    if join_type == "anti":
                        bcap = build_cols_pairs[0][0].shape[0]
                        mask = J.anti_keep_from_parts(
                            counts, live, jnp.arange(cap) < num_rows,
                            probe_op.f.null_aware,
                            [probe_cols_pairs[c][1]
                             for c in probe_op.f.probe_key_channels],
                            jnp.int64(src.n_build),
                            build_key_valids=[
                                build_cols_pairs[c][1]
                                for c in probe_op.f.build.key_channels],
                            build_in_row=jnp.arange(bcap) < src.n_build)
                    else:
                        mask = J.semi_mask(counts, live, anti=False)
                idx, count = selected_positions(mask, None, num_rows,
                                                cap)
                idx = idx.astype(jnp.int32)
                outs = tuple(
                    (v[idx], None if valid is None else valid[idx])
                    for v, valid in probe_cols_pairs)
                return outs, count, etotal
            if join_type == "left":
                # every real probe row emits >=1 row (null-key rows emit the
                # unmatched form); padding rows emit nothing
                pi, bi, rv, unmatched, total = J.expand_matches_outer(
                    lo, counts, jnp.arange(cap) < num_rows,
                    src.perm, out_cap)
            else:
                pi, bi, rv, unmatched, total = J.expand_matches(
                    lo, counts, src.perm, out_cap)
            pi = pi.astype(jnp.int32)
            bi = bi.astype(jnp.int32)
            outs = []
            for v, valid in probe_cols_pairs:
                outs.append((v[pi], None if valid is None else valid[pi]))
            ones = jnp.ones(out_cap, bool)
            for v, valid in build_cols_pairs:
                bvalid = ones if valid is None else valid[bi]
                bvalid = bvalid & ~unmatched
                outs.append((v[bi], bvalid))
            return tuple(outs), total, total

        jitted = jax.jit(kernel)
        self._kernels[key] = jitted
        return jitted

    def _probe_canonical(self) -> None:
        import jax.numpy as jnp

        from presto_tpu.ops import join as J
        from presto_tpu.ops.filter import selected_positions

        src = self.f.build.lookup.get()
        probe = device_concat(self._pending,
                              self.ctx.config.min_batch_capacity)
        self._pending = []
        if probe is None:
            return
        bcols = [(src.data.columns[c].values, src.data.columns[c].valid,
                  src.data.columns[c].type) for c in self.f.build.key_channels]
        pcols = [(probe.columns[c].values, probe.columns[c].valid,
                  probe.columns[c].type) for c in self.f.probe_key_channels]
        bids, pids = J.canonical_ids(bcols, pcols,
                                     jnp.asarray(src.data.num_rows),
                                     jnp.asarray(probe.num_rows))
        sb, perm = J.build_index(bids)
        lo, counts = J.probe_counts(sb, perm, pids)
        live = pids >= 0
        cap = probe.capacity
        n = jnp.asarray(probe.num_rows)
        join_type = self.f.join_type
        if join_type in ("semi", "anti"):
            cres = self._residual_compiled(probe, src)
            if cres is None:
                if join_type == "anti":
                    bcap = src.data.capacity
                    mask = J.anti_keep_from_parts(
                        counts, live, jnp.arange(cap) < n,
                        self.f.null_aware,
                        [probe.columns[c].valid
                         for c in self.f.probe_key_channels],
                        jnp.int64(src.data.num_rows),
                        build_key_valids=[
                            src.data.columns[c].valid
                            for c in self.f.build.key_channels],
                        build_in_row=(jnp.arange(bcap)
                                      < src.data.num_rows))
                else:
                    mask = J.semi_mask(counts, live, anti=False)
            else:
                out_cap = next_bucket(cap * self.f.expansion)
                while True:
                    pi, bi, rv, _, etotal = J.expand_matches(
                        lo, counts, perm, out_cap)
                    if int(etotal) <= out_cap:
                        break
                    out_cap = next_bucket(int(etotal))
                pi = pi.astype(jnp.int32)
                bi = bi.astype(jnp.int32)
                pairs = tuple(
                    (c.values[pi], None if c.valid is None else c.valid[pi])
                    for c in probe.columns) + tuple(
                    (c.values[bi], None if c.valid is None else c.valid[bi])
                    for c in src.data.columns)
                rmask, rvalid = cres.run(pairs, etotal, jnp)
                ok = rv & rmask
                if rvalid is not None:
                    ok = ok & rvalid
                any_pass = jnp.zeros(cap, bool).at[pi].max(ok, mode="drop")
                mask = (live & ~any_pass if join_type == "anti"
                        else live & any_pass)
                if join_type == "anti":
                    # residual anti = correlated NOT EXISTS: null-key
                    # rows never match, keep them
                    pad = jnp.arange(cap) >= n
                    mask = mask | ((~live) & (~pad))
            idx, count = selected_positions(mask, None, n, cap)
            cols = tuple(
                Column(c.type, c.values[idx],
                       None if c.valid is None else c.valid[idx],
                       c.dictionary)
                for c in probe.columns)
            self._out.append(Batch(cols, int(count)))
            return
        out_cap = next_bucket(cap * self.f.expansion)
        while True:
            if join_type == "left":
                pi, bi, rv, unmatched, total = J.expand_matches_outer(
                    lo, counts, jnp.arange(cap) < n, perm, out_cap)
            else:
                pi, bi, rv, unmatched, total = J.expand_matches(
                    lo, counts, perm, out_cap)
            if int(total) <= out_cap:
                break
            out_cap = next_bucket(int(total))
        cols = []
        for c in probe.columns:
            cols.append(Column(c.type, c.values[pi],
                               None if c.valid is None else c.valid[pi],
                               c.dictionary))
        ones = jnp.ones(out_cap, bool)
        for c in src.data.columns:
            bvalid = ones if c.valid is None else c.valid[bi]
            cols.append(Column(c.type, c.values[bi], bvalid & ~unmatched,
                               c.dictionary))
        self._out.append(Batch(tuple(cols), int(total)))

    # -- protocol --------------------------------------------------------
    def get_output(self) -> Optional[Batch]:
        if self._out:
            return self._out.pop(0)
        return None

    def finish(self) -> None:
        if self._finishing:
            return
        super().finish()
        src = self.f.build.lookup.get()
        if src.mode == "spilled":
            self._join_spilled_partitions(src)
            return
        if self._pending:
            self._probe_canonical()

    def _join_spilled_partitions(self, src: "SpilledLookupSource") -> None:
        """Grace hash join: per hash partition, rebuild a resident lookup
        source from the spilled build rows and replay the probe rows
        through a fresh build/probe operator pair (the reference's
        unspill-and-join path; partitions are disjoint in keys so inner/
        left/semi/anti all compose per partition)."""
        probe_spiller = getattr(self, "_probe_spiller", None)
        for p in range(src.n_partitions):
            build_batches = list(src.spiller.partition(p))
            probe_batches = (list(probe_spiller.partition(p))
                             if probe_spiller is not None else [])
            if not probe_batches:
                continue
            if not build_batches and self.f.join_type == "inner":
                continue
            sub_build_f = HashBuildOperatorFactory(
                self.f.build.key_channels, self.f.build.input_types,
                allow_spill=False)
            bctx = OperatorContext(self.ctx.task,
                                   f"{self.ctx.name}.p{p}.build")
            bop = sub_build_f.create(bctx)
            for b in build_batches:
                bop.add_input(b)
            bop.finish()
            sub_probe_f = LookupJoinOperatorFactory(
                sub_build_f, self.f.probe_key_channels, self.f.probe_types,
                self.f.join_type, self.f.expansion, self.f.residual)
            pctx = OperatorContext(self.ctx.task,
                                   f"{self.ctx.name}.p{p}.probe")
            pop = sub_probe_f.create(pctx)
            for b in probe_batches:
                pop.add_input(b)
                while (out := pop.get_output()) is not None:
                    self._out.append(out)
            pop.finish()
            while (out := pop.get_output()) is not None:
                self._out.append(out)
            bop.close()
            pop.close()
        src.spiller.close()
        if probe_spiller is not None:
            probe_spiller.close()

    def is_finished(self) -> bool:
        return self._finishing and not self._out and not self._pending


class _RebuiltBatch:
    """Adapter presenting (values, valid) pairs as Batch-ish columns for
    _probe_ids inside a jit trace."""

    def __init__(self, pairs):
        self.capacity = pairs[0][0].shape[0]
        self.columns = [_Col(v, valid) for v, valid in pairs]


class _Col:
    __slots__ = ("values", "valid")

    def __init__(self, values, valid):
        self.values = values
        self.valid = valid


class LookupJoinOperatorFactory(OperatorFactory):
    def __init__(self, build: HashBuildOperatorFactory,
                 probe_key_channels: Sequence[int],
                 probe_types: Sequence[T.Type],
                 join_type: str = "inner", expansion: int = 2,
                 residual=None, null_aware: bool = False):
        assert join_type in ("inner", "left", "semi", "anti")
        if residual is not None and join_type not in ("semi", "anti"):
            # inner-join residuals become post-join filters in the
            # optimizer; outer-join residuals are pushed into the build
            # input (planner) — only semi/anti need in-kernel residuals
            raise NotImplementedError(
                "residual filters only on semi/anti joins")
        self.build = build
        self.probe_key_channels = list(probe_key_channels)
        self.probe_types = list(probe_types)
        self.join_type = join_type
        self.expansion = expansion
        self.residual = residual
        self.null_aware = null_aware

    def create(self, ctx: OperatorContext) -> LookupJoinOperator:
        return LookupJoinOperator(ctx, self)
