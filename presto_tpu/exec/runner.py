"""Single-process pipeline runner (LocalQueryRunner's execution half).

The reference's LocalQueryRunner plans SQL then hand-pumps drivers in one
process (presto-main/.../testing/LocalQueryRunner.java:214,616-665).  This
module is the pumping half: it executes a DAG of Pipelines in dependency
order.  The SQL half (sql/ package) lowers plans into these pipelines.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

from presto_tpu.config import DEFAULT, EngineConfig
from presto_tpu.exec.context import QueryContext, TaskContext
from presto_tpu.exec.driver import Pipeline


def execute_pipelines(pipelines: Sequence[Pipeline],
                      config: EngineConfig = DEFAULT,
                      memory_limit: Optional[int] = None,
                      on_task_context=None) -> TaskContext:
    """Run pipelines sequentially in the given (dependency) order.

    Build pipelines come before their probe pipelines — the planner emits
    them in that order, mirroring how the reference sequences via
    LookupSourceFactory futures.  Returns the TaskContext (stats).
    ``on_task_context`` receives the TaskContext before execution starts
    so callers (worker memory reporting) can observe live reservations.
    """
    query = QueryContext(config, memory_limit)
    task = TaskContext(query)
    if on_task_context is not None:
        on_task_context(task)
    for p in pipelines:
        driver = p.instantiate(task)
        driver.run_to_completion()
    return task
