"""Single-process pipeline runner (LocalQueryRunner's execution half).

The reference's LocalQueryRunner plans SQL then hand-pumps drivers in one
process (presto-main/.../testing/LocalQueryRunner.java:214,616-665).  This
module is the pumping half: it executes a DAG of Pipelines in dependency
order.  The SQL half (sql/ package) lowers plans into these pipelines.

Multi-split pipelines whose leading operators are parallel-safe run as
``config.task_concurrency`` concurrent feed drivers stitched to the rest
of the chain through a LocalExchange (the reference's
AddLocalExchanges.java:95 + LocalExchange.java:53 shape) — host-side scan
decode overlaps the consumer's device work.
"""

from __future__ import annotations

import threading
from typing import List, Optional, Sequence

from presto_tpu.config import DEFAULT, EngineConfig
from presto_tpu.exec.context import QueryContext, TaskContext
from presto_tpu.exec.driver import Pipeline


def _parallel_prefix(p: Pipeline, config: EngineConfig) -> int:
    """Length of the leading factory run that may replicate into N
    drivers (0 = run the pipeline single-driver)."""
    if config.task_concurrency <= 1 or len(p.splits) <= 1:
        return 0
    if any(getattr(f, "requires_ordered_input", False)
           for f in p.factories):
        # round-robin feeds would interleave the clustered key order a
        # streaming aggregation depends on
        return 0
    k = 0
    for f in p.factories:
        if getattr(f, "parallel_safe", False):
            k += 1
        else:
            break
    # the whole chain being safe means there is no consumer stage left
    # to protect — still split before the terminal sink
    k = min(k, len(p.factories) - 1)
    if k > 1 and getattr(config, "fusion_partial_agg", False):
        from presto_tpu.exec.fusion import FusedSegmentOperatorFactory

        last = p.factories[k - 1]
        if isinstance(last, FusedSegmentOperatorFactory) \
                and last.coalesce_rows:
            # a coalescing segment batches everything it sees anyway, so
            # place it CONSUMER-side: one operator coalesces across all
            # feed drivers and dispatches once per coalesced batch,
            # instead of one flush per feeder.  Feeders keep the
            # parallel half that actually scales on the host (split
            # decode); the device program was serialized regardless.
            k -= 1
    return k


def _run_parallel(p: Pipeline, task: TaskContext, prefix: int,
                  width: int, deadline=None) -> None:
    from presto_tpu.exec.localexchange import (
        LocalExchange, LocalExchangeSinkOperatorFactory,
        LocalExchangeSourceOperatorFactory,
    )

    exchange = LocalExchange(width)
    errors: List[BaseException] = []

    def feed(i: int) -> None:
        feeder = Pipeline(
            p.factories[:prefix]
            + [LocalExchangeSinkOperatorFactory(exchange, producer=i)],
            p.splits[i::width], name=f"{p.name}.feed{i}")
        try:
            feeder.instantiate(task).run_to_completion(deadline=deadline)
        except BaseException as e:  # noqa: BLE001 - crossed to consumer
            errors.append(e)
            exchange.fail(e)

    threads = [threading.Thread(target=feed, args=(i,), daemon=True,
                                name=f"{p.name}.feed{i}")
               for i in range(width)]
    for t in threads:
        t.start()
    consumer = Pipeline(
        [LocalExchangeSourceOperatorFactory(exchange)]
        + p.factories[prefix:], name=p.name)
    try:
        consumer.instantiate(task).run_to_completion(deadline=deadline)
    except BaseException as e:
        # unblock feeders stuck in put() backpressure, then re-raise
        exchange.fail(e)
        raise
    finally:
        for t in threads:
            t.join(timeout=30)
    if errors:
        raise errors[0]


def execute_pipelines(pipelines: Sequence[Pipeline],
                      config: EngineConfig = DEFAULT,
                      memory_limit: Optional[int] = None,
                      on_task_context=None, pool=None,
                      pool_query_id: str = "query") -> TaskContext:
    """Run pipelines sequentially in the given (dependency) order.

    Build pipelines come before their probe pipelines — the planner emits
    them in that order, mirroring how the reference sequences via
    LookupSourceFactory futures.  Returns the TaskContext (stats).
    ``on_task_context`` receives the TaskContext before execution starts
    so callers (worker memory reporting) can observe live reservations.
    ``pool`` is the worker's shared MemoryPool; the reservation tree's
    root charges it under ``pool_query_id`` (server/memorypool.py).
    """
    import time as _time

    from presto_tpu import kernelcache

    # apply the configured compiled-kernel cache capacity (caches are
    # process-global; this sets the process default, cheap + idempotent)
    kernelcache.set_default_capacity(
        getattr(config, "kernel_cache_capacity", 0))
    query = QueryContext(config, memory_limit, pool=pool,
                         pool_query_id=pool_query_id)
    task = TaskContext(query)
    deadline = (_time.monotonic() + config.query_max_run_time_s
                if getattr(config, "query_max_run_time_s", 0) > 0 else None)
    try:
        if on_task_context is not None:
            on_task_context(task)
        for p in pipelines:
            if deadline is not None and _time.monotonic() > deadline:
                raise RuntimeError(
                    "Query exceeded maximum run time "
                    f"({config.query_max_run_time_s:g}s)")
            prefix = _parallel_prefix(p, config)
            width = min(config.task_concurrency, len(p.splits))
            if prefix > 0 and width > 1:
                _run_parallel(p, task, prefix, width, deadline=deadline)
            else:
                driver = p.instantiate(task)
                driver.run_to_completion(deadline=deadline)
    finally:
        task.close()
        # return any charge a failure path never freed — a leak in the
        # SHARED node pool would block every other query on this node
        query.release_pool()
    return task
