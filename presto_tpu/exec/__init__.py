"""Worker execution core: Operator protocol, Driver loop, task/operator
contexts (the presto-main execution/operator layer, SURVEY §2.6)."""
