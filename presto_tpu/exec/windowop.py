"""WindowOperator: partition-sorted segmented-scan window evaluation.

Reference model: WindowOperator (presto-main/.../operator/
WindowOperator.java:61) sorts a PagesIndex by (partition, order) keys and
walks it row-by-row, partition-by-partition, with per-function framing
(operator/window/FrameInfo).  The TPU formulation materializes, runs the
sort-permutation kernel once over all partitions, derives partition/peer
segment ids from adjacent-row key equality, and evaluates every window
function as a data-parallel segmented scan (ops/window.py) — one XLA
program, no per-partition loop.

Output rows come out partition/order-sorted (the reference's output order
as well); the appended channels hold the function results.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

from presto_tpu import types as T
from presto_tpu.batch import Batch, Column
from presto_tpu.exec.context import OperatorContext
from presto_tpu.exec.operator import Operator, OperatorFactory, device_concat
from presto_tpu.exec.sortop import SortSpec
from presto_tpu.sql.plan import PlanWindowFunction


def eval_window_function(fn: PlanWindowFunction, columns, seg, peer):
    """Evaluate one window function over partition-sorted columns.

    ``columns`` is any sequence of column-like objects exposing
    ``values / valid / type / dictionary`` (the operator tier's Column and
    the mesh tier's MCol both do).  Returns
    ``(result_type, values, valid|None, dictionary|None)``.
    """
    import jax.numpy as jnp

    from presto_tpu.ops import window as W

    name = fn.name
    rt = fn.result_type
    if name == "row_number":
        return rt, W.row_number(seg), None, None
    if name == "rank":
        return rt, W.rank(seg, peer), None, None
    if name == "dense_rank":
        return rt, W.dense_rank(seg, peer), None, None
    if name == "percent_rank":
        return rt, W.percent_rank(seg, peer), None, None
    if name == "cume_dist":
        return rt, W.cume_dist(seg, peer), None, None
    if name == "ntile":
        return rt, W.ntile(seg, fn.offset), None, None

    if name in ("lag", "lead"):
        c = columns[fn.arg_channels[0]]
        default = (columns[fn.default_channel].values
                   if fn.default_channel is not None else None)
        off = fn.offset if name == "lag" else -fn.offset
        vals, ok = W.shift_in_partition(seg, c.values, c.valid, off,
                                        default)
        return rt, vals, ok, c.dictionary

    lo, hi = W.frame_ends(seg, peer, fn.frame_unit, fn.frame_start,
                          fn.frame_end, fn.frame_start_offset,
                          fn.frame_end_offset)
    if name in ("first_value", "nth_value"):
        c = columns[fn.arg_channels[0]]
        k = fn.offset or 1
        target = lo + (k - 1)
        in_frame = target <= hi
        tc = jnp.clip(target, 0, c.values.shape[0] - 1)
        vals = c.values[tc]
        ok = in_frame if c.valid is None else (in_frame & c.valid[tc])
        return rt, vals, ok, c.dictionary
    if name == "last_value":
        c = columns[fn.arg_channels[0]]
        vals, ok = W.value_at(c.values, c.valid, hi)
        ok = ok & (lo <= hi)
        return rt, vals, ok, c.dictionary

    # framed aggregates
    if name == "count":
        if not fn.arg_channels:
            ones = jnp.ones(seg.shape[0], jnp.int64)
            s, _ = W.framed_sum_count(seg, ones, None, lo, hi)
            return rt, s, None, None
        c = columns[fn.arg_channels[0]]
        _, cnt = W.framed_sum_count(
            seg, jnp.zeros(seg.shape[0], jnp.int64), c.valid, lo, hi)
        return rt, cnt, None, None
    if name in ("sum", "avg"):
        c = columns[fn.arg_channels[0]]
        vals = c.values
        if T.is_integral(c.type) or isinstance(c.type, T.DecimalType):
            vals = vals.astype(jnp.int64)
        s, cnt = W.framed_sum_count(seg, vals, c.valid, lo, hi)
        ok = cnt > 0
        if name == "sum":
            return rt, s.astype(rt.np_dtype), ok, None
        cnt_safe = jnp.maximum(cnt, 1)
        if isinstance(rt, T.DecimalType):
            # scaled-integer average, round half away from zero
            q = s / cnt_safe
            avg = jnp.where(q >= 0, jnp.floor(q + 0.5),
                            jnp.ceil(q - 0.5)).astype(jnp.int64)
            return rt, avg, ok, None
        avg = s.astype(jnp.float64) / cnt_safe.astype(jnp.float64)
        return rt, avg, ok, None
    if name in ("min", "max"):
        c = columns[fn.arg_channels[0]]
        vals, ok = W.framed_minmax(seg, peer, c.values, c.valid,
                                   fn.frame_unit, fn.frame_start,
                                   fn.frame_end, is_max=(name == "max"),
                                   lo=lo, hi=hi)
        return rt, vals, ok, c.dictionary
    raise NotImplementedError(f"window function {name}")


class WindowOperator(Operator):
    """Spill-capable (SURVEY §2.9: WindowOperator is a spill consumer):
    input accumulates through an embedded external sort keyed by
    (partition, order) — over the revocable threshold, sorted runs go to
    the spill tier and are k-way merged at finish — then window
    evaluation proceeds chunk-by-chunk over groups of COMPLETE
    partitions, so device memory is bounded by the chunk size (a single
    partition larger than memory still must fit, as in the reference)."""

    def __init__(self, ctx: OperatorContext,
                 partition_channels: Sequence[int],
                 order_keys: Sequence[Tuple[int, bool, Optional[bool]]],
                 functions: Sequence[PlanWindowFunction]):
        super().__init__(ctx)
        self.partition_channels = list(partition_channels)
        self.order_keys = list(order_keys)
        self.functions = list(functions)
        self._batches: List[Batch] = []
        self._sorter = None
        self._outputs: List[Batch] = []

    def _sort_specs(self):
        from presto_tpu.exec.sortop import SortSpec

        specs = [SortSpec(ch, False, False)
                 for ch in self.partition_channels]
        specs += [SortSpec(ch, not asc, bool(nf))
                  for ch, asc, nf in self.order_keys]
        return specs

    def add_input(self, batch: Batch) -> None:
        self.ctx.stats.input_rows += batch.num_rows
        specs = self._sort_specs()
        if not specs:
            # OVER (): one global partition — nothing to sort or chunk
            self._batches.append(batch)
            self.ctx.memory.reserve(batch.size_bytes)
            return
        if self._sorter is None:
            from presto_tpu.exec.context import OperatorContext as OC
            from presto_tpu.exec.sortop import OrderByOperator

            sub = OC(self.ctx.task, f"{self.ctx.name}.sort")
            self._sorter = OrderByOperator(sub, specs)
        self._sorter.add_input(batch)

    def finish(self) -> None:
        if self._finishing:
            return
        super().finish()
        if self._sorter is None:
            data = device_concat(self._batches,
                                 self.ctx.config.min_batch_capacity)
            self._batches = []
            self.ctx.memory.free()
            if data is not None:
                self._emit(self._evaluate(data, presorted=False))
            return
        self._sorter.finish()
        self._consume_sorted()
        self._sorter = None

    def close(self) -> None:
        super().close()
        # the embedded sorter is not in the driver's operator list: free
        # its reservations and spilled run files here (failure paths
        # included — the Driver close invariant)
        if self._sorter is not None:
            try:
                self._sorter.close()
            except Exception:  # noqa: BLE001 - teardown is best-effort
                pass
            self._sorter = None

    def _emit(self, out: Batch) -> None:
        self._outputs.append(out)
        self.ctx.stats.output_rows += out.num_rows

    def _partition_starts(self, batch: Batch, prev_tail):
        """Host-side: bool[n] marking rows that START a new partition,
        given the previous stream row's key tuple (or None).  Returns
        (starts, this batch's last-row key tuple)."""
        import numpy as np

        n = batch.num_rows
        if not self.partition_channels:
            starts = np.zeros(n, bool)
            if prev_tail is None and n:
                starts[0] = True
            return starts, ()
        vals = []
        for ch in self.partition_channels:
            c = batch.columns[ch]
            v = np.asarray(c.values)[:n]
            if c.dictionary is not None:
                # codes are per-batch after a merge of spilled runs:
                # compare decoded values
                dic = np.asarray(list(c.dictionary.values) or [""],
                                 dtype=object)
                v = dic[np.clip(v, 0, len(dic) - 1)]
            g = (np.ones(n, bool) if c.valid is None
                 else np.asarray(c.valid)[:n])
            if c.valid is not None:
                # NULL rows may carry arbitrary buffer residue: mask
                # values so null==null (the validity bit carries the
                # distinction), matching the cross-batch tail compare
                v = v.copy()
                v[~g] = "" if v.dtype == object else v.dtype.type(0)
            if v.dtype.kind == "f":
                # NaN != NaN would split a NaN partition into per-row
                # partitions (and break the cross-batch tail compare);
                # compare bit patterns with NaN canonicalized and -0.0
                # folded into +0.0, matching the device-side segment path
                v = v.copy()
                v[v == 0.0] = 0.0
                w = v.view(np.int64 if v.dtype.itemsize == 8 else np.int32)
                w = w.copy()
                w[np.isnan(v)] = -1
                v = w
            vals.append((v, g))
        starts = np.zeros(n, bool)
        for v, g in vals:
            diff = np.zeros(n, bool)
            diff[1:] = (v[1:] != v[:-1]) | (g[1:] != g[:-1])
            starts |= diff
        if prev_tail is None:
            if n:
                starts[0] = True
        else:
            first = tuple((None if not g[0] else v[0])
                          for v, g in vals)
            if first != prev_tail:
                starts[0] = True
        tail = tuple((None if not g[-1] else v[-1]) for v, g in vals) \
            if n else prev_tail
        return starts, tail

    def _consume_sorted(self) -> None:
        """Stream the (possibly spill-merged) sorted batches, cutting
        evaluation chunks at partition boundaries."""
        import numpy as np

        from presto_tpu.batch import concat_batches

        target = max(self.ctx.config.scan_batch_rows, 1)
        pending: List[Batch] = []
        pending_rows = 0
        # global row index (within pending) of each partition start
        starts_acc: List[int] = []
        prev_tail = None

        def evaluate_rows(batches: List[Batch]) -> None:
            data = device_concat(batches,
                                 self.ctx.config.min_batch_capacity)
            if data is not None:
                self._emit(self._evaluate(data, presorted=True))

        while True:
            b = self._sorter.get_output()
            if b is None:
                break
            hb = b.compact().to_numpy() if pending else b
            starts, prev_tail = self._partition_starts(hb, prev_tail)
            starts_acc.extend((pending_rows + i)
                              for i in np.nonzero(starts)[0])
            pending.append(hb)
            pending_rows += hb.num_rows
            if pending_rows >= target:
                # split at the LAST partition start > 0 so every emitted
                # chunk holds only complete partitions
                cut = None
                for s in reversed(starts_acc):
                    if s > 0:
                        cut = s
                        break
                if cut is None:
                    continue      # one giant partition: keep growing
                merged = (concat_batches([x.compact().to_numpy()
                                          for x in pending])
                          if len(pending) > 1 else
                          pending[0].compact().to_numpy())
                head = merged.take(np.arange(0, cut))
                rest = merged.take(np.arange(cut, merged.num_rows))
                evaluate_rows([head])
                pending = [rest] if rest.num_rows else []
                pending_rows = rest.num_rows
                starts_acc = [s - cut for s in starts_acc if s >= cut]
        if pending_rows:
            evaluate_rows(pending)

    def _sort_and_segment(self, data: Batch, presorted: bool = False):
        """Sort by (partition, order) and derive partition/peer segment
        ids — shared by the window evaluation and the TopNRowNumber
        truncation (computed ONCE; each extra device dispatch costs
        seconds through the remote-TPU tunnel).  ``presorted`` skips the
        sort (spill-merged chunks arrive already ordered)."""
        import jax.numpy as jnp

        from presto_tpu.ops import window as W
        from presto_tpu.ops.sort import sort_permutation

        n = data.num_rows
        cap = data.capacity

        def sort_key(channel: int, desc: bool, nulls_first: bool):
            c = data.columns[channel]
            if c.type.is_dictionary:
                ranks = c.dictionary.sort_ranks()
                return (jnp.asarray(ranks)[c.values], c.valid, T.INTEGER,
                        desc, nulls_first)
            return (c.values, c.valid, c.type, desc, nulls_first)

        keys = [sort_key(ch, False, False) for ch in self.partition_channels]
        keys += [sort_key(ch, not asc, bool(nf))
                 for ch, asc, nf in self.order_keys]
        if keys and not presorted:
            perm = sort_permutation(keys, jnp.asarray(n))
            data = Batch(tuple(
                Column(c.type, c.values[perm],
                       None if c.valid is None else c.valid[perm],
                       c.dictionary)
                for c in data.columns), n)

        # adjacent-row equality -> partition segments / peer groups.
        # liveness participates as a pseudo-key so padding rows (all
        # sorted past the live rows) can never merge into the last
        # partition.
        live = jnp.arange(cap) < n

        def eq_prev(channel: int):
            c = data.columns[channel]
            v = c.values
            same = jnp.concatenate(
                [jnp.ones((1,), jnp.bool_), v[1:] == v[:-1]])
            if c.valid is not None:
                g = c.valid
                both_null = jnp.concatenate(
                    [jnp.ones((1,), jnp.bool_), (~g[1:]) & (~g[:-1])])
                both_ok = jnp.concatenate(
                    [jnp.ones((1,), jnp.bool_), g[1:] & g[:-1]])
                same = both_null | (both_ok & same)
            return same

        part_eq = jnp.concatenate([jnp.ones((1,), jnp.bool_),
                                   live[1:] == live[:-1]])
        for ch in self.partition_channels:
            part_eq = part_eq & eq_prev(ch)
        seg = W.segment_ids(part_eq)
        peer_eq = part_eq
        for ch, _, _ in self.order_keys:
            peer_eq = peer_eq & eq_prev(ch)
        peer = W.segment_ids(peer_eq)
        return data, seg, peer, live

    def _evaluate(self, data: Batch, presorted: bool = False) -> Batch:
        data, seg, peer, _live = self._sort_and_segment(data, presorted)
        out_cols = list(data.columns)
        for fn in self.functions:
            out_cols.append(self._eval_function(fn, data, seg, peer))
        return Batch(tuple(out_cols), data.num_rows)

    def _eval_function(self, fn: PlanWindowFunction, data: Batch,
                       seg, peer) -> Column:
        rt, vals, ok, d = eval_window_function(fn, data.columns, seg, peer)
        return Column(rt, vals, ok, d)

    def get_output(self) -> Optional[Batch]:
        if self._outputs:
            return self._outputs.pop(0)
        return None

    def is_finished(self) -> bool:
        return self._finishing and not self._outputs


class TopNRowNumberOperator(WindowOperator):
    """Fused ``row_number() OVER (partition ORDER BY ...) <= N``
    (TopNRowNumberOperator.java:38 role): sorts once by (partition,
    order), keeps only each partition's first N rows, and emits the row
    number with them — the filtered rows never materialize downstream."""

    def __init__(self, ctx: OperatorContext, factory:
                 "TopNRowNumberOperatorFactory"):
        super().__init__(ctx, factory.partition_channels,
                         factory.order_keys, [])
        self.limit = factory.limit
        self.rn_type = factory.rn_type

    def _evaluate(self, data: Batch, presorted: bool = False) -> Batch:
        import jax.numpy as jnp
        import numpy as np

        from presto_tpu.ops import window as W

        full, seg, _peer, live = self._sort_and_segment(data, presorted)
        rn = W.row_number(seg)
        keep = np.asarray(live & (rn <= self.limit))
        idx = np.nonzero(keep)[0]
        out = full.take(jnp.asarray(idx))
        rn_col = Column(self.rn_type,
                        jnp.asarray(rn)[jnp.asarray(idx)]
                        .astype(self.rn_type.np_dtype))
        return Batch(tuple(out.columns) + (rn_col,), len(idx))


class TopNRowNumberOperatorFactory(OperatorFactory):
    def __init__(self, partition_channels: Sequence[int],
                 order_keys: Sequence[Tuple[int, bool, Optional[bool]]],
                 limit: int, rn_type: T.Type):
        self.partition_channels = list(partition_channels)
        self.order_keys = list(order_keys)
        self.limit = limit
        self.rn_type = rn_type

    def create(self, ctx: OperatorContext) -> TopNRowNumberOperator:
        return TopNRowNumberOperator(ctx, self)


class WindowOperatorFactory(OperatorFactory):
    def __init__(self, partition_channels: Sequence[int],
                 order_keys: Sequence[Tuple[int, bool, Optional[bool]]],
                 functions: Sequence[PlanWindowFunction]):
        self.partition_channels = list(partition_channels)
        self.order_keys = list(order_keys)
        self.functions = list(functions)

    def create(self, ctx: OperatorContext) -> WindowOperator:
        return WindowOperator(ctx, self.partition_channels,
                              self.order_keys, self.functions)
