"""Grouped (bucket-by-bucket) join execution — P9, the Lifespan tier.

The reference bounds join memory by running co-bucketed fragments one
driver-group at a time (execution/Lifespan.java:26-38,
PlanFragmenter.analyzeGroupedExecution:146,
PipelineExecutionStrategy.GROUPED_EXECUTION): only 1/k of the build side
is resident at once.  Here the same contract is an operator-level
harness: when both join sides scan tables that the connector can
co-bucket on the join key (range buckets over the key domain), the join
runs bucket-sequentially — build bucket b, probe bucket b, release, next
— on a feeder thread, streaming joined batches to the consumer chain
through a bounded LocalExchange.  Peak HBM for the build side scales
with 1/k; the release is HashBuildOperatorFactory.release() (the
Lifespan-retirement hook).
"""

from __future__ import annotations

import threading
from typing import Callable, List, Optional, Sequence, Tuple

from presto_tpu.batch import Batch
from presto_tpu.connectors.api import Split
from presto_tpu.exec.context import OperatorContext
from presto_tpu.exec.driver import Pipeline
from presto_tpu.exec.localexchange import (
    LocalExchange, LocalExchangeSinkOperatorFactory,
)
from presto_tpu.exec.operator import Operator, OperatorFactory


class GroupedJoinSourceOperatorFactory(OperatorFactory):
    """Source operator that owns the bucket-sequential execution.

    ``buckets`` is a list of
    (build_factories, build_splits, probe_factories, probe_splits); the
    probe factory chain already ends with the LookupJoin for that
    bucket's build.  Each bucket's pipelines run to completion before
    the next bucket starts (the lifespan), with joined batches flowing
    out through a bounded exchange so downstream operators consume
    concurrently instead of buffering every bucket's output.
    """

    def __init__(self, buckets: Sequence[Tuple[List[OperatorFactory],
                                               List[Split],
                                               List[OperatorFactory],
                                               List[Split]]]):
        self.buckets = list(buckets)

    def create(self, ctx: OperatorContext) -> "GroupedJoinSourceOperator":
        return GroupedJoinSourceOperator(ctx, self)

    def reset_for_execution(self) -> None:
        # forward into every bucket's build/probe factory chains (they
        # hold the per-bucket lookup rendezvous)
        for build_fs, _bs, probe_fs, _ps in self.buckets:
            for f in list(build_fs) + list(probe_fs):
                f.reset_for_execution()


class GroupedJoinSourceOperator(Operator):
    def __init__(self, ctx: OperatorContext,
                 factory: GroupedJoinSourceOperatorFactory):
        super().__init__(ctx)
        self.f = factory
        # buckets run SEQUENTIALLY: they share ONE producer slot (a
        # strict round-robin consumer must never wait on a producer
        # that has not started) and the runner thread signals finish
        # once after the last lifespan
        self.exchange = LocalExchange(n_producers=1, capacity=8)
        self._thread: Optional[threading.Thread] = None
        self._error: Optional[BaseException] = None

    def _run_buckets(self) -> None:
        task = self.ctx.task
        try:
            for i, (bfs, bsplits, pfs, psplits) in enumerate(
                    self.f.buckets):
                build = Pipeline(bfs, bsplits, name=f"lifespan{i}.build")
                build.instantiate(task).run_to_completion()
                probe = Pipeline(
                    pfs + [LocalExchangeSinkOperatorFactory(
                        self.exchange, producer=0,
                        signal_finish=False)],
                    psplits, name=f"lifespan{i}.probe")
                # the probe driver's close releases this bucket's build
                # (HashBuildOperatorFactory.release) before the next
                # lifespan builds — the 1/k memory bound
                probe.instantiate(task).run_to_completion()
        except BaseException as e:  # noqa: BLE001 - crossed to consumer
            self._error = e
            self.exchange.fail(e)
        finally:
            self.exchange.producer_finished(0)

    def _ensure_started(self) -> None:
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._run_buckets, daemon=True,
                name=f"grouped-join-{self.ctx.name}")
            self._thread.start()

    def needs_input(self) -> bool:
        return False

    def get_output(self) -> Optional[Batch]:
        self._ensure_started()
        batch = self.exchange.poll()
        if batch is not None:
            self.ctx.stats.output_rows += batch.num_rows
        return batch

    def is_finished(self) -> bool:
        self._ensure_started()
        return self.exchange.drained()

    def close(self) -> None:
        super().close()
        if self._thread is not None:
            self.exchange.fail(RuntimeError("grouped join canceled"))
            self._thread.join(timeout=30)


def scan_column_for_channel(factories: Sequence[OperatorFactory],
                            channel: int) -> Optional[Tuple[object, str]]:
    """Trace an output channel of a factory chain back to its scan
    column through pure InputRef projections.  Returns
    (TableScanOperatorFactory, column_name) or None (the channel is
    computed, or the chain has no scan)."""
    from presto_tpu.exec.operators import (
        FilterProjectOperatorFactory, TableScanOperatorFactory,
    )
    from presto_tpu.expr.ir import InputRef

    ch = channel
    for f in reversed(list(factories)):
        if isinstance(f, FilterProjectOperatorFactory):
            if ch >= len(f.projections):
                return None
            p = f.projections[ch]
            if not isinstance(p, InputRef):
                return None
            ch = p.index
        elif isinstance(f, TableScanOperatorFactory):
            if ch >= len(f.columns):
                return None
            return f, f.columns[ch]
        else:
            return None
    return None
