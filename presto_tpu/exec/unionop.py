"""UNION ALL plumbing: N source pipelines feeding one consumer chain.

Reference model: the reference plans UNION as an ExchangeNode/LocalExchange
gathering multiple driver pipelines into one (LocalExchange.java:53 with
passthrough exchangers).  In the single-process runner the same rendezvous
is a shared buffer: each input branch runs as its own pipeline ending in a
``UnionSinkOperator``; the consuming pipeline starts with a
``UnionSourceOperator`` that drains the buffer.  Pipelines execute in
dependency order (the execute_pipelines contract), so all sinks finish
before the source starts — identical to how build sides rendezvous with
probes.
"""

from __future__ import annotations

from typing import List, Optional

from presto_tpu.batch import Batch
from presto_tpu.exec.context import OperatorContext
from presto_tpu.exec.operator import Operator, OperatorFactory


class UnionBuffer:
    """Shared rendezvous between sink pipelines and the source."""

    def __init__(self, n_sinks: int):
        self.n_sinks = n_sinks
        self.batches: List[Batch] = []
        self.remaining_sinks = n_sinks

    def reset(self) -> None:
        """Re-arm for another execution of the same plan (cached
        physical plans): remaining_sinks counted down to 0 last run and
        must rewind or the source would see an exhausted-or-negative
        sink count and never finish."""
        self.batches = []
        self.remaining_sinks = self.n_sinks


class UnionSinkOperator(Operator):
    def __init__(self, ctx: OperatorContext, buffer: UnionBuffer):
        super().__init__(ctx)
        self.buffer = buffer

    def add_input(self, batch: Batch) -> None:
        self.ctx.stats.input_rows += batch.num_rows
        self.buffer.batches.append(batch)

    def finish(self) -> None:
        if not self._finishing:
            self.buffer.remaining_sinks -= 1
        super().finish()

    def is_finished(self) -> bool:
        return self._finishing


class UnionSinkOperatorFactory(OperatorFactory):
    def __init__(self, buffer: UnionBuffer):
        self.buffer = buffer

    def create(self, ctx: OperatorContext) -> UnionSinkOperator:
        return UnionSinkOperator(ctx, self.buffer)

    def reset_for_execution(self) -> None:
        # idempotent: every sink factory and the source factory share
        # one buffer; the first reset re-arms it for all of them
        self.buffer.reset()


class UnionSourceOperator(Operator):
    def __init__(self, ctx: OperatorContext, buffer: UnionBuffer):
        super().__init__(ctx)
        self.buffer = buffer

    def needs_input(self) -> bool:
        return False

    def get_output(self) -> Optional[Batch]:
        if self.buffer.batches:
            batch = self.buffer.batches.pop(0)
            self.ctx.stats.output_rows += batch.num_rows
            return batch
        return None

    def is_finished(self) -> bool:
        return self.buffer.remaining_sinks == 0 and not self.buffer.batches


class UnionSourceOperatorFactory(OperatorFactory):
    def __init__(self, buffer: UnionBuffer):
        self.buffer = buffer

    def create(self, ctx: OperatorContext) -> UnionSourceOperator:
        return UnionSourceOperator(ctx, self.buffer)

    def reset_for_execution(self) -> None:
        self.buffer.reset()
