"""Driver: the innermost control loop.

A faithful port of the reference's control plane — Driver.processInternal
iterates adjacent operator pairs moving one batch per hop and propagates
finish (presto-main/.../operator/Driver.java:347,367-420) — because this
loop is hardware-agnostic glue.  What differs: a "page" hop hands off a
device array struct (kernel launch already queued asynchronously by jax),
so the host loop is the pipeline feeder, not the compute.

Pipelines (DriverFactory analogue) are instantiated per driver; the
single-process runner executes them in dependency order (build pipelines
before probe pipelines), which substitutes for the reference's
blocked-future dance on LookupSourceFactory.
"""

from __future__ import annotations

import time
from typing import List, Optional, Sequence

from presto_tpu.connectors.api import Split
from presto_tpu.exec.context import OperatorContext, TaskContext
from presto_tpu.exec.operator import Operator, OperatorFactory, SourceOperator


class Driver:
    def __init__(self, operators: Sequence[Operator],
                 pipeline_name: str = ""):
        self.operators = list(operators)
        self.pipeline_name = pipeline_name

    @property
    def source(self) -> Optional[SourceOperator]:
        op = self.operators[0]
        return op if isinstance(op, SourceOperator) else None

    def process(self) -> bool:
        """One scheduling quantum (Driver.processInternal).  Returns True if
        the driver is fully finished."""
        ops = self.operators
        moved = False
        for i in range(len(ops) - 1):
            current, nxt = ops[i], ops[i + 1]
            if not current.is_finished() and nxt.needs_input():
                t0 = time.perf_counter_ns()
                batch = current.get_output()
                current.ctx.stats.wall_ns += time.perf_counter_ns() - t0
                if batch is not None and batch.num_rows > 0:
                    t0 = time.perf_counter_ns()
                    nxt.add_input(batch)
                    nxt.ctx.stats.wall_ns += time.perf_counter_ns() - t0
                    moved = True
            if current.is_finished() and not nxt._finishing:
                t0 = time.perf_counter_ns()
                nxt.finish()
                nxt.ctx.stats.finish_wall_ns += time.perf_counter_ns() - t0
                moved = True
        # let the terminal operator drain even with no downstream
        return ops[-1].is_finished()

    def run_to_completion(self, max_iterations: int = 10_000_000,
                          deadline: Optional[float] = None) -> None:
        # Mirror Driver.close(): operators always release their resources
        # (memory reservations, exchange fetcher threads), success or not.
        try:
            for i in range(max_iterations):
                if self.process():
                    return
                # query_max_run_time enforcement between quanta (checked
                # sparsely — monotonic() per quantum is cheap but the
                # loop can spin fast on tiny batches)
                if deadline is not None and (i & 0xF) == 0 \
                        and time.monotonic() > deadline:
                    raise RuntimeError(
                        "Query exceeded maximum run time")
            raise RuntimeError(
                "driver did not converge (operator protocol bug)")
        finally:
            for op in self.operators:
                try:
                    op.close()
                except Exception:  # noqa: BLE001 - close is best-effort
                    pass
            self._record_driver_stats()

    def _record_driver_stats(self) -> None:
        """Append this run's DriverStats rollup to the TaskContext (the
        OperatorStats -> DriverStats -> TaskStats chain, SURVEY §5.1).
        Rows in = the source operator's output (what entered the chain);
        rows out = the terminal operator's output."""
        if not self.operators:
            return
        from presto_tpu.exec.context import DriverStats

        ops = self.operators
        ds = DriverStats(
            pipeline=self.pipeline_name, operators=len(ops),
            input_rows=ops[0].ctx.stats.output_rows,
            output_rows=ops[-1].ctx.stats.output_rows,
            wall_ns=sum(o.ctx.stats.wall_ns + o.ctx.stats.finish_wall_ns
                        for o in ops))
        ops[0].ctx.task.driver_stats.append(ds)


class Pipeline:
    """An ordered chain of operator factories (DriverFactory)."""

    def __init__(self, factories: Sequence[OperatorFactory],
                 splits: Sequence[Split] = (), name: str = "pipeline"):
        self.factories = list(factories)
        self.splits = list(splits)
        self.name = name

    def instantiate(self, task: TaskContext) -> Driver:
        ops: List[Operator] = []
        for i, f in enumerate(self.factories):
            ctx = OperatorContext(task, f"{self.name}.{i}.{f.name}")
            ops.append(f.create(ctx))
        driver = Driver(ops, pipeline_name=self.name)
        src = driver.source
        if src is not None:
            for s in self.splits:
                src.add_split(s)
            src.no_more_splits()
        return driver
