"""Leaf / streaming operators: scan, values, filter+project, limit, output.

Reference models: TableScanOperator.java:46, ValuesOperator.java:27,
FilterAndProjectOperator.java:38 (+ compiled PageProcessor), LimitOperator
.java:24, TaskOutputOperator.java:33.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from presto_tpu import types as T
from presto_tpu.batch import Batch, Column, next_bucket
from presto_tpu.connectors.api import Connector, Split
from presto_tpu.exec.context import OperatorContext
from presto_tpu.exec.operator import (
    Operator, OperatorFactory, SourceOperator, column_pairs, pad_batch,
)
from presto_tpu.expr.compile import ExprCompiler
from presto_tpu.expr.ir import RowExpression


class TableScanOperator(SourceOperator):
    """Pulls host batches from the connector PageSource and stages them to
    device (the LazyBlock-load + ConnectorPageSource.getNextPage path)."""

    def __init__(self, ctx: OperatorContext, connector: Connector,
                 columns: Sequence[str], batch_rows: int, to_device: bool):
        super().__init__(ctx)
        self.connector = connector
        self.columns = list(columns)
        self.batch_rows = batch_rows
        self.to_device = to_device
        self._splits: List[Split] = []
        self._no_more_splits = False
        self._iter = None

    def add_split(self, split: Split) -> None:
        self._splits.append(split)

    def no_more_splits(self) -> None:
        self._no_more_splits = True

    def needs_input(self) -> bool:
        return False

    def get_output(self) -> Optional[Batch]:
        while True:
            if self._iter is None:
                if not self._splits:
                    return None
                split = self._splits.pop(0)
                self._iter = iter(self.connector.page_source(
                    split, self.columns, self.batch_rows))
            try:
                batch = next(self._iter)
            except StopIteration:
                self._iter = None
                continue
            if batch.num_rows == 0:
                continue
            self.ctx.memory.set_bytes(batch.size_bytes)
            if self.to_device:
                return pad_batch(batch, self.ctx.config.min_batch_capacity)
            return batch

    def is_finished(self) -> bool:
        return (self._no_more_splits and not self._splits
                and self._iter is None) or self._finishing


class TableScanOperatorFactory(OperatorFactory):
    parallel_safe = True

    def __init__(self, connector: Connector, columns: Sequence[str],
                 batch_rows: int = 65536, to_device: bool = True,
                 table: str = ""):
        self.connector = connector
        self.columns = list(columns)
        self.batch_rows = batch_rows
        self.to_device = to_device
        self.table = table  # for grouped-execution bucket lookup

    def create(self, ctx: OperatorContext) -> TableScanOperator:
        return TableScanOperator(ctx, self.connector, self.columns,
                                 self.batch_rows, self.to_device)


class ValuesOperator(Operator):
    def __init__(self, ctx: OperatorContext, batches: Sequence[Batch]):
        super().__init__(ctx)
        self._batches = list(batches)

    def needs_input(self) -> bool:
        return False

    def get_output(self) -> Optional[Batch]:
        if self._batches:
            return self._batches.pop(0)
        return None

    def is_finished(self) -> bool:
        return not self._batches


class ValuesOperatorFactory(OperatorFactory):
    def __init__(self, batches: Sequence[Batch]):
        self.batches = list(batches)

    def create(self, ctx: OperatorContext) -> ValuesOperator:
        return ValuesOperator(ctx, self.batches)


from presto_tpu.kernelcache import cache_get as _cache_get
from presto_tpu.kernelcache import cache_put as _cache_put
from presto_tpu.kernelcache import new_cache as _new_cache
from presto_tpu.kernelcache import record_compile as _record_compile
from presto_tpu.kernelcache import timed_first_call as _timed_first_call

# Compiled filter/project kernels shared GLOBALLY across operator
# instances and queries (the reference's ExpressionCompiler/
# PageFunctionCompiler Guava caches, JoinCompiler-style): RowExpressions
# hash structurally and dictionaries are append-only with monotonic
# tokens, so a repeated query shape reuses the jitted program instead of
# re-tracing — on the TPU tunnel a retrace costs seconds per operator.

_FP_KERNELS = _new_cache("filter_project")
_FP_HOST = _new_cache("filter_project_host")


def dictionary_binding_key(columns) -> tuple:
    """Per-column dictionary-binding component of a kernel cache key.

    (content fingerprint, len) per dictionary column: equal CONTENT in
    equal order implies identical code semantics, so per-execution
    rebuilt dictionaries (deserialized exchange pages, concat-merged
    build sides) share compiled programs instead of churning one
    recompile per query — ``Dictionary.token`` remains the identity
    surface (never reused, unlike id()), but programs key on what they
    actually baked: entry content (per-entry lookup tables) and length
    (append-only growth guard).
    """
    return tuple(
        None if c.dictionary is None
        else (c.dictionary.content_key(), len(c.dictionary))
        for c in columns)


class FilterProjectOperator(Operator):
    """filter -> compact -> project, fused into one jitted XLA program per
    (expressions, capacity, dictionary-binding) — the PageProcessor
    replacement.

    The compiled program returns projected columns plus the selected-row
    count; intermediate selection vectors never leave the device.
    """

    def __init__(self, ctx: OperatorContext,
                 filter_expr: Optional[RowExpression],
                 projections: Sequence[RowExpression],
                 input_types: Sequence[T.Type]):
        super().__init__(ctx)
        self.filter_expr = filter_expr
        self.projections = list(projections)
        self.input_types = list(input_types)
        self._pending: Optional[Batch] = None
        self._expr_key = (filter_expr, tuple(projections),
                          tuple(input_types))
        from presto_tpu.expr.compile import needs_host_path

        # expressions are fixed for the operator's lifetime: decide the
        # host-vs-jit route once
        self._host_exprs = needs_host_path(
            [self.filter_expr] + self.projections)

    def needs_input(self) -> bool:
        return self._pending is None and not self._finishing

    def add_input(self, batch: Batch) -> None:
        self._pending = batch
        self.ctx.stats.input_batches += 1
        self.ctx.stats.input_rows += batch.num_rows

    def _kernel_for(self, batch: Batch):
        import jax

        dict_key = dictionary_binding_key(batch.columns)
        key = (self._expr_key, batch.capacity, dict_key)
        hit = _cache_get(_FP_KERNELS, key)
        if hit is not None:
            return hit
        self.ctx.stats.jit_compiles += 1
        import time as _time

        _t0 = _time.perf_counter_ns()
        compiler = ExprCompiler({i: c.dictionary
                                 for i, c in enumerate(batch.columns)
                                 if c.dictionary is not None})
        cfilter = (compiler.compile(self.filter_expr)
                   if self.filter_expr is not None else None)
        cprojs = [compiler.compile(p) for p in self.projections]
        cap = batch.capacity

        def kernel(cols, num_rows):
            import jax.numpy as jnp

            from presto_tpu.ops.filter import selected_positions

            if cfilter is not None:
                mask, mvalid = cfilter.run(cols, num_rows, jnp)
                idx, count = selected_positions(mask, mvalid, num_rows, cap)
                gathered = tuple(
                    (v[idx], None if valid is None else valid[idx])
                    for v, valid in cols)
            else:
                gathered, count = cols, num_rows
            outs = [p.run(gathered, count, jnp) for p in cprojs]
            return outs, count

        # expression-compile time lands now; the XLA trace+compile wall
        # of the jitted program lands on its first dispatch (wrapper)
        build_ns = _time.perf_counter_ns() - _t0
        self.ctx.stats.jit_compile_ns += build_ns
        _record_compile(_FP_KERNELS, build_ns)
        entry = (_timed_first_call(jax.jit(kernel), self.ctx.stats,
                                   _FP_KERNELS), cprojs)
        _cache_put(_FP_KERNELS, key, entry)
        return entry

    def _host_output(self, batch: Batch) -> Optional[Batch]:
        """Un-jitted path for nested-typed expressions (host Columns)."""
        import numpy as np

        from presto_tpu.expr.compile import (
            ExprCompiler, batch_pairs, result_column,
        )

        batch = batch.compact().to_numpy()
        # cache per dictionary binding (same policy as the jit kernels);
        # dictionaries are append-only so the binding stays valid and
        # per-call-site output dictionaries keep stable codes
        key = (self._expr_key, dictionary_binding_key(batch.columns))
        hit = _cache_get(_FP_HOST, key)
        if hit is None:
            compiler = ExprCompiler({i: c.dictionary
                                     for i, c in enumerate(batch.columns)
                                     if c.dictionary is not None})
            cfilter = (compiler.compile(self.filter_expr)
                       if self.filter_expr is not None else None)
            cprojs = [compiler.compile(p) for p in self.projections]
            hit = (cfilter, cprojs)
            _cache_put(_FP_HOST, key, hit)
        cfilter, cprojs = hit
        n = batch.num_rows
        if cfilter is not None:
            mask, mvalid = cfilter.run(batch_pairs(batch), n, np)
            keep = np.asarray(mask, bool)
            if mvalid is not None:
                keep = keep & np.asarray(mvalid)
            batch = batch.take(np.nonzero(keep[:n])[0])
            n = batch.num_rows
        pairs = batch_pairs(batch)
        cols = tuple(
            result_column(p, *p.run(pairs, n, np)) for p in cprojs)
        return Batch(cols, n)

    def get_output(self) -> Optional[Batch]:
        if self._pending is None:
            return None
        batch, self._pending = self._pending, None
        if (self._host_exprs
                or any(c.type.is_nested for c in batch.columns)):
            out = self._host_output(batch)
            n = out.num_rows
        else:
            jitted, cprojs = self._kernel_for(batch)
            self.ctx.stats.jit_dispatches += 1
            outs, count = jitted(tuple(column_pairs(batch)), batch.num_rows)
            n = int(count)
            cols = tuple(
                Column(p.type, v, valid, p.dictionary)
                for p, (v, valid) in zip(cprojs, outs))
            out = Batch(cols, n)
        self.ctx.stats.output_batches += 1
        self.ctx.stats.output_rows += n
        if n == 0:
            return None
        return out

    def is_finished(self) -> bool:
        return self._finishing and self._pending is None


class FilterProjectOperatorFactory(OperatorFactory):
    parallel_safe = True

    def __init__(self, filter_expr: Optional[RowExpression],
                 projections: Sequence[RowExpression],
                 input_types: Sequence[T.Type]):
        self.filter_expr = filter_expr
        self.projections = list(projections)
        self.input_types = list(input_types)

    def create(self, ctx: OperatorContext) -> FilterProjectOperator:
        return FilterProjectOperator(ctx, self.filter_expr, self.projections,
                                     self.input_types)


class LimitOperator(Operator):
    def __init__(self, ctx: OperatorContext, limit: int):
        super().__init__(ctx)
        self.remaining = limit
        self._pending: Optional[Batch] = None

    def needs_input(self) -> bool:
        return (self._pending is None and self.remaining > 0
                and not self._finishing)

    def add_input(self, batch: Batch) -> None:
        if batch.num_rows > self.remaining:
            batch = batch.head(self.remaining)
        self.remaining -= batch.num_rows
        self._pending = batch

    def get_output(self) -> Optional[Batch]:
        out, self._pending = self._pending, None
        return out

    def is_finished(self) -> bool:
        return (self.remaining == 0 or self._finishing) and \
            self._pending is None


class LimitOperatorFactory(OperatorFactory):
    def __init__(self, limit: int):
        self.limit = limit

    def create(self, ctx: OperatorContext) -> LimitOperator:
        return LimitOperator(ctx, self.limit)


class TableWriterOperator(Operator):
    """Write path terminal: streams batches into a connector PageSink and
    emits the committed row count at finish (the TableWriterOperator +
    TableFinishOperator pair, presto-main/.../operator/TableWriter
    Operator.java:58 / TableFinishOperator.java:46, fused — the engine's
    per-query writes are single-commit)."""

    def __init__(self, ctx: OperatorContext, sink):
        super().__init__(ctx)
        self.sink = sink
        self._rows: Optional[int] = None
        self._emitted = False

    def add_input(self, batch: Batch) -> None:
        self.ctx.stats.input_rows += batch.num_rows
        self.sink.append(batch)

    def finish(self) -> None:
        if not self._finishing:
            super().finish()
            self._rows = self.sink.finish()

    def get_output(self) -> Optional[Batch]:
        if self._rows is None or self._emitted:
            return None
        self._emitted = True
        from presto_tpu.batch import batch_from_pylist

        return batch_from_pylist([T.BIGINT], [(self._rows,)])

    def is_finished(self) -> bool:
        # terminal operator: the driver never pulls it, so emission of the
        # row-count batch is best-effort (read via rows_written instead)
        return self._finishing

    @property
    def rows_written(self) -> Optional[int]:
        return self._rows


class TableWriterOperatorFactory(OperatorFactory):
    def __init__(self, sink):
        self.sink = sink
        self.op: Optional[TableWriterOperator] = None

    def create(self, ctx: OperatorContext) -> TableWriterOperator:
        self.op = TableWriterOperator(ctx, self.sink)
        return self.op


class DistributedTableWriterOperator(Operator):
    """Worker half of a distributed write (P6): stream input into the
    connector's per-task STAGING sink and emit one (rows, fragment) row;
    nothing is visible to readers until the TableFinish commit
    (TableWriterOperator.java:58 under SCALED_WRITER_DISTRIBUTION)."""

    def __init__(self, ctx: OperatorContext, sink):
        super().__init__(ctx)
        self.sink = sink
        self._row: Optional[tuple] = None
        self._emitted = False

    def add_input(self, batch: Batch) -> None:
        self.ctx.stats.input_rows += batch.num_rows
        self.sink.append(batch)

    def finish(self) -> None:
        if not self._finishing:
            super().finish()
            rows = self.sink.finish()
            self._row = (rows, self.sink.fragment())

    def get_output(self) -> Optional[Batch]:
        if self._row is None or self._emitted:
            return None
        self._emitted = True
        from presto_tpu.batch import batch_from_pylist

        self.ctx.stats.output_rows += 1
        return batch_from_pylist([T.BIGINT, T.VARCHAR], [self._row])

    def is_finished(self) -> bool:
        return self._finishing and self._emitted


class DistributedTableWriterOperatorFactory(OperatorFactory):
    def __init__(self, registry, catalog: str, table: str, write_id: str,
                 task_tag: str):
        self.registry = registry
        self.catalog = catalog
        self.table = table
        self.write_id = write_id
        self.task_tag = task_tag

    def create(self, ctx: OperatorContext
               ) -> DistributedTableWriterOperator:
        conn = self.registry.get(self.catalog)
        handle = conn.get_table(self.table)
        sink = conn.task_sink(handle, self.write_id,
                              f"{self.task_tag}.{ctx.name}")
        return DistributedTableWriterOperator(ctx, sink)


class TableFinishOperator(Operator):
    """Commit half (TableFinishOperator.java:46): collects every writer
    task's (rows, fragment) row, publishes all fragments in ONE
    connector call (all-or-nothing), and emits the total row count."""

    def __init__(self, ctx: OperatorContext, registry, catalog: str,
                 table: str, write_id: str):
        super().__init__(ctx)
        self.registry = registry
        self.catalog = catalog
        self.table = table
        self.write_id = write_id
        self._rows = 0
        self._fragments: List[str] = []
        self._emitted = False
        self._committed = False

    def add_input(self, batch: Batch) -> None:
        self.ctx.stats.input_rows += batch.num_rows
        for rows, frag in batch.to_pylist():
            self._rows += int(rows)
            if frag is not None:
                self._fragments.append(frag)

    def finish(self) -> None:
        if self._finishing:
            return
        super().finish()
        conn = self.registry.get(self.catalog)
        handle = conn.get_table(self.table)
        conn.finish_write(handle, self.write_id, self._fragments)
        self._committed = True

    def get_output(self) -> Optional[Batch]:
        if not self._committed or self._emitted:
            return None
        self._emitted = True
        from presto_tpu.batch import batch_from_pylist

        self.ctx.stats.output_rows += 1
        return batch_from_pylist([T.BIGINT], [(self._rows,)])

    def is_finished(self) -> bool:
        return self._finishing and self._emitted


class TableFinishOperatorFactory(OperatorFactory):
    def __init__(self, registry, catalog: str, table: str, write_id: str):
        self.registry = registry
        self.catalog = catalog
        self.table = table
        self.write_id = write_id

    def create(self, ctx: OperatorContext) -> TableFinishOperator:
        return TableFinishOperator(ctx, self.registry, self.catalog,
                                   self.table, self.write_id)


class OutputCollector(Operator):
    """Terminal sink gathering result batches host-side
    (TaskOutputOperator / test MaterializedResult role)."""

    def __init__(self, ctx: OperatorContext):
        super().__init__(ctx)
        self.batches: List[Batch] = []

    def add_input(self, batch: Batch) -> None:
        if batch.num_rows:
            self.batches.append(batch.compact().to_numpy())
        self.ctx.stats.input_batches += 1
        self.ctx.stats.input_rows += batch.num_rows

    def is_finished(self) -> bool:
        return self._finishing

    def rows(self) -> List[tuple]:
        out: List[tuple] = []
        for b in self.batches:
            out.extend(b.to_pylist())
        return out


class OutputCollectorFactory(OperatorFactory):
    def __init__(self):
        self.collectors: List[OutputCollector] = []

    def create(self, ctx: OperatorContext) -> OutputCollector:
        c = OutputCollector(ctx)
        self.collectors.append(c)
        return c

    def reset_for_execution(self) -> None:
        # drop the previous execution's collected batches, or rows()
        # would accumulate across runs of a cached physical plan
        self.collectors = []

    def rows(self) -> List[tuple]:
        out = []
        for c in self.collectors:
            out.extend(c.rows())
        return out
