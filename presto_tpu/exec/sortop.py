"""ORDER BY / TopN operators (OrderByOperator.java:45, TopNOperator.java:35).

Both materialize (as the reference's PagesIndex does), run the device
sort-permutation kernel once, and gather.  TopN is the same kernel with a
truncated gather — a bounded-heap has no TPU advantage over a full
vectorized sort at these sizes.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence

from presto_tpu import types as T
from presto_tpu.batch import Batch, Column
from presto_tpu.exec.context import OperatorContext
from presto_tpu.exec.operator import Operator, OperatorFactory, device_concat


@dataclasses.dataclass(frozen=True)
class SortSpec:
    channel: int
    descending: bool = False
    nulls_first: bool = False


class OrderByOperator(Operator):
    def __init__(self, ctx: OperatorContext, specs: Sequence[SortSpec],
                 limit: Optional[int] = None):
        super().__init__(ctx)
        self.specs = list(specs)
        self.limit = limit
        self._batches: List[Batch] = []
        self._outputs: List[Batch] = []
        self._runs = []            # spilled sorted runs (FileSpiller each)
        self._accumulated_bytes = 0

    def add_input(self, batch: Batch) -> None:
        self._batches.append(batch)
        self.ctx.stats.input_rows += batch.num_rows
        self.ctx.memory.reserve(batch.size_bytes)
        self._accumulated_bytes += batch.size_bytes
        # byte threshold OR node-pool pressure (revoke-first: shed
        # revocable state before anyone blocks on the memory pool)
        if self.ctx.should_spill(self._accumulated_bytes):
            self._spill_run()

    def _sort_batches(self, batches: List[Batch]) -> Optional[Batch]:
        """Device sort of the concatenated batches (one run)."""
        import jax.numpy as jnp
        import numpy as np

        from presto_tpu.ops.sort import sort_permutation

        data = device_concat(batches, self.ctx.config.min_batch_capacity)
        if data is None:
            return None
        keys = []
        for s in self.specs:
            c = data.columns[s.channel]
            if c.type.is_dictionary:
                # order by lexicographic rank, computed host-side over the
                # dictionary (strings never sort on device)
                ranks = c.dictionary.sort_ranks()
                values = jnp.asarray(ranks)[c.values]
                keys.append((values, c.valid, T.INTEGER, s.descending,
                             s.nulls_first))
            else:
                keys.append((c.values, c.valid, c.type, s.descending,
                             s.nulls_first))
        perm = sort_permutation(keys, jnp.asarray(data.num_rows))
        cols = []
        for c in data.columns:
            if c.children:       # nested columns gather host-side
                cols.append(c.to_numpy().take(np.asarray(perm)))
            else:
                cols.append(Column(
                    c.type, c.values[perm],
                    None if c.valid is None else c.valid[perm],
                    c.dictionary))
        return Batch(tuple(cols), data.num_rows)

    def _spill_run(self) -> None:
        """External sort: sort the accumulated chunk on device, spill it as
        one sorted run (OrderByOperator's revocable path; runs are merged
        at finish like the reference's MergeSortedPages)."""
        from presto_tpu.exec.spill import FileSpiller

        run = self._sort_batches(self._batches)
        self._batches = []
        self._accumulated_bytes = 0
        self.ctx.memory.free()
        if run is None:
            return
        import numpy as np

        spiller = FileSpiller(self.ctx.config.spill_path,
                              tag=f"sort-{self.ctx.name}")
        step = max(1, self.ctx.config.scan_batch_rows)
        run = run.compact().to_numpy()
        for lo in range(0, run.num_rows, step):
            hi = min(lo + step, run.num_rows)
            spiller.spill(run.take(np.arange(lo, hi)))
        self._runs.append(spiller)

    def finish(self) -> None:
        if self._finishing:
            return
        super().finish()
        if not self._runs:
            out = self._sort_batches(self._batches)
            self._batches = []
            self.ctx.memory.free()
            if out is not None:
                n = out.num_rows if self.limit is None else min(
                    self.limit, out.num_rows)
                self._outputs.append(out.head(n))
                self.ctx.stats.output_rows += n
            return
        if self._batches:
            self._spill_run()
        self._merge_runs()

    def _merge_runs(self) -> None:
        """K-way merge of spilled sorted runs (MergeOperator.java:45 logic,
        host-side; output batches stream out bounded)."""
        import heapq

        import numpy as np

        from presto_tpu.batch import concat_batches
        from presto_tpu.ops.keys import to_sortable_i64

        def run_iter(spiller):
            for batch in spiller.read_all():
                yield batch.to_numpy()

        class _Rev:
            """Reverse-comparing wrapper for descending string keys."""

            __slots__ = ("v",)

            def __init__(self, v):
                self.v = v

            def __lt__(self, other):
                return other.v < self.v

            def __eq__(self, other):
                return self.v == other.v

        def batch_words(batch: Batch) -> List[np.ndarray]:
            words = []
            for s in self.specs:
                c = batch.columns[s.channel]
                if c.type.is_dictionary:
                    # Compare actual string values, not per-batch ranks:
                    # each spilled run re-codes into its own dictionary
                    # (concat_batches / per-shard scans), so equal codes or
                    # ranks from different runs denote different strings.
                    # The reference's MergeSortedPages likewise compares
                    # real values.
                    dic = np.asarray(c.dictionary.values, dtype=object)
                    w = dic[np.asarray(c.values)]
                    if s.descending:
                        w = np.array([_Rev(v) for v in w], dtype=object)
                else:
                    w = to_sortable_i64(np, np.asarray(c.values), c.type)
                    if s.descending:
                        w = ~w
                # Always emit the null word so key tuples stay structurally
                # comparable across runs (one run may have nulls in this
                # column while another does not).
                if c.valid is not None:
                    valid = np.asarray(c.valid)
                    null_word = np.where(
                        valid,
                        np.int8(1 if s.nulls_first else 0),
                        np.int8(0 if s.nulls_first else 1))
                    if w.dtype == object:
                        w = np.where(valid, w, "")
                    else:
                        w = np.where(valid, w, np.int64(0))
                else:
                    null_word = np.full(batch.num_rows,
                                        1 if s.nulls_first else 0, np.int8)
                words.append(null_word)
                words.append(w)
            return words

        iters = [run_iter(s) for s in self._runs]
        states = []  # per run: [batch, words, pos]
        heap = []
        for ri, it in enumerate(iters):
            batch = next(it, None)
            if batch is None:
                states.append(None)
                continue
            words = batch_words(batch)
            states.append([batch, words, 0])
            heap.append((tuple(w[0] for w in words), ri))
        heapq.heapify(heap)

        emitted = 0
        limit = self.limit
        # ordered emission: accumulate (batch, idx) picks in order, flush
        # as a Batch whenever the output step fills
        order: List[tuple] = []  # (batch, row_idx)
        step = max(1, self.ctx.config.scan_batch_rows)

        def flush():
            nonlocal order, emitted
            if not order:
                return
            groups: List[Batch] = []
            i = 0
            while i < len(order):
                batch = order[i][0]
                idxs = []
                while i < len(order) and order[i][0] is batch:
                    idxs.append(order[i][1])
                    i += 1
                groups.append(batch.take(np.asarray(idxs, np.int64)))
            merged = concat_batches(groups) if len(groups) > 1 else groups[0]
            if limit is not None and emitted + merged.num_rows > limit:
                merged = merged.head(limit - emitted)
            self._outputs.append(merged)
            self.ctx.stats.output_rows += merged.num_rows
            emitted += merged.num_rows
            order = []

        while heap:
            if limit is not None and emitted + len(order) >= limit:
                break
            _, ri = heapq.heappop(heap)
            batch, words, pos = states[ri]
            order.append((batch, pos))
            pos += 1
            if pos >= batch.num_rows:
                nxt = next(iters[ri], None)
                if nxt is None:
                    states[ri] = None
                else:
                    w = batch_words(nxt)
                    states[ri] = [nxt, w, 0]
                    heapq.heappush(heap, (tuple(x[0] for x in w), ri))
            else:
                states[ri][2] = pos
                heapq.heappush(heap,
                               (tuple(w[pos] for w in words), ri))
            if len(order) >= step:
                flush()
        flush()
        for s in self._runs:
            s.close()
        self._runs = []

    def close(self) -> None:
        super().close()
        for s in self._runs:
            try:
                s.close()
            except Exception:  # noqa: BLE001 - teardown is best-effort
                pass
        self._runs = []

    def get_output(self) -> Optional[Batch]:
        if not self._outputs:
            return None
        return self._outputs.pop(0)

    def is_finished(self) -> bool:
        return self._finishing and not self._outputs


class OrderByOperatorFactory(OperatorFactory):
    def __init__(self, specs: Sequence[SortSpec],
                 limit: Optional[int] = None):
        self.specs = list(specs)
        self.limit = limit

    def create(self, ctx: OperatorContext) -> OrderByOperator:
        return OrderByOperator(ctx, self.specs, self.limit)
