"""ORDER BY / TopN operators (OrderByOperator.java:45, TopNOperator.java:35).

Both materialize (as the reference's PagesIndex does), run the device
sort-permutation kernel once, and gather.  TopN is the same kernel with a
truncated gather — a bounded-heap has no TPU advantage over a full
vectorized sort at these sizes.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence

from presto_tpu import types as T
from presto_tpu.batch import Batch, Column
from presto_tpu.exec.context import OperatorContext
from presto_tpu.exec.operator import Operator, OperatorFactory, device_concat


@dataclasses.dataclass(frozen=True)
class SortSpec:
    channel: int
    descending: bool = False
    nulls_first: bool = False


class OrderByOperator(Operator):
    def __init__(self, ctx: OperatorContext, specs: Sequence[SortSpec],
                 limit: Optional[int] = None):
        super().__init__(ctx)
        self.specs = list(specs)
        self.limit = limit
        self._batches: List[Batch] = []
        self._output: Optional[Batch] = None

    def add_input(self, batch: Batch) -> None:
        self._batches.append(batch)
        self.ctx.stats.input_rows += batch.num_rows
        self.ctx.memory.reserve(batch.size_bytes)

    def finish(self) -> None:
        if self._finishing:
            return
        super().finish()
        import jax.numpy as jnp

        from presto_tpu.ops.sort import sort_permutation

        data = device_concat(self._batches, self.ctx.config.min_batch_capacity)
        self._batches = []
        self.ctx.memory.free()
        if data is None:
            return
        keys = []
        for s in self.specs:
            c = data.columns[s.channel]
            if c.type.is_dictionary:
                # order by lexicographic rank, computed host-side over the
                # dictionary (strings never sort on device)
                ranks = c.dictionary.sort_ranks()
                values = jnp.asarray(ranks)[c.values]
                keys.append((values, c.valid, T.INTEGER, s.descending,
                             s.nulls_first))
            else:
                keys.append((c.values, c.valid, c.type, s.descending,
                             s.nulls_first))
        perm = sort_permutation(keys, jnp.asarray(data.num_rows))
        n = data.num_rows if self.limit is None else min(self.limit,
                                                         data.num_rows)
        cols = tuple(
            Column(c.type, c.values[perm],
                   None if c.valid is None else c.valid[perm], c.dictionary)
            for c in data.columns)
        self._output = Batch(cols, n)
        self.ctx.stats.output_rows += n

    def get_output(self) -> Optional[Batch]:
        out, self._output = self._output, None
        return out

    def is_finished(self) -> bool:
        return self._finishing and self._output is None


class OrderByOperatorFactory(OperatorFactory):
    def __init__(self, specs: Sequence[SortSpec],
                 limit: Optional[int] = None):
        self.specs = list(specs)
        self.limit = limit

    def create(self, ctx: OperatorContext) -> OrderByOperator:
        return OrderByOperator(ctx, self.specs, self.limit)
