"""Operator protocol + shared device-batch plumbing.

The contract is the reference's Operator SPI verbatim
(presto-main/.../operator/Operator.java:20-102):

    needs_input() / add_input(batch) / get_output() / finish() /
    is_finished()

kept because the *control plane* of a pull/push pipeline is
hardware-agnostic; what changes on TPU is that each operator's data plane
is a jitted XLA program over padded static shapes.  ``accumulate``-style
operators (agg, join build, sort) materialize their input exactly like the
reference's PagesIndex-backed operators do, then run one kernel at finish.

``device_concat`` / ``pad_columns`` implement the padding-bucket policy
(SURVEY §7 hard part #1): every kernel sees power-of-two row capacities so
XLA compiles a small, reusable set of programs per query shape.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import numpy as np

from presto_tpu import types as T
from presto_tpu.batch import Batch, Column, next_bucket
from presto_tpu.exec.context import OperatorContext


class Operator:
    """One physical operator instance (single driver)."""

    def __init__(self, ctx: OperatorContext):
        self.ctx = ctx
        self._finishing = False

    # -- control protocol (reference-identical) -------------------------
    def needs_input(self) -> bool:
        return not self._finishing

    def add_input(self, batch: Batch) -> None:
        raise NotImplementedError

    def get_output(self) -> Optional[Batch]:
        return None

    def finish(self) -> None:
        """No more input will arrive (Operator.finish)."""
        self._finishing = True

    def is_finished(self) -> bool:
        raise NotImplementedError

    def close(self) -> None:
        self.ctx.memory.free()


class OperatorFactory:
    """Creates per-driver Operator instances
    (reference OperatorFactory; duplicated per driver for parallelism)."""

    def create(self, ctx: OperatorContext) -> Operator:
        raise NotImplementedError

    @property
    def name(self) -> str:
        return type(self).__name__.replace("Factory", "")


class SourceOperator(Operator):
    """An operator at pipeline position 0 fed by splits, not batches
    (reference SourceOperator; split delivery is the scheduler's job)."""

    def add_split(self, split) -> None:
        raise NotImplementedError

    def no_more_splits(self) -> None:
        raise NotImplementedError


# ---------------------------------------------------------------------------
# Device-batch helpers
# ---------------------------------------------------------------------------

def rebucket(batch: Batch, min_capacity: int = 1024) -> Batch:
    """Re-pad a sparsely occupied batch down to its capacity bucket.

    Expansion-sized join/filter outputs otherwise amplify capacity
    multiplicatively down an operator chain (126 live rows riding a
    67M-row padded batch after 5 joins); two static-shape device copies
    (slice + zero-pad) reset the invariant.
    """
    cap = next_bucket(batch.num_rows, min_capacity)
    if batch.capacity <= cap:
        return batch
    return batch.head(batch.num_rows).pad_rows(cap)


def pad_batch(batch: Batch, min_capacity: int = 1024) -> Batch:
    """Pad to the power-of-two bucket and move to device."""
    cap = next_bucket(batch.num_rows, min_capacity)
    return batch.pad_rows(cap).to_device()


def device_concat(batches: Sequence[Batch], min_capacity: int = 1024) -> Batch:
    """Concatenate batches into one padded device Batch.

    Dictionary columns are re-coded into a shared dictionary host-side
    first (cheap: dictionary sizes << row counts)."""
    import jax.numpy as jnp

    from presto_tpu.batch import concat_batches

    live = [b for b in batches if b.num_rows > 0]
    if not live:
        return None
    if len(live) == 1:
        return pad_batch(live[0].compact(), min_capacity)
    # host-side concat handles dictionary merging; arrays may be device or
    # numpy — normalize host-side, then stage once.
    merged = concat_batches([b.to_numpy() for b in live])
    return pad_batch(merged, min_capacity)


def column_pairs(batch: Batch) -> List[Tuple[object, object]]:
    return [(c.values, c.valid) for c in batch.columns]
