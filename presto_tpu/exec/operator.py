"""Operator protocol + shared device-batch plumbing.

The contract is the reference's Operator SPI verbatim
(presto-main/.../operator/Operator.java:20-102):

    needs_input() / add_input(batch) / get_output() / finish() /
    is_finished()

kept because the *control plane* of a pull/push pipeline is
hardware-agnostic; what changes on TPU is that each operator's data plane
is a jitted XLA program over padded static shapes.  ``accumulate``-style
operators (agg, join build, sort) materialize their input exactly like the
reference's PagesIndex-backed operators do, then run one kernel at finish.

``device_concat`` / ``pad_columns`` implement the padding-bucket policy
(SURVEY §7 hard part #1): every kernel sees power-of-two row capacities so
XLA compiles a small, reusable set of programs per query shape.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import numpy as np

from presto_tpu import types as T
from presto_tpu.batch import Batch, Column, next_bucket
from presto_tpu.exec.context import OperatorContext


class Operator:
    """One physical operator instance (single driver)."""

    def __init__(self, ctx: OperatorContext):
        self.ctx = ctx
        self._finishing = False

    # -- control protocol (reference-identical) -------------------------
    def needs_input(self) -> bool:
        return not self._finishing

    def add_input(self, batch: Batch) -> None:
        raise NotImplementedError

    def get_output(self) -> Optional[Batch]:
        return None

    def finish(self) -> None:
        """No more input will arrive (Operator.finish)."""
        self._finishing = True

    def is_finished(self) -> bool:
        raise NotImplementedError

    def close(self) -> None:
        self.ctx.memory.free()


class OperatorFactory:
    """Creates per-driver Operator instances
    (reference OperatorFactory; duplicated per driver for parallelism).

    ``parallel_safe`` marks row-local factories (scan, filter/project,
    unnest, dynamic filter) whose operators may replicate into N
    concurrent feed drivers without changing results — the
    AddLocalExchanges eligibility bit."""

    parallel_safe = False

    def create(self, ctx: OperatorContext) -> Operator:
        raise NotImplementedError

    def reset_for_execution(self) -> None:
        """Clear cross-execution factory state so a cached PhysicalPlan
        can be re-executed (the plan-cache physical-factory sharing
        path).  Most factories keep all runtime state in the Operators
        they create and need nothing; factories that rendezvous ACROSS
        pipelines (output collector, union buffer, build sides) override
        to re-arm their shared state."""

    @property
    def name(self) -> str:
        return type(self).__name__.replace("Factory", "")


class SourceOperator(Operator):
    """An operator at pipeline position 0 fed by splits, not batches
    (reference SourceOperator; split delivery is the scheduler's job)."""

    def add_split(self, split) -> None:
        raise NotImplementedError

    def no_more_splits(self) -> None:
        raise NotImplementedError


# ---------------------------------------------------------------------------
# Device-batch helpers
# ---------------------------------------------------------------------------

def rebucket(batch: Batch, min_capacity: int = 1024) -> Batch:
    """Re-pad a sparsely occupied batch down to its capacity bucket.

    Expansion-sized join/filter outputs otherwise amplify capacity
    multiplicatively down an operator chain (126 live rows riding a
    67M-row padded batch after 5 joins); two static-shape device copies
    (slice + zero-pad) reset the invariant.
    """
    cap = next_bucket(batch.num_rows, min_capacity)
    if batch.capacity <= cap:
        return batch
    return batch.head(batch.num_rows).pad_rows(cap)


def pad_batch(batch: Batch, min_capacity: int = 1024) -> Batch:
    """Pad to the power-of-two bucket and move to device."""
    cap = next_bucket(batch.num_rows, min_capacity)
    return batch.pad_rows(cap).to_device()


def device_concat(batches: Sequence[Batch], min_capacity: int = 1024) -> Batch:
    """Concatenate batches into one padded device Batch.

    Fast path: when every dictionary column shares one dictionary object
    across batches (connector-interned dictionaries) and nothing is
    nested, the concat runs as ONE cached jitted device program —
    downloading every batch to the host first costs a device read per
    column per batch, which dominates aggregation finish on
    remote-attached TPUs.  Otherwise dictionary columns are re-coded
    into a shared dictionary host-side (cheap: dictionary sizes << row
    counts)."""
    from presto_tpu.batch import concat_batches

    live = [b for b in batches if b.num_rows > 0]
    if not live:
        return None
    if len(live) == 1:
        return pad_batch(live[0].compact(), min_capacity)
    fast = _device_concat_fast(live, min_capacity)
    if fast is not None:
        return fast
    # host-side concat handles dictionary merging; arrays may be device or
    # numpy — normalize host-side, then stage once.
    merged = concat_batches([b.to_numpy() for b in live])
    return pad_batch(merged, min_capacity)


from presto_tpu.kernelcache import new_cache as _new_cache

_CONCAT_PROGRAMS = _new_cache("device_concat")


def _device_concat_fast(live: Sequence[Batch],
                        min_capacity: int) -> Optional[Batch]:
    import numpy as np

    from presto_tpu.batch import Batch as _B
    from presto_tpu.batch import Column, next_bucket

    ncols = len(live[0].columns)
    for b in live:
        for ci, c in enumerate(b.columns):
            if c.type.is_nested:
                return None
            if (c.dictionary is not None
                    and c.dictionary is not live[0].columns[ci].dictionary):
                return None
            if isinstance(c.values, np.ndarray):
                return None  # host batch: the host path is already cheap
    total = sum(b.num_rows for b in live)
    out_cap = next_bucket(total, min_capacity)
    # gather indices into the concatenation of the full (padded) arrays;
    # counts are host ints so this is pure numpy
    idx = np.zeros(out_cap, np.int32)
    off = 0
    base = 0
    for b in live:
        idx[off:off + b.num_rows] = base + np.arange(b.num_rows,
                                                     dtype=np.int32)
        off += b.num_rows
        base += b.capacity
    caps = tuple(b.capacity for b in live)
    has_valid = tuple(
        any(b.columns[ci].valid is not None for b in live)
        for ci in range(ncols))
    dtypes = tuple(str(live[0].columns[ci].values.dtype)
                   for ci in range(ncols))
    key = (caps, out_cap, has_valid, dtypes)
    from presto_tpu.kernelcache import cache_get, cache_put

    fn = cache_get(_CONCAT_PROGRAMS, key)
    if fn is None:
        import jax
        import jax.numpy as jnp

        def kernel(cols_per_batch, valids_per_batch, gather_idx):
            outs = []
            for ci2 in range(len(cols_per_batch[0])):
                cat = jnp.concatenate(
                    [cb[ci2] for cb in cols_per_batch])
                out_v = cat[gather_idx]
                if valids_per_batch[0][ci2] is not None:
                    vcat = jnp.concatenate(
                        [vb[ci2] for vb in valids_per_batch])
                    outs.append((out_v, vcat[gather_idx]))
                else:
                    outs.append((out_v, None))
            return tuple(outs)

        fn = jax.jit(kernel)
        cache_put(_CONCAT_PROGRAMS, key, fn, cap=128)
    cols_per_batch = tuple(
        tuple(b.columns[ci].values for ci in range(ncols)) for b in live)
    valids_per_batch = tuple(
        tuple((b.columns[ci].valid if b.columns[ci].valid is not None
               else np.ones(b.capacity, bool)) if has_valid[ci] else None
              for ci in range(ncols))
        for b in live)
    outs = fn(cols_per_batch, valids_per_batch, idx)
    cols = tuple(
        Column(live[0].columns[ci].type, v, valid,
               live[0].columns[ci].dictionary)
        for ci, (v, valid) in enumerate(outs))
    return _B(cols, total)


def column_pairs(batch: Batch) -> List[Tuple[object, object]]:
    return [(c.values, c.valid) for c in batch.columns]
