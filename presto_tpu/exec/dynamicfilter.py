"""Dynamic filtering: build-side key domains prune the probe early.

Reference model: DynamicFilterSourceOperator collects build-side join-key
values into runtime filters that LocalDynamicFilter applies on the probe
scan (presto-main/.../operator/DynamicFilterSourceOperator.java:46,
sql/planner/LocalDynamicFilter.java:45, sql/DynamicFilters.java).

Here the build side always completes before the probe pipeline starts
(the single-process rendezvous), so the filter is synchronously ready:
``HashBuildOperator`` fills a ``DynamicFilter`` with per-key min/max and —
for small builds — the exact distinct key set, and a
``DynamicFilterOperator`` inserted before the probe's LookupJoin drops
non-matching rows with one vectorized mask+gather instead of letting them
reach the join kernel.  (The reference pushes to the scan itself; applying
at the probe-join input is the same work saved for every operator above
this point — channel provenance to the scan is a later refinement.)

Dictionary-coded keys are skipped: probe and build dictionaries intern
independently, so code-domain comparisons would be meaningless.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np

from presto_tpu import types as T
from presto_tpu.batch import Batch, Column
from presto_tpu.exec.context import OperatorContext
from presto_tpu.exec.operator import Operator, OperatorFactory
from presto_tpu.kernelcache import (
    cache_get, cache_put, new_cache, record_compile, timed_first_call,
)

# jitted dynamic-filter programs, shared across queries (values are
# arguments, not constants — see _kernel_for)
_DF_KERNELS = new_cache("dynamic_filter")

# exact-set filtering only below this many distinct build keys
MAX_DISTINCT_SET = 4096


class DynamicFilter:
    """Per-join runtime filter, one entry per equi-key channel."""

    def __init__(self, n_keys: int):
        self.ready = False
        self.mins: List[Optional[np.ndarray]] = [None] * n_keys
        self.maxs: List[Optional[np.ndarray]] = [None] * n_keys
        self.sets: List[Optional[np.ndarray]] = [None] * n_keys
        self.build_empty = False
        self.disabled = False    # spilled build: pass everything through

    def disable(self) -> None:
        self.disabled = True
        self.ready = True

    def fill_from_build(self, data: Optional[Batch],
                        key_channels: Sequence[int]) -> None:
        if data is None or data.num_rows == 0:
            self.build_empty = True
            self.ready = True
            return
        for i, ch in enumerate(key_channels):
            col = data.columns[ch]
            if col.type.is_dictionary or col.type.name == "boolean":
                continue  # incomparable domains / trivial
            vals = np.asarray(col.values)[:data.num_rows]
            if col.valid is not None:
                vals = vals[np.asarray(col.valid)[:data.num_rows]]
            if vals.size == 0:
                self.build_empty = True
                continue
            self.mins[i] = vals.min()
            self.maxs[i] = vals.max()
            uniq = np.unique(vals)
            if uniq.size <= MAX_DISTINCT_SET:
                self.sets[i] = uniq
        self.ready = True


class DynamicFilterOperator(Operator):
    def __init__(self, ctx: OperatorContext, dyn: DynamicFilter,
                 key_channels: Sequence[int]):
        super().__init__(ctx)
        self.dyn = dyn
        self.key_channels = list(key_channels)
        self._pending: Optional[Batch] = None
        # adaptive shutoff (the reference disables ineffective dynamic
        # filters): stop filtering once observed selectivity is poor —
        # un-pruned rows cost nothing extra in static-shape kernels, but
        # each filter application costs a device round-trip
        self._rows_seen = 0
        self._rows_kept = 0
        self._adaptive_off = False

    def needs_input(self) -> bool:
        return not self._finishing and self._pending is None

    def _filters(self):
        out = []
        for i, ch in enumerate(self.key_channels):
            if self.dyn.mins[i] is None:
                continue
            out.append((ch, np.asarray(self.dyn.mins[i]),
                        np.asarray(self.dyn.maxs[i]),
                        self.dyn.sets[i]))
        return out

    def _kernel_for(self, batch: Batch, filters):
        """One jitted mask+compact program per (capacity, filter shape),
        shared GLOBALLY across queries: the bounds and IN-set tables are
        passed as arguments, never baked in as constants, so a new
        query's dynamic-filter values reuse the compiled program (eager
        per-batch dispatch and retraces dominate on remote-attached
        devices)."""
        import jax

        cap = batch.capacity
        chans = tuple(ch for ch, _, _, _ in filters)
        has_set = tuple(st is not None for _, _, _, st in filters)
        key = (cap, chans, has_set)
        hit = cache_get(_DF_KERNELS, key)
        if hit is not None:
            return hit
        self.ctx.stats.jit_compiles += 1
        import time as _time

        _t0 = _time.perf_counter_ns()
        import jax.numpy as jnp

        from presto_tpu.ops.filter import selected_positions

        def kernel(cols, num_rows, bounds, tables):
            mask = jnp.ones(cap, bool)
            ti = 0
            for k, ch in enumerate(chans):
                v, valid = cols[ch]
                mn, mx = bounds[k]
                m = (v >= mn.astype(v.dtype)) & (v <= mx.astype(v.dtype))
                if has_set[k]:
                    table = tables[ti].astype(v.dtype)
                    ti += 1
                    idx = jnp.clip(jnp.searchsorted(table, v), 0,
                                   table.shape[0] - 1)
                    m = m & (table[idx] == v)
                if valid is not None:
                    m = m & valid
                mask = mask & m
            idx, count = selected_positions(mask, None, num_rows, cap)
            gathered = tuple(
                (v[idx], None if valid is None else valid[idx])
                for v, valid in cols)
            return gathered, count

        build_ns = _time.perf_counter_ns() - _t0
        self.ctx.stats.jit_compile_ns += build_ns
        record_compile(_DF_KERNELS, build_ns)
        jitted = timed_first_call(jax.jit(kernel), self.ctx.stats,
                                  _DF_KERNELS)
        cache_put(_DF_KERNELS, key, jitted)
        return jitted

    def add_input(self, batch: Batch) -> None:
        self.ctx.stats.input_rows += batch.num_rows
        if (not self.dyn.ready or self.dyn.disabled
                or self._adaptive_off):
            self._pending = batch  # no filter info: pass through
            return
        if self.dyn.build_empty:
            return  # inner join against empty build: nothing survives
        if any(c.type.is_nested for c in batch.columns):
            self._pending = batch  # nested payloads: pass through
            return
        filters = self._filters()
        if not filters:
            self._pending = batch
            return
        kernel = self._kernel_for(batch, filters)
        from presto_tpu.exec.operator import column_pairs

        self.ctx.stats.jit_dispatches += 1
        bounds = tuple((mn, mx) for _, mn, mx, _ in filters)
        tables = tuple(st for _, _, _, st in filters if st is not None)
        outs, count = kernel(tuple(column_pairs(batch)), batch.num_rows,
                             bounds, tables)
        n_keep = int(count)
        self._rows_seen += batch.num_rows
        self._rows_kept += n_keep
        if self._rows_seen >= 4096 and \
                self._rows_kept > 0.95 * self._rows_seen:
            self._adaptive_off = True
        if n_keep == batch.num_rows:
            self._pending = batch
        elif n_keep > 0:
            cols = tuple(
                Column(c.type, v, valid, c.dictionary)
                for c, (v, valid) in zip(batch.columns, outs))
            self._pending = Batch(cols, n_keep)
        # else: fully pruned, emit nothing
        self.ctx.stats.output_rows += n_keep

    def get_output(self) -> Optional[Batch]:
        out, self._pending = self._pending, None
        return out

    def is_finished(self) -> bool:
        return self._finishing and self._pending is None


class DynamicFilterOperatorFactory(OperatorFactory):
    parallel_safe = True

    def __init__(self, dyn: DynamicFilter, key_channels: Sequence[int]):
        self.dyn = dyn
        self.key_channels = list(key_channels)

    def create(self, ctx: OperatorContext) -> DynamicFilterOperator:
        return DynamicFilterOperator(ctx, self.dyn, self.key_channels)
