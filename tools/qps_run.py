#!/usr/bin/env python
"""Sustained-QPS load plane: drive a live cluster with concurrent
clients and measure latency under contention.

The CLI face of the serving tier (server/dispatcher.py +
sql/plancache.py): boots a real in-process DistributedQueryRunner
(coordinator + workers + HTTP exchanges), then drives a mixed
TPC-H/TPC-DS statement set from N concurrent clients — each with its
own StatementClient and its own user (so resource-group admission is
actually engaged) — and reports QPS, p50/p95/p99 latency, per-client
exact-rows parity against a single-threaded oracle run, and the plan
cache's hit rate:

    JAX_PLATFORMS=cpu python tools/qps_run.py --levels 1,2,4,8
    JAX_PLATFORMS=cpu python tools/qps_run.py --mode open --rate 20
    JAX_PLATFORMS=cpu python tools/qps_run.py --check

Modes:

- ``closed`` (default): each client issues its next statement the
  moment the previous one returns — N in-flight requests, throughput-
  bound (the dashboard-fleet shape);
- ``open``: statements arrive on a fixed schedule (``--rate`` per
  second) regardless of completions, and latency is measured from
  *arrival* — queueing delay under overload is visible (the
  million-users shape).

``--hot`` swaps in the hot-repeat mix (every statement repeated
verbatim — the dashboard-refresh shape) and ``--result-cache`` turns
the cross-query result cache (server/resultcache.py) on for the
cluster; result-cache hit-rate and bytes-served-from-cache are
reported per level beside the plan-cache hit rate either way.

``--check`` is the CI smoke tier: tiny scale, 2 concurrency levels,
exits nonzero unless every client saw exact rows AND the plan cache
recorded hits AND the repeated statement's second execution compiled
nothing — then a hot-repeat run with the result cache on must show
nonzero result-cache hits with exact rows and a result-cache-served
second execution.

Exit code 0 = all levels parity-clean (and --check assertions hold).
"""

import argparse
import json
import os
import queue
import sys
import threading
import time
import urllib.request

# runnable from anywhere: `python tools/qps_run.py` puts tools/ on the
# path, not the repo root (same shim as chaos_run.py)
sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))

#: the mixed statement set: TPC-H aggregations + joins and TPC-DS
#: aggregations + joins, each cheap enough to repeat under load, plus a
#: parameter-bound prepared statement (the EXECUTE plan-cache path).
STATEMENTS = [
    ("tpch_q6ish",
     "select sum(l_extendedprice * l_discount) as revenue "
     "from tpch.lineitem "
     "where l_discount between 0.05 and 0.07 and l_quantity < 24"),
    ("tpch_q1_lite",
     "select l_returnflag, l_linestatus, sum(l_quantity) as sum_qty, "
     "count(*) as cnt from tpch.lineitem "
     "group by l_returnflag, l_linestatus "
     "order by l_returnflag, l_linestatus"),
    ("tpch_nation_join",
     "select n_name, count(*) as c from tpch.customer, tpch.nation "
     "where c_nationkey = n_nationkey "
     "group by n_name order by c desc, n_name"),
    ("tpcds_store_agg",
     "select ss_store_sk, count(*) as c, sum(ss_net_paid) as paid "
     "from tpcds.store_sales group by ss_store_sk order by ss_store_sk"),
    ("tpcds_item_join",
     "select i_class, count(*) as c "
     "from tpcds.store_sales, tpcds.item "
     "where ss_item_sk = i_item_sk "
     "group by i_class order by c desc, i_class"),
]

PREPARE_SQL = ("prepare qps_param from select count(*) as c "
               "from tpch.lineitem where l_quantity < ?")
EXECUTE_SQL = "execute qps_param using 10"

#: the hot-repeat mix (``--hot``): two statements repeated verbatim —
#: the dashboard-refresh shape the cross-query result cache
#: (server/resultcache.py) exists for.  After each statement's first
#: execution every repeat is a cache hit served from spool pages.
HOT_STATEMENTS = ["tpch_q1_lite", "tpcds_store_agg"]


def _norm_rows(rows):
    """Order-insensitive, float-tolerant row normalization for the
    exact-rows parity check."""
    return sorted(tuple(round(v, 6) if isinstance(v, float) else v
                        for v in r) for r in rows)


def _percentile(sorted_vals, q):
    if not sorted_vals:
        return 0.0
    i = min(len(sorted_vals) - 1, int(round(q * (len(sorted_vals) - 1))))
    return sorted_vals[i]


def _client_worklist(n_requests, offset, hot=False):
    """The statement sequence one client walks: the shared mix, rotated
    per client so concurrent clients overlap on every statement (the
    plan-cache contention case) without issuing in lockstep.  ``hot``
    walks the tiny HOT_STATEMENTS mix instead — every statement repeats
    verbatim, the result-cache case."""
    names = (HOT_STATEMENTS if hot
             else [name for name, _ in STATEMENTS] + ["tpch_execute"])
    return [names[(offset + j) % len(names)] for j in range(n_requests)]


class _Oracle:
    """Single-threaded expected rows per statement name."""

    def __init__(self, dqr):
        client = dqr.new_client(user="oracle")
        client.execute(PREPARE_SQL)
        self.rows = {}
        for name, sql in STATEMENTS:
            self.rows[name] = _norm_rows(dqr.execute(sql).rows)
        cols, data = client.execute(EXECUTE_SQL)
        self.rows["tpch_execute"] = _norm_rows([tuple(r) for r in data])
        self.sql = dict(STATEMENTS)
        self.sql["tpch_execute"] = EXECUTE_SQL


def _run_one(client, oracle, name):
    """Issue one statement; returns (latency_s, parity_ok)."""
    t0 = time.perf_counter()
    _cols, data = client.execute(oracle.sql[name])
    lat = time.perf_counter() - t0
    ok = _norm_rows([tuple(r) for r in data]) == oracle.rows[name]
    return lat, ok


def run_closed_level(dqr, oracle, concurrency, requests_per_client,
                     n_users=2, hot=False):
    """Closed loop: N clients, each back-to-back through its worklist."""
    lock = threading.Lock()
    lats, mismatches, errors = [], [], []

    def client_loop(i):
        client = dqr.new_client(user=f"client{i % n_users}")
        try:
            client.execute(PREPARE_SQL)
            for name in _client_worklist(requests_per_client, i, hot):
                lat, ok = _run_one(client, oracle, name)
                with lock:
                    lats.append(lat)
                    if not ok:
                        mismatches.append((i, name))
        except Exception as e:  # noqa: BLE001 - reported in the result
            with lock:
                errors.append(f"client{i}: {type(e).__name__}: {e}")

    threads = [threading.Thread(target=client_loop, args=(i,),
                                daemon=True, name=f"qps-client-{i}")
               for i in range(concurrency)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    return _level_report(concurrency, lats, wall, mismatches, errors,
                         mode="closed")


def run_open_level(dqr, oracle, concurrency, rate_per_s, n_requests,
                   n_users=2, hot=False):
    """Open loop: arrivals on a fixed schedule; latency counts from
    scheduled arrival (queueing under overload is visible).  A pool of
    ``concurrency`` workers drains the arrival queue."""
    lock = threading.Lock()
    lats, mismatches, errors = [], [], []
    work: "queue.Queue" = queue.Queue()
    start = time.perf_counter() + 0.05
    for j, name in enumerate(_client_worklist(n_requests, 0, hot)):
        work.put((start + j / rate_per_s, name))

    def worker(i):
        client = dqr.new_client(user=f"client{i % n_users}")
        try:
            client.execute(PREPARE_SQL)
        except Exception as e:  # noqa: BLE001
            with lock:
                errors.append(f"client{i}: {e}")
            return
        while True:
            try:
                arrival, name = work.get_nowait()
            except queue.Empty:
                return
            now = time.perf_counter()
            if now < arrival:
                time.sleep(arrival - now)
            try:
                _lat, ok = _run_one(client, oracle, name)
                done = time.perf_counter()
                with lock:
                    lats.append(done - arrival)   # includes queue wait
                    if not ok:
                        mismatches.append((i, name))
            except Exception as e:  # noqa: BLE001
                with lock:
                    errors.append(f"client{i}: {type(e).__name__}: {e}")

    threads = [threading.Thread(target=worker, args=(i,), daemon=True,
                                name=f"qps-open-{i}")
               for i in range(concurrency)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    rep = _level_report(concurrency, lats, wall, mismatches, errors,
                        mode="open")
    rep["target_rate_per_s"] = rate_per_s
    return rep


def run_overload_level(dqr, oracle, rate_per_s, n_requests, n_users=4):
    """TRUE open loop: one thread per scheduled arrival, no client-side
    gating — the arrival process never slows down when the server does,
    which is what makes shedding-not-collapse observable.  Every
    request is classified: ``ok`` (exact rows), ``shed`` (the
    dispatcher's QUERY_QUEUE_FULL shape WITH a retry hint), or
    ``other`` (anything else — a 500, a hang, a misshapen rejection —
    which overload must never produce)."""
    from presto_tpu.client import QueryFailed

    lock = threading.Lock()
    ok_lats, shed_lats, other = [], [], []
    names = [name for name, _ in STATEMENTS]
    start = time.perf_counter() + 0.1

    def issue(j, name):
        client = dqr.new_client(user=f"load{j % n_users}")
        arrival = start + j / rate_per_s
        now = time.perf_counter()
        if now < arrival:
            time.sleep(arrival - now)
        try:
            # max_retries=0: classification needs the raw rejection —
            # the retry loop is the client's own graceful-degradation
            # behavior, measured separately (tests/test_overload.py)
            _cols, data = client.execute(oracle.sql[name],
                                         max_retries=0)
            lat = time.perf_counter() - arrival
            parity = _norm_rows([tuple(r) for r in data]) \
                == oracle.rows[name]
            with lock:
                if parity:
                    ok_lats.append(lat)
                else:
                    other.append(f"req{j}: row mismatch on {name}")
        except QueryFailed as e:
            lat = time.perf_counter() - arrival
            well_shaped = (e.error_name == "QUERY_QUEUE_FULL"
                           and e.error_type == "INSUFFICIENT_RESOURCES"
                           and e.retry_after_s is not None)
            with lock:
                if well_shaped:
                    shed_lats.append(lat)
                else:
                    other.append(f"req{j}: {e.error_name}: {e}")
        except Exception as e:  # noqa: BLE001 - the unshaped bucket
            with lock:
                other.append(f"req{j}: {type(e).__name__}: {e}")

    threads = [threading.Thread(
        target=issue, args=(j, names[j % len(names)]), daemon=True,
        name=f"qps-overload-{j}") for j in range(n_requests)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = max(time.perf_counter() - t0, 1e-9)
    lats_sorted = sorted(ok_lats)
    return {
        "mode": "overload",
        "rate_per_s": round(rate_per_s, 2),
        "requests": n_requests,
        "ok": len(ok_lats),
        "shed": len(shed_lats),
        "other": len(other),
        "goodput_qps": round(len(ok_lats) / wall, 2),
        "shed_rate": round(len(shed_lats) / n_requests, 3),
        "p50_ms": round(_percentile(lats_sorted, 0.50) * 1e3, 1),
        "p95_ms": round(_percentile(lats_sorted, 0.95) * 1e3, 1),
        "shed_p95_ms": round(
            _percentile(sorted(shed_lats), 0.95) * 1e3, 1),
        "errors": other[:5],
    }


def run_overload(scale=0.003, pool_size=4, max_queued=8,
                 duration_s=3.0, factors=(0.5, 1.0, 2.0),
                 n_workers=2, quiet=False):
    """Open-loop graceful-degradation sweep over the bounded-pool
    dispatcher (``dispatcher_pool_size`` / ``dispatcher_max_queued``):
    measure peak capacity closed-loop first, then drive open-loop
    arrivals at fractions of it THROUGH saturation.  ``ok`` requires
    zero non-error-shaped failures at every rate, shedding engaged past
    saturation, and goodput at the highest rate >= 80% of peak — load
    past capacity must degrade to fast well-shaped rejections, never
    collapse."""
    import dataclasses

    from presto_tpu.config import DEFAULT
    from presto_tpu.server.dqr import DistributedQueryRunner
    from presto_tpu.session import ResourceGroupManager

    cfg = dataclasses.replace(DEFAULT,
                              dispatcher_pool_size=pool_size,
                              dispatcher_max_queued=max_queued)
    # admission control for this sweep is the DISPATCHER's: keep the
    # resource-group tree wide open so every rejection is the bounded
    # pool's well-shaped shed, not a group-queue shape without a hint
    groups = ResourceGroupManager(
        hard_concurrency_limit=max(16, pool_size * 4),
        per_user_limit=max(16, pool_size * 4))
    report = {"scale": scale, "mode": "overload",
              "n_workers": n_workers,
              "dispatcher": {"pool_size": pool_size,
                             "max_queued": max_queued},
              "levels": []}
    with DistributedQueryRunner.tpcds(scale=scale, n_workers=n_workers,
                                      resource_groups=groups,
                                      config=cfg) as dqr:
        oracle = _Oracle(dqr)          # also warms scan + kernel caches
        closed = run_closed_level(dqr, oracle, pool_size, 6)
        peak = max(closed["qps"], 1.0)
        report["peak_qps"] = peak
        report["peak_parity"] = closed["parity"]
        for f in factors:
            rate = max(peak * f, 1.0)
            n = max(min(int(rate * duration_s), 150), 4)
            level = run_overload_level(dqr, oracle, rate, n)
            level["rate_factor"] = f
            report["levels"].append(level)
            if not quiet:
                print(json.dumps(level), flush=True)
        report["shed_total"] = dqr.coordinator.dispatcher.shed_total
    top = report["levels"][-1]
    # degradation is judged WITHIN the open-loop curve: goodput at the
    # top rate vs the best sustained goodput across the sweep's own
    # levels.  The closed-loop peak only sets the rate schedule — as a
    # ratio denominator it mixes two measurement windows, and on a
    # noisy single-core host the cross-window drift (not the engine)
    # ends up owning the number.  A real collapse still fails: goodput
    # that tanks past saturation tanks against its own curve too.
    crest = max(lv["goodput_qps"] for lv in report["levels"])
    report["goodput_ratio_at_max"] = round(
        top["goodput_qps"] / max(crest, 1e-9), 3)
    report["ok"] = (
        report["peak_parity"]
        and all(lv["other"] == 0 for lv in report["levels"])
        and top["shed"] > 0
        and report["goodput_ratio_at_max"] >= 0.8)
    return report


def _level_report(concurrency, lats, wall, mismatches, errors, mode):
    lats_sorted = sorted(lats)
    return {
        "mode": mode,
        "concurrency": concurrency,
        "requests": len(lats),
        "wall_s": round(wall, 3),
        "qps": round(len(lats) / wall, 2) if wall > 0 else 0.0,
        "p50_ms": round(_percentile(lats_sorted, 0.50) * 1e3, 1),
        "p95_ms": round(_percentile(lats_sorted, 0.95) * 1e3, 1),
        "p99_ms": round(_percentile(lats_sorted, 0.99) * 1e3, 1),
        "parity": not mismatches and not errors,
        "mismatches": mismatches[:5],
        "errors": errors[:5],
    }


def _second_run_jit_compiles(dqr, oracle):
    """Execute an already-cached statement once more and read its
    /v1/query detail: a warm plan-cache + kernel-cache run must show
    jit_compiles == 0 (the cross-query compiled-tier reuse proof).
    With the result cache on, the second run is served from spool
    pages instead (resultCached=true) — its jit counters are genuine
    zeros and no plan was consulted at all."""
    client = dqr.new_client(user="probe")
    name = STATEMENTS[0][0]
    client.execute(oracle.sql[name])          # belt-and-braces warm
    client.execute(oracle.sql[name])
    qid = client.last_query_id
    with urllib.request.urlopen(
            f"{dqr.coordinator.uri}/v1/query/{qid}", timeout=10) as resp:
        detail = json.loads(resp.read())
    return (int((detail.get("queryStats") or {}).get("jit_compiles", -1)),
            bool(detail.get("planCached")),
            bool(detail.get("resultCached")))


def run_qps(scale=0.003, levels=(1, 2, 4, 8), requests_per_client=4,
            mode="closed", rate_per_s=10.0, n_workers=2,
            hard_concurrency=8, per_user_limit=4, quiet=False,
            hot_repeat=False, result_cache=False):
    """Boot the cluster, run every concurrency level, return the report
    dict (the bench_concurrent_qps payload).  ``hot_repeat`` drives the
    repeated-verbatim statement mix; ``result_cache`` turns the
    cross-query result cache on for the cluster (hits are reported per
    level beside the plan-cache numbers either way)."""
    import dataclasses

    from presto_tpu.config import DEFAULT
    from presto_tpu.server import resultcache
    from presto_tpu.server.dqr import DistributedQueryRunner
    from presto_tpu.session import ResourceGroupManager
    from presto_tpu.sql import plancache

    groups = ResourceGroupManager(
        hard_concurrency_limit=hard_concurrency,
        per_user_limit=per_user_limit)
    # the result cache is process-global (like the plan cache): start
    # each load run from a cold, unpolluted cache so hit rates and
    # bytes-served are this run's own
    resultcache.clear()
    cfg = dataclasses.replace(DEFAULT,
                              result_cache_enabled=result_cache)
    report = {"scale": scale, "mode": mode, "n_workers": n_workers,
              "hot_repeat": hot_repeat, "result_cache": result_cache,
              "resource_groups": {"hard_concurrency": hard_concurrency,
                                  "per_user_limit": per_user_limit},
              "levels": []}
    with DistributedQueryRunner.tpcds(scale=scale, n_workers=n_workers,
                                      resource_groups=groups,
                                      config=cfg) as dqr:
        oracle = _Oracle(dqr)          # also warms scan + kernel caches
        for conc in levels:
            before = plancache.stats()
            rc_before = resultcache.stats()
            if mode == "open":
                n_requests = max(requests_per_client * conc, conc)
                level = run_open_level(dqr, oracle, conc, rate_per_s,
                                       n_requests, hot=hot_repeat)
            else:
                level = run_closed_level(dqr, oracle, conc,
                                         requests_per_client,
                                         hot=hot_repeat)
            after = plancache.stats()
            rc_after = resultcache.stats()
            hits = after["hits"] - before["hits"]
            misses = after["misses"] - before["misses"]
            level["plan_cache"] = {
                "hits": hits, "misses": misses,
                "hit_rate": round(hits / (hits + misses), 3)
                if hits + misses else 0.0}
            rc_hits = rc_after["hits"] - rc_before["hits"]
            rc_misses = rc_after["misses"] - rc_before["misses"]
            level["result_cache"] = {
                "hits": rc_hits, "misses": rc_misses,
                "hit_rate": round(rc_hits / (rc_hits + rc_misses), 3)
                if rc_hits + rc_misses else 0.0,
                "bytes_served": rc_after["bytes_served"]
                - rc_before["bytes_served"]}
            report["levels"].append(level)
            if not quiet:
                print(json.dumps(level), flush=True)
        jit, cached, rcached = _second_run_jit_compiles(dqr, oracle)
        report["second_run_jit_compiles"] = jit
        report["second_run_plan_cached"] = cached
        report["second_run_result_cached"] = rcached
        # admission engagement: how many queries actually waited
        with urllib.request.urlopen(
                f"{dqr.coordinator.uri}/v1/query", timeout=10) as resp:
            qs = json.loads(resp.read())
        report["queries_total"] = len(qs)
        report["queries_queued"] = sum(
            1 for q in qs if q.get("queuedS", 0) > 0.0005)
    report["parity"] = all(lv["parity"] for lv in report["levels"])
    hits = sum(lv["plan_cache"]["hits"] for lv in report["levels"])
    misses = sum(lv["plan_cache"]["misses"] for lv in report["levels"])
    report["plan_cache_hit_rate"] = round(
        hits / (hits + misses), 3) if hits + misses else 0.0
    rc_hits = sum(lv["result_cache"]["hits"] for lv in report["levels"])
    rc_misses = sum(lv["result_cache"]["misses"]
                    for lv in report["levels"])
    report["result_cache_hit_rate"] = round(
        rc_hits / (rc_hits + rc_misses), 3) if rc_hits + rc_misses \
        else 0.0
    report["result_cache_bytes_served"] = sum(
        lv["result_cache"]["bytes_served"] for lv in report["levels"])
    return report


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--scale", type=float, default=0.003)
    ap.add_argument("--levels", default="1,2,4,8",
                    help="comma-separated concurrency levels")
    ap.add_argument("--requests", type=int, default=4,
                    help="statements per client (closed) / per level "
                         "x concurrency (open)")
    ap.add_argument("--mode", choices=("closed", "open"),
                    default="closed")
    ap.add_argument("--rate", type=float, default=10.0,
                    help="open-loop arrival rate, statements/s")
    ap.add_argument("--workers", type=int, default=2)
    ap.add_argument("--hot", action="store_true",
                    help="hot-repeat mix: repeat HOT_STATEMENTS "
                         "verbatim (the result-cache shape)")
    ap.add_argument("--result-cache", action="store_true",
                    help="enable the cross-query result cache on the "
                         "cluster")
    ap.add_argument("--open-loop", action="store_true",
                    help="overload sweep: bounded-pool dispatcher, "
                         "open-loop arrivals through saturation; "
                         "reports goodput/shed/latency per rate and "
                         "fails on any non-error-shaped rejection or "
                         "goodput collapse (with --check: a smaller "
                         "sweep with the same assertions)")
    ap.add_argument("--pool-size", type=int, default=4,
                    help="open-loop sweep: dispatcher_pool_size")
    ap.add_argument("--max-queued", type=int, default=8,
                    help="open-loop sweep: dispatcher_max_queued")
    ap.add_argument("--check", action="store_true",
                    help="CI smoke: tiny run, assert parity + plan-cache "
                         "hits + zero second-run compiles, then a "
                         "hot-repeat run asserting nonzero result-cache "
                         "hits with exact-rows parity")
    args = ap.parse_args(argv)

    if args.open_loop:
        # --check = the CI smoke: smaller pool + shorter levels, same
        # assertions — every reject past saturation must carry the
        # queue-full shape + retry hint (never a 500), and goodput must
        # hold at >= 80% of peak
        report = run_overload(
            scale=args.scale,
            pool_size=2 if args.check else args.pool_size,
            max_queued=4 if args.check else args.max_queued,
            duration_s=1.5 if args.check else 3.0,
            factors=(1.0, 2.0) if args.check else (0.5, 1.0, 2.0),
            n_workers=args.workers, quiet=args.check)
        print(json.dumps(report, indent=2))
        return 0 if report["ok"] else 1

    if args.check:
        report = run_qps(scale=0.003, levels=(1, 2),
                         requests_per_client=2, mode="closed",
                         n_workers=2, quiet=True)
        # hot-repeat tier: result cache ON, every statement repeated —
        # hits must happen and every row must still match the
        # single-threaded oracle exactly (a cached result is served
        # from spool pages; parity is per request)
        hot = run_qps(scale=0.003, levels=(2,),
                      requests_per_client=4, mode="closed",
                      n_workers=2, quiet=True, hot_repeat=True,
                      result_cache=True)
        checks = {
            "parity": report["parity"],
            "plan_cache_hits": report["plan_cache_hit_rate"] > 0.0,
            "zero_second_run_compiles":
                report["second_run_jit_compiles"] == 0,
            "second_run_plan_cached": report["second_run_plan_cached"],
            "hot_parity": hot["parity"],
            "result_cache_hits":
                hot["result_cache_hit_rate"] > 0.0,
            "result_cache_bytes_served":
                hot["result_cache_bytes_served"] > 0,
            "hot_second_run_result_cached":
                hot["second_run_result_cached"],
        }
        print(json.dumps({"check": checks, "report": report,
                          "hot_report": hot}))
        return 0 if all(checks.values()) else 1

    levels = tuple(int(x) for x in args.levels.split(",") if x.strip())
    report = run_qps(scale=args.scale, levels=levels,
                     requests_per_client=args.requests, mode=args.mode,
                     rate_per_s=args.rate, n_workers=args.workers,
                     hot_repeat=args.hot,
                     result_cache=args.result_cache)
    print(json.dumps(report, indent=2))
    return 0 if report["parity"] else 1


if __name__ == "__main__":
    sys.exit(main())
