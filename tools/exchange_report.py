"""Per-fragment-boundary exchange-mode report (PR 11 companion to
tools/fusion_report.py: that tool diffs the physical dispatch structure,
this one diffs the DATA PLANE each fragment boundary rides).

For each query: the fragment DAG with one row per boundary —
producer fragment -> consumer fragment, the producer's output
partitioning, and the exchange mode the boundary lowers to:

- ``collective``  — the device-sharded exchange tier (in-program
  ``all_to_all`` / ``all_gather`` / gather inside one SPMD program);
  chosen when mesh_device_exchange is on, every boundary of the query
  is device-eligible, and placements are co-resident on one mesh;
- ``http+spool``  — the task-scheduled wire tier (PartitionedOutput ->
  serde -> HTTP pull, write-through to the spool when spooling is on);
- boundaries that are individually eligible but ride HTTP because a
  SIBLING boundary is not (the program is all-or-nothing) are marked
  ``http+spool (eligible)``.

With ``--segments`` the report also lists each query's fused segments
that touch a boundary (exec/fusion.py boundary_roles): the
exchange-feeding (partition-id computing) and exchange-fed (page
coalescing) segment programs are exactly the work the collective tier
splices away.

With ``--live`` the report EXECUTES each query on a real
``MeshQueryRunner`` mesh and adds per-boundary rows/bytes columns from
the per-shard telemetry the SPMD program itself reports (PR 12): what
each shard actually received through every ``all_to_all`` /
``all_gather`` / gather, not the planning-time view.

Usage:
    python tools/exchange_report.py                 # all TPC-H
    python tools/exchange_report.py q3 tpcds/q72    # subset
    python tools/exchange_report.py --check         # CI smoke: exit 1
        unless TPC-H Q3's boundaries ALL lower to the collective tier
    python tools/exchange_report.py --live --check  # ALSO execute Q3 on
        the mesh and require nonzero device-boundary bytes on every
        collective boundary
"""

import argparse
import dataclasses as dc
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.pop("PALLAS_AXON_POOL_IPS", None)
if "--live" in sys.argv and \
        "xla_force_host_platform_device_count" not in \
        os.environ.get("XLA_FLAGS", ""):
    # a live mesh run needs >1 virtual device for real collectives;
    # only effective when jax has not been imported yet (standalone CLI
    # use — the test suite already forces an 8-device host platform)
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_"
                                 "count=8").strip()

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tests"))
sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def load_queries(names):
    from tpcds_queries import QUERIES as TPCDS
    from tpch_queries import QUERIES as TPCH

    if not names:
        return [("tpch", n, TPCH[n]) for n in sorted(TPCH)]
    out = []
    for name in names:
        catalog, _, q = name.lower().rpartition("/")
        catalog = catalog or "tpch"
        num = int(q.lstrip("q"))
        table = {"tpch": TPCH, "tpcds": TPCDS}[catalog]
        out.append((catalog, num, table[num]))
    return out


def boundary_rows(dplan, all_eligible):
    """(producer fid, consumer fid, partitioning kind, mode) rows."""
    rows = []
    for f in dplan.fragments:
        for fid in f.consumed_fragments:
            prod = dplan.fragments[fid]
            kind = prod.output_partitioning[0]
            if all_eligible:
                mode = "collective"
            elif prod.device_exchange_eligible:
                mode = "http+spool (eligible)"
            else:
                mode = "http+spool"
            rows.append((fid, f.fragment_id, kind, mode))
    return rows


def live_boundary_report(runner, sql: str) -> list:
    """Execute ``sql`` on the mesh runner and return its per-boundary
    telemetry rows: (kind, collective, per-shard rows, per-shard
    bytes) straight from the program's own per-shard counters."""
    runner.execute(sql)
    info = runner.last_run_info
    collective = {"hash": "all_to_all", "arbitrary": "all_to_all",
                  "broadcast": "all_gather", "single": "gather"}
    return [(b["fragment"], b["kind"],
             collective.get(b["kind"], b["kind"]),
             b.get("rows", []), b.get("bytes", []))
            for b in info.get("boundaries", [])]


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("queries", nargs="*",
                    help="q1 q6 tpcds/q3 ... (default: all TPC-H)")
    ap.add_argument("--scale", type=float, default=0.01)
    ap.add_argument("--segments", action="store_true",
                    help="also list boundary-adjacent fused segments")
    ap.add_argument("--live", action="store_true",
                    help="execute each query on a MeshQueryRunner and "
                         "report per-boundary rows/bytes from the "
                         "per-shard telemetry")
    ap.add_argument("--shards", type=int, default=2,
                    help="mesh shard count for --live (clamped to the "
                         "available devices)")
    ap.add_argument("--check", action="store_true",
                    help="CI smoke: exit 1 unless TPC-H Q3's boundaries "
                         "all lower to the collective tier (with --live: "
                         "and report nonzero device bytes on every "
                         "collective boundary)")
    args = ap.parse_args(argv)

    from presto_tpu.config import EngineConfig
    from presto_tpu.localrunner import LocalQueryRunner
    from presto_tpu.server.fragmenter import (
        Fragmenter, annotate_device_exchange,
    )
    from presto_tpu.sql.optimizer import optimize
    from presto_tpu.sql.parser import parse_statement
    from presto_tpu.sql.planner import Planner

    cfg = dc.replace(EngineConfig(), mesh_device_exchange=True)
    runner = LocalQueryRunner.tpch(scale=args.scale, config=cfg)

    mesh = None
    if args.live:
        import jax

        from presto_tpu.parallel.sqlmesh import MeshQueryRunner

        shards = max(1, min(args.shards, len(jax.devices())))
        mesh = MeshQueryRunner.tpch(scale=args.scale, n_devices=shards,
                                    config=cfg)
        print(f"live mesh: {shards} shards "
              f"({jax.devices()[0].platform} devices)")

    failures = []
    q3_collective = None
    q3_live_bytes_ok = None
    for catalog, num, sql in load_queries(args.queries):
        label = f"{catalog}/q{num}"
        runner.metadata.default_catalog = catalog
        try:
            logical = Planner(runner.metadata).plan(parse_statement(sql))
            optimized = optimize(logical, runner.metadata, cfg)
            dplan = Fragmenter(metadata=runner.metadata,
                               config=cfg).fragment(optimized)
            all_eligible = annotate_device_exchange(dplan)
        except Exception as e:  # noqa: BLE001 - report and continue
            print(f"=== {label}: planning failed: {e}")
            failures.append((label, "plan"))
            continue
        rows = boundary_rows(dplan, all_eligible)
        verdict = "collective" if all_eligible else "http+spool"
        print(f"=== {label}: {len(dplan.fragments)} fragments, "
              f"{len(rows)} boundaries, data plane: {verdict}")
        print(f"  {'boundary':<12} {'partitioning':<14} mode")
        for fid, cid, kind, mode in rows:
            print(f"  f{fid}->f{cid:<9} {kind:<14} {mode}")
        if (catalog, num) == ("tpch", 3):
            q3_collective = all_eligible and all(
                m == "collective" for _, _, _, m in rows)
        if mesh is not None and all_eligible:
            # execute on the mesh: per-boundary rows/bytes straight
            # from the program's per-shard telemetry
            mesh.metadata.default_catalog = catalog
            try:
                live = live_boundary_report(mesh, sql)
            except Exception as e:  # noqa: BLE001 - report and continue
                print(f"  live execution failed: {e}")
                failures.append((label, "live"))
                continue
            print(f"  {'boundary':<12} {'collective':<12} "
                  f"{'rows/shard':<24} {'bytes/shard':<28} total bytes")
            for fid, _kind, coll, rws, byt in live:
                print(f"  f{fid:<11} {coll:<12} {str(rws):<24} "
                      f"{str(byt):<28} {sum(byt)}")
            if (catalog, num) == ("tpch", 3):
                q3_live_bytes_ok = bool(live) and all(
                    sum(byt) > 0 for _, _, _, _, byt in live)
        if args.segments:
            # lower each fragment the way a worker task would (stub
            # producer URIs, real output sinks) so the boundary-adjacent
            # fused segments — partition-id feeders and page coalescers,
            # the work the collective tier splices away — are visible
            from presto_tpu.exec.fusion import boundary_roles
            from presto_tpu.server.buffers import OutputBufferManager
            from presto_tpu.server.exchangeop import (
                PartitionedOutputOperatorFactory,
                TaskOutputOperatorFactory,
            )
            from presto_tpu.sql.physical import PhysicalPlanner

            for f in dplan.fragments:
                remotes = {fid: ["http://stub/{part}"]
                           for fid in f.consumed_fragments}
                planner = PhysicalPlanner(runner.registry, cfg,
                                          scan_shard=(0, 2),
                                          remote_sources=remotes)
                kind, channels = f.output_partitioning
                bufs = OutputBufferManager(2)
                if kind == "hash":
                    sink = PartitionedOutputOperatorFactory(
                        bufs, channels, 2)
                else:
                    sink = TaskOutputOperatorFactory(bufs)
                try:
                    pipes = planner.plan_fragment(f.root, sink)
                except Exception as e:  # noqa: BLE001 - advisory
                    print(f"  [f{f.fragment_id}] lowering failed: {e}")
                    continue
                for pname, desc, role in boundary_roles(pipes):
                    if role:
                        print(f"  [f{f.fragment_id} {pname}] "
                              f"{role}: {desc}")
    if args.check:
        if q3_collective is None:
            # --check without q3 in the set: plan (and with --live,
            # execute) it now
            extra = (["--live", "--shards", str(args.shards)]
                     if args.live else [])
            rc = main(["q3", "--scale", str(args.scale), "--check"]
                      + extra)
            return rc if rc else 0
        if not q3_collective:
            print("FAIL: TPC-H Q3 boundaries do not lower to the "
                  "collective tier")
            return 1
        if args.live and not q3_live_bytes_ok:
            print("FAIL: TPC-H Q3 live run did not report nonzero "
                  "device-boundary bytes on every collective boundary")
            return 1
        if failures:
            return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
