"""Sweep the full TPC-DS query set against the engine + sqlite oracle.

Loads the 99 standard query texts (from the benchto-resource naming used
by the reference), normalizes the catalog template, runs each through
LocalQueryRunner at tiny scale, compares with the sqlite oracle, and
prints a per-query verdict + error classification — the worklist for the
conformance tier.
"""

import glob
import os
import re
import sqlite3
import sys
import time
import traceback

# conformance runs on CPU like the test suite: the remote-TPU tunnel's
# per-dispatch latency (0.1-1 s, degrading over long sessions) dominates
# the operator tier's many small dispatches at toy scales
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.pop("PALLAS_AXON_POOL_IPS", None)
import jax

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tests"))
sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

REF = "/root/reference/presto-benchto-benchmarks/src/main/resources/sql/presto/tpcds"
SCALE = 0.003


def normalize(sql: str) -> str:
    sql = sql.replace("${database}.${schema}.", "tpcds.")
    return sql


def main() -> None:
    from presto_tpu.localrunner import LocalQueryRunner
    from test_tpch_conformance import (
        _sqlite_type, _to_sqlite, assert_rows_match, register_sqlite_fns,
        to_sqlite_sql,
    )

    only = None
    slice_lo = slice_hi = None
    if len(sys.argv) > 1:
        if ":" in sys.argv[1]:
            a, _, b = sys.argv[1].partition(":")
            slice_lo, slice_hi = int(a), int(b)
        else:
            only = set(sys.argv[1].split(","))
    runner = LocalQueryRunner.tpch(scale=SCALE)
    oracle = sqlite3.connect(":memory:")
    oracle.execute("PRAGMA case_sensitive_like = ON")
    register_sqlite_fns(oracle)
    tpcds = runner.registry.get("tpcds")
    for table in tpcds.list_tables():
        handle = tpcds.get_table(table)
        schema = tpcds.table_schema(handle)
        names = schema.column_names()
        cols_sql = ", ".join(f"{n} {_sqlite_type(schema.column_type(n))}"
                             for n in names)
        oracle.execute(f"create table {table} ({cols_sql})")
        for split in tpcds.get_splits(handle, 1):
            for batch in tpcds.page_source(split, names, 1 << 20):
                rows = [tuple(_to_sqlite(v) for v in r)
                        for r in batch.to_pylist()]
                ph = ", ".join("?" * len(names))
                oracle.executemany(
                    f"insert into {table} values ({ph})", rows)
        # index the _sk columns: correlated-subquery shapes otherwise run
        # for hours in sqlite
        for n in names:
            if n.endswith("_sk"):
                oracle.execute(
                    f"create index idx_{table}_{n} on {table}({n})")
    oracle.commit()

    import signal

    class _Timeout(Exception):
        pass

    def _alarm(_sig, _frm):
        raise _Timeout()

    signal.signal(signal.SIGALRM, _alarm)
    per_query_s = int(os.environ.get("HARVEST_TIMEOUT_S", "120"))

    ok, results = 0, []
    paths = sorted(glob.glob(os.path.join(REF, "q*.sql")))
    if slice_lo is not None:
        paths = paths[slice_lo:slice_hi]
    for path in paths:
        qn = os.path.basename(path)[1:-4]
        if only and qn not in only:
            continue
        sql = normalize(open(path).read())
        t0 = time.time()
        try:
            signal.alarm(per_query_s)
            got = runner.execute(sql)
        except _Timeout:
            results.append((qn, "ENGINE", "Timeout"))
            print(f"q{qn}: ENGINE Timeout", flush=True)
            continue
        except Exception as e:
            msg = f"{type(e).__name__}: {str(e)[:110]}".replace("\n", " ")
            results.append((qn, "ENGINE", msg))
            print(f"q{qn}: ENGINE {msg}", flush=True)
            continue
        finally:
            signal.alarm(0)
        try:
            signal.alarm(per_query_s)
            osql = to_sqlite_sql(sql.replace("tpcds.", ""))
            cur = oracle.execute(osql)
            want = cur.fetchall()
        except _Timeout:
            results.append((qn, "ORACLE", "Timeout"))
            print(f"q{qn}: ORACLE Timeout", flush=True)
            continue
        except Exception as e:
            msg = f"{type(e).__name__}: {str(e)[:110]}".replace("\n", " ")
            results.append((qn, "ORACLE", msg))
            print(f"q{qn}: ORACLE {msg}", flush=True)
            continue
        finally:
            signal.alarm(0)
        try:
            ordered = "order by" in sql.lower()
            assert_rows_match(got.rows, want, ordered)
        except AssertionError as e:
            msg = str(e)[:160].replace("\n", " ")
            results.append((qn, "MISMATCH", msg))
            print(f"q{qn}: MISMATCH {msg}", flush=True)
            continue
        ok += 1
        results.append((qn, "OK", ""))
        print(f"q{qn}: OK ({time.time()-t0:.0f}s, {len(got.rows)} rows)",
              flush=True)
    print(f"\n{ok}/{len(results)} pass", flush=True)
    from collections import Counter
    cats = Counter()
    for qn, status, msg in results:
        if status != "OK":
            cats[msg.split(":")[0] + ":" + msg[:60]] += 1
    for k, v in cats.most_common(40):
        print(f"{v:3d}  {k}", flush=True)


if __name__ == "__main__":
    main()
