#!/usr/bin/env python
"""Per-query profile: stage span timeline + stats rollup table.

Two modes, one report shape:

- **live**: boot an in-process DistributedQueryRunner, execute one
  statement through the real statement protocol, and render the
  coordinator's StageStats rollup plus the timed span tree from
  ``/v1/query/{id}/spans`` (query -> coordinator phases -> per-stage ->
  per-task-attempt, the presto_tpu.spans shape).  ``--live``
  additionally follows ``/v1/query/{id}/timeseries`` while the
  statement runs and renders the sampler's progress ring;
- **replay** (``--replay query.json``): read a JsonLinesEventListener
  log (events.py, the bundled query.json role) and render each query's
  event timeline, the stage-stats table, and the span tree carried on
  its QueryCompletedEvent.

Usage:
    JAX_PLATFORMS=cpu python tools/query_profile.py \
        --sql "select count(*) from lineitem" --workers 2
    JAX_PLATFORMS=cpu python tools/query_profile.py --live --sql "..."
    JAX_PLATFORMS=cpu python tools/query_profile.py --replay query.json
    JAX_PLATFORMS=cpu python tools/query_profile.py --check   # CI smoke
"""

import argparse
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.pop("PALLAS_AXON_POOL_IPS", None)

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))

TIMELINE_WIDTH = 40


def _fmt_bytes(b) -> str:
    return f"{(b or 0) / (1 << 20):.1f}MiB"


def stage_table(stage_stats) -> list:
    """Render {fid: StageStats dict} as aligned text lines."""
    header = (f"{'stage':>5} {'tasks':>5} {'rep':>4} {'in rows':>11} "
              f"{'out rows':>11} {'wall ms':>9} {'jit':>9} "
              f"{'prereduce':>9} {'peak':>9} {'xchg f/c/p':>14}")
    lines = [header, "-" * len(header)]
    for fid in sorted(stage_stats, key=lambda k: int(k)):
        st = stage_stats[fid]
        jit = f"{st['jit_dispatches']}/{st['jit_compiles']}"
        xchg = (f"{st['exchange_fetched']}/{st['exchange_consumed']}/"
                f"{st['exchange_purged']}")
        lines.append(
            f"{fid:>5} {st['tasks']:>5} {st['reporting']:>4} "
            f"{st['input_rows']:>11} {st['output_rows']:>11} "
            f"{st['wall_ns'] / 1e6:>9.1f} {jit:>9} "
            f"{st['prereduce_rows']:>9} "
            f"{_fmt_bytes(st['peak_memory_bytes']):>9} {xchg:>14}")
    return lines


def _fetch_json(uri: str):
    import json
    import urllib.request

    with urllib.request.urlopen(uri, timeout=10) as resp:
        return json.loads(resp.read())


def timeseries_table(samples) -> list:
    """Render the /v1/query/{id}/timeseries ring: one line per sample
    (live progress as the sampler saw it)."""
    if not samples:
        return ["(no time-series samples — query finished before the "
                "first sweep)"]
    t0 = samples[0]["t"]
    header = (f"{'t+ms':>8} {'state':<9} {'splits q/r/c':>13} "
              f"{'out rows':>11} {'bytes':>10} {'backlog':>8} "
              f"{'peak':>9}")
    lines = [header, "-" * len(header)]
    for s in samples:
        splits = (f"{s['splits_queued']}/{s['splits_running']}/"
                  f"{s['splits_completed']}")
        lines.append(
            f"{(s['t'] - t0) * 1000:>8.0f} {s['state']:<9} "
            f"{splits:>13} {s['output_rows']:>11} "
            f"{s['output_bytes']:>10} {s['exchange_backlog']:>8} "
            f"{_fmt_bytes(s['peak_memory_bytes']):>9}")
    return lines


def profile_live(args) -> int:
    import threading
    import time

    from presto_tpu.server.dqr import DistributedQueryRunner
    from presto_tpu.spans import render_span_tree, validate_span_tree

    boot = (DistributedQueryRunner.tpcds if args.catalog == "tpcds"
            else DistributedQueryRunner.tpch)
    with boot(scale=args.scale, n_workers=args.workers,
              event_log_path=args.event_log) as dqr:
        co_uri = dqr.coordinator.uri
        live_polls = []
        if args.live:
            # --live: run the statement on a thread and follow the
            # timeseries endpoint while the query is RUNNING
            out = {}

            def run():
                try:
                    out["res"] = dqr.execute(args.sql)
                except Exception as e:  # noqa: BLE001
                    out["err"] = e

            t = threading.Thread(target=run)
            t.start()
            qid = None
            while t.is_alive():
                qid = qid or dqr.client.last_query_id
                if qid:
                    try:
                        live_polls.append(_fetch_json(
                            f"{co_uri}/v1/query/{qid}/timeseries"))
                    except Exception:  # noqa: BLE001 - query racing
                        pass
                time.sleep(0.1)
            t.join()
            if "err" in out:
                raise out["err"]
            res = out["res"]
        else:
            res = dqr.execute(args.sql)
        q = list(dqr.coordinator.queries.values())[-1]
        print(f"query {q.query_id} [{q.state}] trace={q.trace_token}")
        print(f"sql: {args.sql}")
        print(f"rows: {len(res.rows)}")
        qs = q.query_stats or {}
        print(f"elapsed: {qs.get('elapsed_s', 0):.3f}s  "
              f"peak memory: {_fmt_bytes(qs.get('peak_memory_bytes'))}  "
              f"jit: {qs.get('jit_dispatches', 0)} dispatches / "
              f"{qs.get('jit_compiles', 0)} compiles "
              f"({qs.get('jit_compile_ns', 0) / 1e6:.1f} ms compile)  "
              f"retries: {q.stage_retry_rounds} stage / "
              f"{q.recovery_rounds} leaf")
        print()
        for line in stage_table(q.stage_stats):
            print(line)
        print()
        # the timed span tree from the live endpoint (the same tree
        # query.json carries on QueryCompletedEvent)
        tree = _fetch_json(f"{co_uri}/v1/query/{q.query_id}/spans")
        violations = validate_span_tree(tree)
        for line in render_span_tree(tree):
            print(line)
        if args.live:
            print()
            ring = _fetch_json(
                f"{co_uri}/v1/query/{q.query_id}/timeseries")
            mid = max((len(p.get("samples", [])) for p in live_polls),
                      default=0)
            print(f"time series ({len(ring['samples'])} samples, "
                  f"{mid} observed mid-query):")
            for line in timeseries_table(ring["samples"]):
                print(line)
        if args.check:
            ok = (q.state == "FINISHED" and q.stage_stats
                  and not violations
                  and tree.get("children")
                  and all(st["reporting"] >= 1
                          for st in q.stage_stats.values())
                  and any(st["input_rows"] > 0
                          for st in q.stage_stats.values())
                  and any(ts.get("elapsed_s", 0) > 0
                          for tss in q.task_stats.values()
                          for ts in tss))
            print(f"\ncheck: profile rollup "
                  f"{'complete' if ok else 'INCOMPLETE'}")
            return 0 if ok else 1
    return 0


def profile_replay(args) -> int:
    from presto_tpu.events import read_event_log

    events = read_event_log(args.replay)
    if not events:
        print("empty event log")
        return 1
    t0 = min(e.get("create_time") or e.get("time") or 0 for e in events)
    for e in events:
        at = (e.get("time") or e.get("end_time") or
              e.get("create_time") or t0) - t0
        kind = e["event"]
        extra = ""
        if kind == "QueryCreatedEvent":
            extra = f"sql={e.get('sql', '')[:60]!r}"
        elif kind == "QueryCompletedEvent":
            extra = (f"state={e.get('state')} rows={e.get('output_rows')} "
                     f"wall={e.get('end_time', 0) - e.get('create_time', 0):.3f}s")
        elif kind == "StageRetryEvent":
            extra = (f"fragments={e.get('fragment_ids')} "
                     f"round={e.get('round')} reason={e.get('reason')!r} "
                     f"producer_reruns={e.get('producer_reruns')} "
                     f"spooled={e.get('spooled')}")
        elif kind == "TaskRecoveryEvent":
            extra = f"dead={e.get('dead_uri')} tasks={e.get('task_ids')}"
        elif kind == "WorkerDrainEvent":
            extra = (f"worker={e.get('worker_uri')} "
                     f"tasks={e.get('task_ids')}")
        elif kind == "SpeculationEvent":
            extra = (f"{e.get('task_id')} -> {e.get('clone_id')} "
                     f"[{e.get('outcome')}]")
        print(f"+{at:8.3f}s {kind:<22} query={e.get('query_id')} "
              f"trace={e.get('trace_token')} {extra}")
    for e in events:
        if e["event"] == "QueryCompletedEvent" and e.get("stage_stats"):
            print(f"\nstage stats for {e['query_id']}:")
            for line in stage_table(
                    {str(st["fragment_id"]): st
                     for st in e["stage_stats"]}):
                print(line)
        if e["event"] == "QueryCompletedEvent" and e.get("spans"):
            # the serialized span tree round-trips: query.json carries
            # the same tree /v1/query/{id}/spans served live
            from presto_tpu.spans import render_span_tree

            print(f"\nspans for {e['query_id']}:")
            for line in render_span_tree(e["spans"]):
                print(line)
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--sql", default="select l_returnflag, count(*), "
                    "sum(l_extendedprice) from lineitem "
                    "group by l_returnflag")
    ap.add_argument("--catalog", choices=["tpch", "tpcds"],
                    default="tpch")
    ap.add_argument("--scale", type=float, default=0.01)
    ap.add_argument("--workers", type=int, default=2)
    ap.add_argument("--event-log", default=None,
                    help="also write a query.json event log here")
    ap.add_argument("--replay", default=None,
                    help="render a query.json event log instead of "
                         "running a statement")
    ap.add_argument("--live", action="store_true",
                    help="follow /v1/query/{id}/timeseries while the "
                         "statement runs and render the sample ring")
    ap.add_argument("--check", action="store_true",
                    help="CI smoke: exit nonzero unless every stage "
                         "reported stats and spans")
    args = ap.parse_args(argv)
    if args.replay:
        return profile_replay(args)
    return profile_live(args)


if __name__ == "__main__":
    sys.exit(main())
