#!/usr/bin/env python
"""Per-query profile: stage span timeline + stats rollup table.

Two modes, one report shape:

- **live**: boot an in-process DistributedQueryRunner, execute one
  statement through the real statement protocol, and render the
  coordinator's StageStats/TaskStats rollup — per-stage stats table and
  a per-task span timeline (when each task ran relative to the query's
  wall clock);
- **replay** (``--replay query.json``): read a JsonLinesEventListener
  log (events.py, the bundled query.json role) and render each query's
  event timeline + the stage-stats table carried on its
  QueryCompletedEvent.

Usage:
    JAX_PLATFORMS=cpu python tools/query_profile.py \
        --sql "select count(*) from lineitem" --workers 2
    JAX_PLATFORMS=cpu python tools/query_profile.py --replay query.json
    JAX_PLATFORMS=cpu python tools/query_profile.py --check   # CI smoke
"""

import argparse
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.pop("PALLAS_AXON_POOL_IPS", None)

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))

TIMELINE_WIDTH = 40


def _fmt_bytes(b) -> str:
    return f"{(b or 0) / (1 << 20):.1f}MiB"


def stage_table(stage_stats) -> list:
    """Render {fid: StageStats dict} as aligned text lines."""
    header = (f"{'stage':>5} {'tasks':>5} {'rep':>4} {'in rows':>11} "
              f"{'out rows':>11} {'wall ms':>9} {'jit':>9} "
              f"{'prereduce':>9} {'peak':>9} {'xchg f/c/p':>14}")
    lines = [header, "-" * len(header)]
    for fid in sorted(stage_stats, key=lambda k: int(k)):
        st = stage_stats[fid]
        jit = f"{st['jit_dispatches']}/{st['jit_compiles']}"
        xchg = (f"{st['exchange_fetched']}/{st['exchange_consumed']}/"
                f"{st['exchange_purged']}")
        lines.append(
            f"{fid:>5} {st['tasks']:>5} {st['reporting']:>4} "
            f"{st['input_rows']:>11} {st['output_rows']:>11} "
            f"{st['wall_ns'] / 1e6:>9.1f} {jit:>9} "
            f"{st['prereduce_rows']:>9} "
            f"{_fmt_bytes(st['peak_memory_bytes']):>9} {xchg:>14}")
    return lines


def span_timeline(task_stats, width: int = TIMELINE_WIDTH) -> list:
    """ASCII span per task: position/extent of [start_time, end_time]
    within the query's [min start, max end] window."""
    spans = []
    for fid in sorted(task_stats, key=lambda k: int(k)):
        for ts in task_stats[fid]:
            if ts.get("start_time"):
                spans.append((fid, ts))
    if not spans:
        return ["(no task spans reported)"]
    t0 = min(ts["start_time"] for _, ts in spans)
    t1 = max(ts.get("end_time") or ts["start_time"] for _, ts in spans)
    total = max(t1 - t0, 1e-6)
    lines = [f"task span timeline ({total * 1000:.1f} ms total)"]
    for fid, ts in spans:
        lo = int((ts["start_time"] - t0) / total * width)
        hi = int(((ts.get("end_time") or t1) - t0) / total * width)
        hi = max(hi, lo + 1)
        bar = " " * lo + "=" * (hi - lo) + " " * (width - hi)
        lines.append(
            f"  F{fid} {ts.get('task_id', '?'):<28} |{bar}| "
            f"{ts.get('elapsed_s', 0) * 1000:>8.1f} ms "
            f"{ts.get('output_rows', 0):>9} rows")
    return lines


def profile_live(args) -> int:
    from presto_tpu.server.dqr import DistributedQueryRunner

    boot = (DistributedQueryRunner.tpcds if args.catalog == "tpcds"
            else DistributedQueryRunner.tpch)
    with boot(scale=args.scale, n_workers=args.workers,
              event_log_path=args.event_log) as dqr:
        res = dqr.execute(args.sql)
        q = list(dqr.coordinator.queries.values())[-1]
        print(f"query {q.query_id} [{q.state}] trace={q.trace_token}")
        print(f"sql: {args.sql}")
        print(f"rows: {len(res.rows)}")
        qs = q.query_stats or {}
        print(f"elapsed: {qs.get('elapsed_s', 0):.3f}s  "
              f"peak memory: {_fmt_bytes(qs.get('peak_memory_bytes'))}  "
              f"jit: {qs.get('jit_dispatches', 0)} dispatches / "
              f"{qs.get('jit_compiles', 0)} compiles  "
              f"retries: {q.stage_retry_rounds} stage / "
              f"{q.recovery_rounds} leaf")
        print()
        for line in stage_table(q.stage_stats):
            print(line)
        print()
        for line in span_timeline(q.task_stats):
            print(line)
        if args.check:
            ok = (q.state == "FINISHED" and q.stage_stats
                  and all(st["reporting"] >= 1
                          for st in q.stage_stats.values())
                  and any(st["input_rows"] > 0
                          for st in q.stage_stats.values())
                  and any(ts.get("elapsed_s", 0) > 0
                          for tss in q.task_stats.values()
                          for ts in tss))
            print(f"\ncheck: profile rollup "
                  f"{'complete' if ok else 'INCOMPLETE'}")
            return 0 if ok else 1
    return 0


def profile_replay(args) -> int:
    from presto_tpu.events import read_event_log

    events = read_event_log(args.replay)
    if not events:
        print("empty event log")
        return 1
    t0 = min(e.get("create_time") or e.get("time") or 0 for e in events)
    for e in events:
        at = (e.get("time") or e.get("end_time") or
              e.get("create_time") or t0) - t0
        kind = e["event"]
        extra = ""
        if kind == "QueryCreatedEvent":
            extra = f"sql={e.get('sql', '')[:60]!r}"
        elif kind == "QueryCompletedEvent":
            extra = (f"state={e.get('state')} rows={e.get('output_rows')} "
                     f"wall={e.get('end_time', 0) - e.get('create_time', 0):.3f}s")
        elif kind == "StageRetryEvent":
            extra = (f"fragments={e.get('fragment_ids')} "
                     f"round={e.get('round')} reason={e.get('reason')!r} "
                     f"producer_reruns={e.get('producer_reruns')} "
                     f"spooled={e.get('spooled')}")
        elif kind == "TaskRecoveryEvent":
            extra = f"dead={e.get('dead_uri')} tasks={e.get('task_ids')}"
        elif kind == "WorkerDrainEvent":
            extra = (f"worker={e.get('worker_uri')} "
                     f"tasks={e.get('task_ids')}")
        elif kind == "SpeculationEvent":
            extra = (f"{e.get('task_id')} -> {e.get('clone_id')} "
                     f"[{e.get('outcome')}]")
        print(f"+{at:8.3f}s {kind:<22} query={e.get('query_id')} "
              f"trace={e.get('trace_token')} {extra}")
    for e in events:
        if e["event"] == "QueryCompletedEvent" and e.get("stage_stats"):
            print(f"\nstage stats for {e['query_id']}:")
            for line in stage_table(
                    {str(st["fragment_id"]): st
                     for st in e["stage_stats"]}):
                print(line)
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--sql", default="select l_returnflag, count(*), "
                    "sum(l_extendedprice) from lineitem "
                    "group by l_returnflag")
    ap.add_argument("--catalog", choices=["tpch", "tpcds"],
                    default="tpch")
    ap.add_argument("--scale", type=float, default=0.01)
    ap.add_argument("--workers", type=int, default=2)
    ap.add_argument("--event-log", default=None,
                    help="also write a query.json event log here")
    ap.add_argument("--replay", default=None,
                    help="render a query.json event log instead of "
                         "running a statement")
    ap.add_argument("--check", action="store_true",
                    help="CI smoke: exit nonzero unless every stage "
                         "reported stats and spans")
    args = ap.parse_args(argv)
    if args.replay:
        return profile_replay(args)
    return profile_live(args)


if __name__ == "__main__":
    sys.exit(main())
