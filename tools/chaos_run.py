#!/usr/bin/env python
"""Chaos smoke run: boot a real in-process cluster, kill a worker
mid-query, assert mid-query task recovery still returns correct rows.

The CLI face of the tests/test_chaos.py tier — run it standalone to
sanity-check the fault-tolerance layer on a box (CI or dev) without the
pytest harness:

    JAX_PLATFORMS=cpu python tools/chaos_run.py --workers 3 --scale 0.01
    JAX_PLATFORMS=cpu python tools/chaos_run.py --mode stage
    JAX_PLATFORMS=cpu python tools/chaos_run.py --mode mesh --check
    JAX_PLATFORMS=cpu python tools/chaos_run.py --check

``--mode leaf`` (default) kills a worker holding leaf tasks; ``--mode
stage`` runs a broadcast-join plan and kills the worker holding the
NON-leaf probe fragment, proving whole-stage retry.  ``--check`` is the
CI smoke tier: it runs the whole ``chaos`` pytest marker headless and
exits nonzero on any inexact result.

Exit code 0 = recovery reproduced the clean run exactly; non-zero =
recovery failed.
"""

import argparse
import dataclasses
import json
import os
import subprocess
import sys
import threading
import time

# runnable from anywhere: `python tools/chaos_run.py` puts tools/ on the
# path, not the repo root (same shim as fusion_report.py)
sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))
if "--mode" in sys.argv and "mesh" in sys.argv and \
        "xla_force_host_platform_device_count" not in \
        os.environ.get("XLA_FLAGS", ""):
    # the mesh sweep needs >1 virtual device for real collectives; only
    # effective before jax is imported (standalone CLI use — the test
    # suite already forces an 8-device host platform)
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_"
                                 "count=8").strip()


def run_spool_sweep(scale: float = 0.003, spooling: bool = True,
                    query_num: int = 72, fragments=None,
                    spool_path=None, quiet: bool = False) -> dict:
    """Kill-every-stage-in-turn sweep of a TPC-DS query on the 2-worker
    mesh (the spooled-exchange acceptance proof): for each fragment of
    the plan, run the query with the root drain held, kill the worker
    hosting that fragment's first task while the query is in flight,
    and record rows-exactness + producer re-runs.

    ``spooling=True`` must recover every stage with ZERO producer
    re-runs (output re-pulled from the spool); ``spooling=False``
    restores the PR 5 cascading behavior (non-leaf kills re-run the
    producer subtree)."""
    import dataclasses as _dc
    import tempfile
    import threading as _th

    from presto_tpu.config import DEFAULT
    from presto_tpu.connectors.api import ConnectorRegistry
    from presto_tpu.connectors.tpcds import TpcdsConnector
    from presto_tpu.localrunner import LocalQueryRunner
    from presto_tpu.server.dqr import DistributedQueryRunner
    from presto_tpu.server.faults import FaultInjector
    from tests.tpcds_queries import QUERIES

    sql = QUERIES[query_num]
    reg = ConnectorRegistry()
    reg.register("tpcds", TpcdsConnector(scale=scale))
    want = sorted(LocalQueryRunner(reg, "tpcds").execute(sql).rows)
    cfg = _dc.replace(
        DEFAULT, task_recovery_interval_s=0.05,
        exchange_spooling_enabled=spooling,
        exchange_spool_path=(spool_path or os.path.join(
            tempfile.mkdtemp(prefix="spool-sweep-"), "spool")))
    # every fragment of the plan, killed in turn
    if fragments is None:
        from presto_tpu.server.fragmenter import Fragmenter
        from presto_tpu.sql.optimizer import optimize
        from presto_tpu.sql.parser import parse_statement
        from presto_tpu.sql.planner import Metadata, Planner

        md = Metadata(reg, "tpcds")
        plan = optimize(Planner(md).plan(parse_statement(sql)), md, cfg)
        fragments = [f.fragment_id for f in Fragmenter(
            metadata=md, config=cfg).fragment(plan).fragments]
    stages = []
    for fid in fragments:
        t0 = time.monotonic()
        co_inj = FaultInjector()
        hold = co_inj.add_rule(r"/results/", method="GET",
                               policy="slow-task")
        res = {}
        with DistributedQueryRunner.tpcds(
                scale=scale, n_workers=2, config=cfg,
                coordinator_injector=co_inj,
                heartbeat_interval_s=0.05,
                heartbeat_max_missed=2) as dqr:
            co = dqr.coordinator
            while len(co.nodes.alive_nodes()) != 2:
                time.sleep(0.02)

            def run():
                try:
                    res["rows"] = dqr.execute(sql).rows
                except Exception as e:  # noqa: BLE001
                    res["err"] = str(e)

            t = _th.Thread(target=run)
            t.start()
            # the victim is whichever worker hosts {fid}.0; the held
            # drain guarantees the query is still in flight at the kill
            victim_uri = None
            deadline = time.monotonic() + 60.0
            while time.monotonic() < deadline:
                qs = list(co.queries.values())
                if qs:
                    hit = [u for f, tid, u in qs[0]._placements
                           if f == fid and tid.endswith(f".{fid}.0")]
                    if hit:
                        victim_uri = hit[0]
                        break
                time.sleep(0.01)
            q = list(co.queries.values())[0]
            victim_idx = next(i for i, w in enumerate(dqr.workers)
                              if w.uri == victim_uri)
            dqr.kill_worker(victim_idx)
            # keep the drain held until the recovery monitor actually
            # handled the dead worker, so every stage kill exercises
            # recovery (not a lucky drain-first finish)
            deadline = time.monotonic() + 60.0
            while time.monotonic() < deadline and \
                    victim_uri not in q._recovered_uris:
                time.sleep(0.02)
            hold.release()
            t.join(timeout=300)
            stage = {
                "fragment": fid, "killed_worker": victim_uri,
                "wall_s": round(time.monotonic() - t0, 2),
                "producer_reruns": q.producer_reruns_total,
                "stage_retry_rounds": q.stage_retry_rounds,
                "recovery_rounds": q.recovery_rounds,
                "spool_repoints": len(q._spool_moves) + sum(
                    1 for _, _, u in q._placements
                    if str(u).startswith("spool://")),
            }
            if t.is_alive():
                stage["ok"] = False
                stage["reason"] = "query hung"
            elif "err" in res:
                stage["ok"] = False
                stage["reason"] = res["err"][:300]
            elif sorted(res["rows"]) != want:
                stage["ok"] = False
                stage["reason"] = "row mismatch"
            elif q.recovery_rounds < 1:
                stage["ok"] = False
                stage["reason"] = "kill never triggered recovery"
            else:
                stage["ok"] = True
            stages.append(stage)
            if not quiet:
                print(json.dumps(stage))
    total_reruns = sum(s["producer_reruns"] for s in stages)
    report = {
        "mode": "spool", "query": f"tpcds q{query_num}",
        "scale": scale, "spooling": spooling,
        "stages": stages,
        "total_producer_reruns": total_reruns,
        "ok": all(s["ok"] for s in stages) and (
            total_reruns == 0 if spooling else True),
    }
    return report


def run_mesh_sweep(scale: float = 0.01, query_num: int = 3,
                   resume_mode: str = "device",
                   quiet: bool = False, smoke: bool = False) -> dict:
    """Kill-every-fragment sweep of the COLLECTIVE data plane (the
    boundary-checkpoint acceptance proof): run a TPC-H query on the
    2-worker mesh with ``mesh_checkpoint_boundaries`` on, inject a
    device-plane fault at every checkpoint group in turn, and record
    rows-exactness + resumes + re-lowered fragments per kill point.

    ``resume_mode='device'`` must recover every kill by re-running ONLY
    the remaining checkpoint groups (checkpointed fragments never
    re-lowered); ``resume_mode='http'`` must degrade to the HTTP plane
    scheduling ONLY the remaining fragments (checkpointed producers
    served as spool:// leaves, zero tasks for them)."""
    import dataclasses as _dc
    import tempfile

    from presto_tpu.config import DEFAULT
    from presto_tpu.localrunner import LocalQueryRunner
    from presto_tpu.parallel import sqlmesh
    from presto_tpu.server.dqr import DistributedQueryRunner
    from presto_tpu.server.faults import FaultInjector
    from tests.tpch_queries import QUERIES

    sql = QUERIES[query_num]
    want = sorted(LocalQueryRunner.tpch(scale=scale).execute(sql).rows)
    cfg = _dc.replace(
        DEFAULT, mesh_device_exchange=True,
        mesh_checkpoint_boundaries=True,
        mesh_resume_mode=resume_mode,
        exchange_spooling_enabled=True,
        exchange_spool_path=os.path.join(
            tempfile.mkdtemp(prefix="mesh-sweep-"), "spool"))
    # ONE cluster for the whole sweep: checkpointed executions never
    # share programs across queries, device rules are one-shot, and a
    # degrade is not sticky on the cached plan — so each kill point is
    # an independent execution on the same booted mesh (a fresh boot
    # per stage would only re-pay data gen + worker startup)
    inj = FaultInjector()
    stages = []
    with DistributedQueryRunner.tpch(scale=scale, n_workers=2,
                                     config=cfg,
                                     coordinator_injector=inj) as dqr:
        # clean run: ground truth on the mesh + the kill matrix (every
        # fragment the checkpointed execution lowers is one kill point)
        rows = sorted(dqr.execute(sql).rows)
        q0 = list(dqr.coordinator.queries.values())[-1]
        info0 = dict(q0.device_exchange_info or {})
        if rows != want:
            return {"mode": "mesh", "resume_mode": resume_mode,
                    "ok": False,
                    "reason": "clean mesh run mismatched the local "
                              "engine"}
        kill_fids = sorted(info0.get("fragments_lowered") or [])
        if not kill_fids or not info0.get("checkpoints"):
            return {"mode": "mesh", "resume_mode": resume_mode,
                    "ok": False,
                    "reason": "checkpointed collective tier never "
                              "engaged",
                    "info": info0}
        if smoke and len(kill_fids) > 3:
            # CI smoke (--check): first group (no checkpoints yet), a
            # mid-DAG boundary, and the root group — the ha-mode
            # precedent (--check = kill-at-RUNNING only); the full run
            # kills every fragment
            kill_fids = sorted({kill_fids[0],
                                kill_fids[len(kill_fids) // 2],
                                kill_fids[-1]})
        for fid in kill_fids:
            t0 = time.monotonic()
            # one-shot fault on this group's dispatch, any shard/query
            # id; exhausted rules from earlier stages are inert
            inj.add_device_rule(rf"/f{fid}/s\d+$")
            hits_before = len(inj.injections)
            lowered_before = sqlmesh.FRAGMENTS_LOWERED
            stage = {"fragment": fid, "ok": False}
            res = {}
            try:
                res["rows"] = sorted(dqr.execute(sql).rows)
            except Exception as e:  # noqa: BLE001 - per-stage verdict
                res["err"] = str(e)
            q = list(dqr.coordinator.queries.values())[-1]
            info = dict(q.device_exchange_info or {})
            resumes = list(q.device_resumes)
            resumed_from = sorted({f for r in resumes
                                   for f in r["resumed_from"]})
            stage["injections"] = len(inj.injections) - hits_before
            stage["resumes"] = len(resumes)
            stage["resume_modes"] = sorted({r["mode"] for r in resumes})
            stage["resumed_from"] = resumed_from
            stage["mesh_relowered"] = \
                sqlmesh.FRAGMENTS_LOWERED - lowered_before
            # zero re-execution of checkpointed fragments, per mode:
            # device = never re-lowered into the resumed SPMD program;
            # http = never given an HTTP task (spool:// leaves instead)
            relowered = sorted(set(resumed_from)
                               & set(info.get("fragments_lowered")
                                     or []))
            retasked = sorted({f for f, _, _ in q._placements
                               if f in resumed_from})
            stage["spool_leaves"] = sorted(
                f for f, uris in q._task_uris.items()
                if any(str(u).startswith("spool://") for u in uris))
            stage["wall_s"] = round(time.monotonic() - t0, 2)
            if "err" in res:
                stage["reason"] = res["err"][:300]
            elif res["rows"] != want:
                stage["reason"] = "row mismatch"
            elif not stage["injections"]:
                stage["reason"] = "fault never fired"
            elif not resumes:
                stage["reason"] = "kill never triggered a resume"
            elif relowered:
                stage["reason"] = (f"checkpointed fragments re-lowered: "
                                   f"{relowered}")
            elif retasked:
                stage["reason"] = (f"checkpointed fragments re-executed "
                                   f"as HTTP tasks: {retasked}")
            else:
                stage["ok"] = True
            stages.append(stage)
            if not quiet:
                print(json.dumps(stage))
    report = {
        "mode": "mesh", "resume_mode": resume_mode,
        "query": f"tpch q{query_num}", "scale": scale,
        "fragments": kill_fids,
        "checkpoint_groups": info0.get("checkpoint_groups"),
        "stages": stages,
        "total_resumes": sum(s["resumes"] for s in stages),
        "ok": all(s["ok"] for s in stages),
    }
    return report


#: the coordinator-HA kill matrix (lifecycle phases of one query)
HA_PHASES = ("QUEUED", "PLANNING", "RUNNING", "SPOOL_COMPLETE",
             "FINISHED")


def run_ha_sweep(phases=HA_PHASES, scale: float = 0.003,
                 query_num: int = 72, quiet: bool = False) -> dict:
    """Kill-the-COORDINATOR sweep (coordinator HA acceptance): run a
    TPC-DS query on a 2-worker HA mesh (primary + standby sharing the
    spool and the durable query-state journal), kill the primary at
    each lifecycle phase in turn, and assert exact rows through the
    standby — with ZERO producer re-runs for stages already complete in
    the spool (and zero task creates at all for the
    all-spool-complete kill)."""
    import dataclasses as _dc
    import tempfile
    import threading as _th
    import urllib.error
    import urllib.request

    from presto_tpu.config import DEFAULT
    from presto_tpu.connectors.api import ConnectorRegistry
    from presto_tpu.connectors.tpcds import TpcdsConnector
    from presto_tpu.localrunner import LocalQueryRunner
    from presto_tpu.server.dqr import HAQueryRunner
    from presto_tpu.server.faults import FaultInjector
    from tests.tpcds_queries import QUERIES

    sql = QUERIES[query_num]
    reg = ConnectorRegistry()
    reg.register("tpcds", TpcdsConnector(scale=scale))
    want = sorted(LocalQueryRunner(reg, "tpcds").execute(sql).rows)

    def poll_standby(standby_uri, qid, timeout_s=120.0):
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            try:
                with urllib.request.urlopen(
                        f"{standby_uri}/v1/statement/executing/{qid}/0",
                        timeout=30) as resp:
                    p = json.loads(resp.read())
            except urllib.error.HTTPError as e:
                if e.code in (404, 503):
                    time.sleep(0.05)
                    continue
                raise
            if "error" in p:
                raise RuntimeError(f"standby failed: {p['error']}")
            if "data" in p:
                return p
            time.sleep(0.05)
        raise RuntimeError("standby never served the query")

    stages = []
    for phase in phases:
        t0 = time.monotonic()
        tmp = tempfile.mkdtemp(prefix="ha-sweep-")
        cfg = _dc.replace(
            DEFAULT,
            exchange_spooling_enabled=True,
            exchange_spool_path=os.path.join(tmp, "spool"),
            coordinator_state_path=os.path.join(tmp, "state"),
            coordinator_lease_ttl_s=0.4,
            task_recovery_interval_s=0.05)
        co_inj = FaultInjector()
        hold = None
        if phase in ("RUNNING", "SPOOL_COMPLETE"):
            hold = co_inj.add_rule(r"/results/", method="GET",
                                   policy="slow-task", delay_s=120.0)
        stage = {"phase": phase, "ok": False}
        res = {}
        with HAQueryRunner.tpcds(
                scale=scale, n_workers=2, config=cfg,
                coordinator_injector=co_inj,
                heartbeat_interval_s=0.05,
                heartbeat_max_missed=2) as ha:
            co = ha.coordinator
            while len(co.nodes.alive_nodes()) != 2:
                time.sleep(0.02)
            try:
                if phase == "QUEUED":
                    co.dispatcher.pause()
                    qid = _ha_submit(co.uri, sql)
                    time.sleep(0.2)
                    ha.kill_primary()
                elif phase == "PLANNING":
                    at = _th.Event()
                    release = _th.Event()

                    def hook(_q, ph):
                        if ph == "PLANNING":
                            at.set()
                            release.wait(timeout=60.0)

                    co.phase_hook = hook
                    qid = _ha_submit(co.uri, sql)
                    if not at.wait(timeout=60.0):
                        raise RuntimeError("never reached PLANNING")
                    ha.kill_primary()
                    release.set()
                elif phase == "FINISHED":
                    cols, data = ha.client.execute(sql)
                    qid = ha.client.last_query_id
                    stage["primary_rows"] = len(data)
                    ha.kill_primary()
                else:   # RUNNING / SPOOL_COMPLETE, drain held
                    def run():
                        try:
                            res["rows"] = ha.execute(sql).rows
                        except Exception as e:  # noqa: BLE001
                            res["err"] = str(e)

                    t = _th.Thread(target=run)
                    t.start()
                    q = None
                    deadline = time.monotonic() + 120.0
                    while time.monotonic() < deadline:
                        qs = list(co.queries.values())
                        if qs and qs[0]._placements and \
                                qs[0].state == "RUNNING":
                            q = qs[0]
                            break
                        time.sleep(0.02)
                    if q is None:
                        raise RuntimeError("never reached RUNNING")
                    qid = q.query_id
                    if phase == "SPOOL_COMPLETE":
                        deadline = time.monotonic() + 120.0
                        while time.monotonic() < deadline:
                            with q._recovery_lock:
                                pl = list(q._placements)
                            if pl and all(co.spool.is_complete(
                                    tid, q._task_specs[tid]["n_out"])
                                    for _, tid, _ in pl):
                                break
                            time.sleep(0.05)
                        else:
                            raise RuntimeError(
                                "stages never all spool-complete")
                    time.sleep(0.3)   # journal writes settle
                    stage["tasks_before"] = sum(
                        len(w.task_manager.tasks) for w in ha.workers)
                    ha.kill_primary()
                ha.wait_for_failover(timeout_s=30.0)
                if phase in ("RUNNING", "SPOOL_COMPLETE"):
                    t.join(timeout=240.0)
                    if t.is_alive():
                        raise RuntimeError("client never finished")
                    if "err" in res:
                        raise RuntimeError(res["err"][:300])
                    rows = sorted(res["rows"])
                else:
                    p = poll_standby(ha.standby.uri, qid)
                    # decode the JSON payload through the client codec
                    # so dates/timestamps compare against the oracle
                    from presto_tpu import types as T
                    from presto_tpu.server.dqr import _from_json

                    types = [T.parse_type(c["type"])
                             for c in p.get("columns", [])]
                    rows = sorted(
                        tuple(_from_json(v, ty)
                              for v, ty in zip(r, types))
                        for r in p["data"])
                sq = ha.standby.queries.get(qid)
                stage["adopted_outcome"] = getattr(
                    sq, "adopt_outcome", None)
                stage["producer_reruns"] = getattr(
                    sq, "producer_reruns_total", 0)
                stage["stage_retry_rounds"] = getattr(
                    sq, "stage_retry_rounds", 0)
                stage["failovers"] = \
                    ha.standby.ha_counters["failovers"]
                if phase == "FINISHED":
                    # both sides are client-protocol JSON payloads:
                    # the standby must re-serve the primary's rows
                    exact = sorted(map(tuple, p["data"])) == \
                        sorted(map(tuple, data))
                else:
                    exact = rows == want
                if phase == "SPOOL_COMPLETE":
                    stage["tasks_after"] = sum(
                        len(w.task_manager.tasks) for w in ha.workers)
                    if stage["tasks_after"] != stage["tasks_before"]:
                        raise RuntimeError(
                            "adoption created tasks for "
                            "spool-complete stages")
                    if stage["producer_reruns"] != 0:
                        raise RuntimeError(
                            "producer re-ran for a spool-complete "
                            "stage")
                if phase == "RUNNING" and \
                        stage["producer_reruns"] != 0:
                    raise RuntimeError(
                        "producer re-ran under spooled HA adoption")
                if not exact:
                    raise RuntimeError("row mismatch through standby")
                stage["ok"] = True
            except Exception as e:  # noqa: BLE001 - per-phase verdict
                stage["reason"] = str(e)[:300]
            if hold is not None:
                hold.release()
        stage["wall_s"] = round(time.monotonic() - t0, 2)
        stages.append(stage)
        if not quiet:
            print(json.dumps(stage))
    report = {
        "mode": "ha", "query": f"tpcds q{query_num}", "scale": scale,
        "phases": [s["phase"] for s in stages],
        "stages": stages,
        "total_producer_reruns": sum(
            s.get("producer_reruns", 0) for s in stages),
        "ok": all(s["ok"] for s in stages),
    }
    return report


def _ha_submit(co_uri: str, sql: str) -> str:
    import urllib.request

    req = urllib.request.Request(
        f"{co_uri}/v1/statement", data=sql.encode(),
        method="POST", headers={"Content-Type": "text/plain"})
    with urllib.request.urlopen(req, timeout=10) as resp:
        return json.loads(resp.read())["id"]


def run_oom_sweep(scale: float = 0.01, survivors: int = 2,
                  quiet: bool = False) -> dict:
    """Overload-survival sweep (the low-memory-killer acceptance proof):
    a held runaway task fills one worker's GENERAL pool (faults.py
    memory-inflation with a hold), concurrent survivor statements then
    BLOCK on the full pool, and the coordinator's arbitration must
    resolve the stall by failing EXACTLY the policy-selected runaway
    with the reference error shape (CLUSTER_OUT_OF_MEMORY /
    INSUFFICIENT_RESOURCES) while every survivor returns exact rows and
    ZERO workers die."""
    import dataclasses as _dc
    import threading as _th

    from presto_tpu.client import QueryFailed
    from presto_tpu.config import DEFAULT
    from presto_tpu.server.dqr import DistributedQueryRunner
    from presto_tpu.server.faults import FaultInjector

    pool = 8 << 20
    runaway_sql = ("select l_returnflag, count(*) from lineitem "
                   "group by l_returnflag")
    survivor_sql = "select count(*) from lineitem"
    # clean run: the survivor ground truth the degraded cluster must
    # still reproduce exactly
    with DistributedQueryRunner.tpch(scale=scale, n_workers=2) as clean:
        want = sorted(clean.execute(survivor_sql).rows)
    cfg = _dc.replace(
        DEFAULT,
        worker_memory_pool_bytes=pool,
        memory_blocked_wait_s=30.0,
        low_memory_killer_delay_s=0.75)
    inj = FaultInjector()
    # the runaway: the first task created on worker 0 reserves ~94% of
    # the node pool and PARKS holding it until the kill aborts it
    inj.add_memory_rule(".*", int(pool * 0.94), times=1, hold_s=60.0)
    t0 = time.monotonic()
    stages = []
    report = {"mode": "oom", "scale": scale, "pool_bytes": pool,
              "survivors": survivors, "stages": stages}
    with DistributedQueryRunner.tpch(
            scale=scale, n_workers=2, config=cfg,
            worker_injectors={0: inj},
            heartbeat_interval_s=0.05,
            heartbeat_max_missed=5) as dqr:
        co = dqr.coordinator
        while len(co.nodes.alive_nodes()) != 2:
            time.sleep(0.02)

        def pool_reserved() -> int:
            return max((mi.get("pool", {}).get("reservedBytes", 0)
                        for mi in co.memory_info.values()), default=0)

        run_res: dict = {}

        def run_runaway():
            try:
                run_res["rows"] = dqr.new_client("runaway").execute(
                    runaway_sql, max_retries=0)[1]
            except QueryFailed as e:
                run_res["err"] = str(e)
                run_res["errorName"] = e.error_name
                run_res["errorType"] = e.error_type
                run_res["errorCode"] = e.error_code

        t_run = _th.Thread(target=run_runaway)
        t_run.start()
        # the runaway must be RUNNING and actually resident before the
        # survivors arrive (deterministic pressure ordering)
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline:
            qs = list(co.queries.values())
            if qs and qs[0].state == "RUNNING" and \
                    pool_reserved() >= int(pool * 0.9):
                break
            time.sleep(0.02)
        resident = pool_reserved()
        stages.append({"stage": "runaway-resident",
                       "pool_reserved": resident,
                       "ok": resident >= int(pool * 0.9)})
        runaway_qid = (list(co.queries.values())[0].query_id
                       if co.queries else None)
        # survivor tasks landing on the full node inflate a LITTLE too,
        # so their drivers genuinely BLOCK on the pool (the stall the
        # killer must resolve); no hold — they proceed once the victim's
        # memory frees, and the inflations all fit in the freed pool
        inj.add_memory_rule(".*", 1 << 20, times=4 * survivors)
        sur_res = [dict() for _ in range(survivors)]

        def run_survivor(i: int):
            try:
                sur_res[i]["rows"] = dqr.new_client(
                    f"survivor{i}").execute(survivor_sql,
                                            max_retries=0)[1]
            except QueryFailed as e:
                sur_res[i]["err"] = str(e)
                sur_res[i]["errorName"] = e.error_name

        threads = [_th.Thread(target=run_survivor, args=(i,))
                   for i in range(survivors)]
        for t in threads:
            t.start()
        t_run.join(timeout=60)
        kill_stage = {
            "stage": "kill", "victim": runaway_qid,
            "errorName": run_res.get("errorName"),
            "errorType": run_res.get("errorType"),
            "errorCode": run_res.get("errorCode"),
            "kill_counters": dict(co.kill_counters),
        }
        kill_stage["ok"] = (
            not t_run.is_alive()
            and run_res.get("errorName") == "CLUSTER_OUT_OF_MEMORY"
            and run_res.get("errorType") == "INSUFFICIENT_RESOURCES"
            and "out of memory" in run_res.get("err", ""))
        if not kill_stage["ok"]:
            kill_stage["reason"] = (
                "runaway hung" if t_run.is_alive() else
                f"unexpected runaway outcome: "
                f"{str(run_res.get('err', run_res.get('rows')))[:300]}")
        stages.append(kill_stage)
        for t in threads:
            t.join(timeout=60)
        norm = [sorted(tuple(r) for r in res.get("rows", []))
                for res in sur_res]
        want_t = sorted(tuple(r) for r in want)
        bad = [res for i, res in enumerate(sur_res)
               if threads[i].is_alive() or "err" in res
               or norm[i] != want_t]
        sur_stage = {"stage": "survivors", "n": survivors,
                     "ok": not bad}
        if bad:
            sur_stage["reason"] = f"{len(bad)} survivor(s) failed: " + \
                "; ".join(str(r.get("err", "row mismatch"))[:120]
                          for r in bad)
        stages.append(sur_stage)
        # post-chaos: clear the fault plane and prove the cluster is
        # whole — both workers alive, pool fully drained, fresh
        # statement exact (zero worker deaths is the acceptance bar)
        inj.release_all()
        inj.clear()
        rec = {"stage": "recovery",
               "alive": len(co.nodes.alive_nodes())}
        deadline = time.monotonic() + 15.0
        while time.monotonic() < deadline and pool_reserved() > 0:
            time.sleep(0.05)
        rec["pool_reserved_after"] = pool_reserved()
        try:
            rows = sorted(dqr.execute(survivor_sql).rows)
            rec["ok"] = (rows == want and rec["alive"] == 2
                         and rec["pool_reserved_after"] == 0)
            if not rec["ok"]:
                rec["reason"] = "cluster degraded after the kill"
        except Exception as e:  # noqa: BLE001 - report must still emit
            rec["ok"] = False
            rec["reason"] = str(e)[:300]
        stages.append(rec)
        if not quiet:
            for s in stages:
                print(json.dumps(s))
    report["wall_s"] = round(time.monotonic() - t0, 2)
    report["ok"] = all(s["ok"] for s in stages)
    return report


def run_check() -> int:
    """CI smoke: the chaos marker tier, headless (quick signal — the
    TPC-DS mesh cases are additionally marked slow and excluded)."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    r = subprocess.run(
        [sys.executable, "-m", "pytest", "-q", "-m", "chaos and not slow",
         "-p", "no:cacheprovider",
         os.path.join(repo, "tests", "test_chaos.py"),
         os.path.join(repo, "tests", "test_spool_exchange.py")],
        cwd=repo, env=env)
    print(json.dumps({"check": "chaos marker tier",
                      "ok": r.returncode == 0}))
    return r.returncode


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--workers", type=int, default=3)
    ap.add_argument("--scale", type=float, default=0.01)
    ap.add_argument("--query", default="select count(*) from lineitem")
    ap.add_argument("--kill-index", type=int, default=None,
                    help="worker to kill (default: last)")
    ap.add_argument("--mode",
                    choices=["leaf", "stage", "spool", "ha", "mesh",
                             "oom"],
                    default="leaf",
                    help="leaf = kill a scan-task worker; stage = kill "
                         "a worker holding a non-leaf fragment "
                         "(whole-stage retry); spool = kill EVERY "
                         "stage of TPC-DS Q72 in turn on the spooled "
                         "exchange, reporting producer re-runs per "
                         "stage (must be zero); ha = kill the "
                         "COORDINATOR at every lifecycle phase of a "
                         "TPC-DS Q72 HA mesh run and assert exact "
                         "rows through the standby (with --check: "
                         "just the kill-at-RUNNING smoke); mesh = "
                         "inject a device-plane fault at EVERY "
                         "checkpoint group of a TPC-H Q3 collective "
                         "run in turn (mesh_checkpoint_boundaries) "
                         "and assert exact rows with zero "
                         "re-execution of checkpointed fragments, in "
                         "both resume modes (with --check: the "
                         "device-resume sweep at first/middle/root "
                         "kill points only); oom = fill one worker's "
                         "memory pool with a held runaway, block "
                         "concurrent survivors on it, and assert the "
                         "low-memory killer fails exactly the runaway "
                         "(CLUSTER_OUT_OF_MEMORY) while survivors "
                         "return exact rows and zero workers die "
                         "(with --check: one survivor at a smaller "
                         "scale)")
    ap.add_argument("--resume-mode", choices=["device", "http", "both"],
                    default="both",
                    help="mesh mode only: which resume path(s) the "
                         "sweep exercises")
    ap.add_argument("--no-spooling", action="store_true",
                    help="spool mode only: run the sweep with "
                         "exchange spooling disabled (PR 5 cascading "
                         "retry) for comparison")
    ap.add_argument("--check", action="store_true",
                    help="run the chaos pytest tier headless; exit "
                         "nonzero on any inexact result")
    ap.add_argument("--event-log", default="query.json",
                    help="write the coordinator's query.json event "
                         "log here (JSON lines; '' disables)")
    args = ap.parse_args(argv)
    if args.mode == "mesh":
        # --check = the CI smoke: ONLY the device-resume sweep; the
        # full run also proves the HTTP-degrade path.  Exit is nonzero
        # on any inexact result or any re-execution of a checkpointed
        # fragment (re-lowered OR re-tasked)
        modes = (("device",) if args.check or args.resume_mode == "device"
                 else ("http",) if args.resume_mode == "http"
                 else ("device", "http"))
        reports = [run_mesh_sweep(scale=args.scale, resume_mode=m,
                                  smoke=args.check)
                   for m in modes]
        report = (reports[0] if len(reports) == 1 else
                  {"mode": "mesh", "sweeps": reports,
                   "ok": all(r["ok"] for r in reports)})
        print(json.dumps(report, indent=2))
        return 0 if report["ok"] else 1
    if args.mode == "ha":
        # --check = the CI smoke: ONLY the kill-at-RUNNING scenario,
        # nonzero on inexact rows or on any producer re-run for
        # spool-complete stages
        report = run_ha_sweep(
            phases=("RUNNING",) if args.check else HA_PHASES,
            scale=args.scale if args.scale != 0.01 else 0.003)
        print(json.dumps(report, indent=2))
        return 0 if report["ok"] else 1
    if args.mode == "oom":
        # --check = the CI smoke: one survivor at the smoke scale;
        # nonzero when the wrong query dies, any survivor fails or
        # returns inexact rows, or the cluster is degraded after
        report = run_oom_sweep(
            scale=0.003 if args.check else args.scale,
            survivors=1 if args.check else 2)
        print(json.dumps(report, indent=2))
        return 0 if report["ok"] else 1
    if args.check:
        return run_check()
    if args.mode == "spool":
        report = run_spool_sweep(
            scale=args.scale if args.scale != 0.01 else 0.003,
            spooling=not args.no_spooling)
        print(json.dumps(report, indent=2))
        return 0 if report["ok"] else 1
    if args.mode == "stage":
        args.query = ("select n_name, count(*) from nation join region "
                      "on n_regionkey = r_regionkey group by n_name")

    from presto_tpu.config import DEFAULT
    from presto_tpu.server.dqr import DistributedQueryRunner
    from presto_tpu.server.faults import FaultInjector

    # clean run first: the ground truth the chaos run must reproduce
    with DistributedQueryRunner.tpch(scale=args.scale,
                                     n_workers=args.workers) as clean:
        want = clean.execute(args.query).rows

    victim_idx = (args.kill_index if args.kill_index is not None
                  else args.workers - 1)
    cfg = dataclasses.replace(DEFAULT, task_recovery_interval_s=0.05)
    inj = FaultInjector()   # victim withholds results => query in flight
    inj.add_rule(r"/results/", method="GET", policy="drop-connection")
    report = {"query": args.query, "workers": args.workers,
              "scale": args.scale, "killed_worker": victim_idx}
    t0 = time.monotonic()
    if args.event_log and os.path.exists(args.event_log):
        os.remove(args.event_log)
    with DistributedQueryRunner.tpch(
            scale=args.scale, n_workers=args.workers, config=cfg,
            worker_injectors={victim_idx: inj},
            heartbeat_interval_s=0.05,
            heartbeat_max_missed=2,
            event_log_path=args.event_log or None) as dqr:
        co = dqr.coordinator
        while len(co.nodes.alive_nodes()) != args.workers:
            time.sleep(0.02)
        res = {}

        def run():
            try:
                res["rows"] = dqr.execute(args.query).rows
            except Exception as e:  # noqa: BLE001
                res["err"] = str(e)

        t = threading.Thread(target=run)
        t.start()
        victim_uri = dqr.workers[victim_idx].uri
        deadline = time.monotonic() + 15.0
        while time.monotonic() < deadline:
            qs = list(co.queries.values())
            if qs and any(
                    u == victim_uri and (
                        args.mode == "leaf"
                        or (qs[0]._dplan is not None and qs[0]._dplan
                            .fragments[f].consumed_fragments))
                    for f, _, u in qs[0]._placements):
                break
            time.sleep(0.02)
        q = list(co.queries.values())[0]
        dqr.kill_worker(victim_idx)
        t.join(timeout=120)
        report["wall_s"] = round(time.monotonic() - t0, 3)
        report["mode"] = args.mode
        report["stage_retry_rounds"] = q.stage_retry_rounds
        report["trace_token"] = q.trace_token
        # the /metrics plane must agree with the coordinator's counters
        # (the Prometheus scrape an operator would alert on)
        try:
            import urllib.request

            with urllib.request.urlopen(f"{co.uri}/metrics",
                                        timeout=5) as resp:
                metrics = resp.read().decode()
            line = next(
                (ln for ln in metrics.splitlines()
                 if ln.startswith("presto_stage_retry_rounds_total ")),
                "presto_stage_retry_rounds_total 0")
            report["metrics_stage_retry_rounds"] = float(line.split()[-1])
        except Exception as e:  # noqa: BLE001 - report must still emit
            report["metrics_stage_retry_rounds"] = f"error: {e}"
        report["recovered_placements"] = [
            (fid, tid, uri) for fid, tid, uri in q._placements]
        if t.is_alive():
            report["ok"] = False
            report["reason"] = "query hung after worker kill"
        elif "err" in res:
            report["ok"] = False
            report["reason"] = f"query failed: {res['err'][:300]}"
        elif sorted(res["rows"]) != sorted(want):
            report["ok"] = False
            report["reason"] = (f"row mismatch: chaos={res['rows'][:3]} "
                                f"clean={want[:3]}")
        elif any(u == victim_uri for _, _, u in q._placements):
            report["ok"] = False
            report["reason"] = "placements still on the dead worker"
        else:
            report["ok"] = True
    if args.event_log:
        # summarize the event log: the StageRetryEvent (stage mode) and
        # the completion event land here with the query's trace token
        from presto_tpu.events import read_event_log

        try:
            events = read_event_log(args.event_log)
        except Exception:  # noqa: BLE001 - log may be disabled
            events = []
        report["event_log"] = args.event_log
        report["events"] = sorted({e["event"] for e in events})
        report["stage_retry_events"] = sum(
            1 for e in events if e["event"] == "StageRetryEvent")
    print(json.dumps(report, indent=2))
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
