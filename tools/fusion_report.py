"""Per-query pipeline-fusion report for the TPC-H / TPC-DS suites.

For each query: the lowered pipeline chains with fused segments expanded
(stage composition, scan coalescing, partition-id fusion), and — with
``--execute`` — the fused vs unfused jit dispatch/compile counters plus a
result-parity check.  Companion to tools/plan_diff.py (which diffs the
LOGICAL plan; this diffs the PHYSICAL dispatch structure).

Usage:
    python tools/fusion_report.py                  # plan-only, all TPC-H
    python tools/fusion_report.py q1 q6 tpcds/q3   # subset
    python tools/fusion_report.py --execute        # + counters/parity
    python tools/fusion_report.py --execute --check  # CI smoke: exit 1 on
        any parity miss or any query where fusion does not reduce launches

``--check --execute`` is the CI smoke mode: it fails when fused execution
loses parity with unfused, when no query fused at all, when TPC-H Q1
at the default scale regresses past the partial-agg pre-reduce pin
(PR 4: fewer than 5 jit dispatches, PR 3's count), or when TPC-H Q3
loses its probe-in-segment lowering (PR 10: the probe stages absorbed
into fused segments, with the dispatch count pinned below 10).

With ``--execute`` each query also reports the **kernel-tier column**:
which tier served every group-by/join hot loop (``hash`` =
device-resident open-addressing, ``direct`` = bounded-domain,
``sort``/``sorted`` = sorted-index, ``stream`` = clustered,
``hash+sort`` = the overflow seam crossed mid-query).
"""

import argparse
import dataclasses as dc
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.pop("PALLAS_AXON_POOL_IPS", None)

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tests"))
sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def load_queries(names):
    from tpch_queries import QUERIES as TPCH
    from tpcds_queries import QUERIES as TPCDS

    if not names:
        return [("tpch", n, TPCH[n]) for n in sorted(TPCH)]
    out = []
    for name in names:
        catalog, _, q = name.lower().rpartition("/")
        catalog = catalog or "tpch"
        num = int(q.lstrip("q"))
        table = {"tpch": TPCH, "tpcds": TPCDS}[catalog]
        out.append((catalog, num, table[num]))
    return out


def plan_chains(runner, sql, config):
    from presto_tpu.sql.optimizer import optimize
    from presto_tpu.sql.parser import parse_statement
    from presto_tpu.sql.physical import PhysicalPlanner
    from presto_tpu.sql.planner import Planner

    plan = optimize(Planner(runner.metadata).plan(parse_statement(sql)),
                    runner.metadata, config)
    return PhysicalPlanner(runner.registry, config).plan(plan).pipelines


def describe(f) -> str:
    from presto_tpu.exec.fusion import FusedSegmentOperatorFactory

    if isinstance(f, FusedSegmentOperatorFactory):
        return f.describe()
    return type(f).__name__.replace("Factory", "")


def rows_close(a, b) -> bool:
    import numpy as np

    if len(a) != len(b):
        return False
    for ra, rb in zip(sorted(a, key=repr), sorted(b, key=repr)):
        if len(ra) != len(rb):
            return False
        for va, vb in zip(ra, rb):
            if isinstance(va, float) and isinstance(vb, float):
                if not (np.isclose(va, vb, rtol=1e-6)
                        or (np.isnan(va) and np.isnan(vb))):
                    return False
            elif va != vb:
                return False
    return True


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("queries", nargs="*",
                    help="q1 q6 tpcds/q3 ... (default: all TPC-H)")
    ap.add_argument("--scale", type=float, default=0.01)
    ap.add_argument("--execute", action="store_true",
                    help="run each query fused + unfused; report "
                         "dispatch counters and parity")
    ap.add_argument("--check", action="store_true",
                    help="CI smoke: nonzero exit on parity miss or "
                         "zero fused segments overall")
    args = ap.parse_args(argv)

    from presto_tpu.config import EngineConfig
    from presto_tpu.exec.fusion import FusedSegmentOperatorFactory
    from presto_tpu.localrunner import LocalQueryRunner

    cfg_on = EngineConfig()
    cfg_off = dc.replace(cfg_on, pipeline_fusion=False)
    runner_on = LocalQueryRunner.tpch(scale=args.scale, config=cfg_on)
    runner_off = LocalQueryRunner.tpch(scale=args.scale, config=cfg_off)

    total_segments = 0
    failures = []
    for catalog, num, sql in load_queries(args.queries):
        label = f"{catalog}/q{num}"
        runner_on.metadata.default_catalog = catalog
        runner_off.metadata.default_catalog = catalog
        try:
            pipelines = plan_chains(runner_on, sql, cfg_on)
        except Exception as e:  # noqa: BLE001 - report and continue
            print(f"=== {label}: planning failed: {e}")
            failures.append((label, "plan"))
            continue
        segs = [f for p in pipelines for f in p.factories
                if isinstance(f, FusedSegmentOperatorFactory)]
        total_segments += len(segs)
        prereduced = sum(1 for s in segs if s.agg_spec is not None)
        print(f"=== {label}: {len(pipelines)} pipelines, "
              f"{len(segs)} fused segments, {prereduced} pre-reduced")
        for p in pipelines:
            print(f"  [{p.name}] " + " -> ".join(
                describe(f) for f in p.factories))
        if not args.execute:
            continue
        try:
            res_on = runner_on.execute(sql)
            jit_on = runner_on._last_task.jit_counters()
            res_off = runner_off.execute(sql)
            jit_off = runner_off._last_task.jit_counters()
        except Exception as e:  # noqa: BLE001
            print(f"  execution failed: {e}")
            failures.append((label, "exec"))
            continue
        parity = rows_close(res_on.rows, res_off.rows)
        tiers = sorted({(s.operator.rsplit(".", 1)[-1], s.kernel_tier)
                        for s in runner_on._last_task.operator_stats
                        if s.kernel_tier})
        tier_col = ", ".join(f"{op}={t}" for op, t in tiers) or "-"
        print(f"  dispatches fused={jit_on['dispatches']} "
              f"unfused={jit_off['dispatches']} "
              f"compiles fused={jit_on['compiles']} "
              f"unfused={jit_off['compiles']} "
              f"prereduce_rows={jit_on.get('prereduce_rows', 0)} "
              f"parity={parity}")
        print(f"  kernel tiers: {tier_col}")
        if not parity:
            failures.append((label, "parity"))
        if jit_on["dispatches"] > jit_off["dispatches"]:
            print(f"  WARNING: fusion increased launches on {label}")
        if (catalog, num) == ("tpch", 1) and args.scale == 0.01 \
                and jit_on["dispatches"] >= 5:
            # the PR 4 acceptance pin: pre-reduce must keep Q1 below
            # PR 3's 5 dispatches at the default report scale
            print(f"  FAIL: Q1 dispatch pin regressed "
                  f"({jit_on['dispatches']} >= 5)")
            failures.append((label, "q1-dispatch-pin"))
        if (catalog, num) == ("tpch", 3) and args.scale == 0.01:
            # the PR 10 pin: Q3's probes run IN-SEGMENT (the
            # filter->project->probe chain is one dispatch per batch)
            if not any("probe(" in describe(f) for p in pipelines
                       for f in p.factories):
                print("  FAIL: Q3 probe-in-segment lowering lost")
                failures.append((label, "q3-probe-pin"))
            if jit_on["dispatches"] >= 10:
                print(f"  FAIL: Q3 dispatch pin regressed "
                      f"({jit_on['dispatches']} >= 10)")
                failures.append((label, "q3-dispatch-pin"))
    print(f"total fused segments: {total_segments}; "
          f"failures: {failures or 'none'}")
    if args.check and (failures or total_segments == 0):
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
