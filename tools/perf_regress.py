#!/usr/bin/env python
"""Perf-regression gate over committed BENCH_*.json artifacts.

The bench trajectory (BENCH_PR2..PR8 and later) is a pile of JSON unless
something reads it: this tool loads two or more artifacts, matches
configs BY METRIC NAME (the top-level headline plus every ``extras``
entry), and reports per-config deltas between consecutive artifacts with
a tolerance band.  All bench metrics are throughput-shaped (rows/s,
qps): higher is better, so a regression is a drop past ``--tolerance``.

``--check`` turns the report into a gate: exit nonzero when any matched
config regressed past the tolerance — the committed artifact pair
becomes an enforced floor instead of an unread number.

Usage:
    python tools/perf_regress.py BENCH_PR7_*.json BENCH_PR8_*.json
    python tools/perf_regress.py --check --tolerance 0.10 OLD.json NEW.json
"""

import argparse
import json
import sys


def load_metrics(path: str) -> tuple:
    """({metric name: value}, {metric name: noise_band}) from one
    artifact: the headline metric plus every extras entry carrying a
    (metric, value) pair.  ``noise_band`` is a config's DOCUMENTED
    run-to-run spread (a fraction, carried on the extras entry by
    bench configs whose single-host variance was measured to exceed
    the global tolerance — e.g. the spooled tpcds mesh config's ~2x
    swings); the gate widens to it for that config only."""
    with open(path, "r", encoding="utf-8") as f:
        doc = json.load(f)
    out = {}
    bands = {}
    if doc.get("metric") is not None and doc.get("value") is not None:
        out[doc["metric"]] = float(doc["value"])
        if doc.get("noise_band") is not None:
            bands[doc["metric"]] = float(doc["noise_band"])
    for extra in doc.get("extras", []) or []:
        if extra.get("metric") is not None \
                and extra.get("value") is not None:
            out[extra["metric"]] = float(extra["value"])
            if extra.get("noise_band") is not None:
                bands[extra["metric"]] = float(extra["noise_band"])
    return out, bands


def compare(old: dict, new: dict, tolerance: float,
            bands: dict = None) -> list:
    """Per-config rows for one artifact pair: (metric, old, new,
    delta fraction or None, status).  Configs only one side has are
    reported (NEW/DROPPED) but never gate.  A config with a declared
    ``noise_band`` (from either artifact) gates on
    max(tolerance, band)."""
    rows = []
    bands = bands or {}
    for name in sorted(set(old) | set(new)):
        if name not in old:
            rows.append((name, None, new[name], None, "NEW"))
            continue
        if name not in new:
            rows.append((name, old[name], None, None, "DROPPED"))
            continue
        o, n = old[name], new[name]
        delta = (n / o - 1.0) if o else 0.0
        band = max(tolerance, bands.get(name, 0.0))
        status = "REGRESSED" if delta < -band else "OK"
        if status == "OK" and delta < -tolerance:
            status = "OK(noise)"
        rows.append((name, o, n, delta, status))
    return rows


def _fmt(v) -> str:
    if v is None:
        return "-"
    return f"{v:,.1f}"


def report(paths: list, tolerance: float) -> tuple:
    """Render every consecutive pair; returns (lines, regressed)."""
    lines = []
    regressed = []
    metrics = [(p, *load_metrics(p)) for p in paths]
    for (old_path, old, old_bands), (new_path, new, new_bands) in \
            zip(metrics, metrics[1:]):
        # a band declared by EITHER side widens the gate: the old
        # artifact may predate the annotation
        bands = {**old_bands, **new_bands}
        lines.append(f"{old_path} -> {new_path} "
                     f"(tolerance {tolerance:.0%})")
        header = (f"  {'config':<56} {'old':>14} {'new':>14} "
                  f"{'delta':>8}  status")
        lines.append(header)
        lines.append("  " + "-" * (len(header) - 2))
        for name, o, n, delta, status in compare(old, new, tolerance,
                                                 bands):
            d = f"{delta:+.1%}" if delta is not None else "-"
            lines.append(f"  {name:<56} {_fmt(o):>14} {_fmt(n):>14} "
                         f"{d:>8}  {status}")
            if status == "REGRESSED":
                regressed.append((new_path, name, delta))
    return lines, regressed


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("artifacts", nargs="+",
                    help="two or more BENCH_*.json artifacts, oldest "
                         "first")
    ap.add_argument("--tolerance", type=float, default=0.10,
                    help="allowed fractional drop per config "
                         "(default 0.10 = 10%%)")
    ap.add_argument("--check", action="store_true",
                    help="exit nonzero when any matched config "
                         "regressed past the tolerance")
    args = ap.parse_args(argv)
    if len(args.artifacts) < 2:
        print("need at least two artifacts to compare")
        return 2
    lines, regressed = report(args.artifacts, args.tolerance)
    for line in lines:
        print(line)
    if regressed:
        print(f"\nREGRESSION: {len(regressed)} config(s) past "
              f"tolerance {args.tolerance:.0%}:")
        for path, name, delta in regressed:
            print(f"  {name} {delta:+.1%} ({path})")
    else:
        print("\nno regressions past tolerance")
    if args.check:
        return 1 if regressed else 0
    return 0


if __name__ == "__main__":
    sys.exit(main())
