"""Memo-on vs memo-off plan diff for a named TPC-H / TPC-DS query.

Prints both optimized logical plan shapes plus the cost model's estimate
of each (weighted total and the cpu/memory/network split), so a CBO
change can be eyeballed per query — the PlanPrinter-diff workflow the
reference drives through EXPLAIN before/after a rule lands.

Usage:
    python tools/plan_diff.py q3            # TPC-H Q3
    python tools/plan_diff.py tpcds/q72     # TPC-DS Q72
    python tools/plan_diff.py q9 --scale 0.01
"""

import argparse
import dataclasses as dc
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.pop("PALLAS_AXON_POOL_IPS", None)

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tests"))
sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def load_query(name: str):
    """'q3' / 'tpch/q3' -> TPC-H; 'tpcds/q72' -> TPC-DS.  Returns
    (catalog, sql)."""
    name = name.lower().lstrip("/")
    catalog = "tpch"
    if "/" in name:
        catalog, name = name.split("/", 1)
    num = int(name.lstrip("q"))
    if catalog == "tpch":
        from tpch_queries import QUERIES
    elif catalog == "tpcds":
        from tpcds_queries import QUERIES
    else:
        raise SystemExit(f"unknown catalog {catalog!r} (tpch or tpcds)")
    if num not in QUERIES:
        raise SystemExit(
            f"no {catalog} q{num}; have {sorted(QUERIES)}")
    return catalog, QUERIES[num]


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("query", help="q3 | tpch/q9 | tpcds/q72 ...")
    ap.add_argument("--scale", type=float, default=0.01)
    args = ap.parse_args(argv)

    from presto_tpu.config import DEFAULT
    from presto_tpu.localrunner import LocalQueryRunner
    from presto_tpu.sql.memo import CostComparator, CostModel
    from presto_tpu.sql.optimizer import optimize
    from presto_tpu.sql.parser import parse_statement
    from presto_tpu.sql.plan import format_plan
    from presto_tpu.sql.planner import Planner
    from presto_tpu.sql.stats import StatsCalculator

    catalog, sql = load_query(args.query)
    runner = LocalQueryRunner.tpch(scale=args.scale)
    runner.metadata.default_catalog = catalog
    stmt = parse_statement(sql)
    comparator = CostComparator()

    totals = {}
    for label, cfg in (("memo-on", DEFAULT),
                       ("memo-off (greedy)",
                        dc.replace(DEFAULT, optimizer_use_memo=False))):
        plan = optimize(Planner(runner.metadata).plan(stmt),
                        runner.metadata, cfg)
        model = CostModel(StatsCalculator(runner.metadata), cfg)
        cost = model.cumulative(plan)
        totals[label] = comparator.total(cost)
        print(f"=== {label} ===")
        print(f"estimated cost: total={comparator.total(cost):.4g} "
              f"(cpu={cost.cpu:.4g}, mem={cost.memory:.4g}, "
              f"net={cost.network:.4g})")
        print(format_plan(plan))
    on, off = totals["memo-on"], totals["memo-off (greedy)"]
    if on < off:
        print(f"memo plan is cheaper-estimated: {on:.4g} < {off:.4g} "
              f"({off / on:.2f}x)")
    elif on == off:
        print("memo and greedy plans cost the same estimate")
    else:
        print(f"WARNING: memo plan estimate {on:.4g} > greedy {off:.4g}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
