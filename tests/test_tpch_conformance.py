"""TPC-H conformance: all 22 queries vs a sqlite3 oracle.

The reference pins SQL semantics by running the same query against H2 and
diffing results (presto-testing/.../H2QueryRunner.java, QueryAssertions
.assertQuery).  Here the oracle is sqlite3 (stdlib): the same TPC-H data
is loaded into sqlite (dates as ISO strings), the query text is adapted to
sqlite's dialect (date literals/arithmetic pre-computed, extract -> substr)
and results are compared with float tolerance.
"""

import datetime
import math
import re
import sqlite3

import numpy as np
import pytest

from presto_tpu.localrunner import LocalQueryRunner

pytestmark = pytest.mark.slow


from tpch_queries import QUERIES

SCALE = 0.01

TABLES = ["region", "nation", "supplier", "customer", "part", "partsupp",
          "orders", "lineitem"]


@pytest.fixture(scope="module")
def runner():
    return LocalQueryRunner.tpch(scale=SCALE)


@pytest.fixture(scope="module")
def oracle(runner):
    """sqlite3 loaded with identical data."""
    conn = sqlite3.connect(":memory:")
    conn.execute("PRAGMA case_sensitive_like = ON")
    tpch = runner.registry.get("tpch")
    for table in TABLES:
        handle = tpch.get_table(table)
        schema = tpch.table_schema(handle)
        names = schema.column_names()
        cols_sql = ", ".join(f"{n} {_sqlite_type(schema.column_type(n))}"
                             for n in names)
        conn.execute(f"create table {table} ({cols_sql})")
        for split in tpch.get_splits(handle, 1):
            for batch in tpch.page_source(split, names, 65536):
                rows = batch.to_pylist()
                rows = [tuple(_to_sqlite(v) for v in r) for r in rows]
                ph = ", ".join("?" * len(names))
                conn.executemany(
                    f"insert into {table} values ({ph})", rows)
    conn.commit()
    return conn


def register_sqlite_fns(conn) -> None:
    """Statistical aggregates sqlite lacks but the suites use."""
    class _Var:
        def __init__(self, pop=False):
            self.n = 0
            self.s = 0.0
            self.sq = 0.0
            self.pop = pop

        def step(self, v):
            if v is None:
                return
            self.n += 1
            self.s += v
            self.sq += v * v

        def value(self):
            d = self.n if self.pop else self.n - 1
            if d <= 0:
                return None
            return max(self.sq - self.s * self.s / self.n, 0.0) / d

        def finalize(self):
            return self.value()

    def _std(pop):
        class _S(_Var):
            def __init__(self):
                super().__init__(pop)

            def finalize(self):
                v = self.value()
                return None if v is None else math.sqrt(v)

        return _S

    def _var(pop):
        class _V(_Var):
            def __init__(self):
                super().__init__(pop)

        return _V

    def _concat(*parts):
        # Presto concat is NULL-propagating (engine matches)
        if any(p is None for p in parts):
            return None
        return "".join(str(p) for p in parts)

    conn.create_function("concat", -1, _concat)
    conn.create_aggregate("stddev_samp", 1, _std(False))
    conn.create_aggregate("stddev", 1, _std(False))
    conn.create_aggregate("stddev_pop", 1, _std(True))
    conn.create_aggregate("var_samp", 1, _var(False))
    conn.create_aggregate("variance", 1, _var(False))
    conn.create_aggregate("var_pop", 1, _var(True))


def _sqlite_type(typ) -> str:
    if typ.name in ("varchar", "char"):
        return "TEXT"
    if typ.name == "date":
        return "TEXT"
    if typ.name in ("double", "real") or typ.name == "decimal":
        return "REAL"
    return "INTEGER"


def _to_sqlite(v):
    if isinstance(v, datetime.date):
        return v.isoformat()
    return v


_DATE_ARITH = re.compile(
    r"date\s+'(\d{4}-\d{2}-\d{2})'\s*([+-])\s*interval\s+'(\d+)'\s+"
    r"(year|month|day)")
_DATE_LIT = re.compile(r"date\s+'(\d{4}-\d{2}-\d{2})'")


def _shift_date(iso: str, sign: str, n: int, unit: str) -> str:
    d = datetime.date.fromisoformat(iso)
    k = n if sign == "+" else -n
    if unit == "day":
        return (d + datetime.timedelta(days=k)).isoformat()
    months = d.year * 12 + (d.month - 1) + (12 * k if unit == "year" else k)
    y, m = divmod(months, 12)
    day = min(d.day, [31, 29 if y % 4 == 0 and (y % 100 != 0 or y % 400 == 0)
                      else 28, 31, 30, 31, 30, 31, 31, 30, 31, 30, 31][m])
    return datetime.date(y, m + 1, day).isoformat()


def _strip_union_parens(sql: str) -> str:
    """sqlite rejects a parenthesized right-hand UNION operand
    (``... UNION ALL (SELECT ...)`` — same for INTERSECT/EXCEPT);
    strip those operand parens.  A
    paren BEFORE a union is left alone — it may be a derived table of
    the first operand (``SELECT ... FROM (sub) UNION ALL ...``)."""
    def match_fwd(s, open_):             # index of ')' matching s[open_]=='('
        depth = 0
        for i in range(open_, len(s)):
            if s[i] == "(":
                depth += 1
            elif s[i] == ")":
                depth -= 1
                if depth == 0:
                    return i
        return -1

    def match_back(s, close):            # index of '(' matching s[close]==')'
        depth = 0
        for i in range(close, -1, -1):
            if s[i] == ")":
                depth += 1
            elif s[i] == "(":
                depth -= 1
                if depth == 0:
                    return i
        return -1

    changed = True
    while changed:
        changed = False
        for m in re.finditer(r"(?i)\b(?:union(?:\s+all)?|intersect|except)\b", sql):
            # operand before: ( (SELECT ...) INTERSECT ... — strip only
            # when the operand paren is itself directly inside another
            # paren (a FROM-derived-table paren is preceded by FROM, not
            # by '(', and must stay)
            j = m.start() - 1
            while j >= 0 and sql[j].isspace():
                j -= 1
            if j >= 0 and sql[j] == ")":
                o = match_back(sql, j)
                p = o - 1
                while p >= 0 and sql[p].isspace():
                    p -= 1
                inner = sql[o + 1:j].lstrip()
                if (o >= 0 and inner[:6].lower() == "select"
                        and (p < 0 or sql[p] == "(")):
                    sql = (sql[:o] + " " + sql[o + 1:j] + " "
                           + sql[j + 1:])
                    changed = True
                    break
            # operand after: UNION ( SELECT ...
            k = m.end()
            while k < len(sql) and sql[k].isspace():
                k += 1
            if k < len(sql) and sql[k] == "(":
                c = match_fwd(sql, k)
                inner = sql[k + 1:c].lstrip()
                if c >= 0 and inner[:6].lower() == "select":
                    sql = (sql[:k] + " " + sql[k + 1:c] + " "
                           + sql[c + 1:])
                    changed = True
                    break
    return sql


_DECIMAL_CAST_TAIL = re.compile(
    r"(?i)\bas\s+decimal\s*(?:\(\s*\d+\s*(?:,\s*\d+\s*)?\))?\s*$")


def _decimal_division_casts_to_real(sql: str) -> str:
    """Rewrite ``CAST(x AS DECIMAL(p, s))`` to ``CAST(x AS REAL)`` only
    when the cast is an operand of ``/`` (the one context where sqlite's
    integer division diverges from decimal division).  Other decimal
    casts are left intact (ROADMAP #9: the global rewrite masked
    fixed-point semantics everywhere)."""
    def match_fwd(s, open_):
        depth = 0
        for i in range(open_, len(s)):
            if s[i] == "(":
                depth += 1
            elif s[i] == ")":
                depth -= 1
                if depth == 0:
                    return i
        return -1

    casts = []                       # (open paren idx, close paren idx)
    for m in re.finditer(r"(?i)\bcast\s*\(", sql):
        close = match_fwd(sql, m.end() - 1)
        if close >= 0:
            casts.append((m.start(), m.end() - 1, close))
    # rewrite right-to-left so earlier offsets stay valid
    for start, op, close in reversed(casts):
        inner = sql[op + 1:close]
        tail = _DECIMAL_CAST_TAIL.search(inner)
        if tail is None:
            continue
        j = start - 1                # char before CAST, skipping spaces
        while j >= 0 and sql[j].isspace():
            j -= 1
        k = close + 1                # char after ')', skipping spaces
        while k < len(sql) and sql[k].isspace():
            k += 1
        if (j < 0 or sql[j] != "/") and (k >= len(sql) or sql[k] != "/"):
            continue                 # not a division operand: keep
        new_inner = inner[:tail.start()] + "as real"
        sql = sql[:op + 1] + new_inner + sql[close:]
    return sql


def to_sqlite_sql(sql: str) -> str:
    # quoted function names ("sum"(...) in the benchto texts) are
    # identifiers to sqlite — unquote them
    sql = re.sub(r'"(\w+)"\s*\(', r"\1(", sql)
    sql = _strip_union_parens(sql)
    # DECIMAL '1.2' typed literals -> plain numeric literal
    sql = re.sub(r"(?i)\bdecimal\s+'(-?[0-9.]+)'", r"\1", sql)
    # CAST(x AS DECIMAL(p, s)) -> CAST(x AS REAL), division contexts
    # only: sqlite NUMERIC affinity keeps integers integral, so q75's
    # cast(cnt as decimal)/cast(cnt as decimal) would integer-divide
    # (61/62 = 0) and wrongly pass the < 0.9 filter the engine's real
    # decimal division correctly rejects.  Elsewhere (q05's typed zero
    # columns, q18's avg inputs) the decimal cast keeps its NUMERIC
    # affinity so the oracle exercises the same fixed-point semantics
    # as the engine instead of drifting through binary floats.
    sql = _decimal_division_casts_to_real(sql)
    sql = _DATE_ARITH.sub(
        lambda m: "'" + _shift_date(m.group(1), m.group(2),
                                    int(m.group(3)), m.group(4)) + "'",
        sql)
    # CAST(x AS DATE) truncates TEXT to an integer in sqlite; dates are
    # already ISO strings, so drop the cast (literals and columns alike)
    sql = re.sub(r"(?i)\bcast\s*\(\s*('[^']*'|\"?[\w.]+\"?)\s+as\s+date"
                 r"\s*\)", r"\1", sql)
    # (date_expr + INTERVAL '30' DAY) over TEXT dates
    sql = re.sub(
        r"(?i)\(?\s*('[^']*'|[\w.\"]+)\s*([+-])\s*interval\s+'(\d+)'"
        r"\s+day\s*\)?",
        lambda m: f"date({m.group(1)}, '{m.group(2)}{m.group(3)} days')",
        sql)
    sql = _DATE_LIT.sub(lambda m: "'" + m.group(1) + "'", sql)
    sql = re.sub(r"extract\s*\(\s*year\s+from\s+(\w+(?:\.\w+)?)\s*\)",
                 r"cast(substr(\1, 1, 4) as integer)", sql)
    # date_diff('day', a, b) -> whole-day difference on ISO strings
    sql = re.sub(
        r"date_diff\s*\(\s*'day'\s*,\s*([\w.]+)\s*,\s*([\w.]+)\s*\)",
        r"cast(julianday(\2) - julianday(\1) as integer)", sql)
    # sqlite has no derived-table column alias lists (``as t (a, b)``);
    # the inner selects already alias matching names (Q13), so drop them
    sql = re.sub(r"\bas\s+(\w+)\s*\(\s*\w+(?:\s*,\s*\w+)*\s*\)",
                 r"as \1", sql)
    # NULL ordering: Presto ASC = NULLS LAST / DESC = NULLS FIRST;
    # sqlite defaults to the opposite
    sql = re.sub(r"(?i)\basc\b(?!\s+nulls)", "ASC NULLS LAST", sql)
    sql = re.sub(r"(?i)\bdesc\b(?!\s+nulls)", "DESC NULLS FIRST", sql)
    return sql


def _normalize(rows):
    out = []
    for r in rows:
        norm = []
        for v in r:
            if isinstance(v, datetime.date):
                norm.append(v.isoformat())
            elif isinstance(v, (np.integer,)):
                norm.append(int(v))
            elif isinstance(v, (np.floating,)):
                norm.append(float(v))
            else:
                norm.append(v)
        out.append(tuple(norm))
    return out


def _row_key(r):
    return tuple("" if v is None else str(v) for v in r)


def assert_rows_match(got, want, ordered):
    got = _normalize(got)
    want = _normalize(want)
    assert len(got) == len(want), (
        f"row count {len(got)} != {len(want)}\n"
        f"got[:5]={got[:5]}\nwant[:5]={want[:5]}")
    if not ordered:
        got = sorted(got, key=_row_key)
        want = sorted(want, key=_row_key)
    for i, (g, w) in enumerate(zip(got, want)):
        assert len(g) == len(w), f"row {i}: arity {len(g)} != {len(w)}"
        for j, (a, b) in enumerate(zip(g, w)):
            if a is None or b is None:
                assert a is None and b is None, f"row {i} col {j}: {a}!={b}"
            elif isinstance(a, float) or isinstance(b, float):
                assert math.isclose(float(a), float(b), rel_tol=1e-6,
                                    abs_tol=1e-6), \
                    f"row {i} col {j}: {a} != {b}"
            else:
                assert a == b, f"row {i} col {j}: {a!r} != {b!r}"


@pytest.mark.parametrize("qnum", sorted(QUERIES))
def test_tpch_query(runner, oracle, qnum):
    sql = QUERIES[qnum]
    got = runner.execute(sql).rows
    want = oracle.execute(to_sqlite_sql(sql)).fetchall()
    # ordered comparison when the ORDER BY forms a total order prefix;
    # ties beyond the sort keys make positional diffs flaky, so compare
    # as sorted multisets (sort keys are part of each row, so ordering
    # errors still surface for fully-keyed rows)
    assert_rows_match(got, want, ordered=False)
