"""SQL-on-the-mesh tests: real SQL through parser -> planner -> fragmenter
-> one shard_mapped SPMD program on the 8-device virtual CPU mesh, verified
against the single-node operator tier (the DistributedQueryRunner-style
in-one-process rig of SURVEY §4.3, with collectives instead of HTTP).
"""

import pytest

from presto_tpu.localrunner import LocalQueryRunner
from presto_tpu.parallel.sqlmesh import MeshQueryRunner, MeshUnsupported

pytestmark = pytest.mark.slow


SCALE = 0.005  # tiny: the 1-core CI host executes 8 shards sequentially


@pytest.fixture(scope="module")
def runners():
    return (MeshQueryRunner.tpch(scale=SCALE),
            LocalQueryRunner.tpch(scale=SCALE))


def _close(a, b):
    if isinstance(a, float) and isinstance(b, float):
        return abs(a - b) <= 1e-6 * max(1.0, abs(a), abs(b))
    return a == b



def _pair_key(r):
    """Sort key that pairs rows robustly across float summation-order
    noise: floats participate rounded, so nearly-equal rows sort
    identically on both sides."""
    return tuple(
        (1, round(v, 4)) if isinstance(v, float)
        else (2, "") if v is None
        else (0, str(v))
        for v in r)


def assert_same(mesh_result, local_result, ordered=False):
    m, l = mesh_result.rows, local_result.rows
    if not ordered:
        m, l = sorted(m, key=_pair_key), sorted(l, key=_pair_key)
    assert len(m) == len(l), (len(m), len(l))
    for x, y in zip(m, l):
        assert len(x) == len(y), (x, y)
        for u, v in zip(x, y):
            assert _close(u, v), (x, y)


def check(runners, sql, ordered=False):
    mesh, local = runners
    assert_same(mesh.execute(sql), local.execute(sql), ordered)


def test_global_aggregate(runners):
    check(runners, "select count(*), sum(l_quantity), min(l_shipdate), "
                   "max(l_extendedprice) from lineitem")


def test_filtered_aggregate(runners):
    check(runners,
          "select sum(l_extendedprice * l_discount) from lineitem "
          "where l_discount between 0.05 and 0.07 and l_quantity < 24")


def test_group_by_exchange(runners):
    # partial agg -> hash exchange on the key -> final agg
    check(runners, "select l_returnflag, l_linestatus, count(*), "
                   "sum(l_quantity), avg(l_extendedprice) from lineitem "
                   "group by l_returnflag, l_linestatus")


def test_hash_join_groupby(runners):
    check(runners,
          "select c_mktsegment, count(*) from customer "
          "join orders on c_custkey = o_custkey group by c_mktsegment")


def test_broadcast_join(runners):
    # nation is tiny -> P2 broadcast of the build side
    check(runners,
          "select n_name, count(*) from nation "
          "join customer on n_nationkey = c_nationkey "
          "group by n_name order by count(*) desc, n_name limit 5",
          ordered=True)


def test_distributed_topn(runners):
    # per-shard sort+limit -> gather -> final merge sort+limit
    check(runners,
          "select o_orderkey, o_totalprice from orders "
          "order by o_totalprice desc limit 10", ordered=True)


def test_left_join(runners):
    check(runners,
          "select c_custkey, o_orderkey from customer "
          "left join orders on c_custkey = o_custkey "
          "where c_custkey <= 100")


def test_semi_join(runners):
    check(runners,
          "select count(*) from orders where o_custkey in "
          "(select c_custkey from customer where c_mktsegment = "
          "'BUILDING')")


def test_anti_join(runners):
    check(runners,
          "select count(*) from customer where c_custkey not in "
          "(select o_custkey from orders)")


def test_string_functions_on_mesh(runners):
    # per-dictionary-entry evaluation becomes a device gather in-program
    check(runners,
          "select p_brand, count(*) from part "
          "where p_type like 'PROMO%' group by p_brand")


# queries whose top-level ORDER BY is a TOTAL order on the output (no
# ties possible) compare row-by-row; the rest (float-sum sort keys or
# tie-prone count prefixes) compare as multisets
_TOTAL_ORDER = {1, 4, 12, 22}


@pytest.mark.parametrize("qn", list(range(1, 23)))
def test_tpch_suite_on_mesh(runners, qn):
    """The full 22-query TPC-H conformance suite through the one-program
    SPMD mesh tier vs the operator tier — the flagship execution mode's
    claim, tested query by query (VERDICT r3 weak #1)."""
    import tests.tpch_queries as Q

    sql = Q.QUERIES[qn]
    check(runners, sql, ordered=qn in _TOTAL_ORDER)


def test_window_functions_on_mesh(runners):
    check(runners,
          "select o_custkey, o_orderkey, "
          "row_number() over (partition by o_custkey "
          "order by o_orderdate, o_orderkey) as rn, "
          "rank() over (order by o_orderdate) as r, "
          "sum(o_totalprice) over (partition by o_custkey "
          "order by o_orderkey) as running "
          "from orders order by o_custkey, rn limit 50", ordered=True)
    check(runners,
          "select o_orderkey, lag(o_totalprice) over "
          "(partition by o_custkey order by o_orderkey) "
          "from orders", ordered=False)


def test_tpch_q3_on_mesh(runners):
    import tests.tpch_queries as Q

    check(runners, Q.QUERIES[3], ordered=True)


def test_tpch_q6_on_mesh(runners):
    import tests.tpch_queries as Q

    check(runners, Q.QUERIES[6])


def test_cross_join_under_aggregation(runners):
    # non-parallel-safe subtree: the fragmenter must run it single-task,
    # not slice both sides per shard (16 instead of 125 regression)
    check(runners, "select count(*) from nation, region")


def test_inner_limit_under_aggregation(runners):
    # per-shard LIMIT replication regression (40 instead of 5)
    check(runners, "select count(*) from (select * from orders limit 5)")


def test_scalar_subquery(runners):
    # replicated scalar row must not multiply through exchanges (the
    # TPC-H Q15 x8-duplication regression)
    check(runners,
          "select o_orderkey from orders where o_totalprice = "
          "(select max(o_totalprice) from orders)")


def test_unsupported_falls_out(runners):
    mesh, _ = runners
    with pytest.raises(MeshUnsupported):
        mesh.execute("select l_returnflag, "
                     "rank() over (order by count(*)) from lineitem "
                     "group by l_returnflag")


def test_union_all_distributes(runners):
    check(runners,
          "select count(*), sum(x) from ("
          "select o_totalprice x from orders "
          "union all select l_extendedprice x from lineitem)")


@pytest.mark.parametrize("qn", [72, 95])
def test_tpcds_baseline_configs_on_mesh(qn):
    """The BASELINE.md multi-chip configs (TPC-DS Q72/Q95) through the
    SPMD mesh tier — the whole skewed multi-join / semijoin plan as one
    shard_mapped program — pinned against the operator tier (ROADMAP
    #3's 'no TPC-DS query has ever run on the mesh')."""
    import tests.tpcds_queries as DS
    from presto_tpu.connectors.api import ConnectorRegistry
    from presto_tpu.connectors.tpcds import TpcdsConnector

    scale = 0.001   # the join tower is heavy on the 1-core CI host
    mesh = MeshQueryRunner.tpcds(scale=scale, n_devices=2)
    reg = ConnectorRegistry()
    reg.register("tpcds", TpcdsConnector(scale=scale))
    local = LocalQueryRunner(reg, "tpcds")
    assert_same(mesh.execute(DS.QUERIES[qn]),
                local.execute(DS.QUERIES[qn]))
