"""Streaming aggregation over key-clustered scans
(StreamingAggregationOperator.java:38 role): results equal the hash
aggregation, the carry survives batch boundaries, and the planner picks
the operator exactly when the keys are a sort-order prefix."""

import pytest

from presto_tpu.config import EngineConfig
from presto_tpu.localrunner import LocalQueryRunner

SCALE = 0.01


def _runner(streaming: bool, batch_rows: int = 4096) -> LocalQueryRunner:
    cfg = EngineConfig(streaming_aggregation_enabled=streaming,
                       task_concurrency=1, scan_batch_rows=batch_rows)
    return LocalQueryRunner.tpch(scale=SCALE, config=cfg)


@pytest.fixture(scope="module")
def on():
    return _runner(True)


@pytest.fixture(scope="module")
def off():
    return _runner(False)


def _same(on, off, sql):
    a = sorted(on.execute(sql).rows, key=repr)
    b = sorted(off.execute(sql).rows, key=repr)
    assert len(a) == len(b), (len(a), len(b))
    for x, y in zip(a, b):
        for u, v in zip(x, y):
            if isinstance(u, float):
                assert u == pytest.approx(v, rel=1e-9), (x, y)
            else:
                assert u == v, (x, y)


def test_clustered_group_by(on, off):
    # l_orderkey is the lineitem sort key: streaming path engages
    _same(on, off,
          "select l_orderkey, count(*), sum(l_quantity), "
          "min(l_extendedprice), max(l_discount) from lineitem "
          "group by l_orderkey")
    stats = on._last_task.operator_stats
    assert any("StreamingAggregation" in s.operator for s in stats), \
        [s.operator for s in stats]


def test_carry_across_tiny_batches(on):
    # 64-row batches guarantee many groups straddle batch boundaries
    tiny = _runner(True, batch_rows=64)
    base = _runner(False)
    _same(tiny, base,
          "select l_orderkey, count(*), sum(l_extendedprice) "
          "from lineitem where l_orderkey < 500 group by l_orderkey")


def test_multi_key_prefix(on, off):
    _same(on, off,
          "select l_orderkey, l_linenumber, sum(l_quantity) "
          "from lineitem group by l_orderkey, l_linenumber")


def test_non_prefix_uses_hash(on):
    # l_partkey is not the sort key: the hash path must be chosen
    on.execute("select l_partkey, count(*) from lineitem "
               "where l_partkey < 50 group by l_partkey")
    stats = on._last_task.operator_stats
    assert not any("StreamingAggregation" in s.operator for s in stats)


def test_filtered_clustered(on, off):
    _same(on, off,
          "select o_orderkey, count(*) from orders "
          "where o_totalprice > 100000 group by o_orderkey")
