"""Memo/CBO tier tests: group dedup, non-destructive exploration, cost
monotonicity, greedy fallback, cost-chosen join distribution, and memo-on
vs memo-off parity on TPC-H Q3/Q9 (the reference pattern: Memo.java +
ReorderJoins/DetermineJoinDistributionType unit tiers plus
TestJoinQueries parity)."""

import dataclasses as dc

import pytest

from presto_tpu import types as T
from presto_tpu.config import DEFAULT
from presto_tpu.expr import build as B
from presto_tpu.localrunner import LocalQueryRunner
from presto_tpu.sql.memo import (
    CostComparator, CostEstimate, CostModel, DetermineJoinDistribution,
    GroupRef, Memo, MemoOptimizer, MemoStatsCalculator,
    try_memo_extract_joins,
)
from presto_tpu.sql.optimizer import optimize
from presto_tpu.sql.parser import parse_statement
from presto_tpu.sql.plan import (
    FilterNode, JoinNode, PlanNode, TableScanNode, format_plan,
)
from presto_tpu.sql.planner import Planner
from presto_tpu.sql.rules import MergeFilters, RuleContext

MEMO_OFF = dc.replace(DEFAULT, optimizer_use_memo=False)


@pytest.fixture(scope="module")
def runner():
    return LocalQueryRunner.tpch(scale=0.01)


def _scan(table="nation", cols=(("a", T.BIGINT), ("b", T.BIGINT))):
    return TableScanNode("tpch", table, tuple(n for n, _ in cols),
                         tuple(cols))


class TestMemoGroups:
    def test_structurally_equal_subtrees_share_a_group(self):
        memo = Memo()
        g1 = memo.insert(FilterNode(_scan(), B.comparison(
            "<", B.ref(0, T.BIGINT), B.const(5, T.BIGINT))))
        g2 = memo.insert(FilterNode(_scan(), B.comparison(
            "<", B.ref(0, T.BIGINT), B.const(5, T.BIGINT))))
        assert g1 == g2
        assert len(memo.members(g1)) == 1

    def test_children_become_group_refs(self):
        memo = Memo()
        gid = memo.insert(FilterNode(_scan(), B.comparison(
            "<", B.ref(0, T.BIGINT), B.const(5, T.BIGINT))))
        (member,) = memo.members(gid)
        assert isinstance(member, FilterNode)
        assert isinstance(member.source, GroupRef)
        # the scan landed in its own (shared) group
        (scan,) = memo.members(member.source.group)
        assert isinstance(scan, TableScanNode)

    def test_add_alternative_dedupes(self):
        memo = Memo()
        gid = memo.insert(_scan())
        assert not memo.add(gid, _scan())
        assert len(memo.members(gid)) == 1


class TestExploration:
    def test_rules_run_non_destructively_over_groups(self, runner):
        """MergeFilters over a Filter(Filter(scan)) group ADDS the merged
        alternative (the original member stays) and extraction commits
        the rewrite — rules.py semantics, minus the destruction."""
        pred1 = B.comparison("<", B.ref(0, T.BIGINT),
                             B.const(20, T.BIGINT))
        pred2 = B.comparison(">", B.ref(0, T.BIGINT),
                             B.const(3, T.BIGINT))
        scan = TableScanNode("tpch", "nation",
                             ("n_nationkey", "n_regionkey"),
                             (("n_nationkey", T.BIGINT),
                              ("n_regionkey", T.BIGINT)))
        plan = FilterNode(FilterNode(scan, pred1), pred2)
        memo = Memo()
        gid = memo.insert(plan)
        opt = MemoOptimizer(memo, metadata=runner.metadata)
        added = opt.explore(RuleContext(runner.metadata, DEFAULT),
                            [MergeFilters()])
        assert added >= 1
        members = memo.members(gid)
        assert len(members) >= 2               # original + merged
        assert isinstance(members[0].source, GroupRef)   # untouched
        best = opt.best(gid)
        assert best is not None
        _, _, chosen = best
        # the chosen plan is the single merged filter over the scan
        assert isinstance(chosen, FilterNode)
        assert isinstance(chosen.source, TableScanNode)

    def test_extraction_materializes_concrete_plan(self, runner):
        memo = Memo()
        gid = memo.insert(FilterNode(_scan("nation", (
            ("n_nationkey", T.BIGINT),)), B.comparison(
                "<", B.ref(0, T.BIGINT), B.const(5, T.BIGINT))))
        opt = MemoOptimizer(memo, metadata=runner.metadata)
        _, _, plan = opt.best(gid)

        def no_refs(node: PlanNode) -> bool:
            if isinstance(node, GroupRef):
                return False
            return all(no_refs(s) for s in node.sources)

        assert no_refs(plan)


class TestCostModel:
    def test_cumulative_cost_monotone_in_children(self, runner):
        """A join's cumulative cost dominates each child's cumulative
        cost, and bigger inputs cost more (cost pruning soundness)."""
        sql = ("select count(*) from orders, lineitem "
               "where o_orderkey = l_orderkey")
        plan = optimize(Planner(runner.metadata).plan(
            parse_statement(sql)), runner.metadata, DEFAULT)

        joins = []

        def walk(n):
            if isinstance(n, JoinNode):
                joins.append(n)
            for s in n.sources:
                walk(s)

        walk(plan)
        assert joins
        from presto_tpu.sql.stats import StatsCalculator

        model = CostModel(StatsCalculator(runner.metadata), DEFAULT)
        comparator = CostComparator()
        for j in joins:
            total = comparator.total(model.cumulative(j))
            for side in (j.left, j.right):
                assert total >= comparator.total(model.cumulative(side))

    def test_cost_estimate_addition(self):
        a = CostEstimate(1.0, 2.0, 3.0)
        b = CostEstimate(10.0, 20.0, 30.0)
        assert a + b == CostEstimate(11.0, 22.0, 33.0)


class TestFallback:
    def test_stats_absent_falls_back_to_greedy(self):
        """No metadata -> leaf row counts unknown -> the memo declines
        and the caller keeps the greedy path."""
        from presto_tpu.expr.ir import InputRef

        scan_a = _scan("a")
        scan_b = _scan("b")
        cross = JoinNode("cross", scan_a, scan_b, (), (),
                         scan_a.columns + scan_b.columns)
        pred = B.comparison("=", InputRef(0, T.BIGINT),
                            InputRef(2, T.BIGINT))
        out = try_memo_extract_joins(FilterNode(cross, pred), None, DEFAULT)
        assert out is None

    def test_oversized_graph_falls_back(self, runner):
        cfg = dc.replace(DEFAULT, memo_max_reorder_relations=2)
        sql = """select count(*) from customer, orders, lineitem
                 where c_custkey = o_custkey and l_orderkey = o_orderkey"""
        plan = optimize(Planner(runner.metadata).plan(
            parse_statement(sql)), runner.metadata, cfg)
        text = format_plan(plan)
        assert "dist=" not in text    # greedy path: no memo annotations

    def test_memo_off_matches_greedy_exactly(self, runner):
        """optimizer_use_memo=false restores the pre-memo plans: the
        config gate is the ONLY divergence point."""
        sql = """select o_orderdate, sum(l_extendedprice)
                 from customer, orders, lineitem
                 where c_custkey = o_custkey and l_orderkey = o_orderkey
                   and c_mktsegment = 'BUILDING'
                 group by o_orderdate"""
        stmt = parse_statement(sql)
        off = optimize(Planner(runner.metadata).plan(stmt),
                       runner.metadata, MEMO_OFF)
        strategy_none = optimize(
            Planner(runner.metadata).plan(stmt), runner.metadata,
            dc.replace(DEFAULT, join_reordering_strategy="none"))
        # memo respects join_reordering_strategy=none the same way the
        # greedy path does (syntactic order, no exploration)
        assert "dist=" not in format_plan(strategy_none)
        assert isinstance(off, type(strategy_none))


class TestDetermineJoinDistribution:
    def _join(self, runner, sql):
        plan = optimize(Planner(runner.metadata).plan(
            parse_statement(sql)), runner.metadata, DEFAULT)

        joins = []

        def walk(n):
            if isinstance(n, JoinNode):
                joins.append(n)
            for s in n.sources:
                walk(s)

        walk(plan)
        return joins

    def test_small_build_marks_replicated(self, runner):
        joins = self._join(
            runner,
            "select count(*) from lineitem, nation "
            "where l_suppkey = n_nationkey")
        assert any(j.distribution == "replicated" for j in joins), joins

    def test_build_above_broadcast_cap_marks_partitioned(self, runner):
        """The broadcast row limit survives as the admissibility cap:
        above it, cost may not choose REPLICATED."""
        scan = TableScanNode(
            "tpch", "orders", ("o_orderkey",), (("o_orderkey", T.BIGINT),))
        scan2 = TableScanNode(
            "tpch", "lineitem", ("l_orderkey",),
            (("l_orderkey", T.BIGINT),))
        join = JoinNode("inner", scan2, scan, (0,), (0,),
                        scan2.columns + scan.columns)
        memo = Memo()
        stats = MemoStatsCalculator(memo, runner.metadata)
        cfg = dc.replace(DEFAULT, broadcast_join_row_limit=100)
        rule = DetermineJoinDistribution(CostModel(stats, cfg))
        out = rule.apply(join, RuleContext(runner.metadata, cfg))
        assert out is not None and out.distribution == "partitioned"

    def test_forced_distribution_skips_annotation(self, runner):
        scan = TableScanNode(
            "tpch", "nation", ("n_nationkey",),
            (("n_nationkey", T.BIGINT),))
        scan2 = TableScanNode(
            "tpch", "lineitem", ("l_suppkey",), (("l_suppkey", T.BIGINT),))
        join = JoinNode("inner", scan2, scan, (0,), (0,),
                        scan2.columns + scan.columns)
        memo = Memo()
        stats = MemoStatsCalculator(memo, runner.metadata)
        cfg = dc.replace(DEFAULT, join_distribution_type="broadcast")
        rule = DetermineJoinDistribution(CostModel(stats, cfg))
        assert rule.apply(join, RuleContext(runner.metadata, cfg)) is None


class TestSerde:
    def test_distribution_round_trips(self):
        from presto_tpu.sql.planserde import node_from_json, node_to_json

        scan = _scan("a")
        scan2 = _scan("b")
        join = JoinNode("inner", scan, scan2, (0,), (0,),
                        scan.columns + scan2.columns,
                        distribution="replicated")
        back = node_from_json(node_to_json(join))
        assert back.distribution == "replicated"
        plain = node_from_json(node_to_json(
            dc.replace(join, distribution=None)))
        assert plain.distribution is None


@pytest.mark.parametrize("qnum", [3, 9])
def test_memo_parity_tpch(runner, qnum):
    """Smoke: memo-on produces valid, value-parity results on TPC-H
    Q3/Q9 vs the memo-off (greedy) plans."""
    import sys
    sys.path.insert(0, "tests")
    from tpch_queries import QUERIES

    sql = QUERIES[qnum]
    runner.execute("set session optimizer_use_memo = true")
    on = runner.execute(sql)
    runner.execute("set session optimizer_use_memo = false")
    off = runner.execute(sql)
    runner.execute("reset session optimizer_use_memo")
    assert on.column_names == off.column_names

    def canon(rows):
        return sorted(
            tuple(round(v, 6) if isinstance(v, float) else v for v in r)
            for r in rows)

    assert canon(on.rows) == canon(off.rows)
