"""Distributed client-session protocol tests: SET SESSION / USE /
PREPARE travel as client-tracked state on request headers, and session
properties reach worker task configs (StatementClientV1 session
tracking + SystemSessionProperties roles)."""

import pytest

from presto_tpu.server.dqr import DistributedQueryRunner


@pytest.fixture(scope="module")
def cluster():
    with DistributedQueryRunner.tpch(scale=0.01, n_workers=2) as dqr:
        yield dqr


def test_set_session_tracked_and_applied(cluster):
    client = cluster.client
    cluster.execute("SET SESSION scan_batch_rows = 4096")
    assert client.session_properties == {"scan_batch_rows": "4096"}
    got = cluster.execute("SHOW SESSION").rows
    by_name = {r[0]: r[1] for r in got}
    assert by_name["scan_batch_rows"] == "4096"
    cluster.execute("RESET SESSION scan_batch_rows")
    assert client.session_properties == {}


def test_bad_session_property_rejected(cluster):
    from presto_tpu.client import QueryFailed

    with pytest.raises(QueryFailed, match="unknown session property"):
        cluster.execute("SET SESSION no_such_prop = 1")
    assert "no_such_prop" not in cluster.client.session_properties


def test_session_property_reaches_worker_tasks(cluster, monkeypatch):
    from presto_tpu.server.task import SqlTaskManager

    seen = []
    orig = SqlTaskManager.create_task

    def spy(self, *args, **kwargs):
        seen.append(kwargs.get("session_properties"))
        return orig(self, *args, **kwargs)

    monkeypatch.setattr(SqlTaskManager, "create_task", spy)
    cluster.execute("SET SESSION scan_batch_rows = 8192")
    try:
        cluster.execute("SELECT count(*) FROM lineitem")
        assert seen and all(p == {"scan_batch_rows": "8192"}
                            for p in seen if p is not None)
    finally:
        cluster.execute("RESET SESSION scan_batch_rows")


def test_use_catalog(cluster):
    cluster.execute("USE memory")
    assert cluster.client.catalog == "memory"
    cluster.execute("CREATE TABLE uc (a bigint)")
    cluster.execute("INSERT INTO uc VALUES (7)")
    assert cluster.execute("SELECT a FROM uc").rows == [(7,)]
    cluster.execute("USE tpch")
    assert cluster.execute("SELECT count(*) FROM nation").rows == [(25,)]


def test_prepare_execute_over_protocol(cluster):
    cluster.execute("PREPARE dq FROM SELECT n_name FROM nation "
                    "WHERE n_nationkey = ?")
    assert "dq" in cluster.client.prepared_statements
    assert cluster.execute("EXECUTE dq USING 3").rows == [("CANADA",)]
    assert cluster.execute("EXECUTE dq USING 0").rows == [("ALGERIA",)]
    cluster.execute("DEALLOCATE PREPARE dq")
    assert "dq" not in cluster.client.prepared_statements
    from presto_tpu.client import QueryFailed

    with pytest.raises(QueryFailed, match="not found"):
        cluster.execute("EXECUTE dq USING 1")


def test_prepared_distributed_aggregate(cluster):
    cluster.execute("PREPARE agg FROM SELECT l_returnflag, count(*) "
                    "FROM lineitem WHERE l_quantity < ? "
                    "GROUP BY l_returnflag ORDER BY l_returnflag")
    got = cluster.execute("EXECUTE agg USING 10").rows
    want = [r for r in got]  # sanity: 3 flags, counts positive
    assert [r[0] for r in got] == ["A", "N", "R"]
    assert all(c > 0 for _, c in got)
    cluster.execute("DEALLOCATE PREPARE agg")


def test_use_catalog_schema_tracked(cluster):
    cluster.execute("USE tpch.tiny")
    assert cluster.client.catalog == "tpch"
    assert cluster.client.schema == "tiny"
    cluster.execute("USE tpch")


def test_session_survives_proxy(cluster):
    from presto_tpu.client import StatementClient
    from presto_tpu.server.proxy import ProxyServer

    proxy = ProxyServer(cluster.coordinator.uri)
    try:
        c = StatementClient(proxy.uri)
        c.execute("SET SESSION scan_batch_rows = 777")
        c.execute("PREPARE px FROM SELECT count(*) FROM nation "
                  "WHERE n_regionkey = ?")
        cols, data = c.execute("EXECUTE px USING 1")
        assert data == [[5]]
        by_name = dict(r[:2] for r in c.execute("SHOW SESSION")[1])
        assert by_name["scan_batch_rows"] == "777"
        c.execute("DEALLOCATE PREPARE px")
    finally:
        proxy.close()
