"""Pallas direct-groupby kernel: correctness under interpret mode.

On CPU the kernel runs through the Pallas interpreter; the real-TPU
compile path was validated on v5e (see ops/pallas_groupby.py docstring
for the measured status vs the XLA einsum)."""

import numpy as np
import pytest

from presto_tpu.ops import pallas_groupby as P


@pytest.mark.skipif(not P.available(), reason="pallas unavailable")
@pytest.mark.parametrize("n,a,g", [(4096, 5, 8), (65536, 13, 8),
                                   (8192, 3, 31)])
def test_segment_sums_match_numpy(n, a, g):
    import jax.numpy as jnp

    rng = np.random.default_rng(0)
    gid = rng.integers(0, g, n).astype(np.int32)
    vals = rng.uniform(0, 1e5, (n, a))
    hi = vals.astype(np.float32)
    lo = (vals - hi.astype(np.float64)).astype(np.float32)
    out = P.direct_segment_sums_pallas(
        jnp.asarray(gid), jnp.asarray(hi), jnp.asarray(lo), g,
        interpret=True)
    ref = np.zeros((g, a))
    np.add.at(ref, gid, vals)
    err = np.abs(np.asarray(out) - ref) / np.maximum(np.abs(ref), 1)
    # per-dot f32 rounding bounds the error (same bound as the einsum
    # path); the compensated pairs keep cross-block accumulation exact
    assert err.max() < 1e-6


@pytest.mark.skipif(not P.available(), reason="pallas unavailable")
def test_engine_results_identical_with_pallas_flag(monkeypatch):
    """The engine must produce identical Q1-shape results whichever
    reduction path is active (flag plumbing check; on CPU the pallas
    gate also requires the TPU backend, so this exercises the gate)."""
    import presto_tpu.ops.groupby as G

    monkeypatch.setenv("PRESTO_TPU_PALLAS", "1")
    from presto_tpu.localrunner import LocalQueryRunner

    r = LocalQueryRunner.tpch(scale=0.01)
    sql = ("select l_returnflag, l_linestatus, sum(l_quantity), count(*) "
           "from lineitem group by l_returnflag, l_linestatus")
    a = sorted(r.execute(sql).rows)
    monkeypatch.setenv("PRESTO_TPU_PALLAS", "0")
    b = sorted(r.execute(sql).rows)
    assert a == b
