"""Pallas kernels: correctness under interpret mode.

On CPU the kernels run through the Pallas interpreter; the real-TPU
compile path was validated on v5e (see ops/pallas_groupby.py docstring
for the measured status vs the XLA einsum).  The open-addressing table
section covers BOTH formulations of the hash tier — the shipping XLA
claim loop (ops/hashtable.py) and the serial Pallas rendering
(ops/pallas_hash.py) — against numpy oracles: collision storms, the
rehash boundary (including the min/max identity carry), null keys, and
the 1-byte hash-prefix reject."""

import collections

import numpy as np
import pytest

from presto_tpu.ops import pallas_groupby as P


@pytest.mark.skipif(not P.available(), reason="pallas unavailable")
@pytest.mark.parametrize("n,a,g", [(4096, 5, 8), (65536, 13, 8),
                                   (8192, 3, 31)])
def test_segment_sums_match_numpy(n, a, g):
    import jax.numpy as jnp

    rng = np.random.default_rng(0)
    gid = rng.integers(0, g, n).astype(np.int32)
    vals = rng.uniform(0, 1e5, (n, a))
    hi = vals.astype(np.float32)
    lo = (vals - hi.astype(np.float64)).astype(np.float32)
    out = P.direct_segment_sums_pallas(
        jnp.asarray(gid), jnp.asarray(hi), jnp.asarray(lo), g,
        interpret=True)
    ref = np.zeros((g, a))
    np.add.at(ref, gid, vals)
    err = np.abs(np.asarray(out) - ref) / np.maximum(np.abs(ref), 1)
    # per-dot f32 rounding bounds the error (same bound as the einsum
    # path); the compensated pairs keep cross-block accumulation exact
    assert err.max() < 1e-6


@pytest.mark.skipif(not P.available(), reason="pallas unavailable")
def test_engine_results_identical_with_pallas_flag(monkeypatch):
    """The engine must produce identical Q1-shape results whichever
    reduction path is active (flag plumbing check; on CPU the pallas
    gate also requires the TPU backend, so this exercises the gate)."""
    import presto_tpu.ops.groupby as G

    monkeypatch.setenv("PRESTO_TPU_PALLAS", "1")
    from presto_tpu.localrunner import LocalQueryRunner

    r = LocalQueryRunner.tpch(scale=0.01)
    sql = ("select l_returnflag, l_linestatus, sum(l_quantity), count(*) "
           "from lineitem group by l_returnflag, l_linestatus")
    a = sorted(r.execute(sql).rows)
    monkeypatch.setenv("PRESTO_TPU_PALLAS", "0")
    b = sorted(r.execute(sql).rows)
    assert a == b


# ---------------------------------------------------------------------------
# open-addressing hash table (ops/hashtable.py + ops/pallas_hash.py)
# ---------------------------------------------------------------------------

def _groupby_oracle(keys, valid, vals):
    ref_sum = collections.defaultdict(float)
    ref_cnt = collections.defaultdict(int)
    for i, k in enumerate(keys):
        kk = int(k) if (valid is None or valid[i]) else None
        ref_sum[kk] += float(vals[i])
        ref_cnt[kk] += 1
    return ref_sum, ref_cnt


def _extract_map(state):
    from presto_tpu.ops import hashtable as H

    n, key_outs, agg_outs = H.groupby_extract(state)
    n = int(n)
    kv, kvalid = key_outs[0]
    kv = np.asarray(kv)[:n]
    kb = (np.ones(n, bool) if kvalid is None
          else np.asarray(kvalid)[:n])
    out = {}
    for i in range(n):
        kk = int(kv[i]) if kb[i] else None
        out[kk] = tuple(float(np.asarray(acc)[:n][i])
                        for acc, _nn in agg_outs)
    return n, out


def test_hash_groupby_collision_storm():
    """Thousands of distinct keys crammed against a table at exactly 2x
    occupancy: every insert round contends, chains grow, and the result
    must still match numpy group-by exactly."""
    import jax.numpy as jnp

    from presto_tpu import types as T
    from presto_tpu.ops import hashtable as H

    rng = np.random.default_rng(7)
    n = 8192
    keys = rng.integers(0, 4096, n)          # ~4096 groups in 8192 slots
    vals = rng.uniform(-100, 100, n)
    state = H.groupby_init(8192, 2, [np.dtype(np.int64)], [True],
                           [("sum", np.dtype(np.float64)),
                            ("count", None)])
    state, ng, ok = H.groupby_update(
        state, [(jnp.asarray(keys), None, T.BIGINT)],
        [("sum", jnp.asarray(vals), None), ("count", None, None)],
        jnp.asarray(n))
    assert bool(ok)
    ref_sum, ref_cnt = _groupby_oracle(keys, None, vals)
    got_n, got = _extract_map(state)
    assert got_n == int(ng) == len(ref_sum)
    for kk, s in ref_sum.items():
        assert got[kk][0] == pytest.approx(s, rel=1e-9, abs=1e-7)
        assert got[kk][1] == ref_cnt[kk]


def test_hash_groupby_null_keys_form_one_group():
    import jax.numpy as jnp

    from presto_tpu import types as T
    from presto_tpu.ops import hashtable as H

    rng = np.random.default_rng(3)
    n = 4096
    keys = rng.integers(0, 64, n)
    valid = rng.random(n) > 0.3              # lots of null keys
    vals = np.ones(n)
    state = H.groupby_init(1024, 2, [np.dtype(np.int64)], [True],
                           [("sum", np.dtype(np.float64))])
    state, ng, ok = H.groupby_update(
        state, [(jnp.asarray(keys), jnp.asarray(valid), T.BIGINT)],
        [("sum", jnp.asarray(vals), None)], jnp.asarray(n))
    assert bool(ok)
    ref_sum, _ = _groupby_oracle(keys, valid, vals)
    got_n, got = _extract_map(state)
    assert got_n == len(ref_sum)             # null key = exactly 1 group
    assert got[None][0] == pytest.approx(ref_sum[None])


def test_hash_groupby_rehash_boundary_carries_minmax_identities():
    """Cross the rehash boundary mid-stream: groups inserted BEFORE the
    rehash carry their accumulated state; groups first installed AFTER
    it must land on identity-initialized min/max cells (regression: a
    zero-initialized cell folded min(0, x) = 0)."""
    import jax.numpy as jnp

    from presto_tpu import types as T
    from presto_tpu.ops import hashtable as H

    n = 2048
    keys1 = np.arange(n) % 400               # groups 0..399
    vals1 = np.arange(n, dtype=np.float64) + 100.0
    state = H.groupby_init(1024, 2, [np.dtype(np.int64)], [True],
                           [("min", np.dtype(np.float64)),
                            ("max", np.dtype(np.float64))])
    kc = [(jnp.asarray(keys1), None, T.BIGINT)]
    ag = [("min", jnp.asarray(vals1), None),
          ("max", jnp.asarray(vals1), None)]
    state, ng, ok = H.groupby_update(state, kc, ag, jnp.asarray(n))
    assert bool(ok) and int(ng) == 400
    state, ok = H.groupby_rehash(state, 4096, ["min", "max"])
    assert bool(ok)
    # batch 2: 400 NEW groups, values strictly positive
    keys2 = 1000 + (np.arange(n) % 400)
    vals2 = np.arange(n, dtype=np.float64) + 500.0
    state, ng, ok = H.groupby_update(
        state, [(jnp.asarray(keys2), None, T.BIGINT)],
        [("min", jnp.asarray(vals2), None),
         ("max", jnp.asarray(vals2), None)], jnp.asarray(n))
    assert bool(ok) and int(ng) == 800
    ref_min = collections.defaultdict(lambda: np.inf)
    ref_max = collections.defaultdict(lambda: -np.inf)
    for k, v in zip(keys1, vals1):
        ref_min[int(k)] = min(ref_min[int(k)], v)
        ref_max[int(k)] = max(ref_max[int(k)], v)
    for k, v in zip(keys2, vals2):
        ref_min[int(k)] = min(ref_min[int(k)], v)
        ref_max[int(k)] = max(ref_max[int(k)], v)
    got_n, got = _extract_map(state)
    assert got_n == 800
    for kk in ref_min:
        assert got[kk][0] == ref_min[kk], kk   # no stale zeros
        assert got[kk][1] == ref_max[kk], kk


def test_hash_insert_full_table_reports_not_ok_and_accumulates_nothing():
    """The rehash-boundary contract: when placement fails, ok=False and
    NO aggregation state changed, so rehash-and-retry is exactly-once."""
    import jax.numpy as jnp

    from presto_tpu import types as T
    from presto_tpu.ops import hashtable as H

    state = H.groupby_init(64, 2, [np.dtype(np.int64)], [True],
                           [("sum", np.dtype(np.float64))])
    keys = np.arange(1000)
    state2, ng, ok = H.groupby_update(
        state, [(jnp.asarray(keys), None, T.BIGINT)],
        [("sum", jnp.asarray(np.ones(1000)), None)], jnp.asarray(1000))
    assert not bool(ok)
    assert float(np.asarray(state2[4][0][0]).sum()) == 0.0


def test_hash_prefix_reject_byte_is_slot_independent():
    """The reject byte must come from hash bits the slot index does not
    use (PagesHash.java:49): keys colliding on the slot still disagree
    on the prefix almost always, so occupied-slot walks reject on one
    byte; and prefix-EQUAL colliding keys must still compare words."""
    import jax.numpy as jnp

    from presto_tpu.ops import hashtable as H

    h = H.hash_words([jnp.asarray(np.arange(1 << 14, dtype=np.int64))])
    slot, prefix = H.slot_and_prefix(h, 256)
    slot = np.asarray(slot)
    prefix = np.asarray(prefix)
    # per slot, prefixes of colliding keys are spread (not a function
    # of the slot): at 64 keys/slot expect ~56 distinct prefix values
    for s in (0, 17, 255):
        ps = prefix[slot == s]
        assert len(ps) > 0
        assert len(np.unique(ps)) > len(ps) // 2
    # correctness under engineered prefix collisions: keys with EQUAL
    # slot and EQUAL prefix must not alias (full word compare decides)
    h_np = np.asarray(h)
    pool = np.arange(1 << 14)
    same = pool[(slot == slot[0]) & (prefix == prefix[0])]
    if len(same) >= 2:
        from presto_tpu import types as T

        keys = np.repeat(same[:2], 8).astype(np.int64)
        state = H.groupby_init(256, 2, [np.dtype(np.int64)], [True],
                               [("count", None)])
        state, ng, ok = H.groupby_update(
            state, [(jnp.asarray(keys), None, T.BIGINT)],
            [("count", None, None)], jnp.asarray(len(keys)))
        assert bool(ok) and int(ng) == 2


def test_pages_hash_duplicate_and_missing_probe_keys():
    import jax.numpy as jnp

    from presto_tpu import types as T
    from presto_tpu.ops import hashtable as H

    rng = np.random.default_rng(11)
    bk = rng.integers(0, 300, 1024)
    bvalid = rng.random(1024) > 0.1
    pk = rng.integers(0, 600, 2048)
    pvalid = rng.random(2048) > 0.1
    table = H.pages_hash_build(
        [(jnp.asarray(bk), jnp.asarray(bvalid), T.BIGINT)],
        jnp.asarray(1000), 2048)
    tw, tp, tu, starts, counts, perm, has_null, ok = table
    assert bool(ok) and bool(has_null)
    lo, cnt, live = H.pages_hash_probe(
        (tw, tp, tu, starts, counts),
        [(jnp.asarray(pk), jnp.asarray(pvalid), T.BIGINT)],
        jnp.asarray(2048))
    lo, cnt = np.asarray(lo), np.asarray(cnt)
    perm_np = np.asarray(perm)
    ref = collections.Counter(
        int(k) for k, v in zip(bk[:1000], bvalid[:1000]) if v)
    for i in range(2048):
        want = ref.get(int(pk[i]), 0) if pvalid[i] else 0
        assert cnt[i] == want, i
        for j in range(cnt[i]):
            assert bk[perm_np[lo[i] + j]] == pk[i]


@pytest.mark.skipif(not P.available(), reason="pallas unavailable")
def test_pallas_insert_matches_claim_loop_group_sets():
    """The serial Pallas formulation (interpret mode) and the shipping
    claim loop must agree on the GROUP PARTITION (same-key rows share a
    slot, distinct keys get distinct slots) under a collision storm."""
    import jax.numpy as jnp

    from presto_tpu.ops import hashtable as H
    from presto_tpu.ops import pallas_hash as PH

    rng = np.random.default_rng(5)
    n = 2048
    keys = rng.integers(0, 700, n).astype(np.int64)
    kw = [jnp.asarray(keys)]
    live = jnp.ones(n, bool)
    # pallas serial insert
    twp, tpp, tup = PH.empty_table_i32(2048, 1)
    slot_p, _, _, _ = PH.pallas_probe_insert(kw, live, twp, tpp, tup,
                                             interpret=True)
    # claim loop
    words = tuple(jnp.zeros(2048, jnp.int64) for _ in range(1))
    slot_c, _, _, _, ok = H.probe_insert(
        kw, live, words, jnp.zeros(2048, jnp.uint8),
        jnp.zeros(2048, bool))
    assert bool(ok)
    for slots in (np.asarray(slot_p), np.asarray(slot_c)):
        m = {}
        for k, s in zip(keys.tolist(), slots.tolist()):
            assert 0 <= s < 2048
            assert m.setdefault(k, s) == s       # same key -> same slot
        assert len(set(m.values())) == len(m)    # distinct -> distinct
