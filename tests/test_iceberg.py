"""Iceberg-role connector tests: snapshot commits, time travel via
"t@snapshot", metadata tables "t$snapshots"/"t$history", rollback
(presto-iceberg IcebergMetadata/SnapshotsTable/HistoryTable roles)."""

import pytest

from presto_tpu.connectors.iceberg import IcebergConnector
from presto_tpu.localrunner import LocalQueryRunner


@pytest.fixture()
def runner(tmp_path):
    r = LocalQueryRunner.tpch(scale=0.01)
    r.register("iceberg", IcebergConnector(str(tmp_path)))
    return r


def test_snapshot_per_commit_and_time_travel(runner):
    runner.execute("CREATE TABLE iceberg.t (a bigint, b varchar)")
    runner.execute("INSERT INTO iceberg.t VALUES (1, 'x')")
    runner.execute("INSERT INTO iceberg.t VALUES (2, 'y'), (3, 'z')")
    assert sorted(runner.execute("SELECT a FROM iceberg.t").rows) == \
        [(1,), (2,), (3,)]
    snaps = runner.execute(
        'SELECT snapshot_id, total_records FROM iceberg."t$snapshots" '
        "ORDER BY snapshot_id").rows
    assert len(snaps) == 2
    assert [r[1] for r in snaps] == [1, 3]  # cumulative records
    first = snaps[0][0]
    # time travel to the first snapshot
    got = runner.execute(f'SELECT a, b FROM iceberg."t@{first}"').rows
    assert got == [(1, "x")]
    # history marks both snapshots as ancestors of current
    hist = runner.execute(
        'SELECT snapshot_id, is_current_ancestor FROM '
        'iceberg."t$history" ORDER BY snapshot_id').rows
    assert [h[1] for h in hist] == [True, True]


def test_rollback(runner):
    runner.execute("CREATE TABLE iceberg.r (v bigint)")
    runner.execute("INSERT INTO iceberg.r VALUES (10)")
    runner.execute("INSERT INTO iceberg.r VALUES (20)")
    conn = runner.registry.get("iceberg")
    snaps = runner.execute(
        'SELECT snapshot_id FROM iceberg."r$snapshots" '
        "ORDER BY snapshot_id").rows
    conn.rollback_to_snapshot("r", snaps[0][0])
    assert runner.execute("SELECT v FROM iceberg.r").rows == [(10,)]
    # rolled-back snapshot is no longer a current ancestor
    hist = dict(runner.execute(
        'SELECT snapshot_id, is_current_ancestor FROM '
        'iceberg."r$history"').rows)
    assert hist[snaps[0][0]] is True
    assert hist[snaps[1][0]] is False
    # writing after rollback branches history from the old snapshot
    runner.execute("INSERT INTO iceberg.r VALUES (30)")
    assert sorted(runner.execute("SELECT v FROM iceberg.r").rows) == \
        [(10,), (30,)]


@pytest.mark.slow
def test_ctas_from_tpch_and_formats(runner):
    runner.execute("CREATE TABLE iceberg.nat WITH (format = 'json') AS "
                   "SELECT n_nationkey, n_name FROM tpch.nation")
    assert runner.execute(
        "SELECT count(*) FROM iceberg.nat").rows == [(25,)]
    a = sorted(runner.execute(
        "SELECT n_name FROM iceberg.nat WHERE n_nationkey < 5").rows)
    b = sorted(runner.execute(
        "SELECT n_name FROM tpch.nation WHERE n_nationkey < 5").rows)
    assert a == b


def test_readers_see_complete_snapshots_only(runner, tmp_path):
    """A reader resolving the table mid-commit sees either the old or
    the new snapshot, never a partial state (atomic hint swap)."""
    runner.execute("CREATE TABLE iceberg.c (v bigint)")
    runner.execute("INSERT INTO iceberg.c VALUES (1)")
    conn = runner.registry.get("iceberg")
    import threading

    errors = []

    def reader():
        for _ in range(50):
            try:
                rows = runner.execute("SELECT count(*) FROM iceberg.c"
                                      ).rows
                assert rows[0][0] in (1, 2, 3, 4, 5, 6)
            except Exception as e:  # noqa: BLE001
                errors.append(e)
                return

    t = threading.Thread(target=reader)
    t.start()
    for _ in range(5):
        runner.execute("INSERT INTO iceberg.c VALUES (9)")
    t.join()
    assert not errors, errors


def test_cannot_write_snapshot_or_meta(runner):
    runner.execute("CREATE TABLE iceberg.w (v bigint)")
    runner.execute("INSERT INTO iceberg.w VALUES (1)")
    snaps = runner.execute(
        'SELECT snapshot_id FROM iceberg."w$snapshots"').rows
    with pytest.raises(Exception):
        runner.execute(
            f'INSERT INTO iceberg."w@{snaps[0][0]}" VALUES (2)')


def test_rename_drop(runner):
    runner.execute("CREATE TABLE iceberg.x (v bigint)")
    runner.execute("INSERT INTO iceberg.x VALUES (5)")
    runner.execute("ALTER TABLE iceberg.x RENAME TO y")
    assert runner.execute("SELECT v FROM iceberg.y").rows == [(5,)]
    runner.execute("DROP TABLE iceberg.y")
    assert ("y",) not in runner.execute("SHOW TABLES FROM iceberg").rows
