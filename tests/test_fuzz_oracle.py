"""Randomized query fuzzing against a sqlite oracle.

The reference pins SQL semantics by diffing against H2 across large
hand-written suites (QueryAssertions.assertQuery + AbstractTestQueries);
this suite generates seeded random queries over TPC-H tables — filters,
expressions, CASE, aggregation, joins, set operations, ORDER BY/LIMIT —
and requires byte-identical (float-tolerant) results from the engine and
sqlite.  Deterministic seeds keep CI stable while covering orders of
magnitude more shapes than the curated conformance files.
"""

import datetime
import math
import random
import sqlite3

import pytest

from presto_tpu.localrunner import LocalQueryRunner

pytestmark = pytest.mark.slow


SCALE = 0.01
TABLES = {
    # table -> numeric columns, string columns (dialect-neutral subset)
    "nation": (["n_nationkey", "n_regionkey"], ["n_name"]),
    "region": (["r_regionkey"], ["r_name"]),
    "customer": (["c_custkey", "c_nationkey", "c_acctbal"],
                 ["c_mktsegment", "c_name"]),
    "orders": (["o_orderkey", "o_custkey", "o_totalprice",
                "o_shippriority"], ["o_orderpriority", "o_orderstatus"]),
    "lineitem": (["l_orderkey", "l_partkey", "l_suppkey", "l_linenumber",
                  "l_quantity", "l_extendedprice", "l_discount", "l_tax"],
                 ["l_returnflag", "l_linestatus", "l_shipmode"]),
}
JOINS = [  # (left table, right table, left key, right key)
    ("nation", "region", "n_regionkey", "r_regionkey"),
    ("customer", "nation", "c_nationkey", "n_nationkey"),
    ("orders", "customer", "o_custkey", "c_custkey"),
    ("lineitem", "orders", "l_orderkey", "o_orderkey"),
]
FLOATY = {"c_acctbal", "o_totalprice", "l_quantity", "l_extendedprice",
          "l_discount", "l_tax"}


@pytest.fixture(scope="module")
def runner():
    return LocalQueryRunner.tpch(scale=SCALE)


@pytest.fixture(scope="module")
def oracle(runner):
    conn = sqlite3.connect(":memory:")
    conn.execute("PRAGMA case_sensitive_like = ON")
    tpch = runner.registry.get("tpch")
    for table, (nums, strs) in TABLES.items():
        handle = tpch.get_table(table)
        names = nums + strs
        cols_sql = ", ".join(
            f"{n} {'REAL' if n in FLOATY else 'INTEGER'}" for n in nums
        ) + ", " + ", ".join(f"{n} TEXT" for n in strs)
        conn.execute(f"create table {table} ({cols_sql})")
        for split in tpch.get_splits(handle, 1):
            for batch in tpch.page_source(split, names, 1 << 20):
                rows = batch.to_pylist()
                ph = ", ".join("?" * len(names))
                conn.executemany(
                    f"insert into {table} values ({ph})", rows)
    conn.commit()
    return conn


class Gen:
    """One seeded random query."""

    def __init__(self, seed: int):
        self.r = random.Random(seed)

    def pick_table(self):
        return self.r.choice(list(TABLES))

    def num_col(self, table, prefix=""):
        return prefix + self.r.choice(TABLES[table][0])

    def str_col(self, table, prefix=""):
        return prefix + self.r.choice(TABLES[table][1])

    def scalar_expr(self, table, prefix=""):
        kind = self.r.random()
        a = self.num_col(table, prefix)
        b = self.num_col(table, prefix)
        if kind < 0.3:
            return a
        if kind < 0.5:
            op = self.r.choice(["+", "-", "*"])
            return f"({a} {op} {b})"
        if kind < 0.65:
            return f"({a} + {self.r.randint(1, 100)})"
        if kind < 0.8:
            c = self.str_col(table, prefix)
            ch = self.r.choice("ABCDEFR")
            return (f"(CASE WHEN {c} >= '{ch}' THEN {a} "
                    f"ELSE {b} END)")
        return f"(- {a})"

    def predicate(self, table, prefix=""):
        parts = []
        for _ in range(self.r.randint(1, 3)):
            kind = self.r.random()
            if kind < 0.45:
                col = self.num_col(table, prefix)
                op = self.r.choice(["<", "<=", ">", ">=", "=", "<>"])
                parts.append(f"{col} {op} {self.r.randint(0, 2000)}")
            elif kind < 0.7:
                col = self.str_col(table, prefix)
                ch = self.r.choice("ABCDEFGHMNOPR")
                op = self.r.choice(["<", ">=", "="])
                parts.append(f"{col} {op} '{ch}'")
            elif kind < 0.85:
                col = self.num_col(table, prefix)
                vals = sorted({self.r.randint(0, 50)
                               for _ in range(self.r.randint(2, 5))})
                parts.append(
                    f"{col} IN ({', '.join(map(str, vals))})")
            else:
                col = self.str_col(table, prefix)
                ch = self.r.choice("ABCDEF")
                parts.append(f"{col} LIKE '{ch}%'")
        joiner = " AND " if self.r.random() < 0.7 else " OR "
        return joiner.join(parts)

    def aggregate(self, table, prefix=""):
        fn = self.r.choice(["sum", "count", "min", "max", "avg"])
        if fn == "count" and self.r.random() < 0.5:
            return "count(*)"
        return f"{fn}({self.num_col(table, prefix)})"

    def simple_select(self):
        t = self.pick_table()
        cols = [self.scalar_expr(t) for _ in range(self.r.randint(1, 3))]
        cols.append(self.str_col(t))
        sel = ", ".join(f"{c} AS c{i}" for i, c in enumerate(cols))
        sql = f"SELECT {sel} FROM {t}"
        if self.r.random() < 0.85:
            sql += f" WHERE {self.predicate(t)}"
        # total order for comparability
        order = ", ".join(f"c{i}" for i in range(len(cols)))
        sql += f" ORDER BY {order}"
        if self.r.random() < 0.5:
            sql += f" LIMIT {self.r.randint(1, 50)}"
        return sql

    def agg_select(self):
        t = self.pick_table()
        key_is_str = self.r.random() < 0.6
        key = self.str_col(t) if key_is_str else self.num_col(t)
        aggs = [self.aggregate(t) for _ in range(self.r.randint(1, 3))]
        sel = f"{key} AS k, " + ", ".join(
            f"{a} AS a{i}" for i, a in enumerate(aggs))
        sql = f"SELECT {sel} FROM {t}"
        if self.r.random() < 0.7:
            sql += f" WHERE {self.predicate(t)}"
        sql += f" GROUP BY {key}"
        if self.r.random() < 0.4:
            sql += f" HAVING count(*) > {self.r.randint(0, 3)}"
        sql += " ORDER BY k"
        return sql

    def join_select(self):
        lt, rt, lk, rk = self.r.choice(JOINS)
        la, ra = "t1.", "t2."
        cols = [f"{la}{lk}", self.scalar_expr(lt, la),
                self.str_col(rt, ra)]
        sel = ", ".join(f"{c} AS c{i}" for i, c in enumerate(cols))
        sql = (f"SELECT {sel} FROM {lt} t1 JOIN {rt} t2 "
               f"ON {la}{lk} = {ra}{rk}")
        preds = []
        if self.r.random() < 0.8:
            preds.append(self.predicate(lt, la))
        if self.r.random() < 0.5:
            preds.append(self.predicate(rt, ra))
        if preds:
            sql += " WHERE " + " AND ".join(f"({p})" for p in preds)
        order = ", ".join(f"c{i}" for i in range(len(cols)))
        sql += f" ORDER BY {order} LIMIT {self.r.randint(5, 80)}"
        return sql

    def setop_select(self):
        t = self.pick_table()
        col = self.num_col(t)
        op = self.r.choice(["UNION", "UNION ALL", "INTERSECT", "EXCEPT"])
        a = f"SELECT {col} AS c0 FROM {t} WHERE {self.predicate(t)}"
        b = f"SELECT {col} AS c0 FROM {t} WHERE {self.predicate(t)}"
        return f"{a} {op} {b} ORDER BY c0"

    def left_join_select(self):
        # join smaller-to-larger reversed so unmatched rows exist
        rt, lt, rk, lk = self.r.choice(JOINS)
        la, ra = "t1.", "t2."
        cols = [f"{la}{lk}", self.num_col(rt, ra), self.str_col(lt, la)]
        sel = ", ".join(f"{c} AS c{i}" for i, c in enumerate(cols))
        sql = (f"SELECT {sel} FROM {lt} t1 LEFT JOIN {rt} t2 "
               f"ON {la}{lk} = {ra}{rk}")
        preds = []
        if self.r.random() < 0.8:
            preds.append(self.predicate(lt, la))
        if self.r.random() < 0.4:
            # NULL-sensitive predicate on the nullable side
            preds.append(f"{ra}{self.num_col(rt)} IS NULL")
        if preds:
            sql += " WHERE " + " AND ".join(f"({p})" for p in preds)
        order = ", ".join(f"c{i}" for i in range(len(cols)))
        sql += f" ORDER BY {order} LIMIT {self.r.randint(10, 90)}"
        return sql

    def subquery_select(self):
        lt, rt, lk, rk = self.r.choice(JOINS)
        form = self.r.random()
        if form < 0.4:
            inner = (f"SELECT {rk} FROM {rt} "
                     f"WHERE {self.predicate(rt)}")
            pred = f"{lk} IN ({inner})"
        elif form < 0.7:
            inner = (f"SELECT {rk} FROM {rt} "
                     f"WHERE {self.predicate(rt)}")
            pred = f"{lk} NOT IN ({inner})"
        else:
            # un-parenthesized OR makes the correlation non-extractable
            # and exercises the keyless (nested-loop-shaped) EXISTS
            # decorrelation — which is quadratic, so keep that shape to
            # the small table pairs
            raw_or = (self.r.random() < 0.4
                      and (lt, rt) in (("nation", "region"),
                                       ("customer", "nation")))
            inner_pred = self.predicate(rt)
            if not raw_or:
                inner_pred = f"({inner_pred})"
            inner = (f"SELECT 1 FROM {rt} WHERE {rt}.{rk} = {lt}.{lk} "
                     f"AND {inner_pred}")
            neg = "NOT " if self.r.random() < 0.5 else ""
            pred = f"{neg}EXISTS ({inner})"
        col = self.num_col(lt)
        distinct = "DISTINCT " if self.r.random() < 0.4 else ""
        sql = (f"SELECT {distinct}{col} AS c0 FROM {lt} "
               f"WHERE {pred} ORDER BY c0")
        if self.r.random() < 0.5:
            sql += f" LIMIT {self.r.randint(5, 60)}"
        return sql

    def query(self):
        kind = self.r.random()
        if kind < 0.25:
            return self.simple_select()
        if kind < 0.45:
            return self.agg_select()
        if kind < 0.6:
            return self.join_select()
        if kind < 0.75:
            return self.left_join_select()
        if kind < 0.9:
            return self.subquery_select()
        return self.setop_select()


def _norm(rows):
    # the production verifier's float/NaN canonicalization, applied
    # row-by-row because _canonical_rows sorts its output and row ORDER
    # is part of what this suite verifies
    from presto_tpu.verifier import _canonical_rows

    return [_canonical_rows([tuple(r)])[0] for r in rows]


@pytest.mark.parametrize("seed", range(60))
def test_fuzz_vs_sqlite(runner, oracle, seed):
    sql = Gen(seed).query()
    got = _norm(runner.execute(sql).rows)
    want = _norm(oracle.execute(sql).fetchall())
    if " LIMIT " in sql:
        # every generated ORDER BY totally orders the projected columns
        # EXCEPT when a tie in all columns exists; a LIMIT cut is then
        # still multiset-unique, so compare as multisets
        assert len(got) == len(want), sql
        assert sorted(got, key=repr) == sorted(want, key=repr), sql
    else:
        assert got == want, sql
