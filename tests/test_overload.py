"""Overload-survival tests: bounded-pool admission + shedding
(server/dispatcher.py), client retry hints (client.py), administrative
kills via CALL system.runtime.kill_query (coordinator + localrunner),
and the cluster memory manager's per-query limit / soft-memory feed
(server/coordinator.py _memory_tick).

Reference analogues: DispatchManager's bounded dispatch executor +
QUERY_QUEUE_FULL rejection, StatementClientV1 retry-after handling,
KillQueryProcedure.java, and ClusterMemoryManager's
EXCEEDED_GLOBAL_MEMORY_LIMIT enforcement.  The error triple
(errorName / errorType / errorCode) must be byte-identical on every
surface: the protocol error object, /v1/query detail + listing,
system.runtime.queries, and the query.json event log."""

import dataclasses
import json
import threading
import time
import urllib.request

import pytest

from presto_tpu import events as ev
from presto_tpu.client import QueryFailed, StatementClient
from presto_tpu.config import DEFAULT
from presto_tpu.localrunner import LocalQueryRunner
from presto_tpu.server.coordinator import ADMINISTRATIVELY_KILLED
from presto_tpu.server.dqr import DistributedQueryRunner
from presto_tpu.server.faults import FaultInjector


def _spin_until(pred, timeout_s=15.0):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(0.01)
    return pred()


def _cfg(**kw):
    return dataclasses.replace(DEFAULT, **kw)


def _post_statement(co_uri: str, sql: str):
    """Raw POST /v1/statement: returns (ack_json, headers)."""
    req = urllib.request.Request(
        f"{co_uri}/v1/statement", data=sql.encode(), method="POST",
        headers={"X-Presto-User": "user"})
    with urllib.request.urlopen(req, timeout=10) as resp:
        return json.loads(resp.read()), dict(resp.headers)


def _query_detail(co_uri: str, qid: str):
    with urllib.request.urlopen(f"{co_uri}/v1/query/{qid}",
                                timeout=10) as resp:
        return json.loads(resp.read())


class _KillRecorder(ev.EventListener):
    def __init__(self):
        self.killed = []

    def query_killed(self, event):
        self.killed.append(event)


# ---------------------------------------------------------------------------
# bounded-pool admission
# ---------------------------------------------------------------------------

def test_bounded_pool_runs_queries_exactly():
    """dispatcher_pool_size > 0 switches to N drainer threads; results
    are identical to thread-per-query."""
    cfg = _cfg(dispatcher_pool_size=2, dispatcher_max_queued=16)
    with DistributedQueryRunner.tpch(scale=0.01, n_workers=2,
                                     config=cfg) as dqr:
        co = dqr.coordinator
        assert len(co.dispatcher._threads) == 2
        assert not hasattr(co.dispatcher, "_thread")
        assert dqr.execute("SELECT count(*) FROM nation").rows == [(25,)]
        got = dqr.execute(
            "SELECT l_returnflag, count(*) FROM lineitem "
            "GROUP BY l_returnflag ORDER BY 1").rows
        assert [r[0] for r in got] == ["A", "N", "R"]
        # a burst wider than the pool still completes everything
        results, errs = [], []

        def one(i):
            try:
                c = dqr.new_client()
                _, data = c.execute("SELECT count(*) FROM region")
                results.append(data)
            except Exception as e:  # noqa: BLE001
                errs.append(e)

        threads = [threading.Thread(target=one, args=(i,))
                   for i in range(6)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        assert not errs
        assert results == [[[5]]] * 6
        assert co.dispatcher.shed_total == 0


def test_thread_per_query_mode_pinned():
    """Knobs off: the historical single dispatch loop, no drainer pool,
    and no shedding no matter the backlog."""
    with DistributedQueryRunner.tpch(scale=0.01, n_workers=1) as dqr:
        co = dqr.coordinator
        assert co.dispatcher.pool_size == 0
        assert co.dispatcher.max_queued == 0
        assert hasattr(co.dispatcher, "_thread")
        assert not hasattr(co.dispatcher, "_threads")
        co.dispatcher.pause()
        try:
            acks = [_post_statement(co.uri,
                                    "SELECT count(*) FROM nation")[0]
                    for _ in range(5)]
            # nothing shed: every statement is queued, none failed
            assert co.dispatcher.shed_total == 0
            for ack in acks:
                assert "error" not in ack
        finally:
            co.dispatcher.resume()
        assert dqr.execute("SELECT count(*) FROM nation").rows == [(25,)]


# ---------------------------------------------------------------------------
# overload shedding: shape on every surface + Retry-After
# ---------------------------------------------------------------------------

def test_shed_shape_on_all_surfaces():
    cfg = _cfg(dispatcher_pool_size=1, dispatcher_max_queued=1)
    with DistributedQueryRunner.tpch(scale=0.01, n_workers=1,
                                     config=cfg) as dqr:
        co = dqr.coordinator
        co.dispatcher.pause()
        try:
            # two held statements: the paused drainer may have grabbed
            # the first off the queue before parking, so the second
            # guarantees a resident backlog entry
            held_acks = [_post_statement(
                co.uri, "SELECT count(*) FROM nation")[0]
                for _ in range(2)]
            assert _spin_until(
                lambda: co.dispatcher._queue.qsize() >= 1, 5.0)
            # shed #1: raw POST — the ack itself carries Retry-After
            shed_ack, shed_hdrs = _post_statement(
                co.uri, "SELECT count(*) FROM region")
            assert int(shed_hdrs["Retry-After"]) >= 1
            shed_qid = shed_ack["id"]
            # shed #2: the client surface (single attempt)
            with pytest.raises(QueryFailed) as ei:
                dqr.new_client().execute("SELECT count(*) FROM region",
                                         max_retries=0)
            e = ei.value
            assert e.error_name == "QUERY_QUEUE_FULL"
            assert e.error_type == "INSUFFICIENT_RESOURCES"
            assert e.error_code == 0x0002_0002
            assert e.retry_after_s is not None and e.retry_after_s >= 1
            assert "queue full" in str(e).lower()
            assert co.dispatcher.shed_total == 2
            # /v1/query/{id} detail
            detail = _query_detail(co.uri, shed_qid)
            assert detail["state"] == "FAILED"
            assert detail["errorName"] == "QUERY_QUEUE_FULL"
            assert detail["errorType"] == "INSUFFICIENT_RESOURCES"
            assert detail["errorCode"] == 0x0002_0002
            # /v1/query listing
            with urllib.request.urlopen(f"{co.uri}/v1/query",
                                        timeout=10) as resp:
                listing = json.loads(resp.read())
            row = next(r for r in listing if r["queryId"] == shed_qid)
            assert row["errorName"] == "QUERY_QUEUE_FULL"
        finally:
            co.dispatcher.resume()
        # held statements survive the overload episode untouched
        assert _spin_until(
            lambda: all(co.queries[a["id"]].state == "FINISHED"
                        for a in held_acks))
        # system.runtime.queries carries the same errorName
        got = dqr.execute(
            "SELECT error_name FROM system.runtime.queries "
            f"WHERE query_id = '{shed_qid}'").rows
        assert got == [("QUERY_QUEUE_FULL",)]


def test_client_honors_retry_after_hint():
    """StatementClient retries ONLY on a server hint, at most
    max_retries times, never past the deadline; hintless failures keep
    the single-attempt behavior exactly."""
    client = StatementClient("http://unreachable.invalid")
    calls = []

    def fail_with_hint(sql, deadline):
        calls.append(sql)
        raise QueryFailed("Query queue full",
                          error_name="QUERY_QUEUE_FULL",
                          error_type="INSUFFICIENT_RESOURCES",
                          error_code=0x0002_0002, retry_after_s=0.01)

    client._execute_once = fail_with_hint
    with pytest.raises(QueryFailed):
        client.execute("SELECT 1", max_retries=2)
    assert len(calls) == 3                 # initial + 2 retries

    calls.clear()
    with pytest.raises(QueryFailed):
        client.execute("SELECT 1", max_retries=0)
    assert len(calls) == 1                 # retrying disabled

    def fail_without_hint(sql, deadline):
        calls.append(sql)
        raise QueryFailed("boom", error_name="DIVISION_BY_ZERO",
                          error_type="USER_ERROR", error_code=8)

    calls.clear()
    client._execute_once = fail_without_hint
    with pytest.raises(QueryFailed):
        client.execute("SELECT 1", max_retries=5)
    assert len(calls) == 1                 # no hint -> no retry, ever

    # a hinted shed that clears resolves transparently
    attempts = []

    def flaky(sql, deadline):
        attempts.append(sql)
        if len(attempts) == 1:
            raise QueryFailed("Query queue full",
                              error_name="QUERY_QUEUE_FULL",
                              error_type="INSUFFICIENT_RESOURCES",
                              error_code=0x0002_0002,
                              retry_after_s=0.01)
        return [{"name": "x", "type": "bigint"}], [[1]]

    client._execute_once = flaky
    assert client.execute("SELECT 1") == (
        [{"name": "x", "type": "bigint"}], [[1]])
    assert len(attempts) == 2

    # the hint never pushes a retry past the statement deadline
    calls.clear()

    def fail_with_huge_hint(sql, deadline):
        calls.append(sql)
        raise QueryFailed("Query queue full",
                          error_name="QUERY_QUEUE_FULL",
                          error_type="INSUFFICIENT_RESOURCES",
                          error_code=0x0002_0002, retry_after_s=3600)

    client._execute_once = fail_with_huge_hint
    t0 = time.monotonic()
    with pytest.raises(QueryFailed):
        client.execute("SELECT 1", timeout_s=0.2, max_retries=5)
    assert time.monotonic() - t0 < 1.0
    assert len(calls) == 1


# ---------------------------------------------------------------------------
# CALL system.runtime.kill_query
# ---------------------------------------------------------------------------

def test_kill_query_local_tier():
    runner = LocalQueryRunner.tpch(scale=0.01)
    rec = _KillRecorder()
    runner.event_bus.register(rec)
    assert runner.execute("SELECT count(*) FROM nation").rows == [(25,)]
    res = runner.execute(
        "CALL system.runtime.kill_query('local-1', 'be gone')")
    assert res.rows == [("killed",)]
    assert len(rec.killed) == 1
    k = rec.killed[0]
    assert k.query_id == "local-1"
    assert k.reason == "kill_query"
    assert k.error_name == ADMINISTRATIVELY_KILLED[0]
    assert k.message == "Query killed via kill_query: be gone"
    # default message without the optional second argument
    runner.execute("CALL system.runtime.kill_query('local-2')")
    assert rec.killed[-1].message == "Query killed via kill_query"
    with pytest.raises(ValueError, match="no such query"):
        runner.execute("CALL system.runtime.kill_query('nope')")
    # the CALL below is this runner's 5th statement: killing its own id
    with pytest.raises(ValueError, match="cannot kill itself"):
        runner.execute("CALL system.runtime.kill_query('local-5')")
    with pytest.raises(ValueError, match="unknown procedure"):
        runner.execute("CALL system.runtime.not_a_proc('x')")


@pytest.mark.slow
def test_kill_query_http_running():
    """Kill a RUNNING distributed query: the victim is parked by a
    memory-inflation hold, the kill is issued via CALL, and the victim's
    client sees the ADMINISTRATIVELY_KILLED triple with the custom
    message — not a generic drain abort."""
    inj = FaultInjector()
    inj.add_memory_rule(".*", 1 << 20, times=1, hold_s=30.0)
    with DistributedQueryRunner.tpch(scale=0.01, n_workers=1,
                                     worker_injectors={0: inj},
                                     heartbeat_interval_s=0.1) as dqr:
        co = dqr.coordinator
        rec = _KillRecorder()
        co.event_bus.register(rec)
        victim = dqr.new_client()
        err = []

        def run_victim():
            try:
                victim.execute("SELECT count(*) FROM lineitem",
                               max_retries=0)
            except QueryFailed as e:
                err.append(e)

        t = threading.Thread(target=run_victim, daemon=True)
        t.start()
        assert _spin_until(
            lambda: victim.last_query_id is not None
            and co.queries.get(victim.last_query_id) is not None
            and co.queries[victim.last_query_id].state == "RUNNING")
        qid = victim.last_query_id
        res = dqr.execute(
            f"CALL system.runtime.kill_query('{qid}', 'admin says stop')")
        assert res.rows == [("killed",)]
        t.join(timeout=30)
        assert not t.is_alive()
        assert len(err) == 1
        e = err[0]
        assert e.error_name == "ADMINISTRATIVELY_KILLED"
        assert e.error_type == "USER_ERROR"
        assert e.error_code == 0x0000_0005
        assert "admin says stop" in str(e)
        assert co.kill_counters.get("kill_query") == 1
        assert [k.query_id for k in rec.killed] == [qid]
        assert rec.killed[0].error_name == "ADMINISTRATIVELY_KILLED"
        # the cluster is healthy afterwards
        inj.release_all()
        inj.clear()
        assert dqr.execute("SELECT count(*) FROM nation").rows == [(25,)]


def test_kill_preserves_shape_on_queued_query():
    """A kill that lands while the query is still QUEUED must win over
    the dispatcher's generic cancel shape (_fail_dispatch guard)."""
    with DistributedQueryRunner.tpch(scale=0.01, n_workers=1) as dqr:
        co = dqr.coordinator
        co.dispatcher.pause()
        try:
            ack, _ = _post_statement(co.uri, "SELECT count(*) FROM nation")
            qid = ack["id"]
            assert _spin_until(lambda: co.queries[qid].state == "QUEUED")
            co.queries[qid].kill("killed while queued",
                                 ADMINISTRATIVELY_KILLED,
                                 reason="kill_query")
        finally:
            co.dispatcher.resume()
        assert _spin_until(lambda: co.queries[qid].state == "FAILED")
        q = co.queries[qid]
        assert q.error == "killed while queued"
        assert (q.error_name, q.error_type, q.error_code) == \
            ADMINISTRATIVELY_KILLED


# ---------------------------------------------------------------------------
# cluster memory manager: per-query limit on every surface
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_memory_exceeded_shape_on_all_surfaces(tmp_path):
    """SET SESSION query_max_total_memory_bytes + an inflated resident
    reservation: the ClusterMemoryManager kills the query with
    EXCEEDED_GLOBAL_MEMORY_LIMIT, and the triple is identical on the
    client error, /v1/query detail + listing, system.runtime.queries,
    and the query.json event log."""
    log = tmp_path / "query.json"
    inj = FaultInjector()
    inj.add_memory_rule(".*", 2_000_000, times=1, hold_s=30.0)
    with DistributedQueryRunner.tpch(scale=0.01, n_workers=1,
                                     worker_injectors={0: inj},
                                     heartbeat_interval_s=0.1,
                                     event_log_path=str(log)) as dqr:
        co = dqr.coordinator
        client = dqr.new_client()
        client.execute("SET SESSION query_max_total_memory_bytes = 1000000")
        with pytest.raises(QueryFailed) as ei:
            client.execute("SELECT count(*) FROM lineitem", max_retries=0)
        e = ei.value
        assert e.error_name == "EXCEEDED_GLOBAL_MEMORY_LIMIT"
        assert e.error_type == "INSUFFICIENT_RESOURCES"
        assert e.error_code == 0x0002_0001
        assert "total memory limit" in str(e)
        qid = client.last_query_id
        assert co.kill_counters.get("per-query-total-limit") == 1
        detail = _query_detail(co.uri, qid)
        assert detail["state"] == "FAILED"
        assert detail["errorName"] == "EXCEEDED_GLOBAL_MEMORY_LIMIT"
        assert detail["errorType"] == "INSUFFICIENT_RESOURCES"
        assert detail["errorCode"] == 0x0002_0001
        with urllib.request.urlopen(f"{co.uri}/v1/query",
                                    timeout=10) as resp:
            listing = json.loads(resp.read())
        row = next(r for r in listing if r["queryId"] == qid)
        assert row["errorName"] == "EXCEEDED_GLOBAL_MEMORY_LIMIT"
        got = dqr.execute(
            "SELECT error_name FROM system.runtime.queries "
            f"WHERE query_id = '{qid}'").rows
        assert got == [("EXCEEDED_GLOBAL_MEMORY_LIMIT",)]
        inj.release_all()
        inj.clear()
    events = [json.loads(line) for line in
              log.read_text().splitlines() if line.strip()]
    killed = [r for r in events if r["event"] == "QueryKilledEvent"
              and r["query_id"] == qid]
    assert len(killed) == 1
    assert killed[0]["error_name"] == "EXCEEDED_GLOBAL_MEMORY_LIMIT"
    assert killed[0]["reason"] == "per-query-total-limit"
    assert "total memory limit" in killed[0]["message"]


# ---------------------------------------------------------------------------
# resource-group soft memory fed by live worker MemoryInfo
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_soft_memory_gate_fed_by_live_worker_memory():
    """The memory tick feeds group usage WITHOUT any cluster memory
    limit configured (the old loop only ran when
    cluster_memory_limit_bytes was set): a group over its soft limit
    queues new admissions until the hog's reservations drain."""
    inj = FaultInjector()
    inj.add_memory_rule(".*", 4_000_000, times=1, hold_s=30.0)
    with DistributedQueryRunner.tpch(scale=0.01, n_workers=1,
                                     worker_injectors={0: inj},
                                     heartbeat_interval_s=0.1) as dqr:
        co = dqr.coordinator
        assert co.cluster_memory_limit_bytes is None
        group = co.resource_groups.configure_group(
            "alice", soft_memory_limit_bytes=1_000_000)
        hog = dqr.new_client(user="alice")
        hog_err = []

        def run_hog():
            try:
                hog.execute("SELECT count(*) FROM lineitem",
                            max_retries=0)
            except QueryFailed as e:
                hog_err.append(e)

        th = threading.Thread(target=run_hog, daemon=True)
        th.start()
        # live MemoryInfo reaches the group within a few ticks
        assert _spin_until(lambda: group.memory_usage >= 4_000_000)
        # a second alice statement parks in admission (soft limit)
        late_done = []

        def run_late():
            c = dqr.new_client(user="alice")
            _, data = c.execute("SELECT count(*) FROM region",
                                max_retries=0)
            late_done.append(data)

        tl = threading.Thread(target=run_late, daemon=True)
        tl.start()
        time.sleep(0.8)
        assert not late_done          # still gated by the soft limit
        waiting = [q for q in co.queries.values()
                   if q.user == "alice"
                   and q.state in ("QUEUED", "WAITING_FOR_RESOURCES")]
        assert waiting
        # release the hog: usage drains, the waiter admits and finishes
        inj.release_all()
        th.join(timeout=30)
        assert not th.is_alive() and not hog_err
        assert _spin_until(lambda: group.memory_usage == 0)
        tl.join(timeout=30)
        assert late_done == [[[5]]]
