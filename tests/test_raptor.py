"""Raptor-role native shard storage tests: shard files in the engine
wire format, sqlite metadata, bucketing, compaction, backup/recovery
(reference: presto-raptor-legacy ShardManager/OrcStorageManager/
ShardCompactor/BackupStore)."""

import os

import pytest

from presto_tpu.connectors.raptor import RaptorConnector
from presto_tpu.localrunner import LocalQueryRunner


@pytest.fixture()
def runner(tmp_path):
    r = LocalQueryRunner.tpch(scale=0.01)
    r.register("raptor", RaptorConnector(
        str(tmp_path / "data"), backup_root=str(tmp_path / "backup")))
    return r


def test_ddl_insert_select_roundtrip(runner):
    runner.execute("CREATE TABLE raptor.t (a bigint, b varchar, c double)")
    runner.execute("INSERT INTO raptor.t VALUES (1,'x',0.5),(2,NULL,1.5)")
    runner.execute("INSERT INTO raptor.t VALUES (3,'z',-2.0)")
    got = sorted(runner.execute("SELECT * FROM raptor.t").rows)
    assert got == [(1, "x", 0.5), (2, None, 1.5), (3, "z", -2.0)]
    # two INSERTs -> two shards (grouped into splits on demand)
    conn = runner.registry.get("raptor")
    splits = conn.get_splits(conn.get_table("t"), 1)
    assert sum(len(s.info[0]) for s in splits) == 2
    assert len(conn.get_splits(conn.get_table("t"), 2)) == 2


def test_ctas_and_persistence(runner, tmp_path):
    runner.execute("CREATE TABLE raptor.nat AS SELECT n_nationkey, n_name "
                   "FROM tpch.nation")
    # reopen the warehouse: a fresh connector sees the same data
    r2 = LocalQueryRunner.tpch(scale=0.01)
    r2.register("raptor", RaptorConnector(str(tmp_path / "data")))
    got = r2.execute("SELECT count(*) FROM raptor.nat").rows
    assert got == [(25,)]
    assert sorted(r2.execute(
        "SELECT n_name FROM raptor.nat WHERE n_nationkey < 2").rows) == \
        [("ALGERIA",), ("ARGENTINA",)]


def test_bucketed_table(runner):
    runner.execute(
        "CREATE TABLE raptor.b (k bigint, v varchar) "
        "WITH (bucket_count = 4, bucketed_on = ARRAY['k'])")
    rows = ", ".join(f"({i}, 'v{i}')" for i in range(40))
    runner.execute(f"INSERT INTO raptor.b VALUES {rows}")
    conn = runner.registry.get("raptor")
    splits = conn.get_splits(conn.get_table("b"), 1)
    # one split per touched bucket, each tagged with its bucket number
    buckets = {s.info[1] for s in splits}
    assert len(splits) == len(buckets) and len(buckets) > 1
    # same key always lands in the same bucket: re-insert key 7 and check
    runner.execute("INSERT INTO raptor.b VALUES (7, 'again')")
    splits2 = conn.get_splits(conn.get_table("b"), 1)
    b7 = [s for s in splits2
          if any("7" in str(r) for batch_rows in [
              [b.to_pylist() for b in conn.page_source(s, ["k", "v"])]]
              for batch in batch_rows for r in batch if r[0] == 7)]
    assert len({s.info[1] for s in b7}) == 1
    assert runner.execute(
        "SELECT count(*) FROM raptor.b").rows == [(41,)]


def test_compaction(runner):
    runner.execute("CREATE TABLE raptor.c (a bigint)")
    for i in range(6):
        runner.execute(f"INSERT INTO raptor.c VALUES ({i})")
    conn = runner.registry.get("raptor")
    before, after = conn.compact("c")
    assert before == 6 and after == 1
    assert sorted(runner.execute("SELECT a FROM raptor.c").rows) == \
        [(i,) for i in range(6)]


def test_backup_recovery(runner, tmp_path):
    runner.execute("CREATE TABLE raptor.r (a bigint)")
    runner.execute("INSERT INTO raptor.r VALUES (42)")
    conn = runner.registry.get("raptor")
    # simulate primary shard loss
    shard_dir = tmp_path / "data" / "shards"
    shards = [f for f in os.listdir(shard_dir) if f.endswith(".shard")]
    assert shards
    for f in shards:
        os.remove(shard_dir / f)
    # read recovers from the backup store
    assert runner.execute("SELECT a FROM raptor.r").rows == [(42,)]
    # and the primary is restored on disk
    assert any(f.endswith(".shard") for f in os.listdir(shard_dir))


def test_rename_drop(runner, tmp_path):
    runner.execute("CREATE TABLE raptor.x (a bigint)")
    runner.execute("INSERT INTO raptor.x VALUES (5)")
    runner.execute("ALTER TABLE raptor.x RENAME TO y")
    assert runner.execute("SELECT a FROM raptor.y").rows == [(5,)]
    runner.execute("DROP TABLE raptor.y")
    assert not [f for f in os.listdir(tmp_path / "data" / "shards")
                if f.endswith(".shard")]
    with pytest.raises(Exception):
        runner.execute("SELECT * FROM raptor.y")
