"""Coordinator HA (server/statestore.py): the kill-the-coordinator
matrix.

The last unaddressed failure domain — SURVEY §5.3 names the coordinator
a SPOF with no checkpoint/resume.  These tests prove the closure:

- a coordinator killed at EVERY lifecycle phase (QUEUED / PLANNING /
  RUNNING-mid-drain / all-stages-complete-in-spool / FINISHED) yields
  exact rows through the standby, via the durable query-state journal
  + lease takeover + journal adoption;
- stages already complete in the spool are NEVER re-executed on
  failover (``producer_reruns_total == 0``, zero new task creates for
  the all-spool-complete kill);
- the takeover lease is mutually exclusive: two standbys racing the
  claim produce exactly one winner (compare-and-swap marker);
- the journal serde round-trips every field.

The client follows failover transparently: ``StatementClient`` with a
standby address list resumes its polls against whichever coordinator
answers (query ids are stable across adoption).
"""

import dataclasses
import json
import threading
import time
import urllib.error
import urllib.request

import pytest

from presto_tpu.config import DEFAULT
from presto_tpu.server.dqr import HAQueryRunner
from presto_tpu.server.faults import FaultInjector
from presto_tpu.server.spool import LocalObjectApi
from presto_tpu.server.statestore import (
    QueryJournal, QueryStateStore,
)

pytestmark = pytest.mark.chaos

Q_AGG = ("select l_returnflag, count(*) c, sum(l_quantity) s "
         "from lineitem group by l_returnflag order by l_returnflag")


def _ha_cfg(tmp_path, **over):
    return dataclasses.replace(
        DEFAULT,
        exchange_spooling_enabled=True,
        exchange_spool_path=str(tmp_path / "spool"),
        coordinator_state_path=str(tmp_path / "state"),
        coordinator_lease_ttl_s=0.4,
        task_recovery_interval_s=0.05, **over)


def _oracle(sql, scale=0.01):
    from presto_tpu.connectors.api import ConnectorRegistry
    from presto_tpu.connectors.tpch import TpchConnector
    from presto_tpu.localrunner import LocalQueryRunner

    reg = ConnectorRegistry()
    reg.register("tpch", TpchConnector(scale=scale))
    return LocalQueryRunner(reg, "tpch").execute(sql).rows


def _submit_raw(co_uri, sql):
    req = urllib.request.Request(
        f"{co_uri}/v1/statement", data=sql.encode(),
        method="POST", headers={"Content-Type": "text/plain"})
    with urllib.request.urlopen(req, timeout=10) as resp:
        return json.loads(resp.read())["id"]


def _poll_standby(standby_uri, qid, timeout_s=60.0):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        try:
            with urllib.request.urlopen(
                    f"{standby_uri}/v1/statement/executing/{qid}/0",
                    timeout=30) as resp:
                p = json.loads(resp.read())
        except urllib.error.HTTPError as e:
            if e.code in (404, 503):
                time.sleep(0.05)
                continue
            raise
        if "error" in p:
            raise AssertionError(f"standby failed the query: "
                                 f"{p['error']}")
        if "data" in p or "columns" in p:
            return p
        time.sleep(0.05)
    raise AssertionError("standby never served the query")


def _wait_running(co, timeout_s=30.0):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        for q in co.queries.values():
            if q.state == "RUNNING" and q._placements:
                return q
        time.sleep(0.01)
    raise AssertionError("query never reached RUNNING with placements")


# -- unit tier: journal serde + lease ---------------------------------------

def test_journal_roundtrip_serde(tmp_path):
    store = QueryStateStore(LocalObjectApi(str(tmp_path / "state")))
    j = QueryJournal(
        query_id="q1", sql="select 1", user="alice", catalog="tpch",
        session_properties={"k": "v"}, prepared={"p": "select 2"},
        trace_token="tt-abc", plan_key_sql="select 1\0execute\0[]",
        state="RUNNING", error=None, create_time=123.5,
        dplan={"fragments": [], "root_fragment_id": 0,
               "column_names": [], "column_types": []},
        placements=[(0, "q1.0.0", "http://w1"),
                    (1, "q1.1.0a2", "spool://v1/task/q1.1.0/results/")],
        attempts={"q1.1.0": 2},
        task_specs={"q1.0.0": {"fid": 0, "index": 0,
                               "scan_shard": [0, 1], "n_out": 1,
                               "broadcast": False, "consumer_index": 0,
                               "base": "q1.0.0"}},
        root_locations=["http://w1/v1/task/q1.0.0/results/0"],
        root_tokens={"http://w1/v1/task/q1.0.0/results/0": 3},
        result_task_id="haabc.0.0", result_locations=1,
        result_bytes=42, column_names=["c"], column_types=["bigint"],
        row_count=1, inline_rows=[[1]], result_cache_task_id=None)
    store.write(j)
    back = store.read("q1")
    assert back == j
    assert store.list_queries() == ["q1"]
    store.delete("q1")
    assert store.read("q1") is None


def test_journal_gc_terminal_reaped_inflight_never(tmp_path):
    """PR 17 journal GC: TERMINAL entries are reaped past the retention
    window (then oldest-first past the retention count); in-flight
    entries are NEVER touched regardless of age — a standby must always
    be able to adopt them."""
    store = QueryStateStore(LocalObjectApi(str(tmp_path / "state")))
    for qid, state in (("t-fin", "FINISHED"), ("t-fail", "FAILED"),
                       ("live-run", "RUNNING"), ("live-q", "QUEUED"),
                       ("live-plan", "PLANNING")):
        store.write(QueryJournal(query_id=qid, sql="select 1",
                                 state=state))
    # nothing is old enough: GC is a no-op
    assert store.gc_terminal(3600.0, 1024) == []
    # age-based reap: against a far-future clock, BOTH terminal entries
    # go and every in-flight entry survives
    deleted = store.gc_terminal(10.0, 1024, now=time.time() + 100.0)
    assert deleted == ["t-fail", "t-fin"]
    for qid in ("live-run", "live-q", "live-plan"):
        assert store.read(qid) is not None, f"{qid} must never be GC'd"
    # count-based reap: oldest terminal entries beyond the cap go, the
    # newest stay, in-flight entries are still untouched
    for i in range(4):
        store.write(QueryJournal(query_id=f"fin-{i}", sql="select 1",
                                 state="FINISHED"))
        time.sleep(0.02)   # distinct mtimes for oldest-first ordering
    deleted = store.gc_terminal(3600.0, 2)
    assert deleted == ["fin-0", "fin-1"]
    assert store.read("fin-2") is not None
    assert store.read("fin-3") is not None
    # maximum pressure (zero retention, zero cap, far-future clock):
    # in-flight entries STILL survive
    store.gc_terminal(0.0, 0, now=time.time() + 1e6)
    for qid in ("live-run", "live-q", "live-plan"):
        assert store.read(qid) is not None, f"{qid} must never be GC'd"
    assert store.read("fin-2") is None and store.read("fin-3") is None


def test_journal_gc_rides_the_active_lease_heartbeat(tmp_path):
    """The wiring pin: a live coordinator's HA loop reaps a terminal
    journal entry within the retention/4 throttle cadence."""
    import os

    cfg = _ha_cfg(tmp_path, coordinator_journal_retention_s=0.2)
    with HAQueryRunner.tpch(scale=0.01, n_workers=1, config=cfg) as ha:
        store = ha.coordinator.statestore
        store.write(QueryJournal(query_id="old-fin", sql="select 1",
                                 state="FINISHED"))
        path = store.api._path("queries/old-fin")
        old = time.time() - 60.0
        os.utime(path, (old, old))
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            if store.read("old-fin") is None:
                break
            time.sleep(0.05)
        assert store.read("old-fin") is None, \
            "the active coordinator never reaped the terminal entry"


def test_lease_takeover_mutual_exclusion(tmp_path):
    """Two standbys race an expired lease: the compare-and-swap claim
    admits exactly ONE winner per generation."""
    store = QueryStateStore(LocalObjectApi(str(tmp_path / "state")))
    assert store.try_claim_lease("primary", ttl_s=0.05,
                                 force=True) == 1
    assert store.renew_lease("primary", 1, 0.05)
    # not expired yet: no takeover
    assert store.try_claim_lease("standby-a", ttl_s=1.0) is None
    time.sleep(0.1)   # lease expires
    results = {}
    barrier = threading.Barrier(2)

    def claim(name):
        barrier.wait()
        results[name] = store.try_claim_lease(name, ttl_s=5.0)

    ts = [threading.Thread(target=claim, args=(n,))
          for n in ("standby-a", "standby-b")]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    wins = [n for n, gen in results.items() if gen is not None]
    assert len(wins) == 1, results
    assert results[wins[0]] == 2
    # the loser cannot renew; the winner can
    loser = next(n for n in results if n not in wins)
    assert not store.renew_lease(loser, 2, 1.0)
    assert store.renew_lease(wins[0], 2, 1.0)
    # a superseded old primary is refused too
    assert not store.renew_lease("primary", 1, 1.0)


def test_two_standbys_one_winner(tmp_path):
    """Cluster-level mutual exclusion: a primary plus TWO standbys;
    kill the primary and exactly one standby activates."""
    from presto_tpu.server.coordinator import CoordinatorServer
    from presto_tpu.connectors.api import ConnectorRegistry
    from presto_tpu.connectors.tpch import TpchConnector

    cfg = _ha_cfg(tmp_path)

    def registry():
        reg = ConnectorRegistry()
        reg.register("tpch", TpchConnector(scale=0.001))
        return reg

    primary = CoordinatorServer(registry(), "tpch", cfg)
    standbys = [CoordinatorServer(registry(), "tpch", cfg,
                                  standby_of=primary.uri)
                for _ in range(2)]
    try:
        time.sleep(0.3)
        assert primary.is_active
        assert not any(s.is_active for s in standbys)
        primary.kill()
        deadline = time.monotonic() + 15.0
        while time.monotonic() < deadline:
            if any(s.is_active for s in standbys):
                break
            time.sleep(0.02)
        # settle one extra lease period: the loser must NOT also claim
        time.sleep(3 * cfg.coordinator_lease_ttl_s)
        active = [s for s in standbys if s.is_active]
        assert len(active) == 1
        assert active[0].ha_counters["failovers"] == 1
    finally:
        for s in standbys:
            s.close()
        primary.close()


# -- the kill matrix --------------------------------------------------------

def test_kill_at_queued(tmp_path):
    """Kill with the query still QUEUED (dispatcher paused): the
    standby re-enters it into admission under the SAME id and the
    client's failover-follow gets exact rows."""
    want = _oracle("select count(*) from orders")
    cfg = _ha_cfg(tmp_path)
    with HAQueryRunner.tpch(scale=0.01, n_workers=2, config=cfg,
                            heartbeat_interval_s=0.05,
                            heartbeat_max_missed=2) as ha:
        ha.coordinator.dispatcher.pause()
        qid = _submit_raw(ha.coordinator.uri,
                          "select count(*) from orders")
        time.sleep(0.2)   # journal write lands at submit
        ha.kill_primary()
        ha.wait_for_failover()
        p = _poll_standby(ha.standby.uri, qid)
        assert [tuple(r) for r in p["data"]] == want
        assert ha.standby.ha_counters["adopted"].get("requeued") == 1


def test_kill_at_planning(tmp_path):
    """Kill while the query is held AT the PLANNING transition (phase
    hook): no tasks existed, so adoption re-queues it."""
    want = _oracle(Q_AGG)
    cfg = _ha_cfg(tmp_path)
    with HAQueryRunner.tpch(scale=0.01, n_workers=2, config=cfg,
                            heartbeat_interval_s=0.05,
                            heartbeat_max_missed=2) as ha:
        at_planning = threading.Event()
        release = threading.Event()

        def hook(_q, phase):
            if phase == "PLANNING":
                at_planning.set()
                release.wait(timeout=30.0)

        ha.coordinator.phase_hook = hook
        qid = _submit_raw(ha.coordinator.uri, Q_AGG)
        assert at_planning.wait(timeout=15.0)
        ha.kill_primary()
        release.set()       # hook returns; killed check stops the thread
        ha.wait_for_failover()
        p = _poll_standby(ha.standby.uri, qid)
        assert sorted(tuple(r) for r in p["data"]) == sorted(want)
        assert ha.standby.ha_counters["adopted"].get("requeued") == 1


def test_kill_at_running_mid_drain(tmp_path):
    """Kill mid-drain (root results held by the injector): the standby
    adopts the RUNNING query, re-attaches/repoints, and re-pulls the
    spooled root stream from token 0 — exact rows, ZERO producer
    re-runs."""
    want = _oracle(Q_AGG)
    cfg = _ha_cfg(tmp_path)
    co_inj = FaultInjector()
    co_inj.add_rule(r"/results/", method="GET", policy="slow-task")
    with HAQueryRunner.tpch(scale=0.01, n_workers=2, config=cfg,
                            coordinator_injector=co_inj,
                            heartbeat_interval_s=0.05,
                            heartbeat_max_missed=2) as ha:
        res = {}

        def run():
            try:
                res["rows"] = ha.execute(Q_AGG).rows
            except Exception as e:  # noqa: BLE001
                res["err"] = repr(e)

        t = threading.Thread(target=run)
        t.start()
        q = _wait_running(ha.coordinator)
        time.sleep(0.3)   # let the RUNNING journal write land
        ha.kill_primary()
        ha.wait_for_failover()
        t.join(timeout=90)
        assert not t.is_alive(), "client never finished"
        assert "err" not in res, res
        assert sorted(res["rows"]) == sorted(want)
        sq = ha.standby.queries[q.query_id]
        assert sq.state == "FINISHED"
        assert sq.producer_reruns_total == 0
        assert sq.adopted
        assert ha.standby.ha_counters["failovers"] == 1


def test_kill_at_all_spool_complete(tmp_path):
    """Kill once every stage is complete in the spool (drain held):
    adoption is PURE repoint — zero re-execution, zero new task
    creates, zero producer re-runs."""
    want = _oracle(Q_AGG)
    cfg = _ha_cfg(tmp_path)
    co_inj = FaultInjector()
    co_inj.add_rule(r"/results/", method="GET", policy="slow-task")
    with HAQueryRunner.tpch(scale=0.01, n_workers=2, config=cfg,
                            coordinator_injector=co_inj,
                            heartbeat_interval_s=0.05,
                            heartbeat_max_missed=2) as ha:
        res = {}

        def run():
            try:
                res["rows"] = ha.execute(Q_AGG).rows
            except Exception as e:  # noqa: BLE001
                res["err"] = repr(e)

        t = threading.Thread(target=run)
        t.start()
        q = _wait_running(ha.coordinator)
        # wait until EVERY task is complete in the spool
        deadline = time.monotonic() + 60.0
        while time.monotonic() < deadline:
            with q._recovery_lock:
                placements = list(q._placements)
            if placements and all(
                    ha.coordinator.spool.is_complete(
                        tid, q._task_specs[tid]["n_out"])
                    for _, tid, _ in placements):
                break
            time.sleep(0.05)
        else:
            raise AssertionError("stages never all completed in spool")
        time.sleep(0.3)
        # count worker-side task creates before the kill
        n_tasks_before = sum(len(w.task_manager.tasks)
                             for w in ha.workers)
        ha.kill_primary()
        ha.wait_for_failover()
        t.join(timeout=90)
        assert not t.is_alive(), "client never finished"
        assert "err" not in res, res
        assert sorted(res["rows"]) == sorted(want)
        sq = ha.standby.queries[q.query_id]
        assert sq.state == "FINISHED"
        # the acceptance pin: nothing re-ran anywhere
        assert sq.producer_reruns_total == 0
        assert sq.stage_retry_rounds == 0
        n_tasks_after = sum(len(w.task_manager.tasks)
                            for w in ha.workers)
        assert n_tasks_after == n_tasks_before, \
            "adoption must not create tasks when all stages are " \
            "complete in the spool"


def test_kill_at_finished(tmp_path):
    """Kill AFTER the query finished: the terminal journal adopted the
    root output into a durable ha* spool stream, so the standby
    re-serves the rows byte-exact with zero re-execution."""
    cfg = _ha_cfg(tmp_path)
    with HAQueryRunner.tpch(scale=0.01, n_workers=2, config=cfg,
                            heartbeat_interval_s=0.05,
                            heartbeat_max_missed=2) as ha:
        cols, data = ha.client.execute(Q_AGG)
        qid = ha.client.last_query_id
        n_tasks_before = sum(len(w.task_manager.tasks)
                             for w in ha.workers)
        ha.kill_primary()
        ha.wait_for_failover()
        p = _poll_standby(ha.standby.uri, qid)
        assert p["data"] == data
        sq = ha.standby.queries[qid]
        assert sq.adopt_outcome == "served"
        assert sum(len(w.task_manager.tasks) for w in ha.workers) == \
            n_tasks_before
        # observability: the failover + adoption land on /metrics
        with urllib.request.urlopen(f"{ha.standby.uri}/metrics",
                                    timeout=5) as resp:
            metrics = resp.read().decode()
        assert "presto_coordinator_failover_total 1" in metrics
        assert 'presto_queries_adopted_total{outcome="served"} 1' \
            in metrics


def test_failover_events_in_log(tmp_path):
    """CoordinatorFailoverEvent + QueryAdoptedEvent ride the standby's
    event bus (query.json shape)."""
    cfg = _ha_cfg(tmp_path)
    log = tmp_path / "events.json"
    with HAQueryRunner.tpch(scale=0.01, n_workers=2, config=cfg,
                            heartbeat_interval_s=0.05,
                            heartbeat_max_missed=2,
                            event_log_path=str(log)) as ha:
        ha.client.execute("select count(*) from region")
        qid = ha.client.last_query_id
        ha.kill_primary()
        ha.wait_for_failover()
        _poll_standby(ha.standby.uri, qid)
        from presto_tpu.events import read_event_log

        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            events = read_event_log(str(log))
            kinds = {e["event"] for e in events}
            if {"CoordinatorFailoverEvent",
                    "QueryAdoptedEvent"} <= kinds:
                break
            time.sleep(0.05)
        fo = [e for e in events
              if e["event"] == "CoordinatorFailoverEvent"]
        ad = [e for e in events if e["event"] == "QueryAdoptedEvent"]
        assert fo and fo[0]["adopted_queries"] >= 1
        assert any(e["query_id"] == qid and e["outcome"] == "served"
                   for e in ad)


def test_no_state_path_leaves_paths_inert(tmp_path):
    """standby_of=None + no state path (the default): no journal, no
    lease, no HA thread — pinned by the statestore staying absent and
    a normal query running exactly as before."""
    cfg = dataclasses.replace(
        DEFAULT, exchange_spooling_enabled=True,
        exchange_spool_path=str(tmp_path / "spool"))
    from presto_tpu.server.dqr import DistributedQueryRunner

    with DistributedQueryRunner.tpch(scale=0.01, n_workers=2,
                                     config=cfg) as dqr:
        assert dqr.coordinator.statestore is None
        assert dqr.coordinator.is_active
        assert not hasattr(dqr.coordinator, "_ha_thread")
        r = dqr.execute("select count(*) from region")
        assert r.rows == [(5,)]
        q = list(dqr.coordinator.queries.values())[0]
        assert not q.adopted


@pytest.mark.slow
def test_q72_mesh_full_phase_sweep():
    """The acceptance sweep: kill the coordinator at EVERY lifecycle
    phase of a TPC-DS Q72 2-worker mesh run (QUEUED / PLANNING /
    RUNNING-mid-drain / all-spool-complete / FINISHED) — exact rows
    through the standby each time, ZERO producer re-runs for
    spool-complete stages, zero task creates for the all-spool-complete
    kill (tools/chaos_run.py --mode ha is the CLI face)."""
    import os
    import sys

    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                    "tools"))
    import importlib

    chaos_run = importlib.import_module("chaos_run")
    report = chaos_run.run_ha_sweep(quiet=True)
    assert report["ok"], report
    assert report["total_producer_reruns"] == 0
    by_phase = {s["phase"]: s for s in report["stages"]}
    assert set(by_phase) == set(chaos_run.HA_PHASES)
    assert by_phase["QUEUED"]["adopted_outcome"] is None or \
        by_phase["QUEUED"].get("adopted_outcome") != "failed"
    assert by_phase["SPOOL_COMPLETE"]["tasks_after"] == \
        by_phase["SPOOL_COMPLETE"]["tasks_before"]
    assert by_phase["FINISHED"]["adopted_outcome"] == "served"
