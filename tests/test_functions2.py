"""Scalar function breadth: cast-to-varchar, date formatting, JSON,
binary/hash, URL, and multi-string-column host evaluation.

Reference models: presto-main/.../operator/scalar/ (JsonFunctions,
VarbinaryFunctions, UrlFunctions, DateTimeFunctions.formatDatetime /
dateFormat) and the cast framework in type/*Operators.java."""

import pytest

from presto_tpu.localrunner import LocalQueryRunner


@pytest.fixture(scope="module")
def runner():
    return LocalQueryRunner.tpch(scale=0.01)


def q1(runner, sql):
    rows = runner.execute(sql).rows
    assert len(rows) == 1
    return rows[0]


CASES = [
    # casts to varchar
    ("select cast(42 as varchar)", ("42",)),
    ("select cast(-7 as varchar)", ("-7",)),
    ("select cast(1.5 as varchar)", ("1.5",)),
    ("select cast(true as varchar), cast(false as varchar)",
     ("true", "false")),
    ("select cast(date '2020-03-05' as varchar)", ("2020-03-05",)),
    ("select cast(cast(1.25 as decimal(5,2)) as varchar)", ("1.25",)),
    ("select cast(cast(null as bigint) as varchar)", (None,)),
    ("select cast(array[1,2] as array(double))", ([1.0, 2.0],)),
    # date formatting
    ("select date_format(timestamp '2020-03-05 14:30:45', "
     "'%Y/%m/%d %H:%i:%s')", ("2020/03/05 14:30:45",)),
    ("select format_datetime(timestamp '2020-03-05 14:30:45', "
     "'yyyy-MM-dd HH:mm')", ("2020-03-05 14:30",)),
    # json
    ('select json_extract_scalar(\'{"a": {"b": 7}}\', \'$.a.b\')', ("7",)),
    ('select json_extract(\'{"a": [1, 2]}\', \'$.a\')', ("[1,2]",)),
    ("select json_array_length('[1,2,3]')", (3,)),
    ("select json_array_get('[10,20,30]', 1)", ("20",)),
    ("select json_array_get('[10,20,30]', -1)", ("30",)),
    ('select json_extract_scalar(\'{"a": 1}\', \'$.missing\')', (None,)),
    ("select json_array_length('not json')", (None,)),
    ('select json_size(\'{"a": {"b": 1, "c": 2}}\', \'$.a\')', (2,)),
    # binary / hashing (known digests)
    ("select to_hex(md5(to_utf8('abc')))",
     ("900150983CD24FB0D6963F7D28E17F72",)),
    ("select to_hex(sha256(to_utf8('abc')))",
     ("BA7816BF8F01CFEA414140DE5DAE2223B00361A396177A9CB410FF61F20015AD",)),
    ("select crc32(to_utf8('abc'))", (891568578,)),
    ("select to_base64(to_utf8('hi')), from_utf8(from_base64('aGk='))",
     ("aGk=", "hi")),
    ("select to_hex(from_hex('DEADBEEF'))", ("DEADBEEF",)),
    # url
    ("select url_extract_host('https://x.io:8080/p?q=1')", ("x.io",)),
    ("select url_extract_port('https://x.io:8080/p')", (8080,)),
    ("select url_extract_protocol('https://x.io/p')", ("https",)),
    ("select url_extract_path('https://x.io/a/b?q=1')", ("/a/b",)),
    ("select url_extract_query('https://x.io/p?q=1&r=2')", ("q=1&r=2",)),
    ("select url_extract_parameter('http://a/b?k=v&x=2', 'x')", ("2",)),
    ("select url_encode('a b'), url_decode('a%20b')", ("a%20b", "a b")),
]


@pytest.mark.parametrize("sql,expected", CASES,
                         ids=[c[0][:60] for c in CASES])
def test_scalar(runner, sql, expected):
    assert q1(runner, sql) == expected


def test_cast_varchar_over_column(runner):
    rows = runner.execute(
        "select cast(o_orderkey as varchar) from orders "
        "where o_orderkey <= 3 order by o_orderkey").rows
    assert rows == [("1",), ("2",), ("3",)]


def test_multi_string_column_concat(runner):
    rows = runner.execute(
        "select concat(o_orderpriority, '/', o_orderstatus) "
        "from orders where o_orderkey = 1").rows
    (v,) = rows[0]
    assert "/" in v and v.endswith(("F", "O", "P"))


def test_multi_string_column_matches_oracle(runner):
    # concat of two columns must equal python-side concat row by row
    rows = runner.execute(
        "select o_orderpriority, o_orderstatus, "
        "concat(o_orderpriority, o_orderstatus) from orders "
        "where o_orderkey < 50").rows
    for a, b, c in rows:
        assert c == a + b


def test_string_fn_with_column_arg(runner):
    rows = runner.execute(
        "select substr(o_orderpriority, 1, o_orderkey) from orders "
        "where o_orderkey <= 2 order by o_orderkey").rows
    assert rows[0] == ("3",) or len(rows[0][0]) == 1
    assert len(rows[1][0]) == 2


def test_date_format_grouping(runner):
    sql = ("select date_format(cast(o_orderdate as timestamp), '%Y-%m') "
           "as ym, count(*) from orders group by 1 order by 1 limit 3")
    rows = runner.execute(sql).rows
    assert all(len(ym) == 7 and ym[4] == "-" for ym, _ in rows)
    assert sorted(rows) == rows


MORE_CASES = [
    ("select width_bucket(3.0, 0.0, 10.0, 5)", (2,)),
    ("select width_bucket(-1.0, 0.0, 10.0, 5)", (0,)),
    ("select width_bucket(11.0, 0.0, 10.0, 5)", (6,)),
    ("select try_cast('abc' as bigint)", (None,)),
    ("select try_cast('42' as bigint)", (42,)),
    ("select try_cast('nope' as date)", (None,)),
    ("select position('b' in 'abc'), position('zz' in 'abc')", (2, 0)),
    ("select typeof(1.5), typeof('x'), typeof(array[1])",
     ("double", "varchar", "array(integer)")),
    ("select bit_count(7, 64), bit_count(255, 8)", (3, 8)),
    ("select normalize('abc')", ("abc",)),
    ("select zip(array[1,2], array['a'])", ([(1, "a"), (2, None)],)),
    ("select zip_with(array[1,2], array[10,20], (x,y) -> x + y)",
     ([11, 22],)),
    ("select map_entries(map(array['a'], array[1]))", ([("a", 1)],)),
    ("select array_average(array[1.0, 2.0, 3.0])", (2.0,)),
    ("select array_average(array[1.0, null, 3.0])", (2.0,)),
]


@pytest.mark.parametrize("sql,expected", MORE_CASES,
                         ids=[c[0][:60] for c in MORE_CASES])
def test_scalar_more(runner, sql, expected):
    assert q1(runner, sql) == expected


def test_current_temporals(runner):
    d, ts_ok = q1(runner, "select current_date, now() is not null")
    import datetime

    assert isinstance(d, datetime.date) and d.year >= 2026 and ts_ok
