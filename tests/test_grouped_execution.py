"""Grouped (bucket-by-bucket) join execution — P9 Lifespans: identical
results to the all-at-once join, with build-side peak memory scaling
~1/k (execution/Lifespan.java:26-38, PlanFragmenter.java:146 roles)."""

import pytest

from presto_tpu.config import EngineConfig
from presto_tpu.localrunner import LocalQueryRunner

SCALE = 0.02


def _runner(buckets: int) -> LocalQueryRunner:
    cfg = EngineConfig(grouped_execution_buckets=buckets,
                       task_concurrency=1,
                       dynamic_filtering_enabled=False)
    return LocalQueryRunner.tpch(scale=SCALE, config=cfg)


@pytest.fixture(scope="module")
def plain():
    return _runner(1)


@pytest.fixture(scope="module")
def grouped():
    return _runner(8)


JOIN_SQL = ("select count(*), sum(l_extendedprice) from orders "
            "join lineitem on o_orderkey = l_orderkey "
            "where o_totalprice > 50000")


@pytest.mark.slow
def test_results_identical(plain, grouped):
    a = plain.execute(JOIN_SQL).rows
    b = grouped.execute(JOIN_SQL).rows
    assert a[0][0] == b[0][0]
    assert abs(a[0][1] - b[0][1]) <= 1e-6 * abs(a[0][1])


@pytest.mark.slow
def test_left_join_grouped(plain, grouped):
    sql = ("select count(*) from orders left join lineitem "
           "on o_orderkey = l_orderkey where o_orderkey < 1000")
    assert plain.execute(sql).rows == grouped.execute(sql).rows


def test_peak_memory_scales_down(plain, grouped):
    """With 8 lifespans only ~1/8 of the build side is resident."""
    plain.execute(JOIN_SQL)
    peak1 = plain._last_task.memory.peak
    grouped.execute(JOIN_SQL)
    peak8 = grouped._last_task.memory.peak
    assert peak8 < peak1 * 0.5, (peak1, peak8)


def test_non_coparitioned_join_falls_back(grouped):
    # customer x orders joins custkey against an orderkey-bucketed
    # table: domains differ, so the standard join runs (still correct)
    sql = ("select count(*) from customer join orders "
           "on c_custkey = o_custkey")
    plain = _runner(1)
    assert grouped.execute(sql).rows == plain.execute(sql).rows


def test_many_batches_per_bucket_no_deadlock():
    """Each bucket emits many more batches than the exchange capacity:
    the sequential-producer protocol must keep streaming (regression:
    strict round-robin waiting on a not-yet-started bucket while the
    current bucket blocked on a full queue)."""
    cfg = EngineConfig(grouped_execution_buckets=4, task_concurrency=1,
                       dynamic_filtering_enabled=False,
                       scan_batch_rows=512)
    r = LocalQueryRunner.tpch(scale=SCALE, config=cfg)
    got = r.execute(JOIN_SQL).rows
    want = _runner(1).execute(JOIN_SQL).rows
    assert got[0][0] == want[0][0]
