"""Plan-fragment JSON serde round-trip tests.

The task-create wire format is JSON (the reference's TaskUpdateRequest,
presto-main/.../server/TaskUpdateRequest.java) — fragments must survive
encode -> json.dumps -> json.loads -> decode exactly, with function
bindings re-resolved from the registry rather than shipped.
"""

import json

import pytest

from presto_tpu.localrunner import LocalQueryRunner
from presto_tpu.server.fragmenter import Fragmenter
from presto_tpu.sql import tree as t
from presto_tpu.sql.optimizer import optimize
from presto_tpu.sql.parser import parse_statement
from presto_tpu.sql.planner import Metadata, Planner
from presto_tpu.sql.planserde import (
    PlanSerdeError, expr_from_json, expr_to_json, fragment_from_json,
    fragment_to_json,
)

QUERIES = [
    # scan + filter + project + agg + sort (Q1 shape)
    "select l_returnflag, l_linestatus, sum(l_quantity), count(*) "
    "from lineitem where l_shipdate <= date '1998-09-02' "
    "group by l_returnflag, l_linestatus order by l_returnflag",
    # co-partitioned join + agg + limit (Q3 shape)
    "select o_orderpriority, count(*) from orders join lineitem "
    "on o_orderkey = l_orderkey where l_quantity > 45 "
    "group by o_orderpriority order by 2 desc limit 5",
    # semijoin + case + window
    "select o_orderkey, row_number() over (partition by o_orderpriority "
    "order by o_totalprice desc) from orders "
    "where o_orderkey in (select l_orderkey from lineitem "
    "where l_quantity > 49)",
    # union + values + expression zoo
    "select cast(o_orderkey as double), "
    "case when o_totalprice > 100000 then 'big' else 'small' end, "
    "coalesce(nullif(o_orderpriority, '1-URGENT'), 'urgent'), "
    "round(o_totalprice, 1), substr(o_orderpriority, 1, 3) "
    "from orders union all select 0.0, 'y', 'z', 0.5, 'w'",
    # distinct agg + avg/stddev decompositions
    "select o_orderpriority, count(distinct o_custkey), avg(o_totalprice), "
    "stddev(o_totalprice) from orders group by o_orderpriority",
]


@pytest.fixture(scope="module")
def metadata():
    return Metadata(LocalQueryRunner.tpch(scale=0.01).registry, "tpch")


@pytest.mark.parametrize("sql", QUERIES)
def test_fragment_roundtrip(metadata, sql):
    stmt = parse_statement(sql)
    logical = Planner(metadata).plan(stmt)
    dplan = Fragmenter(metadata=metadata).fragment(
        optimize(logical, metadata))
    assert dplan.fragments
    for frag in dplan.fragments:
        wire = json.dumps(fragment_to_json(frag))
        back = fragment_from_json(json.loads(wire))
        assert back == frag
        # re-encode is a fixpoint
        assert json.dumps(fragment_to_json(back)) == wire


@pytest.mark.parametrize("sql", QUERIES)
def test_producer_subtree_is_transitive_closure(metadata, sql):
    """The whole-stage-retry annotation: every fragment's
    producer_subtree is exactly the transitive closure of its consumed
    fragments (the re-run unit when one of its tasks is lost)."""
    stmt = parse_statement(sql)
    dplan = Fragmenter(metadata=metadata).fragment(
        optimize(Planner(metadata).plan(stmt), metadata))
    by_id = {f.fragment_id: f for f in dplan.fragments}

    def closure(fid):
        out = set()
        stack = list(by_id[fid].consumed_fragments)
        while stack:
            c = stack.pop()
            if c not in out:
                out.add(c)
                stack.extend(by_id[c].consumed_fragments)
        return out

    for f in dplan.fragments:
        assert set(f.producer_subtree) == closure(f.fragment_id), \
            f.fragment_id


def test_expr_roundtrip_rebinds_functions(metadata):
    sql = ("select l_extendedprice * (1 - l_discount) from lineitem "
           "where l_shipdate between date '1994-01-01' "
           "and date '1994-12-31'")
    stmt = parse_statement(sql)
    logical = Planner(metadata).plan(stmt)
    dplan = Fragmenter(metadata=metadata).fragment(
        optimize(logical, metadata))
    from presto_tpu.expr.ir import Call, walk
    from presto_tpu.sql.plan import FilterNode, ProjectNode

    def nodes(n):
        yield n
        for s in n.sources:
            yield from nodes(s)

    calls = 0
    for frag in dplan.fragments:
        for node in nodes(frag.root):
            exprs = []
            if isinstance(node, FilterNode):
                exprs.append(node.predicate)
            if isinstance(node, ProjectNode):
                exprs.extend(node.expressions)
            for e in exprs:
                back = expr_from_json(json.loads(json.dumps(expr_to_json(e))))
                assert back == e
                for sub in walk(back):
                    if isinstance(sub, Call):
                        calls += 1
                        assert sub.fn is not None  # rebound, not shipped
    assert calls > 0


def test_malformed_fragment_rejected():
    with pytest.raises((PlanSerdeError, KeyError)):
        fragment_from_json({"fragment_id": 0, "root": {"k": "evil"},
                            "partitioning": "single",
                            "output_partitioning": ["single", []],
                            "consumed_fragments": []})


def test_worker_rejects_bad_task_body():
    """POSTing garbage to task-create must yield 400, never execution."""
    import urllib.error
    import urllib.request

    from presto_tpu.server.worker import WorkerServer

    w = WorkerServer(LocalQueryRunner.tpch(scale=0.01).registry)
    try:
        req = urllib.request.Request(
            f"{w.uri}/v1/task/t0", data=b"\x80\x04nonsense", method="POST",
            headers={"Content-Type": "application/octet-stream"})
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req, timeout=10)
        assert ei.value.code == 400
    finally:
        w.close()
