"""Device-sharded exchange tier (collectives as the data plane).

Covers the PR 11 acceptance pins:

- parity: the SAME queries through a mesh_device_exchange cluster (the
  whole fragment DAG lowered to ONE SPMD program, boundaries as
  in-program collectives) vs the operator-tier HTTP exchange cluster —
  exact rows across TPC-H Q1/Q3/Q6/Q9 and a TPC-DS rollup query;
- knobs-off restores PR 10: with the three knobs at their off values
  the fragmenter emits byte-identical plans, queries schedule real
  worker tasks, and every boundary rides the HTTP plane;
- forced fallback: an unsupported shape (COUNT(DISTINCT)) on a
  device-exchange cluster falls back to the HTTP plane mid-query with
  exact rows and a recorded fallback reason;
- the partitioned lookup source (P8) and bucket-sequential grouped
  execution (P9) tiers hold parity on the mesh runner, and the
  exchange-mode / kernel-tier counters land in the stats rollup.

And the PR 12 telemetry plane (TestDeviceTelemetry): per-shard stats
read OUT of the SPMD program render in distributed EXPLAIN ANALYZE and
fold into stageStats/taskStats on /v1/query/{id}; progress beacons make
a mid-program client poll observe >=2 RUNNING samples with monotonic
progress; beacons OFF restores the PR 11 sampling surfaces exactly;
the span tree (with lower/compile attribution) round-trips through
query.json; and fallback reasons / device bytes land on /metrics.
"""

import dataclasses as dc
import sys

import numpy as np
import pytest

from presto_tpu.config import DEFAULT, EngineConfig
from presto_tpu.server.dqr import DistributedQueryRunner

sys.path.insert(0, __file__.rsplit("/", 1)[0])
from tpch_queries import QUERIES as TPCH  # noqa: E402

DEV_CFG = dc.replace(DEFAULT, mesh_device_exchange=True)


def _close(a, b):
    if len(a) != len(b):
        return False
    for ra, rb in zip(sorted(a, key=repr), sorted(b, key=repr)):
        if len(ra) != len(rb):
            return False
        for va, vb in zip(ra, rb):
            if isinstance(va, float) and isinstance(vb, float):
                if not (np.isclose(va, vb, rtol=1e-6)
                        or (np.isnan(va) and np.isnan(vb))):
                    return False
            elif va != vb:
                return False
    return True


@pytest.fixture(scope="module")
def clusters():
    with DistributedQueryRunner.tpch(scale=0.01, n_workers=2) as http:
        with DistributedQueryRunner.tpch(scale=0.01, n_workers=2,
                                         config=DEV_CFG) as dev:
            yield http, dev


def _last_query(runner):
    return list(runner.coordinator.queries.values())[-1]


class TestDeviceExchangeParity:
    @pytest.mark.parametrize("qn", [1, 3, 6, 9])
    def test_tpch_parity_device_vs_http(self, clusters, qn):
        http, dev = clusters
        sql = TPCH[qn]
        want = http.execute(sql).rows
        q_http = _last_query(http)
        got = dev.execute(sql).rows
        q_dev = _last_query(dev)
        assert _close(got, want), f"q{qn} rows diverge across tiers"
        # the control cluster rode the wire; the device cluster lowered
        # every boundary to an in-program collective
        assert set(q_http.exchange_modes) == {"http"}
        assert set(q_dev.exchange_modes) == {"device"}
        assert not q_dev._tasks_scheduled
        assert q_dev.query_stats.get("exchange_modes", {}).get("device", 0) \
            == q_dev.exchange_modes["device"]

    def test_tpcds_rollup_parity(self):
        """A TPC-DS ROLLUP config (q27, one of the ENGINE_ONLY rollups)
        through both tiers: exact rows whichever tier the shape lands
        on (rollup grouping falls back to the HTTP plane when outside
        the collective subset)."""
        import os

        path = os.path.join(os.path.dirname(__file__), "tpcds_suite",
                            "q27.sql")
        with open(path) as f:
            sql = f.read()
        with DistributedQueryRunner.tpcds(scale=0.003,
                                          n_workers=2) as http:
            want = http.execute(sql).rows
        with DistributedQueryRunner.tpcds(scale=0.003, n_workers=2,
                                          config=DEV_CFG) as dev:
            got = dev.execute(sql).rows
            q_dev = _last_query(dev)
        assert _close(got, want)
        # whichever tier served it, the boundary accounting is complete
        assert set(q_dev.exchange_modes) <= {"device", "http"}
        assert q_dev.exchange_modes

    def test_repeat_statement_reuses_compiled_program(self, clusters):
        _http, dev = clusters
        sql = TPCH[6]
        first = dev.execute(sql).rows
        second = dev.execute(sql).rows
        assert _close(first, second)
        assert set(_last_query(dev).exchange_modes) == {"device"}


class TestKnobsOffRestoresPr10:
    def test_defaults_are_off_values(self):
        cfg = EngineConfig()
        assert cfg.mesh_device_exchange is False
        assert cfg.grouped_mesh_execution == 1

    def test_fragmenter_plans_identical(self):
        """The annotation pass never changes the structural plan: the
        fragment DAG (ids, roots, partitionings, boundaries) and its
        rendering are byte-identical with the knobs on and off."""
        from presto_tpu.localrunner import LocalQueryRunner
        from presto_tpu.server.coordinator import QueryExecution
        from presto_tpu.server.fragmenter import (
            Fragmenter, annotate_device_exchange,
        )
        from presto_tpu.sql.optimizer import optimize
        from presto_tpu.sql.parser import parse_statement
        from presto_tpu.sql.planner import Planner

        runner = LocalQueryRunner.tpch(scale=0.001)
        for qn in (3, 6):
            logical = Planner(runner.metadata).plan(
                parse_statement(TPCH[qn]))
            texts = {}
            for label, cfg in (("off", DEFAULT), ("on", DEV_CFG)):
                optimized = optimize(logical, runner.metadata, cfg)
                dplan = Fragmenter(metadata=runner.metadata,
                                   config=cfg).fragment(optimized)
                if label == "on":
                    annotate_device_exchange(dplan)
                texts[label] = QueryExecution._format_dplan(dplan)
            assert texts["on"] == texts["off"]

    def test_knobs_off_schedules_tasks(self, clusters):
        http, _dev = clusters
        http.execute("select count(*) from tpch.region")
        q = _last_query(http)
        assert q._tasks_scheduled
        assert q._placements
        assert set(q.exchange_modes) == {"http"}


class TestForcedFallback:
    def test_unsupported_shape_falls_back_to_http(self, clusters):
        """approx_percentile's sketch component is outside the mesh
        primitive set: the device cluster must schedule real tasks (the
        HTTP plane) and still return exact rows, recording why it fell
        back."""
        http, dev = clusters
        sql = ("select approx_percentile(l_quantity, 0.5) as p, "
               "count(*) as n from tpch.lineitem")
        want = http.execute(sql).rows
        got = dev.execute(sql).rows
        q = _last_query(dev)
        assert _close(got, want)
        assert q._tasks_scheduled
        assert set(q.exchange_modes) == {"http"}
        assert q.device_exchange_info.get("fallback")

    def test_session_knob_disables_per_query(self, clusters):
        _http, dev = clusters
        client = dev.new_client()
        client.execute("set session mesh_device_exchange = false")
        _cols, _data = client.execute(
            "select count(*) from tpch.region")
        q = _last_query(dev)
        assert q._tasks_scheduled
        assert set(q.exchange_modes) == {"http"}


class TestDeviceTelemetry:
    def test_explain_analyze_renders_per_shard(self, clusters):
        """Distributed EXPLAIN ANALYZE of a mesh query: per-fragment
        sections with one row PER SHARD (in/out rows + exchanged
        bytes), the boundary footer naming the collective per
        boundary, and the single-dispatch program line."""
        _http, dev = clusters
        res = dev.execute("explain analyze " + TPCH[3])
        text = "\n".join(r[0] for r in res.rows)
        q = _last_query(dev)
        assert set(q.exchange_modes) == {"device"}
        assert "shard" in text and "exchanged bytes" in text
        assert "exchange boundaries (device):" in text
        assert "all_to_all" in text and "gather" in text
        assert "1 SPMD dispatch" in text
        # per-shard rows: both shards of a sharded fragment render
        assert "x2 shards" in text

    def test_http_analyze_gains_boundary_footer(self, clusters):
        """The wire tier's EXPLAIN ANALYZE names its boundaries too, so
        the two tiers stay diffable on the same footer shape."""
        http, _dev = clusters
        res = http.execute("explain analyze " + TPCH[6])
        text = "\n".join(r[0] for r in res.rows)
        assert "exchange boundaries (http):" in text
        assert "via http" in text

    def test_query_detail_stage_task_stats(self, clusters):
        """/v1/query/{id} of a mesh query carries real per-fragment
        stageStats and synthetic per-shard taskStats — the same
        payload shape an HTTP query fills from remote task info."""
        import json
        import urllib.request

        _http, dev = clusters
        dev.execute(TPCH[3])
        q = _last_query(dev)
        with urllib.request.urlopen(
                f"{dev.coordinator.uri}/v1/query/{q.query_id}") as r:
            d = json.loads(r.read())
        assert d["stageStats"] and d["taskStats"]
        # sharded fragments fold one task per shard, FINISHED, with
        # rows and device-boundary bytes
        flat = [ts for lst in d["taskStats"].values() for ts in lst]
        assert any(ts["output_rows"] > 0 for ts in flat)
        assert any(ts["device_exchange_bytes"] > 0 for ts in flat)
        assert all(ts["state"] == "FINISHED" for ts in flat)
        sharded = [fid for fid, st in d["stageStats"].items()
                   if st["tasks"] == 2]
        assert sharded, "no sharded stage folded 2 per-shard tasks"
        # the ONE program dispatch lands on the rollup
        assert d["queryStats"]["jit_dispatches"] == 1
        assert d["queryStats"]["device_exchange_bytes"] > 0
        assert d["deviceExchange"]["per_shard"]["fragments"]

    def test_mid_query_progress_beacons(self, clusters):
        """The acceptance pin: while the SPMD program executes (held by
        the beacon test hook), a client poll observes >=2 RUNNING
        samples with monotonically increasing progress, and the
        sampler ring fills mid-program."""
        import threading
        import time

        _http, dev = clusters
        co = dev.coordinator
        sql = TPCH[3]
        known = set(co.queries)

        def hook(_fid, _shard, _rows):
            time.sleep(0.25)

        co._beacon_test_hook = hook
        try:
            done = []
            t = threading.Thread(
                target=lambda: done.append(dev.execute(sql)))
            t.start()
            polls = []
            deadline = time.time() + 60
            q = None
            while time.time() < deadline and t.is_alive():
                if q is None:
                    fresh = [co.queries[k] for k in co.queries
                             if k not in known]
                    q = fresh[-1] if fresh else None
                if q is not None:
                    stats = q.protocol_stats()
                    if stats["state"] == "RUNNING" \
                            and "progressPercent" in stats:
                        polls.append(stats["progressPercent"])
                time.sleep(0.02)
            t.join(timeout=60)
            assert done, "query did not finish"
        finally:
            co._beacon_test_hook = None
        running = polls
        assert len(running) >= 2, f"saw {len(running)} RUNNING polls"
        assert running == sorted(running), "progress regressed"
        assert running[-1] > running[0], "progress never advanced"
        # the sampler ring filled MID-program with monotonic units
        ring = [s for s in q.timeseries if s["state"] == "RUNNING"]
        assert len(ring) >= 2
        completed = [s["splits_completed"] for s in ring]
        assert completed == sorted(completed)
        # and the final settle reports 100%
        assert q._progress["progressPercent"] == 100.0

    def test_beacons_off_restores_pr11_sampling(self):
        """mesh_progress_beacons=false traces a beacon-free program:
        no mid-run samples, no progress object — the PR 11 sampling
        surfaces for a device query, exactly — while the per-shard
        stats rollup (program outputs, not callbacks) stays intact."""
        cfg = dc.replace(DEV_CFG, mesh_progress_beacons=False)
        with DistributedQueryRunner.tpch(scale=0.01, n_workers=2,
                                         config=cfg) as dev:
            rows = dev.execute(TPCH[6]).rows
            q = _last_query(dev)
            assert rows
            assert set(q.exchange_modes) == {"device"}
            assert q.timeseries == []
            assert q._progress == {}
            # tentpole (a) is beacon-independent: stats still fold
            assert q.stage_stats and q.task_stats
            assert q.query_stats["jit_dispatches"] == 1

    def test_span_roundtrip_query_json(self, tmp_path):
        """The span tree of a mesh query — with lower/compile phases
        from the program build — validates structurally and
        round-trips through QueryCompletedEvent/query.json identical
        to the live /v1/query/{id}/spans payload."""
        import json
        import urllib.request

        from presto_tpu.spans import validate_span_tree

        log = tmp_path / "query.json"
        with DistributedQueryRunner.tpch(
                scale=0.01, n_workers=2, config=DEV_CFG,
                event_log_path=str(log)) as dev:
            dev.execute(TPCH[6])
            q = _last_query(dev)
            with urllib.request.urlopen(
                    f"{dev.coordinator.uri}/v1/query/"
                    f"{q.query_id}/spans") as r:
                live = json.loads(r.read())
        records = [json.loads(ln) for ln in
                   log.read_text().splitlines()]
        completed = [r for r in records
                     if r["event"] == "QueryCompletedEvent"
                     and r["query_id"] == q.query_id]
        assert completed, "no QueryCompletedEvent in query.json"
        tree = completed[-1]["spans"]
        assert validate_span_tree(tree) == []
        names = [c["name"] for c in tree["children"]]
        # the program was BUILT by this fresh cluster: lower + compile
        # phases recorded, execute always
        assert "execute" in names
        assert "lower" in names and "compile" in names
        assert any(c["kind"] == "stage" for c in tree["children"])
        # live endpoint serves the same phases for the same query
        assert [c["name"] for c in live["children"]] == names

    def test_fallback_and_device_metrics(self, clusters):
        """/metrics: fallback reasons (bounded labels) from the
        recorded device_exchange_info, plus served-query and
        per-mode byte counters from the per-shard telemetry."""
        import urllib.request

        _http, dev = clusters
        # one served query and one forced fallback
        dev.execute(TPCH[6])
        dev.execute("select approx_percentile(l_quantity, 0.5) "
                    "from tpch.lineitem")
        q = _last_query(dev)
        assert q.device_exchange_info.get("fallback")
        assert q.device_exchange_info.get("fallback_kind")
        with urllib.request.urlopen(
                f"{dev.coordinator.uri}/metrics") as r:
            body = r.read().decode()
        lines = [ln for ln in body.splitlines()
                 if ln.startswith("presto_device_exchange")]
        q_total = [ln for ln in lines
                   if ln.startswith("presto_device_exchange_queries")]
        assert q_total and float(q_total[0].split()[-1]) >= 1
        assert any(ln.startswith("presto_device_exchange_bytes_total"
                                 '{mode="hash"}')
                   and float(ln.split()[-1]) > 0 for ln in lines)
        fb = [ln for ln in lines
              if ln.startswith("presto_device_exchange_fallback_total")
              and 'reason="none"' not in ln]
        assert fb and sum(float(ln.split()[-1]) for ln in fb) >= 1

    def test_program_cache_hit_reports_zero_compile(self, clusters):
        """Cross-query program-cache hits: the second execution of a
        statement reports compile_ns=0 / program_cached=true while the
        first paid (and recorded) the build."""
        _http, dev = clusters
        sql = ("select sum(l_extendedprice) from tpch.lineitem "
               "where l_quantity < 10")
        dev.execute(sql)
        first = _last_query(dev).device_exchange_info
        dev.execute(sql)
        second = _last_query(dev).device_exchange_info
        assert not first["program_cached"]
        assert first["compile_ns"] > 0
        assert second["program_cached"]
        assert second["compile_ns"] == 0
        assert _last_query(dev).query_stats["jit_compiles"] == 0


class TestMeshJoinTiers:
    SQL = ("select o_orderpriority, count(*) as c, "
           "sum(l_extendedprice) as s from lineitem, orders "
           "where l_orderkey = o_orderkey "
           "group by o_orderpriority order by o_orderpriority")
    LEFT = ("select l_returnflag, count(*) as c, sum(l_quantity) as q "
            "from lineitem left join orders on l_orderkey = o_orderkey "
            "group by l_returnflag")

    @pytest.fixture(scope="class")
    def oracle(self):
        from presto_tpu.localrunner import LocalQueryRunner

        local = LocalQueryRunner.tpch(scale=0.01)
        return {s: local.execute(s).rows for s in (self.SQL, self.LEFT)}

    def _run(self, cfg, oracle):
        from presto_tpu.parallel.sqlmesh import MeshQueryRunner

        mesh = MeshQueryRunner.tpch(scale=0.01, n_devices=2, config=cfg)
        for sql, want in oracle.items():
            got = mesh.execute(sql)
            assert _close(got.rows, want), f"mesh diverges: {sql[:40]}"
        return mesh.last_run_info

    def test_partitioned_lookup_source_parity(self, oracle):
        """P8: the PagesHash build table sharded per shard, probes
        resolved through the (lo, counts) contract."""
        info = self._run(dc.replace(
            DEFAULT, partitioned_join_build=True,
            device_join_probe_max_build_rows=1), oracle)
        assert any(t.endswith(":pages_hash")
                   for t in info["kernel_tiers"])

    def test_partitioned_build_off_restores_sorted_tier(self, oracle):
        info = self._run(dc.replace(
            DEFAULT, partitioned_join_build=False), oracle)
        assert not any("pages_hash" in t for t in info["kernel_tiers"])

    def test_grouped_mesh_execution_parity(self, oracle):
        """P9: bucket-sequential grouped join — every bucket's tier
        marker lands, rows exact."""
        info = self._run(dc.replace(
            DEFAULT, grouped_mesh_execution=4,
            partitioned_join_build=True,
            device_join_probe_max_build_rows=1), oracle)
        buckets = {t for t in info["kernel_tiers"]
                   if t.startswith("grouped join")}
        assert len(buckets) == 4
        assert all("pages_hash" in t for t in buckets)

    def test_grouped_execution_off_is_single_pass(self, oracle):
        info = self._run(dc.replace(
            DEFAULT, grouped_mesh_execution=1), oracle)
        assert not any(t.startswith("grouped join")
                       for t in info["kernel_tiers"])


class TestStickyFallback:
    def test_boundary_fallback_annotations_cached(self, clusters):
        """An annotation-level fallback (approx_percentile is outside
        the collective subset) is already cheap on repeat: eligibility
        is cached on the cached plan's fragments, and the repeat is
        still counted under the same bounded reason."""
        _http, dev = clusters
        sql = ("select approx_percentile(l_tax, 0.5) as p, count(*) n "
               "from tpch.lineitem where l_quantity < 10")
        dev.execute(sql)
        q1 = _last_query(dev)
        assert q1.device_exchange_info.get("fallback_kind") == \
            "unsupported_boundary"
        fb1 = dict(
            dev.coordinator.device_exchange_counters["fallbacks"])
        dev.execute(sql)
        q2 = _last_query(dev)
        assert q2.plan_cached and q2._tasks_scheduled
        fb2 = dev.coordinator.device_exchange_counters["fallbacks"]
        assert fb2["unsupported_boundary"] == \
            fb1["unsupported_boundary"] + 1

    def test_capacity_nonconvergence_fallback_is_sticky(self, clusters):
        """A capacity non-convergence (MeshUnsupported raised AT
        lowering/execution, after annotation passed) records its
        fallback ON the cached fragmented plan: the repeat statement
        reuses the already-fragmented plan on the HTTP plane — plan
        cache hit, ZERO mesh-executor attempts (no re-lowering, no
        4-bucket overflow ladder per repeat) — and is still counted
        under presto_device_exchange_fallback_total{reason=}."""
        from presto_tpu.parallel import sqlmesh

        _http, dev = clusters
        sql = ("select l_linestatus, count(*) c from tpch.lineitem "
               "where l_quantity < 4 group by l_linestatus")

        orig = sqlmesh.MeshQueryRunner.execute_dplan

        def non_converging(self, dplan, key):
            raise sqlmesh.MeshUnsupported(
                "mesh execution did not converge: overflow at "
                "cap_scale=8")

        sqlmesh.MeshQueryRunner.execute_dplan = non_converging
        try:
            want = dev.execute(sql).rows
        finally:
            sqlmesh.MeshQueryRunner.execute_dplan = orig
        q1 = _last_query(dev)
        assert q1.device_exchange_info.get("fallback_kind") == \
            "unsupported_shape"
        assert "did not converge" in \
            q1.device_exchange_info.get("fallback", "")
        assert q1._tasks_scheduled, "fallback ran the HTTP plane"
        fb1 = dict(
            dev.coordinator.device_exchange_counters["fallbacks"])
        # the repeat must never touch the mesh executor again
        calls = []
        orig_ex = dev.coordinator.mesh_executor

        def counting(cfg, nparts):
            calls.append(nparts)
            return orig_ex(cfg, nparts)

        dev.coordinator.mesh_executor = counting
        try:
            got = dev.execute(sql).rows
        finally:
            dev.coordinator.mesh_executor = orig_ex
        q2 = _last_query(dev)
        assert sorted(got) == sorted(want)
        assert q2.plan_cached, "repeat must hit the plan cache"
        assert not calls, "sticky fallback must skip the mesh executor"
        assert set(q2.exchange_modes) == {"http"}
        assert q2.device_exchange_info.get("fallback_kind") == \
            "unsupported_shape"
        fb2 = dev.coordinator.device_exchange_counters["fallbacks"]
        assert fb2["unsupported_shape"] == \
            fb1.get("unsupported_shape", 0) + 1, \
            "the repeat fallback must still be counted"


# -- PR 17: boundary checkpoints + partial-state resume ----------------------

_ORACLES = {}


def _oracle(sql, scale=0.01):
    from presto_tpu.localrunner import LocalQueryRunner

    if scale not in _ORACLES:
        _ORACLES[scale] = LocalQueryRunner.tpch(scale=scale)
    return _ORACLES[scale].execute(sql).rows


def _ckpt_cfg(tmp, **over):
    return dc.replace(DEV_CFG, mesh_checkpoint_boundaries=True,
                      exchange_spooling_enabled=True,
                      exchange_spool_path=str(tmp / "spool"), **over)


class TestMeshResume:
    """PR 17: the collective data plane is restartable at
    fragment-boundary granularity.

    - clean checkpointed runs hold exact parity and spool every
      non-root boundary (complete streams, counted bytes);
    - the kill-every-checkpoint-boundary sweep (TPC-H Q3 and Q9)
      recovers exact rows at EVERY kill point with zero re-execution of
      checkpointed fragments: each fragment is lowered exactly once
      across kill + resume (the FRAGMENTS_LOWERED pin);
    - mesh_resume_mode='http' degrades to the task-scheduled plane
      scheduling ONLY the remaining fragments — checkpointed producers
      serve as spool:// leaf inputs, never as HTTP tasks;
    - checkpoints off restores the PR 14 all-or-nothing device plane
      exactly (DEVICE fault rules are dead code, no mid-program seams);
    - a coordinator KILLED mid-mesh-query hands its checkpoint journal
      to the standby, which resumes from the adopted boundaries.
    """

    @pytest.fixture(scope="class")
    def ckpt(self, tmp_path_factory):
        from presto_tpu.server.faults import FaultInjector

        inj = FaultInjector()
        cfg = _ckpt_cfg(tmp_path_factory.mktemp("mesh-ckpt"))
        with DistributedQueryRunner.tpch(scale=0.01, n_workers=2,
                                         config=cfg,
                                         coordinator_injector=inj) as dev:
            yield dev, inj

    # Q9 (the widest DAG) rides the slow tier: the checkpointed mode
    # compiles every group per execution, so its kill-every-boundary
    # sweep alone costs ~90s — tier-1 keeps the Q3 sweep
    Q39 = [3, pytest.param(9, marks=pytest.mark.slow)]

    @pytest.mark.parametrize("qn", Q39)
    def test_clean_checkpointed_parity(self, ckpt, qn):
        dev, _inj = ckpt
        sql = TPCH[qn]
        want = _oracle(sql)
        got = dev.execute(sql).rows
        q = _last_query(dev)
        assert _close(got, want), f"q{qn} checkpointed rows diverge"
        assert set(q.exchange_modes) == {"device"}
        assert not q._tasks_scheduled
        info = q.device_exchange_info
        assert info.get("checkpoint_groups", 0) >= 2
        assert not q.device_resumes
        # every non-root boundary is spool-complete under the query's
        # own checkpoint task ids, and the bytes are accounted
        assert q._device_ckpts
        for fid, rec in q._device_ckpts.items():
            assert rec["task_id"].startswith(f"{q.query_id}.ckpt{fid}.")
            assert dev.coordinator.spool.is_complete(rec["task_id"],
                                                     rec["n_out"])
        assert info.get("checkpoint_bytes", 0) > 0

    @pytest.mark.parametrize("qn", Q39)
    def test_kill_every_boundary_device_resume(self, ckpt, qn):
        from presto_tpu.parallel import sqlmesh

        dev, inj = ckpt
        sql = TPCH[qn]
        want = _oracle(sql)
        dev.execute(sql)
        info0 = _last_query(dev).device_exchange_info
        kill_fids = sorted(info0.get("fragments_lowered") or [])
        assert len(kill_fids) >= 2, "need a multi-group DAG to sweep"
        for fid in kill_fids:
            inj.add_device_rule(rf"/f{fid}/s\d+$")
            hits0 = len(inj.injections)
            lowered0 = sqlmesh.FRAGMENTS_LOWERED
            got = dev.execute(sql).rows
            q = _last_query(dev)
            assert _close(got, want), f"kill at f{fid}: rows diverge"
            assert len(inj.injections) > hits0, \
                f"kill at f{fid}: fault never fired"
            assert q.device_resumes, f"kill at f{fid}: no resume"
            assert q.device_resumes[-1]["mode"] == "device"
            assert q.device_resumes[-1]["failed_fragment"] == fid
            assert not q._tasks_scheduled, "resume stayed on the mesh"
            resumed_from = set(q.device_resumes[-1]["resumed_from"])
            info = q.device_exchange_info
            # the zero-re-execution pin: checkpointed fragments are fed
            # from the spool, never re-lowered into the resumed program
            assert not resumed_from & set(
                info.get("fragments_lowered") or []), \
                f"kill at f{fid}: checkpointed fragments re-lowered"
            # and across kill + resume, each fragment of the DAG was
            # lowered exactly once
            assert sqlmesh.FRAGMENTS_LOWERED - lowered0 == \
                len(kill_fids), f"kill at f{fid}: re-lowering happened"

    def test_http_degrade_schedules_only_remaining_fragments(
            self, tmp_path):
        """mesh_resume_mode='http': every kill point degrades to the
        HTTP plane with exact rows; fragments with complete checkpoints
        become spool:// leaf inputs (zero HTTP tasks), only the
        remaining fragments are scheduled."""
        from presto_tpu.server.faults import FaultInjector

        inj = FaultInjector()
        cfg = _ckpt_cfg(tmp_path, mesh_resume_mode="http")
        sql = TPCH[3]
        want = _oracle(sql)
        with DistributedQueryRunner.tpch(scale=0.01, n_workers=2,
                                         config=cfg,
                                         coordinator_injector=inj) as dev:
            dev.execute(sql)
            info0 = _last_query(dev).device_exchange_info
            kill_fids = sorted(info0.get("fragments_lowered") or [])
            assert len(kill_fids) >= 2
            # first (no checkpoints yet), a mid-DAG boundary, and the
            # root group (the merge-consumer edge case) — the full
            # every-point http sweep rides tools/chaos_run.py
            kill_fids = sorted({kill_fids[0],
                                kill_fids[len(kill_fids) // 2],
                                kill_fids[-1]})
            stages_with_leaves = 0
            for fid in kill_fids:
                inj.add_device_rule(rf"/f{fid}/s\d+$")
                got = dev.execute(sql).rows
                q = _last_query(dev)
                assert _close(got, want), f"kill at f{fid}: rows diverge"
                assert q.device_resumes
                assert q.device_resumes[-1]["mode"] == "http"
                assert q._tasks_scheduled, "degrade rides the HTTP plane"
                resumed_from = set(q.device_resumes[-1]["resumed_from"])
                placed = {f for f, _, _ in q._placements}
                assert not placed & resumed_from, \
                    f"kill at f{fid}: checkpointed fragments re-tasked"
                leaves = {f for f, uris in q._task_uris.items()
                          if uris and any(str(u).startswith("spool://")
                                          for u in uris)}
                assert leaves <= resumed_from
                if leaves:
                    stages_with_leaves += 1
            # late kills must actually serve checkpoints as leaf inputs
            assert stages_with_leaves >= 1

    def test_checkpoints_off_restores_all_or_nothing(self, tmp_path):
        """mesh_checkpoint_boundaries=False restores the PR 14 device
        plane exactly: one SPMD program for the whole DAG, no
        checkpoint spooling, no resume surfaces — DEVICE fault rules
        never even fire (there is no mid-program seam to hook)."""
        from presto_tpu.server.faults import FaultInjector

        inj = FaultInjector()
        sql = TPCH[3]
        want = _oracle(sql)
        with DistributedQueryRunner.tpch(scale=0.01, n_workers=2,
                                         config=DEV_CFG,
                                         coordinator_injector=inj) as dev:
            inj.add_device_rule(r"/f\d+/s\d+$")
            got = dev.execute(sql).rows
            q = _last_query(dev)
            assert _close(got, want)
            assert set(q.exchange_modes) == {"device"}
            assert not inj.injections, \
                "checkpoints off: DEVICE rules must be dead code"
            assert not q.device_resumes
            assert not q._device_ckpts
            info = q.device_exchange_info
            assert "checkpoint_groups" not in info
            assert "checkpoint_bytes" not in info

    def test_resume_surfaces_land_everywhere(self, ckpt):
        """One killed boundary, every observability surface: /metrics
        counters, /v1/query/{id} deviceCheckpoints/deviceResumes, and
        the EXPLAIN ANALYZE footer."""
        import json
        import urllib.request

        dev, inj = ckpt
        sql = TPCH[3]
        # the discovery run doubles as the EXPLAIN ANALYZE footer pin
        analyze = dev.execute(f"explain analyze {sql}").rows
        text = "\n".join(r[0] for r in analyze)
        assert "device checkpoints:" in text
        fids = sorted(
            _last_query(dev).device_exchange_info["fragments_lowered"])
        inj.add_device_rule(rf"/f{fids[-1]}/s\d+$")
        dev.execute(sql)
        q = _last_query(dev)
        assert q.device_resumes
        uri = dev.coordinator.uri
        with urllib.request.urlopen(f"{uri}/metrics", timeout=10) as r:
            metrics = r.read().decode()
        assert 'presto_device_exchange_resume_total{mode="device"}' \
            in metrics
        assert "presto_device_checkpoint_bytes_total" in metrics
        for line in metrics.splitlines():
            if line.startswith("presto_device_exchange_resume_total"
                               '{mode="device"}'):
                assert float(line.rsplit(" ", 1)[1]) >= 1
        with urllib.request.urlopen(
                f"{uri}/v1/query/{q.query_id}", timeout=10) as r:
            detail = json.loads(r.read())
        assert detail["deviceResumes"]
        assert detail["deviceResumes"][-1]["mode"] == "device"
        assert detail["deviceCheckpoints"]

    def test_coordinator_kill_mid_mesh_adopts_checkpoint_journal(
            self, tmp_path):
        """The HA shape: kill the PRIMARY mid-checkpoint-sequence (the
        mesh held by a DEVICE delay rule).  The standby requeues the
        query seeded with the journaled checkpoints and resumes from
        the adopted boundaries — exact rows, completed fragments never
        re-lowered."""
        import threading
        import time

        from presto_tpu.server.dqr import HAQueryRunner
        from presto_tpu.server.faults import FaultInjector

        inj = FaultInjector()
        cfg = _ckpt_cfg(tmp_path,
                        coordinator_state_path=str(tmp_path / "state"),
                        coordinator_lease_ttl_s=0.4,
                        task_recovery_interval_s=0.05)
        sql = TPCH[3]
        want = _oracle(sql)
        with HAQueryRunner.tpch(scale=0.01, n_workers=2, config=cfg,
                                coordinator_injector=inj,
                                heartbeat_interval_s=0.05,
                                heartbeat_max_missed=2) as ha:
            # hold every checkpoint group ~0.8s on the PRIMARY only (the
            # standby has no injector), so the kill lands mid-sequence
            # with boundaries already journaled
            inj.add_device_rule(r"/f\d+/s0$", policy="delay",
                                delay_s=0.8)
            res = {}

            def run():
                try:
                    res["rows"] = ha.execute(sql).rows
                except Exception as e:  # noqa: BLE001
                    res["err"] = repr(e)

            t = threading.Thread(target=run)
            t.start()
            q0 = None
            deadline = time.monotonic() + 30.0
            while time.monotonic() < deadline:
                for q in list(ha.coordinator.queries.values()):
                    if q.sql == sql and q._device_ckpts:
                        q0 = q
                        break
                if q0 is not None:
                    break
                time.sleep(0.02)
            assert q0 is not None, "no boundary ever checkpointed"
            time.sleep(0.1)   # let the checkpoint journal write land
            ha.kill_primary()
            ha.wait_for_failover()
            t.join(timeout=120)
            assert not t.is_alive(), "client never finished"
            assert "err" not in res, res
            assert _close(res["rows"], want)
            sq = ha.standby.queries[q0.query_id]
            assert sq.state == "FINISHED"
            assert ha.standby.ha_counters["adopted"].get("requeued") == 1
            assert sq.device_resumes
            first = sq.device_resumes[0]
            assert first["reason"] == "adopted checkpoint journal"
            assert first["resumed_from"], \
                "standby must resume from adopted boundaries"
            assert not set(first["resumed_from"]) & set(
                sq.device_exchange_info.get("fragments_lowered") or []), \
                "adopted checkpoints were re-lowered on the standby"
            assert not sq._tasks_scheduled
