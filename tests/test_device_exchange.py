"""Device-sharded exchange tier (collectives as the data plane).

Covers the PR 11 acceptance pins:

- parity: the SAME queries through a mesh_device_exchange cluster (the
  whole fragment DAG lowered to ONE SPMD program, boundaries as
  in-program collectives) vs the operator-tier HTTP exchange cluster —
  exact rows across TPC-H Q1/Q3/Q6/Q9 and a TPC-DS rollup query;
- knobs-off restores PR 10: with the three knobs at their off values
  the fragmenter emits byte-identical plans, queries schedule real
  worker tasks, and every boundary rides the HTTP plane;
- forced fallback: an unsupported shape (COUNT(DISTINCT)) on a
  device-exchange cluster falls back to the HTTP plane mid-query with
  exact rows and a recorded fallback reason;
- the partitioned lookup source (P8) and bucket-sequential grouped
  execution (P9) tiers hold parity on the mesh runner, and the
  exchange-mode / kernel-tier counters land in the stats rollup.
"""

import dataclasses as dc
import sys

import numpy as np
import pytest

from presto_tpu.config import DEFAULT, EngineConfig
from presto_tpu.server.dqr import DistributedQueryRunner

sys.path.insert(0, __file__.rsplit("/", 1)[0])
from tpch_queries import QUERIES as TPCH  # noqa: E402

DEV_CFG = dc.replace(DEFAULT, mesh_device_exchange=True)


def _close(a, b):
    if len(a) != len(b):
        return False
    for ra, rb in zip(sorted(a, key=repr), sorted(b, key=repr)):
        if len(ra) != len(rb):
            return False
        for va, vb in zip(ra, rb):
            if isinstance(va, float) and isinstance(vb, float):
                if not (np.isclose(va, vb, rtol=1e-6)
                        or (np.isnan(va) and np.isnan(vb))):
                    return False
            elif va != vb:
                return False
    return True


@pytest.fixture(scope="module")
def clusters():
    with DistributedQueryRunner.tpch(scale=0.01, n_workers=2) as http:
        with DistributedQueryRunner.tpch(scale=0.01, n_workers=2,
                                         config=DEV_CFG) as dev:
            yield http, dev


def _last_query(runner):
    return list(runner.coordinator.queries.values())[-1]


class TestDeviceExchangeParity:
    @pytest.mark.parametrize("qn", [1, 3, 6, 9])
    def test_tpch_parity_device_vs_http(self, clusters, qn):
        http, dev = clusters
        sql = TPCH[qn]
        want = http.execute(sql).rows
        q_http = _last_query(http)
        got = dev.execute(sql).rows
        q_dev = _last_query(dev)
        assert _close(got, want), f"q{qn} rows diverge across tiers"
        # the control cluster rode the wire; the device cluster lowered
        # every boundary to an in-program collective
        assert set(q_http.exchange_modes) == {"http"}
        assert set(q_dev.exchange_modes) == {"device"}
        assert not q_dev._tasks_scheduled
        assert q_dev.query_stats.get("exchange_modes", {}).get("device", 0) \
            == q_dev.exchange_modes["device"]

    def test_tpcds_rollup_parity(self):
        """A TPC-DS ROLLUP config (q27, one of the ENGINE_ONLY rollups)
        through both tiers: exact rows whichever tier the shape lands
        on (rollup grouping falls back to the HTTP plane when outside
        the collective subset)."""
        import os

        path = os.path.join(os.path.dirname(__file__), "tpcds_suite",
                            "q27.sql")
        with open(path) as f:
            sql = f.read()
        with DistributedQueryRunner.tpcds(scale=0.003,
                                          n_workers=2) as http:
            want = http.execute(sql).rows
        with DistributedQueryRunner.tpcds(scale=0.003, n_workers=2,
                                          config=DEV_CFG) as dev:
            got = dev.execute(sql).rows
            q_dev = _last_query(dev)
        assert _close(got, want)
        # whichever tier served it, the boundary accounting is complete
        assert set(q_dev.exchange_modes) <= {"device", "http"}
        assert q_dev.exchange_modes

    def test_repeat_statement_reuses_compiled_program(self, clusters):
        _http, dev = clusters
        sql = TPCH[6]
        first = dev.execute(sql).rows
        second = dev.execute(sql).rows
        assert _close(first, second)
        assert set(_last_query(dev).exchange_modes) == {"device"}


class TestKnobsOffRestoresPr10:
    def test_defaults_are_off_values(self):
        cfg = EngineConfig()
        assert cfg.mesh_device_exchange is False
        assert cfg.grouped_mesh_execution == 1

    def test_fragmenter_plans_identical(self):
        """The annotation pass never changes the structural plan: the
        fragment DAG (ids, roots, partitionings, boundaries) and its
        rendering are byte-identical with the knobs on and off."""
        from presto_tpu.localrunner import LocalQueryRunner
        from presto_tpu.server.coordinator import QueryExecution
        from presto_tpu.server.fragmenter import (
            Fragmenter, annotate_device_exchange,
        )
        from presto_tpu.sql.optimizer import optimize
        from presto_tpu.sql.parser import parse_statement
        from presto_tpu.sql.planner import Planner

        runner = LocalQueryRunner.tpch(scale=0.001)
        for qn in (3, 6):
            logical = Planner(runner.metadata).plan(
                parse_statement(TPCH[qn]))
            texts = {}
            for label, cfg in (("off", DEFAULT), ("on", DEV_CFG)):
                optimized = optimize(logical, runner.metadata, cfg)
                dplan = Fragmenter(metadata=runner.metadata,
                                   config=cfg).fragment(optimized)
                if label == "on":
                    annotate_device_exchange(dplan)
                texts[label] = QueryExecution._format_dplan(dplan)
            assert texts["on"] == texts["off"]

    def test_knobs_off_schedules_tasks(self, clusters):
        http, _dev = clusters
        http.execute("select count(*) from tpch.region")
        q = _last_query(http)
        assert q._tasks_scheduled
        assert q._placements
        assert set(q.exchange_modes) == {"http"}


class TestForcedFallback:
    def test_unsupported_shape_falls_back_to_http(self, clusters):
        """approx_percentile's sketch component is outside the mesh
        primitive set: the device cluster must schedule real tasks (the
        HTTP plane) and still return exact rows, recording why it fell
        back."""
        http, dev = clusters
        sql = ("select approx_percentile(l_quantity, 0.5) as p, "
               "count(*) as n from tpch.lineitem")
        want = http.execute(sql).rows
        got = dev.execute(sql).rows
        q = _last_query(dev)
        assert _close(got, want)
        assert q._tasks_scheduled
        assert set(q.exchange_modes) == {"http"}
        assert q.device_exchange_info.get("fallback")

    def test_session_knob_disables_per_query(self, clusters):
        _http, dev = clusters
        client = dev.new_client()
        client.execute("set session mesh_device_exchange = false")
        _cols, _data = client.execute(
            "select count(*) from tpch.region")
        q = _last_query(dev)
        assert q._tasks_scheduled
        assert set(q.exchange_modes) == {"http"}


class TestMeshJoinTiers:
    SQL = ("select o_orderpriority, count(*) as c, "
           "sum(l_extendedprice) as s from lineitem, orders "
           "where l_orderkey = o_orderkey "
           "group by o_orderpriority order by o_orderpriority")
    LEFT = ("select l_returnflag, count(*) as c, sum(l_quantity) as q "
            "from lineitem left join orders on l_orderkey = o_orderkey "
            "group by l_returnflag")

    @pytest.fixture(scope="class")
    def oracle(self):
        from presto_tpu.localrunner import LocalQueryRunner

        local = LocalQueryRunner.tpch(scale=0.01)
        return {s: local.execute(s).rows for s in (self.SQL, self.LEFT)}

    def _run(self, cfg, oracle):
        from presto_tpu.parallel.sqlmesh import MeshQueryRunner

        mesh = MeshQueryRunner.tpch(scale=0.01, n_devices=2, config=cfg)
        for sql, want in oracle.items():
            got = mesh.execute(sql)
            assert _close(got.rows, want), f"mesh diverges: {sql[:40]}"
        return mesh.last_run_info

    def test_partitioned_lookup_source_parity(self, oracle):
        """P8: the PagesHash build table sharded per shard, probes
        resolved through the (lo, counts) contract."""
        info = self._run(dc.replace(
            DEFAULT, partitioned_join_build=True,
            device_join_probe_max_build_rows=1), oracle)
        assert any(t.endswith(":pages_hash")
                   for t in info["kernel_tiers"])

    def test_partitioned_build_off_restores_sorted_tier(self, oracle):
        info = self._run(dc.replace(
            DEFAULT, partitioned_join_build=False), oracle)
        assert not any("pages_hash" in t for t in info["kernel_tiers"])

    def test_grouped_mesh_execution_parity(self, oracle):
        """P9: bucket-sequential grouped join — every bucket's tier
        marker lands, rows exact."""
        info = self._run(dc.replace(
            DEFAULT, grouped_mesh_execution=4,
            partitioned_join_build=True,
            device_join_probe_max_build_rows=1), oracle)
        buckets = {t for t in info["kernel_tiers"]
                   if t.startswith("grouped join")}
        assert len(buckets) == 4
        assert all("pages_hash" in t for t in buckets)

    def test_grouped_execution_off_is_single_pass(self, oracle):
        info = self._run(dc.replace(
            DEFAULT, grouped_mesh_execution=1), oracle)
        assert not any(t.startswith("grouped join")
                       for t in info["kernel_tiers"])
