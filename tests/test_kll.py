"""Mergeable approx_percentile (KLL sketch) — bounded state, partial/final
parity (the QuantileDigestAggregationFunction role, VERDICT r3 #7)."""

import numpy as np
import pytest

from presto_tpu import types as T
from presto_tpu.localrunner import LocalQueryRunner
from presto_tpu.sketch import KllSketch


def test_rank_accuracy_and_bounded_state():
    rng = np.random.default_rng(1)
    data = rng.lognormal(size=100_000)
    s = KllSketch()
    s.add_many(data.tolist())
    for q in (0.05, 0.25, 0.5, 0.75, 0.95):
        got = s.quantile(q)
        rank_err = abs(float((data <= got).mean()) - q)
        assert rank_err < 0.02, (q, rank_err)
    # bounded state: far below the 100k raw values
    assert len(s.serialize()) < 64_000


def test_merge_matches_single_sketch():
    rng = np.random.default_rng(2)
    data = rng.normal(size=40_000)
    parts = [KllSketch(seed=i + 1) for i in range(8)]
    for i, chunk in enumerate(np.array_split(data, 8)):
        parts[i].add_many(chunk.tolist())
    merged = KllSketch()
    for p in parts:
        merged.merge(KllSketch.deserialize(p.serialize()))
    assert merged.count == len(data)
    for q in (0.1, 0.5, 0.9):
        got = merged.quantile(q)
        rank_err = abs(float((data <= got).mean()) - q)
        assert rank_err < 0.03, (q, rank_err)


def test_partial_final_split_parity():
    """The distributed path: partial 'kll' components on row slices,
    'kll_merge' at FINAL — same answer as one sketch over everything."""
    from presto_tpu.batch import batch_from_pylist
    from presto_tpu.exec.aggregation import AggChannel, host_aggregate

    rng = np.random.default_rng(3)
    vals = rng.integers(0, 1000, size=9000).astype(float)
    batches = [
        batch_from_pylist([T.BIGINT, T.DOUBLE],
                          [(int(v) % 3, float(v)) for v in chunk])
        for chunk in np.array_split(vals, 4)
    ]
    partials = []
    for b in batches:
        out = host_aggregate([b], [0], [AggChannel("kll", 1, T.VARBINARY)],
                             global_row=False)
        partials.append(out)
    final = host_aggregate(partials, [0],
                           [AggChannel("kll_merge", 1, T.VARBINARY)],
                           global_row=False)
    rows = final.to_pylist()
    assert len(rows) == 3
    for key, payload in rows:
        grp = vals[vals.astype(int) % 3 == key]
        med = KllSketch.deserialize(payload).quantile(0.5)
        rank_err = abs(float((grp <= med).mean()) - 0.5)
        assert rank_err < 0.03, (key, rank_err)


@pytest.fixture(scope="module")
def runner():
    return LocalQueryRunner.tpch(scale=0.01)


def test_sql_approx_percentile(runner):
    (m,) = runner.execute(
        "SELECT approx_percentile(l_quantity, 0.5) "
        "FROM tpch.lineitem").rows[0]
    # l_quantity is uniform 1..50: true median 25, rank tolerance ~2
    assert 23 <= m <= 27
    rows = runner.execute(
        "SELECT l_returnflag, approx_percentile(l_extendedprice, 0.9) "
        "FROM tpch.lineitem GROUP BY l_returnflag").rows
    assert len(rows) == 3 and all(r[1] > 0 for r in rows)
    assert runner.execute(
        "SELECT approx_percentile(l_quantity, 0.5) FROM tpch.lineitem "
        "WHERE 1=0").rows == [(None,)]
