"""JDBC-family connector tests (presto-base-jdbc + concrete-driver role
over stdlib sqlite3): metadata, reads with pushdown, writes, DDL."""

import pytest

from presto_tpu.connectors.jdbc import SqliteConnector
from presto_tpu.localrunner import LocalQueryRunner


@pytest.fixture()
def runner(tmp_path):
    r = LocalQueryRunner.tpch(scale=0.01)
    r.register("sqlite", SqliteConnector(str(tmp_path / "db.sqlite")))
    return r


def test_ddl_insert_select(runner):
    runner.execute("CREATE TABLE sqlite.t (a bigint, b varchar, "
                   "c double, d date, e boolean)")
    runner.execute("INSERT INTO sqlite.t VALUES "
                   "(1, 'x', 0.5, DATE '2021-06-01', true), "
                   "(2, NULL, -1.5, NULL, false)")
    got = sorted(runner.execute("SELECT * FROM sqlite.t").rows)
    import datetime

    assert got[0] == (1, "x", 0.5, datetime.date(2021, 6, 1), True)
    assert got[1] == (2, None, -1.5, None, False)
    assert ("t",) in runner.execute("SHOW TABLES FROM sqlite").rows
    cols = dict(runner.execute("DESCRIBE sqlite.t").rows)
    assert cols["a"] == "bigint" and cols["e"] == "boolean"


def test_predicate_pushdown_to_remote_sql(runner, monkeypatch):
    runner.execute("CREATE TABLE sqlite.p (k bigint, v varchar)")
    runner.execute("INSERT INTO sqlite.p VALUES (1,'a'),(2,'b'),(3,'c'),"
                   "(4,'d')")
    scanned = []
    orig = SqliteConnector.page_source

    def spy(self, split, columns, batch_rows=65536):
        scanned.append(split.info)
        return orig(self, split, columns, batch_rows)

    monkeypatch.setattr(SqliteConnector, "page_source", spy)
    got = sorted(runner.execute(
        "SELECT v FROM sqlite.p WHERE k >= 2 AND k IN (1, 2, 4)").rows)
    assert got == [("b",), ("d",)]
    # the split carries a remote WHERE clause with bind parameters,
    # not inlined literals
    assert scanned
    where, params = scanned[0]
    assert "IN" in where and ">=" in where, where
    assert 2 in params and 4 in params


@pytest.mark.slow
def test_ctas_roundtrip_with_tpch(runner):
    runner.execute("CREATE TABLE sqlite.nat AS SELECT n_nationkey, n_name "
                   "FROM tpch.nation WHERE n_regionkey = 0")
    got = sorted(runner.execute("SELECT n_name FROM sqlite.nat").rows)
    want = sorted(runner.execute(
        "SELECT n_name FROM tpch.nation WHERE n_regionkey = 0").rows)
    assert got == want
    # join remote table against tpch
    j = runner.execute(
        "SELECT count(*) FROM sqlite.nat s JOIN tpch.nation n "
        "ON s.n_nationkey = n.n_nationkey").rows
    assert j == [(5,)]


def test_rename_drop(runner):
    runner.execute("CREATE TABLE sqlite.r1 (a bigint)")
    runner.execute("ALTER TABLE sqlite.r1 RENAME TO r2")
    runner.execute("INSERT INTO sqlite.r2 VALUES (9)")
    assert runner.execute("SELECT * FROM sqlite.r2").rows == [(9,)]
    runner.execute("DROP TABLE sqlite.r2")
    with pytest.raises(Exception):
        runner.execute("SELECT * FROM sqlite.r2")


def test_schema_discovery_of_preexisting_db(tmp_path):
    import sqlite3

    db = str(tmp_path / "ext.sqlite")
    cx = sqlite3.connect(db)
    cx.execute("CREATE TABLE ext (id INTEGER, name TEXT, score REAL, "
               "ok BOOLEAN, born DATE)")
    cx.execute("INSERT INTO ext VALUES (7, 'zed', 2.25, 1, '1990-05-04')")
    cx.commit()
    cx.close()

    r = LocalQueryRunner.tpch(scale=0.01)
    r.register("ext", SqliteConnector(db))
    import datetime

    assert r.execute("SELECT * FROM ext.ext").rows == [
        (7, "zed", 2.25, True, datetime.date(1990, 5, 4))]
