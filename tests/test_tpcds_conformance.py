"""TPC-DS conformance: the query suite vs a sqlite3 oracle.

Same rig as the TPC-H conformance tier (presto-testing's H2QueryRunner
role): the tpcds connector's data is loaded into sqlite, the query text is
adapted to sqlite's dialect, and results are compared row-for-row with
float tolerance.  This value-verifies every query in
``tests/tpcds_queries.py`` including the BASELINE.md pinned Q72/Q95.
"""

import sqlite3

import pytest

from presto_tpu.localrunner import LocalQueryRunner

pytestmark = pytest.mark.slow


from test_tpch_conformance import (
    _sqlite_type, _to_sqlite, assert_rows_match, to_sqlite_sql,
)
from tpcds_queries import QUERIES

SCALE = 0.003


@pytest.fixture(scope="module")
def runner():
    return LocalQueryRunner.tpch(scale=SCALE)


@pytest.fixture(scope="module")
def oracle(runner):
    conn = sqlite3.connect(":memory:")
    conn.execute("PRAGMA case_sensitive_like = ON")
    tpcds = runner.registry.get("tpcds")
    for table in tpcds.list_tables():
        handle = tpcds.get_table(table)
        schema = tpcds.table_schema(handle)
        names = schema.column_names()
        cols_sql = ", ".join(f"{n} {_sqlite_type(schema.column_type(n))}"
                             for n in names)
        conn.execute(f"create table {table} ({cols_sql})")
        for split in tpcds.get_splits(handle, 1):
            for batch in tpcds.page_source(split, names, 1 << 20):
                rows = [tuple(_to_sqlite(v) for v in r)
                        for r in batch.to_pylist()]
                ph = ", ".join("?" * len(names))
                conn.executemany(
                    f"insert into {table} values ({ph})", rows)
        # without indexes sqlite nested-loops the 8-10-way star joins
        # (Q72 alone runs for hours); index every surrogate key
        for n in names:
            if n.endswith("_sk") or n.endswith("_number"):
                conn.execute(
                    f"create index idx_{table}_{n} on {table} ({n})")
    conn.execute("analyze")
    conn.commit()
    return conn


def _strip_catalog(sql: str) -> str:
    return sql.replace("tpcds.", "")


@pytest.mark.parametrize("qnum", sorted(QUERIES))
def test_tpcds_query(runner, oracle, qnum):
    sql = QUERIES[qnum]
    got = runner.execute(sql).rows
    want = oracle.execute(_strip_catalog(to_sqlite_sql(sql))).fetchall()
    # sorted-multiset comparison: ORDER BY ties beyond the sort keys make
    # positional diffs flaky (same policy as the TPC-H tier)
    assert_rows_match(got, want, ordered=False)
