"""TPC-DS-derived benchmark queries over the tpcds connector.

Adapted from the public TPC-DS query set the same way the reference ships
them as benchto resources (presto-benchto-benchmarks/.../sql/presto/tpcds/):
standard parameter substitutions, and date arithmetic written with
date_diff where the engine lacks interval-on-date addition.  Q72/Q95 are
the BASELINE.md pinned configs.
"""

QUERIES = {
    # star join: brand revenue for a manufacturer, November
    3: """
select d_year, i_brand_id brand_id, i_brand brand,
       sum(ss_ext_sales_price) sum_agg
from tpcds.date_dim, tpcds.store_sales, tpcds.item
where d_date_sk = ss_sold_date_sk and ss_item_sk = i_item_sk
  and i_manufact_id = 436 and d_moy = 12
group by d_year, i_brand_id, i_brand
order by d_year, sum_agg desc, brand_id
limit 100
""",
    # demographics + promotion channels
    7: """
select i_item_id, avg(ss_quantity) agg1, avg(ss_list_price) agg2,
       avg(ss_sales_price) agg4
from tpcds.store_sales, tpcds.customer_demographics, tpcds.date_dim,
     tpcds.item, tpcds.promotion
where ss_sold_date_sk = d_date_sk and ss_item_sk = i_item_sk
  and ss_cdemo_sk = cd_demo_sk and ss_promo_sk = p_promo_sk
  and cd_gender = 'M' and cd_marital_status = 'S'
  and cd_education_status = 'College'
  and (p_channel_email = 'N' or p_channel_event = 'N')
  and d_year = 2000
group by i_item_id order by i_item_id limit 100
""",
    # brand revenue by manager in a month window
    19: """
select i_brand_id brand_id, i_brand brand, i_manufact_id,
       sum(ss_ext_sales_price) ext_price
from tpcds.date_dim, tpcds.store_sales, tpcds.item, tpcds.customer,
     tpcds.customer_address, tpcds.store
where d_date_sk = ss_sold_date_sk and ss_item_sk = i_item_sk
  and i_manager_id = 7 and d_moy = 11 and d_year = 1999
  and ss_customer_sk = c_customer_sk
  and c_current_addr_sk = ca_address_sk and ss_store_sk = s_store_sk
group by i_brand_id, i_brand, i_manufact_id
order by ext_price desc, brand_id limit 100
""",
    42: """
select d_year, i_category_id, i_category, sum(ss_ext_sales_price) s
from tpcds.date_dim, tpcds.store_sales, tpcds.item
where d_date_sk = ss_sold_date_sk and ss_item_sk = i_item_sk
  and i_manager_id = 1 and d_moy = 11 and d_year = 2000
group by d_year, i_category_id, i_category
order by s desc, d_year, i_category_id, i_category
limit 100
""",
    52: """
select d_year, i_brand_id brand_id, i_brand brand,
       sum(ss_ext_sales_price) ext_price
from tpcds.date_dim, tpcds.store_sales, tpcds.item
where d_date_sk = ss_sold_date_sk and ss_item_sk = i_item_sk
  and i_manager_id = 1 and d_moy = 11 and d_year = 2000
group by d_year, i_brand_id, i_brand
order by d_year, ext_price desc, brand_id limit 100
""",
    55: """
select i_brand_id brand_id, i_brand brand,
       sum(ss_ext_sales_price) ext_price
from tpcds.date_dim, tpcds.store_sales, tpcds.item
where d_date_sk = ss_sold_date_sk and ss_item_sk = i_item_sk
  and i_manager_id = 28 and d_moy = 11 and d_year = 1999
group by i_brand_id, i_brand
order by ext_price desc, brand_id limit 100
""",
    # BASELINE config: skewed multi-join (inventory shortfall vs promo)
    72: """
select i_item_desc, w_warehouse_name, d1.d_week_seq,
       sum(case when p_promo_sk is null then 1 else 0 end) no_promo,
       sum(case when p_promo_sk is not null then 1 else 0 end) promo,
       count(*) total_cnt
from tpcds.catalog_sales
join tpcds.inventory on cs_item_sk = inv_item_sk
join tpcds.warehouse on w_warehouse_sk = inv_warehouse_sk
join tpcds.item on i_item_sk = cs_item_sk
join tpcds.customer_demographics on cs_bill_cdemo_sk = cd_demo_sk
join tpcds.household_demographics on cs_bill_hdemo_sk = hd_demo_sk
join tpcds.date_dim d1 on cs_sold_date_sk = d1.d_date_sk
join tpcds.date_dim d2 on inv_date_sk = d2.d_date_sk
join tpcds.date_dim d3 on cs_ship_date_sk = d3.d_date_sk
left join tpcds.promotion on cs_promo_sk = p_promo_sk
left join tpcds.catalog_returns on cr_item_sk = cs_item_sk
    and cr_order_number = cs_order_number
where d1.d_week_seq = d2.d_week_seq
  and inv_quantity_on_hand < cs_quantity
  and date_diff('day', d1.d_date, d3.d_date) > 5
  and hd_buy_potential = '>10000'
  and d1.d_year = 1999
  and cd_marital_status = 'D'
group by i_item_desc, w_warehouse_name, d1.d_week_seq
order by total_cnt desc, i_item_desc, w_warehouse_name, d1.d_week_seq
limit 100
""",
    # BASELINE config: multi-warehouse returned web orders
    95: """
with ws_wh as (
    select ws1.ws_order_number wow
    from tpcds.web_sales ws1, tpcds.web_sales ws2
    where ws1.ws_order_number = ws2.ws_order_number
      and ws1.ws_warehouse_sk <> ws2.ws_warehouse_sk)
select count(distinct ws_order_number) order_count,
       sum(ws_ext_ship_cost) total_shipping_cost,
       sum(ws_net_profit) total_net_profit
from tpcds.web_sales ws1, tpcds.date_dim, tpcds.customer_address,
     tpcds.web_site
where d_date between date '1999-02-01' and date '1999-04-02'
  and ws1.ws_ship_date_sk = d_date_sk
  and ws1.ws_ship_addr_sk = ca_address_sk and ca_state = 'IL'
  and ws1.ws_web_site_sk = web_site_sk and web_company_name = 'pri'
  and ws1.ws_order_number in (select wow from ws_wh)
  and ws1.ws_order_number in (select wr_order_number
                              from tpcds.web_returns)
""",
}
