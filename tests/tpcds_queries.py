"""TPC-DS-derived benchmark queries over the tpcds connector.

Adapted from the public TPC-DS query set the same way the reference ships
them as benchto resources (presto-benchto-benchmarks/.../sql/presto/tpcds/):
standard parameter substitutions, and date arithmetic written with
date_diff where the engine lacks interval-on-date addition.  Q72/Q95 are
the BASELINE.md pinned configs.
"""

QUERIES = {
    # star join: brand revenue for a manufacturer, November
    3: """
select d_year, i_brand_id brand_id, i_brand brand,
       sum(ss_ext_sales_price) sum_agg
from tpcds.date_dim, tpcds.store_sales, tpcds.item
where d_date_sk = ss_sold_date_sk and ss_item_sk = i_item_sk
  and i_manufact_id = 436 and d_moy = 12
group by d_year, i_brand_id, i_brand
order by d_year, sum_agg desc, brand_id
limit 100
""",
    # demographics + promotion channels
    7: """
select i_item_id, avg(ss_quantity) agg1, avg(ss_list_price) agg2,
       avg(ss_sales_price) agg4
from tpcds.store_sales, tpcds.customer_demographics, tpcds.date_dim,
     tpcds.item, tpcds.promotion
where ss_sold_date_sk = d_date_sk and ss_item_sk = i_item_sk
  and ss_cdemo_sk = cd_demo_sk and ss_promo_sk = p_promo_sk
  and cd_gender = 'M' and cd_marital_status = 'S'
  and cd_education_status = 'College'
  and (p_channel_email = 'N' or p_channel_event = 'N')
  and d_year = 2000
group by i_item_id order by i_item_id limit 100
""",
    # brand revenue by manager in a month window
    19: """
select i_brand_id brand_id, i_brand brand, i_manufact_id,
       sum(ss_ext_sales_price) ext_price
from tpcds.date_dim, tpcds.store_sales, tpcds.item, tpcds.customer,
     tpcds.customer_address, tpcds.store
where d_date_sk = ss_sold_date_sk and ss_item_sk = i_item_sk
  and i_manager_id = 7 and d_moy = 11 and d_year = 1999
  and ss_customer_sk = c_customer_sk
  and c_current_addr_sk = ca_address_sk and ss_store_sk = s_store_sk
group by i_brand_id, i_brand, i_manufact_id
order by ext_price desc, brand_id limit 100
""",
    42: """
select d_year, i_category_id, i_category, sum(ss_ext_sales_price) s
from tpcds.date_dim, tpcds.store_sales, tpcds.item
where d_date_sk = ss_sold_date_sk and ss_item_sk = i_item_sk
  and i_manager_id = 1 and d_moy = 11 and d_year = 2000
group by d_year, i_category_id, i_category
order by s desc, d_year, i_category_id, i_category
limit 100
""",
    52: """
select d_year, i_brand_id brand_id, i_brand brand,
       sum(ss_ext_sales_price) ext_price
from tpcds.date_dim, tpcds.store_sales, tpcds.item
where d_date_sk = ss_sold_date_sk and ss_item_sk = i_item_sk
  and i_manager_id = 1 and d_moy = 11 and d_year = 2000
group by d_year, i_brand_id, i_brand
order by d_year, ext_price desc, brand_id limit 100
""",
    55: """
select i_brand_id brand_id, i_brand brand,
       sum(ss_ext_sales_price) ext_price
from tpcds.date_dim, tpcds.store_sales, tpcds.item
where d_date_sk = ss_sold_date_sk and ss_item_sk = i_item_sk
  and i_manager_id = 28 and d_moy = 11 and d_year = 1999
group by i_brand_id, i_brand
order by ext_price desc, brand_id limit 100
""",
    # BASELINE config: skewed multi-join (inventory shortfall vs promo)
    72: """
select i_item_desc, w_warehouse_name, d1.d_week_seq,
       sum(case when p_promo_sk is null then 1 else 0 end) no_promo,
       sum(case when p_promo_sk is not null then 1 else 0 end) promo,
       count(*) total_cnt
from tpcds.catalog_sales
join tpcds.inventory on cs_item_sk = inv_item_sk
join tpcds.warehouse on w_warehouse_sk = inv_warehouse_sk
join tpcds.item on i_item_sk = cs_item_sk
join tpcds.customer_demographics on cs_bill_cdemo_sk = cd_demo_sk
join tpcds.household_demographics on cs_bill_hdemo_sk = hd_demo_sk
join tpcds.date_dim d1 on cs_sold_date_sk = d1.d_date_sk
join tpcds.date_dim d2 on inv_date_sk = d2.d_date_sk
join tpcds.date_dim d3 on cs_ship_date_sk = d3.d_date_sk
left join tpcds.promotion on cs_promo_sk = p_promo_sk
left join tpcds.catalog_returns on cr_item_sk = cs_item_sk
    and cr_order_number = cs_order_number
where d1.d_week_seq = d2.d_week_seq
  and inv_quantity_on_hand < cs_quantity
  and date_diff('day', d1.d_date, d3.d_date) > 5
  and hd_buy_potential = '>10000'
  and d1.d_year = 1999
  and cd_marital_status = 'D'
group by i_item_desc, w_warehouse_name, d1.d_week_seq
order by total_cnt desc, i_item_desc, w_warehouse_name, d1.d_week_seq
limit 100
""",
    # demographic/state brackets driving avg quantities
    13: """
select avg(ss_quantity) q, avg(ss_ext_sales_price) p,
       avg(ss_ext_wholesale_cost) c, sum(ss_ext_wholesale_cost) s
from tpcds.store_sales, tpcds.store, tpcds.customer_demographics,
     tpcds.household_demographics, tpcds.customer_address, tpcds.date_dim
where s_store_sk = ss_store_sk and ss_sold_date_sk = d_date_sk
  and d_year = 2001
  and ((ss_hdemo_sk = hd_demo_sk and cd_demo_sk = ss_cdemo_sk
        and cd_marital_status = 'M' and cd_education_status = 'Advanced Degree'
        and ss_sales_price between 100.00 and 150.00 and hd_dep_count = 3)
   or (ss_hdemo_sk = hd_demo_sk and cd_demo_sk = ss_cdemo_sk
        and cd_marital_status = 'S' and cd_education_status = 'College'
        and ss_sales_price between 50.00 and 100.00 and hd_dep_count = 1)
   or (ss_hdemo_sk = hd_demo_sk and cd_demo_sk = ss_cdemo_sk
        and cd_marital_status = 'W' and cd_education_status = '2 yr Degree'
        and ss_sales_price between 150.00 and 200.00 and hd_dep_count = 1))
  and ((ss_addr_sk = ca_address_sk and ca_country = 'United States'
        and ca_state in ('TX', 'OH', 'TX')
        and ss_net_profit between 100 and 200)
   or (ss_addr_sk = ca_address_sk and ca_country = 'United States'
        and ca_state in ('OR', 'MN', 'KY')
        and ss_net_profit between 150 and 300)
   or (ss_addr_sk = ca_address_sk and ca_country = 'United States'
        and ca_state in ('VA', 'TX', 'MI')
        and ss_net_profit between 50 and 250))
""",
    # catalog sales by buyer zip bracket
    15: """
select ca_zip, sum(cs_sales_price) total
from tpcds.catalog_sales, tpcds.customer, tpcds.customer_address,
     tpcds.date_dim
where cs_bill_customer_sk = c_customer_sk
  and c_current_addr_sk = ca_address_sk
  and (substr(ca_zip, 1, 5) in ('10012', '10033', '10074', '10105',
                                '10146', '10187', '10060', '10081')
       or ca_state in ('CA', 'WA', 'GA') or cs_sales_price > 500)
  and cs_sold_date_sk = d_date_sk and d_qoy = 2 and d_year = 2001
group by ca_zip order by ca_zip limit 100
""",
    # catalog-channel analogue of Q7
    26: """
select i_item_id, avg(cs_quantity) agg1, avg(cs_list_price) agg2,
       avg(cs_sales_price) agg4
from tpcds.catalog_sales, tpcds.customer_demographics, tpcds.date_dim,
     tpcds.item, tpcds.promotion
where cs_sold_date_sk = d_date_sk and cs_item_sk = i_item_sk
  and cs_bill_cdemo_sk = cd_demo_sk and cs_promo_sk = p_promo_sk
  and cd_gender = 'M' and cd_marital_status = 'S'
  and cd_education_status = 'College'
  and (p_channel_email = 'N' or p_channel_event = 'N')
  and d_year = 2000
group by i_item_id order by i_item_id limit 100
""",
    # three-channel union by manufacturer
    33: """
with ss as (
    select i_manufact_id, sum(ss_ext_sales_price) total_sales
    from tpcds.store_sales, tpcds.date_dim, tpcds.customer_address,
         tpcds.item
    where i_category = 'Electronics' and ss_item_sk = i_item_sk
      and ss_sold_date_sk = d_date_sk and d_year = 1998 and d_moy = 5
      and ss_addr_sk = ca_address_sk and ca_gmt_offset = -5
    group by i_manufact_id),
 cs as (
    select i_manufact_id, sum(cs_ext_sales_price) total_sales
    from tpcds.catalog_sales, tpcds.date_dim, tpcds.customer_address,
         tpcds.item
    where i_category = 'Electronics' and cs_item_sk = i_item_sk
      and cs_sold_date_sk = d_date_sk and d_year = 1998 and d_moy = 5
      and cs_ship_addr_sk = ca_address_sk and ca_gmt_offset = -5
    group by i_manufact_id),
 ws as (
    select i_manufact_id, sum(ws_ext_sales_price) total_sales
    from tpcds.web_sales, tpcds.date_dim, tpcds.customer_address,
         tpcds.item
    where i_category = 'Electronics' and ws_item_sk = i_item_sk
      and ws_sold_date_sk = d_date_sk and d_year = 1998 and d_moy = 5
      and ws_ship_addr_sk = ca_address_sk and ca_gmt_offset = -5
    group by i_manufact_id)
select i_manufact_id, sum(total_sales) total_sales
from (select * from ss union all select * from cs
      union all select * from ws) tmp1
group by i_manufact_id order by total_sales, i_manufact_id limit 100
""",
    # big-party tickets (HAVING over per-ticket counts)
    34: """
select c_last_name, c_first_name, ss_ticket_number, cnt
from (select ss_ticket_number, ss_customer_sk, count(*) cnt
      from tpcds.store_sales, tpcds.date_dim, tpcds.store,
           tpcds.household_demographics
      where ss_sold_date_sk = d_date_sk and ss_store_sk = s_store_sk
        and ss_hdemo_sk = hd_demo_sk
        and (d_dom between 1 and 3 or d_dom between 25 and 28)
        and (hd_buy_potential = '>10000'
             or hd_buy_potential = 'Unknown')
        and hd_vehicle_count > 0
        and d_year in (1999, 2000, 2001)
        and s_county in ('Williamson County', 'Franklin Parish')
      group by ss_ticket_number, ss_customer_sk) dn, tpcds.customer
where ss_customer_sk = c_customer_sk and cnt between 2 and 20
order by c_last_name, c_first_name, ss_ticket_number desc, cnt
""",
    # catalog items with bounded inventory in a window
    37: """
select i_item_id, i_item_desc, i_current_price
from tpcds.item, tpcds.inventory, tpcds.date_dim, tpcds.catalog_sales
where i_current_price between 20 and 50
  and inv_item_sk = i_item_sk and d_date_sk = inv_date_sk
  and d_date between date '1999-03-06' and date '1999-05-05'
  and i_manufact_id in (18, 120, 260, 402, 482, 566, 659, 775)
  and inv_quantity_on_hand between 100 and 500
  and cs_item_sk = i_item_sk
group by i_item_id, i_item_desc, i_current_price
order by i_item_id limit 100
""",
    # catalog sales net of returns before/after a pivot date
    40: """
select w_state, i_item_id,
       sum(case when d_date < date '1999-04-10'
                then cs_sales_price - coalesce(cr_refunded_cash, 0)
                else 0 end) sales_before,
       sum(case when d_date >= date '1999-04-10'
                then cs_sales_price - coalesce(cr_refunded_cash, 0)
                else 0 end) sales_after
from tpcds.catalog_sales
left join tpcds.catalog_returns on cs_order_number = cr_order_number
    and cs_item_sk = cr_item_sk, tpcds.warehouse, tpcds.item,
    tpcds.date_dim
where i_current_price between 0.99 and 1.49
  and i_item_sk = cs_item_sk and cs_warehouse_sk = w_warehouse_sk
  and cs_sold_date_sk = d_date_sk
  and d_date between date '1999-03-10' and date '1999-05-10'
group by w_state, i_item_id order by w_state, i_item_id limit 100
""",
    # store sales per day-of-week, pivoted with CASE
    43: """
select s_store_name, s_store_id,
       sum(case when d_day_name = 'Sunday' then ss_sales_price
                else null end) sun_sales,
       sum(case when d_day_name = 'Monday' then ss_sales_price
                else null end) mon_sales,
       sum(case when d_day_name = 'Friday' then ss_sales_price
                else null end) fri_sales,
       sum(case when d_day_name = 'Saturday' then ss_sales_price
                else null end) sat_sales
from tpcds.date_dim, tpcds.store, tpcds.store_sales
where d_date_sk = ss_sold_date_sk and s_store_sk = ss_store_sk
  and s_gmt_offset = -5 and d_year = 2000
group by s_store_name, s_store_id
order by s_store_name, s_store_id limit 100
""",
    # web buyers in zip list or buying flagged items
    45: """
select ca_zip, ca_county, sum(ws_ext_sales_price) total
from tpcds.web_sales, tpcds.customer, tpcds.customer_address,
     tpcds.date_dim, tpcds.item
where ws_bill_customer_sk = c_customer_sk
  and c_current_addr_sk = ca_address_sk and ws_item_sk = i_item_sk
  and (substr(ca_zip, 1, 5) in ('10012', '10033', '10074', '10105',
                                '10146', '10187', '10060', '10081')
       or i_item_sk in (2, 3, 5, 7, 11, 13, 17, 19, 23, 29))
  and ws_sold_date_sk = d_date_sk and d_qoy = 2 and d_year = 2001
group by ca_zip, ca_county order by ca_zip, ca_county limit 100
""",
    # quantity sum over demographic/state/price brackets
    48: """
select sum(ss_quantity) q
from tpcds.store_sales, tpcds.store, tpcds.customer_demographics,
     tpcds.customer_address, tpcds.date_dim
where s_store_sk = ss_store_sk and ss_sold_date_sk = d_date_sk
  and d_year = 2000
  and ((cd_demo_sk = ss_cdemo_sk and cd_marital_status = 'M'
        and cd_education_status = '4 yr Degree'
        and ss_sales_price between 100.00 and 150.00)
   or (cd_demo_sk = ss_cdemo_sk and cd_marital_status = 'D'
        and cd_education_status = '2 yr Degree'
        and ss_sales_price between 50.00 and 100.00)
   or (cd_demo_sk = ss_cdemo_sk and cd_marital_status = 'S'
        and cd_education_status = 'College'
        and ss_sales_price between 150.00 and 200.00))
  and ((ss_addr_sk = ca_address_sk and ca_country = 'United States'
        and ca_state in ('CO', 'OH', 'TX')
        and ss_net_profit between 0 and 2000)
   or (ss_addr_sk = ca_address_sk and ca_country = 'United States'
        and ca_state in ('OR', 'MN', 'KY')
        and ss_net_profit between 150 and 3000)
   or (ss_addr_sk = ca_address_sk and ca_country = 'United States'
        and ca_state in ('VA', 'CA', 'MS')
        and ss_net_profit between 50 and 25000))
""",
    # three-channel union by item id for one category
    60: """
with ss as (
    select i_item_id, sum(ss_ext_sales_price) total_sales
    from tpcds.store_sales, tpcds.date_dim, tpcds.customer_address,
         tpcds.item
    where i_category = 'Music' and ss_item_sk = i_item_sk
      and ss_sold_date_sk = d_date_sk and d_year = 1998 and d_moy = 9
      and ss_addr_sk = ca_address_sk and ca_gmt_offset = -5
    group by i_item_id),
 cs as (
    select i_item_id, sum(cs_ext_sales_price) total_sales
    from tpcds.catalog_sales, tpcds.date_dim, tpcds.customer_address,
         tpcds.item
    where i_category = 'Music' and cs_item_sk = i_item_sk
      and cs_sold_date_sk = d_date_sk and d_year = 1998 and d_moy = 9
      and cs_ship_addr_sk = ca_address_sk and ca_gmt_offset = -5
    group by i_item_id),
 ws as (
    select i_item_id, sum(ws_ext_sales_price) total_sales
    from tpcds.web_sales, tpcds.date_dim, tpcds.customer_address,
         tpcds.item
    where i_category = 'Music' and ws_item_sk = i_item_sk
      and ws_sold_date_sk = d_date_sk and d_year = 1998 and d_moy = 9
      and ws_ship_addr_sk = ca_address_sk and ca_gmt_offset = -5
    group by i_item_id)
select i_item_id, sum(total_sales) total_sales
from (select * from ss union all select * from cs
      union all select * from ws) tmp1
group by i_item_id order by i_item_id, total_sales limit 100
""",
    # items selling at <= 10% of their store's average revenue
    65: """
select s_store_name, i_item_desc, sc.revenue, i_current_price,
       i_wholesale_cost, i_brand
from tpcds.store, tpcds.item,
     (select ss_store_sk, avg(revenue) as ave
      from (select ss_store_sk, ss_item_sk,
                   sum(ss_sales_price) as revenue
            from tpcds.store_sales, tpcds.date_dim
            where ss_sold_date_sk = d_date_sk
              and d_month_seq between 108 and 119
            group by ss_store_sk, ss_item_sk) sa
      group by ss_store_sk) sb,
     (select ss_store_sk, ss_item_sk, sum(ss_sales_price) as revenue
      from tpcds.store_sales, tpcds.date_dim
      where ss_sold_date_sk = d_date_sk
        and d_month_seq between 108 and 119
      group by ss_store_sk, ss_item_sk) sc
where sb.ss_store_sk = sc.ss_store_sk and sc.revenue <= 0.1 * sb.ave
  and s_store_sk = sc.ss_store_sk and i_item_sk = sc.ss_item_sk
order by s_store_name, i_item_desc, sc.revenue limit 100
""",
    # purchase-estimate histogram for store-only shoppers
    69: """
select cd_gender, cd_marital_status, cd_education_status, count(*) cnt1,
       cd_purchase_estimate, count(*) cnt2
from tpcds.customer c, tpcds.customer_address ca,
     tpcds.customer_demographics
where c.c_current_addr_sk = ca.ca_address_sk
  and ca_state in ('KY', 'GA', 'NC')
  and cd_demo_sk = c.c_current_cdemo_sk
  and exists (select * from tpcds.store_sales, tpcds.date_dim
              where c.c_customer_sk = ss_customer_sk
                and ss_sold_date_sk = d_date_sk and d_year = 2001
                and d_moy between 4 and 6)
  and not exists (select * from tpcds.web_sales, tpcds.date_dim
                  where c.c_customer_sk = ws_bill_customer_sk
                    and ws_sold_date_sk = d_date_sk and d_year = 2001
                    and d_moy between 4 and 6)
group by cd_gender, cd_marital_status, cd_education_status,
         cd_purchase_estimate
order by cd_gender, cd_marital_status, cd_education_status,
         cd_purchase_estimate limit 100
""",
    # store analogue of Q37 (inventory-bounded items)
    82: """
select i_item_id, i_item_desc, i_current_price
from tpcds.item, tpcds.inventory, tpcds.date_dim, tpcds.store_sales
where i_current_price between 30 and 60
  and inv_item_sk = i_item_sk and d_date_sk = inv_date_sk
  and d_date between date '2002-05-30' and date '2002-07-29'
  and i_manufact_id in (437, 129, 727, 663, 850, 311, 419, 584)
  and inv_quantity_on_hand between 100 and 500
  and ss_item_sk = i_item_sk
group by i_item_id, i_item_desc, i_current_price
order by i_item_id limit 100
""",
    # class revenue share within category (window over aggregation)
    98: """
select i_item_desc, i_category, i_class, i_current_price, itemrevenue,
       itemrevenue * 100 / sum(itemrevenue)
           over (partition by i_class) as revenueratio
from (select i_item_desc, i_category, i_class, i_current_price,
             sum(ss_ext_sales_price) as itemrevenue
      from tpcds.store_sales, tpcds.item, tpcds.date_dim
      where ss_item_sk = i_item_sk
        and i_category in ('Sports', 'Books', 'Home')
        and ss_sold_date_sk = d_date_sk
        and d_date between date '1999-02-22' and date '1999-03-24'
      group by i_item_desc, i_category, i_class, i_current_price) t
order by i_category, i_class, i_item_desc, revenueratio
limit 100
""",
    # BASELINE config: multi-warehouse returned web orders
    95: """
with ws_wh as (
    select ws1.ws_order_number wow
    from tpcds.web_sales ws1, tpcds.web_sales ws2
    where ws1.ws_order_number = ws2.ws_order_number
      and ws1.ws_warehouse_sk <> ws2.ws_warehouse_sk)
select count(distinct ws_order_number) order_count,
       sum(ws_ext_ship_cost) total_shipping_cost,
       sum(ws_net_profit) total_net_profit
from tpcds.web_sales ws1, tpcds.date_dim, tpcds.customer_address,
     tpcds.web_site
where d_date between date '1999-02-01' and date '1999-04-02'
  and ws1.ws_ship_date_sk = d_date_sk
  and ws1.ws_ship_addr_sk = ca_address_sk and ca_state = 'IL'
  and ws1.ws_web_site_sk = web_site_sk and web_company_name = 'pri'
  and ws1.ws_order_number in (select wow from ws_wh)
  and ws1.ws_order_number in (select wr_order_number
                              from tpcds.web_returns)
""",
}
