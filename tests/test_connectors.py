"""Memory/blackhole/system/information_schema connectors + DML path.

Reference coverage analogue: presto-memory and presto-blackhole connector
tests plus AbstractTestDistributedQueries' CREATE TABLE AS / INSERT
coverage (SURVEY §2.10, §4.4)."""

import pytest

from presto_tpu.localrunner import LocalQueryRunner


@pytest.fixture()
def runner():
    return LocalQueryRunner.tpch(scale=0.001)


class TestMemoryConnector:
    def test_create_insert_select(self, runner):
        runner.execute("create table memory.t (a bigint, b varchar)")
        res = runner.execute(
            "insert into memory.t values (1, 'x'), (2, 'y')")
        assert res.rows == [(2,)]
        assert runner.execute(
            "select * from memory.t order by a").rows == \
            [(1, "x"), (2, "y")]

    def test_insert_column_subset_fills_nulls(self, runner):
        runner.execute("create table memory.t (a bigint, b varchar)")
        runner.execute("insert into memory.t (b) values ('only-b')")
        assert runner.execute("select * from memory.t").rows == \
            [(None, "only-b")]

    def test_insert_coerces_types(self, runner):
        runner.execute("create table memory.t (a double)")
        runner.execute("insert into memory.t values (1)")
        assert runner.execute("select * from memory.t").rows == [(1.0,)]

    def test_ctas(self, runner):
        runner.execute("create table memory.asia as "
                       "select n_name, n_nationkey from nation, region "
                       "where n_regionkey = r_regionkey "
                       "and r_name = 'ASIA'")
        assert runner.execute(
            "select count(*) from memory.asia").rows == [(5,)]
        # written table joins back against tpch tables
        rows = runner.execute(
            "select count(*) from memory.asia a, nation n "
            "where a.n_nationkey = n.n_nationkey").rows
        assert rows == [(5,)]

    def test_drop(self, runner):
        runner.execute("create table memory.t (a bigint)")
        runner.execute("drop table memory.t")
        with pytest.raises(Exception):
            runner.execute("select * from memory.t")

    def test_insert_from_aggregate_query(self, runner):
        runner.execute("create table memory.agg (k bigint, c bigint)")
        runner.execute("insert into memory.agg select n_regionkey, "
                       "count(*) from nation group by n_regionkey")
        assert runner.execute(
            "select sum(c) from memory.agg").rows == [(25,)]


class TestBlackhole:
    def test_swallow(self, runner):
        runner.execute("create table blackhole.sink (x bigint)")
        res = runner.execute("insert into blackhole.sink "
                             "select n_nationkey from nation")
        assert res.rows == [(25,)]
        assert runner.execute(
            "select count(*) from blackhole.sink").rows == [(0,)]


class TestSystemTables:
    def test_nodes(self, runner):
        rows = runner.execute(
            "select node_id, coordinator, state from system.nodes").rows
        assert rows == [("local", True, "ACTIVE")]

    def test_information_schema_tables(self, runner):
        rows = runner.execute(
            "select table_name from information_schema.tables "
            "where table_catalog = 'tpch' order by 1").rows
        names = [r[0] for r in rows]
        assert "lineitem" in names and "orders" in names

    def test_information_schema_columns(self, runner):
        rows = runner.execute(
            "select column_name, data_type "
            "from information_schema.columns "
            "where table_name = 'region' order by ordinal_position").rows
        assert [r[0] for r in rows] == \
            ["r_regionkey", "r_name", "r_comment"]


class TestValues:
    def test_values_in_from(self, runner):
        rows = runner.execute(
            "select x + 1, upper(y) from "
            "(values (1, 'a'), (2, 'b')) t(x, y) order by 1").rows
        assert rows == [(2, "A"), (3, "B")]

    def test_values_join(self, runner):
        rows = runner.execute(
            "select r_name from region, (values (0), (2)) t(k) "
            "where r_regionkey = k order by 1").rows
        assert rows == [("AFRICA",), ("ASIA",)]


class TestCli:
    def test_format_table(self):
        from presto_tpu.cli import format_table

        text = format_table(["a", "bb"], [(1, "x"), (None, "yy")])
        lines = text.splitlines()
        assert lines[0].split(" | ")[0].strip() == "a"
        assert "NULL" in lines[3]
        assert "(2 rows)" in lines[-1]

    def test_embedded_backend(self):
        from presto_tpu.cli import _EmbeddedBackend

        b = _EmbeddedBackend("tpch", 0.001)
        names, rows = b.execute("select count(*) c from region")
        assert names == ["c"]
        assert rows == [(5,)]
