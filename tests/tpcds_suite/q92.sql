SELECT "sum"("ws_ext_discount_amt") "Excess Discount Amount"
FROM
  tpcds.web_sales
, tpcds.item
, tpcds.date_dim
WHERE ("i_manufact_id" = 350)
   AND ("i_item_sk" = "ws_item_sk")
   AND ("d_date" BETWEEN CAST('2000-01-27' AS DATE) AND (CAST('2000-01-27' AS DATE) + INTERVAL  '90' DAY))
   AND ("d_date_sk" = "ws_sold_date_sk")
   AND ("ws_ext_discount_amt" > (
      SELECT (DECIMAL '1.3' * "avg"("ws_ext_discount_amt"))
      FROM
        tpcds.web_sales
      , tpcds.date_dim
      WHERE ("ws_item_sk" = "i_item_sk")
         AND ("d_date" BETWEEN CAST('2000-01-27' AS DATE) AND (CAST('2000-01-27' AS DATE) + INTERVAL  '90' DAY))
         AND ("d_date_sk" = "ws_sold_date_sk")
   ))
ORDER BY "sum"("ws_ext_discount_amt") ASC
LIMIT 100
