WITH
  ws AS (
   SELECT
     "d_year" "ws_sold_year"
   , "ws_item_sk"
   , "ws_bill_customer_sk" "ws_customer_sk"
   , "sum"("ws_quantity") "ws_qty"
   , "sum"("ws_wholesale_cost") "ws_wc"
   , "sum"("ws_sales_price") "ws_sp"
   FROM
     ((tpcds.web_sales
   LEFT JOIN tpcds.web_returns ON ("wr_order_number" = "ws_order_number")
      AND ("ws_item_sk" = "wr_item_sk"))
   INNER JOIN tpcds.date_dim ON ("ws_sold_date_sk" = "d_date_sk"))
   WHERE ("wr_order_number" IS NULL)
   GROUP BY "d_year", "ws_item_sk", "ws_bill_customer_sk"
) 
, cs AS (
   SELECT
     "d_year" "cs_sold_year"
   , "cs_item_sk"
   , "cs_bill_customer_sk" "cs_customer_sk"
   , "sum"("cs_quantity") "cs_qty"
   , "sum"("cs_wholesale_cost") "cs_wc"
   , "sum"("cs_sales_price") "cs_sp"
   FROM
     ((tpcds.catalog_sales
   LEFT JOIN tpcds.catalog_returns ON ("cr_order_number" = "cs_order_number")
      AND ("cs_item_sk" = "cr_item_sk"))
   INNER JOIN tpcds.date_dim ON ("cs_sold_date_sk" = "d_date_sk"))
   WHERE ("cr_order_number" IS NULL)
   GROUP BY "d_year", "cs_item_sk", "cs_bill_customer_sk"
) 
, ss AS (
   SELECT
     "d_year" "ss_sold_year"
   , "ss_item_sk"
   , "ss_customer_sk"
   , "sum"("ss_quantity") "ss_qty"
   , "sum"("ss_wholesale_cost") "ss_wc"
   , "sum"("ss_sales_price") "ss_sp"
   FROM
     ((tpcds.store_sales
   LEFT JOIN tpcds.store_returns ON ("sr_ticket_number" = "ss_ticket_number")
      AND ("ss_item_sk" = "sr_item_sk"))
   INNER JOIN tpcds.date_dim ON ("ss_sold_date_sk" = "d_date_sk"))
   WHERE ("sr_ticket_number" IS NULL)
   GROUP BY "d_year", "ss_item_sk", "ss_customer_sk"
) 
SELECT
  "ss_sold_year"
, "ss_item_sk"
, "ss_customer_sk"
, "round"((CAST("ss_qty" AS DECIMAL(10,2)) / COALESCE(("ws_qty" + "cs_qty"), 1)), 2) "ratio"
, "ss_qty" "store_qty"
, "ss_wc" "store_wholesale_cost"
, "ss_sp" "store_sales_price"
, (COALESCE("ws_qty", 0) + COALESCE("cs_qty", 0)) "other_chan_qty"
, (COALESCE("ws_wc", 0) + COALESCE("cs_wc", 0)) "other_chan_wholesale_cost"
, (COALESCE("ws_sp", 0) + COALESCE("cs_sp", 0)) "other_chan_sales_price"
FROM
  ((ss
LEFT JOIN ws ON ("ws_sold_year" = "ss_sold_year")
   AND ("ws_item_sk" = "ss_item_sk")
   AND ("ws_customer_sk" = "ss_customer_sk"))
LEFT JOIN cs ON ("cs_sold_year" = "ss_sold_year")
   AND ("cs_item_sk" = "cs_item_sk")
   AND ("cs_customer_sk" = "ss_customer_sk"))
WHERE (COALESCE("ws_qty", 0) > 0)
   AND (COALESCE("cs_qty", 0) > 0)
   AND ("ss_sold_year" = 2000)
ORDER BY "ss_sold_year" ASC, "ss_item_sk" ASC, "ss_customer_sk" ASC, "ss_qty" DESC, "ss_wc" DESC, "ss_sp" DESC, "other_chan_qty" ASC, "other_chan_wholesale_cost" ASC, "other_chan_sales_price" ASC, "round"((CAST("ss_qty" AS DECIMAL(10,2)) / COALESCE(("ws_qty" + "cs_qty"), 1)), 2) ASC
LIMIT 100
