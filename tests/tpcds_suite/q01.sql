WITH
  customer_total_return AS (
   SELECT
     "sr_customer_sk" "ctr_customer_sk"
   , "sr_store_sk" "ctr_store_sk"
   , "sum"("sr_return_amt") "ctr_total_return"
   FROM
     tpcds.store_returns
   , tpcds.date_dim
   WHERE ("sr_returned_date_sk" = "d_date_sk")
      AND ("d_year" = 2000)
   GROUP BY "sr_customer_sk", "sr_store_sk"
) 
SELECT "c_customer_id"
FROM
  customer_total_return ctr1
, tpcds.store
, tpcds.customer
WHERE ("ctr1"."ctr_total_return" > (
      SELECT ("avg"("ctr_total_return") * DECIMAL '1.2')
      FROM
        customer_total_return ctr2
      WHERE ("ctr1"."ctr_store_sk" = "ctr2"."ctr_store_sk")
   ))
   AND ("s_store_sk" = "ctr1"."ctr_store_sk")
   AND ("s_state" = 'TN')
   AND ("ctr1"."ctr_customer_sk" = "c_customer_sk")
ORDER BY "c_customer_id" ASC
LIMIT 100
