SELECT
  "i_item_id"
, "i_item_desc"
, "i_category"
, "i_class"
, "i_current_price"
, "sum"("cs_ext_sales_price") "tpcds.itemrevenue"
, (("sum"("cs_ext_sales_price") * 100) / "sum"("sum"("cs_ext_sales_price")) OVER (PARTITION BY "i_class")) "revenueratio"
FROM
  tpcds.catalog_sales
, tpcds.item
, tpcds.date_dim
WHERE ("cs_item_sk" = "i_item_sk")
   AND ("i_category" IN ('Sports', 'Books', 'Home'))
   AND ("cs_sold_date_sk" = "d_date_sk")
   AND (CAST("d_date" AS DATE) BETWEEN CAST('1999-02-22' AS DATE) AND (CAST('1999-02-22' AS DATE) + INTERVAL  '30' DAY))
GROUP BY "i_item_id", "i_item_desc", "i_category", "i_class", "i_current_price"
ORDER BY "i_category" ASC, "i_class" ASC, "i_item_id" ASC, "i_item_desc" ASC, "revenueratio" ASC
LIMIT 100
