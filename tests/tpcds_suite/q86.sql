SELECT
  "sum"("ws_net_paid") "total_sum"
, "i_category"
, "i_class"
, (GROUPING ("i_category") + GROUPING ("i_class")) "lochierarchy"
, "rank"() OVER (PARTITION BY (GROUPING ("i_category") + GROUPING ("i_class")), (CASE WHEN (GROUPING ("i_class") = 0) THEN "i_category" END) ORDER BY "sum"("ws_net_paid") DESC) "rank_within_parent"
FROM
  tpcds.web_sales
, tpcds.date_dim d1
, tpcds.item
WHERE ("d1"."d_month_seq" BETWEEN 1200 AND (1200 + 11))
   AND ("d1"."d_date_sk" = "ws_sold_date_sk")
   AND ("i_item_sk" = "ws_item_sk")
GROUP BY ROLLUP (i_category, i_class)
ORDER BY "lochierarchy" DESC, (CASE WHEN ("lochierarchy" = 0) THEN "i_category" END) ASC, "rank_within_parent" ASC
LIMIT 100
