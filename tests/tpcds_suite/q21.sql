SELECT *
FROM
  (
   SELECT
     "w_warehouse_name"
   , "i_item_id"
   , "sum"((CASE WHEN (CAST("d_date" AS DATE) < CAST('2000-03-11' AS DATE)) THEN "inv_quantity_on_hand" ELSE 0 END)) "inv_before"
   , "sum"((CASE WHEN (CAST("d_date" AS DATE) >= CAST('2000-03-11' AS DATE)) THEN "inv_quantity_on_hand" ELSE 0 END)) "inv_after"
   FROM
     tpcds.inventory
   , tpcds.warehouse
   , tpcds.item
   , tpcds.date_dim
   WHERE ("i_current_price" BETWEEN DECIMAL '0.99' AND DECIMAL '1.49')
      AND ("i_item_sk" = "inv_item_sk")
      AND ("inv_warehouse_sk" = "w_warehouse_sk")
      AND ("inv_date_sk" = "d_date_sk")
      AND ("d_date" BETWEEN (CAST('2000-03-11' AS DATE) - INTERVAL  '30' DAY) AND (CAST('2000-03-11' AS DATE) + INTERVAL  '30' DAY))
   GROUP BY "w_warehouse_name", "i_item_id"
)  x
WHERE ((CASE WHEN ("inv_before" > 0) THEN (CAST("inv_after" AS DECIMAL(7,2)) / "inv_before") ELSE null END) BETWEEN (DECIMAL '2.00' / DECIMAL '3.00') AND (DECIMAL '3.00' / DECIMAL '2.00'))
ORDER BY "w_warehouse_name" ASC, "i_item_id" ASC
LIMIT 100
