SELECT
  "s_store_name"
, "s_store_id"
, "sum"((CASE WHEN ("d_day_name" = 'Sunday') THEN "ss_sales_price" ELSE null END)) "sun_sales"
, "sum"((CASE WHEN ("d_day_name" = 'Monday') THEN "ss_sales_price" ELSE null END)) "mon_sales"
, "sum"((CASE WHEN ("d_day_name" = 'Tuesday') THEN "ss_sales_price" ELSE null END)) "tue_sales"
, "sum"((CASE WHEN ("d_day_name" = 'Wednesday') THEN "ss_sales_price" ELSE null END)) "wed_sales"
, "sum"((CASE WHEN ("d_day_name" = 'Thursday') THEN "ss_sales_price" ELSE null END)) "thu_sales"
, "sum"((CASE WHEN ("d_day_name" = 'Friday') THEN "ss_sales_price" ELSE null END)) "fri_sales"
, "sum"((CASE WHEN ("d_day_name" = 'Saturday') THEN "ss_sales_price" ELSE null END)) "sat_sales"
FROM
  tpcds.date_dim
, tpcds.store_sales
, tpcds.store
WHERE ("d_date_sk" = "ss_sold_date_sk")
   AND ("s_store_sk" = "ss_store_sk")
   AND ("s_gmt_offset" = -5)
   AND ("d_year" = 2000)
GROUP BY "s_store_name", "s_store_id"
ORDER BY "s_store_name" ASC, "s_store_id" ASC, "sun_sales" ASC, "mon_sales" ASC, "tue_sales" ASC, "wed_sales" ASC, "thu_sales" ASC, "fri_sales" ASC, "sat_sales" ASC
LIMIT 100
