SELECT
  "i_item_id"
, "ca_country"
, "ca_state"
, "ca_county"
, "avg"(CAST("cs_quantity" AS DECIMAL(12,2))) "agg1"
, "avg"(CAST("cs_list_price" AS DECIMAL(12,2))) "agg2"
, "avg"(CAST("cs_coupon_amt" AS DECIMAL(12,2))) "agg3"
, "avg"(CAST("cs_sales_price" AS DECIMAL(12,2))) "agg4"
, "avg"(CAST("cs_net_profit" AS DECIMAL(12,2))) "agg5"
, "avg"(CAST("c_birth_year" AS DECIMAL(12,2))) "agg6"
, "avg"(CAST("cd1"."cd_dep_count" AS DECIMAL(12,2))) "agg7"
FROM
  tpcds.catalog_sales
, tpcds.customer_demographics cd1
, tpcds.customer_demographics cd2
, tpcds.customer
, tpcds.customer_address
, tpcds.date_dim
, tpcds.item
WHERE ("cs_sold_date_sk" = "d_date_sk")
   AND ("cs_item_sk" = "i_item_sk")
   AND ("cs_bill_cdemo_sk" = "cd1"."cd_demo_sk")
   AND ("cs_bill_customer_sk" = "c_customer_sk")
   AND ("cd1"."cd_gender" = 'F')
   AND ("cd1"."cd_education_status" = 'Unknown')
   AND ("c_current_cdemo_sk" = "cd2"."cd_demo_sk")
   AND ("c_current_addr_sk" = "ca_address_sk")
   AND ("c_birth_month" IN (1, 6, 8, 9, 12, 2))
   AND ("d_year" = 1998)
   AND ("ca_state" IN ('MS', 'IN', 'ND', 'OK', 'NM', 'VA', 'MS'))
GROUP BY ROLLUP (i_item_id, ca_country, ca_state, ca_county)
ORDER BY "ca_country" ASC, "ca_state" ASC, "ca_county" ASC, "i_item_id" ASC
LIMIT 100
