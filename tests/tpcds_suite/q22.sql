SELECT
  "i_product_name"
, "i_brand"
, "i_class"
, "i_category"
, "avg"("inv_quantity_on_hand") "qoh"
FROM
  tpcds.inventory
, tpcds.date_dim
, tpcds.item
WHERE ("inv_date_sk" = "d_date_sk")
   AND ("inv_item_sk" = "i_item_sk")
   AND ("d_month_seq" BETWEEN 1200 AND (1200 + 11))
GROUP BY ROLLUP (i_product_name, i_brand, i_class, i_category)
ORDER BY "qoh" ASC, "i_product_name" ASC, "i_brand" ASC, "i_class" ASC, "i_category" ASC
LIMIT 100
