SELECT
  "w_warehouse_name"
, "w_warehouse_sq_ft"
, "w_city"
, "w_county"
, "w_state"
, "w_country"
, "ship_carriers"
, "year"
, "sum"("jan_sales") "jan_sales"
, "sum"("feb_sales") "feb_sales"
, "sum"("mar_sales") "mar_sales"
, "sum"("apr_sales") "apr_sales"
, "sum"("may_sales") "may_sales"
, "sum"("jun_sales") "jun_sales"
, "sum"("jul_sales") "jul_sales"
, "sum"("aug_sales") "aug_sales"
, "sum"("sep_sales") "sep_sales"
, "sum"("oct_sales") "oct_sales"
, "sum"("nov_sales") "nov_sales"
, "sum"("dec_sales") "dec_sales"
, "sum"(("jan_sales" / "w_warehouse_sq_ft")) "jan_sales_per_sq_foot"
, "sum"(("feb_sales" / "w_warehouse_sq_ft")) "feb_sales_per_sq_foot"
, "sum"(("mar_sales" / "w_warehouse_sq_ft")) "mar_sales_per_sq_foot"
, "sum"(("apr_sales" / "w_warehouse_sq_ft")) "apr_sales_per_sq_foot"
, "sum"(("may_sales" / "w_warehouse_sq_ft")) "may_sales_per_sq_foot"
, "sum"(("jun_sales" / "w_warehouse_sq_ft")) "jun_sales_per_sq_foot"
, "sum"(("jul_sales" / "w_warehouse_sq_ft")) "jul_sales_per_sq_foot"
, "sum"(("aug_sales" / "w_warehouse_sq_ft")) "aug_sales_per_sq_foot"
, "sum"(("sep_sales" / "w_warehouse_sq_ft")) "sep_sales_per_sq_foot"
, "sum"(("oct_sales" / "w_warehouse_sq_ft")) "oct_sales_per_sq_foot"
, "sum"(("nov_sales" / "w_warehouse_sq_ft")) "nov_sales_per_sq_foot"
, "sum"(("dec_sales" / "w_warehouse_sq_ft")) "dec_sales_per_sq_foot"
, "sum"("jan_net") "jan_net"
, "sum"("feb_net") "feb_net"
, "sum"("mar_net") "mar_net"
, "sum"("apr_net") "apr_net"
, "sum"("may_net") "may_net"
, "sum"("jun_net") "jun_net"
, "sum"("jul_net") "jul_net"
, "sum"("aug_net") "aug_net"
, "sum"("sep_net") "sep_net"
, "sum"("oct_net") "oct_net"
, "sum"("nov_net") "nov_net"
, "sum"("dec_net") "dec_net"
FROM
(
      SELECT
        "w_warehouse_name"
      , "w_warehouse_sq_ft"
      , "w_city"
      , "w_county"
      , "w_state"
      , "w_country"
      , "concat"("concat"('DHL', ','), 'BARIAN') "ship_carriers"
      , "d_year" "YEAR"
      , "sum"((CASE WHEN ("d_moy" = 1) THEN ("ws_ext_sales_price" * "ws_quantity") ELSE 0 END)) "jan_sales"
      , "sum"((CASE WHEN ("d_moy" = 2) THEN ("ws_ext_sales_price" * "ws_quantity") ELSE 0 END)) "feb_sales"
      , "sum"((CASE WHEN ("d_moy" = 3) THEN ("ws_ext_sales_price" * "ws_quantity") ELSE 0 END)) "mar_sales"
      , "sum"((CASE WHEN ("d_moy" = 4) THEN ("ws_ext_sales_price" * "ws_quantity") ELSE 0 END)) "apr_sales"
      , "sum"((CASE WHEN ("d_moy" = 5) THEN ("ws_ext_sales_price" * "ws_quantity") ELSE 0 END)) "may_sales"
      , "sum"((CASE WHEN ("d_moy" = 6) THEN ("ws_ext_sales_price" * "ws_quantity") ELSE 0 END)) "jun_sales"
      , "sum"((CASE WHEN ("d_moy" = 7) THEN ("ws_ext_sales_price" * "ws_quantity") ELSE 0 END)) "jul_sales"
      , "sum"((CASE WHEN ("d_moy" = 8) THEN ("ws_ext_sales_price" * "ws_quantity") ELSE 0 END)) "aug_sales"
      , "sum"((CASE WHEN ("d_moy" = 9) THEN ("ws_ext_sales_price" * "ws_quantity") ELSE 0 END)) "sep_sales"
      , "sum"((CASE WHEN ("d_moy" = 10) THEN ("ws_ext_sales_price" * "ws_quantity") ELSE 0 END)) "oct_sales"
      , "sum"((CASE WHEN ("d_moy" = 11) THEN ("ws_ext_sales_price" * "ws_quantity") ELSE 0 END)) "nov_sales"
      , "sum"((CASE WHEN ("d_moy" = 12) THEN ("ws_ext_sales_price" * "ws_quantity") ELSE 0 END)) "dec_sales"
      , "sum"((CASE WHEN ("d_moy" = 1) THEN ("ws_net_paid" * "ws_quantity") ELSE 0 END)) "jan_net"
      , "sum"((CASE WHEN ("d_moy" = 2) THEN ("ws_net_paid" * "ws_quantity") ELSE 0 END)) "feb_net"
      , "sum"((CASE WHEN ("d_moy" = 3) THEN ("ws_net_paid" * "ws_quantity") ELSE 0 END)) "mar_net"
      , "sum"((CASE WHEN ("d_moy" = 4) THEN ("ws_net_paid" * "ws_quantity") ELSE 0 END)) "apr_net"
      , "sum"((CASE WHEN ("d_moy" = 5) THEN ("ws_net_paid" * "ws_quantity") ELSE 0 END)) "may_net"
      , "sum"((CASE WHEN ("d_moy" = 6) THEN ("ws_net_paid" * "ws_quantity") ELSE 0 END)) "jun_net"
      , "sum"((CASE WHEN ("d_moy" = 7) THEN ("ws_net_paid" * "ws_quantity") ELSE 0 END)) "jul_net"
      , "sum"((CASE WHEN ("d_moy" = 8) THEN ("ws_net_paid" * "ws_quantity") ELSE 0 END)) "aug_net"
      , "sum"((CASE WHEN ("d_moy" = 9) THEN ("ws_net_paid" * "ws_quantity") ELSE 0 END)) "sep_net"
      , "sum"((CASE WHEN ("d_moy" = 10) THEN ("ws_net_paid" * "ws_quantity") ELSE 0 END)) "oct_net"
      , "sum"((CASE WHEN ("d_moy" = 11) THEN ("ws_net_paid" * "ws_quantity") ELSE 0 END)) "nov_net"
      , "sum"((CASE WHEN ("d_moy" = 12) THEN ("ws_net_paid" * "ws_quantity") ELSE 0 END)) "dec_net"
      FROM
        tpcds.web_sales
      , tpcds.warehouse
      , tpcds.date_dim
      , tpcds.time_dim
      , tpcds.ship_mode
      WHERE ("ws_warehouse_sk" = "w_warehouse_sk")
         AND ("ws_sold_date_sk" = "d_date_sk")
         AND ("ws_sold_time_sk" = "t_time_sk")
         AND ("ws_ship_mode_sk" = "sm_ship_mode_sk")
         AND ("d_year" = 2001)
         AND ("t_time" BETWEEN 30838 AND (30838 + 28800))
         AND ("sm_carrier" IN ('DHL'      , 'BARIAN'))
      GROUP BY "w_warehouse_name", "w_warehouse_sq_ft", "w_city", "w_county", "w_state", "w_country", "d_year"
   UNION ALL
      SELECT
        "w_warehouse_name"
      , "w_warehouse_sq_ft"
      , "w_city"
      , "w_county"
      , "w_state"
      , "w_country"
      , "concat"("concat"('DHL', ','), 'BARIAN') "ship_carriers"
      , "d_year" "YEAR"
      , "sum"((CASE WHEN ("d_moy" = 1) THEN ("cs_sales_price" * "cs_quantity") ELSE 0 END)) "jan_sales"
      , "sum"((CASE WHEN ("d_moy" = 2) THEN ("cs_sales_price" * "cs_quantity") ELSE 0 END)) "feb_sales"
      , "sum"((CASE WHEN ("d_moy" = 3) THEN ("cs_sales_price" * "cs_quantity") ELSE 0 END)) "mar_sales"
      , "sum"((CASE WHEN ("d_moy" = 4) THEN ("cs_sales_price" * "cs_quantity") ELSE 0 END)) "apr_sales"
      , "sum"((CASE WHEN ("d_moy" = 5) THEN ("cs_sales_price" * "cs_quantity") ELSE 0 END)) "may_sales"
      , "sum"((CASE WHEN ("d_moy" = 6) THEN ("cs_sales_price" * "cs_quantity") ELSE 0 END)) "jun_sales"
      , "sum"((CASE WHEN ("d_moy" = 7) THEN ("cs_sales_price" * "cs_quantity") ELSE 0 END)) "jul_sales"
      , "sum"((CASE WHEN ("d_moy" = 8) THEN ("cs_sales_price" * "cs_quantity") ELSE 0 END)) "aug_sales"
      , "sum"((CASE WHEN ("d_moy" = 9) THEN ("cs_sales_price" * "cs_quantity") ELSE 0 END)) "sep_sales"
      , "sum"((CASE WHEN ("d_moy" = 10) THEN ("cs_sales_price" * "cs_quantity") ELSE 0 END)) "oct_sales"
      , "sum"((CASE WHEN ("d_moy" = 11) THEN ("cs_sales_price" * "cs_quantity") ELSE 0 END)) "nov_sales"
      , "sum"((CASE WHEN ("d_moy" = 12) THEN ("cs_sales_price" * "cs_quantity") ELSE 0 END)) "dec_sales"
      , "sum"((CASE WHEN ("d_moy" = 1) THEN ("cs_net_paid_inc_tax" * "cs_quantity") ELSE 0 END)) "jan_net"
      , "sum"((CASE WHEN ("d_moy" = 2) THEN ("cs_net_paid_inc_tax" * "cs_quantity") ELSE 0 END)) "feb_net"
      , "sum"((CASE WHEN ("d_moy" = 3) THEN ("cs_net_paid_inc_tax" * "cs_quantity") ELSE 0 END)) "mar_net"
      , "sum"((CASE WHEN ("d_moy" = 4) THEN ("cs_net_paid_inc_tax" * "cs_quantity") ELSE 0 END)) "apr_net"
      , "sum"((CASE WHEN ("d_moy" = 5) THEN ("cs_net_paid_inc_tax" * "cs_quantity") ELSE 0 END)) "may_net"
      , "sum"((CASE WHEN ("d_moy" = 6) THEN ("cs_net_paid_inc_tax" * "cs_quantity") ELSE 0 END)) "jun_net"
      , "sum"((CASE WHEN ("d_moy" = 7) THEN ("cs_net_paid_inc_tax" * "cs_quantity") ELSE 0 END)) "jul_net"
      , "sum"((CASE WHEN ("d_moy" = 8) THEN ("cs_net_paid_inc_tax" * "cs_quantity") ELSE 0 END)) "aug_net"
      , "sum"((CASE WHEN ("d_moy" = 9) THEN ("cs_net_paid_inc_tax" * "cs_quantity") ELSE 0 END)) "sep_net"
      , "sum"((CASE WHEN ("d_moy" = 10) THEN ("cs_net_paid_inc_tax" * "cs_quantity") ELSE 0 END)) "oct_net"
      , "sum"((CASE WHEN ("d_moy" = 11) THEN ("cs_net_paid_inc_tax" * "cs_quantity") ELSE 0 END)) "nov_net"
      , "sum"((CASE WHEN ("d_moy" = 12) THEN ("cs_net_paid_inc_tax" * "cs_quantity") ELSE 0 END)) "dec_net"
      FROM
        tpcds.catalog_sales
      , tpcds.warehouse
      , tpcds.date_dim
      , tpcds.time_dim
      , tpcds.ship_mode
      WHERE ("cs_warehouse_sk" = "w_warehouse_sk")
         AND ("cs_sold_date_sk" = "d_date_sk")
         AND ("cs_sold_time_sk" = "t_time_sk")
         AND ("cs_ship_mode_sk" = "sm_ship_mode_sk")
         AND ("d_year" = 2001)
         AND ("t_time" BETWEEN 30838 AND (30838 + 28800))
         AND ("sm_carrier" IN ('DHL'      , 'BARIAN'))
      GROUP BY "w_warehouse_name", "w_warehouse_sq_ft", "w_city", "w_county", "w_state", "w_country", "d_year"
   )  x
GROUP BY "w_warehouse_name", "w_warehouse_sq_ft", "w_city", "w_county", "w_state", "w_country", "ship_carriers", "year"
ORDER BY "w_warehouse_name" ASC
LIMIT 100
