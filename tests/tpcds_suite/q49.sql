SELECT
  'web' "channel"
, "web"."item"
, "web"."return_ratio"
, "web"."return_rank"
, "web"."currency_rank"
FROM
  (
   SELECT
     "item"
   , "return_ratio"
   , "currency_ratio"
   , "rank"() OVER (ORDER BY "return_ratio" ASC) "return_rank"
   , "rank"() OVER (ORDER BY "currency_ratio" ASC) "currency_rank"
   FROM
     (
      SELECT
        "ws"."ws_item_sk" "item"
      , (CAST("sum"(COALESCE("wr"."wr_return_quantity", 0)) AS DECIMAL(15,4)) / CAST("sum"(COALESCE("ws"."ws_quantity", 0)) AS DECIMAL(15,4))) "return_ratio"
      , (CAST("sum"(COALESCE("wr"."wr_return_amt", 0)) AS DECIMAL(15,4)) / CAST("sum"(COALESCE("ws"."ws_net_paid", 0)) AS DECIMAL(15,4))) "currency_ratio"
      FROM
        (tpcds.web_sales ws
      LEFT JOIN tpcds.web_returns wr ON ("ws"."ws_order_number" = "wr"."wr_order_number")
         AND ("ws"."ws_item_sk" = "wr"."wr_item_sk"))
      , tpcds.date_dim
      WHERE ("wr"."wr_return_amt" > 10000)
         AND ("ws"."ws_net_profit" > 1)
         AND ("ws"."ws_net_paid" > 0)
         AND ("ws"."ws_quantity" > 0)
         AND ("ws_sold_date_sk" = "d_date_sk")
         AND ("d_year" = 2001)
         AND ("d_moy" = 12)
      GROUP BY "ws"."ws_item_sk"
   )  in_web
)  web
WHERE ("web"."return_rank" <= 10)
   OR ("web"."currency_rank" <= 10)
UNION SELECT
  'catalog' "channel"
, "catalog"."item"
, "catalog"."return_ratio"
, "catalog"."return_rank"
, "catalog"."currency_rank"
FROM
  (
   SELECT
     "item"
   , "return_ratio"
   , "currency_ratio"
   , "rank"() OVER (ORDER BY "return_ratio" ASC) "return_rank"
   , "rank"() OVER (ORDER BY "currency_ratio" ASC) "currency_rank"
   FROM
     (
      SELECT
        "cs"."cs_item_sk" "item"
      , (CAST("sum"(COALESCE("cr"."cr_return_quantity", 0)) AS DECIMAL(15,4)) / CAST("sum"(COALESCE("cs"."cs_quantity", 0)) AS DECIMAL(15,4))) "return_ratio"
      , (CAST("sum"(COALESCE("cr"."cr_return_amount", 0)) AS DECIMAL(15,4)) / CAST("sum"(COALESCE("cs"."cs_net_paid", 0)) AS DECIMAL(15,4))) "currency_ratio"
      FROM
        (tpcds.catalog_sales cs
      LEFT JOIN tpcds.catalog_returns cr ON ("cs"."cs_order_number" = "cr"."cr_order_number")
         AND ("cs"."cs_item_sk" = "cr"."cr_item_sk"))
      , tpcds.date_dim
      WHERE ("cr"."cr_return_amount" > 10000)
         AND ("cs"."cs_net_profit" > 1)
         AND ("cs"."cs_net_paid" > 0)
         AND ("cs"."cs_quantity" > 0)
         AND ("cs_sold_date_sk" = "d_date_sk")
         AND ("d_year" = 2001)
         AND ("d_moy" = 12)
      GROUP BY "cs"."cs_item_sk"
   )  in_cat
)  "CATALOG"
WHERE ("catalog"."return_rank" <= 10)
   OR ("catalog"."currency_rank" <= 10)
UNION SELECT
  'tpcds.store' "channel"
, "store"."item"
, "store"."return_ratio"
, "store"."return_rank"
, "store"."currency_rank"
FROM
  (
   SELECT
     "item"
   , "return_ratio"
   , "currency_ratio"
   , "rank"() OVER (ORDER BY "return_ratio" ASC) "return_rank"
   , "rank"() OVER (ORDER BY "currency_ratio" ASC) "currency_rank"
   FROM
     (
      SELECT
        "sts"."ss_item_sk" "item"
      , (CAST("sum"(COALESCE("sr"."sr_return_quantity", 0)) AS DECIMAL(15,4)) / CAST("sum"(COALESCE("sts"."ss_quantity", 0)) AS DECIMAL(15,4))) "return_ratio"
      , (CAST("sum"(COALESCE("sr"."sr_return_amt", 0)) AS DECIMAL(15,4)) / CAST("sum"(COALESCE("sts"."ss_net_paid", 0)) AS DECIMAL(15,4))) "currency_ratio"
      FROM
        (tpcds.store_sales sts
      LEFT JOIN tpcds.store_returns sr ON ("sts"."ss_ticket_number" = "sr"."sr_ticket_number")
         AND ("sts"."ss_item_sk" = "sr"."sr_item_sk"))
      , tpcds.date_dim
      WHERE ("sr"."sr_return_amt" > 10000)
         AND ("sts"."ss_net_profit" > 1)
         AND ("sts"."ss_net_paid" > 0)
         AND ("sts"."ss_quantity" > 0)
         AND ("ss_sold_date_sk" = "d_date_sk")
         AND ("d_year" = 2001)
         AND ("d_moy" = 12)
      GROUP BY "sts"."ss_item_sk"
   )  in_store
)  store
WHERE ("store"."return_rank" <= 10)
   OR ("store"."currency_rank" <= 10)
ORDER BY 1 ASC, 4 ASC, 5 ASC, 2 ASC
LIMIT 100
