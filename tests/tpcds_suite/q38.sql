SELECT "count"(*)
FROM
  (
   SELECT DISTINCT
     "c_last_name"
   , "c_first_name"
   , "d_date"
   FROM
     tpcds.store_sales
   , tpcds.date_dim
   , tpcds.customer
   WHERE ("store_sales"."ss_sold_date_sk" = "date_dim"."d_date_sk")
      AND ("store_sales"."ss_customer_sk" = "customer"."c_customer_sk")
      AND ("d_month_seq" BETWEEN 1200 AND (1200 + 11))
INTERSECT    SELECT DISTINCT
     "c_last_name"
   , "c_first_name"
   , "d_date"
   FROM
     tpcds.catalog_sales
   , tpcds.date_dim
   , tpcds.customer
   WHERE ("catalog_sales"."cs_sold_date_sk" = "date_dim"."d_date_sk")
      AND ("catalog_sales"."cs_bill_customer_sk" = "customer"."c_customer_sk")
      AND ("d_month_seq" BETWEEN 1200 AND (1200 + 11))
INTERSECT    SELECT DISTINCT
     "c_last_name"
   , "c_first_name"
   , "d_date"
   FROM
     tpcds.web_sales
   , tpcds.date_dim
   , tpcds.customer
   WHERE ("web_sales"."ws_sold_date_sk" = "date_dim"."d_date_sk")
      AND ("web_sales"."ws_bill_customer_sk" = "customer"."c_customer_sk")
      AND ("d_month_seq" BETWEEN 1200 AND (1200 + 11))
)  hot_cust
LIMIT 100
