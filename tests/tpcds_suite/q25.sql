SELECT
  "i_item_id"
, "i_item_desc"
, "s_store_id"
, "s_store_name"
, "sum"("ss_net_profit") "store_sales_profit"
, "sum"("sr_net_loss") "store_returns_loss"
, "sum"("cs_net_profit") "catalog_sales_profit"
FROM
  tpcds.store_sales
, tpcds.store_returns
, tpcds.catalog_sales
, tpcds.date_dim d1
, tpcds.date_dim d2
, tpcds.date_dim d3
, tpcds.store
, tpcds.item
WHERE ("d1"."d_moy" = 4)
   AND ("d1"."d_year" = 2001)
   AND ("d1"."d_date_sk" = "ss_sold_date_sk")
   AND ("i_item_sk" = "ss_item_sk")
   AND ("s_store_sk" = "ss_store_sk")
   AND ("ss_customer_sk" = "sr_customer_sk")
   AND ("ss_item_sk" = "sr_item_sk")
   AND ("ss_ticket_number" = "sr_ticket_number")
   AND ("sr_returned_date_sk" = "d2"."d_date_sk")
   AND ("d2"."d_moy" BETWEEN 4 AND 10)
   AND ("d2"."d_year" = 2001)
   AND ("sr_customer_sk" = "cs_bill_customer_sk")
   AND ("sr_item_sk" = "cs_item_sk")
   AND ("cs_sold_date_sk" = "d3"."d_date_sk")
   AND ("d3"."d_moy" BETWEEN 4 AND 10)
   AND ("d3"."d_year" = 2001)
GROUP BY "i_item_id", "i_item_desc", "s_store_id", "s_store_name"
ORDER BY "i_item_id" ASC, "i_item_desc" ASC, "s_store_id" ASC, "s_store_name" ASC
LIMIT 100
