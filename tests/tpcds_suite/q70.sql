SELECT
  "sum"("ss_net_profit") "total_sum"
, "s_state"
, "s_county"
, (GROUPING ("s_state") + GROUPING ("s_county")) "lochierarchy"
, "rank"() OVER (PARTITION BY (GROUPING ("s_state") + GROUPING ("s_county")), (CASE WHEN (GROUPING ("s_county") = 0) THEN "s_state" END) ORDER BY "sum"("ss_net_profit") DESC) "rank_within_parent"
FROM
  tpcds.store_sales
, tpcds.date_dim d1
, tpcds.store
WHERE ("d1"."d_month_seq" BETWEEN 1200 AND (1200 + 11))
   AND ("d1"."d_date_sk" = "ss_sold_date_sk")
   AND ("s_store_sk" = "ss_store_sk")
   AND ("s_state" IN (
   SELECT "s_state"
   FROM
     (
      SELECT
        "s_state" "s_state"
      , "rank"() OVER (PARTITION BY "s_state" ORDER BY "sum"("ss_net_profit") DESC) "ranking"
      FROM
        tpcds.store_sales
      , tpcds.store
      , tpcds.date_dim
      WHERE ("d_month_seq" BETWEEN 1200 AND (1200 + 11))
         AND ("d_date_sk" = "ss_sold_date_sk")
         AND ("s_store_sk" = "ss_store_sk")
      GROUP BY "s_state"
   )  tmp1
   WHERE ("ranking" <= 5)
))
GROUP BY ROLLUP (s_state, s_county)
ORDER BY "lochierarchy" DESC, (CASE WHEN ("lochierarchy" = 0) THEN "s_state" END) ASC, "rank_within_parent" ASC
LIMIT 100
