SELECT
  "i_item_desc"
, "w_warehouse_name"
, "d1"."d_week_seq"
, "sum"((CASE WHEN ("p_promo_sk" IS NULL) THEN 1 ELSE 0 END)) "no_promo"
, "sum"((CASE WHEN ("p_promo_sk" IS NOT NULL) THEN 1 ELSE 0 END)) "promo"
, "count"(*) "total_cnt"
FROM
  ((((((((((tpcds.catalog_sales
INNER JOIN tpcds.inventory ON ("cs_item_sk" = "inv_item_sk"))
INNER JOIN tpcds.warehouse ON ("w_warehouse_sk" = "inv_warehouse_sk"))
INNER JOIN tpcds.item ON ("i_item_sk" = "cs_item_sk"))
INNER JOIN tpcds.customer_demographics ON ("cs_bill_cdemo_sk" = "cd_demo_sk"))
INNER JOIN tpcds.household_demographics ON ("cs_bill_hdemo_sk" = "hd_demo_sk"))
INNER JOIN tpcds.date_dim d1 ON ("cs_sold_date_sk" = "d1"."d_date_sk"))
INNER JOIN tpcds.date_dim d2 ON ("inv_date_sk" = "d2"."d_date_sk"))
INNER JOIN tpcds.date_dim d3 ON ("cs_ship_date_sk" = "d3"."d_date_sk"))
LEFT JOIN tpcds.promotion ON ("cs_promo_sk" = "p_promo_sk"))
LEFT JOIN tpcds.catalog_returns ON ("cr_item_sk" = "cs_item_sk")
   AND ("cr_order_number" = "cs_order_number"))
WHERE ("d1"."d_week_seq" = "d2"."d_week_seq")
   AND ("inv_quantity_on_hand" < "cs_quantity")
   AND ("d3"."d_date" > ("d1"."d_date" + INTERVAL  '5' DAY))
   AND ("hd_buy_potential" = '>10000')
   AND ("d1"."d_year" = 1999)
   AND ("cd_marital_status" = 'D')
GROUP BY "i_item_desc", "w_warehouse_name", "d1"."d_week_seq"
ORDER BY "total_cnt" DESC, "i_item_desc" ASC, "w_warehouse_name" ASC, "d1"."d_week_seq" ASC
LIMIT 100
