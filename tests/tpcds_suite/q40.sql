SELECT
  "w_state"
, "i_item_id"
, "sum"((CASE WHEN (CAST("d_date" AS DATE) < CAST('2000-03-11' AS DATE)) THEN ("cs_sales_price" - COALESCE("cr_refunded_cash", 0)) ELSE 0 END)) "sales_before"
, "sum"((CASE WHEN (CAST("d_date" AS DATE) >= CAST('2000-03-11' AS DATE)) THEN ("cs_sales_price" - COALESCE("cr_refunded_cash", 0)) ELSE 0 END)) "sales_after"
FROM
  (tpcds.catalog_sales
LEFT JOIN tpcds.catalog_returns ON ("cs_order_number" = "cr_order_number")
   AND ("cs_item_sk" = "cr_item_sk"))
, tpcds.warehouse
, tpcds.item
, tpcds.date_dim
WHERE ("i_current_price" BETWEEN DECIMAL '0.99' AND DECIMAL '1.49')
   AND ("i_item_sk" = "cs_item_sk")
   AND ("cs_warehouse_sk" = "w_warehouse_sk")
   AND ("cs_sold_date_sk" = "d_date_sk")
   AND (CAST("d_date" AS DATE) BETWEEN (CAST('2000-03-11' AS DATE) - INTERVAL  '30' DAY) AND (CAST('2000-03-11' AS DATE) + INTERVAL  '30' DAY))
GROUP BY "w_state", "i_item_id"
ORDER BY "w_state" ASC, "i_item_id" ASC
LIMIT 100
