SELECT
  "count"(DISTINCT "cs_order_number") "order count"
, "sum"("cs_ext_ship_cost") "total shipping cost"
, "sum"("cs_net_profit") "total net profit"
FROM
  tpcds.catalog_sales cs1
, tpcds.date_dim
, tpcds.customer_address
, tpcds.call_center
WHERE ("d_date" BETWEEN CAST('2002-2-01' AS DATE) AND (CAST('2002-2-01' AS DATE) + INTERVAL  '60' DAY))
   AND ("cs1"."cs_ship_date_sk" = "d_date_sk")
   AND ("cs1"."cs_ship_addr_sk" = "ca_address_sk")
   AND ("ca_state" = 'GA')
   AND ("cs1"."cs_call_center_sk" = "cc_call_center_sk")
   AND ("cc_county" IN ('Williamson County', 'Williamson County', 'Williamson County', 'Williamson County', 'Williamson County'))
   AND (EXISTS (
   SELECT *
   FROM
     tpcds.catalog_sales cs2
   WHERE ("cs1"."cs_order_number" = "cs2"."cs_order_number")
      AND ("cs1"."cs_warehouse_sk" <> "cs2"."cs_warehouse_sk")
))
   AND (NOT (EXISTS (
   SELECT *
   FROM
     tpcds.catalog_returns cr1
   WHERE ("cs1"."cs_order_number" = "cr1"."cr_order_number")
)))
ORDER BY "count"(DISTINCT "cs_order_number") ASC
LIMIT 100
