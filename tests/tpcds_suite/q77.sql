WITH
  ss AS (
   SELECT
     "s_store_sk"
   , "sum"("ss_ext_sales_price") "sales"
   , "sum"("ss_net_profit") "profit"
   FROM
     tpcds.store_sales
   , tpcds.date_dim
   , tpcds.store
   WHERE ("ss_sold_date_sk" = "d_date_sk")
      AND ("d_date" BETWEEN CAST('2000-08-23' AS DATE) AND (CAST('2000-08-23' AS DATE) + INTERVAL  '30' DAY))
      AND ("ss_store_sk" = "s_store_sk")
   GROUP BY "s_store_sk"
) 
, sr AS (
   SELECT
     "s_store_sk"
   , "sum"("sr_return_amt") "returns"
   , "sum"("sr_net_loss") "profit_loss"
   FROM
     tpcds.store_returns
   , tpcds.date_dim
   , tpcds.store
   WHERE ("sr_returned_date_sk" = "d_date_sk")
      AND ("d_date" BETWEEN CAST('2000-08-23' AS DATE) AND (CAST('2000-08-23' AS DATE) + INTERVAL  '30' DAY))
      AND ("sr_store_sk" = "s_store_sk")
   GROUP BY "s_store_sk"
) 
, cs AS (
   SELECT
     "cs_call_center_sk"
   , "sum"("cs_ext_sales_price") "sales"
   , "sum"("cs_net_profit") "profit"
   FROM
     tpcds.catalog_sales
   , tpcds.date_dim
   WHERE ("cs_sold_date_sk" = "d_date_sk")
      AND ("d_date" BETWEEN CAST('2000-08-23' AS DATE) AND (CAST('2000-08-23' AS DATE) + INTERVAL  '30' DAY))
   GROUP BY "cs_call_center_sk"
) 
, cr AS (
   SELECT
     "cr_call_center_sk"
   , "sum"("cr_return_amount") "returns"
   , "sum"("cr_net_loss") "profit_loss"
   FROM
     tpcds.catalog_returns
   , tpcds.date_dim
   WHERE ("cr_returned_date_sk" = "d_date_sk")
      AND ("d_date" BETWEEN CAST('2000-08-23' AS DATE) AND (CAST('2000-08-23' AS DATE) + INTERVAL  '30' DAY))
   GROUP BY "cr_call_center_sk"
) 
, ws AS (
   SELECT
     "wp_web_page_sk"
   , "sum"("ws_ext_sales_price") "sales"
   , "sum"("ws_net_profit") "profit"
   FROM
     tpcds.web_sales
   , tpcds.date_dim
   , tpcds.web_page
   WHERE ("ws_sold_date_sk" = "d_date_sk")
      AND ("d_date" BETWEEN CAST('2000-08-23' AS DATE) AND (CAST('2000-08-23' AS DATE) + INTERVAL  '30' DAY))
      AND ("ws_web_page_sk" = "wp_web_page_sk")
   GROUP BY "wp_web_page_sk"
) 
, wr AS (
   SELECT
     "wp_web_page_sk"
   , "sum"("wr_return_amt") "returns"
   , "sum"("wr_net_loss") "profit_loss"
   FROM
     tpcds.web_returns
   , tpcds.date_dim
   , tpcds.web_page
   WHERE ("wr_returned_date_sk" = "d_date_sk")
      AND ("d_date" BETWEEN CAST('2000-08-23' AS DATE) AND (CAST('2000-08-23' AS DATE) + INTERVAL  '30' DAY))
      AND ("wr_web_page_sk" = "wp_web_page_sk")
   GROUP BY "wp_web_page_sk"
) 
SELECT
  "channel"
, "id"
, "sum"("sales") "sales"
, "sum"("returns") "returns"
, "sum"("profit") "profit"
FROM
  (
   SELECT
     'tpcds.store channel' "channel"
   , "ss"."s_store_sk" "id"
   , "sales"
   , COALESCE("returns", 0) "returns"
   , ("profit" - COALESCE("profit_loss", 0)) "profit"
   FROM
     (ss
   LEFT JOIN sr ON ("ss"."s_store_sk" = "sr"."s_store_sk"))
UNION ALL    SELECT
     'catalog channel' "channel"
   , "cs_call_center_sk" "id"
   , "sales"
   , "returns"
   , ("profit" - "profit_loss") "profit"
   FROM
     cs
   , cr
UNION ALL    SELECT
     'web channel' "channel"
   , "ws"."wp_web_page_sk" "id"
   , "sales"
   , COALESCE("returns", 0) "returns"
   , ("profit" - COALESCE("profit_loss", 0)) "profit"
   FROM
     (ws
   LEFT JOIN wr ON ("ws"."wp_web_page_sk" = "wr"."wp_web_page_sk"))
)  x
GROUP BY ROLLUP (channel, id)
ORDER BY "channel" ASC, "id" ASC, "sales" ASC
LIMIT 100
