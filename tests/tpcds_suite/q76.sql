SELECT
  "channel"
, "col_name"
, "d_year"
, "d_qoy"
, "i_category"
, "count"(*) "sales_cnt"
, "sum"("ext_sales_price") "sales_amt"
FROM
  (
   SELECT
     'tpcds.store' "channel"
   , 'ss_store_sk' "col_name"
   , "d_year"
   , "d_qoy"
   , "i_category"
   , "ss_ext_sales_price" "ext_sales_price"
   FROM
     tpcds.store_sales
   , tpcds.item
   , tpcds.date_dim
   WHERE ("ss_store_sk" IS NULL)
      AND ("ss_sold_date_sk" = "d_date_sk")
      AND ("ss_item_sk" = "i_item_sk")
UNION ALL    SELECT
     'web' "channel"
   , 'ws_ship_customer_sk' "col_name"
   , "d_year"
   , "d_qoy"
   , "i_category"
   , "ws_ext_sales_price" "ext_sales_price"
   FROM
     tpcds.web_sales
   , tpcds.item
   , tpcds.date_dim
   WHERE ("ws_ship_customer_sk" IS NULL)
      AND ("ws_sold_date_sk" = "d_date_sk")
      AND ("ws_item_sk" = "i_item_sk")
UNION ALL    SELECT
     'catalog' "channel"
   , 'cs_ship_addr_sk' "col_name"
   , "d_year"
   , "d_qoy"
   , "i_category"
   , "cs_ext_sales_price" "ext_sales_price"
   FROM
     tpcds.catalog_sales
   , tpcds.item
   , tpcds.date_dim
   WHERE ("cs_ship_addr_sk" IS NULL)
      AND ("cs_sold_date_sk" = "d_date_sk")
      AND ("cs_item_sk" = "i_item_sk")
)  foo
GROUP BY "channel", "col_name", "d_year", "d_qoy", "i_category"
ORDER BY "channel" ASC, "col_name" ASC, "d_year" ASC, "d_qoy" ASC, "i_category" ASC
LIMIT 100
