SELECT
  ("sum"("ss_net_profit") / "sum"("ss_ext_sales_price")) "gross_margin"
, "i_category"
, "i_class"
, (GROUPING ("i_category") + GROUPING ("i_class")) "lochierarchy"
, "rank"() OVER (PARTITION BY (GROUPING ("i_category") + GROUPING ("i_class")), (CASE WHEN (GROUPING ("i_class") = 0) THEN "i_category" END) ORDER BY ("sum"("ss_net_profit") / "sum"("ss_ext_sales_price")) ASC) "rank_within_parent"
FROM
  tpcds.store_sales
, tpcds.date_dim d1
, tpcds.item
, tpcds.store
WHERE ("d1"."d_year" = 2001)
   AND ("d1"."d_date_sk" = "ss_sold_date_sk")
   AND ("i_item_sk" = "ss_item_sk")
   AND ("s_store_sk" = "ss_store_sk")
   AND ("s_state" IN (
     'TN'
   , 'TN'
   , 'TN'
   , 'TN'
   , 'TN'
   , 'TN'
   , 'TN'
   , 'TN'))
GROUP BY ROLLUP (i_category, i_class)
ORDER BY "lochierarchy" DESC, (CASE WHEN ("lochierarchy" = 0) THEN "i_category" END) ASC, "rank_within_parent" ASC, "i_category", "i_class"
LIMIT 100
