SELECT
  "count"(DISTINCT "ws_order_number") "order count"
, "sum"("ws_ext_ship_cost") "total shipping cost"
, "sum"("ws_net_profit") "total net profit"
FROM
  tpcds.web_sales ws1
, tpcds.date_dim
, tpcds.customer_address
, tpcds.web_site
WHERE ("d_date" BETWEEN CAST('1999-2-01' AS DATE) AND (CAST('1999-2-01' AS DATE) + INTERVAL  '60' DAY))
   AND ("ws1"."ws_ship_date_sk" = "d_date_sk")
   AND ("ws1"."ws_ship_addr_sk" = "ca_address_sk")
   AND ("ca_state" = 'IL')
   AND ("ws1"."ws_web_site_sk" = "web_site_sk")
   AND ("web_company_name" = 'pri')
   AND (EXISTS (
   SELECT *
   FROM
     tpcds.web_sales ws2
   WHERE ("ws1"."ws_order_number" = "ws2"."ws_order_number")
      AND ("ws1"."ws_warehouse_sk" <> "ws2"."ws_warehouse_sk")
))
   AND (NOT (EXISTS (
   SELECT *
   FROM
     tpcds.web_returns wr1
   WHERE ("ws1"."ws_order_number" = "wr1"."wr_order_number")
)))
ORDER BY "count"(DISTINCT "ws_order_number") ASC
LIMIT 100
