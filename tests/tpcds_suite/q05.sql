WITH
  ssr AS (
   SELECT
     "s_store_id"
   , "sum"("sales_price") "sales"
   , "sum"("profit") "profit"
   , "sum"("return_amt") "returns"
   , "sum"("net_loss") "profit_loss"
   FROM
     (
      SELECT
        "ss_store_sk" "store_sk"
      , "ss_sold_date_sk" "date_sk"
      , "ss_ext_sales_price" "sales_price"
      , "ss_net_profit" "profit"
      , CAST(0 AS DECIMAL(7,2)) "return_amt"
      , CAST(0 AS DECIMAL(7,2)) "net_loss"
      FROM
        tpcds.store_sales
UNION ALL       SELECT
        "sr_store_sk" "store_sk"
      , "sr_returned_date_sk" "date_sk"
      , CAST(0 AS DECIMAL(7,2)) "sales_price"
      , CAST(0 AS DECIMAL(7,2)) "profit"
      , "sr_return_amt" "return_amt"
      , "sr_net_loss" "net_loss"
      FROM
        tpcds.store_returns
   )  salesreturns
   , tpcds.date_dim
   , tpcds.store
   WHERE ("date_sk" = "d_date_sk")
      AND ("d_date" BETWEEN CAST('2000-08-23' AS DATE) AND (CAST('2000-08-23' AS DATE) + INTERVAL  '14' DAY))
      AND ("store_sk" = "s_store_sk")
   GROUP BY "s_store_id"
) 
, csr AS (
   SELECT
     "cp_catalog_page_id"
   , "sum"("sales_price") "sales"
   , "sum"("profit") "profit"
   , "sum"("return_amt") "returns"
   , "sum"("net_loss") "profit_loss"
   FROM
     (
      SELECT
        "cs_catalog_page_sk" "page_sk"
      , "cs_sold_date_sk" "date_sk"
      , "cs_ext_sales_price" "sales_price"
      , "cs_net_profit" "profit"
      , CAST(0 AS DECIMAL(7,2)) "return_amt"
      , CAST(0 AS DECIMAL(7,2)) "net_loss"
      FROM
        tpcds.catalog_sales
UNION ALL       SELECT
        "cr_catalog_page_sk" "page_sk"
      , "cr_returned_date_sk" "date_sk"
      , CAST(0 AS DECIMAL(7,2)) "sales_price"
      , CAST(0 AS DECIMAL(7,2)) "profit"
      , "cr_return_amount" "return_amt"
      , "cr_net_loss" "net_loss"
      FROM
        tpcds.catalog_returns
   )  salesreturns
   , tpcds.date_dim
   , tpcds.catalog_page
   WHERE ("date_sk" = "d_date_sk")
      AND ("d_date" BETWEEN CAST('2000-08-23' AS DATE) AND (CAST('2000-08-23' AS DATE) + INTERVAL  '14' DAY))
      AND ("page_sk" = "cp_catalog_page_sk")
   GROUP BY "cp_catalog_page_id"
) 
, wsr AS (
   SELECT
     "web_site_id"
   , "sum"("sales_price") "sales"
   , "sum"("profit") "profit"
   , "sum"("return_amt") "returns"
   , "sum"("net_loss") "profit_loss"
   FROM
     (
      SELECT
        "ws_web_site_sk" "wsr_web_site_sk"
      , "ws_sold_date_sk" "date_sk"
      , "ws_ext_sales_price" "sales_price"
      , "ws_net_profit" "profit"
      , CAST(0 AS DECIMAL(7,2)) "return_amt"
      , CAST(0 AS DECIMAL(7,2)) "net_loss"
      FROM
        tpcds.web_sales
UNION ALL       SELECT
        "ws_web_site_sk" "wsr_web_site_sk"
      , "wr_returned_date_sk" "date_sk"
      , CAST(0 AS DECIMAL(7,2)) "sales_price"
      , CAST(0 AS DECIMAL(7,2)) "profit"
      , "wr_return_amt" "return_amt"
      , "wr_net_loss" "net_loss"
      FROM
        (tpcds.web_returns
      LEFT JOIN tpcds.web_sales ON ("wr_item_sk" = "ws_item_sk")
         AND ("wr_order_number" = "ws_order_number"))
   )  salesreturns
   , tpcds.date_dim
   , tpcds.web_site
   WHERE ("date_sk" = "d_date_sk")
      AND ("d_date" BETWEEN CAST('2000-08-23' AS DATE) AND (CAST('2000-08-23' AS DATE) + INTERVAL  '14' DAY))
      AND ("wsr_web_site_sk" = "web_site_sk")
   GROUP BY "web_site_id"
) 
SELECT
  "channel"
, "id"
, "sum"("sales") "sales"
, "sum"("returns") "returns"
, "sum"("profit") "profit"
FROM
  (
   SELECT
     'tpcds.store channel' "channel"
   , "concat"('store', "s_store_id") "id"
   , "sales"
   , "returns"
   , ("profit" - "profit_loss") "profit"
   FROM
     ssr
UNION ALL    SELECT
     'catalog channel' "channel"
   , "concat"('catalog_page', "cp_catalog_page_id") "id"
   , "sales"
   , "returns"
   , ("profit" - "profit_loss") "profit"
   FROM
     csr
UNION ALL    SELECT
     'web channel' "channel"
   , "concat"('web_site', "web_site_id") "id"
   , "sales"
   , "returns"
   , ("profit" - "profit_loss") "profit"
   FROM
     wsr
)  x
GROUP BY ROLLUP (channel, id)
ORDER BY "channel" ASC, "id" ASC
LIMIT 100
