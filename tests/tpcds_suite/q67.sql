SELECT *
FROM
  (
   SELECT
     "i_category"
   , "i_class"
   , "i_brand"
   , "i_product_name"
   , "d_year"
   , "d_qoy"
   , "d_moy"
   , "s_store_id"
   , "sumsales"
   , "rank"() OVER (PARTITION BY "i_category" ORDER BY "sumsales" DESC) "rk"
   FROM
     (
      SELECT
        "i_category"
      , "i_class"
      , "i_brand"
      , "i_product_name"
      , "d_year"
      , "d_qoy"
      , "d_moy"
      , "s_store_id"
      , "sum"(COALESCE(("ss_sales_price" * "ss_quantity"), 0)) "sumsales"
      FROM
        tpcds.store_sales
      , tpcds.date_dim
      , tpcds.store
      , tpcds.item
      WHERE ("ss_sold_date_sk" = "d_date_sk")
         AND ("ss_item_sk" = "i_item_sk")
         AND ("ss_store_sk" = "s_store_sk")
         AND ("d_month_seq" BETWEEN 1200 AND (1200 + 11))
      GROUP BY ROLLUP (i_category, i_class, i_brand, i_product_name, d_year, d_qoy, d_moy, s_store_id)
   )  dw1
)  dw2
WHERE ("rk" <= 100)
ORDER BY "i_category" ASC, "i_class" ASC, "i_brand" ASC, "i_product_name" ASC, "d_year" ASC, "d_qoy" ASC, "d_moy" ASC, "s_store_id" ASC, "sumsales" ASC, "rk" ASC
LIMIT 100
