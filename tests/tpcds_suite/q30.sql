WITH
  customer_total_return AS (
   SELECT
     "wr_returning_customer_sk" "ctr_customer_sk"
   , "ca_state" "ctr_state"
   , "sum"("wr_return_amt") "ctr_total_return"
   FROM
     tpcds.web_returns
   , tpcds.date_dim
   , tpcds.customer_address
   WHERE ("wr_returned_date_sk" = "d_date_sk")
      AND ("d_year" = 2002)
      AND ("wr_returning_addr_sk" = "ca_address_sk")
   GROUP BY "wr_returning_customer_sk", "ca_state"
) 
SELECT
  "c_customer_id"
, "c_salutation"
, "c_first_name"
, "c_last_name"
, "c_preferred_cust_flag"
, "c_birth_day"
, "c_birth_month"
, "c_birth_year"
, "c_birth_country"
, "c_login"
, "c_email_address"
, "c_last_review_date_sk"
, "ctr_total_return"
FROM
  customer_total_return ctr1
, tpcds.customer_address
, tpcds.customer
WHERE ("ctr1"."ctr_total_return" > (
      SELECT ("avg"("ctr_total_return") * DECIMAL '1.2')
      FROM
        customer_total_return ctr2
      WHERE ("ctr1"."ctr_state" = "ctr2"."ctr_state")
   ))
   AND ("ca_address_sk" = "c_current_addr_sk")
   AND ("ca_state" = 'GA')
   AND ("ctr1"."ctr_customer_sk" = "c_customer_sk")
ORDER BY "c_customer_id" ASC, "c_salutation" ASC, "c_first_name" ASC, "c_last_name" ASC, "c_preferred_cust_flag" ASC, "c_birth_day" ASC, "c_birth_month" ASC, "c_birth_year" ASC, "c_birth_country" ASC, "c_login" ASC, "c_email_address" ASC, "c_last_review_date_sk" ASC, "ctr_total_return" ASC
LIMIT 100
