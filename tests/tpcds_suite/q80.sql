WITH
  ssr AS (
   SELECT
     "s_store_id" "store_id"
   , "sum"("ss_ext_sales_price") "sales"
   , "sum"(COALESCE("sr_return_amt", 0)) "returns"
   , "sum"(("ss_net_profit" - COALESCE("sr_net_loss", 0))) "profit"
   FROM
     (tpcds.store_sales
   LEFT JOIN tpcds.store_returns ON ("ss_item_sk" = "sr_item_sk")
      AND ("ss_ticket_number" = "sr_ticket_number"))
   , tpcds.date_dim
   , tpcds.store
   , tpcds.item
   , tpcds.promotion
   WHERE ("ss_sold_date_sk" = "d_date_sk")
      AND (CAST("d_date" AS DATE) BETWEEN CAST('2000-08-23' AS DATE) AND (CAST('2000-08-23' AS DATE) + INTERVAL  '30' DAY))
      AND ("ss_store_sk" = "s_store_sk")
      AND ("ss_item_sk" = "i_item_sk")
      AND ("i_current_price" > 50)
      AND ("ss_promo_sk" = "p_promo_sk")
      AND ("p_channel_tv" = 'N')
   GROUP BY "s_store_id"
) 
, csr AS (
   SELECT
     "cp_catalog_page_id" "catalog_page_id"
   , "sum"("cs_ext_sales_price") "sales"
   , "sum"(COALESCE("cr_return_amount", 0)) "returns"
   , "sum"(("cs_net_profit" - COALESCE("cr_net_loss", 0))) "profit"
   FROM
     (tpcds.catalog_sales
   LEFT JOIN tpcds.catalog_returns ON ("cs_item_sk" = "cr_item_sk")
      AND ("cs_order_number" = "cr_order_number"))
   , tpcds.date_dim
   , tpcds.catalog_page
   , tpcds.item
   , tpcds.promotion
   WHERE ("cs_sold_date_sk" = "d_date_sk")
      AND (CAST("d_date" AS DATE) BETWEEN CAST('2000-08-23' AS DATE) AND (CAST('2000-08-23' AS DATE) + INTERVAL  '30' DAY))
      AND ("cs_catalog_page_sk" = "cp_catalog_page_sk")
      AND ("cs_item_sk" = "i_item_sk")
      AND ("i_current_price" > 50)
      AND ("cs_promo_sk" = "p_promo_sk")
      AND ("p_channel_tv" = 'N')
   GROUP BY "cp_catalog_page_id"
) 
, wsr AS (
   SELECT
     "web_site_id"
   , "sum"("ws_ext_sales_price") "sales"
   , "sum"(COALESCE("wr_return_amt", 0)) "returns"
   , "sum"(("ws_net_profit" - COALESCE("wr_net_loss", 0))) "profit"
   FROM
     (tpcds.web_sales
   LEFT JOIN tpcds.web_returns ON ("ws_item_sk" = "wr_item_sk")
      AND ("ws_order_number" = "wr_order_number"))
   , tpcds.date_dim
   , tpcds.web_site
   , tpcds.item
   , tpcds.promotion
   WHERE ("ws_sold_date_sk" = "d_date_sk")
      AND (CAST("d_date" AS DATE) BETWEEN CAST('2000-08-23' AS DATE) AND (CAST('2000-08-23' AS DATE) + INTERVAL  '30' DAY))
      AND ("ws_web_site_sk" = "web_site_sk")
      AND ("ws_item_sk" = "i_item_sk")
      AND ("i_current_price" > 50)
      AND ("ws_promo_sk" = "p_promo_sk")
      AND ("p_channel_tv" = 'N')
   GROUP BY "web_site_id"
) 
SELECT
  "channel"
, "id"
, "sum"("sales") "sales"
, "sum"("returns") "returns"
, "sum"("profit") "profit"
FROM
  (
   SELECT
     'tpcds.store channel' "channel"
   , "concat"('store', "store_id") "id"
   , "sales"
   , "returns"
   , "profit"
   FROM
     ssr
UNION ALL    SELECT
     'catalog channel' "channel"
   , "concat"('catalog_page', "catalog_page_id") "id"
   , "sales"
   , "returns"
   , "profit"
   FROM
     csr
UNION ALL    SELECT
     'web channel' "channel"
   , "concat"('web_site', "web_site_id") "id"
   , "sales"
   , "returns"
   , "profit"
   FROM
     wsr
)  x
GROUP BY ROLLUP (channel, id)
ORDER BY "channel" ASC, "id" ASC
LIMIT 100
