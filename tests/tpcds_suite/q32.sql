SELECT "sum"("cs_ext_discount_amt") "excess discount amount"
FROM
  tpcds.catalog_sales
, tpcds.item
, tpcds.date_dim
WHERE ("i_manufact_id" = 977)
   AND ("i_item_sk" = "cs_item_sk")
   AND ("d_date" BETWEEN CAST('2000-01-27' AS DATE) AND (CAST('2000-01-27' AS DATE) + INTERVAL  '90' DAY))
   AND ("d_date_sk" = "cs_sold_date_sk")
   AND ("cs_ext_discount_amt" > (
      SELECT (DECIMAL '1.3' * "avg"("cs_ext_discount_amt"))
      FROM
        tpcds.catalog_sales
      , tpcds.date_dim
      WHERE ("cs_item_sk" = "i_item_sk")
         AND ("d_date" BETWEEN CAST('2000-01-27' AS DATE) AND (CAST('2000-01-27' AS DATE) + INTERVAL  '90' DAY))
         AND ("d_date_sk" = "cs_sold_date_sk")
   ))
LIMIT 100
