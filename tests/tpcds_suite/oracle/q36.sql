-- sqlite-oracle variant of q36: GROUP BY ROLLUP(i_category, i_class)
-- expanded into a UNION ALL of its three grouping levels, with
-- GROUPING(...) replaced by per-level constants (sqlite has neither
-- ROLLUP nor GROUPING); semantics otherwise identical to q36.sql
WITH lvl AS (
   SELECT sum(ss_net_profit) / sum(ss_ext_sales_price) gross_margin,
          i_category, i_class, 0 lochierarchy, 0 g_class
   FROM store_sales, date_dim d1, item, store
   WHERE d1.d_year = 2001 AND d1.d_date_sk = ss_sold_date_sk
     AND i_item_sk = ss_item_sk AND s_store_sk = ss_store_sk
     AND s_state IN ('TN', 'TN', 'TN', 'TN', 'TN', 'TN', 'TN', 'TN')
   GROUP BY i_category, i_class
   UNION ALL
   SELECT sum(ss_net_profit) / sum(ss_ext_sales_price),
          i_category, NULL, 1, 1
   FROM store_sales, date_dim d1, item, store
   WHERE d1.d_year = 2001 AND d1.d_date_sk = ss_sold_date_sk
     AND i_item_sk = ss_item_sk AND s_store_sk = ss_store_sk
     AND s_state IN ('TN', 'TN', 'TN', 'TN', 'TN', 'TN', 'TN', 'TN')
   GROUP BY i_category
   UNION ALL
   SELECT sum(ss_net_profit) / sum(ss_ext_sales_price),
          NULL, NULL, 2, 1
   FROM store_sales, date_dim d1, item, store
   WHERE d1.d_year = 2001 AND d1.d_date_sk = ss_sold_date_sk
     AND i_item_sk = ss_item_sk AND s_store_sk = ss_store_sk
     AND s_state IN ('TN', 'TN', 'TN', 'TN', 'TN', 'TN', 'TN', 'TN')
)
SELECT gross_margin, i_category, i_class, lochierarchy,
       rank() OVER (PARTITION BY lochierarchy,
                    CASE WHEN g_class = 0 THEN i_category END
                    ORDER BY gross_margin ASC) rank_within_parent
FROM lvl
ORDER BY lochierarchy DESC,
         CASE WHEN lochierarchy = 0 THEN i_category END ASC,
         rank_within_parent ASC, i_category, i_class
LIMIT 100
