-- sqlite-oracle variant of q70: ROLLUP(s_state, s_county) expanded to a
-- UNION ALL of grouping levels with GROUPING() as per-level constants
WITH top_states AS (
   SELECT s_state
   FROM (
      SELECT s_state s_state,
             rank() OVER (PARTITION BY s_state
                          ORDER BY sum(ss_net_profit) DESC) ranking
      FROM store_sales, store, date_dim
      WHERE d_month_seq BETWEEN 1200 AND (1200 + 11)
        AND d_date_sk = ss_sold_date_sk
        AND s_store_sk = ss_store_sk
      GROUP BY s_state
   ) tmp1
   WHERE ranking <= 5
), lvl AS (
   SELECT sum(ss_net_profit) total_sum, s_state, s_county,
          0 lochierarchy, 0 g_county
   FROM store_sales, date_dim d1, store
   WHERE d1.d_month_seq BETWEEN 1200 AND (1200 + 11)
     AND d1.d_date_sk = ss_sold_date_sk
     AND s_store_sk = ss_store_sk
     AND s_state IN (SELECT s_state FROM top_states)
   GROUP BY s_state, s_county
   UNION ALL
   SELECT sum(ss_net_profit), s_state, NULL, 1, 1
   FROM store_sales, date_dim d1, store
   WHERE d1.d_month_seq BETWEEN 1200 AND (1200 + 11)
     AND d1.d_date_sk = ss_sold_date_sk
     AND s_store_sk = ss_store_sk
     AND s_state IN (SELECT s_state FROM top_states)
   GROUP BY s_state
   UNION ALL
   SELECT sum(ss_net_profit), NULL, NULL, 2, 1
   FROM store_sales, date_dim d1, store
   WHERE d1.d_month_seq BETWEEN 1200 AND (1200 + 11)
     AND d1.d_date_sk = ss_sold_date_sk
     AND s_store_sk = ss_store_sk
     AND s_state IN (SELECT s_state FROM top_states)
)
SELECT total_sum, s_state, s_county, lochierarchy,
       rank() OVER (PARTITION BY lochierarchy,
                    CASE WHEN g_county = 0 THEN s_state END
                    ORDER BY total_sum DESC) rank_within_parent
FROM lvl
ORDER BY lochierarchy DESC,
         CASE WHEN lochierarchy = 0 THEN s_state END ASC,
         rank_within_parent ASC
LIMIT 100
