-- sqlite-oracle variant of q86: ROLLUP(i_category, i_class) expanded to
-- a UNION ALL of grouping levels with GROUPING() as per-level constants
WITH lvl AS (
   SELECT sum(ws_net_paid) total_sum, i_category, i_class,
          0 lochierarchy, 0 g_class
   FROM web_sales, date_dim d1, item
   WHERE d1.d_month_seq BETWEEN 1200 AND (1200 + 11)
     AND d1.d_date_sk = ws_sold_date_sk
     AND i_item_sk = ws_item_sk
   GROUP BY i_category, i_class
   UNION ALL
   SELECT sum(ws_net_paid), i_category, NULL, 1, 1
   FROM web_sales, date_dim d1, item
   WHERE d1.d_month_seq BETWEEN 1200 AND (1200 + 11)
     AND d1.d_date_sk = ws_sold_date_sk
     AND i_item_sk = ws_item_sk
   GROUP BY i_category
   UNION ALL
   SELECT sum(ws_net_paid), NULL, NULL, 2, 1
   FROM web_sales, date_dim d1, item
   WHERE d1.d_month_seq BETWEEN 1200 AND (1200 + 11)
     AND d1.d_date_sk = ws_sold_date_sk
     AND i_item_sk = ws_item_sk
)
SELECT total_sum, i_category, i_class, lochierarchy,
       rank() OVER (PARTITION BY lochierarchy,
                    CASE WHEN g_class = 0 THEN i_category END
                    ORDER BY total_sum DESC) rank_within_parent
FROM lvl
ORDER BY lochierarchy DESC,
         CASE WHEN lochierarchy = 0 THEN i_category END ASC,
         rank_within_parent ASC
LIMIT 100
