WITH
  customer_total_return AS (
   SELECT
     "cr_returning_customer_sk" "ctr_customer_sk"
   , "ca_state" "ctr_state"
   , "sum"("cr_return_amt_inc_tax") "ctr_total_return"
   FROM
     tpcds.catalog_returns
   , tpcds.date_dim
   , tpcds.customer_address
   WHERE ("cr_returned_date_sk" = "d_date_sk")
      AND ("d_year" = 2000)
      AND ("cr_returning_addr_sk" = "ca_address_sk")
   GROUP BY "cr_returning_customer_sk", "ca_state"
) 
SELECT
  "c_customer_id"
, "c_salutation"
, "c_first_name"
, "c_last_name"
, "ca_street_number"
, "ca_street_name"
, "ca_street_type"
, "ca_suite_number"
, "ca_city"
, "ca_county"
, "ca_state"
, "ca_zip"
, "ca_country"
, "ca_gmt_offset"
, "ca_location_type"
, "ctr_total_return"
FROM
  customer_total_return ctr1
, tpcds.customer_address
, tpcds.customer
WHERE ("ctr1"."ctr_total_return" > (
      SELECT ("avg"("ctr_total_return") * DECIMAL '1.2')
      FROM
        customer_total_return ctr2
      WHERE ("ctr1"."ctr_state" = "ctr2"."ctr_state")
   ))
   AND ("ca_address_sk" = "c_current_addr_sk")
   AND ("ca_state" = 'GA')
   AND ("ctr1"."ctr_customer_sk" = "c_customer_sk")
ORDER BY "c_customer_id" ASC, "c_salutation" ASC, "c_first_name" ASC, "c_last_name" ASC, "ca_street_number" ASC, "ca_street_name" ASC, "ca_street_type" ASC, "ca_suite_number" ASC, "ca_city" ASC, "ca_county" ASC, "ca_state" ASC, "ca_zip" ASC, "ca_country" ASC, "ca_gmt_offset" ASC, "ca_location_type" ASC, "ctr_total_return" ASC
LIMIT 100
