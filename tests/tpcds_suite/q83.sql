WITH
  sr_items AS (
   SELECT
     "i_item_id" "item_id"
   , "sum"("sr_return_quantity") "sr_item_qty"
   FROM
     tpcds.store_returns
   , tpcds.item
   , tpcds.date_dim
   WHERE ("sr_item_sk" = "i_item_sk")
      AND ("d_date" IN (
      SELECT "d_date"
      FROM
        tpcds.date_dim
      WHERE ("d_week_seq" IN (
         SELECT "d_week_seq"
         FROM
           tpcds.date_dim
         WHERE ("d_date" IN (CAST('2000-06-30' AS DATE)         , CAST('2000-09-27' AS DATE)         , CAST('2000-11-17' AS DATE)))
      ))
   ))
      AND ("sr_returned_date_sk" = "d_date_sk")
   GROUP BY "i_item_id"
) 
, cr_items AS (
   SELECT
     "i_item_id" "item_id"
   , "sum"("cr_return_quantity") "cr_item_qty"
   FROM
     tpcds.catalog_returns
   , tpcds.item
   , tpcds.date_dim
   WHERE ("cr_item_sk" = "i_item_sk")
      AND ("d_date" IN (
      SELECT "d_date"
      FROM
        tpcds.date_dim
      WHERE ("d_week_seq" IN (
         SELECT "d_week_seq"
         FROM
           tpcds.date_dim
         WHERE ("d_date" IN (CAST('2000-06-30' AS DATE)         , CAST('2000-09-27' AS DATE)         , CAST('2000-11-17' AS DATE)))
      ))
   ))
      AND ("cr_returned_date_sk" = "d_date_sk")
   GROUP BY "i_item_id"
) 
, wr_items AS (
   SELECT
     "i_item_id" "item_id"
   , "sum"("wr_return_quantity") "wr_item_qty"
   FROM
     tpcds.web_returns
   , tpcds.item
   , tpcds.date_dim
   WHERE ("wr_item_sk" = "i_item_sk")
      AND ("d_date" IN (
      SELECT "d_date"
      FROM
        tpcds.date_dim
      WHERE ("d_week_seq" IN (
         SELECT "d_week_seq"
         FROM
           tpcds.date_dim
         WHERE ("d_date" IN (CAST('2000-06-30' AS DATE)         , CAST('2000-09-27' AS DATE)         , CAST('2000-11-17' AS DATE)))
      ))
   ))
      AND ("wr_returned_date_sk" = "d_date_sk")
   GROUP BY "i_item_id"
) 
SELECT
  "sr_items"."item_id"
, "sr_item_qty"
, CAST(((("sr_item_qty" / ((CAST("sr_item_qty" AS DECIMAL(9,4)) + "cr_item_qty") + "wr_item_qty")) / DECIMAL '3.0') * 100) AS DECIMAL(7,2)) "sr_dev"
, "cr_item_qty"
, CAST(((("cr_item_qty" / ((CAST("sr_item_qty" AS DECIMAL(9,4)) + "cr_item_qty") + "wr_item_qty")) / DECIMAL '3.0') * 100) AS DECIMAL(7,2)) "cr_dev"
, "wr_item_qty"
, CAST(((("wr_item_qty" / ((CAST("sr_item_qty" AS DECIMAL(9,4)) + "cr_item_qty") + "wr_item_qty")) / DECIMAL '3.0') * 100) AS DECIMAL(7,2)) "wr_dev"
, ((("sr_item_qty" + "cr_item_qty") + "wr_item_qty") / DECIMAL '3.00') "average"
FROM
  sr_items
, cr_items
, wr_items
WHERE ("sr_items"."item_id" = "cr_items"."item_id")
   AND ("sr_items"."item_id" = "wr_items"."item_id")
ORDER BY "sr_items"."item_id" ASC, "sr_item_qty" ASC
LIMIT 100
