SELECT
  (CASE WHEN ((
      SELECT "count"(*)
      FROM
        tpcds.store_sales
      WHERE ("ss_quantity" BETWEEN 1 AND 20)
   ) > 74129) THEN (
   SELECT "avg"("ss_ext_discount_amt")
   FROM
     tpcds.store_sales
   WHERE ("ss_quantity" BETWEEN 1 AND 20)
) ELSE (
   SELECT "avg"("ss_net_paid")
   FROM
     tpcds.store_sales
   WHERE ("ss_quantity" BETWEEN 1 AND 20)
) END) "bucket1"
, (CASE WHEN ((
      SELECT "count"(*)
      FROM
        tpcds.store_sales
      WHERE ("ss_quantity" BETWEEN 21 AND 40)
   ) > 122840) THEN (
   SELECT "avg"("ss_ext_discount_amt")
   FROM
     tpcds.store_sales
   WHERE ("ss_quantity" BETWEEN 21 AND 40)
) ELSE (
   SELECT "avg"("ss_net_paid")
   FROM
     tpcds.store_sales
   WHERE ("ss_quantity" BETWEEN 21 AND 40)
) END) "bucket2"
, (CASE WHEN ((
      SELECT "count"(*)
      FROM
        tpcds.store_sales
      WHERE ("ss_quantity" BETWEEN 41 AND 60)
   ) > 56580) THEN (
   SELECT "avg"("ss_ext_discount_amt")
   FROM
     tpcds.store_sales
   WHERE ("ss_quantity" BETWEEN 41 AND 60)
) ELSE (
   SELECT "avg"("ss_net_paid")
   FROM
     tpcds.store_sales
   WHERE ("ss_quantity" BETWEEN 41 AND 60)
) END) "bucket3"
, (CASE WHEN ((
      SELECT "count"(*)
      FROM
        tpcds.store_sales
      WHERE ("ss_quantity" BETWEEN 61 AND 80)
   ) > 10097) THEN (
   SELECT "avg"("ss_ext_discount_amt")
   FROM
     tpcds.store_sales
   WHERE ("ss_quantity" BETWEEN 61 AND 80)
) ELSE (
   SELECT "avg"("ss_net_paid")
   FROM
     tpcds.store_sales
   WHERE ("ss_quantity" BETWEEN 61 AND 80)
) END) "bucket4"
, (CASE WHEN ((
      SELECT "count"(*)
      FROM
        tpcds.store_sales
      WHERE ("ss_quantity" BETWEEN 81 AND 100)
   ) > 165306) THEN (
   SELECT "avg"("ss_ext_discount_amt")
   FROM
     tpcds.store_sales
   WHERE ("ss_quantity" BETWEEN 81 AND 100)
) ELSE (
   SELECT "avg"("ss_net_paid")
   FROM
     tpcds.store_sales
   WHERE ("ss_quantity" BETWEEN 81 AND 100)
) END) "bucket5"
FROM
  tpcds.reason
WHERE ("r_reason_sk" = 1)
