"""Spill tier tests: partitioned aggregation spill + external sort.

Reference analogues: TestSpilledAggregations / TestSpilledOrderBy /
spiller unit tests (presto-main/.../spiller/, SURVEY §2.9).  A tiny
spill threshold forces every accumulating operator through the spill
path; results must equal the in-memory path."""

import dataclasses

import numpy as np
import pytest

from presto_tpu.config import DEFAULT
from presto_tpu.localrunner import LocalQueryRunner

# spiller primitives stay in the quick tier; the forced-spill SQL suites
# re-execute whole queries through tiny thresholds (many runs, many
# compiles) and belong to the slow tier's budget


def spilly_config(**kw):
    return dataclasses.replace(DEFAULT, spill_threshold_bytes=1 << 10,
                               spill_partitions=4, **kw)


@pytest.fixture(scope="module")
def spill_runner():
    return LocalQueryRunner.tpch(scale=0.01, config=spilly_config())


@pytest.fixture(scope="module")
def mem_runner():
    return LocalQueryRunner.tpch(scale=0.01)


def norm(rows):
    return sorted(
        tuple(round(v, 6) if isinstance(v, float) else v for v in r)
        for r in rows)


class TestSpillerPrimitives:
    def test_file_spiller_roundtrip(self, tmp_path):
        from presto_tpu.batch import batch_from_pylist
        from presto_tpu import types as T
        from presto_tpu.exec.spill import FileSpiller

        s = FileSpiller(str(tmp_path))
        b1 = batch_from_pylist([T.BIGINT], [(i,) for i in range(100)])
        b2 = batch_from_pylist([T.BIGINT], [(i,) for i in range(100, 150)])
        s.spill(b1)
        s.spill(b2)
        assert s.rows_written == 150
        got = [tuple(r) for b in s.read_all() for r in b.to_pylist()]
        assert got == [(i,) for i in range(150)]
        s.close()

    def test_partitioning_spiller_covers_all_rows(self, tmp_path):
        from presto_tpu.batch import batch_from_pylist
        from presto_tpu import types as T
        from presto_tpu.exec.spill import PartitioningSpiller

        s = PartitioningSpiller(str(tmp_path), 4, [0])
        rows = [(i % 37,) for i in range(1000)]
        s.spill(batch_from_pylist([T.BIGINT], rows))
        seen = []
        key_to_part = {}
        for p in range(4):
            for b in s.partition(p):
                for (k,) in b.to_pylist():
                    seen.append(k)
                    # a key must always land in the same partition
                    assert key_to_part.setdefault(k, p) == p
        assert sorted(seen) == sorted(k for k, in rows)
        s.close()


@pytest.mark.slow
class TestSpilledQueries:
    def test_spilled_aggregation_matches(self, spill_runner, mem_runner):
        sql = ("select l_suppkey, count(*), sum(l_quantity), "
               "avg(l_extendedprice), min(l_shipdate), max(l_discount) "
               "from lineitem group by l_suppkey")
        assert norm(spill_runner.execute(sql).rows) == \
            norm(mem_runner.execute(sql).rows)

    def test_spilled_aggregation_varchar_keys(self, spill_runner,
                                              mem_runner):
        sql = ("select l_returnflag, l_linestatus, count(*) "
               "from lineitem group by l_returnflag, l_linestatus")
        assert norm(spill_runner.execute(sql).rows) == \
            norm(mem_runner.execute(sql).rows)

    def test_spilled_order_by_matches(self, spill_runner, mem_runner):
        sql = ("select l_orderkey, l_linenumber, l_shipdate from lineitem "
               "where l_suppkey < 30 "
               "order by l_shipdate desc, l_orderkey, l_linenumber")
        got = spill_runner.execute(sql).rows
        want = mem_runner.execute(sql).rows
        assert got == want  # exact ordered comparison

    def test_spilled_order_by_varchar_matches(self, spill_runner,
                                              mem_runner):
        # Each spilled run re-codes its varchar keys into its own
        # dictionary (different first-seen order per run), so the k-way
        # merge must compare actual string values, not codes or ranks.
        sql = ("select l_comment, l_orderkey from lineitem "
               "where l_suppkey < 30 "
               "order by l_comment, l_orderkey")
        got = spill_runner.execute(sql).rows
        want = mem_runner.execute(sql).rows
        assert got == want  # exact ordered comparison

    def test_spilled_order_by_varchar_desc_nulls(self, spill_runner,
                                                 mem_runner):
        sql = ("select l_shipinstruct, l_comment, l_orderkey from lineitem "
               "where l_suppkey < 30 "
               "order by l_comment desc, l_orderkey")
        got = spill_runner.execute(sql).rows
        want = mem_runner.execute(sql).rows
        assert got == want

    @pytest.mark.parametrize("descending", [False, True])
    def test_merge_compares_values_across_run_dictionaries(
            self, tmp_path, descending):
        # Each spilled run carries its OWN dictionary (batch_from_pylist
        # interns in first-seen order), so codes/ranks are not comparable
        # across runs: the k-way merge must compare decoded string values.
        import dataclasses as dc

        from presto_tpu import types as T
        from presto_tpu.batch import batch_from_pylist
        from presto_tpu.exec.context import (
            OperatorContext, QueryContext, TaskContext,
        )
        from presto_tpu.exec.sortop import OrderByOperator, SortSpec

        cfg = dc.replace(DEFAULT, spill_threshold_bytes=1,
                         spill_path=str(tmp_path))
        ctx = OperatorContext(TaskContext(QueryContext(cfg)), "sort")
        op = OrderByOperator(ctx, [SortSpec(0, descending=descending)])
        # run 1 dictionary: banana=0, apple=1; run 2: zebra=0, cherry=1.
        # Rank-based merge would interleave per-run ranks (apple~cherry,
        # banana~zebra); value-based merge restores global order.
        runs = [[("banana",), ("apple",), (None,)],
                [("zebra",), ("cherry",)]]
        for rows in runs:
            op.add_input(batch_from_pylist([T.VARCHAR], rows))
        assert len(op._runs) == 2  # every batch became its own spilled run
        op.finish()
        got = []
        while (b := op.get_output()) is not None:
            got += [r[0] for r in b.to_pylist()]
        want = ["apple", "banana", "cherry", "zebra"]
        want = (want[::-1] if descending else want) + [None]  # nulls last
        assert got == want

    def test_spilled_topn_matches(self, spill_runner, mem_runner):
        sql = ("select l_orderkey, l_extendedprice from lineitem "
               "order by l_extendedprice desc, l_orderkey limit 25")
        assert spill_runner.execute(sql).rows == \
            mem_runner.execute(sql).rows

    def test_spilled_join_query(self, spill_runner, mem_runner):
        # join whose build side AND agg spill (grace hash join path)
        sql = ("select o_orderpriority, count(*) from orders, lineitem "
               "where o_orderkey = l_orderkey and l_quantity > 45 "
               "group by o_orderpriority")
        assert norm(spill_runner.execute(sql).rows) == \
            norm(mem_runner.execute(sql).rows)

    def test_spilled_join_row_level(self, spill_runner, mem_runner):
        # row-level join output parity through the partitioned replay
        sql = ("select o_orderkey, l_linenumber, l_quantity from orders "
               "join lineitem on o_orderkey = l_orderkey "
               "where o_custkey < 50 order by 1, 2")
        assert spill_runner.execute(sql).rows == \
            mem_runner.execute(sql).rows

    def test_spilled_left_join(self, spill_runner, mem_runner):
        sql = ("select c_custkey, o_orderkey from customer "
               "left join orders on c_custkey = o_custkey "
               "where c_custkey < 100 order by 1, 2")
        assert spill_runner.execute(sql).rows == \
            mem_runner.execute(sql).rows

    def test_spilled_join_varchar_key(self, spill_runner, mem_runner):
        # varchar equi-key: partition routing must hash string VALUES
        sql = ("select n1.n_name, n2.n_name from nation n1 "
               "join nation n2 on n1.n_name = n2.n_name order by 1")
        assert spill_runner.execute(sql).rows == \
            mem_runner.execute(sql).rows

    def test_spilled_semi_join(self, spill_runner, mem_runner):
        sql = ("select count(*) from orders where o_orderkey in "
               "(select l_orderkey from lineitem where l_quantity > 48)")
        assert spill_runner.execute(sql).rows == \
            mem_runner.execute(sql).rows


class TestPartitionStarts:
    def test_nan_partition_keys_form_one_partition(self, tmp_path):
        """NaN != NaN must not split a NaN partition into per-row
        partitions on the chunked (host) path; cross-batch tails with
        NaN keys must also compare equal (ADVICE r4)."""
        import dataclasses as dc
        import math

        from presto_tpu import types as T
        from presto_tpu.batch import batch_from_pylist
        from presto_tpu.exec.context import (
            OperatorContext, QueryContext, TaskContext,
        )
        from presto_tpu.exec.windowop import WindowOperator

        cfg = dc.replace(DEFAULT, spill_path=str(tmp_path))
        ctx = OperatorContext(TaskContext(QueryContext(cfg)), "win")
        op = WindowOperator(ctx, [0], [], [])
        nan = math.nan
        b1 = batch_from_pylist([T.DOUBLE],
                               [(1.0,), (nan,), (nan,), (-0.0,)])
        starts, tail = op._partition_starts(b1, None)
        # rows: 1.0 | nan nan | -0.0  -> starts at 0, 1, 3
        assert starts.tolist() == [True, True, False, True]
        b2 = batch_from_pylist([T.DOUBLE], [(0.0,), (nan,), (nan,)])
        starts2, _ = op._partition_starts(b2, tail)
        # -0.0 tail == +0.0 head (SQL equality); nan run starts once
        assert starts2.tolist() == [False, True, False]


@pytest.mark.slow
class TestWindowSpill:
    """WindowOperator as a spill consumer (SURVEY §2.9, VERDICT r3 #8):
    sorted runs spill under the revocable threshold; evaluation then
    proceeds chunk-by-chunk over whole partitions."""

    def test_spilled_row_number_matches(self, spill_runner, mem_runner):
        sql = ("select o_custkey, o_orderkey, row_number() over "
               "(partition by o_custkey order by o_orderdate, o_orderkey) "
               "from orders")
        assert norm(spill_runner.execute(sql).rows) == \
            norm(mem_runner.execute(sql).rows)

    def test_spilled_running_sum_matches(self, spill_runner, mem_runner):
        sql = ("select o_orderkey, sum(o_totalprice) over "
               "(partition by o_custkey order by o_orderkey) "
               "from orders")
        assert norm(spill_runner.execute(sql).rows) == \
            norm(mem_runner.execute(sql).rows)

    def test_spilled_rank_varchar_partition(self, spill_runner,
                                            mem_runner):
        sql = ("select o_orderpriority, o_orderkey, rank() over "
               "(partition by o_orderpriority order by o_orderkey) "
               "from orders where o_orderkey <= 2000")
        assert norm(spill_runner.execute(sql).rows) == \
            norm(mem_runner.execute(sql).rows)

    def test_spilled_lag_lead(self, spill_runner, mem_runner):
        sql = ("select o_orderkey, lag(o_totalprice) over "
               "(partition by o_custkey order by o_orderkey), "
               "lead(o_totalprice) over "
               "(partition by o_custkey order by o_orderkey) "
               "from orders")
        assert norm(spill_runner.execute(sql).rows) == \
            norm(mem_runner.execute(sql).rows)
