"""Execution runtime tests: hand-built operator pipelines over TPC-H data,
parity-checked against direct numpy computation (reference tier:
HandTpchQuery1/6 benchmarks + OperatorAssertion golden results)."""

import numpy as np
import pytest

from presto_tpu import types as T
from presto_tpu.batch import batch_from_pylist
from presto_tpu.connectors.tpch import TpchConnector
from presto_tpu.exec.aggregation import (
    AggChannel, GlobalAggregationOperatorFactory, HashAggregationOperatorFactory,
)
from presto_tpu.exec.driver import Pipeline
from presto_tpu.exec.joinop import (
    HashBuildOperatorFactory, LookupJoinOperatorFactory,
)
from presto_tpu.exec.operators import (
    FilterProjectOperatorFactory, LimitOperatorFactory, OutputCollectorFactory,
    TableScanOperatorFactory, ValuesOperatorFactory,
)
from presto_tpu.exec.runner import execute_pipelines
from presto_tpu.exec.sortop import OrderByOperatorFactory, SortSpec
from presto_tpu.expr import build as B

SCALE = 0.005


@pytest.fixture(scope="module")
def tpch():
    return TpchConnector(scale=SCALE)


def scan_numpy(conn, table, columns):
    handle = conn.get_table(table)
    from presto_tpu.batch import concat_batches

    batches = []
    for split in conn.get_splits(handle, 1):
        batches.extend(conn.page_source(split, columns))
    return concat_batches(batches)


def all_splits(conn, table, n=3):
    return conn.get_splits(conn.get_table(table), n)


def test_q6_filter_global_agg(tpch):
    """TPC-H Q6: sum(extendedprice * discount) with date/qty/discount range
    filters — the FilterAndProject + AggregationOperator slice."""
    cols = ["l_shipdate", "l_quantity", "l_discount", "l_extendedprice"]
    D, Q, DISC, EX = range(4)
    filt = B.and_(
        B.comparison(">=", B.ref(D, T.DATE), B.const("1994-01-01", T.DATE)),
        B.comparison("<", B.ref(D, T.DATE), B.const("1995-01-01", T.DATE)),
        B.between(B.ref(DISC, T.DOUBLE), B.const(0.05, T.DOUBLE),
                  B.const(0.07, T.DOUBLE)),
        B.comparison("<", B.ref(Q, T.DOUBLE), B.const(24.0, T.DOUBLE)))
    proj = [B.call("multiply", B.ref(EX, T.DOUBLE), B.ref(DISC, T.DOUBLE))]
    out = OutputCollectorFactory()
    pipeline = Pipeline([
        TableScanOperatorFactory(tpch, cols, batch_rows=4096),
        FilterProjectOperatorFactory(filt, proj, [T.DATE, T.DOUBLE,
                                                  T.DOUBLE, T.DOUBLE]),
        GlobalAggregationOperatorFactory([AggChannel("sum", 0, T.DOUBLE)],
                                         [T.DOUBLE]),
        out,
    ], splits=all_splits(tpch, "lineitem"))
    execute_pipelines([pipeline])
    (got,) = out.rows()[0]

    # numpy oracle
    b = scan_numpy(tpch, "lineitem", cols).to_numpy()
    ship = np.asarray(b.columns[0].values)
    qty = np.asarray(b.columns[1].values)
    disc = np.asarray(b.columns[2].values)
    ext = np.asarray(b.columns[3].values)
    lo = T.DATE.from_python("1994-01-01")
    hi = T.DATE.from_python("1995-01-01")
    mask = (ship >= lo) & (ship < hi) & (disc >= 0.05) & (disc <= 0.07) & \
        (qty < 24.0)
    expected = float((ext[mask] * disc[mask]).sum())
    assert got == pytest.approx(expected, rel=1e-9)
    assert expected > 0


def test_q1_grouped_agg(tpch):
    """TPC-H Q1 slice: grouped aggregation over two dictionary key columns
    with computed measures, then ORDER BY."""
    cols = ["l_returnflag", "l_linestatus", "l_quantity", "l_extendedprice",
            "l_discount", "l_tax", "l_shipdate"]
    RF, LS, Q, EP, DI, TX, SD = range(7)
    cutoff = "1998-09-02"
    filt = B.comparison("<=", B.ref(SD, T.DATE), B.const(cutoff, T.DATE))
    disc_price = B.call("multiply", B.ref(EP, T.DOUBLE),
                        B.call("subtract", B.const(1.0, T.DOUBLE),
                               B.ref(DI, T.DOUBLE)))
    charge = B.call("multiply", disc_price,
                    B.call("add", B.const(1.0, T.DOUBLE), B.ref(TX, T.DOUBLE)))
    proj = [B.ref(RF, T.VARCHAR), B.ref(LS, T.VARCHAR), B.ref(Q, T.DOUBLE),
            B.ref(EP, T.DOUBLE), disc_price, charge]
    out = OutputCollectorFactory()
    pipeline = Pipeline([
        TableScanOperatorFactory(tpch, cols, batch_rows=8192),
        FilterProjectOperatorFactory(
            filt, proj, [T.VARCHAR, T.VARCHAR] + [T.DOUBLE] * 4 + [T.DATE]),
        HashAggregationOperatorFactory(
            [0, 1],
            [AggChannel("sum", 2, T.DOUBLE), AggChannel("sum", 3, T.DOUBLE),
             AggChannel("sum", 4, T.DOUBLE), AggChannel("sum", 5, T.DOUBLE),
             AggChannel("count", None, T.BIGINT)],
            [T.VARCHAR, T.VARCHAR] + [T.DOUBLE] * 4),
        OrderByOperatorFactory([SortSpec(0), SortSpec(1)]),
        out,
    ], splits=all_splits(tpch, "lineitem"))
    execute_pipelines([pipeline])
    got = out.rows()

    b = scan_numpy(tpch, "lineitem", cols)
    rows = b.to_pylist()
    cutoff_d = __import__("datetime").date(1998, 9, 2)
    agg = {}
    for rf, ls, q, ep, di, tx, sd in rows:
        if sd <= cutoff_d:
            e = agg.setdefault((rf, ls), [0.0, 0.0, 0.0, 0.0, 0])
            e[0] += q
            e[1] += ep
            e[2] += ep * (1 - di)
            e[3] += ep * (1 - di) * (1 + tx)
            e[4] += 1
    expected = sorted((k[0], k[1], *v) for k, v in agg.items())
    assert len(got) == len(expected)
    for g, e in zip(got, expected):
        assert g[0] == e[0] and g[1] == e[1]
        for gv, ev in zip(g[2:6], e[2:6]):
            assert gv == pytest.approx(ev, rel=1e-9)
        assert g[6] == e[6]


def test_join_pipeline(tpch):
    """orders JOIN customer ON o_custkey = c_custkey (single-key streaming
    build/probe), counting matches."""
    build = HashBuildOperatorFactory([0], [T.BIGINT, T.VARCHAR])
    build_pipeline = Pipeline([
        TableScanOperatorFactory(tpch, ["c_custkey", "c_mktsegment"]),
        build,
    ], splits=all_splits(tpch, "customer"), name="build")
    out = OutputCollectorFactory()
    probe_pipeline = Pipeline([
        TableScanOperatorFactory(tpch, ["o_orderkey", "o_custkey"]),
        LookupJoinOperatorFactory(build, [1], [T.BIGINT, T.BIGINT], "inner"),
        out,
    ], splits=all_splits(tpch, "orders"), name="probe")
    execute_pipelines([build_pipeline, probe_pipeline])
    rows = out.rows()
    orders = scan_numpy(tpch, "orders", ["o_orderkey", "o_custkey"]).to_pylist()
    cust = dict(scan_numpy(tpch, "customer",
                           ["c_custkey", "c_mktsegment"]).to_pylist())
    assert len(rows) == len(orders)  # every order has exactly one customer
    for okey, ckey, ckey2, seg in rows[:500]:
        assert ckey == ckey2
        assert seg == cust[ckey]


def test_left_join_and_semi(tpch):
    """customer LEFT JOIN orders + semijoin: 1/3 of customers have no
    orders (the 2/3-customer rule)."""
    build = HashBuildOperatorFactory([0], [T.BIGINT])
    build_pipeline = Pipeline([
        TableScanOperatorFactory(tpch, ["o_custkey"]),
        build,
    ], splits=all_splits(tpch, "orders"), name="build")
    out = OutputCollectorFactory()
    probe = Pipeline([
        TableScanOperatorFactory(tpch, ["c_custkey"]),
        LookupJoinOperatorFactory(build, [0], [T.BIGINT], "semi"),
        out,
    ], splits=all_splits(tpch, "customer"), name="probe")
    execute_pipelines([build_pipeline, probe])
    with_orders = {r[0] for r in out.rows()}
    ordered_custkeys = {r[0] for r in
                        scan_numpy(tpch, "orders", ["o_custkey"]).to_pylist()}
    assert with_orders == ordered_custkeys

    # anti join: customers without orders
    build2 = HashBuildOperatorFactory([0], [T.BIGINT])
    bp2 = Pipeline([TableScanOperatorFactory(tpch, ["o_custkey"]), build2],
                   splits=all_splits(tpch, "orders"), name="b2")
    out2 = OutputCollectorFactory()
    pp2 = Pipeline([
        TableScanOperatorFactory(tpch, ["c_custkey"]),
        LookupJoinOperatorFactory(build2, [0], [T.BIGINT], "anti"),
        out2,
    ], splits=all_splits(tpch, "customer"), name="p2")
    execute_pipelines([bp2, pp2])
    n_cust = tpch.row_count("customer")
    assert {r[0] for r in out2.rows()} == \
        set(range(1, n_cust + 1)) - ordered_custkeys


def test_packed_multikey_join(tpch):
    """lineitem JOIN partsupp ON (partkey, suppkey) — the packed two-word
    id path (Q9's join shape)."""
    build = HashBuildOperatorFactory(
        [0, 1], [T.BIGINT, T.BIGINT, T.BIGINT])
    bp = Pipeline([
        TableScanOperatorFactory(tpch, ["ps_partkey", "ps_suppkey",
                                        "ps_availqty"]),
        build,
    ], splits=all_splits(tpch, "partsupp"), name="build")
    out = OutputCollectorFactory()
    pp = Pipeline([
        TableScanOperatorFactory(tpch, ["l_partkey", "l_suppkey"]),
        LookupJoinOperatorFactory(build, [0, 1],
                                  [T.BIGINT, T.BIGINT], "inner"),
        out,
    ], splits=all_splits(tpch, "lineitem"), name="probe")
    execute_pipelines([bp, pp])
    rows = out.rows()
    li = scan_numpy(tpch, "lineitem", ["l_partkey", "l_suppkey"]).to_pylist()
    assert len(rows) == len(li)  # ps (partkey,suppkey) unique -> 1 match each
    for lp, ls, bp_, bs, qty in rows[:300]:
        assert (lp, ls) == (bp_, bs)


def test_order_by_limit_values():
    b = batch_from_pylist([T.BIGINT, T.DOUBLE],
                          [(3, 1.5), (1, 9.0), (2, -4.0), (5, 0.0), (4, 2.0)])
    out = OutputCollectorFactory()
    p = Pipeline([
        ValuesOperatorFactory([b]),
        OrderByOperatorFactory([SortSpec(1, descending=True)], limit=3),
        LimitOperatorFactory(3),
        out,
    ])
    execute_pipelines([p])
    assert out.rows() == [(1, 9.0), (4, 2.0), (3, 1.5)]


def test_empty_results():
    b = batch_from_pylist([T.BIGINT], [(1,), (2,)])
    out = OutputCollectorFactory()
    p = Pipeline([
        ValuesOperatorFactory([b]),
        FilterProjectOperatorFactory(
            B.comparison(">", B.ref(0, T.BIGINT), B.const(100, T.BIGINT)),
            [B.ref(0, T.BIGINT)], [T.BIGINT]),
        HashAggregationOperatorFactory(
            [0], [AggChannel("count", None, T.BIGINT)], [T.BIGINT]),
        out,
    ])
    execute_pipelines([p])
    assert out.rows() == []  # grouped agg over empty input: no rows
