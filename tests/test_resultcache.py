"""Cross-query result cache (server/resultcache.py): a repeated
statement's second execution is served ENTIRELY from its first
execution's root-output spool pages.

The acceptance pins:

- second execution over HTTP: zero tasks created, zero physical plans
  built, zero jit dispatches — pinned via queryStats/_tasks_scheduled/
  sql.physical.PLANS_BUILT — with exact rows and a FINISHED query that
  resource groups, events, /v1/query, system.runtime, and /metrics all
  see (``resultCached=true``);
- invalidation is the plan cache's: INSERT/CTAS/DDL between repeats
  bumps the catalog stats epoch and the next execution re-runs with
  exact rows; a session-property change misses (fingerprint);
- ``result_cache_enabled=false`` (the default) restores PR 12 behavior
  exactly: repeats schedule tasks and the cache sees zero traffic;
- eviction (capacity or byte pressure) deletes the entry's spool
  pages; the spool GC of the source query never touches them;
- the object-store spool tier serves hits byte-exact, including under
  a faults.py spool-read-error policy.
"""

import dataclasses
import json
import threading
import time
import urllib.request

import pytest

from presto_tpu import events as ev
from presto_tpu.config import DEFAULT
from presto_tpu.server import resultcache
from presto_tpu.server.dqr import DistributedQueryRunner
from presto_tpu.server.faults import FaultInjector
from presto_tpu.sql import physical

pytestmark = pytest.mark.chaos


def _get_json(uri):
    with urllib.request.urlopen(uri, timeout=10) as resp:
        return json.loads(resp.read())


def _cfg(tmp_path, **over):
    return dataclasses.replace(
        DEFAULT, result_cache_enabled=True,
        exchange_spool_path=str(tmp_path / "spool"), **over)


@pytest.fixture(autouse=True)
def _fresh_cache():
    resultcache.clear()
    yield
    resultcache.clear()


def _detail(dqr, client=None):
    qid = (client or dqr.client).last_query_id
    return _get_json(f"{dqr.coordinator.uri}/v1/query/{qid}")


SQL = ("select l_returnflag, count(*) as c, sum(l_quantity) as q "
       "from lineitem group by l_returnflag order by l_returnflag")


# -- unit tier ---------------------------------------------------------------

def test_unit_lru_and_byte_eviction_delete_pages(tmp_path):
    """The cache's own LRU: capacity and byte caps evict oldest-first
    and eviction deletes the entry's spool pages through its store."""
    from presto_tpu.server.spool import FileSystemSpoolStore
    from presto_tpu.sql.plancache import StatsEpochs

    store = FileSystemSpoolStore(str(tmp_path / "s"))
    epochs = StatsEpochs()

    def entry(i, nbytes=100):
        tid = resultcache.new_task_id()
        store.write_page(tid, 0, 0, b"x" * nbytes)
        store.set_complete(tid, 0, 1)
        return resultcache.CachedResult(
            tid, 1, ["c"], [], 1, nbytes, store)

    e1, e2, e3 = entry(1), entry(2), entry(3)
    k = resultcache.cache_key
    resultcache.put(k(epochs, "q1", "t", None), e1, epochs, ["t"],
                    capacity=2, max_total_bytes=1 << 20)
    resultcache.put(k(epochs, "q2", "t", None), e2, epochs, ["t"],
                    capacity=2, max_total_bytes=1 << 20)
    assert resultcache.stats()["size"] == 2
    # capacity eviction drops the LRU entry AND its pages
    resultcache.put(k(epochs, "q3", "t", None), e3, epochs, ["t"],
                    capacity=2, max_total_bytes=1 << 20)
    st = resultcache.stats()
    assert st["size"] == 2 and st["evictions"] == 1
    assert store.get_pages(e1.task_id, 0, 0) == ([], 0, False)
    assert store.get_pages(e3.task_id, 0, 0)[0]   # newest survives
    # epoch invalidation on lookup: bump -> entry dropped, pages gone
    epochs.bump("t")
    assert resultcache.get(k(epochs, "q3", "t", None), epochs) is None
    st = resultcache.stats()
    assert st["evictions"] == 2 and st["misses"] == 1
    assert store.get_pages(e3.task_id, 0, 0) == ([], 0, False)
    # byte-cap eviction
    big = entry(4, nbytes=200)
    resultcache.put(k(epochs, "q4", "t", None), big, epochs, ["t"],
                    capacity=10, max_total_bytes=250)
    assert resultcache.stats()["size"] == 1   # e2 (100b) evicted: 300>250


# -- serving tier ------------------------------------------------------------

def test_second_execution_zero_tasks_zero_plans_zero_jit(tmp_path):
    """THE acceptance pin: the second execution of a repeated statement
    over HTTP is served entirely from the result cache — no tasks, no
    physical plans, no jit dispatches — while lifecycle/events/stats
    all still see a normal FINISHED query."""
    events = []
    with DistributedQueryRunner.tpch(scale=0.01, n_workers=2,
                                     config=_cfg(tmp_path)) as dqr:
        dqr.event_bus.register(
            type("L", (ev.EventListener,), {
                "query_completed":
                    staticmethod(lambda e: events.append(e))})())
        r1 = dqr.execute(SQL)
        d1 = _detail(dqr)
        assert d1["resultCached"] is False
        plans_before = physical.PLANS_BUILT
        r2 = dqr.execute(SQL)
        assert r2.rows == r1.rows
        d2 = _detail(dqr)
        q2 = dqr.coordinator.queries[d2["queryId"]]
        # zero tasks created
        assert q2._tasks_scheduled is False
        assert q2._placements == []
        # zero physical plans built anywhere in the process
        assert physical.PLANS_BUILT == plans_before
        # zero jit work, pinned via queryStats over HTTP
        qs = d2["queryStats"]
        assert d2["resultCached"] is True
        assert d2["state"] == "FINISHED"
        assert qs["jit_dispatches"] == 0 and qs["jit_compiles"] == 0
        assert qs["stages"] == 0
        assert qs["result_cached"] == 1
        assert qs["result_cache_bytes"] == d2["resultCacheBytes"] > 0
        assert qs["output_rows"] == len(r2.rows)
        # the serving plane still saw a full lifecycle
        assert any(e.query_id == d2["queryId"] and e.state == "FINISHED"
                   for e in events)
        listing = _get_json(f"{dqr.coordinator.uri}/v1/query")
        row = next(x for x in listing if x["queryId"] == d2["queryId"])
        assert row["resultCached"] is True
        # system.runtime sees it (the third execution is ALSO a hit and
        # must not disturb the listing's correctness)
        rows = dqr.execute(
            "select result_cached, result_cache_bytes from "
            "system.runtime.queries where query_id = '"
            + d2["queryId"] + "'").rows
        assert rows == [(True, d2["resultCacheBytes"])]
        # /metrics carries the counter families
        with urllib.request.urlopen(
                f"{dqr.coordinator.uri}/metrics", timeout=10) as resp:
            text = resp.read().decode()
        for fam in ("presto_result_cache_hits_total",
                    "presto_result_cache_misses_total",
                    "presto_result_cache_evictions_total",
                    "presto_result_cache_bytes_served_total"):
            assert fam in text, fam
        st = resultcache.stats()
        assert st["hits"] >= 1 and st["bytes_served"] > 0


def test_insert_between_repeats_reexecutes_exact(tmp_path):
    """INSERT between repeats bumps the target catalog's stats epoch:
    the next execution is a MISS that re-runs (tasks scheduled) and
    returns the new exact rows; the stale entry is evicted."""
    with DistributedQueryRunner.tpch(scale=0.01, n_workers=2,
                                     config=_cfg(tmp_path)) as dqr:
        dqr.execute("create table memory.rc as select * from region")
        sql = "select count(*) as c from memory.rc"
        assert dqr.execute(sql).rows == [(5,)]
        assert dqr.execute(sql).rows == [(5,)]
        assert _detail(dqr)["resultCached"] is True
        ev_before = resultcache.stats()["evictions"]
        dqr.execute("insert into memory.rc select * from region")
        r = dqr.execute(sql)
        d = _detail(dqr)
        assert r.rows == [(10,)]
        assert d["resultCached"] is False
        assert dqr.coordinator.queries[d["queryId"]]._tasks_scheduled
        assert resultcache.stats()["evictions"] == ev_before + 1
        # and the refreshed result re-admits
        assert dqr.execute(sql).rows == [(10,)]
        assert _detail(dqr)["resultCached"] is True


def test_ctas_and_ddl_invalidate(tmp_path):
    """CTAS (distributed write) and DDL both bump the epoch: cached
    results over the touched catalog re-run."""
    with DistributedQueryRunner.tpch(scale=0.01, n_workers=2,
                                     config=_cfg(tmp_path)) as dqr:
        dqr.execute("create table memory.src as select * from nation")
        sql = ("select count(*) as c from memory.src")
        dqr.execute(sql)
        dqr.execute(sql)
        assert _detail(dqr)["resultCached"] is True
        # CTAS against the same catalog invalidates
        dqr.execute("create table memory.other as select * from region")
        dqr.execute(sql)
        assert _detail(dqr)["resultCached"] is False
        dqr.execute(sql)
        assert _detail(dqr)["resultCached"] is True
        # DDL (drop) invalidates too
        dqr.execute("drop table memory.other")
        dqr.execute(sql)
        assert _detail(dqr)["resultCached"] is False


def test_session_property_fingerprint_miss(tmp_path):
    """A session-property change produces a different key: the repeat
    under new properties re-executes (same rows)."""
    with DistributedQueryRunner.tpch(scale=0.01, n_workers=2,
                                     config=_cfg(tmp_path)) as dqr:
        base = dqr.new_client(user="fp")
        base.execute(SQL)
        _cols, d0 = base.execute(SQL)
        assert _detail(dqr, base)["resultCached"] is True
        other = dqr.new_client(user="fp")
        other.session_properties["slow_query_log_threshold_s"] = "123"
        _cols, d1 = other.execute(SQL)
        det = _detail(dqr, other)
        assert det["resultCached"] is False
        assert sorted(map(tuple, d1)) == sorted(map(tuple, d0))
        # and the new fingerprint now has its own entry
        other.execute(SQL)
        assert _detail(dqr, other)["resultCached"] is True


def test_execute_bound_statements_key_on_parameters(tmp_path):
    """EXECUTE statements hit under (prepared text + bound parameters):
    the same EXECUTE repeats hit; different parameters miss."""
    with DistributedQueryRunner.tpch(scale=0.01, n_workers=2,
                                     config=_cfg(tmp_path)) as dqr:
        c = dqr.new_client(user="ex")
        c.execute("prepare p1 from "
                  "select count(*) as c from lineitem "
                  "where l_quantity < ?")
        _cols, a1 = c.execute("execute p1 using 10")
        _cols, a2 = c.execute("execute p1 using 10")
        assert a2 == a1
        assert _detail(dqr, c)["resultCached"] is True
        _cols, b1 = c.execute("execute p1 using 20")
        assert _detail(dqr, c)["resultCached"] is False
        assert b1 != a1


def test_disabled_restores_pr12_exactly(tmp_path):
    """The default (result_cache_enabled=false) is PR 12 exactly:
    repeats schedule tasks, the plan cache serves them, and the result
    cache sees ZERO traffic."""
    cfg = dataclasses.replace(
        DEFAULT, exchange_spool_path=str(tmp_path / "spool"))
    with DistributedQueryRunner.tpch(scale=0.01, n_workers=2,
                                     config=cfg) as dqr:
        r1 = dqr.execute(SQL)
        r2 = dqr.execute(SQL)
        assert r2.rows == r1.rows
        d = _detail(dqr)
        q = dqr.coordinator.queries[d["queryId"]]
        assert d["resultCached"] is False
        assert d["planCached"] is True       # the PR 8 path, untouched
        assert q._tasks_scheduled is True
        assert resultcache.stats() == {
            "size": 0, "bytes": 0, "hits": 0, "misses": 0,
            "evictions": 0, "bytes_served": 0}


def test_system_runtime_results_never_cached(tmp_path):
    """Live engine state has no stats epoch: queries over
    system.runtime are never admitted (a cached queries-listing would
    replay stale state forever)."""
    with DistributedQueryRunner.tpch(scale=0.01, n_workers=2,
                                     config=_cfg(tmp_path)) as dqr:
        sql = "select count(*) as c from system.runtime.nodes"
        dqr.execute(sql)
        dqr.execute(sql)
        assert _detail(dqr)["resultCached"] is False
        assert resultcache.stats()["size"] == 0


def test_eviction_deletes_pages_and_source_gc_spares_them(tmp_path):
    """Entry pages live under their own rc* spool id: the source
    query's end-of-query spool GC leaves them servable, and capacity
    eviction deletes exactly them."""
    import os

    with DistributedQueryRunner.tpch(
            scale=0.01, n_workers=2,
            config=_cfg(tmp_path, result_cache_capacity=1)) as dqr:
        dqr.execute(SQL)
        # the source query's spool dir is GC'd, the rc dir is not
        spool_root = str(tmp_path / "spool")
        dirs = [d for d in os.listdir(spool_root)
                if d.startswith("rc")]
        assert len(dirs) == 1
        r2 = dqr.execute(SQL)
        assert _detail(dqr)["resultCached"] is True
        # capacity 1: a second statement evicts the first entry AND
        # removes its rc directory
        dqr.execute("select count(*) as c from nation")
        dqr.execute("select count(*) as c from nation")
        assert _detail(dqr)["resultCached"] is True
        dirs_after = [d for d in os.listdir(spool_root)
                      if d.startswith("rc")]
        assert len(dirs_after) == 1
        assert dirs_after != dirs
        assert resultcache.stats()["evictions"] >= 1


def test_concurrent_repeats_all_exact(tmp_path):
    """4 clients hammering the same two statements: every response is
    exact whether it came from execution or the cache, and hits
    dominate after warmup."""
    with DistributedQueryRunner.tpch(scale=0.01, n_workers=2,
                                     config=_cfg(tmp_path)) as dqr:
        def norm(rows):
            return sorted(
                tuple(round(v, 6) if isinstance(v, float) else v
                      for v in r) for r in rows)

        sqls = [SQL, "select count(*) as c from orders"]
        expected = [norm(dqr.execute(s).rows) for s in sqls]
        failures = []

        def loop(i):
            client = dqr.new_client(user=f"hot{i}")
            try:
                for j in range(6):
                    s = sqls[(i + j) % len(sqls)]
                    _cols, data = client.execute(s)
                    if norm(tuple(r) for r in data) != \
                            expected[(i + j) % len(sqls)]:
                        failures.append((i, s))
            except Exception as e:  # noqa: BLE001
                failures.append((i, repr(e)))

        threads = [threading.Thread(target=loop, args=(i,),
                                    daemon=True) for i in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        assert not failures, failures
        assert resultcache.stats()["hits"] >= 2


def test_vanished_entry_falls_back_to_execution(tmp_path):
    """An entry whose spool pages vanished under it (eviction raced the
    lookup / operator deleted the spool root) must NOT fail or hang
    the query: the stalled drain gives up after exchange_spool_stall_s,
    the entry is invalidated, and the statement re-executes normally
    with exact rows."""
    import shutil

    cfg = _cfg(tmp_path, exchange_spool_stall_s=1.0)
    with DistributedQueryRunner.tpch(scale=0.01, n_workers=2,
                                     config=cfg) as dqr:
        r1 = dqr.execute(SQL)
        assert resultcache.stats()["size"] == 1
        # yank the pages out from under the live entry
        import os

        spool_root = str(tmp_path / "spool")
        for d in os.listdir(spool_root):
            if d.startswith("rc"):
                shutil.rmtree(os.path.join(spool_root, d))
        r2 = dqr.execute(SQL)
        d2 = _detail(dqr)
        assert r2.rows == r1.rows
        assert d2["state"] == "FINISHED"
        assert d2["resultCached"] is False   # served by real execution
        assert dqr.coordinator.queries[d2["queryId"]]._tasks_scheduled
        st = resultcache.stats()
        assert st["evictions"] >= 1          # the dead entry was dropped
        # and the fresh execution re-admitted: next repeat hits again
        r3 = dqr.execute(SQL)
        assert r3.rows == r1.rows
        assert _detail(dqr)["resultCached"] is True


def test_object_tier_hit_byte_exact_under_read_faults(tmp_path):
    """The satellite pin: result-cache entries on the OBJECT spool
    tier re-serve byte-exact, and a transient faults.py spool-read
    error on the hit path retries on the error budget instead of
    failing the query."""
    inj = FaultInjector()
    cfg = _cfg(tmp_path, exchange_spool_tier="object")
    with DistributedQueryRunner.tpch(
            scale=0.01, n_workers=2, config=cfg,
            coordinator_injector=inj) as dqr:
        from presto_tpu.server.spool import ObjectStoreSpoolStore

        assert isinstance(dqr.coordinator.spool, ObjectStoreSpoolStore)
        r1 = dqr.execute(SQL)
        rule = inj.add_spool_rule(r"^rc", policy="spool-read-error",
                                  times=2)
        r2 = dqr.execute(SQL)
        assert r2.rows == r1.rows
        assert _detail(dqr)["resultCached"] is True
        assert rule.remaining == 0          # both faults really fired
        # eviction pressure on the object tier still re-serves the
        # survivor byte-exact
        r3 = dqr.execute(SQL)
        assert r3.rows == r1.rows


def test_nondeterministic_statements_never_cached(tmp_path):
    """ROADMAP 4i non-determinism guard: a statement containing a
    now()/current_timestamp/random()-family expression is rejected at
    cache admission (the analyzer-side predicate shared with the plan
    cache's keying module) and RE-EXECUTES on every repeat — the named
    blocker for ``result_cache_enabled`` default-ON."""
    from presto_tpu.sql import plancache

    # the predicate itself (shared with the plan-cache key path)
    assert plancache.has_nondeterministic_functions(
        "select now(), count(*) from t")
    assert plancache.has_nondeterministic_functions(
        "select current_timestamp")
    assert plancache.has_nondeterministic_functions(
        "select random() * 2")
    assert not plancache.has_nondeterministic_functions(
        "select 'now()' from t")        # inside a string literal
    assert not plancache.has_nondeterministic_functions(SQL)
    with DistributedQueryRunner.tpch(
            scale=0.01, n_workers=2, config=_cfg(tmp_path)) as dqr:
        nondet = ("select count(*) + 0 * cast(to_unixtime(now()) "
                  "as bigint) from lineitem")
        dqr.execute(nondet)
        assert resultcache.stats()["size"] == 0, \
            "non-deterministic statement must never be admitted"
        dqr.execute(nondet)
        d = _detail(dqr)
        assert d["resultCached"] is False
        assert dqr.coordinator.queries[d["queryId"]]._tasks_scheduled, \
            "repeat of a non-deterministic statement must re-execute"
        # deterministic control: same cluster, cache engages normally
        dqr.execute(SQL)
        dqr.execute(SQL)
        assert _detail(dqr)["resultCached"] is True
