"""EventListener SPI / QueryMonitor (SURVEY §5.5) — local tier, the
distributed event stream (coordinator EventBus, query.json listener,
retry/speculation events), and trace-token propagation."""

import dataclasses
import threading
import time

import pytest

from presto_tpu.config import DEFAULT
from presto_tpu.events import EventListener
from presto_tpu.localrunner import LocalQueryRunner


class Recorder(EventListener):
    def __init__(self):
        self.created = []
        self.completed = []
        self.stage_retries = []
        self.recoveries = []
        self.speculations = []

    def query_created(self, e):
        self.created.append(e)

    def query_completed(self, e):
        self.completed.append(e)

    def stage_retry(self, e):
        self.stage_retries.append(e)

    def task_recovery(self, e):
        self.recoveries.append(e)

    def speculation(self, e):
        self.speculations.append(e)


def test_events_fire_on_success():
    r = LocalQueryRunner.tpch(scale=0.001)
    rec = Recorder()
    r.event_bus.register(rec)
    r.execute("select count(*) from nation")
    assert len(rec.created) == 1 and len(rec.completed) == 1
    done = rec.completed[0]
    assert done.state == "FINISHED"
    assert done.output_rows == 1
    assert done.wall_s >= 0
    assert any(s["operator"].endswith("OutputCollector")
               for s in done.operator_stats)


def test_events_fire_on_failure():
    r = LocalQueryRunner.tpch(scale=0.001)
    rec = Recorder()
    r.event_bus.register(rec)
    try:
        r.execute("select no_col from nation")
    except Exception:
        pass
    assert rec.completed[0].state == "FAILED"
    assert rec.completed[0].error


def test_broken_listener_never_fails_query():
    class Broken(EventListener):
        def query_created(self, e):
            raise RuntimeError("observer bug")

    r = LocalQueryRunner.tpch(scale=0.001)
    r.event_bus.register(Broken())
    assert r.execute("select 1").rows == [(1,)]


def test_local_events_carry_trace_token_and_stage_stats():
    """The local tier reports its one task as one stage, so local and
    distributed QueryCompletedEvents share a shape."""
    r = LocalQueryRunner.tpch(scale=0.001)
    rec = Recorder()
    r.event_bus.register(rec)
    r.execute("select count(*) from nation")
    created, done = rec.created[0], rec.completed[0]
    assert created.trace_token.startswith("tt-")
    assert done.trace_token == created.trace_token
    assert len(done.stage_stats) == 1
    st = done.stage_stats[0]
    assert st["tasks"] == 1 and st["input_rows"] > 0
    assert st["wall_ns"] > 0
    # the DriverStats level below TaskStats was recorded per pipeline
    assert r._last_task.driver_stats
    assert all(d.operators >= 1 for d in r._last_task.driver_stats)


# ---------------------------------------------------------------------------
# distributed event stream
# ---------------------------------------------------------------------------

def _wait_nodes(co, n, timeout_s=5.0):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if len(co.nodes.alive_nodes()) == n:
            return
        time.sleep(0.02)
    raise AssertionError(f"cluster never reached {n} nodes")


def test_distributed_events_fire_from_dqr_run():
    """QueryCreated/QueryCompleted fire on the coordinator's EventBus
    for a 2-worker DQR run, with matching trace tokens and the
    stage-stats rollup aggregated from real remote task info."""
    from presto_tpu.server.dqr import DistributedQueryRunner

    rec = Recorder()
    with DistributedQueryRunner.tpch(scale=0.01, n_workers=2) as dqr:
        dqr.event_bus.register(rec)
        rows = dqr.execute(
            "select l_returnflag, count(*) from lineitem "
            "group by l_returnflag").rows
        assert len(rows) == 3
    assert rec.created and rec.completed
    done = rec.completed[0]
    assert done.state == "FINISHED" and done.error is None
    assert done.trace_token == rec.created[0].trace_token
    assert done.trace_token.startswith("tt-")
    assert done.output_rows == 3
    # rollup from REAL remote tasks: the leaf stage scanned lineitem
    # across 2 workers, the single stage merged it
    assert len(done.stage_stats) >= 2
    leaf = done.stage_stats[0]
    assert leaf["tasks"] == 2 and leaf["reporting"] == 2
    assert leaf["input_rows"] > 0 and leaf["wall_ns"] > 0
    assert done.peak_memory_bytes > 0


def test_distributed_events_fire_on_worker_failure():
    """A failed distributed query still completes the event stream:
    state FAILED, the error carries the trace token."""
    from presto_tpu.client import QueryFailed
    from presto_tpu.server.dqr import DistributedQueryRunner

    rec = Recorder()
    with DistributedQueryRunner.tpch(scale=0.001, n_workers=2) as dqr:
        dqr.event_bus.register(rec)
        with pytest.raises(QueryFailed):
            # the cast fails batch-side, i.e. on a worker task
            dqr.execute("select cast(n_name as bigint) from nation")
    done = [e for e in rec.completed if e.state == "FAILED"]
    assert done
    assert done[0].error and done[0].trace_token.startswith("tt-")


def test_trace_token_in_worker_error_surfaced_to_client(caplog):
    """Trace-token propagation (TraceTokenModule role): a worker-side
    task failure surfaces to the statement-protocol client stamped with
    the query's trace token, the same token is on the coordinator's
    query object and detail payload, and worker task-lifecycle log
    lines carry it."""
    import json
    import logging
    import urllib.request

    from presto_tpu.client import QueryFailed
    from presto_tpu.server.dqr import DistributedQueryRunner

    caplog.set_level(logging.INFO, logger="presto_tpu.worker")
    with DistributedQueryRunner.tpch(scale=0.001, n_workers=2) as dqr:
        with pytest.raises(QueryFailed) as exc_info:
            # the cast fails batch-side, i.e. on a worker task
            dqr.execute("select cast(n_name as bigint) from nation")
        q = list(dqr.coordinator.queries.values())[0]
        assert q.trace_token.startswith("tt-")
        # the worker stamped the token into the task error, which rode
        # the 500 body -> drain failure -> client-facing message
        assert f"[trace:{q.trace_token}]" in str(exc_info.value)
        with urllib.request.urlopen(
                f"{dqr.coordinator.uri}/v1/query/{q.query_id}",
                timeout=10) as resp:
            detail = json.loads(resp.read())
        assert detail["traceToken"] == q.trace_token
        assert f"[trace:{q.trace_token}]" in (detail["error"] or "")
        # worker task-lifecycle log lines are stamped with the token
        worker_lines = [r.getMessage() for r in caplog.records
                        if r.name == "presto_tpu.worker"]
        assert any(f"[trace:{q.trace_token}]" in ln
                   for ln in worker_lines), worker_lines


def test_client_supplied_trace_token_is_honored():
    """X-Presto-Trace-Token on POST /v1/statement wins over the
    generated token (the airlift behavior: use the caller's token when
    present so cross-system traces correlate)."""
    import json
    import urllib.request

    from presto_tpu.server.dqr import DistributedQueryRunner

    with DistributedQueryRunner.tpch(scale=0.001, n_workers=2) as dqr:
        req = urllib.request.Request(
            f"{dqr.coordinator.uri}/v1/statement",
            data=b"select 1", method="POST",
            headers={"X-Presto-Trace-Token": "caller-token-42"})
        with urllib.request.urlopen(req, timeout=10) as resp:
            qid = json.loads(resp.read())["id"]
        q = dqr.coordinator.queries[qid]
        assert q.trace_token == "caller-token-42"
        q.rows_done.wait(timeout=30)


@pytest.mark.chaos
def test_stage_retry_event_in_query_json_and_metrics():
    """The acceptance pin: a chaos run (non-leaf worker kill) produces
    a query.json event log containing a StageRetryEvent whose trace
    token matches the query's, and /metrics on the coordinator reports
    the retry counter."""
    import urllib.request

    from presto_tpu.events import read_event_log
    from presto_tpu.server.dqr import DistributedQueryRunner
    from presto_tpu.server.faults import FaultInjector

    cfg = dataclasses.replace(DEFAULT, task_recovery_interval_s=0.05)
    inj = FaultInjector()
    inj.add_rule(r"/results/", method="GET", policy="drop-connection")
    import tempfile

    log_path = tempfile.mktemp(suffix="-query.json")
    rec = Recorder()
    with DistributedQueryRunner.tpch(
            scale=0.01, n_workers=2, config=cfg,
            worker_injectors={1: inj},
            heartbeat_interval_s=0.05, heartbeat_max_missed=2,
            event_log_path=log_path) as dqr:
        co = dqr.coordinator
        dqr.event_bus.register(rec)
        _wait_nodes(co, 2)
        res = {}

        def run():
            try:
                res["rows"] = dqr.execute(
                    "select n_name, count(*) from nation join region "
                    "on n_regionkey = r_regionkey group by n_name").rows
            except Exception as e:  # noqa: BLE001
                res["err"] = e

        t = threading.Thread(target=run)
        t.start()
        # wait until a NON-leaf task lands on the victim, then kill it
        victim_uri = dqr.workers[1].uri
        deadline = time.monotonic() + 15.0
        while time.monotonic() < deadline:
            qs = list(co.queries.values())
            if qs and qs[0]._dplan is not None and any(
                    u == victim_uri
                    and qs[0]._dplan.fragments[f].consumed_fragments
                    for f, _, u in qs[0]._placements):
                break
            time.sleep(0.02)
        dqr.kill_worker(1)
        q = list(co.queries.values())[0]
        t.join(timeout=120)
        assert not t.is_alive() and "err" not in res, res
        assert q.stage_retry_rounds >= 1
        # in-process listener saw the retry with the query's token
        assert rec.stage_retries
        assert rec.stage_retries[0].trace_token == q.trace_token
        assert rec.stage_retries[0].fragment_ids
        # the query.json log has the same event, replayable
        events = read_event_log(log_path)
        retries = [e for e in events if e["event"] == "StageRetryEvent"]
        assert retries, [e["event"] for e in events]
        assert retries[0]["trace_token"] == q.trace_token
        assert retries[0]["query_id"] == q.query_id
        done = [e for e in events
                if e["event"] == "QueryCompletedEvent"]
        assert done and done[0]["trace_token"] == q.trace_token
        # /metrics reports the retry counter (Prometheus text plane)
        with urllib.request.urlopen(f"{co.uri}/metrics",
                                    timeout=5) as resp:
            metrics = resp.read().decode()
        line = next(ln for ln in metrics.splitlines()
                    if ln.startswith("presto_stage_retry_rounds_total "))
        assert float(line.split()[-1]) >= 1
        assert "presto_queries" in metrics
    import os

    os.remove(log_path)


def test_worker_metrics_endpoint():
    """Worker /metrics: task states, exchange page counters, memory."""
    import urllib.request

    from presto_tpu.server.dqr import DistributedQueryRunner

    with DistributedQueryRunner.tpch(scale=0.01, n_workers=2) as dqr:
        assert dqr.execute(
            "select count(*) from lineitem").rows == [(59785,)]
        texts = []
        for w in dqr.workers:
            with urllib.request.urlopen(f"{w.uri}/metrics",
                                        timeout=5) as resp:
                assert resp.status == 200
                assert "text/plain" in resp.headers["Content-Type"]
                texts.append(resp.read().decode())
    joined = "\n".join(texts)
    assert 'presto_worker_tasks{state="FINISHED"}' in joined
    assert "presto_worker_output_pages_total" in joined
    # the single-stage consumer fetched real exchange pages
    import re

    consumed = [
        float(m.group(1)) for m in re.finditer(
            r'presto_worker_exchange_pages_total\{kind="consumed"\} '
            r'([0-9.]+)', joined)]
    assert sum(consumed) > 0, joined
    assert "presto_worker_jit_total" in joined


def test_spool_counters_in_stats_rollup_and_metrics():
    """Spooled-exchange observability: with write-through spooling on
    (the default) a mesh query reports per-stage spooled-page counts in
    the PR 6 stats rollup (/v1/query/{id} stageStats + queryStats),
    system.runtime.queries carries the spooled_pages column, and both
    metrics planes export presto_spool_bytes_written/read/evicted_total."""
    import json
    import urllib.request

    from presto_tpu.server.dqr import DistributedQueryRunner

    with DistributedQueryRunner.tpch(scale=0.01, n_workers=2) as dqr:
        assert dqr.execute(
            "select count(*) from lineitem").rows == [(59785,)]
        co = dqr.coordinator
        qid = list(co.queries)[0]
        with urllib.request.urlopen(f"{co.uri}/v1/query/{qid}",
                                    timeout=5) as resp:
            detail = json.loads(resp.read())
        # every producing stage wrote its pages through to the spool
        stage_spooled = {fid: st["pages_spooled"]
                         for fid, st in detail["stageStats"].items()}
        assert sum(stage_spooled.values()) > 0, detail["stageStats"]
        assert detail["queryStats"]["pages_spooled"] == \
            sum(stage_spooled.values())
        assert detail["producerReruns"] == 0
        # system.runtime.queries surfaces the same rollup as SQL
        rows = dqr.execute(
            "select spooled_pages, producer_reruns from "
            "system.runtime.queries where query_id = "
            f"'{qid}'").rows
        assert rows and rows[0][0] >= sum(stage_spooled.values())
        assert rows[0][1] == 0
        # worker /metrics: write-through bytes counted
        wrote = 0.0
        for w in dqr.workers:
            with urllib.request.urlopen(f"{w.uri}/metrics",
                                        timeout=5) as resp:
                text = resp.read().decode()
            assert "presto_worker_spool_bytes_evicted_total" in text
            line = next(ln for ln in text.splitlines() if ln.startswith(
                "presto_worker_spool_bytes_written_total "))
            wrote += float(line.split()[-1])
        assert wrote > 0
        # coordinator /metrics: spool + producer-rerun families present
        with urllib.request.urlopen(f"{co.uri}/metrics",
                                    timeout=5) as resp:
            text = resp.read().decode()
        assert "presto_spool_bytes_read_total" in text
        line = next(ln for ln in text.splitlines() if ln.startswith(
            "presto_producer_reruns_total "))
        assert float(line.split()[-1]) == 0


def test_json_lines_listener_swallows_bad_path():
    """An unwritable event log must never fail a query (observers are
    isolated, the EventBus contract)."""
    from presto_tpu.events import (
        JsonLinesEventListener, QueryCreatedEvent,
    )

    r = LocalQueryRunner.tpch(scale=0.001)
    r.event_bus.register(
        JsonLinesEventListener("/nonexistent-dir/query.json"))
    assert r.execute("select 1").rows == [(1,)]
    # direct call also swallows nothing — the bus does the isolation;
    # the listener itself raises
    lst = JsonLinesEventListener("/nonexistent-dir/query.json")
    with pytest.raises(OSError):
        lst.query_created(QueryCreatedEvent("q", "u", "s", 0.0))
