"""EventListener SPI / QueryMonitor (SURVEY §5.5)."""

from presto_tpu.events import EventListener
from presto_tpu.localrunner import LocalQueryRunner


class Recorder(EventListener):
    def __init__(self):
        self.created = []
        self.completed = []

    def query_created(self, e):
        self.created.append(e)

    def query_completed(self, e):
        self.completed.append(e)


def test_events_fire_on_success():
    r = LocalQueryRunner.tpch(scale=0.001)
    rec = Recorder()
    r.event_bus.register(rec)
    r.execute("select count(*) from nation")
    assert len(rec.created) == 1 and len(rec.completed) == 1
    done = rec.completed[0]
    assert done.state == "FINISHED"
    assert done.output_rows == 1
    assert done.wall_s >= 0
    assert any(s["operator"].endswith("OutputCollector")
               for s in done.operator_stats)


def test_events_fire_on_failure():
    r = LocalQueryRunner.tpch(scale=0.001)
    rec = Recorder()
    r.event_bus.register(rec)
    try:
        r.execute("select no_col from nation")
    except Exception:
        pass
    assert rec.completed[0].state == "FAILED"
    assert rec.completed[0].error


def test_broken_listener_never_fails_query():
    class Broken(EventListener):
        def query_created(self, e):
            raise RuntimeError("observer bug")

    r = LocalQueryRunner.tpch(scale=0.001)
    r.event_bus.register(Broken())
    assert r.execute("select 1").rows == [(1,)]
