"""Whole-query XLA execution through LocalQueryRunner: supported
queries compile into one cached program (warm = one dispatch),
unsupported shapes and mutable tables keep full correctness."""

import pytest

from presto_tpu.config import EngineConfig
from presto_tpu.localrunner import LocalQueryRunner

pytestmark = pytest.mark.slow  # virtual-mesh lowering is compile-heavy


@pytest.fixture(scope="module")
def wq():
    return LocalQueryRunner.tpch(scale=0.005, config=EngineConfig(
        whole_query_execution=True))


@pytest.fixture(scope="module")
def base():
    return LocalQueryRunner.tpch(scale=0.005)


def same(a, b):
    assert len(a.rows) == len(b.rows)
    for x, y in zip(sorted(a.rows, key=repr), sorted(b.rows, key=repr)):
        for u, v in zip(x, y):
            if isinstance(u, float):
                assert u == pytest.approx(v, rel=1e-6), (x, y)
            else:
                assert u == v, (x, y)


def test_join_agg_matches_and_caches(wq, base):
    import time

    sql = ("select c_mktsegment, count(*), sum(o_totalprice) "
           "from customer join orders on c_custkey = o_custkey "
           "group by c_mktsegment")
    a = wq.execute(sql)
    same(a, base.execute(sql))
    t0 = time.time()
    b = wq.execute(sql)
    warm = time.time() - t0
    assert sorted(a.rows, key=repr) == sorted(b.rows, key=repr)
    assert warm < 2.0, warm


def test_unsupported_falls_back_to_operators(wq, base):
    sql = ("select o_custkey, row_number() over (order by o_orderkey) "
           "from orders where o_custkey < 5")
    same(wq.execute(sql), base.execute(sql))


def test_mutable_table_not_served_stale(wq):
    wq.execute("create table memory.wqt (a bigint)")
    wq.execute("insert into memory.wqt values (1), (2)")
    assert wq.execute("select count(*) from memory.wqt").rows == [(2,)]
    wq.execute("insert into memory.wqt values (3)")
    assert wq.execute("select count(*) from memory.wqt").rows == [(3,)]


def test_many_programs_coexist_and_rerun(wq, base):
    """Several compiled whole-query programs in one process, each
    re-executed warm (regression: a module-level jnp sentinel imported
    lazily INSIDE a trace became a leaked tracer baked into every later
    program as a phantom parameter)."""
    queries = [
        "select count(*), sum(l_quantity) from lineitem",
        "select o_orderpriority, count(*) from orders "
        "group by o_orderpriority",
        "select c_mktsegment, count(*) from customer "
        "join orders on c_custkey = o_custkey group by c_mktsegment",
        "select n_name, count(*) from nation join customer "
        "on n_nationkey = c_nationkey group by n_name",
    ]
    first = [wq.execute(q).rows for q in queries]
    # warm re-execution of EVERY program after all traces exist
    for q, want in zip(queries, first):
        again = wq.execute(q).rows
        assert sorted(again, key=repr) == sorted(want, key=repr)
        same(wq.execute(q), base.execute(q))
