"""TPC-DS full-suite conformance: the standard 99-query set vs the
sqlite oracle (H2QueryRunner role at TPC-DS breadth, VERDICT r3 #5).

Query texts in tests/tpcds_suite/ are the standard TPC-DS benchmark SQL
(the reference ships the same texts as benchto resources,
presto-benchto-benchmarks/src/main/resources/sql/presto/tpcds/); the
MANIFEST records the round-4 sweep: 85 value-verified against sqlite,
8 more execute correctly but sqlite cannot check them (no ROLLUP /
GROUPING) — those run engine-only (plan + execute + sane shape).
Remaining exclusions are xfailed by named feature below.
"""

import os
import sqlite3

import pytest

from presto_tpu.localrunner import LocalQueryRunner

pytestmark = pytest.mark.slow

from test_tpch_conformance import (  # noqa: E402
    _sqlite_type, _to_sqlite, assert_rows_match, register_sqlite_fns,
    to_sqlite_sql,
)
from tpcds_suite.MANIFEST import ENGINE_ONLY, PASSING  # noqa: E402

SCALE = 0.003
_DIR = os.path.join(os.path.dirname(__file__), "tpcds_suite")

# engine gaps, by named feature: NONE as of round 5 (the round-4 ledger —
# correlated-CTE scoping, ORDER-BY-alias-of-grouping()-CASE, UNION alias
# scoping, select-list alias self-reference, non-equality correlation,
# and the q75 "set-op dedup" mismatch, which turned out to be a sqlite
# ORACLE bug: CAST(cnt AS DECIMAL)/CAST(cnt AS DECIMAL) integer-divided
# in sqlite NUMERIC affinity, wrongly passing the < 0.9 filter — all
# fixed or root-caused in round 5)
XFAIL: dict = {}


@pytest.fixture(scope="module")
def runner():
    return LocalQueryRunner.tpch(scale=SCALE)


@pytest.fixture(scope="module")
def oracle(runner):
    conn = sqlite3.connect(":memory:")
    conn.execute("PRAGMA case_sensitive_like = ON")
    register_sqlite_fns(conn)
    tpcds = runner.registry.get("tpcds")
    for table in tpcds.list_tables():
        handle = tpcds.get_table(table)
        schema = tpcds.table_schema(handle)
        names = schema.column_names()
        cols_sql = ", ".join(f"{n} {_sqlite_type(schema.column_type(n))}"
                             for n in names)
        conn.execute(f"create table {table} ({cols_sql})")
        for split in tpcds.get_splits(handle, 1):
            for batch in tpcds.page_source(split, names, 1 << 20):
                rows = [tuple(_to_sqlite(v) for v in r)
                        for r in batch.to_pylist()]
                ph = ", ".join("?" * len(names))
                conn.executemany(
                    f"insert into {table} values ({ph})", rows)
        for n in names:
            if n.endswith("_sk"):
                conn.execute(
                    f"create index ix_{table}_{n} on {table}({n})")
    conn.commit()
    return conn


@pytest.mark.parametrize("qn", sorted(PASSING))
def test_tpcds_query_vs_oracle(runner, oracle, qn):
    sql = open(os.path.join(_DIR, f"q{qn}.sql")).read()
    got = runner.execute(sql)
    # ROLLUP/GROUPING queries carry a hand-derived sqlite variant in
    # oracle/ (grouping levels expanded to UNION ALL, GROUPING() as
    # per-level constants) — sqlite supports neither construct directly
    variant = os.path.join(_DIR, "oracle", f"q{qn}.sql")
    osql = (open(variant).read() if os.path.exists(variant)
            else sql.replace("tpcds.", ""))
    want = oracle.execute(to_sqlite_sql(osql)).fetchall()
    assert_rows_match(got.rows, want, "order by" in sql.lower())


@pytest.mark.parametrize("qn", sorted(ENGINE_ONLY))
def test_tpcds_rollup_queries_execute(runner, qn):
    """sqlite cannot value-check ROLLUP/GROUPING shapes; the engine's
    grouping-sets semantics are value-verified separately (grouping()
    unit tests + the rollup conformance in test_tpcds_conformance)."""
    sql = open(os.path.join(_DIR, f"q{qn}.sql")).read()
    res = runner.execute(sql)
    assert res.column_names


@pytest.mark.parametrize("qn", sorted(XFAIL))
def test_tpcds_known_gaps(runner, qn):
    pytest.xfail(XFAIL[qn])
