"""Window functions + set operations vs a pure-Python oracle.

Mirrors the reference's window coverage (TestWindowOperator.java +
AbstractTestWindowQueries) at the SQL level: results of windowed queries on
TPC-H data are compared against an independent row-at-a-time Python
evaluation of the same window semantics.
"""

import math
from collections import defaultdict

import pytest

from presto_tpu.localrunner import LocalQueryRunner


@pytest.fixture(scope="module")
def runner():
    return LocalQueryRunner.tpch(scale=0.001)


def fetch(runner, sql):
    return runner.execute(sql).rows


def by_partition(rows, part_idx, order_key):
    parts = defaultdict(list)
    for row in rows:
        parts[tuple(row[i] for i in part_idx)].append(row)
    for p in parts.values():
        p.sort(key=order_key)
    return parts


class TestRanking:
    def test_row_number_rank_dense_rank(self, runner):
        rows = fetch(runner, """
            select o_custkey, o_totalprice, o_orderkey,
                   row_number() over (partition by o_custkey
                                      order by o_totalprice desc) rn,
                   rank() over (partition by o_custkey
                                order by o_totalprice desc) rk,
                   dense_rank() over (partition by o_custkey
                                      order by o_totalprice desc) dr
            from orders""")
        parts = by_partition(rows, [0], lambda r: -r[1])
        for p in parts.values():
            expect_rn = 0
            expect_rank = 0
            expect_dense = 0
            prev_price = None
            for i, row in enumerate(p):
                expect_rn = i + 1
                if row[1] != prev_price:
                    expect_rank = i + 1
                    expect_dense += 1
                    prev_price = row[1]
                assert row[3] == expect_rn
                assert row[4] == expect_rank
                assert row[5] == expect_dense

    def test_percent_rank_cume_dist(self, runner):
        rows = fetch(runner, """
            select n_regionkey, n_nationkey,
                   percent_rank() over (partition by n_regionkey
                                        order by n_nationkey) pr,
                   cume_dist() over (partition by n_regionkey
                                     order by n_nationkey) cd
            from nation""")
        parts = by_partition(rows, [0], lambda r: r[1])
        for p in parts.values():
            n = len(p)
            for i, row in enumerate(p):
                want_pr = 0.0 if n == 1 else i / (n - 1)
                want_cd = (i + 1) / n
                assert math.isclose(row[2], want_pr), (row, want_pr)
                assert math.isclose(row[3], want_cd), (row, want_cd)

    def test_ntile(self, runner):
        rows = fetch(runner, """
            select o_orderkey,
                   ntile(4) over (order by o_orderkey) nt
            from orders limit 1000000""")
        rows.sort(key=lambda r: r[0])
        n = len(rows)
        base, rem = divmod(n, 4)
        sizes = [base + 1] * rem + [base] * (4 - rem)
        want = []
        for b, size in enumerate(sizes):
            want += [b + 1] * size
        assert [r[1] for r in rows] == want


class TestValueFunctions:
    def test_lag_lead(self, runner):
        rows = fetch(runner, """
            select o_custkey, o_orderkey, o_totalprice,
                   lag(o_totalprice) over (partition by o_custkey
                                           order by o_orderkey) lg,
                   lead(o_totalprice, 2) over (partition by o_custkey
                                               order by o_orderkey) ld,
                   lag(o_totalprice, 1, -1.0) over (partition by o_custkey
                                                    order by o_orderkey) lgd
            from orders""")
        parts = by_partition(rows, [0], lambda r: r[1])
        for p in parts.values():
            for i, row in enumerate(p):
                want_lag = p[i - 1][2] if i >= 1 else None
                want_lead = p[i + 2][2] if i + 2 < len(p) else None
                want_lagd = p[i - 1][2] if i >= 1 else -1.0
                assert row[3] == want_lag
                assert row[4] == want_lead
                assert row[5] == want_lagd

    def test_first_last_nth(self, runner):
        rows = fetch(runner, """
            select o_custkey, o_orderkey,
                   first_value(o_orderkey) over (partition by o_custkey
                                                 order by o_orderkey) fv,
                   last_value(o_orderkey) over (partition by o_custkey
                        order by o_orderkey
                        rows between unbounded preceding
                        and unbounded following) lv,
                   nth_value(o_orderkey, 2) over (partition by o_custkey
                                                  order by o_orderkey) nv
            from orders""")
        parts = by_partition(rows, [0], lambda r: r[1])
        for p in parts.values():
            keys = [r[1] for r in p]
            for i, row in enumerate(p):
                assert row[2] == keys[0]
                assert row[3] == keys[-1]
                # nth_value over default frame: NULL until 2 rows in frame
                want_nv = keys[1] if i >= 1 and len(keys) >= 2 else None
                assert row[4] == want_nv


class TestWindowAggregates:
    def test_running_sum_count_avg(self, runner):
        rows = fetch(runner, """
            select o_custkey, o_orderkey, o_totalprice,
                   sum(o_totalprice) over (partition by o_custkey
                                           order by o_orderkey) rsum,
                   count(*) over (partition by o_custkey
                                  order by o_orderkey) rcnt,
                   avg(o_totalprice) over (partition by o_custkey
                                           order by o_orderkey) ravg
            from orders""")
        parts = by_partition(rows, [0], lambda r: r[1])
        for p in parts.values():
            run = 0.0
            for i, row in enumerate(p):
                run += row[2]
                assert math.isclose(row[3], run, rel_tol=1e-9)
                assert row[4] == i + 1
                assert math.isclose(row[5], run / (i + 1), rel_tol=1e-9)

    def test_partition_total(self, runner):
        rows = fetch(runner, """
            select n_regionkey, n_nationkey,
                   sum(n_nationkey) over (partition by n_regionkey) tot,
                   max(n_nationkey) over (partition by n_regionkey) mx,
                   min(n_nationkey) over (partition by n_regionkey) mn
            from nation""")
        parts = by_partition(rows, [0], lambda r: r[1])
        for p in parts.values():
            keys = [r[1] for r in p]
            for row in p:
                assert row[2] == sum(keys)
                assert row[3] == max(keys)
                assert row[4] == min(keys)

    def test_rows_frame_moving_sum(self, runner):
        rows = fetch(runner, """
            select o_custkey, o_orderkey, o_totalprice,
                   sum(o_totalprice) over (partition by o_custkey
                        order by o_orderkey
                        rows between 2 preceding and current row) ms
            from orders""")
        parts = by_partition(rows, [0], lambda r: r[1])
        for p in parts.values():
            for i, row in enumerate(p):
                want = sum(r[2] for r in p[max(0, i - 2):i + 1])
                assert math.isclose(row[3], want, rel_tol=1e-9)

    def test_rows_frame_moving_minmax(self, runner):
        """Bounded N PRECEDING frame starts for min/max (the sparse-table
        range-extremum path)."""
        rows = fetch(runner, """
            select o_custkey, o_orderkey, o_totalprice,
                   min(o_totalprice) over (partition by o_custkey
                        order by o_orderkey
                        rows between 2 preceding and current row) mn,
                   max(o_totalprice) over (partition by o_custkey
                        order by o_orderkey
                        rows between 3 preceding and 1 preceding) mx
            from orders""")
        parts = by_partition(rows, [0], lambda r: r[1])
        for p in parts.values():
            for i, row in enumerate(p):
                mn_want = min(r[2] for r in p[max(0, i - 2):i + 1])
                assert math.isclose(row[3], mn_want, rel_tol=1e-9), (
                    row, mn_want)
                window = p[max(0, i - 3):i]
                if window:
                    mx_want = max(r[2] for r in window)
                    assert math.isclose(row[4], mx_want, rel_tol=1e-9), (
                        row, mx_want)
                else:
                    assert row[4] is None, row

    def test_range_frame_peers(self, runner):
        # RANGE (default) includes the whole peer group in the running sum
        rows = fetch(runner, """
            select l_orderkey, l_quantity,
                   sum(l_quantity) over (order by l_quantity) s
            from lineitem where l_orderkey < 200""")
        rows.sort(key=lambda r: r[1])
        total_by_qty = defaultdict(float)
        for r in rows:
            total_by_qty[r[1]] += r[1]
        run = 0.0
        want = {}
        for qty in sorted(total_by_qty):
            run += total_by_qty[qty]
            want[qty] = run
        for r in rows:
            assert math.isclose(r[2], want[r[1]], rel_tol=1e-9), r

    def test_windowed_aggregate_of_aggregate(self, runner):
        rows = fetch(runner, """
            select o_orderpriority, count(*) c,
                   sum(count(*)) over () total
            from orders group by o_orderpriority""")
        total = sum(r[1] for r in rows)
        for r in rows:
            assert r[2] == total


class TestSetOperations:
    def test_union_all_vs_distinct(self, runner):
        all_rows = fetch(runner, """
            select n_regionkey from nation union all
            select r_regionkey from region""")
        assert len(all_rows) == 30  # 25 nations + 5 regions
        dist = fetch(runner, """
            select n_regionkey from nation union
            select r_regionkey from region""")
        assert sorted(r[0] for r in dist) == [0, 1, 2, 3, 4]

    def test_union_type_coercion(self, runner):
        rows = fetch(runner, """
            select 1 x union all select 2.5 union all select 3""")
        assert sorted(r[0] for r in rows) == [1.0, 2.5, 3.0]
        assert all(isinstance(r[0], float) for r in rows)

    def test_intersect(self, runner):
        rows = fetch(runner, """
            select n_regionkey from nation where n_regionkey < 3
            intersect
            select r_regionkey from region""")
        assert sorted(r[0] for r in rows) == [0, 1, 2]

    def test_except(self, runner):
        rows = fetch(runner, """
            select r_regionkey from region
            except
            select n_regionkey from nation where n_regionkey < 2""")
        assert sorted(r[0] for r in rows) == [2, 3, 4]

    def test_set_op_order_and_limit(self, runner):
        rows = fetch(runner, """
            select n_name nm from nation union all
            select r_name from region
            order by nm desc limit 3""")
        assert len(rows) == 3
        assert rows[0][0] >= rows[1][0] >= rows[2][0]

    def test_union_in_subquery(self, runner):
        rows = fetch(runner, """
            select count(*) from (
                select n_regionkey k from nation
                union select 99 from region
            ) t""")
        assert rows[0][0] == 6  # 5 distinct region keys + 99

    def test_intersect_precedence(self, runner):
        # INTERSECT binds tighter than UNION
        rows = fetch(runner, """
            select 1 x union select 2 intersect select 2""")
        assert sorted(r[0] for r in rows) == [1, 2]


class TestGroupingSets:
    """ROLLUP / CUBE / GROUPING SETS (GroupIdOperator role)."""

    def test_rollup(self, runner):
        rows = fetch(runner, """
            select n_regionkey, n_nationkey, count(*) c from nation
            where n_regionkey < 2
            group by rollup (n_regionkey, n_nationkey) order by 1, 2""")
        per_nation = [r for r in rows if r[1] is not None]
        subtotals = [r for r in rows if r[1] is None and r[0] is not None]
        grand = [r for r in rows if r[0] is None and r[1] is None]
        assert len(per_nation) == 10 and all(r[2] == 1 for r in per_nation)
        assert sorted(subtotals) == [(0, None, 5), (1, None, 5)]
        assert grand == [(None, None, 10)]

    def test_cube(self, runner):
        rows = fetch(runner, """
            select n_regionkey, count(*) from nation
            group by cube (n_regionkey) order by 1""")
        assert rows[-1] == (None, 25)
        assert len(rows) == 6

    def test_grouping_sets_explicit(self, runner):
        rows = fetch(runner, """
            select r_regionkey, r_name, count(*) from region
            group by grouping sets ((r_regionkey), (r_name), ())""")
        by_key = [r for r in rows if r[0] is not None]
        by_name = [r for r in rows if r[1] is not None]
        total = [r for r in rows if r[0] is None and r[1] is None]
        assert len(by_key) == 5 and len(by_name) == 5
        assert total == [(None, None, 5)]

    def test_rollup_with_aggregates(self, runner):
        rows = fetch(runner, """
            select l_returnflag, sum(l_quantity) q, count(*) c
            from lineitem group by rollup (l_returnflag) order by 1""")
        detail = [r for r in rows if r[0] is not None]
        grand = [r for r in rows if r[0] is None]
        assert len(grand) == 1
        assert abs(grand[0][1] - sum(r[1] for r in detail)) < 1e-6
        assert grand[0][2] == sum(r[2] for r in detail)


class TestTopNRowNumber:
    def test_fused_matches_unfused(self, runner):
        """row_number() <= N over a subquery lowers to the fused
        TopNRowNumber operator (TopNRowNumberOperator.java:38) with
        identical results to the plain window + filter."""
        sql = """
            select o_custkey, o_orderkey, rn from (
                select o_custkey, o_orderkey,
                       row_number() over (partition by o_custkey
                                          order by o_totalprice desc) rn
                from orders) t
            where rn <= 2"""
        rows = fetch(runner, sql)
        stats = runner._last_task.operator_stats
        assert any("TopNRowNumber" in s.operator for s in stats), \
            [s.operator for s in stats]
        # oracle: recompute with the plain python path
        base = fetch(runner, """
            select o_custkey, o_orderkey, o_totalprice from orders""")
        parts = by_partition(base, [0], lambda r: (-r[2], r[1]))
        want = set()
        for key, p in parts.items():
            for i, r in enumerate(p[:2]):
                want.add((r[0], r[1], i + 1))
        assert set(rows) == want

    def test_rn_equals_one(self, runner):
        sql = """
            select o_custkey, o_orderkey from (
                select o_custkey, o_orderkey,
                       row_number() over (partition by o_custkey
                                          order by o_orderkey) rn
                from orders) t
            where rn = 1"""
        rows = fetch(runner, sql)
        base = fetch(runner, "select o_custkey, o_orderkey from orders")
        parts = by_partition(base, [0], lambda r: r[1])
        want = {(k[0], p[0][1]) for k, p in parts.items()}
        assert set(rows) == want
