"""Aggregate breadth: approx_distinct (HLL), approx_percentile,
corr/covar/regr, geometric_mean, checksum — single-node and the
partial/final merge path.

Reference models: ApproximateCountDistinctAggregation (HLL state),
ApproximateDoublePercentileAggregations, DoubleCovariance/
DoubleRegressionAggregation, GeometricMeanAggregations,
ChecksumAggregationFunction (presto-main/.../operator/aggregation/)."""

import math

import numpy as np
import pytest

from presto_tpu.localrunner import LocalQueryRunner


@pytest.fixture(scope="module")
def runner():
    return LocalQueryRunner.tpch(scale=0.01)


def q1(runner, sql):
    rows = runner.execute(sql).rows
    assert len(rows) == 1
    return rows[0]


class TestHll:
    def test_sketch_accuracy(self):
        from presto_tpu.sketch import HyperLogLog

        h = HyperLogLog()
        h.add_many(range(50_000))
        est = h.cardinality()
        assert abs(est - 50_000) / 50_000 < 0.05

    def test_sketch_merge_equals_union(self):
        from presto_tpu.sketch import HyperLogLog

        a, b, u = HyperLogLog(), HyperLogLog(), HyperLogLog()
        a.add_many(range(0, 6000))
        b.add_many(range(3000, 9000))
        u.add_many(range(0, 9000))
        a.merge(HyperLogLog.deserialize(b.serialize()))
        assert a.cardinality() == u.cardinality()

    def test_sql_accuracy(self, runner):
        ad, ex = q1(runner, "select approx_distinct(l_orderkey), "
                            "count(distinct l_orderkey) from lineitem")
        assert abs(ad - ex) / ex < 0.05

    def test_grouped(self, runner):
        rows = runner.execute(
            "select l_returnflag, approx_distinct(l_suppkey), "
            "count(distinct l_suppkey) from lineitem "
            "group by l_returnflag").rows
        for _, ad, ex in rows:
            assert abs(ad - ex) / ex < 0.1

    def test_strings(self, runner):
        ad, ex = q1(runner, "select approx_distinct(o_orderpriority), "
                            "count(distinct o_orderpriority) from orders")
        assert ad == ex  # tiny cardinality: exact in linear-counting range

    def test_varbinary_input_not_mistaken_for_merge(self, runner):
        # input type == sketch state type (varbinary): must still
        # ACCUMULATE, not merge raw values as sketches
        (ad,) = q1(runner, "select approx_distinct(to_utf8("
                           "o_orderpriority)) from orders")
        assert ad == 5


class TestPercentile:
    def test_median_rank_accuracy(self, runner):
        (p50,) = q1(runner,
                    "select approx_percentile(l_quantity, 0.5) "
                    "from lineitem")
        # sketch-backed (KLL): approximate by design, like the
        # reference's qdigest — check the RANK error, not exact equality
        from presto_tpu.connectors.tpch import TpchConnector

        conn = TpchConnector(scale=0.01)
        h = conn.get_table("lineitem")
        s = conn.get_splits(h, 1)[0]
        b = next(iter(conn.page_source(s, ["l_quantity"], 1 << 22)))
        vals = np.asarray(b.columns[0].values)[:b.num_rows]
        rank_err = abs(float((vals <= p50).mean()) - 0.5)
        assert rank_err < 0.03, (p50, rank_err)

    def test_two_percentiles(self, runner):
        p50, p90 = q1(runner, "select approx_percentile(l_quantity, 0.5), "
                              "approx_percentile(l_quantity, 0.9) "
                              "from lineitem")
        assert p50 < p90


class TestStatistics:
    def test_corr_matches_numpy(self, runner):
        from presto_tpu.connectors.tpch import TpchConnector

        conn = TpchConnector(scale=0.01)
        h = conn.get_table("lineitem")
        s = conn.get_splits(h, 1)[0]
        b = next(iter(conn.page_source(
            s, ["l_quantity", "l_extendedprice"], 1 << 22)))
        x = np.asarray(b.columns[0].values)[:b.num_rows].astype(float)
        y = np.asarray(b.columns[1].values)[:b.num_rows].astype(float)
        (got,) = q1(runner, "select corr(l_quantity, l_extendedprice) "
                            "from lineitem")
        assert abs(got - np.corrcoef(x, y)[0, 1]) < 1e-9

    def test_covar(self, runner):
        cs, cp = q1(runner,
                    "select covar_samp(x, y), covar_pop(x, y) from "
                    "(values (1.0,2.0),(2.0,4.0),(3.0,5.0)) t(x,y)")
        x = np.array([1.0, 2.0, 3.0])
        y = np.array([2.0, 4.0, 5.0])
        assert abs(cs - np.cov(x, y, ddof=1)[0, 1]) < 1e-12
        assert abs(cp - np.cov(x, y, ddof=0)[0, 1]) < 1e-12

    def test_regression(self, runner):
        slope, icept = q1(
            runner, "select regr_slope(y, x), regr_intercept(y, x) from "
                    "(values (1.0,10.0),(2.0,20.0),(3.0,30.0)) t(x,y)")
        assert abs(slope - 10.0) < 1e-12 and abs(icept) < 1e-9

    def test_geometric_mean(self, runner):
        (gm,) = q1(runner, "select geometric_mean(x) from "
                           "(values (1.0),(4.0),(16.0)) t(x)")
        assert abs(gm - 4.0) < 1e-9

    def test_checksum_order_independent(self, runner):
        a = q1(runner, "select checksum(x) from (values (1),(2),(3)) t(x)")
        b = q1(runner, "select checksum(x) from (values (3),(1),(2)) t(x)")
        c = q1(runner, "select checksum(x) from (values (3),(1),(5)) t(x)")
        assert a == b and a != c and a[0] != 0


class TestDistributedMerge:
    """Partial -> exchange -> final merge for sketch/collect aggregates."""

    @pytest.fixture(scope="class")
    def cluster(self):
        from presto_tpu.server.dqr import DistributedQueryRunner

        dqr = DistributedQueryRunner.tpch(scale=0.01, n_workers=3)
        yield dqr
        dqr.close()

    def test_approx_distinct_merge(self, cluster, runner):
        sql = "select approx_distinct(l_orderkey) from lineitem"
        assert cluster.execute(sql).rows == runner.execute(sql).rows

    def test_percentile_merge(self, cluster, runner):
        # sketch results depend on the split/merge plan; both answers
        # must sit within rank tolerance of the true median (l_quantity
        # is uniform 1..50 -> true median 25.5)
        sql = "select approx_percentile(l_quantity, 0.5) from lineitem"
        (d,), (l,) = cluster.execute(sql).rows[0], runner.execute(sql).rows[0]
        assert 23 <= d <= 28 and 23 <= l <= 28, (d, l)

    def test_corr_merge(self, cluster, runner):
        sql = "select corr(l_quantity, l_extendedprice) from lineitem"
        (d,), (l,) = cluster.execute(sql).rows[0], runner.execute(sql).rows[0]
        assert abs(d - l) < 1e-9

    def test_array_agg_merge(self, cluster, runner):
        sql = ("select o_orderpriority, array_agg(o_orderkey) from orders "
               "group by o_orderpriority")
        d = {k: sorted(v) for k, v in cluster.execute(sql).rows}
        l = {k: sorted(v) for k, v in runner.execute(sql).rows}
        assert d == l
