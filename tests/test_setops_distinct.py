"""Set-operation NULL semantics and multi-channel distinct aggregates.

Reference models: SetOperationNodeTranslator (markers + GROUP BY, so NULL
keys use distinct semantics, not join matching) and the MarkDistinct /
OptimizeMixedDistinctAggregations rewrites
(presto-main/.../sql/planner/optimizations/)."""

import pytest

from presto_tpu.localrunner import LocalQueryRunner


@pytest.fixture(scope="module")
def runner():
    return LocalQueryRunner.tpch(scale=0.01)


def rows(runner, sql):
    key = lambda v: (v is None, v)  # noqa: E731
    return sorted(runner.execute(sql).rows,
                  key=lambda r: tuple(key(v) for v in r))


class TestSetOpNulls:
    def test_intersect_keeps_null(self, runner):
        assert rows(runner,
                    "select x from (values (1),(null),(2)) a(x) intersect "
                    "select y from (values (null),(2),(3)) b(y)") \
            == [(2,), (None,)]

    def test_except_keeps_null(self, runner):
        assert rows(runner,
                    "select x from (values (1),(null),(2)) a(x) except "
                    "select y from (values (2)) b(y)") == [(1,), (None,)]

    def test_except_removes_null(self, runner):
        assert rows(runner,
                    "select x from (values (1),(null)) a(x) except "
                    "select y from (values (null)) b(y)") == [(1,)]

    def test_intersect_distinct_output(self, runner):
        assert rows(runner,
                    "select x from (values (1),(2),(2)) a(x) intersect "
                    "select y from (values (2),(2),(5)) b(y)") == [(2,)]

    def test_multi_column(self, runner):
        assert rows(runner,
                    "select * from (values (1,null),(2,'b')) a(x,y) "
                    "intersect select * from (values (1,null),(3,'c')) "
                    "b(x,y)") == [(1, None)]

    def test_tpch_intersect(self, runner):
        got = rows(runner,
                   "select o_orderkey from orders where o_orderkey < 10 "
                   "intersect select l_orderkey from lineitem "
                   "where l_orderkey < 8")
        want = rows(runner,
                    "select distinct o_orderkey from orders "
                    "where o_orderkey < 8")
        assert got == want


class TestMultiDistinct:
    def test_two_distinct_channels(self, runner):
        assert runner.execute(
            "select count(distinct l_suppkey), count(distinct l_partkey) "
            "from lineitem").rows == [(100, 2000)]

    def test_grouped_two_distinct_plus_plain(self, runner):
        got = runner.execute(
            "select l_returnflag, count(distinct l_suppkey), "
            "count(distinct l_shipmode), count(*) from lineitem "
            "group by l_returnflag order by 1").rows
        # oracles from single-distinct queries
        for rf, ds, dm, cnt in got:
            (ds2,) = runner.execute(
                f"select count(distinct l_suppkey) from lineitem "
                f"where l_returnflag = '{rf}'").rows[0]
            (dm2,) = runner.execute(
                f"select count(distinct l_shipmode) from lineitem "
                f"where l_returnflag = '{rf}'").rows[0]
            assert (ds, dm) == (ds2, dm2)

    def test_global_mixed(self, runner):
        (a, b, c) = runner.execute(
            "select count(distinct l_suppkey), sum(distinct l_linenumber),"
            " count(*) from lineitem").rows[0]
        assert a == 100 and b == 1 + 2 + 3 + 4 + 5 + 6 + 7
        (total,) = runner.execute(
            "select count(*) from lineitem").rows[0]
        assert c == total

    def test_same_channel_two_aggs(self, runner):
        assert runner.execute(
            "select count(distinct l_linenumber), "
            "sum(distinct l_linenumber) from lineitem").rows == [(7, 28)]
