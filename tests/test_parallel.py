"""Multi-chip exchange + partitioned-operator tests on a virtual 8-device
CPU mesh (the DistributedQueryRunner-in-one-process pattern, SURVEY §4.3)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from presto_tpu import types as T
from presto_tpu.parallel.exchange import broadcast_rows, repartition
from presto_tpu.parallel.mesh import AXIS, make_mesh, row_sharding
from presto_tpu.parallel.steps import (
    jit_step, make_partitioned_aggregate_step, make_partitioned_join_step,
)

NDEV = 8
CAP = 64  # per-shard row capacity


def _mesh():
    return make_mesh(NDEV)


def _shard(mesh, arr):
    return jax.device_put(jnp.asarray(arr), row_sharding(mesh, arr.ndim))


def _make_rows(rng, total_live):
    """Global [NDEV*CAP] arrays with ragged per-shard live counts."""
    counts = rng.multinomial(total_live, [1 / NDEV] * NDEV)
    counts = np.minimum(counts, CAP)
    vals = np.zeros(NDEV * CAP, dtype=np.int64)
    keys = np.zeros(NDEV * CAP, dtype=np.int64)
    live_keys, live_vals = [], []
    for s in range(NDEV):
        n = counts[s]
        k = rng.integers(0, 13, size=n)
        v = rng.integers(-50, 50, size=n)
        keys[s * CAP:s * CAP + n] = k
        vals[s * CAP:s * CAP + n] = v
        live_keys.append(k)
        live_vals.append(v)
    return (keys, vals, counts.astype(np.int64),
            np.concatenate(live_keys), np.concatenate(live_vals))


def test_repartition_round_trip():
    mesh = _mesh()
    rng = np.random.default_rng(7)
    keys, vals, counts, live_k, live_v = _make_rows(rng, 300)

    def shard_fn(k, v, n):
        live = jnp.arange(CAP) < n[0]
        dest = (k % NDEV).astype(jnp.int32)
        (k2, v2), n2, of = repartition([k, v], live, dest,
                                       slot_cap=CAP, out_cap=NDEV * CAP,
                                       axis_name=AXIS)
        return k2, v2, n2.reshape(1), of.reshape(1)

    from jax.sharding import PartitionSpec as P
    row = P(AXIS)
    fn = jit_step(mesh, shard_fn, (row, row, row), (row, row, row, row))
    k2, v2, n2, of = fn(_shard(mesh, keys), _shard(mesh, vals),
                        _shard(mesh, counts))
    k2, v2 = np.asarray(k2), np.asarray(v2)
    n2, of = np.asarray(n2), np.asarray(of)
    assert not of.any()
    assert n2.sum() == len(live_k)
    got = []
    out_cap = NDEV * CAP
    for s in range(NDEV):
        n = n2[s]
        ks = k2[s * out_cap:s * out_cap + n]
        vs = v2[s * out_cap:s * out_cap + n]
        # every row landed on its hash destination
        assert (ks % NDEV == s).all()
        got.append(np.stack([ks, vs], 1))
    got = np.concatenate(got)
    want = np.stack([live_k, live_v], 1)
    assert (got[np.lexsort(got.T)] == want[np.lexsort(want.T)]).all()


def test_broadcast_rows():
    mesh = _mesh()
    rng = np.random.default_rng(3)
    keys, vals, counts, live_k, live_v = _make_rows(rng, 150)
    out_cap = 512

    def shard_fn(k, v, n):
        (k2, v2), n2, of = broadcast_rows([k, v], n[0], out_cap, AXIS)
        return k2, v2, n2.reshape(1), of.reshape(1)

    from jax.sharding import PartitionSpec as P
    row = P(AXIS)
    fn = jit_step(mesh, shard_fn, (row, row, row), (row, row, row, row))
    k2, v2, n2, of = fn(_shard(mesh, keys), _shard(mesh, vals),
                        _shard(mesh, counts))
    k2, n2 = np.asarray(k2), np.asarray(n2)
    assert not np.asarray(of).any()
    want = np.sort(live_k)
    for s in range(NDEV):
        assert n2[s] == len(live_k)
        ks = k2[s * out_cap:s * out_cap + n2[s]]
        assert (np.sort(ks) == want).all()


def test_partitioned_aggregate_matches_numpy():
    mesh = _mesh()
    rng = np.random.default_rng(11)
    keys, vals, counts, live_k, live_v = _make_rows(rng, 350)
    all_true = np.ones(NDEV * CAP, bool)

    shard_fn, in_specs, out_specs = make_partitioned_aggregate_step(
        key_types=[T.BIGINT], agg_prims=["sum", "count", "min"],
        group_cap=128, slot_cap=128, out_cap=128)
    fn = jit_step(mesh, shard_fn, in_specs, out_specs)
    (okv, okg, ovals, ocnts, ng, of) = fn(
        [_shard(mesh, keys)], [_shard(mesh, all_true)],
        [_shard(mesh, vals), _shard(mesh, vals), _shard(mesh, vals)],
        [_shard(mesh, all_true)] * 3,
        _shard(mesh, counts))
    assert not np.asarray(of).any()
    ng = np.asarray(ng)
    kv = np.asarray(okv[0])
    sums = np.asarray(ovals[0])
    cnt_agg = np.asarray(ovals[1])
    mins = np.asarray(ovals[2])

    got = {}
    for s in range(NDEV):
        for i in range(ng[s]):
            j = s * 128 + i
            assert kv[j] not in got, "key landed on two shards"
            got[kv[j]] = (sums[j], cnt_agg[j], mins[j])
    want = {}
    for k in np.unique(live_k):
        sel = live_v[live_k == k]
        want[k] = (sel.sum(), len(sel), sel.min())
    assert got == {k: (int(a), int(b), int(c))
                   for k, (a, b, c) in want.items()}


@pytest.mark.parametrize("broadcast", [False, True])
def test_partitioned_join_matches_numpy(broadcast):
    mesh = _mesh()
    rng = np.random.default_rng(23)
    bk, bv, bn, blive_k, blive_v = _make_rows(rng, 120)
    pk, pv, pn, plive_k, plive_v = _make_rows(rng, 260)
    all_true = np.ones(NDEV * CAP, bool)

    shard_fn, in_specs, out_specs = make_partitioned_join_step(
        key_types=[T.BIGINT], n_build_payload=2, n_probe_payload=2,
        slot_cap=256, local_cap=1024, out_cap=4096,
        broadcast_build=broadcast)
    fn = jit_step(mesh, shard_fn, in_specs, out_specs)
    b_out, p_out, total, of = fn(
        [_shard(mesh, bk)], [_shard(mesh, all_true)],
        [_shard(mesh, bk), _shard(mesh, bv)],
        [_shard(mesh, pk)], [_shard(mesh, all_true)],
        [_shard(mesh, pk), _shard(mesh, pv)],
        _shard(mesh, bn), _shard(mesh, pn))
    assert not np.asarray(of).any()
    total = np.asarray(total)
    rows = []
    for s in range(NDEV):
        n = total[s]
        sl = slice(s * 4096, s * 4096 + n)
        rows.append(np.stack([np.asarray(b_out[0])[sl],
                              np.asarray(b_out[1])[sl],
                              np.asarray(p_out[0])[sl],
                              np.asarray(p_out[1])[sl]], 1))
    got = np.concatenate(rows)
    assert (got[:, 0] == got[:, 2]).all()  # join keys equal

    want = []
    for i in range(len(blive_k)):
        for j in range(len(plive_k)):
            if blive_k[i] == plive_k[j]:
                want.append((blive_k[i], blive_v[i],
                             plive_k[j], plive_v[j]))
    want = np.asarray(sorted(want), dtype=np.int64).reshape(-1, 4)
    assert got.shape == want.shape
    assert (got[np.lexsort(got.T[::-1])] == want).all()


def test_partitioned_topn_step():
    """Distributed TopN: local sort+truncate -> all_gather -> final
    TopN replicated on every shard, vs a numpy oracle."""
    from presto_tpu.parallel.steps import make_partitioned_topn_step

    mesh = _mesh()
    P_, C, K = NDEV, CAP, 7
    fn, ins, outs = make_partitioned_topn_step(
        sort_types=[T.DOUBLE, T.BIGINT], descending=[True, False],
        n_payload=1, limit=K)
    step = jit_step(mesh, fn, ins, outs)

    rng = np.random.default_rng(5)
    vals = rng.uniform(0, 1000, P_ * C)
    ties = rng.integers(0, 9, P_ * C)
    pay = rng.integers(0, 1 << 40, P_ * C)
    nrows = rng.integers(C // 2, C + 1, P_)  # ragged shard occupancy

    sh = lambda a: _shard(mesh, np.asarray(a))
    tvals = np.ones(P_ * C, bool)
    (sv, svd, py, cnt) = step(
        [sh(vals), sh(ties.astype(np.int64))], [sh(tvals), sh(tvals)],
        [sh(pay)], jnp.asarray(nrows))
    # numpy oracle over exactly the live rows
    live_rows = []
    for p in range(P_):
        for i in range(int(nrows[p])):
            j = p * C + i
            live_rows.append((-vals[j], ties[j], pay[j]))
    live_rows.sort()
    want = live_rows[:K]
    got = sorted(
        (-float(sv[0][i]), int(sv[1][i]), int(py[0][i]))
        for i in range(int(cnt)))
    assert [w[:2] for w in sorted(want)] == [g[:2] for g in got]
    # payloads match where keys are untied
    assert got == sorted(want)


def test_partitioned_topn_limit_exceeds_shard_capacity():
    """limit > per-shard capacity: every shard contributes all its rows
    and the final truncate is still exact (review regression)."""
    from presto_tpu.parallel.steps import make_partitioned_topn_step

    mesh = _mesh()
    C, K = 4, 6  # limit above the per-shard block
    fn, ins, outs = make_partitioned_topn_step(
        sort_types=[T.BIGINT], descending=[True], n_payload=0, limit=K)
    step = jit_step(mesh, fn, ins, outs)
    vals = np.arange(NDEV * C, dtype=np.int64)  # 0..31
    nrows = np.full(NDEV, C, np.int64)
    sv, _valid, _pay, cnt = step(
        [_shard(mesh, vals)], [_shard(mesh, np.ones(NDEV * C, bool))],
        [], jnp.asarray(nrows))
    got = [int(sv[0][i]) for i in range(int(cnt))]
    assert got == [31, 30, 29, 28, 27, 26], got
