"""Batch/Column tests (reference tier: presto-spi Page/Block tests —
round-trip, regions, positions; SURVEY §4.1)."""

import numpy as np
import pytest

from presto_tpu import types as T
from presto_tpu.batch import (
    Batch, Column, Dictionary, batch_from_pylist, concat_batches,
    column_from_pylist, empty_batch, next_bucket,
)


def test_next_bucket():
    assert next_bucket(0) == 1024
    assert next_bucket(1024) == 1024
    assert next_bucket(1025) == 2048
    assert next_bucket(3, minimum=2) == 4


def test_pylist_roundtrip():
    schema = [T.BIGINT, T.DOUBLE, T.VARCHAR, T.DATE]
    rows = [
        (1, 1.5, "alpha", "1995-01-01"),
        (2, None, "beta", "1996-06-30"),
        (None, 3.5, "alpha", None),
    ]
    b = batch_from_pylist(schema, rows)
    assert b.num_rows == 3
    out = b.to_pylist()
    import datetime

    assert out[0][0] == 1 and out[0][2] == "alpha"
    assert out[1][1] is None
    assert out[2][0] is None and out[2][3] is None
    assert out[0][3] == datetime.date(1995, 1, 1)
    # dictionary got deduped
    assert len(b.columns[2].dictionary) == 2


def test_take_and_channels():
    b = batch_from_pylist([T.BIGINT, T.VARCHAR],
                          [(10, "x"), (20, "y"), (30, "z")])
    g = b.take(np.array([2, 0]))
    assert g.to_pylist() == [(30, "z"), (10, "x")]
    assert b.select_channels([1]).to_pylist() == [("x",), ("y",), ("z",)]


def test_pad_and_compact():
    b = batch_from_pylist([T.BIGINT], [(1,), (2,), (3,)])
    p = b.pad_rows(8)
    assert p.capacity == 8 and p.num_rows == 3
    assert p.to_pylist() == [(1,), (2,), (3,)]
    assert p.compact().capacity == 3


def test_concat_merges_dictionaries():
    b1 = batch_from_pylist([T.VARCHAR], [("a",), ("b",)])
    b2 = batch_from_pylist([T.VARCHAR], [("b",), ("c",)])
    out = concat_batches([b1, b2])
    assert out.to_pylist() == [("a",), ("b",), ("b",), ("c",)]
    assert len(out.columns[0].dictionary) == 3


def test_concat_nulls():
    b1 = batch_from_pylist([T.BIGINT], [(1,), (None,)])
    b2 = batch_from_pylist([T.BIGINT], [(3,)])
    out = concat_batches([b1, b2])
    assert out.to_pylist() == [(1,), (None,), (3,)]


def test_dictionary_ranks():
    d = Dictionary(["pear", "apple", "zebra"])
    ranks = d.sort_ranks()
    assert list(ranks) == [1, 0, 2]


def test_dictionary_column_requires_dictionary():
    with pytest.raises(ValueError):
        Column(T.VARCHAR, np.zeros(2, np.int32))


def test_empty_batch():
    b = empty_batch([T.BIGINT, T.VARCHAR])
    assert b.num_rows == 0 and b.to_pylist() == []


def test_device_roundtrip():
    b = batch_from_pylist([T.BIGINT, T.DOUBLE], [(1, 2.0), (3, 4.0)])
    d = b.to_device()
    assert d.to_pylist() == b.to_pylist()
    assert d.size_bytes == b.size_bytes
