"""TPC-H connector tests (reference tier: presto-tpch connector tests —
determinism, schema shape, distribution sanity)."""

import numpy as np
import pytest

from presto_tpu import types as T
from presto_tpu.batch import concat_batches
from presto_tpu.connectors.tpch import CURRENT_DATE, TpchConnector


@pytest.fixture(scope="module")
def conn():
    return TpchConnector(scale=0.01)


def scan(conn, table, columns, desired_splits=1):
    handle = conn.get_table(table)
    batches = []
    for split in conn.get_splits(handle, desired_splits):
        for b in conn.page_source(split, columns, batch_rows=5000):
            batches.append(b)
    return concat_batches(batches)


def test_tables_and_schema(conn):
    assert conn.list_tables() == [
        "customer", "lineitem", "nation", "orders", "part", "partsupp",
        "region", "supplier"]
    schema = conn.table_schema(conn.get_table("lineitem"))
    assert schema.column_names()[:4] == [
        "l_orderkey", "l_partkey", "l_suppkey", "l_linenumber"]
    assert schema.column_type("l_extendedprice") is T.DOUBLE
    assert schema.column_type("l_shipdate") is T.DATE


def test_fixed_tables(conn):
    region = scan(conn, "region", ["r_regionkey", "r_name"])
    assert region.num_rows == 5
    assert region.to_pylist()[2] == (2, "ASIA")
    nation = scan(conn, "nation", ["n_nationkey", "n_name", "n_regionkey"])
    assert nation.num_rows == 25
    rows = nation.to_pylist()
    assert rows[6] == (6, "FRANCE", 3)
    assert rows[24] == (24, "UNITED STATES", 1)


def test_row_counts(conn):
    assert scan(conn, "supplier", ["s_suppkey"]).num_rows == 100
    assert scan(conn, "customer", ["c_custkey"]).num_rows == 1500
    assert scan(conn, "part", ["p_partkey"]).num_rows == 2000
    assert scan(conn, "partsupp", ["ps_partkey"]).num_rows == 8000
    assert scan(conn, "orders", ["o_orderkey"]).num_rows == 15000


def test_split_invariance(conn):
    """Any split decomposition generates identical data (counter-based)."""
    one = scan(conn, "orders", ["o_orderkey", "o_custkey", "o_totalprice"], 1)
    many = scan(conn, "orders", ["o_orderkey", "o_custkey", "o_totalprice"], 7)
    assert one.to_pylist() == many.to_pylist()


def test_column_lazy_consistency(conn):
    """The same column requested alone or with others is identical."""
    a = scan(conn, "lineitem", ["l_orderkey", "l_quantity"])
    b = scan(conn, "lineitem", ["l_quantity"])
    assert a.select_channels([1]).to_pylist() == b.to_pylist()


def test_lineitem_invariants(conn):
    b = scan(conn, "lineitem", [
        "l_orderkey", "l_linenumber", "l_quantity", "l_discount",
        "l_shipdate", "l_commitdate", "l_receiptdate", "l_returnflag",
        "l_linestatus"])
    okey = np.asarray(b.columns[0].values)
    ln = np.asarray(b.columns[1].values)
    qty = np.asarray(b.columns[2].values)
    disc = np.asarray(b.columns[3].values)
    ship = np.asarray(b.columns[4].values)
    receipt = np.asarray(b.columns[6].values)
    assert (qty >= 1).all() and (qty <= 50).all()
    assert (disc >= 0).all() and (disc <= 0.10).all()
    assert (receipt > ship).all()
    # linenumbers are 1..count per order
    assert ln.min() == 1 and ln.max() <= 7
    assert (np.diff(okey) >= 0).all()
    # returnflag/linestatus derivation
    flags = b.columns[7].to_pylist(b.num_rows)
    status = b.columns[8].to_pylist(b.num_rows)
    ship_py = np.asarray(ship)
    for i in range(0, b.num_rows, 997):
        if receipt[i] <= CURRENT_DATE:
            assert flags[i] in ("R", "A")
        else:
            assert flags[i] == "N"
        assert status[i] == ("O" if ship_py[i] > CURRENT_DATE else "F")


def test_referential_integrity(conn):
    orders = scan(conn, "orders", ["o_custkey"])
    ck = np.asarray(orders.columns[0].values)
    assert (ck >= 1).all() and (ck <= 1500).all()
    assert (ck % 3 != 0).all()  # 2/3-customer rule
    li = scan(conn, "lineitem", ["l_partkey", "l_suppkey"])
    pk = np.asarray(li.columns[0].values)
    sk = np.asarray(li.columns[1].values)
    assert (pk >= 1).all() and (pk <= 2000).all()
    assert (sk >= 1).all() and (sk <= 100).all()
    # lineitem (partkey, suppkey) pairs exist in partsupp
    ps = scan(conn, "partsupp", ["ps_partkey", "ps_suppkey"])
    pairs = set(zip(np.asarray(ps.columns[0].values).tolist(),
                    np.asarray(ps.columns[1].values).tolist()))
    for i in range(0, li.num_rows, 499):
        assert (int(pk[i]), int(sk[i])) in pairs


def test_orderstatus_totalprice_consistency(conn):
    orders = scan(conn, "orders", ["o_orderkey", "o_orderstatus", "o_totalprice"])
    li = scan(conn, "lineitem", [
        "l_orderkey", "l_extendedprice", "l_discount", "l_tax", "l_linestatus"])
    rows = li.to_pylist()
    by_order = {}
    for okey, ext, disc, tax, ls in rows:
        tot, statuses = by_order.setdefault(okey, [0.0, set()])
        by_order[okey][0] = tot + round(ext * 100) * (100 - round(disc * 100)) \
            * (100 + round(tax * 100)) // 10_000 / 100.0
        statuses.add(ls)
    for okey, st, total in orders.to_pylist()[:200]:
        exp_total, statuses = by_order[okey]
        assert abs(exp_total - total) < 0.5
        expected = "O" if statuses == {"O"} else ("F" if statuses == {"F"} else "P")
        assert st == expected


def test_enum_distributions(conn):
    b = scan(conn, "customer", ["c_mktsegment"])
    segs = set(b.columns[0].to_pylist(b.num_rows))
    assert segs == {"AUTOMOBILE", "BUILDING", "FURNITURE", "MACHINERY",
                    "HOUSEHOLD"}
    b = scan(conn, "lineitem", ["l_shipmode"])
    modes = set(b.columns[0].to_pylist(b.num_rows))
    assert len(modes) == 7


def test_part_name_contains_colors(conn):
    b = scan(conn, "part", ["p_name"])
    names = b.columns[0].to_pylist(b.num_rows)
    assert any("green" in n.split() for n in names)
    assert all(len(n.split()) == 5 for n in names[:50])


def test_retailprice_formula(conn):
    b = scan(conn, "part", ["p_partkey", "p_retailprice"])
    for pk, rp in b.to_pylist()[:100]:
        expected = (90000 + (pk // 10) % 20001 + 100 * (pk % 1000)) / 100.0
        assert abs(rp - expected) < 1e-9


def test_statistics(conn):
    stats = conn.table_statistics(conn.get_table("orders"))
    assert stats.row_count == 15000
