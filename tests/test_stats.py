"""Cost-based-optimizer tests: stats derivation, selectivity, the
broadcast-vs-partitioned distribution flip, and stats-driven join order
(cost/FilterStatsCalculator.java, iterative/rule/
DetermineJoinDistributionType.java:50, ReorderJoins analogues)."""

import pytest

from presto_tpu.localrunner import LocalQueryRunner
from presto_tpu.server.fragmenter import Fragmenter
from presto_tpu.sql.optimizer import optimize
from presto_tpu.sql.parser import parse_statement
from presto_tpu.sql.plan import JoinNode, TableScanNode
from presto_tpu.sql.planner import Planner
from presto_tpu.sql.stats import StatsCalculator


@pytest.fixture(scope="module")
def runner():
    return LocalQueryRunner.tpch(scale=1.0)   # stats are analytic: no data


def _plan(runner, sql):
    stmt = parse_statement(sql)
    logical = Planner(runner.metadata).plan(stmt)
    return optimize(logical, runner.metadata)


def _fragment(runner, sql):
    return Fragmenter(metadata=runner.metadata).fragment(
        _plan(runner, sql))


def test_scan_stats(runner):
    plan = _plan(runner, "select o_orderkey, o_orderdate from orders")
    sc = StatsCalculator(runner.metadata)
    st = sc.stats(plan.source)
    assert st.row_count == pytest.approx(1_500_000)


def test_range_filter_selectivity(runner):
    # ~one year out of the ~6.5-year o_orderdate domain
    plan = _plan(runner, "select o_orderkey from orders "
                         "where o_orderdate >= date '1997-01-01' "
                         "and o_orderdate < date '1998-01-01'")
    sc = StatsCalculator(runner.metadata)
    rc = sc.stats(plan.source).row_count
    assert 130_000 < rc < 320_000, rc


def test_equality_selectivity_uses_ndv(runner):
    plan = _plan(runner, "select c_custkey from customer "
                         "where c_mktsegment = 'BUILDING'")
    sc = StatsCalculator(runner.metadata)
    rc = sc.stats(plan.source).row_count
    # 5 segments -> 1/5 of 150k
    assert rc == pytest.approx(30_000, rel=0.01)


def test_join_output_uses_key_ndv(runner):
    plan = _plan(runner, "select count(*) from customer "
                         "join orders on c_custkey = o_custkey")
    sc = StatsCalculator(runner.metadata)

    def find_join(node):
        if isinstance(node, JoinNode):
            return node
        for s in node.sources:
            j = find_join(s)
            if j is not None:
                return j
        return None

    join = find_join(plan)
    rc = sc.stats(join).row_count
    # every order matches exactly one customer -> ~|orders|
    assert 1_000_000 < rc < 2_500_000, rc


def test_filtered_table_flips_to_broadcast(runner):
    """A large build side qualifies for broadcast once its FILTERED
    cardinality is small (the VERDICT round-2 finding: the decision must
    use post-filter stats, not the raw connector row count)."""
    big = ("select count(*) from lineitem "
           "join orders on l_orderkey = o_orderkey")
    filtered = ("select count(*) from lineitem l join "
                "(select o_orderkey from orders where "
                "o_orderkey < 300) o on l.l_orderkey = o.o_orderkey")
    frags_big = _fragment(runner, big).fragments
    frags_filt = _fragment(runner, filtered).fragments
    kinds_big = {f.output_partitioning[0] for f in frags_big}
    kinds_filt = {f.output_partitioning[0] for f in frags_filt}
    assert "broadcast" not in kinds_big        # 1.5M-row build: hash-hash
    assert "broadcast" in kinds_filt           # ~300-row build: broadcast


def test_cache_does_not_alias_recycled_ids(runner):
    """Throwaway probe nodes at recycled object addresses must not
    inherit a previous node's memoized stats."""
    import dataclasses

    plan = _plan(runner, "select count(*) from customer "
                         "join orders on c_custkey = o_custkey")

    def find(node):
        if isinstance(node, JoinNode):
            return node
        for s in node.sources:
            j = find(s)
            if j is not None:
                return j

    join = find(plan)
    sc = StatsCalculator(runner.metadata)
    a = dataclasses.replace(join)
    inner_rc = sc.stats(a).row_count
    del a  # free the address so CPython may recycle it
    b = dataclasses.replace(join, kind="cross", left_keys=(),
                            right_keys=())
    cross_rc = sc.stats(b).row_count
    assert cross_rc > inner_rc * 10, (inner_rc, cross_rc)


def test_join_order_smallest_intermediate_first(runner):
    """Q9-style chain: greedy order joins the most selective edge first.
    lineitem x (part filtered to ~1/25 by brand) must join part before
    the unfiltered orders relation.  Pins the GREEDY orderer (memo off);
    the memo path has its own pins in test_plan_golden/test_memo."""
    import dataclasses as dc

    from presto_tpu.config import DEFAULT

    sql = ("select count(*) from lineitem, orders, part "
           "where l_orderkey = o_orderkey and l_partkey = p_partkey "
           "and p_brand = 'Brand#11'")
    logical = Planner(runner.metadata).plan(parse_statement(sql))
    plan = optimize(logical, runner.metadata,
                    dc.replace(DEFAULT, optimizer_use_memo=False))

    order = []

    def walk(node):
        if isinstance(node, JoinNode):
            walk(node.left)
            order.append(node)
            return
        for s in node.sources:
            walk(s)

    walk(plan)
    # the first (innermost) join's build side must reach the part scan
    def scans(node, acc):
        if isinstance(node, TableScanNode):
            acc.append(node.table)
        for s in node.sources:
            scans(s, acc)
        return acc

    first_build = scans(order[0].right, [])
    assert first_build == ["part"], first_build
