"""TPC-DS connector + query suite tests.

Two tiers, mirroring the tpch coverage pattern (SURVEY §4.7): generator
invariants (FK integrity, determinism, split independence), and query
results pinned against an independent numpy oracle where tractable plus
smoke-executed for the rest.  Q72 runs only at bench time (it is the
heaviest TPC-DS join even on the reference).
"""

import numpy as np
import pytest

from presto_tpu.connectors.tpcds import TpcdsConnector
from presto_tpu.localrunner import LocalQueryRunner
from tests.tpcds_queries import QUERIES

pytestmark = pytest.mark.slow


SCALE = 0.005


@pytest.fixture(scope="module")
def runner():
    return LocalQueryRunner.tpch(scale=SCALE)


@pytest.fixture(scope="module")
def conn():
    return TpcdsConnector(scale=SCALE)


def scan(conn, table, columns):
    h = conn.get_table(table)
    parts = []
    for s in conn.get_splits(h, 4):
        for b in conn.page_source(s, columns, 1 << 20):
            parts.append(b.to_pylist())
    return [row for p in parts for row in p]


class TestGenerator:
    def test_deterministic(self, conn):
        a = scan(conn, "item", ["i_item_sk", "i_brand_id", "i_category"])
        b = scan(conn, "item", ["i_item_sk", "i_brand_id", "i_category"])
        assert a == b

    def test_split_independence(self, conn):
        one = TpcdsConnector(scale=SCALE)
        h = one.get_table("store_sales")
        cols = ["ss_ticket_number", "ss_item_sk", "ss_ext_sales_price"]
        single = [row for s in one.get_splits(h, 1)
                  for b in one.page_source(s, cols, 1 << 20)
                  for row in b.to_pylist()]
        many = [row for s in one.get_splits(h, 7)
                for b in one.page_source(s, cols, 1 << 20)
                for row in b.to_pylist()]
        assert sorted(single) == sorted(many)

    def test_fk_integrity(self, conn, runner):
        # every fact FK hits its dimension (join-loss would corrupt
        # every star query)
        checks = [
            ("store_sales", "ss_item_sk", "item", "i_item_sk"),
            ("store_sales", "ss_store_sk", "store", "s_store_sk"),
            ("catalog_sales", "cs_bill_cdemo_sk", "customer_demographics",
             "cd_demo_sk"),
            ("web_sales", "ws_web_site_sk", "web_site", "web_site_sk"),
            ("inventory", "inv_warehouse_sk", "warehouse",
             "w_warehouse_sk"),
        ]
        for fact, fk, dim, pk in checks:
            n = runner.execute(
                f"select count(*) from tpcds.{fact} "
                f"where {fk} not in (select {pk} from tpcds.{dim})"
            ).rows[0][0]
            assert n == 0, (fact, fk)

    def test_date_dim_calendar(self, runner):
        rows = runner.execute(
            "select d_year, count(*) from tpcds.date_dim "
            "where d_year in (1996, 1999, 2000) group by d_year "
            "order by 1").rows
        assert rows == [(1996, 366), (1999, 365), (2000, 366)]
        row = runner.execute(
            "select d_moy, d_dom, d_day_name from tpcds.date_dim "
            "where d_date = date '1999-02-14'").rows
        assert row == [(2, 14, "Sunday")]

    def test_date_sk_joinable(self, runner):
        n = runner.execute(
            "select count(*) from tpcds.store_sales "
            "where ss_sold_date_sk not in "
            "(select d_date_sk from tpcds.date_dim)").rows[0][0]
        assert n == 0


class TestQueriesVsOracle:
    def test_q42_matches_numpy(self, conn, runner):
        got = runner.execute(QUERIES[42]).rows
        # independent recomputation
        dd = {r[0]: (r[1], r[2]) for r in scan(
            conn, "date_dim", ["d_date_sk", "d_year", "d_moy"])}
        items = {r[0]: (r[1], r[2], r[3]) for r in scan(
            conn, "item",
            ["i_item_sk", "i_manager_id", "i_category_id", "i_category"])}
        agg = {}
        for sk, isk, price in scan(
                conn, "store_sales",
                ["ss_sold_date_sk", "ss_item_sk", "ss_ext_sales_price"]):
            year, moy = dd[sk]
            mgr, cid, cat = items[isk]
            if mgr == 1 and moy == 11 and year == 2000:
                key = (year, cid, cat)
                agg[key] = agg.get(key, 0.0) + price
        want = sorted(((y, c, cat, s) for (y, c, cat), s in agg.items()),
                      key=lambda r: (-r[3], r[0], r[1], r[2]))[:100]
        assert len(got) == len(want)
        for g, w in zip(got, want):
            assert g[:3] == w[:3]
            assert abs(g[3] - w[3]) < 1e-6

    def test_q95_shape(self, runner):
        rows = runner.execute(QUERIES[95]).rows
        assert len(rows) == 1
        count = rows[0][0]
        assert count >= 0  # tiny scale may legitimately select nothing


@pytest.mark.parametrize("qid", [3, 7, 19, 52, 55])
def test_query_smoke(runner, qid):
    """Executes, deterministic, correct arity (the benchto-suite role)."""
    first = runner.execute(QUERIES[qid])
    again = runner.execute(QUERIES[qid])
    assert first.rows == again.rows
    assert len(first.column_names) == len(first.column_types)
