"""Intra-task driver parallelism (LocalExchange tier): N concurrent
scan-feed drivers stitched to one consumer chain — the
AddLocalExchanges.java:95 / LocalExchange.java:53 shape, with results
pinned against single-driver execution."""

import pytest

from presto_tpu.config import EngineConfig
from presto_tpu.exec.localexchange import LocalExchange
from presto_tpu.localrunner import LocalQueryRunner


def _runner(concurrency: int) -> LocalQueryRunner:
    cfg = EngineConfig(task_concurrency=concurrency, scan_batch_rows=4096)
    return LocalQueryRunner.tpch(scale=0.01, config=cfg)


@pytest.fixture(scope="module")
def serial():
    return _runner(1)


@pytest.fixture(scope="module")
def parallel():
    return _runner(4)


def assert_same(serial, parallel, sql, ordered=False):
    a = serial.execute(sql).rows
    b = parallel.execute(sql).rows
    if not ordered:
        a, b = sorted(a, key=repr), sorted(b, key=repr)
    assert a == b


def test_scan_aggregate(serial, parallel):
    assert_same(serial, parallel,
                "select l_returnflag, count(*), sum(l_quantity) "
                "from lineitem group by l_returnflag")


@pytest.mark.slow
def test_join_parallel_feed(serial, parallel):
    assert_same(serial, parallel,
                "select c_mktsegment, count(*) from customer "
                "join orders on c_custkey = o_custkey "
                "group by c_mktsegment")


def test_ordered_output(serial, parallel):
    assert_same(serial, parallel,
                "select o_orderpriority, count(*) c from orders "
                "group by o_orderpriority order by c desc, "
                "o_orderpriority", ordered=True)


def test_feed_overlap_engages():
    """The parallel path must actually run >1 feed driver: the scan
    operator appears once per feed driver in the stats."""
    cfg = EngineConfig(task_concurrency=4, scan_batch_rows=4096)
    r = LocalQueryRunner.tpch(scale=0.01, config=cfg)
    r.execute("select count(*) from lineitem where l_quantity > 10")
    stats = r._last_task.operator_stats
    scans = [s for s in stats if "TableScan" in s.operator]
    assert len(scans) > 1, [s.operator for s in stats]


def test_producer_error_propagates():
    ex = LocalExchange(1)
    ex.fail(RuntimeError("boom"))
    with pytest.raises(RuntimeError, match="boom"):
        ex.poll()
