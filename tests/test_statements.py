"""Statement-surface tests: PREPARE/EXECUTE, DESCRIBE, SHOW variants,
views, DELETE, transactions, ANALYZE/SHOW STATS, GRANT/REVOKE, USE,
ALTER TABLE RENAME (reference: SqlBase.g4 statement alternatives and
their executions under presto-main/.../execution/*Task.java)."""

import pytest

from presto_tpu import types as T
from presto_tpu.localrunner import LocalQueryRunner


@pytest.fixture(scope="module")
def runner():
    return LocalQueryRunner.tpch(scale=0.01)


def rows(runner, sql):
    return runner.execute(sql).rows


def test_show_catalogs(runner):
    got = [r[0] for r in rows(runner, "SHOW CATALOGS")]
    assert "tpch" in got and "memory" in got
    assert [r[0] for r in rows(runner, "SHOW CATALOGS LIKE 'tp%'")] == \
        ["tpch", "tpcds"] or set(
            r[0] for r in rows(runner, "SHOW CATALOGS LIKE 'tp%'")
        ) == {"tpch", "tpcds"}


def test_show_schemas_and_functions(runner):
    assert ("default",) in rows(runner, "SHOW SCHEMAS")
    fns = rows(runner, "SHOW FUNCTIONS")
    names = {r[0] for r in fns}
    assert {"sum", "lower", "array_distinct", "row_number"} <= names
    kinds = dict(fns)
    assert kinds["sum"] == "aggregate"
    assert kinds["row_number"] == "window"
    only_like = rows(runner, "SHOW FUNCTIONS LIKE 'json%'")
    assert only_like and all(r[0].startswith("json") for r in only_like)


def test_describe(runner):
    got = rows(runner, "DESCRIBE tpch.nation")
    assert ("n_nationkey", "bigint") in got
    assert ("n_name", "varchar") in got


def test_show_create_table(runner):
    txt = rows(runner, "SHOW CREATE TABLE tpch.nation")[0][0]
    assert "CREATE TABLE" in txt and "n_nationkey bigint" in txt


def test_prepare_execute_deallocate(runner):
    runner.execute("PREPARE q1 FROM SELECT n_name FROM tpch.nation "
                   "WHERE n_nationkey < ? ORDER BY n_nationkey")
    got = rows(runner, "EXECUTE q1 USING 3")
    assert got == [("ALGERIA",), ("ARGENTINA",), ("BRAZIL",)]
    # re-execute with different binding
    assert len(rows(runner, "EXECUTE q1 USING 5")) == 5
    inp = rows(runner, "DESCRIBE INPUT q1")
    assert inp == [(0, "unknown")]
    out = rows(runner, "DESCRIBE OUTPUT q1")
    assert out == [("n_name", "varchar")]
    runner.execute("DEALLOCATE PREPARE q1")
    with pytest.raises(Exception, match="not found"):
        runner.execute("EXECUTE q1 USING 1")


def test_views(runner):
    runner.execute("CREATE VIEW v_nation AS SELECT n_name, n_regionkey "
                   "FROM tpch.nation WHERE n_regionkey = 1")
    got = rows(runner, "SELECT count(*) FROM v_nation")
    assert got == [(5,)]
    # view over view + alias
    runner.execute("CREATE VIEW v2 AS SELECT n_name FROM v_nation")
    assert len(rows(runner, "SELECT * FROM v2 v WHERE v.n_name LIKE "
                            "'%A%'")) > 0
    ddl = rows(runner, "SHOW CREATE VIEW v_nation")[0][0]
    assert ddl.startswith("CREATE VIEW")
    with pytest.raises(Exception, match="already exists"):
        runner.execute("CREATE VIEW v_nation AS SELECT 1 AS x")
    runner.execute("CREATE OR REPLACE VIEW v_nation AS SELECT 1 AS x")
    assert rows(runner, "SELECT * FROM v_nation") == [(1,)]
    runner.execute("DROP VIEW v2")
    runner.execute("DROP VIEW v_nation")
    runner.execute("DROP VIEW IF EXISTS v_nation")
    with pytest.raises(Exception, match="does not exist"):
        runner.execute("DROP VIEW v_nation")


def test_delete_and_analyze_stats():
    r = LocalQueryRunner.tpch(scale=0.01)
    r.execute("CREATE TABLE memory.d (a bigint, s varchar)")
    r.execute("INSERT INTO memory.d VALUES (1,'x'),(2,'y'),(3,NULL),"
              "(4,'w'),(5,'x')")
    assert rows(r, "DELETE FROM memory.d WHERE a % 2 = 0") == [(2,)]
    assert rows(r, "SELECT count(*) FROM memory.d") == [(3,)]
    # NULL predicate rows are not deleted
    assert rows(r, "DELETE FROM memory.d WHERE s = 'nope'") == [(0,)]
    r.execute("ANALYZE memory.d")
    stats = rows(r, "SHOW STATS FOR memory.d")
    by_col = {row[0]: row for row in stats}
    assert by_col["a"][2] == 3.0          # ndv
    assert by_col[None][4] == 3.0         # row_count summary row
    assert by_col["s"][3] == pytest.approx(1 / 3)  # nulls fraction
    assert rows(r, "DELETE FROM memory.d") == [(3,)]
    assert rows(r, "SELECT count(*) FROM memory.d") == [(0,)]


def test_transactions():
    r = LocalQueryRunner.tpch(scale=0.01)
    r.execute("CREATE TABLE memory.tx (a bigint)")
    r.execute("INSERT INTO memory.tx VALUES (1)")
    r.execute("START TRANSACTION")
    r.execute("INSERT INTO memory.tx VALUES (2)")
    r.execute("ROLLBACK")
    assert rows(r, "SELECT count(*) FROM memory.tx") == [(1,)]
    r.execute("START TRANSACTION")
    r.execute("INSERT INTO memory.tx VALUES (3)")
    r.execute("COMMIT")
    assert sorted(rows(r, "SELECT a FROM memory.tx")) == [(1,), (3,)]
    with pytest.raises(Exception, match="no transaction"):
        r.execute("COMMIT")


def test_use_and_rename():
    r = LocalQueryRunner.tpch(scale=0.01)
    r.execute("USE memory")
    r.execute("CREATE TABLE ren (a bigint)")
    r.execute("ALTER TABLE ren RENAME TO ren2")
    assert ("ren2",) in rows(r, "SHOW TABLES")
    r.execute("USE tpch")
    assert ("nation",) in rows(r, "SHOW TABLES")


def test_grant_revoke_access_control():
    from presto_tpu.session import GrantAwareAccessControl, Session

    ac = GrantAwareAccessControl()
    r = LocalQueryRunner.tpch(scale=0.01, access_control=ac,
                              session=Session(user="admin"))
    ac.grants = r.grants
    r.execute("CREATE TABLE memory.sec (a bigint)")
    r.execute("INSERT INTO memory.sec VALUES (1)")

    bob = LocalQueryRunner(r.registry, "tpch", r.config,
                           session=Session(user="bob"), access_control=ac)
    bob.grants = r.grants
    with pytest.raises(PermissionError):
        bob.execute("SELECT * FROM memory.sec")
    r.execute("GRANT SELECT ON memory.sec TO bob")
    assert bob.execute("SELECT * FROM memory.sec").rows == [(1,)]
    with pytest.raises(PermissionError):
        bob.execute("DELETE FROM memory.sec")
    r.execute("REVOKE SELECT ON memory.sec FROM bob")
    with pytest.raises(PermissionError):
        bob.execute("SELECT * FROM memory.sec")


def test_if_exists_variants(runner):
    runner.execute("DROP TABLE IF EXISTS memory.nope")
    runner.execute("CREATE TABLE memory.ife (a bigint)")
    runner.execute("CREATE TABLE IF NOT EXISTS memory.ife (a bigint)")
    runner.execute("DROP TABLE memory.ife")


def test_null_comparison_coercion(runner):
    assert rows(runner, "SELECT NULL = 1") == [(None,)]
    assert rows(runner, "SELECT 1 < NULL") == [(None,)]


def test_parameters_in_projection(runner):
    runner.execute("PREPARE p2 FROM SELECT ? + n_nationkey FROM "
                   "tpch.nation WHERE n_nationkey = ?")
    assert rows(runner, "EXECUTE p2 USING 100, 3") == [(103,)]
    runner.execute("DEALLOCATE PREPARE p2")


def test_distributed_utility_statements():
    from presto_tpu.server.dqr import DistributedQueryRunner

    with DistributedQueryRunner.tpch(scale=0.01, n_workers=2) as dqr:
        got = dqr.execute("SHOW CATALOGS")
        assert ("tpch",) in got.rows
        dqr.execute("CREATE TABLE memory.dt (a bigint)")
        dqr.execute("INSERT INTO memory.dt VALUES (5)")
        assert dqr.execute("SELECT * FROM memory.dt").rows == [(5,)]
        dqr.execute("CREATE VIEW memory.dv AS SELECT a * 2 AS b "
                    "FROM memory.dt")
        assert dqr.execute("SELECT b FROM memory.dv").rows == [(10,)]
        assert dqr.execute("DELETE FROM memory.dt WHERE a = 5"
                           ).rows == [(1,)]
        assert dqr.execute("SELECT count(*) FROM memory.dt").rows == [(0,)]


def test_grant_requires_authority():
    from presto_tpu.session import GrantAwareAccessControl, Session

    ac = GrantAwareAccessControl()
    admin = LocalQueryRunner.tpch(scale=0.01, access_control=ac,
                                  session=Session(user="admin"))
    ac.grants = admin.grants
    admin.execute("CREATE TABLE memory.g (a bigint)")
    mallory = LocalQueryRunner(admin.registry, "tpch", admin.config,
                               session=Session(user="mallory"),
                               access_control=ac)
    mallory.grants = admin.grants
    # self-granting must be denied
    with pytest.raises(PermissionError):
        mallory.execute("GRANT ALL ON memory.g TO mallory")
    # creating over an existing table must not steal ownership
    with pytest.raises(Exception):
        mallory.execute("CREATE TABLE memory.g (x bigint)")
    with pytest.raises(PermissionError):
        mallory.execute("DROP TABLE memory.g")
    # rename requires ownership and migrates grants
    admin.execute("GRANT SELECT ON memory.g TO mallory")
    with pytest.raises(PermissionError):
        mallory.execute("ALTER TABLE memory.g RENAME TO h")
    admin.execute("ALTER TABLE memory.g RENAME TO h")
    assert mallory.execute("SELECT count(*) FROM memory.h").rows == [(0,)]


def test_drop_if_exists_unknown_catalog(runner):
    with pytest.raises(KeyError):
        runner.execute("DROP TABLE IF EXISTS nocatalog.t")


def test_show_functions_excludes_internal_names(runner):
    names = {r[0] for r in rows(runner, "SHOW FUNCTIONS")}
    assert not ({"eq", "ne", "add", "subtract", "modulus"} & names)


def test_recursive_view_rejected(runner):
    runner.registry.views[("tpch", "rv")] = "SELECT * FROM rv"
    try:
        with pytest.raises(Exception, match="recursive"):
            runner.execute("SELECT * FROM rv")
    finally:
        del runner.registry.views[("tpch", "rv")]


def test_mutually_recursive_views_rejected(runner):
    runner.registry.views[("tpch", "va")] = "SELECT * FROM vb"
    runner.registry.views[("tpch", "vb")] = "SELECT * FROM va"
    try:
        with pytest.raises(Exception, match="recursive"):
            runner.execute("SELECT * FROM va")
    finally:
        del runner.registry.views[("tpch", "va")]
        del runner.registry.views[("tpch", "vb")]


def test_explain_types(runner):
    import json as _json

    dist = runner.execute(
        "EXPLAIN (TYPE DISTRIBUTED) SELECT l_returnflag, count(*) "
        "FROM lineitem GROUP BY l_returnflag").rows
    text = "\n".join(r[0] for r in dist)
    assert "Fragment 0" in text and "Aggregation" in text
    assert runner.execute(
        "EXPLAIN (TYPE VALIDATE) SELECT 1").rows == [(True,)]
    io = runner.execute(
        "EXPLAIN (TYPE IO) SELECT n_name FROM tpch.nation").rows
    doc = _json.loads(io[0][0])
    assert doc["inputTables"] == [{"catalog": "tpch", "table": "nation",
                                   "columns": ["n_name"]}]
    with pytest.raises(Exception):
        runner.execute("EXPLAIN (TYPE BOGUS) SELECT 1")


def test_explain_validate_checks_dml(runner):
    with pytest.raises(Exception):
        runner.execute(
            "EXPLAIN (TYPE VALIDATE) INSERT INTO memory.no_such_table "
            "VALUES (1)")
    with pytest.raises(Exception):
        runner.execute(
            "EXPLAIN (TYPE VALIDATE) SELECT no_such_col FROM tpch.nation")
    runner.execute("CREATE TABLE memory.val_t (a bigint)")
    assert runner.execute(
        "EXPLAIN (TYPE VALIDATE) INSERT INTO memory.val_t VALUES (1)"
    ).rows == [(True,)]
    assert runner.execute(
        "SELECT count(*) FROM memory.val_t").rows == [(0,)]  # not executed
    runner.execute("DROP TABLE memory.val_t")
