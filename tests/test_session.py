"""Session properties, access control, transactions, resource groups.

Reference analogues: SystemSessionProperties + SET SESSION, the security
SPI with file-based rules, TransactionManager, InternalResourceGroup
(SURVEY §2.12, §5.6)."""

import threading
import time

import pytest

from presto_tpu.localrunner import LocalQueryRunner
from presto_tpu.session import (
    AccessDeniedError, QueryQueueFullError, ResourceGroupManager,
    RuleBasedAccessControl, Session, SessionError, TransactionManager,
)


class TestSessionProperties:
    def test_set_show_reset(self):
        r = LocalQueryRunner.tpch(scale=0.001)
        r.execute("set session spill_enabled = false")
        rows = dict((n, v) for n, v, _ in
                    r.execute("show session").rows)
        assert rows["spill_enabled"] == "False"
        r.execute("reset session spill_enabled")
        rows = dict((n, v) for n, v, _ in
                    r.execute("show session").rows)
        assert rows["spill_enabled"] == "True"

    def test_property_affects_execution(self):
        r = LocalQueryRunner.tpch(scale=0.001)
        r.execute("set session scan_batch_rows = 128")
        assert r.session.effective_config(r.config).scan_batch_rows == 128
        # still executes correctly with tiny batches
        assert r.execute("select count(*) from nation").rows == [(25,)]

    def test_unknown_property_rejected(self):
        s = Session()
        with pytest.raises(SessionError):
            s.set_property("no_such_prop", "1")

    def test_bad_value_rejected(self):
        s = Session()
        with pytest.raises(SessionError):
            s.set_property("spill_partitions", "banana")


class TestAccessControl:
    def _runner(self, user: str):
        rules = [
            {"user": "admin", "privileges": ["select", "insert", "create",
                                             "drop"]},
            {"user": "reader", "catalog": "tpch",
             "privileges": ["select"]},
        ]
        return LocalQueryRunner.tpch(
            scale=0.001, session=Session(user=user, catalog="tpch"),
            access_control=RuleBasedAccessControl(rules))

    def test_admin_can_do_everything(self):
        r = self._runner("admin")
        r.execute("select count(*) from nation")
        r.execute("create table memory.t (a bigint)")
        r.execute("insert into memory.t values (1)")
        r.execute("drop table memory.t")

    def test_reader_can_only_select_tpch(self):
        r = self._runner("reader")
        assert r.execute("select count(*) from nation").rows == [(25,)]
        with pytest.raises(AccessDeniedError):
            r.execute("create table memory.t (a bigint)")

    def test_stranger_denied(self):
        r = self._runner("stranger")
        with pytest.raises(AccessDeniedError):
            r.execute("select count(*) from nation")


class TestTransactions:
    def test_commit_and_abort_flow(self):
        tm = TransactionManager()
        events = []
        txn = tm.begin()
        txn.commit_actions.append(lambda: events.append("commit"))
        tm.commit(txn)
        assert events == ["commit"]
        assert txn.state == "COMMITTED"

        txn2 = tm.begin()
        txn2.abort_actions.append(lambda: events.append("abort"))
        tm.abort(txn2)
        assert events == ["commit", "abort"]
        assert not tm.transactions

    def test_failed_insert_aborts(self):
        r = LocalQueryRunner.tpch(scale=0.001)
        r.execute("create table memory.t (a bigint)")
        with pytest.raises(Exception):
            r.execute("insert into memory.t "
                      "select no_col from nation")
        # nothing half-written
        assert r.execute("select count(*) from memory.t").rows == [(0,)]


class TestResourceGroups:
    def test_concurrency_limit_queues(self):
        mgr = ResourceGroupManager(hard_concurrency_limit=2,
                                   per_user_limit=2)
        g = mgr.group_for(Session(user="u"))
        g.acquire()
        g.acquire()
        started = threading.Event()
        acquired = threading.Event()

        def waiter():
            started.set()
            g.acquire(timeout_s=10)
            acquired.set()

        th = threading.Thread(target=waiter, daemon=True)
        th.start()
        started.wait(1)
        assert not acquired.wait(0.3)  # blocked at the limit
        g.release()
        assert acquired.wait(5)
        g.release()
        g.release()

    def test_queue_full_rejects(self):
        mgr = ResourceGroupManager(hard_concurrency_limit=1,
                                   per_user_limit=1, max_queued=0)
        g = mgr.group_for(Session(user="u"))
        g.acquire()
        with pytest.raises(QueryQueueFullError):
            g.acquire(timeout_s=0.1)
        g.release()

    def test_per_user_isolation(self):
        mgr = ResourceGroupManager(hard_concurrency_limit=10,
                                   per_user_limit=1)
        ga = mgr.group_for(Session(user="a"))
        gb = mgr.group_for(Session(user="b"))
        ga.acquire()
        gb.acquire()  # b unaffected by a's per-user limit
        ga.release()
        gb.release()

    def test_weighted_fair_prefers_higher_weight(self):
        """When one root slot frees with both users waiting, the
        weighted_fair policy admits the under-served high-weight group
        (WeightedFairQueue.java role)."""
        mgr = ResourceGroupManager(hard_concurrency_limit=1,
                                   per_user_limit=5,
                                   scheduling_policy="weighted_fair")
        heavy = mgr.configure_group("heavy", scheduling_weight=10)
        light = mgr.configure_group("light", scheduling_weight=1)
        blocker = mgr.group_for(Session(user="blocker"))
        blocker.acquire()          # occupies the single root slot
        order = []
        done = {"light": threading.Event(), "heavy": threading.Event()}

        def waiter(name, g):
            g.acquire(timeout_s=10)
            order.append(name)
            done[name].set()

        # light queues FIRST; weighted_fair must still pick heavy
        tl = threading.Thread(target=waiter, args=("light", light),
                              daemon=True)
        tl.start()
        time.sleep(0.1)
        th = threading.Thread(target=waiter, args=("heavy", heavy),
                              daemon=True)
        th.start()
        time.sleep(0.1)
        blocker.release()
        assert done["heavy"].wait(5)
        assert order[0] == "heavy", order
        heavy.release()
        assert done["light"].wait(5)
        light.release()

    def test_fair_policy_fifo_within_group(self):
        mgr = ResourceGroupManager(hard_concurrency_limit=1,
                                   per_user_limit=5)
        g = mgr.group_for(Session(user="u"))
        g.acquire()
        order = []
        evs = [threading.Event() for _ in range(2)]

        def waiter(i):
            g.acquire(timeout_s=10)
            order.append(i)
            evs[i].set()

        for i in range(2):
            threading.Thread(target=waiter, args=(i,), daemon=True).start()
            time.sleep(0.1)
        g.release()
        assert evs[0].wait(5)
        assert order[0] == 0, order   # FIFO: first waiter first
        g.release()
        assert evs[1].wait(5)
        g.release()

    def test_soft_memory_limit_gates_admission(self):
        mgr = ResourceGroupManager(hard_concurrency_limit=10,
                                   per_user_limit=10)
        g = mgr.configure_group("u", soft_memory_limit_bytes=1000)
        g.set_memory_usage(5000)   # over the soft limit
        admitted = threading.Event()

        def waiter():
            g.acquire(timeout_s=10)
            admitted.set()

        threading.Thread(target=waiter, daemon=True).start()
        assert not admitted.wait(0.3)          # blocked by memory
        g.set_memory_usage(0)                  # usage drops
        assert admitted.wait(5)
        g.release()


class TestPlannerSteeringProperties:
    """Round-4 SystemSessionProperties surface: planner/scheduler
    behaviors steerable per query (VERDICT r3 missing #8)."""

    def _runner(self):
        from presto_tpu.localrunner import LocalQueryRunner

        return LocalQueryRunner.tpch(scale=0.01)

    def test_join_distribution_type(self):
        r = self._runner()
        sql = ("select count(*) from tpch.orders o join tpch.customer c "
               "on o.o_custkey = c.c_custkey")
        want = r.execute(sql).rows
        for mode in ("broadcast", "partitioned", "automatic"):
            r.execute(f"SET SESSION join_distribution_type = '{mode}'")
            assert r.execute(sql).rows == want
            plan = r.execute(
                f"EXPLAIN (TYPE DISTRIBUTED) {sql}").rows
            text = "\n".join(row[0] for row in plan)
            if mode == "broadcast":
                assert "broadcast" in text
            if mode == "partitioned":
                assert "broadcast" not in text
        r.execute("RESET SESSION join_distribution_type")

    def test_join_reordering_strategy(self):
        r = self._runner()
        sql = ("select count(*) from tpch.lineitem l, tpch.orders o, "
               "tpch.customer c where l.l_orderkey = o.o_orderkey "
               "and o.o_custkey = c.c_custkey")
        want = r.execute(sql).rows
        r.execute("SET SESSION join_reordering_strategy = 'none'")
        assert r.execute(sql).rows == want
        with pytest.raises(Exception):
            r.execute("SET SESSION join_reordering_strategy = 'bogus'")

    def test_partial_aggregation_toggle(self):
        r = self._runner()
        sql = ("select o_orderpriority, count(*) from tpch.orders "
               "group by o_orderpriority")
        want = sorted(r.execute(sql).rows)
        r.execute("SET SESSION partial_aggregation_enabled = false")
        assert sorted(r.execute(sql).rows) == want
        plan = r.execute(f"EXPLAIN (TYPE DISTRIBUTED) {sql}").rows
        text = "\n".join(row[0] for row in plan)
        assert "partial" not in text.lower()

    def test_query_max_memory(self):
        r = self._runner()
        r.execute("SET SESSION query_max_memory_bytes = 1024")
        r.execute("SET SESSION spill_enabled = false")
        with pytest.raises(Exception, match="[Mm]emory"):
            r.execute("select l_orderkey, count(*) from tpch.lineitem "
                      "group by l_orderkey order by 2 desc limit 5")

    def test_query_max_run_time_enforced(self):
        r = self._runner()
        r.execute("SET SESSION query_max_run_time_s = 0.001")
        with pytest.raises(Exception, match="maximum run time"):
            # nested-loop self cross join: long enough that the deadline
            # fires between scheduling quanta
            r.execute("select count(*) from tpch.lineitem l1, "
                      "tpch.lineitem l2 where l1.l_comment < l2.l_comment")
        r.execute("RESET SESSION query_max_run_time_s")
        rows = r.execute("SHOW SESSION").rows
        assert any(row[0] == "query_max_run_time_s" for row in rows)
