"""RequestErrorTracker / RetryingHttpClient / FaultInjector unit tier.

Everything here runs on an injectable clock + sleeper: the whole backoff
schedule and error budget are exercised without a single real delay
(the reference's TestingTicker pattern for RequestErrorTracker)."""

import io
import urllib.error

import pytest

from presto_tpu.server.errortracker import (
    RemoteRequestError, RequestErrorTracker, RetryingHttpClient,
    is_retryable,
)
from presto_tpu.server.faults import FaultInjector, InjectedFault


class FakeClock:
    """Manual clock; sleeping advances it (so backoff time is counted
    against the error budget exactly as wall time would be)."""

    def __init__(self):
        self.now = 1000.0
        self.sleeps = []

    def __call__(self):
        return self.now

    def sleep(self, s):
        self.sleeps.append(s)
        self.now += s


def _conn_refused():
    return urllib.error.URLError(ConnectionRefusedError("refused"))


def _http_error(code, body=b"boom"):
    return urllib.error.HTTPError("http://x/y", code, "err", {},
                                  io.BytesIO(body))


def test_classification():
    assert is_retryable(_conn_refused())
    assert is_retryable(_http_error(503))
    assert is_retryable(_http_error(502))
    assert is_retryable(_http_error(504))
    assert is_retryable(TimeoutError())
    assert is_retryable(ConnectionResetError())
    import http.client

    assert is_retryable(http.client.RemoteDisconnected())
    assert not is_retryable(_http_error(400))
    assert not is_retryable(_http_error(500))
    assert not is_retryable(_http_error(404))


def test_backoff_schedule_deterministic():
    clk = FakeClock()
    t = RequestErrorTracker("http://w/v1/task/t1", task_id="q.0.1",
                            max_error_duration_s=100.0,
                            min_backoff_s=0.05, max_backoff_s=2.0,
                            clock=clk, sleeper=clk.sleep)
    for _ in range(8):
        t.failed(_conn_refused())
    # 0.05 * 2^n capped at 2.0
    assert clk.sleeps == [0.05, 0.1, 0.2, 0.4, 0.8, 1.6, 2.0, 2.0]


def test_success_resets_budget():
    clk = FakeClock()
    t = RequestErrorTracker("http://w", max_error_duration_s=1.0,
                            min_backoff_s=0.4, max_backoff_s=10.0,
                            clock=clk, sleeper=clk.sleep)
    t.failed(_conn_refused())
    t.failed(_conn_refused())          # elapsed 0.4 < 1.0
    t.succeeded()
    # budget and backoff start over after a success
    t.failed(_conn_refused())
    assert clk.sleeps[-1] == 0.4
    assert t.error_count == 1


def test_budget_exhaustion_names_task_and_endpoint():
    clk = FakeClock()
    t = RequestErrorTracker("http://worker:1/v1/task/q.0.1/results/0",
                            task_id="q.1.0",
                            description="exchange fetch",
                            max_error_duration_s=1.0,
                            min_backoff_s=0.3, max_backoff_s=0.3,
                            clock=clk, sleeper=clk.sleep)
    with pytest.raises(RemoteRequestError) as ei:
        for _ in range(10):
            t.failed(_conn_refused())
    e = ei.value
    assert e.retryable
    assert "q.1.0" in str(e)
    assert "http://worker:1/v1/task/q.0.1/results/0" in str(e)
    assert "error budget" in str(e)
    # failures land at t=0, .3, .6, .9, 1.2 — the fifth crosses the
    # 1.0s budget
    assert e.error_count == 5


def test_fatal_error_raises_immediately_with_body():
    clk = FakeClock()
    t = RequestErrorTracker("http://w/v1/task/t", task_id="q.0.0",
                            clock=clk, sleeper=clk.sleep)
    with pytest.raises(RemoteRequestError) as ei:
        t.failed(_http_error(400, b'{"error": "bad task update"}'))
    assert not ei.value.retryable
    assert ei.value.status == 400
    assert "bad task update" in str(ei.value)
    assert clk.sleeps == []            # no backoff on fatal errors


class FakeOpener:
    """Scripted urlopen: pops the next outcome per call."""

    def __init__(self, outcomes):
        self.outcomes = list(outcomes)
        self.calls = []

    def __call__(self, req, timeout=None):
        self.calls.append(req.full_url)
        out = self.outcomes.pop(0)
        if isinstance(out, Exception):
            raise out

        class Resp:
            status = 200
            headers = {}

            def read(self_):
                return out

            def __enter__(self_):
                return self_

            def __exit__(self_, *a):
                return False

        return Resp()


def _client(outcomes, clk, **kw):
    return RetryingHttpClient(clock=clk, sleeper=clk.sleep,
                              opener=FakeOpener(outcomes), **kw)


def test_client_retries_transient_then_succeeds():
    clk = FakeClock()
    c = _client([_conn_refused(), _http_error(503), b"ok"], clk,
                max_error_duration_s=60.0)
    resp = c.request("http://w/v1/task/t", task_id="q.0.0")
    assert resp.body == b"ok"
    assert len(clk.sleeps) == 2        # two backoffs, no real time


def test_client_budget_zero_single_attempt():
    clk = FakeClock()
    c = _client([_conn_refused(), b"never"], clk)
    with pytest.raises(RemoteRequestError) as ei:
        c.request("http://w/v1/task/t", max_error_duration_s=0.0)
    assert ei.value.retryable
    assert clk.sleeps == []


def test_client_retry_cb_relocates_and_resets_budget():
    clk = FakeClock()
    c = _client([_conn_refused(), _conn_refused(), b"moved"], clk,
                max_error_duration_s=600.0)

    def relocate(exc):
        return "http://replacement/v1/task/t/results/0/0"

    resp = c.request("http://dead/v1/task/t/results/0/0",
                     retry_cb=relocate)
    assert resp.body == b"moved"
    # second attempt already goes to the replacement
    assert c.opener.calls[1].startswith("http://replacement/")


def test_client_retry_cb_can_abort():
    clk = FakeClock()
    c = _client([_conn_refused()] * 5, clk, max_error_duration_s=600.0)

    def abort(exc):
        raise RuntimeError("Query killed")

    with pytest.raises(RuntimeError, match="Query killed"):
        c.request("http://w/x", retry_cb=abort)


# ---------------------------------------------------------------------------
# fault injector (client side; the server side is exercised in
# tests/test_chaos.py against a real worker handler)
# ---------------------------------------------------------------------------

def test_injector_fail_n_times_then_clean():
    clk = FakeClock()
    inj = FaultInjector(sleeper=clk.sleep)
    inj.add_rule(r"/results/", method="GET", policy="fail-n-times",
                 times=2)
    c = _client([b"page"], clk, injector=inj, max_error_duration_s=60.0)
    resp = c.request("http://w/v1/task/t/results/0/0")
    assert resp.body == b"page"
    assert [p for _, _, p in inj.injections] == ["fail-n-times"] * 2


def test_injector_http_503_is_retryable():
    inj = FaultInjector()
    inj.add_rule(r"/v1/task", method="POST", policy="http-503", times=1)
    with pytest.raises(urllib.error.HTTPError) as ei:
        inj.apply_client("http://w/v1/task/t", "POST")
    assert ei.value.code == 503
    assert is_retryable(ei.value)
    # consumed: second request passes
    inj.apply_client("http://w/v1/task/t", "POST")


def test_injector_method_and_pattern_keying():
    inj = FaultInjector()
    inj.add_rule(r"/v1/task/[^/]+$", method="DELETE",
                 policy="drop-connection")
    inj.apply_client("http://w/v1/task/t/results/0/0", "GET")  # no match
    inj.apply_client("http://w/v1/task/t", "GET")              # method
    with pytest.raises(InjectedFault) as ei:
        inj.apply_client("http://w/v1/task/t", "DELETE")
    # injected drops classify exactly like real transport failures
    assert is_retryable(ei.value)


def test_injector_delay_uses_injected_sleeper():
    clk = FakeClock()
    inj = FaultInjector(sleeper=clk.sleep)
    inj.add_rule(r"/results/", policy="delay", delay_s=7.5, times=1)
    inj.apply_client("http://w/v1/task/t/results/0/0", "GET")
    assert clk.sleeps == [7.5]
