"""Expression engine tests (reference tier: TestExpressionCompiler /
operator/scalar tests — same expression evaluated through the interpreter
and through the compiled path must agree; SURVEY §4.1)."""

import datetime
import decimal

import numpy as np
import pytest

from presto_tpu import types as T
from presto_tpu.batch import batch_from_pylist
from presto_tpu.expr import build as B
from presto_tpu.expr.compile import batch_dictionaries, compile_expr, evaluate


def run_both(expr, batch):
    """Evaluate via numpy (oracle) and under jax.jit (XLA); assert equal."""
    import jax
    import jax.numpy as jnp

    out_np = evaluate(expr, batch)
    compiled = compile_expr(expr, batch_dictionaries(batch))

    cols = tuple((c.values, c.valid) for c in batch.columns)

    @jax.jit
    def kernel(cols):
        return compiled.run(cols, batch.num_rows, jnp)

    values, valid = kernel(cols)
    np.testing.assert_allclose(np.asarray(values),
                               np.asarray(out_np.values), rtol=1e-12)
    if out_np.valid is None:
        assert valid is None or bool(np.asarray(valid).all())
    else:
        np.testing.assert_array_equal(np.asarray(valid), np.asarray(out_np.valid))
    from presto_tpu.batch import Column
    return Column(out_np.type, out_np.values, out_np.valid,
                  out_np.dictionary).to_pylist(batch.num_rows)


DEC = T.DecimalType("decimal", 15, 2)


def test_arith_bigint():
    b = batch_from_pylist([T.BIGINT, T.BIGINT], [(7, 2), (-7, 2), (5, None)])
    assert run_both(B.call("add", B.ref(0, T.BIGINT), B.ref(1, T.BIGINT)),
                    b) == [9, -5, None]
    assert run_both(B.call("divide", B.ref(0, T.BIGINT), B.ref(1, T.BIGINT)),
                    b) == [3, -3, None]  # truncates toward zero
    assert run_both(B.call("modulus", B.ref(0, T.BIGINT), B.ref(1, T.BIGINT)),
                    b) == [1, -1, None]


def test_divide_by_zero_is_null():
    b = batch_from_pylist([T.BIGINT, T.BIGINT], [(7, 0), (8, 2)])
    assert run_both(B.call("divide", B.ref(0, T.BIGINT), B.ref(1, T.BIGINT)),
                    b) == [None, 4]


def test_decimal_arith():
    b = batch_from_pylist([DEC, DEC], [("12.34", "1.11"), ("-5.00", "2.50")])
    add = B.call("add", B.ref(0, DEC), B.ref(1, DEC))
    assert add.type == T.DecimalType("decimal", 16, 2)
    assert run_both(add, b) == [decimal.Decimal("13.45"), decimal.Decimal("-2.50")]
    mul = B.call("multiply", B.ref(0, DEC), B.ref(1, DEC))
    assert mul.type.scale == 4
    assert run_both(mul, b) == [decimal.Decimal("13.6974"),
                                decimal.Decimal("-12.5000")]
    div = B.call("divide", B.ref(0, DEC), B.ref(1, DEC))
    assert run_both(div, b) == [decimal.Decimal("11.12"),  # 11.117→11.12 half-up
                                decimal.Decimal("-2.00")]


def test_decimal_int_mixed():
    b = batch_from_pylist([DEC, T.BIGINT], [("12.34", 2)])
    out = run_both(B.call("multiply", B.ref(0, DEC), B.ref(1, T.BIGINT)), b)
    assert out == [decimal.Decimal("24.68")]


def test_double_decimal_mixed():
    b = batch_from_pylist([DEC, T.DOUBLE], [("12.00", 0.5)])
    out = run_both(B.call("multiply", B.ref(0, DEC), B.ref(1, T.DOUBLE)), b)
    assert out == [6.0]


def test_comparisons():
    b = batch_from_pylist([T.BIGINT, T.DOUBLE], [(1, 1.5), (2, 2.0), (3, None)])
    assert run_both(B.comparison("<", B.ref(0, T.BIGINT), B.ref(1, T.DOUBLE)),
                    b) == [True, False, None]
    d = batch_from_pylist([DEC, DEC], [("1.10", "1.2"), ("3.00", "3.00")])
    assert run_both(B.comparison("<", B.ref(0, DEC), B.ref(1, DEC)),
                    d) == [True, False]


def test_kleene_and_or():
    b = batch_from_pylist([T.BOOLEAN, T.BOOLEAN],
                          [(True, None), (False, None), (None, None),
                           (True, True), (True, False)])
    a = B.and_(B.ref(0, T.BOOLEAN), B.ref(1, T.BOOLEAN))
    assert run_both(a, b) == [None, False, None, True, False]
    o = B.or_(B.ref(0, T.BOOLEAN), B.ref(1, T.BOOLEAN))
    assert run_both(o, b) == [True, None, None, True, True]


def test_is_null_not():
    b = batch_from_pylist([T.BIGINT], [(1,), (None,), (3,)])
    assert run_both(B.call("is_null", B.ref(0, T.BIGINT)), b) == \
        [False, True, False]
    assert run_both(B.call("is_not_null", B.ref(0, T.BIGINT)), b) == \
        [True, False, True]
    assert run_both(B.not_(B.call("is_null", B.ref(0, T.BIGINT))), b) == \
        [True, False, True]


def test_string_predicates():
    b = batch_from_pylist([T.VARCHAR],
                          [("BUILDING",), ("AUTOMOBILE",), ("HOUSEHOLD",)])
    eq = B.comparison("=", B.ref(0, T.VARCHAR), B.const("BUILDING", T.VARCHAR))
    assert run_both(eq, b) == [True, False, False]
    like = B.call("like", B.ref(0, T.VARCHAR), B.const("%HOLD", T.VARCHAR))
    assert run_both(like, b) == [False, False, True]
    isin = B.in_(B.ref(0, T.VARCHAR), [B.const("BUILDING", T.VARCHAR),
                                       B.const("HOUSEHOLD", T.VARCHAR)])
    assert run_both(isin, b) == [True, False, True]


def test_string_functions_produce_dictionary():
    b = batch_from_pylist([T.VARCHAR], [("PROMO BRUSHED TIN",), ("STANDARD X",)])
    sub = B.call("substr", B.ref(0, T.VARCHAR), B.const(1, T.BIGINT),
                 B.const(5, T.BIGINT))
    col = evaluate(sub, b)
    assert col.to_pylist(2) == ["PROMO", "STAND"]
    ln = B.call("length", B.ref(0, T.VARCHAR))
    assert run_both(ln, b) == [17, 10]


def test_in_numeric():
    b = batch_from_pylist([T.BIGINT], [(1,), (2,), (9,)])
    e = B.in_(B.ref(0, T.BIGINT),
              [B.const(1, T.BIGINT), B.const(9, T.BIGINT)])
    assert run_both(e, b) == [True, False, True]


def test_dates():
    b = batch_from_pylist([T.DATE], [("1995-03-15",), ("1998-12-01",),
                                     ("1996-02-29",)])
    y = B.call("extract_year", B.ref(0, T.DATE))
    assert run_both(y, b) == [1995, 1998, 1996]
    m = B.call("extract_month", B.ref(0, T.DATE))
    assert run_both(m, b) == [3, 12, 2]
    d = B.call("extract_day", B.ref(0, T.DATE))
    assert run_both(d, b) == [15, 1, 29]
    q = B.call("extract_quarter", B.ref(0, T.DATE))
    assert run_both(q, b) == [1, 4, 1]
    plus90 = B.call("add_days", B.ref(0, T.DATE), B.const(90, T.INTEGER))
    assert run_both(plus90, b)[0] == datetime.date(1995, 6, 13)
    plus3m = B.call("add_months", B.ref(0, T.DATE), B.const(3, T.INTEGER))
    out = run_both(plus3m, b)
    assert out[0] == datetime.date(1995, 6, 15)
    assert out[2] == datetime.date(1996, 5, 29)
    minus1m = B.call("add_months", B.ref(0, T.DATE), B.const(-12, T.INTEGER))
    assert run_both(minus1m, b)[2] == datetime.date(1995, 2, 28)  # clamped


def test_date_comparison_with_literal():
    b = batch_from_pylist([T.DATE], [("1995-03-15",), ("1998-12-01",)])
    e = B.comparison("<", B.ref(0, T.DATE), B.const("1996-01-01", T.DATE))
    assert run_both(e, b) == [True, False]


def test_case_if_coalesce():
    b = batch_from_pylist([T.BIGINT], [(1,), (2,), (None,)])
    e = B.if_(B.comparison("=", B.ref(0, T.BIGINT), B.const(1, T.BIGINT)),
              B.const(10, T.BIGINT), B.const(20, T.BIGINT))
    assert run_both(e, b) == [10, 20, 20]
    c = B.case_when(
        [(B.comparison("=", B.ref(0, T.BIGINT), B.const(1, T.BIGINT)),
          B.const(100, T.BIGINT)),
         (B.comparison("=", B.ref(0, T.BIGINT), B.const(2, T.BIGINT)),
          B.const(200, T.BIGINT))], None)
    assert run_both(c, b) == [100, 200, None]
    co = B.coalesce(B.ref(0, T.BIGINT), B.const(-1, T.BIGINT))
    assert run_both(co, b) == [1, 2, -1]


def test_if_over_strings_merges_dictionaries():
    b = batch_from_pylist([T.BOOLEAN], [(True,), (False,)])
    e = B.if_(B.ref(0, T.BOOLEAN), B.const("yes", T.VARCHAR),
              B.const("no", T.VARCHAR))
    col = evaluate(e, b)
    assert col.to_pylist(2) == ["yes", "no"]


def test_casts():
    b = batch_from_pylist([T.BIGINT], [(3,), (-3,)])
    assert run_both(B.cast(B.ref(0, T.BIGINT), T.DOUBLE), b) == [3.0, -3.0]
    assert run_both(B.cast(B.ref(0, T.BIGINT), DEC), b) == \
        [decimal.Decimal("3.00"), decimal.Decimal("-3.00")]
    d = batch_from_pylist([T.DOUBLE], [(2.5,), (-2.5,), (2.4,)])
    assert run_both(B.cast(B.ref(0, T.DOUBLE), T.BIGINT), d) == [3, -3, 2]
    s = batch_from_pylist([T.VARCHAR], [("1995-06-17",)])
    assert run_both(B.cast(B.ref(0, T.VARCHAR), T.DATE), s) == \
        [datetime.date(1995, 6, 17)]


def test_round_and_math():
    b = batch_from_pylist([T.DOUBLE], [(2.5,), (-2.5,), (1.234,)])
    assert run_both(B.round_digits(B.ref(0, T.DOUBLE), 0), b) == [3.0, -3.0, 1.0]
    assert run_both(B.round_digits(B.ref(0, T.DOUBLE), 2), b) == \
        [2.5, -2.5, 1.23]
    d = batch_from_pylist([DEC], [("2.345",)])  # scale 2 -> 2.35 storage 235
    assert run_both(B.call("abs", B.ref(0, DEC)), d) == [decimal.Decimal("2.35")]
    assert run_both(B.call("ceil", B.ref(0, DEC)), d) == [decimal.Decimal(3)]
    assert run_both(B.call("floor", B.ref(0, DEC)), d) == [decimal.Decimal(2)]


def test_between():
    b = batch_from_pylist([T.BIGINT], [(5,), (15,), (10,)])
    e = B.between(B.ref(0, T.BIGINT), B.const(5, T.BIGINT),
                  B.const(10, T.BIGINT))
    assert run_both(e, b) == [True, False, True]


def test_constant_fold_string():
    b = batch_from_pylist([T.BIGINT], [(1,), (2,)])
    e = B.call("length", B.const("hello", T.VARCHAR))
    assert run_both(e, b) == [5, 5]
