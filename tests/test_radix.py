"""Radix sort kernels vs the lexsort oracle (ops/sort.py)."""

import jax.numpy as jnp
import numpy as np
import pytest

from presto_tpu import types as T
from presto_tpu.ops.radix import (
    counting_sort_perm, radix_argsort_i64, radix_sort_permutation,
)
from presto_tpu.ops.sort import sort_permutation


def _rand(rng, n, lo, hi):
    return rng.integers(lo, hi, size=n, dtype=np.int64)


@pytest.mark.parametrize("n,lo,hi", [
    (1, 0, 10),
    (17, 0, 4),               # heavy duplicates, tests stability
    pytest.param(128, -1000, 1000, marks=pytest.mark.slow),
    pytest.param(1000, -2**62, 2**62, marks=pytest.mark.slow),
    pytest.param(513, 0, 250, marks=pytest.mark.slow),
])
def test_argsort_single_word(n, lo, hi):
    rng = np.random.default_rng(n)
    w = _rand(rng, n, lo, hi)
    perm = np.asarray(radix_argsort_i64([jnp.asarray(w)]))
    expect = np.argsort(w, kind="stable")
    np.testing.assert_array_equal(perm, expect)


@pytest.mark.slow
def test_argsort_extreme_spread():
    """Live spread exceeding int64 must not wrap the range reduction
    (regression: pass-skipping saw rng=0 and ran zero passes)."""
    w = np.array([2**62 + 100, -(2**62), 2**62 + 7, -2**62 - 1000, 0],
                 dtype=np.int64)
    perm = np.asarray(radix_argsort_i64([jnp.asarray(w)]))
    np.testing.assert_array_equal(perm, np.argsort(w, kind="stable"))


@pytest.mark.slow
def test_argsort_multi_word():
    rng = np.random.default_rng(7)
    a = _rand(rng, 400, 0, 5)
    b = _rand(rng, 400, -100, 100)
    perm = np.asarray(radix_argsort_i64(
        [jnp.asarray(a), jnp.asarray(b)]))
    expect = np.lexsort((b, a))  # a major
    np.testing.assert_array_equal(perm, expect)


@pytest.mark.slow
def test_argsort_with_pad():
    rng = np.random.default_rng(3)
    w = _rand(rng, 100, 0, 50)
    pad = np.arange(100) >= 60
    perm = np.asarray(radix_argsort_i64(
        [jnp.asarray(w)], pad=jnp.asarray(pad)))
    live = perm[:60]
    np.testing.assert_array_equal(live, np.argsort(w[:60], kind="stable"))
    assert set(perm[60:].tolist()) == set(range(60, 100))


@pytest.mark.parametrize("desc", [False, pytest.param(True, marks=pytest.mark.slow)])
@pytest.mark.parametrize("nulls_first", [False, pytest.param(True, marks=pytest.mark.slow)])
def test_sort_permutation_parity(desc, nulls_first):
    """radix_sort_permutation == sort_permutation on mixed-type keys with
    nulls, descending, and padding."""
    rng = np.random.default_rng(11)
    n, live = 200, 163
    ints = _rand(rng, n, -50, 50)
    dbls = rng.normal(size=n)
    valid = rng.random(n) > 0.3
    keys = [
        (jnp.asarray(ints), jnp.asarray(valid), T.BIGINT, desc, nulls_first),
        (jnp.asarray(dbls), None, T.DOUBLE, not desc, nulls_first),
    ]
    got = np.asarray(radix_sort_permutation(keys, jnp.asarray(live)))
    expect = np.asarray(sort_permutation(keys, jnp.asarray(live)))
    # live prefix must match exactly (stable order); the relative order of
    # padding rows is unspecified — they only need to all land at the end
    np.testing.assert_array_equal(got[:live], expect[:live])
    assert set(got[live:].tolist()) == set(expect[live:].tolist())


def test_counting_sort():
    rng = np.random.default_rng(5)
    codes = rng.integers(0, 8, size=300)
    perm = np.asarray(counting_sort_perm(jnp.asarray(codes), 8))
    np.testing.assert_array_equal(perm, np.argsort(codes, kind="stable"))


def test_jit_one_program_many_ranges():
    """The same compiled program must serve different key ranges (the
    whole point: pass skipping is runtime, not compile-time)."""
    import jax

    calls = {"n": 0}

    @jax.jit
    def f(w):
        calls["n"] += 1
        return radix_argsort_i64([w])

    rng = np.random.default_rng(9)
    for lo, hi in [(0, 4), (0, 10**6), (-2**60, 2**60)]:
        w = _rand(rng, 256, lo, hi)
        perm = np.asarray(f(jnp.asarray(w)))
        np.testing.assert_array_equal(perm, np.argsort(w, kind="stable"))
    assert calls["n"] == 1  # one trace, three ranges
