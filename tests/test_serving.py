"""Serving tier: async dispatch, resource-group admission, the shared
plan cache, and concurrent execution (server/dispatcher.py +
sql/plancache.py — the DispatchManager / InternalResourceGroup /
QueryStateMachine roles).

Covers the PR 8 acceptance pins: N-thread mixed statement storm with
exact-rows parity per client, plan-cache hit/invalidation semantics
(DDL bumps the stats epoch; a session-property change misses;
``plan_cache_enabled=false`` restores inline planning exactly),
queue-full rejection with the reference's error shape, queued-query
cancellation that never starts execution, zero jit compiles on the
second execution of a cached plan, and a chaos case (worker kill with
three queries in flight, recovered by the PR 5/7 machinery).
"""

import json
import threading
import time
import urllib.request

import pytest

from presto_tpu import events as ev
from presto_tpu.client import QueryFailed
from presto_tpu.server.dqr import DistributedQueryRunner
from presto_tpu.session import (
    QueryQueueFullError, ResourceGroupManager, Session,
)
from presto_tpu.sql import plancache


def _get_json(uri):
    with urllib.request.urlopen(uri, timeout=10) as resp:
        return json.loads(resp.read())


def _norm(rows):
    return sorted(tuple(round(v, 6) if isinstance(v, float) else v
                        for v in r) for r in rows)


@pytest.fixture(scope="module")
def dqr():
    with DistributedQueryRunner.tpch(scale=0.01, n_workers=2) as runner:
        yield runner


class TestConcurrentServing:
    STORM = [
        "select count(*) as c from tpch.lineitem",
        "select l_returnflag, count(*) as c, sum(l_quantity) as q "
        "from tpch.lineitem group by l_returnflag order by l_returnflag",
        "select n_name, count(*) as c from tpch.customer, tpch.nation "
        "where c_nationkey = n_nationkey group by n_name "
        "order by c desc, n_name",
        "select o_orderpriority, count(*) as c from tpch.orders "
        "group by o_orderpriority order by o_orderpriority",
    ]

    def test_statement_storm_exact_rows_per_client(self, dqr):
        """4 clients x 4 mixed statements concurrently: every client
        sees exactly the single-threaded rows (shared kernel caches,
        shared plan cache, concurrent drivers — no cross-query bleed)."""
        expected = {sql: _norm(dqr.execute(sql).rows)
                    for sql in self.STORM}
        failures = []

        def client_loop(i):
            client = dqr.new_client(user=f"storm{i}")
            try:
                for j in range(len(self.STORM)):
                    sql = self.STORM[(i + j) % len(self.STORM)]
                    _cols, data = client.execute(sql)
                    if _norm([tuple(r) for r in data]) != expected[sql]:
                        failures.append((i, sql))
            except Exception as e:  # noqa: BLE001
                failures.append((i, repr(e)))

        threads = [threading.Thread(target=client_loop, args=(i,),
                                    daemon=True) for i in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        assert not failures, failures

    def test_lifecycle_states_and_queued_split(self, dqr):
        """A query blocked on admission is visible as
        WAITING_FOR_RESOURCES in /v1/query/{id}; once run, its detail
        reports the queued-vs-execution split."""
        co = dqr.coordinator
        blocker = co.resource_groups.configure_group(
            "split", hard_concurrency_limit=1)
        blocker.acquire()
        try:
            req = urllib.request.Request(
                f"{co.uri}/v1/statement",
                data=b"select count(*) from tpch.region",
                method="POST", headers={"X-Presto-User": "split"})
            qid = _get_json_req(req)["id"]
            state = _wait_for_state(
                co.uri, qid, ("WAITING_FOR_RESOURCES",), timeout=10)
            assert state == "WAITING_FOR_RESOURCES"
            time.sleep(0.2)      # accrue measurable queued time
        finally:
            blocker.release()
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            detail = _get_json(f"{co.uri}/v1/query/{qid}")
            if detail["state"] in ("FINISHED", "FAILED"):
                break
            time.sleep(0.05)
        assert detail["state"] == "FINISHED", detail.get("error")
        assert detail["resourceGroup"] == "global.split"
        assert detail["queuedS"] > 0.1
        assert detail["executionS"] > 0
        qs = detail["queryStats"]
        assert qs["queued_s"] > 0.1 and qs["execution_s"] > 0
        # the lifecycle is visible through system.runtime too
        rows = dqr.execute(
            "select state, queued_s, resource_group from "
            "system.runtime.queries where query_id = '" + qid + "'").rows
        assert rows and rows[0][0] == "FINISHED"
        assert rows[0][1] > 0.1 and rows[0][2] == "global.split"

    def test_chaos_worker_kill_with_three_in_flight(self):
        """Worker dies while 3 concurrent queries are mid-flight: all
        recover exactly via the PR 5/7 retry/spool machinery."""
        sqls = [
            "select l_returnflag, count(*) as c, sum(l_extendedprice) "
            "as s from tpch.lineitem group by l_returnflag "
            "order by l_returnflag",
            "select n_name, count(*) as c from tpch.supplier, "
            "tpch.nation where s_nationkey = n_nationkey "
            "group by n_name order by c desc, n_name",
            "select count(*) as c, sum(o_totalprice) as s "
            "from tpch.orders",
        ]
        with DistributedQueryRunner.tpch(
                scale=0.01, n_workers=3,
                heartbeat_interval_s=0.1,
                heartbeat_max_missed=2) as runner:
            expected = [_norm(runner.execute(s).rows) for s in sqls]
            results = [None] * len(sqls)
            errors = []

            def run(i):
                client = runner.new_client(user=f"chaos{i}")
                try:
                    _cols, data = client.execute(sqls[i])
                    results[i] = _norm([tuple(r) for r in data])
                except Exception as e:  # noqa: BLE001
                    errors.append(f"{i}: {e}")

            threads = [threading.Thread(target=run, args=(i,),
                                        daemon=True)
                       for i in range(len(sqls))]
            for t in threads:
                t.start()
            # wait for the CONDITION the kill is meant to hit — all 3
            # queries actually mid-flight (tasks scheduled) — instead of
            # assuming 50 ms of wall clock covers admission+planning
            # (under a loaded full-suite run it does not, and the kill
            # races scheduling into stage-retry exhaustion)
            co = runner.coordinator
            deadline = time.monotonic() + 30

            def mid_flight():
                qs = [q for q in list(co.queries.values())
                      if q.user.startswith("chaos")]
                return len(qs) == len(sqls) and all(
                    q._tasks_scheduled
                    or q.state in ("FINISHED", "FAILED") for q in qs)

            while time.monotonic() < deadline and not mid_flight():
                time.sleep(0.01)
            runner.kill_worker(1)
            for t in threads:
                t.join(timeout=120)
            assert not errors, errors
            for i, want in enumerate(expected):
                assert results[i] == want, f"query {i} inexact"


class TestAdmissionControl:
    def test_queue_full_rejection_error_shape(self):
        """A full queue rejects with the reference's error shape:
        QUERY_QUEUE_FULL / INSUFFICIENT_RESOURCES / 0x0002_0002."""
        groups = ResourceGroupManager(hard_concurrency_limit=4,
                                      max_queued=0, per_user_limit=1)
        with DistributedQueryRunner.tpch(
                scale=0.001, n_workers=1,
                resource_groups=groups) as runner:
            blocker = groups.group_for(Session(user="alice"))
            blocker.acquire()
            try:
                client = runner.new_client(user="alice")
                with pytest.raises(QueryFailed) as ei:
                    client.execute("select count(*) from tpch.region")
                assert ei.value.error_name == "QUERY_QUEUE_FULL"
                assert ei.value.error_type == "INSUFFICIENT_RESOURCES"
                assert ei.value.error_code == 0x0002_0002
                assert "Too many queued queries" in str(ei.value)
            finally:
                blocker.release()
            # the slot was never leaked: alice can run again
            assert runner.new_client(user="alice").execute(
                "select count(*) from tpch.region")[1] == [[5]]

    def test_queued_query_cancellation(self):
        """DELETE on a queued query dequeues it without ever starting
        execution, releases its resource-group slot, and still fires
        QueryCompletedEvent (FAILED, USER_CANCELED)."""
        groups = ResourceGroupManager(hard_concurrency_limit=4,
                                      max_queued=8, per_user_limit=1)
        completed = []

        class Listener(ev.EventListener):
            def query_completed(self, event):
                completed.append(event)

        with DistributedQueryRunner.tpch(
                scale=0.001, n_workers=1,
                resource_groups=groups) as runner:
            runner.event_bus.register(Listener())
            co = runner.coordinator
            blocker = groups.group_for(Session(user="bob"))
            blocker.acquire()
            try:
                req = urllib.request.Request(
                    f"{co.uri}/v1/statement",
                    data=b"select count(*) from tpch.lineitem",
                    method="POST", headers={"X-Presto-User": "bob"})
                qid = _get_json_req(req)["id"]
                assert _wait_for_state(
                    co.uri, qid, ("WAITING_FOR_RESOURCES",),
                    timeout=10) == "WAITING_FOR_RESOURCES"
                req = urllib.request.Request(
                    f"{co.uri}/v1/query/{qid}", method="DELETE")
                _get_json_req(req)
                assert _wait_for_state(co.uri, qid, ("FAILED",),
                                       timeout=10) == "FAILED"
                q = co.queries[qid]
                assert q.error_name == "USER_CANCELED"
                assert q.error_type == "USER_ERROR"
                assert q.error_code == 0x0000_0003
                # execution never started: no tasks were ever created
                assert q._tasks_scheduled is False
                assert q.state == "FAILED"
                deadline = time.monotonic() + 5
                while time.monotonic() < deadline and not any(
                        e.query_id == qid for e in completed):
                    time.sleep(0.02)
                done = [e for e in completed if e.query_id == qid]
                assert done and done[0].state == "FAILED"
                # the group queue slot was released, not leaked
                assert groups.group_for(
                    Session(user="bob")).queued == 0
            finally:
                blocker.release()
            # bob's group admits normally afterwards
            assert runner.new_client(user="bob").execute(
                "select count(*) from tpch.region")[1] == [[5]]

    def test_cpu_accounting_gates_admission(self):
        """A group over its hard CPU limit admits nothing until the
        regeneration rate pays the debt down (cpuUsageMillis /
        cpuQuotaGenerationMillisPerSecond role)."""
        mgr = ResourceGroupManager(hard_concurrency_limit=8,
                                   per_user_limit=8)
        g = mgr.configure_group("cpu_user", hard_cpu_limit_s=1.0)
        g.charge_cpu(5.0)
        with pytest.raises(QueryQueueFullError):
            g.acquire(timeout_s=0.2)       # no regeneration configured
        g.cpu_quota_generation_s_per_s = 50.0
        admitted = threading.Event()

        def waiter():
            g.acquire(timeout_s=10)
            admitted.set()

        threading.Thread(target=waiter, daemon=True).start()
        # regeneration is checked lazily on wakeups — nudge the tree
        deadline = time.monotonic() + 5
        while not admitted.is_set() and time.monotonic() < deadline:
            g.wake()
            time.sleep(0.02)
        assert admitted.is_set()
        g.release()


class TestPlanCache:
    def test_repeat_statement_hits_and_skips_compiles(self, dqr):
        """Second execution of a repeated statement reuses the cached
        plan (planCached=true) and pays zero jit compiles (kernel cache
        + DictionaryPool are coordinator-lifetime, shared cross-query)."""
        sql = ("select count(*) as c_repeat, sum(l_tax) as t_repeat "
               "from tpch.lineitem where l_linenumber = 1")
        client = dqr.new_client(user="cache")
        before = plancache.stats()
        _cols, first = client.execute(sql)
        qid1 = client.last_query_id
        _cols, second = client.execute(sql)
        qid2 = client.last_query_id
        after = plancache.stats()
        assert second == first
        assert after["hits"] >= before["hits"] + 1
        co = dqr.coordinator
        d1 = _get_json(f"{co.uri}/v1/query/{qid1}")
        d2 = _get_json(f"{co.uri}/v1/query/{qid2}")
        assert d1["planCached"] is False
        assert d2["planCached"] is True
        # identical plan text: the cached plan IS the planned plan
        assert d1["plan"] == d2["plan"]
        # zero compiles on the cached re-execution (existing counters)
        assert d2["queryStats"]["jit_compiles"] == 0

    def test_ddl_insert_bumps_epoch_and_invalidates(self, dqr):
        """INSERT bumps the target catalog's stats epoch: the cached
        plan is invalidated (counted as eviction), re-planned, and the
        query sees the new rows."""
        client = dqr.new_client(user="cache")
        client.execute("create table memory.serving_inv (x bigint)")
        client.execute("insert into memory.serving_inv values (1), (2)")
        sql = "select sum(x) as s from memory.serving_inv"
        assert client.execute(sql)[1] == [[3]]
        assert client.execute(sql)[1] == [[3]]          # cached hit
        d = _get_json(f"{dqr.coordinator.uri}/v1/query/"
                      f"{client.last_query_id}")
        assert d["planCached"] is True
        before = plancache.stats()
        client.execute("insert into memory.serving_inv values (10)")
        assert client.execute(sql)[1] == [[13]]         # fresh plan
        d = _get_json(f"{dqr.coordinator.uri}/v1/query/"
                      f"{client.last_query_id}")
        assert d["planCached"] is False
        after = plancache.stats()
        assert after["evictions"] >= before["evictions"] + 1

    def test_session_property_change_misses(self, dqr):
        """A session-property change produces a different fingerprint —
        the cached plan for other settings is not reused."""
        sql = ("select count(*) as c_fp from tpch.orders "
               "where o_shippriority = 0")
        client = dqr.new_client(user="cache")
        client.execute(sql)
        client.execute(sql)
        d = _get_json(f"{dqr.coordinator.uri}/v1/query/"
                      f"{client.last_query_id}")
        assert d["planCached"] is True
        client.session_properties["scan_batch_rows"] = "32768"
        client.execute(sql)
        d = _get_json(f"{dqr.coordinator.uri}/v1/query/"
                      f"{client.last_query_id}")
        assert d["planCached"] is False

    def test_disabled_restores_inline_planning(self, dqr):
        """plan_cache_enabled=false restores inline planning exactly:
        same rows, same plan text, no cache traffic — the single-client
        one-query-at-a-time pin."""
        sql = ("select count(*) as c_off, min(p_size) as m_off "
               "from tpch.part")
        on_client = dqr.new_client(user="cache")
        _c, want = on_client.execute(sql)
        plan_on = _get_json(f"{dqr.coordinator.uri}/v1/query/"
                            f"{on_client.last_query_id}")["plan"]
        off = dqr.new_client(user="cache")
        off.session_properties["plan_cache_enabled"] = "false"
        before = plancache.stats()
        for _ in range(2):
            _c, got = off.execute(sql)
            assert got == want
            d = _get_json(f"{dqr.coordinator.uri}/v1/query/"
                          f"{off.last_query_id}")
            assert d["planCached"] is False
        after = plancache.stats()
        # no hits and no inserts for the disabled session (misses may
        # accrue from the pre-parse probe of OTHER sessions only)
        assert after["hits"] == before["hits"]
        assert d["plan"] == plan_on

    def test_execute_prepared_binding_cached(self, dqr):
        """EXECUTE-bound prepared statements cache per (prepared text,
        parameters): a repeated binding hits, a different binding plans
        fresh, and a re-PREPARE under the same name never aliases."""
        client = dqr.new_client(user="cache")
        client.execute("prepare sp from select count(*) as c from "
                       "tpch.lineitem where l_quantity < ?")
        assert client.execute("execute sp using 10")[1] == \
            client.execute("execute sp using 10")[1]
        d = _get_json(f"{dqr.coordinator.uri}/v1/query/"
                      f"{client.last_query_id}")
        assert d["planCached"] is True
        r10 = client.execute("execute sp using 10")[1]
        r2 = client.execute("execute sp using 2")[1]
        assert r2 != r10                      # distinct binding, fresh plan
        # re-PREPARE the same name with different SQL: must not alias
        client.execute("prepare sp from select count(*) as c from "
                       "tpch.orders where o_custkey < ?")
        fresh = client.execute("execute sp using 10")[1]
        assert fresh != r10

    def test_metrics_expose_serving_counters(self, dqr):
        """/metrics carries the per-group admission gauges and the
        plan-cache counters."""
        client = dqr.new_client(user="cache")
        client.execute("select 1 as one_metrics from tpch.region")
        with urllib.request.urlopen(
                f"{dqr.coordinator.uri}/metrics", timeout=10) as resp:
            text = resp.read().decode()
        assert "presto_resource_group_queued{" in text
        assert "presto_resource_group_running{" in text
        assert 'group="global"' in text
        assert "presto_plan_cache_hits_total" in text
        assert "presto_plan_cache_misses_total" in text
        assert "presto_plan_cache_evictions_total" in text

    def test_explain_analyze_surfaces_split(self, dqr):
        """Both EXPLAIN ANALYZE surfaces report the queued-vs-execution
        split."""
        rows = dqr.execute("explain analyze select count(*) "
                           "from tpch.region").rows
        text = "\n".join(r[0] for r in rows)
        assert "serving: queued" in text and "execution" in text
        from presto_tpu.localrunner import LocalQueryRunner

        local = LocalQueryRunner.tpch(scale=0.001)
        out = local.execute("explain analyze select count(*) "
                            "from region").rows
        text = "\n".join(r[0] for r in out)
        assert "serving: queued 0.000 s" in text


class TestLocalPlanCache:
    def test_local_runner_caches_and_invalidates(self):
        """The single-process tier shares the same plan-cache semantics:
        repeat statements skip plan/optimize, DDL bumps the epoch."""
        from presto_tpu.localrunner import LocalQueryRunner

        runner = LocalQueryRunner.tpch(scale=0.001)
        sql = "select count(*) as c_local from lineitem"
        before = plancache.stats()
        first = runner.execute(sql).rows
        second = runner.execute(sql).rows
        after = plancache.stats()
        assert second == first
        assert after["hits"] >= before["hits"] + 1
        runner.execute("create table memory.lt (x bigint)")
        msql = "select count(*) as c_local_m from memory.lt"
        assert runner.execute(msql).rows == [(0,)]
        assert runner.execute(msql).rows == [(0,)]      # cached
        runner.execute("insert into memory.lt values (7)")
        assert runner.execute(msql).rows == [(1,)]      # invalidated

    def test_physical_plan_shared_on_second_run(self):
        """Plan-cache physical-factory sharing (PR 11): the SECOND
        execution of a cached statement must not re-run the physical
        planner — the cached entry carries the operator factory chains,
        reset per execution (ROADMAP #3's biggest per-query CPU line
        item)."""
        from presto_tpu.localrunner import LocalQueryRunner
        from presto_tpu.sql import physical

        runner = LocalQueryRunner.tpch(scale=0.001)
        sqls = [
            "select l_returnflag, count(*) as c_phys from lineitem "
            "group by l_returnflag order by l_returnflag",
            # cross-pipeline rendezvous shapes (union buffer, build
            # side) must re-arm on reuse
            "select count(*) as u_phys from ("
            "select o_orderkey k from orders union all "
            "select l_orderkey k from lineitem)",
            "select n_name, count(*) as j_phys from supplier, nation "
            "where s_nationkey = n_nationkey group by n_name",
        ]
        for sql in sqls:
            first = runner.execute(sql).rows
            built = physical.PLANS_BUILT
            second = runner.execute(sql).rows
            third = runner.execute(sql).rows
            assert second == first and third == first
            assert physical.PLANS_BUILT == built, \
                f"physical planner re-ran on repeat of {sql[:40]!r}"

    def test_normalization_shares_entries(self):
        """Whitespace-reformatted statements share one entry; string
        literals are preserved."""
        assert plancache.normalize_sql(
            "select  1\n from   t;") == "select 1 from t"
        assert plancache.normalize_sql(
            "select 'a  b' from t") == "select 'a  b' from t"
        from presto_tpu.localrunner import LocalQueryRunner

        runner = LocalQueryRunner.tpch(scale=0.001)
        runner.execute("select max(n_nationkey) as m_norm from nation")
        before = plancache.stats()
        runner.execute("select   max(n_nationkey)  as m_norm\n"
                       "from nation")
        after = plancache.stats()
        assert after["hits"] == before["hits"] + 1


def _get_json_req(req):
    with urllib.request.urlopen(req, timeout=10) as resp:
        return json.loads(resp.read())


def _wait_for_state(base_uri, qid, states, timeout=10.0):
    deadline = time.monotonic() + timeout
    state = None
    while time.monotonic() < deadline:
        state = _get_json(f"{base_uri}/v1/query/{qid}")["state"]
        if state in states or state in ("FINISHED", "FAILED"):
            return state
        time.sleep(0.02)
    return state


class TestWorkerFragmentCache:
    def test_repeat_statement_lowers_zero_fragments(self):
        """The distributed half of physical-factory sharing (ROADMAP
        #3): repeat task creates of the same statement reuse the
        worker-side lowered pipelines — the SECOND execution builds
        ZERO fragment lowerings (sql/physical.FRAGMENTS_LOWERED), with
        exact rows, across join + agg + merge-exchange shapes."""
        from presto_tpu.sql import physical

        with DistributedQueryRunner.tpch(scale=0.01,
                                         n_workers=2) as dqr:
            sqls = [
                "select l_returnflag, count(*) c_wfc from lineitem "
                "group by l_returnflag order by l_returnflag",
                "select n_name, count(*) j_wfc from supplier, nation "
                "where s_nationkey = n_nationkey group by n_name "
                "order by n_name",
            ]
            for sql in sqls:
                first = dqr.execute(sql).rows
                lowered = physical.FRAGMENTS_LOWERED
                second = dqr.execute(sql).rows
                assert second == first
                assert physical.FRAGMENTS_LOWERED == lowered, \
                    f"worker re-lowered fragments on repeat of " \
                    f"{sql[:40]!r}"
                # cache counters moved on every worker that got tasks
                hits = sum(w.task_manager.fragment_cache.stats["hits"]
                           for w in dqr.workers)
                assert hits > 0

    def test_epoch_change_invalidates_worker_cache(self):
        """A DML between repeats bumps the coordinator's stats epoch;
        the shipped epoch snapshot changes the worker cache key, so the
        repeat RE-LOWERS (fresh pipelines over fresh data) and returns
        the new rows."""
        from presto_tpu.sql import physical

        with DistributedQueryRunner.tpch(scale=0.01,
                                         n_workers=2) as dqr:
            dqr.execute("create table memory.wfc as "
                        "select n_nationkey, n_name from tpch.nation")
            sql = "select count(*) c_ep from memory.wfc"
            assert dqr.execute(sql).rows == [(25,)]
            dqr.execute("insert into memory.wfc "
                        "select n_nationkey, n_name from tpch.nation")
            lowered = physical.FRAGMENTS_LOWERED
            assert dqr.execute(sql).rows == [(50,)]
            assert physical.FRAGMENTS_LOWERED > lowered, \
                "epoch bump must force a fresh fragment lowering"

    def test_disabled_lowering_every_create(self):
        """worker_fragment_cache_enabled=false restores per-create
        lowering exactly (no cache constructed, counter moves every
        run)."""
        import dataclasses

        from presto_tpu.config import DEFAULT
        from presto_tpu.sql import physical

        cfg = dataclasses.replace(DEFAULT,
                                  worker_fragment_cache_enabled=False)
        with DistributedQueryRunner.tpch(scale=0.01, n_workers=2,
                                         config=cfg) as dqr:
            assert all(w.task_manager.fragment_cache is None
                       for w in dqr.workers)
            sql = "select count(*) c_off from lineitem"
            first = dqr.execute(sql).rows
            lowered = physical.FRAGMENTS_LOWERED
            assert dqr.execute(sql).rows == first
            assert physical.FRAGMENTS_LOWERED > lowered
